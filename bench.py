"""North-star benchmark (BASELINE.md): one mainnet-scale epoch of
attestation aggregation + fork choice at 1M validators, on one chip.

Workload per epoch (the reference's own protocol shape):
- attestation aggregation: 2048 committee aggregates (64 committees x 32
  slots, pos-evolution.md:472-475) covering ~1M signers, batch-verified on
  device (config #3; fake-BLS pipeline shape — gather/hash/XOR-reduce);
- fork choice: 32 per-slot get_head passes over a 64-block tree with the
  full 1M-entry latest-message table (config #1);
- plus the epoch-boundary registry sweep (config #4).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where
vs_baseline = (1 s target) / measured — >1 means faster than the north-star
target of <1 s on a TPU v5e (BASELINE.json).

Measurement methodology (revised in round 3 after discovering that
``jax.block_until_ready`` does NOT synchronize through the axon relay in
its default mode — timings taken that way measure enqueue latency, not
execution, and the r1/r2 recorded numbers are invalid for the TPU path):

see ``pos_evolution_tpu/utils/benchtime.py`` (the shared implementation of
the fused-loop work-difference recipe) for the details.
"""

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def _probe_accelerator(timeout_s: int = 90) -> bool:
    """Check the accelerator tunnel is alive in a subprocess (a wedged
    tunnel makes jax.devices() hang forever; never hang the bench).
    A real round-trip transfer is the probe — device enumeration alone
    can succeed while the execution path hangs."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax, numpy, jax.numpy as jnp; d=jax.devices(); "
             "numpy.asarray(jnp.arange(4) + 1); import sys; "
             "sys.exit(0 if d and d[0].platform != 'cpu' else 3)"],
            timeout=timeout_s, capture_output=True)
        return proc.returncode == 0
    except (subprocess.TimeoutExpired, OSError):
        return False


def main():
    if os.environ.get("POS_BENCH_CHILD") != "1" and not _probe_accelerator():
        # tunnel dead or CPU-only: re-exec pinned to CPU so the bench always
        # produces its JSON line
        env = dict(os.environ, POS_BENCH_CHILD="1", JAX_PLATFORMS="cpu",
                   PALLAS_AXON_POOL_IPS="")
        os.execve(sys.executable,
                  [sys.executable, os.path.abspath(__file__)] + sys.argv[1:],
                  env)
    import jax
    import jax.numpy as jnp

    from pos_evolution_tpu.config import mainnet_config
    from pos_evolution_tpu.ops.aggregation import aggregate_verify_batch
    from pos_evolution_tpu.ops.epoch import DenseRegistry, process_epoch_dense
    from pos_evolution_tpu.ops.forkchoice import DenseStore, head_and_weights
    from pos_evolution_tpu.telemetry import MetricsRegistry, jaxrt
    from pos_evolution_tpu.utils.benchtime import checksum_tree, fused_measure

    # JAX runtime telemetry for the whole bench: recompile counts, timed
    # dispatches, checksum transfer bytes — folded into the emitted JSON
    # so scripts/perf_gate.py can gate the NEXT run's counts against it.
    registry = MetricsRegistry()
    jaxrt.install(registry)

    on_accel = jax.default_backend() not in ("cpu",)
    # Per-invocation entropy folded into every salt: the relay's execution
    # cache persists ACROSS processes, so fixed salts + a fixed rng seed
    # would replay prior runs' results after the first invocation ever.
    entropy = int.from_bytes(os.urandom(3), "little")
    slots = 32
    committees_per_slot = 64
    a_total = slots * committees_per_slot           # 2048 aggregates
    capacity = 64                                   # fork-choice tree size
    gwei = 10**9
    cfg = mainnet_config()

    def make_epoch_body(n, agg_fn):
        """Build the one-epoch workload at validator count ``n``:
        aggregation + 32 head passes + epoch sweep, every output folded
        into the i32 accumulator (checksum_tree uses full reductions so no
        stage dead-code-eliminates).

        Returns ``(body, captures)`` where ``body(salt, acc, captures)``
        takes every input table as a TRACED capture pytree instead of a
        closure: closed-over tables are HLO constants, and XLA constant-
        folded the fork-choice vote reduction (an ``s64[65]`` scatter-add
        over the full message table) at compile time — >1 s per compile,
        twice, in the BENCH_r05 tail. See ``benchtime.fused_measure``'s
        ``captures`` contract; telemetry's ``jax_backend_compiles_total``
        pins that the traced form costs the same number of compiles
        (tests/test_profiling.py)."""
        lanes = max(n // a_total, 1)                # ~512 signers/aggregate at 1M
        rng = np.random.default_rng(0)

        reg = DenseRegistry(
            effective_balance=jnp.asarray(np.full(n, 32 * gwei, np.int64)),
            balance=jnp.asarray(rng.integers(31 * gwei, 33 * gwei, n).astype(np.int64)),
            activation_epoch=jnp.zeros(n, jnp.int64),
            exit_epoch=jnp.asarray(np.full(n, 2**62, np.int64)),
            withdrawable_epoch=jnp.asarray(np.full(n, 2**62, np.int64)),
            slashed=jnp.zeros(n, bool),
            prev_flags=jnp.asarray(rng.integers(0, 8, n).astype(np.uint8)),
            cur_flags=jnp.asarray(rng.integers(0, 8, n).astype(np.uint8)),
            inactivity_scores=jnp.zeros(n, jnp.int64),
        )
        bits = jnp.zeros(4, bool)

        pk_states = jnp.asarray(
            rng.integers(0, 2**32, (n, 8), dtype=np.uint64).astype(np.uint32))
        committees = jnp.asarray(
            rng.permutation(n)[: a_total * lanes].reshape(a_total, lanes).astype(np.int32))
        agg_bits = jnp.asarray(rng.random((a_total, lanes)) < 0.99)
        messages = jnp.asarray(
            rng.integers(0, 2**32, (a_total, 8), dtype=np.uint64).astype(np.uint32))
        signatures = jnp.asarray(rng.integers(0, 2**32, (a_total, 24), dtype=np.uint64)
                                 .astype(np.uint32))

        parent = np.arange(-1, capacity - 1, dtype=np.int32)
        store = DenseStore(
            parent=jnp.asarray(parent),
            slot=jnp.arange(capacity, dtype=jnp.int32),
            rank=jnp.asarray(rng.permutation(capacity).astype(np.int32)),
            real=jnp.ones(capacity, bool),
            leaf_viable=jnp.ones(capacity, bool),
            justified_idx=jnp.int32(0),
            msg_block=jnp.asarray(rng.integers(0, capacity, n).astype(np.int32)),
            msg_epoch=jnp.zeros(n, jnp.int64),
            weight=reg.effective_balance,
            boost_idx=jnp.int32(capacity - 1),
            boost_amount=jnp.int64(32 * gwei * (n // 32) // 4),
        )

        captures = {"store": store, "reg": reg, "pk_states": pk_states,
                    "committees": committees, "agg_bits": agg_bits,
                    "messages": messages, "signatures": signatures}

        def one_epoch(salt, acc, cap):
            store, reg = cap["store"], cap["reg"]
            ok = agg_fn(cap["pk_states"], cap["committees"], cap["agg_bits"],
                        cap["messages"].at[0, 0].set(
                            salt.astype(jnp.uint32)),
                        cap["signatures"])
            acc = acc + ok.sum().astype(jnp.int32)

            def head_body(s, a):
                t = salt.astype(jnp.int64) * slots + s
                st = store._replace(
                    msg_epoch=store.msg_epoch.at[0].set(t),
                    boost_idx=(t % capacity).astype(jnp.int32))
                h, w = head_and_weights(st, capacity)
                return a + h.astype(jnp.int32) + checksum_tree(w)

            acc = jax.lax.fori_loop(0, slots, head_body, acc)
            out = process_epoch_dense(
                reg._replace(balance=reg.balance.at[0].set(
                    31 * gwei + salt.astype(jnp.int64))),
                10, 8, bits, 8, 9, 0, cfg)
            return acc + checksum_tree(out)

        return one_epoch, captures

    # Watchdog supervision (utils/watchdog.py): every measurement phase is
    # a supervised step whose result is committed to JSON the moment it
    # lands, so a later compile OOM / hang cannot un-measure it; a failed
    # step records an incident and the bench still prints its line and
    # exits 0 (north-star: long device runs must die gracefully).
    from pos_evolution_tpu.utils.watchdog import Watchdog, WatchdogTimeout
    wd = Watchdog.from_env(
        "bench.py",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "bench_partial.json"))

    extra = {}
    if on_accel:
        body, caps = make_epoch_body(1_000_000, aggregate_verify_batch)
        best = wd.step(
            "xla_aggregation",
            fused_measure, body, captures=caps,
            entropy=entropy, tag="xla aggregation")
        # Race the Pallas per-committee aggregation kernel; keep the faster.
        # A Mosaic lowering/compile rejection is the EXPECTED fallback on
        # plenty of toolchains — handled inside the step (quiet stderr
        # note, None result) so it does not mark the run as degraded; the
        # watchdog incident path is for the step dying, not for opting out.
        def _pallas():
            try:
                from pos_evolution_tpu.ops.pallas_aggregation import (
                    aggregate_verify_batch_pallas_jit,
                )
                p_body, p_caps = make_epoch_body(
                    1_000_000, aggregate_verify_batch_pallas_jit)
                return fused_measure(
                    p_body, captures=p_caps,
                    entropy=entropy, tag="pallas aggregation")
            except WatchdogTimeout:
                raise          # a hang IS an incident, not an opt-out
            except Exception as e:
                print(f"# pallas aggregation unavailable: {e!r:.120}",
                      file=sys.stderr)
                return None

        t_pl = wd.step("pallas_aggregation", _pallas)
        candidates = [x for x in (best, t_pl) if x is not None]
        if not candidates:
            print(json.dumps({
                "metric": "epoch_1m_validators_aggregation_plus_forkchoice",
                "error": "no aggregation path completed",
                "incidents": wd.incidents,
                "telemetry": {"counts": registry.counts()},
            }))
            return
        t = float(min(candidates))
    else:
        # CPU fallback: no single-n linear extrapolation (the assumed
        # exponent was never validated — VERDICT r4 weak #1). Measure a
        # size ladder, fit the log-log scaling exponent, extrapolate to 1M
        # with the FITTED exponent, and report the raw (n, t) pairs so the
        # number is auditable. Each rung is a supervised step: a rung that
        # dies is dropped from the fit (and recorded as an incident).
        ns = [65_536, 131_072, 262_144]
        pairs = []
        for ni in ns:
            body_i, caps_i = make_epoch_body(ni, aggregate_verify_batch)
            ti = wd.step(f"xla_aggregation_n{ni}",
                         fused_measure, body_i, captures=caps_i,
                         entropy=entropy, tag=f"xla aggregation n={ni}")
            if ti is not None:
                pairs.append((ni, float(ti)))
        if len(pairs) < 2:
            print(json.dumps({
                "metric": "epoch_1m_validators_aggregation_plus_forkchoice",
                "error": "size ladder incomplete, cannot fit exponent",
                "measured_n_seconds": [[ni, round(ti, 6)] for ni, ti in pairs],
                "incidents": wd.incidents,
                "telemetry": {"counts": registry.counts()},
            }))
            return
        slope = float(np.polyfit(np.log([p[0] for p in pairs]),
                                 np.log([p[1] for p in pairs]), 1)[0])
        n_top, t_top = pairs[-1]
        t = t_top * (1_000_000 / n_top) ** slope
        extra = {
            "cpu_fallback": True,
            "measured_n_seconds": [[ni, round(ti, 6)] for ni, ti in pairs],
            "fitted_scaling_exponent": round(slope, 4),
            "extrapolation": f"t({n_top}) * (1e6/{n_top})**{slope:.4f}",
        }

    if "--trace" in sys.argv:
        # One traced epoch of the measured workload (SURVEY §5 / VERDICT
        # r4 item 7): xplane protobuf under bench_trace/, plus a top-op
        # table in bench_trace/top_ops.json via profiling/xplane.py.
        # The fresh trace lands in a TEMP dir and only replaces
        # bench_trace/ after the summary succeeds — a failed traced run
        # must not delete the committed top_ops.json artifact.
        import shutil
        import tempfile

        def _trace():
            from pos_evolution_tpu.utils.metrics import device_trace
            n_tr = 1_000_000 if on_accel else 65_536
            body, caps = make_epoch_body(n_tr, aggregate_verify_batch)
            traced = jax.jit(lambda s, cap: body(s, jnp.int32(0), cap))
            np.asarray(traced(jnp.int32(entropy), caps))  # compile outside
            here = os.path.dirname(os.path.abspath(__file__))
            trace_dir = os.path.join(here, "bench_trace")
            tmp_dir = tempfile.mkdtemp(prefix=".bench_trace_", dir=here)
            try:
                with device_trace(tmp_dir, annotation="bench_epoch"):
                    np.asarray(traced(jnp.int32(entropy + 1), caps))
                from pos_evolution_tpu.profiling.xplane import summarize_path
                top = summarize_path(tmp_dir)
                with open(os.path.join(tmp_dir, "top_ops.json"), "w") as f:
                    json.dump({"backend": jax.default_backend(), "n": n_tr,
                               "planes": top}, f, indent=1)
                # summary succeeded: swap via rename-aside so no window
                # exists where the committed artifact is deleted but the
                # new one not yet in place (a kill between rmtree and
                # rename would lose both)
                aside = tmp_dir + ".old"
                if os.path.isdir(trace_dir):
                    os.replace(trace_dir, aside)
                try:
                    os.replace(tmp_dir, trace_dir)
                except BaseException:
                    if os.path.isdir(aside):
                        os.replace(aside, trace_dir)   # restore committed
                    raise
                shutil.rmtree(aside, ignore_errors=True)
            except BaseException:
                shutil.rmtree(tmp_dir, ignore_errors=True)
                raise
            print(f"# trace: top-op table in {trace_dir}/top_ops.json",
                  file=sys.stderr)
            return os.path.join(trace_dir, "top_ops.json")

        trace_fresh = wd.step("trace", _trace) is not None
        if not trace_fresh:
            print("# trace failed (incident recorded; committed "
                  "bench_trace/ left untouched)", file=sys.stderr)
    else:
        trace_fresh = False

    # Default profiling pass (ISSUE 4; opt out with --no-profile): one
    # extra epoch at the smallest rung under a ProfiledRegion, exported as
    # Chrome trace_event JSON + a device flamegraph under bench_trace/.
    # Separate from --trace (which owns the top_ops.json swap protocol);
    # this is the always-on "where did the time go" artifact.
    here = os.path.dirname(os.path.abspath(__file__))
    profile_summary = None
    if "--no-profile" not in sys.argv:
        def _profile():
            from pos_evolution_tpu.profiling import (
                ProfiledRegion, attribution, xplane,
            )
            from pos_evolution_tpu.profiling.export import write_artifacts
            trace_dir = os.path.join(here, "bench_trace")
            planes = top_ops = by_jit = None
            # trace_fresh, not just "--trace in argv": a FAILED trace step
            # leaves the previous run's committed xplane in place, and
            # attributing an old build's trace to this run would poison
            # the emission and the history entry
            if trace_fresh and xplane.xplane_files(trace_dir):
                # --trace just captured this exact workload (same n, same
                # body): reuse its xplane instead of paying a second
                # multi-second compile + epoch run for identical data
                try:
                    planes = xplane.parse_path(trace_dir)
                    if not planes:        # parseable but empty: unusable
                        raise ValueError("no planes in --trace xplane")
                    top_ops = xplane.top_table(
                        xplane.summarize_planes(planes), 10)
                    by_jit = attribution.group_by_jit(
                        planes, exclude_ops={"bench_epoch"})
                except ValueError as e:   # torn/empty trace: recapture
                    print(f"# profile: --trace xplane unusable "
                          f"({e!r:.80}); capturing fresh", file=sys.stderr)
                    planes = None
            if planes is None:
                n_pr = 1_000_000 if on_accel else 65_536
                body, caps = make_epoch_body(n_pr, aggregate_verify_batch)
                traced = jax.jit(lambda s, cap: body(s, jnp.int32(0), cap))
                np.asarray(traced(jnp.int32(entropy + 3), caps))  # compile
                with ProfiledRegion("bench_epoch", top_n=10) as prof:
                    np.asarray(traced(jnp.int32(entropy + 4), caps))
                if prof.error:
                    raise RuntimeError(prof.error)
                planes, top_ops, by_jit = (prof.planes, prof.top_ops,
                                           prof.by_jit)
            # a CPU epoch records ~300K per-thunk events (~40 MB of JSON);
            # keep the 20K longest slices — the cap is recorded in the
            # trace's own "truncated" metadata event. top_ops=None: the
            # committed bench_trace/top_ops.json belongs to the --trace
            # step's swap protocol, never overwritten here.
            written = write_artifacts(trace_dir, planes=planes,
                                      max_device_events=20_000,
                                      exclude_ops={"bench_epoch"})
            print(f"# profile: Chrome trace in {written['chrome_trace.json']}"
                  f" (load in Perfetto / chrome://tracing)", file=sys.stderr)
            # report exactly what write_artifacts wrote — never claim a
            # flamegraph that an empty trace skipped
            rel = {name: os.path.join("bench_trace", name)
                   for name in written}
            return {"chrome_trace": rel.get("chrome_trace.json"),
                    "device_flame": rel.get("flame_device.txt"),
                    "top_ops": top_ops,
                    "by_jit": {k: v["total_ms"]
                               for k, v in by_jit.items()}}

        profile_summary = wd.step("profile", _profile)
        if profile_summary is not None:
            extra["profile"] = {k: profile_summary[k]
                                for k in ("chrome_trace", "device_flame",
                                          "by_jit")}

    if wd.incidents:
        # a degraded run must not print an indistinguishable "clean" line
        extra["watchdog_incidents"] = wd.incidents
    result = {
        "metric": "epoch_1m_validators_aggregation_plus_forkchoice",
        "value": round(t, 6),
        "unit": "s",
        "vs_baseline": round(1.0 / t, 3),
        "telemetry": {"counts": registry.counts()},
        **extra,
    }
    print(json.dumps(result))

    # Bench history (profiling/history.py): every run appends its full
    # emission (+ top device ops when profiled) to the schema-versioned
    # time-series scripts/perf_gate.py --history gates against.
    if "--no-history" not in sys.argv:
        try:
            from pos_evolution_tpu.profiling import history as _history
            _history.append_entry(
                os.path.join(here, "bench_history.jsonl"), result,
                kind="bench",
                top_ops=(profile_summary or {}).get("top_ops"))
        except Exception as e:
            print(f"# bench history append failed: {e!r:.120}",
                  file=sys.stderr)


if __name__ == "__main__":
    main()
