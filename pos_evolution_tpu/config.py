"""Frozen protocol configuration.

The reference spec is parameterized by named constants used throughout
(`pos-evolution.md:465-467,521,1021-1022,1054,126-128,587,1272,1355,1585,1589`).
We gather every knob into one frozen, hashable dataclass so it can be threaded
statically into jitted functions, with a mainnet-like preset and a small
"minimal" preset for fast tests (mirroring the pyspec mainnet/minimal split).
"""

from __future__ import annotations

import dataclasses
import threading
from contextlib import contextmanager

FAR_FUTURE_EPOCH = 2**64 - 1
GENESIS_EPOCH = 0
GENESIS_SLOT = 0
ETH_TO_GWEI = 10**9

# Participation flag indices (Altair participation accounting).
TIMELY_SOURCE_FLAG_INDEX = 0
TIMELY_TARGET_FLAG_INDEX = 1
TIMELY_HEAD_FLAG_INDEX = 2
PARTICIPATION_FLAG_WEIGHTS = (14, 26, 14)  # source, target, head
WEIGHT_DENOMINATOR = 64
PROPOSER_WEIGHT = 8
SYNC_REWARD_WEIGHT = 2

# BLS signature domains (4-byte little-endian tags).
DOMAIN_BEACON_PROPOSER = b"\x00\x00\x00\x00"
DOMAIN_BEACON_ATTESTER = b"\x01\x00\x00\x00"
DOMAIN_RANDAO = b"\x02\x00\x00\x00"
DOMAIN_DEPOSIT = b"\x03\x00\x00\x00"
DOMAIN_VOLUNTARY_EXIT = b"\x04\x00\x00\x00"
DOMAIN_SYNC_COMMITTEE = b"\x07\x00\x00\x00"


@dataclasses.dataclass(frozen=True)
class Config:
    """All protocol constants for one simulation/protocol instance.

    Hashable and immutable so it can be a static argument to ``jax.jit``.
    """

    name: str = "mainnet"

    # --- time / slot structure (pos-evolution.md:191-199, 1536) ---
    seconds_per_slot: int = 12
    intervals_per_slot: int = 3  # 3Δ slot: propose / attest / aggregate
    slots_per_epoch: int = 32

    # --- committees (pos-evolution.md:461-475) ---
    max_committees_per_slot: int = 64
    target_committee_size: int = 128
    max_validators_per_committee: int = 2048
    shuffle_round_count: int = 90  # pos-evolution.md:521
    min_seed_lookahead: int = 1
    max_seed_lookahead: int = 4

    # --- registry / balances (pos-evolution.md:110-134) ---
    validator_registry_limit: int = 2**40
    max_effective_balance: int = 32 * ETH_TO_GWEI
    effective_balance_increment: int = ETH_TO_GWEI
    ejection_balance: int = 16 * ETH_TO_GWEI
    hysteresis_quotient: int = 4
    hysteresis_downward_multiplier: int = 1
    hysteresis_upward_multiplier: int = 5
    min_deposit_amount: int = ETH_TO_GWEI

    # --- state history vectors (pos-evolution.md:346-357) ---
    slots_per_historical_root: int = 8192
    epochs_per_historical_vector: int = 65536
    epochs_per_slashings_vector: int = 8192
    historical_roots_limit: int = 2**24

    # --- attestations (pos-evolution.md:722-758) ---
    min_attestation_inclusion_delay: int = 1

    # --- justification / finalization (pos-evolution.md:817-852) ---
    justification_bits_length: int = 4

    # --- fork choice (pos-evolution.md:1021-1024, 1054, 1355) ---
    safe_slots_to_update_justified: int = 8
    # Boost as a percentage of one slot's committee weight. The reference
    # mainline uses W/4 (pos-evolution.md:1355); its attack analyses use
    # 0.7W and 0.8W (:1385, :1525), so this is a percent knob.
    proposer_score_boost_percent: int = 25

    # --- rewards ---
    base_reward_factor: int = 64
    inactivity_score_bias: int = 4
    inactivity_score_recovery_rate: int = 16
    inactivity_penalty_quotient: int = 2**24
    min_slashing_penalty_quotient: int = 64
    whistleblower_reward_quotient: int = 512
    proportional_slashing_multiplier: int = 2

    # --- deposits (pos-evolution.md:105-107, 139-175) ---
    deposit_contract_tree_depth: int = 32
    max_deposits: int = 16

    # --- block body operation limits (pos-evolution.md:632-644) ---
    max_proposer_slashings: int = 16
    max_attester_slashings: int = 2
    max_attestations: int = 128
    max_voluntary_exits: int = 16

    # --- sync committee (pos-evolution.md:542, 564-589) ---
    sync_committee_size: int = 512
    epochs_per_sync_committee_period: int = 256

    # --- validator lifecycle / churn ---
    min_validator_withdrawability_delay: int = 256
    min_per_epoch_churn_limit: int = 4
    churn_limit_quotient: int = 65536
    max_seed_lookahead_epochs: int = 4
    shard_committee_period: int = 256

    # --- weak subjectivity (pos-evolution.md:1225-1302) ---
    safety_decay: int = 10  # percent

    # --- eth1 ---
    epochs_per_eth1_voting_period: int = 64

    # --- merge transition (pos-evolution.md:1011-1013) ---
    # The simulator's PoW chain is tiny, so the default threshold is small;
    # mainnet's 5.875e22 would just be this knob set higher.
    terminal_total_difficulty: int = 2**20
    terminal_block_hash: bytes = b"\x00" * 32
    terminal_block_hash_activation_epoch: int = 2**64 - 1

    # --- data availability sampling (das/, DESIGN.md §15) ---
    # One blob = ``das_cells_per_blob`` data cells of ``das_cell_bytes``
    # bytes; Reed-Solomon extension doubles it to a 2k-cell grid, any k of
    # which reconstruct the blob. 2k must stay <= 256 (GF(2^8) evaluation
    # points) and power-of-two (the commitment tree is a padded binary
    # merkle tree over the extended grid).
    das_cell_bytes: int = 64
    das_cells_per_blob: int = 16
    das_max_blobs_per_block: int = 2
    das_samples_per_client: int = 8

    # --- KZG cell commitments (kzg/, DESIGN.md §23) ---
    # Seed of the deterministic (insecure-by-design) powers-of-tau
    # setup: every node and every resumed checkpoint must regenerate
    # the identical SRS from config alone, so tau derives from this
    # public value. The domain size is n_cells * cell_bytes/16.
    kzg_setup_seed: int = 0

    # --- device merkleization (ops/merkle_device.py, DESIGN.md §22) ---
    # Level sweeps with fewer sibling pairs than this stay on the host
    # SHA-256 path: below the crossover the fixed device-dispatch
    # overhead (transfer + launch) loses to the host kernel. Measured by
    # ``scripts/bench_merkle.py``; auto-dispatch additionally requires a
    # real accelerator (jax-on-CPU never wins against the native core).
    merkle_device_min_pairs: int = 4096

    # --- protocol-variant knobs (L7) ---
    # Vote expiry period η: ∞ (None→2**62) = LMD, 1 = Goldfish
    # (pos-evolution.md:1585).
    vote_expiry_slots: int = 2**62
    # Slot structure for propose-vote-merge protocols: 3 phases (3Δ) or
    # 4 phases (4Δ with fast confirmation, pos-evolution.md:1562,1617).
    phases_per_slot: int = 3
    # κ-deep (slow) confirmation rule depth (pos-evolution.md:1556).
    confirmation_depth: int = 4

    # ------------------------------------------------------------------
    @property
    def max_random_byte(self) -> int:
        return 2**8 - 1

    def slot_at_epoch(self, epoch: int) -> int:
        return epoch * self.slots_per_epoch

    def replace(self, **kw) -> "Config":
        return dataclasses.replace(self, **kw)


def mainnet_config() -> Config:
    return Config()


def minimal_config() -> Config:
    """Small preset for fast unit tests (analogous to pyspec 'minimal')."""
    return Config(
        name="minimal",
        slots_per_epoch=8,
        max_committees_per_slot=4,
        target_committee_size=4,
        shuffle_round_count=10,
        slots_per_historical_root=64,
        epochs_per_historical_vector=64,
        epochs_per_slashings_vector=64,
        sync_committee_size=32,
        epochs_per_sync_committee_period=8,
        min_validator_withdrawability_delay=32,
        safe_slots_to_update_justified=2,
        epochs_per_eth1_voting_period=4,
        inactivity_penalty_quotient=2**24,
        das_cells_per_blob=8,
        das_samples_per_client=4,
    )


# --- active-config context ---------------------------------------------------
# The spec-level functions keep the reference signatures
# (e.g. ``state_transition(state, signed_block)``) and therefore read the
# active config from a context, exactly like pyspec modules read module
# constants. Jitted array-level kernels instead take the config explicitly
# as a static argument.

_local = threading.local()


def cfg() -> Config:
    c = getattr(_local, "cfg", None)
    if c is None:
        c = mainnet_config()
        _local.cfg = c
    return c


def set_config(c: Config) -> None:
    _local.cfg = c


@contextmanager
def use_config(c: Config):
    prev = getattr(_local, "cfg", None)
    _local.cfg = c
    try:
        yield c
    finally:
        _local.cfg = prev
