"""Serving chaos mode: seeded failure injection for the RPC tier.

The serving twin of ``scripts/chaos_fuzz.py``'s network chaos — every
injection is a pure function of (seed, occasion), so a chaos run is
replayable, and every injection maps to a real operational failure:

- **worker stalls** — a worker thread sleeps mid-request (GC pause, a
  page fault storm, a noisy neighbor): the queue backs up, admission
  control must shed honestly and hedged retries must route around it;
- **cache wipes at block boundaries** — the proof-path LRU is cleared
  exactly when a new view publishes (process restart, cache eviction
  storm): the very next sampling wave is all-miss, the single-flight
  stampede case;
- **burst windows** — 10x arrival-rate multipliers for the load
  generator (a viral moment);
- **slow-loris clients** — connections that dribble a frame
  byte-by-byte and never finish: they must only ever cost the server
  their own connection reader, never a worker slot;
- **backing faults** — a window where every backing-store access raises
  (disk dies, downstream store partition): the circuit breaker must
  trip, answer ``unavailable`` honestly, and probe its way closed again.

The acceptance bar under ALL of this: throughput may degrade, latency
may spike, requests may be shed — but every proof actually served still
verifies and every rejection is honest.
"""

from __future__ import annotations

import hashlib
import socket
import struct
import threading
import time

__all__ = ["FdExhaustSwarm", "ServeChaos", "SlowLorisSwarm"]


def _unit(seed: int, *parts) -> float:
    """Deterministic [0, 1) draw from (seed, parts) — the
    ``sim/faults.stateless_unit`` posture for serving chaos."""
    h = hashlib.sha256(
        b"serve-chaos" + seed.to_bytes(8, "little", signed=True)
        + "/".join(str(p) for p in parts).encode()).digest()
    return int.from_bytes(h[:8], "little") / float(1 << 64)


class ServeChaos:
    """Seeded chaos schedule consulted by ``ServeFront`` at its hooks."""

    def __init__(self, seed: int = 0, stall_prob: float = 0.0,
                 stall_s: float = 0.05, wipe_prob: float = 0.0,
                 backing_fault_until: float | None = None,
                 clock=time.monotonic):
        self.seed = int(seed)
        self.stall_prob = float(stall_prob)
        self._stall_s = float(stall_s)
        self.wipe_prob = float(wipe_prob)
        # wall window (monotonic) during which backing access raises —
        # armed with ``fail_backing_for``
        self._backing_fault_until = backing_fault_until
        self.clock = clock
        self._stall_n = 0
        self._stall_windows: dict[int, list[tuple[float, float]]] = {}
        # process-level kill schedule: [fire_at, worker_id, fired]
        self._kill_sched: list[list] = []
        self._lock = threading.Lock()
        self.log: list[dict] = []

    # -- worker stalls ---------------------------------------------------------

    def arm_stalls(self, start: float, duration_s: float, n_stalls: int,
                   stall_s: float, workers: int) -> list[dict]:
        """Seeded wall-clock stall WINDOWS: worker w freezes for
        ``stall_s`` starting at a seeded offset inside [start, start +
        duration). Windows, not per-request draws — a per-request
        probability scales the injected damage with the arrival rate,
        which turns a 10x burst into a total outage instead of the
        'one worker went away for a while' failure it models."""
        planned = []
        for k in range(n_stalls):
            w = int(_unit(self.seed, "stall-worker", k) * workers)
            lo = start + _unit(self.seed, "stall-at", k) * max(
                duration_s - stall_s, 0.0)
            with self._lock:
                self._stall_windows.setdefault(w, []).append(
                    (lo, lo + stall_s))
            planned.append({"kind": "worker_stall_armed", "worker": w,
                            "at_s": round(lo - start, 3),
                            "stall_s": stall_s})
        with self._lock:
            self.log.extend(planned)
        return planned

    def stall_s(self, worker_id: int) -> float:
        """Seconds this worker must stall before its next request
        (0 almost always): the remainder of an armed window it is
        inside, or a seeded per-request draw when ``stall_prob`` is set
        (unit-test convenience)."""
        now = self.clock()
        with self._lock:
            for lo, hi in self._stall_windows.get(worker_id, ()):
                if lo <= now < hi:
                    self.log.append({"kind": "worker_stall",
                                     "worker": worker_id,
                                     "stall_s": round(hi - now, 4)})
                    return hi - now
        if self.stall_prob <= 0:
            return 0.0
        with self._lock:
            n = self._stall_n
            self._stall_n += 1
        if _unit(self.seed, "stall", worker_id, n) < self.stall_prob:
            with self._lock:
                self.log.append({"kind": "worker_stall",
                                 "worker": worker_id,
                                 "stall_s": self._stall_s})
            return self._stall_s
        return 0.0

    # -- process-level injections (the multi-process plane) --------------------

    def arm_worker_kills(self, start: float, duration_s: float,
                         n_kills: int, workers: int) -> list[dict]:
        """Seeded SIGKILL schedule against worker PROCESSES: kill k
        fires at a seeded offset inside [start, start + duration)
        against a seeded worker id. The pool's watch loop polls
        ``worker_kills_due`` and delivers the signal — chaos plans,
        the supervisor executes, so the kill shows up in the SAME
        interruption accounting as a real crash."""
        planned = []
        # seeded permutation, so n_kills <= workers hits DISTINCT
        # workers — the scenario bar is 'N live workers killed', which
        # a with-replacement draw can silently under-deliver
        order = sorted(range(workers),
                       key=lambda w: _unit(self.seed, "kill-order", w))
        with self._lock:
            for k in range(n_kills):
                w = order[k % workers]
                at = start + (0.15 + 0.7 * _unit(
                    self.seed, "kill-at", k)) * duration_s
                self._kill_sched.append([at, w, False])
                planned.append({"kind": "worker_kill_armed", "worker": w,
                                "at_s": round(at - start, 3)})
            self.log.extend(planned)
        return planned

    def worker_kills_due(self) -> list[int]:
        """Worker ids whose kill time has passed, each returned exactly
        once (the consumer SIGKILLs them)."""
        now = self.clock()
        due = []
        with self._lock:
            for item in self._kill_sched:
                if not item[2] and now >= item[0]:
                    item[2] = True
                    due.append(item[1])
                    self.log.append({"kind": "worker_kill_fired",
                                     "worker": item[1]})
        return due

    def wedge_windows(self, start_unix: float, duration_s: float,
                      n_wedges: int, wedge_s: float,
                      workers: int) -> dict[int, list[tuple[float, float]]]:
        """Seeded heartbeat-wedge windows in UNIX time, keyed by worker
        id — embedded into spawn specs (``spec["chaos"]["wedge_windows"]``)
        so the worker itself skips beats inside its window while still
        serving: the liveness lie the pool's hang detector must catch.
        Unix (not monotonic) time because the window crosses a process
        boundary; monotonic clocks do not agree across processes."""
        out: dict[int, list[tuple[float, float]]] = {}
        # draw wedge targets from the TAIL of the kill-order
        # permutation: kills + wedges <= workers then hit DISJOINT
        # workers, so each injection's detection path is exercised on
        # its own victim
        order = sorted(range(workers),
                       key=lambda w: _unit(self.seed, "kill-order", w))
        for k in range(n_wedges):
            w = order[workers - 1 - (k % workers)]
            lo = start_unix + _unit(self.seed, "wedge-at", k) * max(
                duration_s - wedge_s, 0.0)
            out.setdefault(w, []).append((lo, lo + wedge_s))
        with self._lock:
            self.log.append({"kind": "wedge_windows",
                             "workers": sorted(out)})
        return out

    # -- cache wipes on publish ------------------------------------------------

    def on_publish(self, front, view, version: int) -> None:
        """Block-boundary hook: seeded proof-cache wipe — the new block's
        first sampling wave then misses EVERYTHING at once."""
        if self.wipe_prob > 0 and _unit(self.seed, "wipe",
                                        version) < self.wipe_prob:
            front.das.proof_cache.clear()
            with self._lock:
                self.log.append({"kind": "cache_wipe", "version": version,
                                 "slot": int(view.slot)})

    # -- backing-store faults --------------------------------------------------

    def fail_backing_for(self, seconds: float) -> None:
        self._backing_fault_until = self.clock() + float(seconds)
        with self._lock:
            self.log.append({"kind": "backing_fault_window",
                             "seconds": float(seconds)})

    def maybe_backing_fault(self) -> None:
        until = self._backing_fault_until
        if until is not None and self.clock() < until:
            raise RuntimeError("chaos: backing store unavailable")

    # -- load-side helpers -----------------------------------------------------

    def burst_windows(self, duration_s: float, n_bursts: int = 1,
                      mult: float = 10.0,
                      width_frac: float = 0.1) -> tuple:
        """Seeded (t_lo, t_hi, mult) windows for the load generator."""
        out = []
        width = duration_s * width_frac
        for k in range(n_bursts):
            lo = _unit(self.seed, "burst", k) * (duration_s - width)
            out.append((lo, lo + width, mult))
        with self._lock:
            self.log.append({"kind": "burst_windows", "windows": out})
        return tuple(out)

    def summary(self) -> dict:
        with self._lock:
            log = list(self.log)
        kinds: dict[str, int] = {}
        for e in log:
            kinds[e["kind"]] = kinds.get(e["kind"], 0) + 1
        return {"seed": self.seed, "injections": kinds,
                "log_tail": log[-10:]}


class SlowLorisSwarm:
    """N connections that dribble one frame forever (until stopped).

    Each loris sends a valid length prefix claiming a large frame, then
    one byte every ``dribble_s`` — the attack that pins naive
    thread-per-request servers. The server's mid-frame read timeout must
    close these while real traffic keeps flowing.
    """

    def __init__(self, addr, n: int = 8, dribble_s: float = 0.5):
        self.addr = (addr[0], int(addr[1]))
        self.n = int(n)
        self.dribble_s = float(dribble_s)
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self.connected = 0
        self.closed_by_server = 0
        self._lock = threading.Lock()

    def _loris(self, k: int) -> None:
        try:
            sock = socket.create_connection(self.addr, timeout=2.0)
        except OSError:
            return
        with self._lock:
            self.connected += 1
        try:
            sock.sendall(struct.pack(">I", 1 << 20))  # promise 1 MiB...
            while not self._stop.is_set():
                sock.sendall(b"x")  # ...deliver a byte at a time
                if self._stop.wait(self.dribble_s):
                    break
        except OSError:
            with self._lock:
                self.closed_by_server += 1
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def start(self) -> None:
        for k in range(self.n):
            t = threading.Thread(target=self._loris, args=(k,),
                                 name=f"slow-loris-{k}", daemon=True)
            t.start()
            # start() runs once on the owning thread; the loris threads
            # never touch _threads
            self._threads.append(t)  # pev: ignore[PEV101]

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=3.0)


class FdExhaustSwarm:
    """N connections opened at once and held idle — the fd/conn-slot
    exhaustion window. The server's ``max_connections`` cap must refuse
    the overflow at accept (``conn_rejected``) while ALREADY-established
    traffic keeps flowing; when the swarm releases, capacity returns.
    Nothing is ever sent, so no worker slot is ever at risk — only
    accept-side resources are under attack."""

    def __init__(self, addr, n: int = 256, hold_s: float = 2.0):
        self.addr = (addr[0], int(addr[1]))
        self.n = int(n)
        self.hold_s = float(hold_s)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.connected = 0
        self.refused = 0

    def _run(self) -> None:
        socks = []
        connected = refused = 0
        for _ in range(self.n):
            if self._stop.is_set():
                break
            try:
                socks.append(socket.create_connection(self.addr,
                                                      timeout=1.0))
                connected += 1
            # not a swallow: the refusal IS the datum this swarm exists
            # to count (the server shedding accepts under fd pressure)
            except OSError:  # pev: ignore[PEV005]
                refused += 1
        self.connected, self.refused = connected, refused
        self._stop.wait(self.hold_s)
        for s in socks:
            try:
                s.close()
            # closing an already-dead socket during teardown
            except OSError:  # pev: ignore[PEV005]
                pass

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run,
                                        name="fd-exhaust", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
