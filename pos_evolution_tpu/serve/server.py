"""``ServeFront``: the multi-worker socket-facing RPC tier.

Architecture (all stdlib, DESIGN.md §19):

- an **acceptor** thread admits connections (bounded — a connection
  flood is load-shed at accept, before it owns any buffer);
- one **reader** thread per connection incrementally parses pipelined
  length-prefixed frames; a connection that stalls MID-frame past the
  read timeout is a slow-loris and is closed (it only ever held its own
  reader, never a worker); complete requests go through **admission**
  (``serve/admission.py``) — shed verdicts are answered straight from
  the reader in microseconds;
- N **worker** threads drain the two-tier queue (interactive strictly
  first), refuse work whose deadline already expired (honest
  ``timeout`` — deadline propagation means never doing work the client
  has stopped waiting for), run the handler with the remaining budget,
  and write the response under a per-connection lock;
- the DAS proof path shares the hardened ``LRUCache`` + per-(block,
  blob) single-flight with ``das/server.DasServer`` — one backing build
  per new (block, blob) however many sockets stampede it — and the
  **circuit breaker** wraps every backing-store access.

Handlers answer from the atomically published ``ServeView``
(``serve/state.py``): the driver's live stores are never touched from a
worker thread.
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import socket
import struct
import threading
import time

import numpy as np

# through the package __init__ (NOT das.server directly): the das
# package controls its own submodule import order, which keeps the
# serve <-> das import cycle one-directional at module scope
from pos_evolution_tpu.das import DasServer, LRUCache
from pos_evolution_tpu.das.server import _MISS
from pos_evolution_tpu.serve.admission import (
    AdmissionQueue,
    BrownoutController,
    CircuitBreaker,
    ServiceEstimator,
)
from pos_evolution_tpu.serve.protocol import (
    MAX_FRAME_BYTES,
    ProtocolError,
    send_frame,
)
from pos_evolution_tpu.serve.state import ServingState
from pos_evolution_tpu.telemetry.tracing import record_span

__all__ = ["ServeFront", "TIER_INTERACTIVE", "TIER_BULK", "METHOD_TIERS"]

TIER_INTERACTIVE = 0
TIER_BULK = 1

# The server derives the tier from the method — a client-declared tier
# is advisory only, or bulk traffic would simply claim to be interactive.
METHOD_TIERS = {
    "ping": TIER_INTERACTIVE,
    "head": TIER_INTERACTIVE,
    "finality": TIER_INTERACTIVE,
    "lc_update": TIER_INTERACTIVE,
    "stats": TIER_INTERACTIVE,
    "metrics": TIER_INTERACTIVE,
    "das_cells": TIER_BULK,
    "das_aggregate": TIER_BULK,
}

_LEN = struct.Struct(">I")
_LAT_CAP = 1 << 20  # exact per-tier latency samples kept for p999

# methods the reader may answer from the response cache WITHOUT a JSON
# parse ("stats" deliberately absent: it must take the full path)
_FAST_METHODS = {b"ping": "ping", b"head": "head",
                 b"finality": "finality", b"lc_update": "lc_update"}


def _scan_interactive(body: bytes):
    """``(id, method)`` when ``body`` is a well-formed interactive
    request in the clients' canonical encoding (``{"id":N,...`` with a
    ``"method":"..."`` member), else None — the json.loads a reader
    pays per request is most of a cached reply's cost at 20k+/s, and
    anything this scan cannot prove falls back to the full parse."""
    if not body.startswith(b'{"id":') or not body.endswith(b"}"):
        return None
    try:
        rid = int(body[6:body.index(b",", 6, 24)])
    except ValueError:
        return None
    m = body.find(b'"method":"')
    if m < 0:
        return None
    m += 10
    method = _FAST_METHODS.get(body[m:body.find(b'"', m)])
    return None if method is None else (rid, method)
# caps the das_cells RESPONSE well under MAX_FRAME_BYTES (a sample is
# ~cell_bytes + depth*32 hex-encoded); a real sampling client draws ~8
MAX_SAMPLES_PER_REQUEST = 512


class _Conn:
    """One accepted connection: socket + write lock + parse buffer."""

    __slots__ = ("sock", "wlock", "alive")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.wlock = threading.Lock()
        self.alive = True

    def reply(self, obj: dict) -> bool:
        try:
            with self.wlock:
                send_frame(self.sock, obj)
            return True
        except (OSError, ProtocolError):
            # ProtocolError = the RESPONSE outgrew the frame cap; the
            # worker must survive it (and the request cap on samples
            # makes it unreachable for honest handlers anyway)
            self.alive = False
            return False

    def reply_raw(self, payload: bytes) -> bool:
        """Send pre-encoded frame bytes (length prefix included) —
        the fast path's replies are built from cached templates and
        coalesced, one ``sendall`` per recv batch."""
        try:
            with self.wlock:
                self.sock.sendall(payload)
            return True
        except OSError:
            self.alive = False
            return False

    def close(self) -> None:
        self.alive = False
        try:
            self.sock.close()
        except OSError:
            pass


class ServeFront:
    """Multi-worker RPC front over a published ``ServingState``."""

    def __init__(self, state: ServingState, scheme=None, registry=None,
                 workers: int = 4, host: str = "127.0.0.1", port: int = 0,
                 das_server: DasServer | None = None,
                 proof_cache: int | LRUCache = 4096,
                 max_depth: int = 512, admit_factor: float = 0.8,
                 brownout: BrownoutController | None = None,
                 breaker: CircuitBreaker | None = None,
                 read_timeout_s: float = 2.0, max_connections: int = 512,
                 default_deadline_ms: float = 1000.0, chaos=None,
                 reuse_port: bool = False, ident: str | None = None,
                 metrics_dir: str | None = None,
                 worker_id: int | None = None):
        self.state = state
        self.registry = registry
        self.workers = int(workers)
        self.host, self.port = host, int(port)
        self.read_timeout_s = float(read_timeout_s)
        self.max_connections = int(max_connections)
        self.default_deadline_ms = float(default_deadline_ms)
        self.chaos = chaos
        # SO_REUSEPORT lets N worker PROCESSES bind the same port and
        # have the kernel spread connections across them — the
        # multi-process plane's listener strategy (serve/workers.py)
        self.reuse_port = bool(reuse_port)
        self.ident = ident
        # fleet observability (ISSUE 18): the ``metrics`` RPC aggregates
        # sibling snapshot files under metrics_dir on top of this
        # process's live registry (labelled worker_id)
        self.metrics_dir = metrics_dir
        self.worker_id = worker_id
        # per-view interactive response cache: head/finality/lc_update
        # answers are pure functions of the published view, so the hex
        # walks run once per (view, method), not once per request
        self._resp_view = None
        self._resp_cache: dict = {}
        # encoded twin of _resp_cache: (view, {method: reply-tail
        # bytes}) swapped as ONE tuple so reader threads never pair a
        # new view with a stale method's bytes
        self._fast: tuple = (None, {})
        # the DAS proof path IS a DasServer: same hardened LRU, same
        # single-flight, same scheme_builds counter — the socket tier and
        # the in-process vectorized path are one cache domain
        if das_server is not None:
            self.das = das_server
        else:
            assert scheme is not None, \
                "ServeFront needs a commitment scheme (or a DasServer)"
            self.das = DasServer(scheme, registry=registry,
                                 proof_cache=proof_cache)
        self.estimator = ServiceEstimator()
        self.queue = AdmissionQueue(self.workers, max_depth=max_depth,
                                    admit_factor=admit_factor,
                                    estimator=self.estimator)
        self.brownout = brownout or BrownoutController()
        self.breaker = breaker or CircuitBreaker()
        self._threads: list[threading.Thread] = []
        self._active_cfg = None      # captured at start(), see there
        self._conns: list[_Conn] = []
        self._conn_lock = threading.Lock()
        self._listener: socket.socket | None = None
        self._stopping = threading.Event()
        self._lat: dict[int, list[float]] = {TIER_INTERACTIVE: [],
                                             TIER_BULK: []}
        self._lat_lock = threading.Lock()
        # fast-path tallies: {method: [count, latency_sum_s]}, folded
        # into the registry in one update per method at read time
        # (_flush_fast_metrics) — the per-request counter inc +
        # histogram observe is most of a cached reply's CPU
        self._fast_ok: dict[str, list] = {}
        self.slow_loris_closed = 0
        self.conn_rejected = 0
        self.frame_errors = 0
        self.chaos_stalls = 0
        self.started_at: float | None = None
        # chaos cache wipes ride the publish boundary: a wiped proof
        # cache on a NEW block is the maximal stampede
        if chaos is not None and hasattr(chaos, "on_publish"):
            state.on_publish(lambda view, version: chaos.on_publish(
                self, view, version))

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> tuple[str, int]:
        from pos_evolution_tpu.config import cfg
        # capture the owning thread's active config: worker threads get
        # their own thread-local, and scheme handlers that read cfg()
        # (the kzg commit/aggregate geometry) must see the composition
        # the front was started under, not the defaults
        self._active_cfg = cfg()
        self.started_at = time.monotonic()
        lst = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        lst.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        if self.reuse_port:
            lst.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        lst.bind((self.host, self.port))
        lst.listen(256)
        self._listener = lst
        self.host, self.port = lst.getsockname()
        acceptor = threading.Thread(target=self._accept_loop,
                                    name="serve-accept", daemon=True)
        acceptor.start()
        # start() runs once on the owning thread before any worker exists;
        # _threads is never touched from the spawned threads
        self._threads.append(acceptor)  # pev: ignore[PEV101]
        for w in range(self.workers):
            t = threading.Thread(target=self._worker_loop, args=(w,),
                                 name=f"serve-worker-{w}", daemon=True)
            t.start()
            self._threads.append(t)  # pev: ignore[PEV101]
        return self.host, self.port

    def stop(self) -> None:
        self._stopping.set()
        self.queue.close()
        # honest drain: whatever was admitted but not yet served gets a
        # shed + retry-after answer before its connection dies — a
        # stopping (or SIGTERM'd) worker never swallows queued work
        for item in self.queue.drain():
            req, conn, _arrival, _expires, tier = item
            self._count("serve_requests_total", "requests by status",
                        method=req.get("method"), status="shed")
            self._count("serve_shed_total", "load-shed requests",
                        tier=tier, reason="draining")
            if conn is not None:  # best-effort: the conn may be gone
                conn.reply({"id": req["id"], "status": "shed",
                            "reason": "draining", "retry_after_ms": 50.0})
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._conn_lock:
            conns = list(self._conns)
        for conn in conns:
            conn.close()
        for t in self._threads:
            t.join(timeout=5.0)

    # -- metrics helpers -------------------------------------------------------

    def _count(self, name: str, help_: str, n: int = 1, **labels) -> None:
        if self.registry is not None:
            self.registry.counter(name, help_).inc(n, **labels)

    def _record_latency(self, tier: int, seconds: float,
                        status: str) -> None:
        with self._lat_lock:
            lat = self._lat[tier]
            if len(lat) < _LAT_CAP:
                lat.append(seconds)
        if self.registry is not None:
            self.registry.histogram(
                "serve_request_seconds",
                "arrival -> response write, per tier").observe(
                seconds, tier=tier, status=status)

    # -- accept / read ---------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return  # listener closed
            with self._conn_lock:
                # prune dead connections here (the one place that scans
                # anyway): without it the list grows for the server's
                # lifetime under connection churn
                self._conns = [c for c in self._conns if c.alive]
                n_alive = len(self._conns)
                if n_alive >= self.max_connections:
                    self.conn_rejected += 1
                    sock.close()
                    continue
                sock.settimeout(self.read_timeout_s)
                # small request/response frames ping-ponging through
                # Nagle + delayed ACK stall for whole ACK timeouts;
                # at serving rates that idleness IS the latency floor
                sock.setsockopt(socket.IPPROTO_TCP,
                                socket.TCP_NODELAY, 1)
                conn = _Conn(sock)
                self._conns.append(conn)
            t = threading.Thread(target=self._reader_loop, args=(conn,),
                                 name="serve-reader", daemon=True)
            t.start()

    def _reader_loop(self, conn: _Conn) -> None:
        """Incremental frame parser: pipelined requests, slow-loris
        detection (a read timeout with a PARTIAL frame buffered means the
        peer is dribbling; an empty buffer is just an idle connection)."""
        buf = bytearray()
        while conn.alive and not self._stopping.is_set():
            try:
                chunk = conn.sock.recv(65536)
            except socket.timeout:
                if buf:
                    with self._conn_lock:  # N readers share this counter
                        self.slow_loris_closed += 1
                    self._count("serve_slow_loris_closed_total",
                                "connections dropped mid-frame")
                    conn.close()
                    return
                continue  # idle is fine
            except OSError:
                conn.close()
                return
            if not chunk:
                conn.close()
                return
            buf.extend(chunk)
            # fast-path replies for THIS recv batch coalesce into one
            # sendall — a pipelined client's 64-frame burst costs one
            # write syscall, not 64
            out: list = []
            while True:
                if len(buf) < _LEN.size:
                    break
                (length,) = _LEN.unpack(buf[:_LEN.size])
                if length > MAX_FRAME_BYTES:
                    # counted: a peer streaming unframed bytes reads as
                    # a giant bogus length here, and an uncounted close
                    # makes that bug invisible in every stats bundle
                    with self._conn_lock:
                        self.frame_errors += 1
                    conn.close()
                    return
                if len(buf) < _LEN.size + length:
                    break
                body = bytes(buf[_LEN.size:_LEN.size + length])
                del buf[:_LEN.size + length]
                try:
                    self._on_request(conn, body, out)
                except Exception:
                    # ProtocolError or anything a hostile payload can
                    # provoke: close THIS connection (flushing replies
                    # already owed for earlier frames in the batch); a
                    # dead reader with a live socket would leak a slot
                    with self._conn_lock:
                        self.frame_errors += 1
                    if out:
                        conn.reply_raw(b"".join(out))
                    conn.close()
                    return
            if out:
                conn.reply_raw(b"".join(out))

    _PING_TAIL = b',"status":"ok","result":{},"served_by":-1}'

    def _on_request(self, conn: _Conn, body: bytes,
                    out: list | None = None) -> None:
        # parse-free fast path: a canonical interactive request whose
        # answer is already in the per-view cache is served straight
        # from the byte scan — id + method are the only fields a cached
        # reply depends on (the deadline cannot matter: the reply is
        # constructed inline, microseconds after arrival)
        scan = _scan_interactive(body)
        if scan is not None:
            rid, method = scan
            if method == "ping":
                tail = self._PING_TAIL
            else:
                fview, tmpl = self._fast
                tail = (tmpl.get(method)
                        if fview is self.state.current() else None)
            if tail is not None:
                arrival = time.monotonic()
                rbody = b'{"id":%d' % rid + tail
                payload = _LEN.pack(len(rbody)) + rbody
                dt = time.monotonic() - arrival
                with self._lat_lock:
                    lat = self._lat[TIER_INTERACTIVE]
                    if len(lat) < _LAT_CAP:
                        lat.append(dt)
                    row = self._fast_ok.get(method)
                    if row is None:
                        self._fast_ok[method] = row = [0, 0.0]
                    row[0] += 1
                    row[1] += dt
                if out is not None:
                    out.append(payload)
                else:
                    conn.reply_raw(payload)
                return
        try:
            req = json.loads(body)
        except json.JSONDecodeError as e:
            raise ProtocolError(str(e)) from None
        if not isinstance(req, dict) or not isinstance(req.get("id"), int):
            raise ProtocolError("request must be an object with int id")
        method = req.get("method")
        tier = (METHOD_TIERS.get(method)
                if isinstance(method, str) else None)
        if tier is None:
            # fixed label, never the raw string: attacker-chosen method
            # names must not mint unbounded counter series (or smuggle
            # ';'/'=' into the label encoding)
            self._count("serve_requests_total", "requests by status",
                        method="<unknown>", status="error")
            conn.reply({"id": req["id"], "status": "error",
                        "error": f"unknown method {str(method)[:64]!r}"})
            return
        arrival = time.monotonic()
        if method == "metrics":
            # admission-exempt introspection: answered from memory on
            # the reader thread — never queued, never breaker-gated —
            # so the fleet stays observable through overload and
            # backing outages (the whole point of a metrics scrape)
            self._count("serve_requests_total", "requests by status",
                        method=method, status="ok")
            self._record_latency(TIER_INTERACTIVE,
                                 time.monotonic() - arrival, "ok")
            conn.reply({"id": req["id"], "status": "ok",
                        "result": self._metrics_payload(),
                        "served_by": -1})
            return
        trace = req.get("trace")
        traced = (trace.get("id")
                  if isinstance(trace, dict) and trace.get("s") else None)
        # interactive fast path: when the per-view response cache
        # already holds this method's answer, serve it straight from
        # the reader — a queue hop (condvar wakeup + worker context
        # switch) costs more than the cached reply itself, and at
        # 20k+/s on a shared core that overhead IS the capacity limit.
        # The FIRST request per (view, method) still takes the full
        # admission path and populates the cache; bulk always queues.
        # A TRACED request always queues too: its spans (queue wait,
        # service) are the observation, and sampled traffic is rare
        # enough that skipping the template costs nothing measurable.
        if tier == TIER_INTERACTIVE and method != "stats" \
                and traced is None:
            if method == "ping":
                tail = self._PING_TAIL
            else:
                fview, tmpl = self._fast
                tail = (tmpl.get(method)
                        if fview is self.state.current() else None)
            if tail is not None:
                self._count("serve_requests_total",
                            "requests by status",
                            method=method, status="ok")
                self._record_latency(tier, time.monotonic() - arrival,
                                     "ok")
                rbody = b'{"id":%d' % req["id"] + tail
                payload = _LEN.pack(len(rbody)) + rbody
                if out is not None:
                    out.append(payload)
                else:
                    conn.reply_raw(payload)
                return
        deadline_ms = req.get("deadline_ms", self.default_deadline_ms)
        # NaN/Infinity parse as valid JSON numbers and would sail past
        # every `now >= expires_at` / projected-wait comparison —
        # bypassing the admission control this tier is built on. Only a
        # FINITE client deadline is honored.
        budget_s = (float(deadline_ms) / 1e3
                    if isinstance(deadline_ms, (int, float))
                    and not isinstance(deadline_ms, bool)
                    and math.isfinite(deadline_ms)
                    else self.default_deadline_ms / 1e3)
        item = (req, conn, arrival, arrival + budget_s, tier)
        verdict = self.queue.offer(item, tier, budget_s,
                                   brownout=self.brownout.active)
        if verdict is not None:
            # honest rejection from the reader thread: the worker pool
            # never sees work the tier cannot finish in time
            self._count("serve_requests_total", "requests by status",
                        method=method, status="shed")
            self._count("serve_shed_total", "load-shed requests",
                        tier=tier, reason=verdict["reason"])
            conn.reply({"id": req["id"], "status": "shed",
                        "reason": verdict["reason"],
                        "retry_after_ms": verdict["retry_after_ms"]})

    # -- workers ---------------------------------------------------------------

    def _worker_loop(self, worker_id: int) -> None:
        from pos_evolution_tpu.config import use_config
        with contextlib.ExitStack() as stack:
            if self._active_cfg is not None:
                stack.enter_context(use_config(self._active_cfg))
            self._worker_body(worker_id)

    def _worker_body(self, worker_id: int) -> None:
        while not self._stopping.is_set():
            item = self.queue.take(timeout=0.25)
            if item is None:
                continue
            try:
                self._serve_item(worker_id, item)
            except Exception:
                # last-resort guard: whatever a hostile request managed
                # to provoke, a worker thread must never die — a dead
                # worker is capacity lost for the server's lifetime
                self._count("serve_worker_errors_total",
                            "requests that escaped every handler path")

    def _serve_item(self, worker_id: int, item) -> None:
        req, conn, arrival, expires_at, tier = item
        if self.chaos is not None:
            stall = self.chaos.stall_s(worker_id)
            if stall > 0:
                with self._conn_lock:  # N workers share this counter
                    self.chaos_stalls += 1
                self._count("serve_chaos_stalls_total",
                            "chaos-injected worker stalls")
                time.sleep(stall)
        now = time.monotonic()
        wait_s = now - arrival
        if tier == TIER_INTERACTIVE:
            self.brownout.observe_interactive_wait(wait_s)
        method = req["method"]
        trace = req.get("trace")
        traced = (trace.get("id")
                  if isinstance(trace, dict) and trace.get("s") else None)
        if traced is not None:
            record_span(traced, "queue_wait", time.time() - wait_s,
                        wait_s * 1e3, tid=worker_id, method=method)
        if now >= expires_at:
            # deadline propagation: the client stopped waiting —
            # touching the backing store now would be pure waste
            self._count("serve_requests_total", "requests by status",
                        method=method, status="timeout")
            self._record_latency(tier, now - arrival, "timeout")
            if traced is not None:
                record_span(traced, "service", time.time(), 0.0,
                            tid=worker_id, method=method,
                            status="timeout")
            conn.reply({"id": req["id"], "status": "timeout"})
            return
        # the circuit breaker guards the BACKING STORE, so only the
        # methods that touch it consult it — head/finality answer
        # from the in-memory view even while the store is down
        backed = method in ("das_cells", "das_aggregate")
        if backed:
            allowed, retry_s = self.breaker.allow()
            if not allowed:
                self._count("serve_requests_total",
                            "requests by status",
                            method=method, status="unavailable")
                self._record_latency(tier, now - arrival,
                                     "unavailable")
                conn.reply({"id": req["id"], "status": "unavailable",
                            "reason": "circuit_open",
                            "retry_after_ms": round(retry_s * 1e3, 3)})
                return
        t0 = time.monotonic()
        try:
            result = self._handle(method, req.get("params") or {},
                                  expires_at, trace=traced,
                                  tid=worker_id)
            if backed:
                self.breaker.record_success()
            status = "ok"
            resp = {"id": req["id"], "status": "ok", "result": result,
                    "served_by": worker_id}
        except _Expired:
            # no verdict on the backing store was reached — release
            # any probe slot we held, or a mid-handler expiry in
            # half-open would wedge the breaker forever
            if backed:
                self.breaker.abandon()
            status = "timeout"
            resp = {"id": req["id"], "status": "timeout"}
        except _BadRequest as e:
            # the CLIENT was wrong (bad hex, rotated-out root,
            # out-of-range sample) — says nothing about backing
            # health, so it must not trip the breaker open
            if backed:
                self.breaker.abandon()
            status = "error"
            resp = {"id": req["id"], "status": "error",
                    "error": str(e)}
        except _NotReady as e:
            # the SERVER isn't ready (no view yet) — also not a
            # backing-store verdict; an honest unavailable with a
            # short retry-after instead of a breaker trip
            if backed:
                self.breaker.abandon()
            status = "unavailable"
            resp = {"id": req["id"], "status": "unavailable",
                    "reason": str(e), "retry_after_ms": 50.0}
        except Exception as e:
            if backed:
                self.breaker.record_failure()
            status = "error"
            resp = {"id": req["id"], "status": "error",
                    "error": f"{type(e).__name__}: {e}"}
        service_s = time.monotonic() - t0
        if status == "ok":
            self.estimator.observe(service_s)
        self._count("serve_requests_total", "requests by status",
                    method=method, status=status)
        self._record_latency(tier, wait_s + service_s, status)
        if traced is not None:
            record_span(traced, "service", time.time() - service_s,
                        service_s * 1e3, tid=worker_id, method=method,
                        status=status, worker=self.worker_id)
        conn.reply(resp)

    # -- handlers --------------------------------------------------------------

    def _view(self):
        view = self.state.current()
        if view is None:
            # not the backing store's fault: the driver just hasn't
            # published yet — honest "come back shortly", never a
            # breaker trip
            raise _NotReady("no serving view published yet")
        return view

    def _handle(self, method: str, params: dict, expires_at: float,
                trace: str | None = None, tid: int = 0):
        if method == "ping":
            return {}
        if method == "stats":
            return self.summary()
        if method == "metrics":
            # normally answered on the reader thread; reachable here
            # only through in-process calls — same memory-served payload
            return self._metrics_payload()
        view = self._view()
        if method in ("head", "finality", "lc_update"):
            # identity-keyed per-view cache: these answers are pure
            # functions of the published view, and the hex walks are
            # most of an interactive request's CPU at high rate
            if self._resp_view is not view:
                self._resp_view, self._resp_cache = view, {}
            hit = self._resp_cache.get(method)
            if hit is None:
                if method == "head":
                    hit = view.head_summary()
                elif method == "finality":
                    hit = view.finality_summary()
                elif view.update_ssz is None:
                    hit = {"update": None, "update_root": None}
                else:
                    hit = {"update": view.update_ssz.hex(),
                           "update_root": view.update_root.hex()}
                # idempotent per-view memo: concurrent builders store
                # equal values, so a lost setitem costs one recompute
                # pev: ignore[PEV101]
                self._resp_cache[method] = hit
            fast = self._fast
            if fast[0] is not view:
                fast = (view, {})
                self._fast = fast
            if method not in fast[1]:
                enc = json.dumps(hit, separators=(",", ":")).encode()
                fast[1][method] = (b',"status":"ok","result":' + enc
                                   + b',"served_by":-1}')
            return hit
        if method == "das_aggregate":
            return self._das_aggregate(view, params, expires_at,
                                       trace=trace, tid=tid)
        assert method == "das_cells"
        return self._das_cells(view, params, expires_at,
                               trace=trace, tid=tid)

    def _parse_das_params(self, view, params: dict):
        try:
            root = bytes.fromhex(params["block_root"])
            samples = [(int(b), int(c)) for b, c in params["samples"]]
        except (KeyError, TypeError, ValueError) as e:
            raise _BadRequest(f"malformed das params: {e}") from None
        if len(samples) > MAX_SAMPLES_PER_REQUEST:
            # also bounds the RESPONSE size under the frame cap — a
            # huge sample list must be an honest refusal, not a reply
            # too large to send
            raise _BadRequest(
                f"{len(samples)} samples exceeds the per-request cap "
                f"of {MAX_SAMPLES_PER_REQUEST}")
        sidecars = view.sidecars.get(root)
        if sidecars is None:
            raise _BadRequest(f"block {root.hex()[:16]} not in the "
                              f"serving window")
        for blob, cell in samples:
            if not (0 <= blob < len(sidecars) and 0 <= cell < view.n_cells):
                raise _BadRequest(f"sample ({blob}, {cell}) outside the "
                                  f"grid")
        return root, samples, sidecars

    def _das_aggregate(self, view, params: dict, expires_at: float,
                       trace: str | None = None, tid: int = 0) -> dict:
        """One aggregated opening proof for the request's whole sampled
        set (kzg-style schemes) — the response ships |proof| bytes total
        instead of depth*32 bytes per sample."""
        scheme = self.das.scheme
        if not getattr(scheme, "aggregates", False):
            raise _BadRequest(
                f"scheme {scheme.name!r} serves per-cell branches; "
                f"use das_cells")
        root, samples, sidecars = self._parse_das_params(view, params)
        # canonical coords: the proof covers the deduped sorted set (the
        # transcript is order-sensitive, so server and client must agree)
        coords = tuple(sorted(set(samples)))
        if time.monotonic() >= expires_at:
            raise _Expired()
        if self.chaos is not None:
            self.chaos.maybe_backing_fault()
        leads0 = self.das._flight.leads
        b_wall, b_t0 = time.time(), time.monotonic()
        proof = self.das.build_aggregate_proof(root, sidecars, coords)
        if trace is not None:
            # single-flight followers share the trace id AND the time
            # range of the leader's build — the merged trace links them
            record_span(trace, "backing", b_wall,
                        (time.monotonic() - b_t0) * 1e3, tid=tid,
                        kind="das_aggregate", block=root.hex()[:16],
                        flight=("lead" if self.das._flight.leads > leads0
                                else "follow"))
        grids = {b for b, _ in coords}
        cells_out = [
            bytes(np.ascontiguousarray(sidecars[b].cells,
                                       dtype=np.uint8)[c]).hex()
            for b, c in coords]
        return {
            "block_root": root.hex(),
            "scheme": scheme.name,
            "commitments": [bytes(sc.commitment).hex() for sc in sidecars],
            "samples": [[int(b), int(c)] for b, c in coords],
            "cells": cells_out,
            "proof": [p.hex() for p in scheme.encode_proof(proof)],
            "proof_bytes": int(scheme.proof_n_bytes(proof)),
            "n_cells": int(view.n_cells),
            "blobs_opened": len(grids),
        }

    def _das_cells(self, view, params: dict, expires_at: float,
                   trace: str | None = None, tid: int = 0) -> dict:
        if getattr(self.das.scheme, "aggregates", False):
            # an aggregate scheme has no per-cell branch walk to serve —
            # honest refusal, not an AttributeError in a worker
            raise _BadRequest(
                f"scheme {self.das.scheme.name!r} serves aggregated "
                f"proofs; use das_aggregate")
        root, samples, sidecars = self._parse_das_params(view, params)
        cells_out, branches_out = [], []
        cache = self.das.proof_cache
        for blob, cell in samples:
            hit = cache.get((root, blob, cell))
            if hit is _MISS:
                # budget check before the (comparatively) expensive
                # backing build — a mid-request expiry becomes an honest
                # timeout instead of a late answer nobody reads
                if time.monotonic() >= expires_at:
                    raise _Expired()
                # the proof build IS the backing-store access: an
                # in-memory head scalar never needs the store, so only
                # this path feels a chaos backing outage (and only this
                # path's failures should trip the breaker open)
                if self.chaos is not None:
                    self.chaos.maybe_backing_fault()
                leads0 = self.das._flight.leads
                b_wall, b_t0 = time.time(), time.monotonic()
                built = self.das.build_blob_proofs(root, blob,
                                                   sidecars[blob])
                if trace is not None:
                    record_span(
                        trace, "backing", b_wall,
                        (time.monotonic() - b_t0) * 1e3, tid=tid,
                        kind="das_cells", block=root.hex()[:16],
                        blob=blob,
                        flight=("lead"
                                if self.das._flight.leads > leads0
                                else "follow"))
                hit = built[cell]
            cell_bytes, branch = hit
            cells_out.append(bytes(cell_bytes).hex())
            branches_out.append([bytes(b).hex() for b in branch])
        return {
            "block_root": root.hex(),
            "commitments": [bytes(sidecars[int(b)].commitment).hex()
                            for b, _ in samples],
            "indices": [int(c) for _, c in samples],
            "cells": cells_out,
            "branches": branches_out,
            "n_cells": int(view.n_cells),
        }

    # -- reporting -------------------------------------------------------------

    def _percentiles(self, xs: list[float]) -> dict:
        from pos_evolution_tpu.utils.metrics import percentile_ms
        if not xs:
            return {"count": 0}
        return {"count": len(xs), "p50_ms": percentile_ms(xs, 50),
                "p99_ms": percentile_ms(xs, 99),
                "p999_ms": percentile_ms(xs, 99.9)}

    def _metrics_payload(self) -> dict:
        """The ``metrics`` RPC result: this process's LIVE registry plus
        every sibling snapshot under ``metrics_dir``, merged with
        per-worker labels (ISSUE 18 leg a). Served entirely from memory
        + local files — no queue, no backing store, no breaker."""
        from pos_evolution_tpu.telemetry import fleet
        from pos_evolution_tpu.telemetry.registry import SNAPSHOT_VERSION
        self._flush_fast_metrics()
        agg = fleet.FleetAggregator()
        own = None
        if self.metrics_dir is not None:
            if self.worker_id is not None:
                # skip our OWN snapshot file: the live registry below is
                # the same counters, fresher — merging both doubles them
                own = os.path.abspath(fleet.snapshot_path(
                    self.metrics_dir, self.worker_id, os.getpid()))
            for path in fleet.discover_snapshots(self.metrics_dir):
                if own is not None and os.path.abspath(path) == own:
                    continue
                agg.add(fleet.load_snapshot(path))
        if self.registry is not None:
            agg.add({
                "v": SNAPSHOT_VERSION,
                "worker": (self.worker_id if self.worker_id is not None
                           else 0),
                "pid": os.getpid(), "front": None, "generation": None,
                "wall": time.time(),
                "registry": self.registry.snapshot(),
            })
        return {
            "fleet": agg.summary(),
            "prometheus": agg.registry.to_prometheus(),
        }

    def _flush_fast_metrics(self) -> None:
        """Fold fast-path tallies into the registry — one counter inc
        and one batched histogram update per method instead of one of
        each per request."""
        with self._lat_lock:
            if not self._fast_ok:
                return
            pending, self._fast_ok = self._fast_ok, {}
        if self.registry is None:
            return
        for method, (n, total_s) in pending.items():
            self.registry.counter(
                "serve_requests_total", "requests by status").inc(
                n, method=method, status="ok")
            self.registry.histogram(
                "serve_request_seconds",
                "arrival -> response write, per tier").observe_n(
                total_s / n, n, tier=TIER_INTERACTIVE, status="ok")

    def summary(self) -> dict:
        """The ``serve_summary`` payload: everything the run report's
        "Serving" section and the bench_serve emission need."""
        self._flush_fast_metrics()
        with self._lat_lock:
            lat = {t: list(v) for t, v in self._lat.items()}
        by_status: dict[str, int] = {}
        by_method: dict[str, int] = {}
        if self.registry is not None:
            for key, val in self.registry.counts().items():
                if key.startswith("serve_requests_total;"):
                    labels = dict(p.split("=", 1)
                                  for p in key.split(";")[1:]
                                  if "=" in p)
                    st, me = labels.get("status"), labels.get("method")
                    by_status[st] = by_status.get(st, 0) + val
                    by_method[me] = by_method.get(me, 0) + val
        total = sum(by_status.values())
        shed = by_status.get("shed", 0)
        cache = self.das.proof_cache
        return {
            "workers": self.workers,
            "queue_depth": self.queue.depth(),
            "admitted": self.queue.admitted,
            "requests_total": total,
            "by_status": by_status,
            "by_method": by_method,
            "shed_rate": round(shed / total, 4) if total else 0.0,
            "shed_by_reason": dict(self.queue.shed),
            "interactive": self._percentiles(lat[TIER_INTERACTIVE]),
            "bulk": self._percentiles(lat[TIER_BULK]),
            "brownout_transitions": len(self.brownout.transitions),
            "brownout_active": self.brownout.active,
            "breaker_state": self.breaker.state,
            "breaker_transitions": len(self.breaker.transitions),
            "singleflight": {"leads": self.das._flight.leads,
                             "waits": self.das._flight.waits},
            "scheme_builds": self.das.scheme_builds,
            "proof_cache": {"hits": cache.hits, "misses": cache.misses,
                            "hit_rate": round(cache.hit_rate, 4)},
            "slow_loris_closed": self.slow_loris_closed,
            "conn_rejected": self.conn_rejected,
            "frame_errors": self.frame_errors,
            "chaos_stalls": self.chaos_stalls,
            "service_ema_ms": round(self.estimator.ema_s * 1e3, 4),
        }


class _Expired(Exception):
    """Internal: the request's deadline expired mid-handler."""


class _BadRequest(Exception):
    """Internal: the client's parameters were wrong. Answered as an
    honest ``error`` but NEVER counted against the backing store — a
    hostile client must not be able to trip the breaker open."""


class _NotReady(Exception):
    """Internal: the server has no published view yet. Answered as an
    honest ``unavailable`` + retry-after; not a backing-store verdict."""
