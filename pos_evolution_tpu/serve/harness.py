"""The multi-process serving scenario, runnable from one call.

``run_mp_scenario`` stands up the whole plane — shared-memory view
board, a supervised :class:`WorkerPool` of SO_REUSEPORT worker
processes, a live view publisher, a health-routed :class:`Balancer`,
and the pipelined :class:`SwarmLoadGenerator` — drives it at the
requested rate under seeded process-level chaos (worker SIGKILLs,
heartbeat wedges, an fd-exhaustion window), then tears everything down
and returns one self-judging result dict.

Three callers share it so their verdicts cannot drift apart:

- ``scripts/serve_demo.py --mp`` — the headline demo artifact;
- ``scripts/chaos_fuzz.py --serve-mp`` — the chaos gate (exit code
  follows ``verdict["ok"]``);
- the ``serve-mp-smoke`` CI job.

The verdict bar (what "the plane survives chaos" means here):

- **accounting**: every scheduled arrival resolves — answered, retried
  to resolution, or recorded ``lost``; records == schedule, always;
- **integrity**: zero bulk-proof verification failures — overload may
  shed, it may NEVER corrupt;
- **goodput**: interactive goodput and p99 stay inside the SLO while
  workers are being killed and wedged under them;
- **supervision**: every armed kill shows up in the pool's interruption
  ledger as a crash, every wedge is caught by hang detection, and every
  respawned worker serves from the CURRENT shared-memory generation
  (a respawn that serves a stale view is a silent fork);
- **observability**: the fleet metrics scraped off the admission-exempt
  ``metrics`` RPC agree with the loadgen's own ledger — per-worker
  request counts sum to the arrivals actually sent, within resends,
  shed retries, and the beat-interval a SIGKILLed incarnation loses.

With ``trace_rate > 0`` a seeded fraction of arrivals carry a trace id
end to end (``telemetry/tracing.py``); every process in the plane —
this one included — writes its spans to ``<run_dir>/trace/`` for
``scripts/trace_merge.py`` to stitch into one Chrome trace.
"""

from __future__ import annotations

import dataclasses
import os
import socket
import tempfile
import threading
import time

from pos_evolution_tpu.config import cfg
from pos_evolution_tpu.serve.balancer import Balancer, SwarmLoadGenerator
from pos_evolution_tpu.serve.chaos import FdExhaustSwarm, ServeChaos
from pos_evolution_tpu.serve.protocol import (
    ProtocolError,
    recv_frame,
    send_frame,
)
from pos_evolution_tpu.serve.shm import ShmViewBoard
from pos_evolution_tpu.serve.state import ServeView
from pos_evolution_tpu.serve.workers import WorkerPool, worker_spec
from pos_evolution_tpu.telemetry import tracing

__all__ = ["run_mp_scenario"]

SCHEMA = 1


class _Sidecar:
    __slots__ = ("cells", "commitment")

    def __init__(self, cells, commitment):
        self.cells = cells
        self.commitment = commitment


def _scrape_metrics(addrs: list[tuple[str, int]]) -> dict | None:
    """One ``metrics`` RPC against the first front that answers: the
    fleet view is the same whichever worker serves it (every worker
    aggregates the shared snapshot directory)."""
    for addr in addrs:
        try:
            with socket.create_connection(addr, timeout=3.0) as s:
                s.settimeout(3.0)
                send_frame(s, {"id": 1, "method": "metrics",
                               "params": {}, "deadline_ms": 2500.0,
                               "tier": 0})
                resp = recv_frame(s)
        except (OSError, ProtocolError):
            continue
        if isinstance(resp, dict) and resp.get("status") == "ok":
            return resp.get("result")
    return None


def _free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _make_view(engine, slot: int, n_blobs: int) -> tuple[ServeView, bytes]:
    root = bytes([slot % 251 + 1]) * 32
    grids, coms, _ = engine.build_for(n_blobs, root)
    sidecars = [_Sidecar(grids[i], bytes(coms[i])) for i in range(n_blobs)]
    view = ServeView(
        slot=slot, head_root=root, head_slot=slot,
        justified_epoch=max(slot // 8 - 1, 0), justified_root=b"\x01" * 32,
        finalized_epoch=max(slot // 8 - 2, 0), finalized_root=b"\x02" * 32,
        update_ssz=b"\x00" * 64, update_root=b"\x03" * 32,
        sidecars={root: sidecars},
        n_cells=n_blobs * cfg().das_cells_per_blob)
    return view, root


def run_mp_scenario(
        *, n_fronts: int = 2, workers_per_front: int = 2,
        arrivals: int = 60000, rate: float = 20000.0, seed: int = 0,
        bulk_fraction: float = 0.05, samples_per_request: int = 4,
        n_blobs: int = 2, publish_every_s: float = 0.5,
        kills: int = 2, wedges: int = 1, wedge_s: float = 4.0,
        fd_exhaust_n: int = 0, fd_exhaust_hold_s: float = 1.0,
        hang_timeout_s: float = 3.0, rss_limit_mb: float = 0.0,
        backoff_s: float = 0.15, backoff_cap_s: float = 1.0,
        conns_per_front: int = 4, slo_ms: float = 300.0,
        ready_grace_s: float = 8.0, worker_threads: int = 2,
        run_dir: str | None = None, events_bus=None,
        trace_rate: float = 0.0, trace_seed: int | None = None,
        trace_dir: str | None = None) -> dict:
    """Run one seeded multi-process serving scenario end to end.

    ``kills`` / ``wedges`` are process-level injections: SIGKILLs
    delivered by the pool's watch loop on the chaos schedule, and
    heartbeat-wedge windows the worker itself honors (it keeps serving
    but stops beating — the liveness lie hang detection must catch).
    ``fd_exhaust_n`` holds that many idle connections against front 0
    for ``fd_exhaust_hold_s`` mid-run. Everything is a pure function of
    ``seed``, so a scenario replays.
    """
    own_dir = run_dir is None
    if own_dir:
        run_dir = tempfile.mkdtemp(prefix="serve_mp_")
    os.makedirs(run_dir, exist_ok=True)
    lock_path = os.path.join(run_dir, "board.lock")
    duration_s = arrivals / float(rate)
    if trace_rate <= 0.0:
        trace_dir = None
    else:
        # an explicit trace_dir lets two phases (steady + chaos, each
        # with its own run_dir so their fleet snapshots never mix) pour
        # spans into ONE directory for a single merged timeline
        if trace_dir is None:
            trace_dir = os.path.join(run_dir, "trace")
        os.makedirs(trace_dir, exist_ok=True)
        # the harness process records the client-side spans (dispatch,
        # balancer pick, resolution) — workers install their own sinks
        tracing.install_buffer(trace_dir, proc="loadgen")

    from pos_evolution_tpu.das import BlobEngine
    engine = BlobEngine(seed=seed + 11)
    view, root = _make_view(engine, 7, n_blobs)

    n_workers = n_fronts * workers_per_front
    board = ShmViewBoard.create(lock_path, n_fronts=max(n_workers, 16))
    result: dict = {"schema": SCHEMA, "seed": seed, "arrivals": arrivals,
                    "rate": rate, "fronts": n_fronts,
                    "workers": n_workers}
    pool = publisher = loris = None
    stop_pub = threading.Event()
    try:
        board.publish(view)
        ports = _free_ports(n_fronts)
        chaos = ServeChaos(seed=seed)
        # wedge windows live in UNIX time (they cross the process
        # boundary into spawn specs); the load run is then ALIGNED to
        # the same origin by sleeping out the remainder of the grace
        # window after the pool reports ready
        start_unix = time.time() + ready_grace_s
        wedge_map = (chaos.wedge_windows(start_unix, duration_s, wedges,
                                         wedge_s, n_workers)
                     if wedges > 0 else {})
        cfg_dict = dataclasses.asdict(cfg())
        specs = [
            worker_spec(
                i, ports[i % n_fronts], board.name, lock_path, run_dir,
                threads=worker_threads, config=cfg_dict,
                trace_dir=trace_dir,
                chaos=({"wedge_windows": wedge_map[i]}
                       if i in wedge_map else None))
            for i in range(n_workers)]
        pool = WorkerPool(specs, board, hang_timeout_s=hang_timeout_s,
                          rss_limit_mb=rss_limit_mb,
                          backoff_s=backoff_s,
                          backoff_cap_s=backoff_cap_s, seed=seed,
                          events_bus=events_bus, chaos=chaos)
        pool.start()
        if not pool.wait_ready(max(ready_grace_s * 4, 30.0)):
            raise RuntimeError("worker pool never became ready")
        ready_lag = time.time() - start_unix
        if ready_lag > 0:
            # pool took longer than the grace window: wedge windows
            # skew early relative to the load run — recorded, not fatal
            result["wedge_skew_s"] = round(ready_lag, 3)
        else:
            time.sleep(-ready_lag)

        # live publisher: a fresh generation every publish_every_s for
        # the whole run, so workers (including respawned ones) must
        # FOLLOW the board, not serve their spawn-time view
        def _publish_loop() -> None:
            slot = 8
            while not stop_pub.wait(publish_every_s):
                # same root + sidecars (bulk requests stay valid across
                # the whole run); the advancing slot is what proves a
                # worker is FOLLOWING generations rather than caching
                board.publish(ServeView(
                    slot=slot, head_root=root, head_slot=slot,
                    justified_epoch=max(slot // 8 - 1, 0),
                    justified_root=b"\x01" * 32,
                    finalized_epoch=max(slot // 8 - 2, 0),
                    finalized_root=b"\x02" * 32,
                    update_ssz=b"\x00" * 64, update_root=b"\x03" * 32,
                    sidecars=view.sidecars, n_cells=view.n_cells))
                slot += 1

        publisher = threading.Thread(target=_publish_loop,
                                     name="mp-publisher", daemon=True)
        publisher.start()

        slot_map = [[i for i in range(n_workers) if i % n_fronts == j]
                    for j in range(n_fronts)]
        balancer = Balancer(n_fronts, board=board, slot_map=slot_map,
                            metrics_dir=run_dir)
        targets = {"roots": [root.hex()],
                   "n_cells": n_blobs * cfg().das_cells_per_blob,
                   "n_blobs": {root.hex(): n_blobs}}
        gen = SwarmLoadGenerator(
            [("127.0.0.1", p) for p in ports], arrivals, rate,
            balancer=balancer, conns_per_front=conns_per_front,
            seed=seed, bulk_fraction=bulk_fraction,
            samples_per_request=samples_per_request,
            targets_fn=lambda: targets,
            trace_rate=trace_rate, trace_seed=trace_seed)

        if kills > 0:
            chaos.arm_worker_kills(time.monotonic(), duration_s, kills,
                                   n_workers)
        if fd_exhaust_n > 0:
            loris = FdExhaustSwarm(("127.0.0.1", ports[0]),
                                   n=fd_exhaust_n,
                                   hold_s=fd_exhaust_hold_s)
            offset = 0.2 * duration_s
            threading.Timer(offset, loris.start).start()

        load = gen.run()

        # settle: a wedge is only DETECTABLE hang_timeout_s after its
        # window opens, and a respawn needs its backoff + spawn time —
        # the watch loop keeps running here, so wait out the chaos
        # that is still scheduled to land before judging
        stop_pub.set()
        publisher.join(timeout=3.0)
        wedge_hi = max((hi for ws in wedge_map.values()
                        for _lo, hi in ws), default=time.time())
        settle_unix = (max(wedge_hi, time.time()) + hang_timeout_s
                       + backoff_cap_s + 2.5)
        while time.time() < settle_unix:
            snap = pool.summary()
            reasons = snap["interruptions_by_reason"]
            rows = snap["workers"]
            if (reasons.get("hang", 0) >= wedges
                    and snap["chaos_kills_delivered"] >= min(
                        kills, n_workers)
                    and all(r["alive"] or r["parked"] for r in rows)):
                break
            time.sleep(0.15)
        # generation convergence: with the publisher stopped, every
        # live worker's follow loop must land on the final generation
        board_gen, _v = board.current()
        gen_deadline = time.monotonic() + 3.0
        while time.monotonic() < gen_deadline:
            rows = pool.worker_rows()
            live = [r for r in rows if r["alive"]]
            if live and all(r.get("generation") == board_gen
                            for r in live):
                break
            time.sleep(0.1)
        pool_sum = pool.summary()
        result["load"] = load
        result["pool"] = pool_sum
        result["chaos"] = chaos.summary()
        result["board_generation"] = board_gen
        if loris is not None:
            loris.stop()
            result["fd_exhaust"] = {"connected": loris.connected,
                                    "refused": loris.refused}
        # fleet scrape (ISSUE 18 leg a): after settle every surviving
        # worker has flushed ≥1 beat since the last response, so the
        # merged registry is the plane's complete request ledger (less
        # at most one beat-interval per SIGKILLed incarnation)
        time.sleep(0.4)  # one beat + slack: let the final beats land
        scraped = _scrape_metrics([("127.0.0.1", p) for p in ports])
        if scraped is not None:
            result["fleet"] = scraped.get("fleet")
            result["fleet_prometheus"] = scraped.get("prometheus")
        if trace_dir is not None:
            buf = tracing.get_buffer()
            if buf is not None:
                buf.flush()
            result["trace_dir"] = trace_dir
        result["beat_s"] = 0.25
        result["verdict"] = _judge(result, kills, wedges, slo_ms)
    finally:
        stop_pub.set()
        if publisher is not None:
            publisher.join(timeout=3.0)
        if loris is not None:
            loris.stop()
        if pool is not None:
            pool.stop()
        board.close()
    return result


def _judge(result: dict, kills: int, wedges: int, slo_ms: float) -> dict:
    load = result["load"]
    pool = result["pool"]
    inter = load["tiers"]["interactive"]
    by_reason = pool["interruptions_by_reason"]
    kills_fired = result["chaos"]["injections"].get(
        "worker_kill_fired", 0)
    kills_delivered = pool.get("chaos_kills_delivered", 0)
    # a SIGKILLed worker surfaces as a crash interruption; a wedged one
    # as a hang (the pool could not tell it was lying, only that the
    # heartbeat stopped — which is the point)
    crashes = by_reason.get("crash", 0)
    hangs = by_reason.get("hang", 0)
    # every live worker ends on the board's current generation: a
    # respawned worker serving an old view would be a silent fork
    board_gen = result["board_generation"]
    live_rows = [r for r in pool["workers"] if r["alive"]]
    current = all(r.get("generation") == board_gen for r in live_rows)
    verdict = {
        "records_match_schedule": load["arrivals"] == result["arrivals"],
        "interactive_goodput_pct": inter["goodput_pct"],
        "goodput_ok": (inter["goodput_pct"] or 0) >= 99.0,
        "interactive_p99_ms": inter["p99_ms"],
        "slo_ms": slo_ms,
        "slo_ok": (inter["p99_ms"] is not None
                   and inter["p99_ms"] <= slo_ms),
        "verified_proofs": load.get("verified_proofs"),
        "verify_failures": load.get("verify_failures", 0),
        "integrity_ok": load.get("verify_failures", 0) == 0,
        "lost": load.get("lost", 0),
        "resends": load.get("resends", 0),
        "kills_armed": kills, "kills_fired": kills_fired,
        "kills_delivered": kills_delivered,
        "crash_interruptions": crashes,
        "kills_detected": (kills_delivered >= kills
                           and crashes >= kills_delivered),
        "wedges_armed": wedges, "hang_interruptions": hangs,
        "wedges_detected": hangs >= min(wedges, 1),
        "restarts": pool["restarts"],
        "respawned_on_current_generation": current,
        "live_workers": len(live_rows),
    }
    # fleet-consistency (ISSUE 18 leg a): the per-worker request
    # counters scraped off the metrics RPC must sum to what the loadgen
    # actually sent. Over-count allowance: resends and shed retries put
    # the same arrival on a second worker; the scrape itself counts
    # once. Under-count allowance: a ``lost`` arrival may never have
    # reached a worker, and each SIGKILLed incarnation keeps only its
    # last beat-flushed counts (≤ ~2 beat-intervals of its share of
    # the arrival rate).
    fleet_view = result.get("fleet")
    if fleet_view is not None:
        by_worker = fleet_view.get("requests_by_worker") or {}
        fleet_sum = sum(float(v) for v in by_worker.values())
        arrivals = result["arrivals"]
        incarnations_killed = (kills_delivered
                               + by_reason.get("hang", 0)
                               + by_reason.get("rss", 0))
        kill_slack = (incarnations_killed * result["rate"]
                      / max(result["workers"], 1)
                      * 2.0 * result.get("beat_s", 0.25))
        lo = arrivals - load.get("lost", 0) - kill_slack - 8
        hi = (arrivals + load.get("resends", 0)
              + load.get("shed_retries", 0) + kill_slack + 8)
        verdict["fleet_requests_by_worker"] = dict(by_worker)
        verdict["fleet_requests_total"] = fleet_sum
        verdict["fleet_window"] = [round(lo, 1), round(hi, 1)]
        verdict["fleet_workers_reporting"] = len(by_worker)
        verdict["fleet_consistent"] = bool(lo <= fleet_sum <= hi)
    else:
        verdict["fleet_consistent"] = False
    verdict["ok"] = bool(
        verdict["records_match_schedule"] and verdict["goodput_ok"]
        and verdict["slo_ok"] and verdict["integrity_ok"]
        and verdict["kills_detected"] and verdict["wedges_detected"]
        and verdict["respawned_on_current_generation"]
        and verdict["fleet_consistent"])
    return verdict
