"""The published serving view: what the RPC front reads, atomically.

The driver mutates its fork-choice stores freely on its own thread; the
serving workers must never see a half-updated store. The seam is a
**published immutable snapshot**: at the end of each slot the driver
builds a ``ServeView`` (head/finality scalars, the current best
light-client update pre-serialized, and the DAS window's sidecars) and
swaps it into ``ServingState`` — one reference assignment, atomic under
the GIL, no locks on the read path. Handlers grab ``current()`` once per
request and work off that view even if a new one lands mid-request
(serving a just-superseded head is normal distributed-systems staleness;
serving a torn one would be a correctness bug).

Publishing is also the serving tier's **block boundary**: new head root
means every proof-path cache key changes, which is exactly the stampede
moment the single-flight machinery (and the chaos mode's cache wipe)
exercises. ``on_publish`` listeners hook that moment.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

__all__ = ["ServeView", "ServingState"]


@dataclass(frozen=True)
class ServeView:
    """Immutable per-slot snapshot served by ``ServeFront``."""

    slot: int
    head_root: bytes
    head_slot: int
    justified_epoch: int
    justified_root: bytes
    finalized_epoch: int
    finalized_root: bytes
    # pre-serialized best update (ssz bytes) + its hash_tree_root, so
    # serving never touches live containers and clients can check the
    # served bytes against the root the head endpoint advertises
    update_ssz: bytes | None = None
    update_root: bytes | None = None
    # DAS window: {block_root: [sidecar, ...]} — each sidecar exposes
    # ``.cells`` (n_cells, cell_bytes) and ``.commitment`` (32 bytes)
    sidecars: dict = field(default_factory=dict)
    n_cells: int = 0
    # the cell-commitment scheme serving this window ("merkle"/"kzg"):
    # remote clients pick das_cells vs das_aggregate from this
    scheme: str = "merkle"

    def head_summary(self) -> dict:
        return {
            "slot": int(self.slot),
            "head_root": self.head_root.hex(),
            "head_slot": int(self.head_slot),
            "update_root": (self.update_root.hex()
                            if self.update_root else None),
            "das_roots": [r.hex() for r in self.sidecars],
            # grid geometry per served root, so a REMOTE load generator
            # can discover its bulk targets from this one endpoint
            # (serve/loadgen.discover_targets) instead of in-process
            # introspection (ISSUE 13 / ROADMAP item 3 remainder)
            "n_cells": int(self.n_cells),
            "scheme": self.scheme,
            "das_blobs": {r.hex(): len(cars)
                          for r, cars in self.sidecars.items()},
        }

    def finality_summary(self) -> dict:
        return {
            "justified_epoch": int(self.justified_epoch),
            "justified_root": self.justified_root.hex(),
            "finalized_epoch": int(self.finalized_epoch),
            "finalized_root": self.finalized_root.hex(),
        }


class ServingState:
    """Atomic view holder + publish listeners (+ optional history for
    replaying a recorded run against a live front)."""

    def __init__(self, keep_history: bool = False):
        self._view: ServeView | None = None
        self._lock = threading.Lock()
        self._listeners: list = []
        self.version = 0
        self.keep_history = keep_history
        self.views: list[ServeView] = []

    def publish(self, view: ServeView) -> int:
        with self._lock:
            self._view = view
            self.version += 1
            version = self.version
            if self.keep_history:
                self.views.append(view)
            listeners = list(self._listeners)
        for fn in listeners:
            fn(view, version)
        return version

    def current(self) -> ServeView | None:
        return self._view  # one ref read — atomic, lock-free

    def on_publish(self, fn) -> None:
        with self._lock:
            self._listeners.append(fn)
