"""Shared-memory view publication: serialize once, serve from N processes.

The multi-process serving plane's data seam (DESIGN.md §19, ROADMAP
item 3): the driver serializes each immutable ``ServeView`` exactly once
into a ``multiprocessing.shared_memory`` segment and publishes it with an
atomic generation bump; worker processes attach read-only and decode at
most once per generation — never a pickle per request, never a copy per
worker beyond the one decode. One segment carries three regions:

- **view payload** behind a *seqlock*: the writer bumps the generation
  counter to an odd value, writes the payload, bumps it even; a reader
  copies the payload and retries if the generation moved (or was odd)
  under it. Readers never block the writer and a torn read is
  detectable, not servable;
- **health board**: one slot per serving front (pid, seen generation,
  brownout flag, queue depth, request count, beat time) — single writer
  per slot, so fronts and the load balancer read each other's health
  without locks and brownout decisions can coordinate across processes;
- **lease table**: per-(block, blob) cross-process build leases — the
  process-level half of single-flight. A leader claims the lease (table
  mutations serialize through an ``fcntl`` lock file — kernel-released
  on death, so a SIGKILLed leader can never wedge the table), builds the
  blob's proofs once, spools them into a named side segment, and marks
  the lease done; waiters poll the 4-byte state word and attach the
  spool instead of re-running the backing build. Dead-leader takeover is
  pid-liveness at claim time.

The spool segments are GC'd two ways: a claimer that recycles a lease
slot unlinks the previous digest's spool, and the board OWNER unlinks
every live spool at ``close(unlink=True)`` — bounded residue, no
cross-process refcounting.
"""

from __future__ import annotations

import fcntl
import hashlib
import json
import os
import struct
import threading
import time
from multiprocessing import resource_tracker, shared_memory

import numpy as np

from pos_evolution_tpu.serve.state import ServeView

__all__ = ["ShmSidecar", "ShmViewBoard", "encode_view", "decode_view",
           "lease_digest"]

_MAGIC = b"PEVSHM1\x00"
_HEADER = struct.Struct("<8sQQQQ16x")       # magic, gen, payload_len,
                                            # n_fronts, n_lease_slots
_HEALTH = struct.Struct("<QQQQQdQ8x")       # pid, generation, brownout,
                                            # depth, requests, unix, shed
_LEASE = struct.Struct("<16sIId")           # digest, state, owner_pid, unix
LEASE_FREE, LEASE_BUILDING, LEASE_DONE = 0, 1, 2


_track_lock = threading.Lock()


def _open_shm(name: str | None = None, create: bool = False,
              size: int = 0) -> shared_memory.SharedMemory:
    """``SharedMemory`` WITHOUT resource-tracker registration.

    The 3.10 tracker registers every open (create AND attach,
    bpo-38119) and unlinks everything it knows at process exit — which
    would tear the board out from under every sibling when one worker
    exits cleanly. Unregistering after the fact is racy across the
    pool's shared tracker process (its name set is flat, so interleaved
    attach/untrack from two workers double-removes and the tracker
    spews KeyErrors at shutdown). Suppressing registration at
    construction sends the tracker nothing at all; lifetime is owned
    explicitly — the creating process unlinks at ``close``."""
    with _track_lock:
        orig = resource_tracker.register
        resource_tracker.register = lambda *a, **k: None
        try:
            return shared_memory.SharedMemory(name=name, create=create,
                                              size=size)
        finally:
            resource_tracker.register = orig


def _unlink_shm(shm) -> None:
    """``unlink`` for a segment opened via ``_open_shm``: 3.10's
    ``unlink()`` unconditionally unregisters from the tracker, which —
    since we never registered — makes the tracker process spew
    KeyErrors at shutdown. Suppress the unregister the same way."""
    with _track_lock:
        orig = resource_tracker.unregister
        resource_tracker.unregister = lambda *a, **k: None
        try:
            shm.unlink()
        except FileNotFoundError:
            pass
        finally:
            resource_tracker.unregister = orig


class ShmSidecar:
    """The decoded view's sidecar stand-in: exactly the two attributes
    the serving handlers touch (``.cells`` grid, ``.commitment``)."""

    __slots__ = ("cells", "commitment")

    def __init__(self, cells: np.ndarray, commitment: bytes):
        self.cells = cells
        self.commitment = commitment


def encode_view(view: ServeView) -> bytes:
    """One flat buffer: 4-byte meta length + JSON meta + raw blobs
    (update bytes, then each sidecar's cell grid + commitment in meta
    order). Scalars ride the JSON; bulk bytes are raw slices — decode
    is a handful of ``np.frombuffer`` copies, not a pickle walk."""
    blobs: list[bytes] = []
    meta_cars = []
    update = view.update_ssz or b""
    blobs.append(update)
    for root, cars in view.sidecars.items():
        entry = {"root": root.hex(), "cars": []}
        for car in cars:
            grid = np.ascontiguousarray(car.cells, dtype=np.uint8)
            entry["cars"].append({"shape": list(grid.shape)})
            blobs.append(grid.tobytes())
            blobs.append(bytes(car.commitment))
        meta_cars.append(entry)
    meta = {
        "slot": int(view.slot),
        "head_root": view.head_root.hex(),
        "head_slot": int(view.head_slot),
        "justified_epoch": int(view.justified_epoch),
        "justified_root": view.justified_root.hex(),
        "finalized_epoch": int(view.finalized_epoch),
        "finalized_root": view.finalized_root.hex(),
        "update_len": len(update),
        "update_root": (view.update_root.hex()
                        if view.update_root else None),
        "n_cells": int(view.n_cells),
        "sidecars": meta_cars,
    }
    mb = json.dumps(meta, separators=(",", ":")).encode()
    return struct.pack("<I", len(mb)) + mb + b"".join(blobs)


def decode_view(buf: bytes) -> ServeView:
    (mlen,) = struct.unpack_from("<I", buf, 0)
    meta = json.loads(buf[4:4 + mlen])
    off = 4 + mlen
    update = bytes(buf[off:off + meta["update_len"]]) or None
    off += meta["update_len"]
    sidecars: dict = {}
    for entry in meta["sidecars"]:
        cars = []
        for car in entry["cars"]:
            n, w = car["shape"]
            grid = np.frombuffer(buf, dtype=np.uint8, count=n * w,
                                 offset=off).reshape(n, w).copy()
            off += n * w
            commitment = bytes(buf[off:off + 32])
            off += 32
            cars.append(ShmSidecar(grid, commitment))
        sidecars[bytes.fromhex(entry["root"])] = cars
    return ServeView(
        slot=meta["slot"],
        head_root=bytes.fromhex(meta["head_root"]),
        head_slot=meta["head_slot"],
        justified_epoch=meta["justified_epoch"],
        justified_root=bytes.fromhex(meta["justified_root"]),
        finalized_epoch=meta["finalized_epoch"],
        finalized_root=bytes.fromhex(meta["finalized_root"]),
        update_ssz=update,
        update_root=(bytes.fromhex(meta["update_root"])
                     if meta["update_root"] else None),
        sidecars=sidecars,
        n_cells=meta["n_cells"],
    )


def lease_digest(key) -> bytes:
    """16-byte stable digest of a lease key tuple (e.g. ``("blob_proofs",
    block_root, blob)``) — the lease table's identity."""
    h = hashlib.sha256()
    for part in key:
        p = part if isinstance(part, bytes) else str(part).encode()
        h.update(struct.pack("<I", len(p)))
        h.update(p)
    return h.digest()[:16]


class ShmViewBoard:
    """One shared segment: seqlock'd view payload + health board +
    lease table. ``create`` on the owner (publisher / pool) side,
    ``attach`` in every worker."""

    def __init__(self, shm, lock_path: str, owner: bool,
                 n_fronts: int, n_lease_slots: int, capacity: int):
        self._shm = shm
        self._buf = shm.buf
        self.name = shm.name
        self.lock_path = lock_path
        self.owner = owner
        self.n_fronts = n_fronts
        self.n_lease_slots = n_lease_slots
        self.capacity = capacity
        self._health_off = _HEADER.size
        self._lease_off = self._health_off + n_fronts * _HEALTH.size
        self._payload_off = self._lease_off + n_lease_slots * _LEASE.size
        self._gen_cache = -1
        self._view_cache: ServeView | None = None
        self._lock_fd: int | None = None
        self.publishes = 0
        self.read_retries = 0

    # -- lifecycle -------------------------------------------------------------

    @classmethod
    def create(cls, lock_path: str, capacity: int = 1 << 20,
               n_fronts: int = 16, n_lease_slots: int = 128,
               name: str | None = None) -> "ShmViewBoard":
        size = (_HEADER.size + n_fronts * _HEALTH.size
                + n_lease_slots * _LEASE.size + capacity)
        shm = _open_shm(name=name, create=True, size=size)
        shm.buf[:size] = b"\x00" * size
        _HEADER.pack_into(shm.buf, 0, _MAGIC, 0, 0, n_fronts,
                          n_lease_slots)
        # the lock file backs fcntl.flock for lease-table mutations;
        # created by the owner so workers can open it read-write
        with open(lock_path, "w") as f:
            f.write(shm.name + "\n")
        return cls(shm, lock_path, owner=True, n_fronts=n_fronts,
                   n_lease_slots=n_lease_slots, capacity=capacity)

    @classmethod
    def attach(cls, name: str, lock_path: str) -> "ShmViewBoard":
        shm = _open_shm(name=name)
        magic, _gen, _plen, n_fronts, n_lease = _HEADER.unpack_from(
            shm.buf, 0)
        assert magic == _MAGIC, f"not a ShmViewBoard segment: {name}"
        capacity = (shm.size - _HEADER.size - n_fronts * _HEALTH.size
                    - n_lease * _LEASE.size)
        return cls(shm, lock_path, owner=False, n_fronts=int(n_fronts),
                   n_lease_slots=int(n_lease), capacity=int(capacity))

    def close(self, unlink: bool | None = None) -> None:
        unlink = self.owner if unlink is None else unlink
        if unlink:
            self.gc_spools()
        if self._lock_fd is not None:
            try:
                os.close(self._lock_fd)
            except OSError:
                pass
            self._lock_fd = None
        # drop every exported view of the buffer before closing the
        # mmap, or SharedMemory.close raises BufferError
        self._buf = None
        self._view_cache = None
        try:
            self._shm.close()
        except BufferError:
            pass
        if unlink:
            _unlink_shm(self._shm)

    # -- seqlock'd view payload ------------------------------------------------

    def _gen(self) -> int:
        return struct.unpack_from("<Q", self._buf, 8)[0]

    def _set_gen(self, g: int) -> None:
        struct.pack_into("<Q", self._buf, 8, g)

    def publish(self, view: ServeView) -> int:
        """Serialize ONCE, publish by generation bump. Returns the new
        (even) generation. Owner-side only — one writer by contract."""
        payload = encode_view(view)
        if len(payload) > self.capacity:
            raise ValueError(
                f"encoded view ({len(payload)} B) exceeds the board's "
                f"payload capacity ({self.capacity} B)")
        g = self._gen()
        self._set_gen(g + 1)            # odd: writer in the payload
        struct.pack_into("<Q", self._buf, 16, len(payload))
        self._buf[self._payload_off:self._payload_off + len(payload)] = \
            payload
        self._set_gen(g + 2)            # even: consistent again
        self.publishes += 1
        return g + 2

    def generation(self) -> int:
        """The current published generation (0 = nothing published)."""
        return self._gen()

    def current(self) -> tuple[int, ServeView | None]:
        """(generation, view) — decoded at most once per generation per
        attached process; seqlock retry on a concurrent publish."""
        for _ in range(1000):
            g1 = self._gen()
            if g1 == 0:
                return 0, None
            if g1 == self._gen_cache:
                return g1, self._view_cache
            if g1 & 1:
                self.read_retries += 1
                time.sleep(0.0002)
                continue
            (plen,) = struct.unpack_from("<Q", self._buf, 16)
            payload = bytes(
                self._buf[self._payload_off:self._payload_off + plen])
            if self._gen() != g1:
                self.read_retries += 1
                continue
            view = decode_view(payload)
            self._gen_cache, self._view_cache = g1, view
            return g1, view
        raise RuntimeError("seqlock read never stabilized — is the "
                           "publisher wedged mid-write?")

    # -- health board ----------------------------------------------------------

    def write_health(self, front_id: int, generation: int = 0,
                     brownout: bool = False, depth: int = 0,
                     requests: int = 0, shed: int = 0) -> None:
        """Publish one front's health into its own slot (single writer
        per slot — torn reads are tolerable staleness, not corruption)."""
        assert 0 <= front_id < self.n_fronts
        _HEALTH.pack_into(self._buf,
                          self._health_off + front_id * _HEALTH.size,
                          os.getpid(), int(generation), int(brownout),
                          int(depth), int(requests), time.time(),
                          int(shed))

    def clear_health(self, front_id: int) -> None:
        """Tombstone a slot: the SUPERVISOR calls this the instant it
        sees a worker die, so routing reacts immediately instead of
        waiting out heartbeat staleness (a dead front kept 'live' for
        STALE_S is a window of connection refusals)."""
        assert 0 <= front_id < self.n_fronts
        _HEALTH.pack_into(self._buf,
                          self._health_off + front_id * _HEALTH.size,
                          0, 0, 0, 0, 0, 0.0, 0)

    def read_health(self) -> list[dict]:
        """Every occupied health slot, as dicts with ``age_s``."""
        now = time.time()
        out = []
        for i in range(self.n_fronts):
            pid, gen, brown, depth, req, unix, shed = _HEALTH.unpack_from(
                self._buf, self._health_off + i * _HEALTH.size)
            if pid == 0:
                continue
            out.append({"front": i, "pid": int(pid),
                        "generation": int(gen),
                        "brownout": bool(brown), "depth": int(depth),
                        "requests": int(req), "shed": int(shed),
                        "age_s": max(now - unix, 0.0)})
        return out

    def brownout_fraction(self) -> float:
        """Fraction of live fronts currently browned out — the
        cross-front overload signal (a front whose siblings are all
        shedding should not wait for its own queue to prove it)."""
        rows = [r for r in self.read_health() if r["age_s"] < 5.0]
        if not rows:
            return 0.0
        return sum(1 for r in rows if r["brownout"]) / len(rows)

    # -- lease table (cross-process single-flight) -----------------------------

    def _flock(self):
        if self._lock_fd is None:
            self._lock_fd = os.open(self.lock_path, os.O_RDWR)
        fcntl.flock(self._lock_fd, fcntl.LOCK_EX)
        return self._lock_fd

    def _funlock(self) -> None:
        fcntl.flock(self._lock_fd, fcntl.LOCK_UN)

    def _lease_slot(self, i: int) -> tuple[bytes, int, int, float]:
        return _LEASE.unpack_from(self._buf,
                                  self._lease_off + i * _LEASE.size)

    def _write_lease(self, i: int, digest: bytes, state: int,
                     pid: int) -> None:
        _LEASE.pack_into(self._buf, self._lease_off + i * _LEASE.size,
                         digest, state, pid, time.time())

    @staticmethod
    def _alive(pid: int) -> bool:
        if pid <= 0:
            return False
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return False
        except PermissionError:
            return True
        return True

    def spool_name(self, digest: bytes) -> str:
        return f"{self.name}_sp_{digest.hex()[:12]}"

    def lease_acquire(self, digest: bytes) -> tuple[str, int]:
        """(role, slot): role is ``"lead"`` (this process must build),
        ``"wait"`` (a live leader is building — poll the slot), or
        ``"done"`` (the spool already holds the build). A lease whose
        owner died mid-build is taken over by the claimant — the
        fcntl file lock serializes the table walk, and the kernel
        releases it however the holder dies."""
        me = os.getpid()
        self._flock()
        try:
            free = None
            for i in range(self.n_lease_slots):
                d, state, pid, _unix = self._lease_slot(i)
                if state != LEASE_FREE and d == digest:
                    if state == LEASE_DONE:
                        return "done", i
                    if self._alive(pid):
                        return "wait", i
                    # dead leader: take the build over
                    self._write_lease(i, digest, LEASE_BUILDING, me)
                    return "lead", i
                if free is None and state == LEASE_FREE:
                    free = i
            if free is None:
                # table full: recycle the stalest DONE slot (its spool
                # is garbage for the current head anyway); unlink that
                # digest's spool so the name can be reborn later
                oldest, oldest_unix = None, float("inf")
                for i in range(self.n_lease_slots):
                    d, state, pid, unix = self._lease_slot(i)
                    if state == LEASE_DONE and unix < oldest_unix:
                        oldest, oldest_unix = i, unix
                if oldest is None:
                    # every slot mid-build (pathological): behave as a
                    # lone builder rather than deadlock the table
                    return "lead", -1
                d, _s, _p, _u = self._lease_slot(oldest)
                self._unlink_spool(d)
                free = oldest
            self._write_lease(free, digest, LEASE_BUILDING, me)
            return "lead", free
        finally:
            self._funlock()

    def lease_state(self, slot: int, digest: bytes) -> tuple[int, int]:
        """(state, owner_pid) of ``slot`` if it still holds ``digest``
        (LEASE_FREE otherwise) — the waiters' lock-free poll."""
        if slot < 0:
            return LEASE_FREE, 0
        d, state, pid, _unix = self._lease_slot(slot)
        if d != digest:
            return LEASE_FREE, 0
        return state, pid

    def lease_done(self, slot: int, digest: bytes) -> None:
        if slot < 0:
            return
        self._flock()
        try:
            self._write_lease(slot, digest, LEASE_DONE, os.getpid())
        finally:
            self._funlock()

    def lease_abort(self, slot: int, digest: bytes) -> None:
        """The leader's build failed: free the lease so the next miss
        elects a fresh leader instead of waiting on a corpse."""
        if slot < 0:
            return
        self._flock()
        try:
            d, state, pid, _unix = self._lease_slot(slot)
            if d == digest and pid == os.getpid():
                self._write_lease(slot, b"\x00" * 16, LEASE_FREE, 0)
        finally:
            self._funlock()

    # -- proof spools ----------------------------------------------------------

    def spool_write(self, digest: bytes, built: dict) -> None:
        """Serialize one blob's built proofs ({cell: (cell_bytes,
        branch)}) into the digest's named segment, so waiters populate
        their per-process LRU without re-running the backing build."""
        n = len(built)
        cells = np.stack([np.asarray(built[c][0], dtype=np.uint8)
                          for c in range(n)])
        branches = np.stack([np.asarray(built[c][1], dtype=np.uint8)
                             for c in range(n)])
        header = struct.pack("<QQQQ", n, cells.shape[1],
                             branches.shape[1], branches.shape[2])
        payload = header + cells.tobytes() + branches.tobytes()
        try:
            sp = _open_shm(name=self.spool_name(digest),
                           create=True, size=len(payload))
        except FileExistsError:
            # a recycled lease slot's spool name resurrected before its
            # unlink — overwrite in place (sizes match by construction
            # for one grid geometry; if not, unlink and recreate)
            sp = _open_shm(name=self.spool_name(digest))
            if sp.size < len(payload):
                sp.close()
                _unlink_shm(_open_shm(name=self.spool_name(digest)))
                sp = _open_shm(name=self.spool_name(digest),
                               create=True, size=len(payload))
        sp.buf[:len(payload)] = payload
        sp.close()

    def spool_read(self, digest: bytes) -> dict | None:
        """Decode the digest's spool into {cell: (cell_bytes, branch)}
        (copies — the caller's LRU owns the arrays), or None when the
        spool vanished (treat as a fresh miss)."""
        try:
            sp = _open_shm(name=self.spool_name(digest))
        except FileNotFoundError:
            return None
        try:
            n, w, depth, sib = struct.unpack_from("<QQQQ", sp.buf, 0)
            off = 32
            cells = np.frombuffer(sp.buf, dtype=np.uint8, count=n * w,
                                  offset=off).reshape(n, w).copy()
            off += n * w
            branches = np.frombuffer(
                sp.buf, dtype=np.uint8, count=n * depth * sib,
                offset=off).reshape(n, depth, sib).copy()
            return {c: (cells[c], branches[c]) for c in range(int(n))}
        finally:
            sp.close()

    def _unlink_spool(self, digest: bytes) -> None:
        try:
            sp = _open_shm(name=self.spool_name(digest))
        except FileNotFoundError:
            return
        sp.close()
        _unlink_shm(sp)

    def gc_spools(self) -> int:
        """Unlink every non-free lease's spool (owner-side, at close)."""
        n = 0
        for i in range(self.n_lease_slots):
            d, state, _pid, _unix = self._lease_slot(i)
            if state != LEASE_FREE:
                self._unlink_spool(d)
                n += 1
        return n
