"""Admission control, brownout, and the backing-store circuit breaker.

The overload posture of the serving tier, in one sentence: **never let
work the tier cannot finish in time consume the capacity of work it
can** — and say so honestly.

Three cooperating pieces, all host-side, all clock-injectable so tests
never sleep:

- ``AdmissionQueue`` — a priority-tiered bounded queue whose bound is
  *deadline-derived*: at offer time the projected queue wait (depth ahead
  of the request x EMA service time / workers) is compared against the
  request's remaining deadline budget; a request that would time out in
  the queue is rejected NOW with ``retry_after`` = the projected wait,
  which is exactly when retrying could succeed. Rejecting at the door
  costs microseconds; timing out in the queue costs a worker slot and
  still fails the client.
- ``BrownoutController`` — graceful degradation under sustained
  overload: when the INTERACTIVE tier's observed queue delay climbs past
  the enter threshold, bulk sampling traffic is shed outright until the
  delay falls below the exit threshold for several consecutive
  observations (hysteresis — flapping in and out of brownout is worse
  than either state). Head/finality/update traffic is never browned out:
  it is the tier's reason to exist and the ISSUE's goodput criterion.
- ``CircuitBreaker`` — the classic closed/open/half-open machine around
  the backing store: consecutive failures trip it open, clients get
  honest ``unavailable`` + retry-after for the cooldown, then ONE
  half-open probe decides between closing and re-opening. A broken
  backing store served at full concurrency is a retry storm amplifier.
"""

from __future__ import annotations

import threading
import time
from collections import deque

__all__ = ["ServiceEstimator", "AdmissionQueue", "BrownoutController",
           "CircuitBreaker"]


class ServiceEstimator:
    """Thread-safe EMA of observed service (and queue-wait) seconds."""

    def __init__(self, initial_s: float = 0.002, alpha: float = 0.1):
        self._lock = threading.Lock()
        self.alpha = float(alpha)
        self._ema = float(initial_s)
        self.observations = 0

    def observe(self, seconds: float) -> None:
        with self._lock:
            self._ema += self.alpha * (float(seconds) - self._ema)
            self.observations += 1

    @property
    def ema_s(self) -> float:
        with self._lock:
            return self._ema


class AdmissionQueue:
    """Bounded two-tier (interactive / bulk) admission queue.

    ``offer`` either admits (returns None) or returns the shed verdict
    ``{"reason": ..., "retry_after_ms": ...}``. ``take`` blocks workers,
    draining interactive strictly before bulk.
    """

    def __init__(self, workers: int, max_depth: int = 512,
                 admit_factor: float = 0.8,
                 estimator: ServiceEstimator | None = None,
                 clock=time.monotonic):
        self.workers = max(int(workers), 1)
        self.max_depth = int(max_depth)
        # fraction of the remaining deadline the projected wait may eat
        # before admission becomes dishonest (the service itself and the
        # response write need the rest)
        self.admit_factor = float(admit_factor)
        self.estimator = estimator or ServiceEstimator()
        self.clock = clock
        self._cond = threading.Condition()
        self._tiers: tuple[deque, deque] = (deque(), deque())
        self._closed = False
        self.admitted = 0
        self.shed = {"deadline": 0, "depth": 0, "brownout": 0,
                     "draining": 0}

    def depth(self, tier: int | None = None) -> int:
        with self._cond:
            if tier is None:
                return sum(len(q) for q in self._tiers)
            return len(self._tiers[tier])

    def projected_wait_s(self, tier: int) -> float:
        """Seconds a request admitted NOW to ``tier`` expects to queue:
        everything that will be served before it, over the worker pool.
        Bulk waits behind the whole interactive backlog (strict
        priority); interactive waits only behind its own tier."""
        with self._cond:
            ahead = len(self._tiers[0]) + (len(self._tiers[1])
                                           if tier == 1 else 0)
        return ahead * self.estimator.ema_s / self.workers

    def offer(self, item, tier: int, budget_s: float,
              brownout: bool = False) -> dict | None:
        """Admit ``item`` or return the shed verdict. ``budget_s`` is the
        request's remaining deadline budget at offer time."""
        wait_s = self.projected_wait_s(tier)
        if brownout and tier == 1:
            verdict = {"reason": "brownout",
                       "retry_after_ms": max(wait_s, self.estimator.ema_s
                                             * self.workers) * 1e3}
        elif wait_s > max(budget_s, 0.0) * self.admit_factor:
            verdict = {"reason": "deadline",
                       "retry_after_ms": wait_s * 1e3}
        else:
            with self._cond:
                if len(self._tiers[tier]) >= self.max_depth:
                    verdict = {"reason": "depth",
                               "retry_after_ms": wait_s * 1e3}
                else:
                    self._tiers[tier].append(item)
                    self.admitted += 1
                    self._cond.notify()
                    return None
        with self._cond:  # shed counts feed the report: no lost updates
            self.shed[verdict["reason"]] += 1
        verdict["retry_after_ms"] = round(
            max(verdict["retry_after_ms"], 1.0), 3)
        return verdict

    def take(self, timeout: float | None = None):
        """Pop the next item (interactive first); None on close/timeout."""
        with self._cond:
            deadline = (self.clock() + timeout) if timeout is not None \
                else None
            while not self._closed:
                for q in self._tiers:
                    if q:
                        return q.popleft()
                remaining = None
                if deadline is not None:
                    remaining = deadline - self.clock()
                    if remaining <= 0:
                        return None
                self._cond.wait(remaining)
            return None

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def drain(self) -> list:
        """Every still-queued item, after ``close()`` — the honest-drain
        path: a stopping front answers each with ``shed`` + retry-after
        instead of letting admitted work vanish silently."""
        with self._cond:
            assert self._closed, "drain() is for closed queues"
            items = [item for q in self._tiers for item in q]
            for q in self._tiers:
                q.clear()
            self.shed["draining"] += len(items)
            return items


class BrownoutController:
    """Hysteresis state machine shedding BULK before interactive.

    Feed it the interactive tier's observed queue waits; read
    ``active`` at offer time. Enter is immediate (overload hurts now),
    exit needs ``exit_streak`` consecutive calm observations.
    """

    def __init__(self, enter_wait_s: float = 0.05,
                 exit_wait_s: float = 0.01, exit_streak: int = 16,
                 clock=time.monotonic):
        assert exit_wait_s <= enter_wait_s
        self.enter_wait_s = float(enter_wait_s)
        self.exit_wait_s = float(exit_wait_s)
        self.exit_streak = int(exit_streak)
        self.clock = clock
        self._lock = threading.Lock()
        self.active = False
        self._calm = 0
        self.transitions: list[dict] = []

    def observe_interactive_wait(self, wait_s: float) -> bool:
        """Record one interactive queue wait; returns the (possibly
        updated) brownout state."""
        with self._lock:
            if not self.active:
                if wait_s > self.enter_wait_s:
                    self.active = True
                    self._calm = 0
                    self.transitions.append(
                        {"state": "brownout", "t": self.clock(),
                         "wait_ms": round(wait_s * 1e3, 3)})
            else:
                if wait_s < self.exit_wait_s:
                    self._calm += 1
                    if self._calm >= self.exit_streak:
                        self.active = False
                        self.transitions.append(
                            {"state": "normal", "t": self.clock(),
                             "wait_ms": round(wait_s * 1e3, 3)})
                else:
                    self._calm = 0
            return self.active


class CircuitBreaker:
    """closed -> (N consecutive failures) -> open -> (cooldown) ->
    half-open -> one probe -> closed | open."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, failure_threshold: int = 5,
                 cooldown_s: float = 1.0, clock=time.monotonic):
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        self.clock = clock
        self._lock = threading.Lock()
        self.state = self.CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False
        self.transitions: list[dict] = []

    def _set(self, state: str) -> None:
        self.state = state
        self.transitions.append({"state": state, "t": self.clock()})

    def allow(self) -> tuple[bool, float]:
        """(admit?, retry_after_s when not). In half-open exactly one
        caller gets the probe slot; the rest are refused until the probe
        reports."""
        with self._lock:
            if self.state == self.OPEN:
                remaining = self._opened_at + self.cooldown_s - self.clock()
                if remaining > 0:
                    return False, remaining
                self._set(self.HALF_OPEN)
                self._probing = False
            if self.state == self.HALF_OPEN:
                if self._probing:
                    return False, self.cooldown_s
                self._probing = True
            return True, 0.0

    def abandon(self) -> None:
        """The caller who held an admission (possibly THE half-open
        probe slot) finished without a verdict on the backing store —
        e.g. its deadline expired before the backing access ran. Free
        the probe slot; leaving it held would wedge the breaker in
        half-open forever (nothing admitted, so no verdict can ever
        arrive)."""
        with self._lock:
            self._probing = False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probing = False
            if self.state != self.CLOSED:
                self._set(self.CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._probing = False
            if self.state == self.HALF_OPEN:
                self._opened_at = self.clock()
                self._set(self.OPEN)
                return
            self._failures += 1
            if (self.state == self.CLOSED
                    and self._failures >= self.failure_threshold):
                self._opened_at = self.clock()
                self._set(self.OPEN)
