"""Hardened live serving tier (DESIGN.md §19, ROADMAP item 3).

Turns the vectorized-batch serving story (``das/server.py``'s coalescing
``DasServer``, the light-client update builders, head/finality queries)
into an actual **traffic** story: a multi-worker async RPC front speaking
length-prefixed JSON over sockets, with the overload machinery a tier
facing 10^5+ untrusted clients needs to degrade gracefully instead of
collapsing:

- **admission control** — a bounded, priority-tiered queue whose bound is
  *deadline-derived*: a request whose projected queue wait already
  exceeds its remaining deadline budget is rejected immediately with an
  honest ``shed`` + ``retry_after_ms``, never silently dropped
  (``serve/admission.py``);
- **backpressure & brownout** — when the interactive tier's queue delay
  climbs, the controller sheds bulk sampling traffic *first* and keeps
  head/finality/update goodput high; hysteresis keeps the tier from
  flapping;
- **deadline propagation** — the client's remaining budget rides every
  frame; workers refuse expired work before touching the backing store,
  and handlers check the budget between proof batches;
- **hedged retries** — the client library (``serve/client.py``) hedges a
  slow request onto a second connection after a latency-derived delay,
  takes the first answer, and honors ``retry_after_ms`` after a shed;
- **stampede suppression** — proof-path cache misses for a new block
  collapse onto ONE backing build per (block, blob) via
  ``serve/singleflight.py`` (shared with ``DasServer.serve_samples``);
- **circuit breaker** — consecutive backing-store failures open the
  breaker; clients get honest ``unavailable`` + retry-after while the
  half-open probe tests recovery;
- **chaos & load** — a seeded open-loop load generator
  (diurnal/bursty/adversarial-hotspot arrivals, ``serve/loadgen.py``)
  and a serving chaos mode (worker stalls, cache wipes at block
  boundaries, 10x bursts, slow-loris clients, ``serve/chaos.py``)
  audited through the existing telemetry machinery — overload degrades
  throughput but never correctness: every served proof still verifies,
  every shed request gets an honest rejection.

The **multi-process plane** (PR 16) scales the same tier past the GIL:
immutable ``ServeView`` snapshots publish once into POSIX shared memory
(``serve/shm.py``'s seqlock ``ShmViewBoard``), a supervised
``WorkerPool`` (``serve/workers.py``) runs spawn-context worker
*processes* sharing SO_REUSEPORT listeners with heartbeat / crash /
hang / rss supervision and capped-backoff respawn, cross-process
stampedes collapse onto one build via the board's lease table
(``utils/singleflight.ProcessFlight``), and a health-routed
``Balancer`` (``serve/balancer.py``) spreads a pipelined swarm load
across fronts. ``serve/harness.py``'s ``run_mp_scenario`` runs the
whole plane under seeded process chaos and returns a self-judging
verdict.
"""

from pos_evolution_tpu.serve.admission import (
    AdmissionQueue,
    BrownoutController,
    CircuitBreaker,
    ServiceEstimator,
)
from pos_evolution_tpu.serve.balancer import Balancer, SwarmLoadGenerator
from pos_evolution_tpu.serve.chaos import (
    FdExhaustSwarm,
    ServeChaos,
    SlowLorisSwarm,
)
from pos_evolution_tpu.serve.client import ClientResult, ServeClient
from pos_evolution_tpu.serve.harness import run_mp_scenario
from pos_evolution_tpu.serve.loadgen import (
    LoadGenerator,
    arrival_times,
    discover_targets,
)
from pos_evolution_tpu.serve.protocol import (
    ProtocolError,
    recv_frame,
    send_frame,
)
from pos_evolution_tpu.serve.server import TIER_BULK, TIER_INTERACTIVE, ServeFront
from pos_evolution_tpu.serve.shm import ShmViewBoard
from pos_evolution_tpu.serve.state import ServeView, ServingState
from pos_evolution_tpu.serve.workers import WorkerPool, worker_spec
from pos_evolution_tpu.utils.singleflight import ProcessFlight, SingleFlight

__all__ = [
    "AdmissionQueue",
    "Balancer",
    "BrownoutController",
    "CircuitBreaker",
    "ClientResult",
    "FdExhaustSwarm",
    "LoadGenerator",
    "ProcessFlight",
    "ProtocolError",
    "ServeChaos",
    "ServeClient",
    "ServeFront",
    "ServeView",
    "ServiceEstimator",
    "ServingState",
    "ShmViewBoard",
    "SingleFlight",
    "SlowLorisSwarm",
    "SwarmLoadGenerator",
    "TIER_BULK",
    "TIER_INTERACTIVE",
    "WorkerPool",
    "arrival_times",
    "discover_targets",
    "recv_frame",
    "run_mp_scenario",
    "send_frame",
    "worker_spec",
]
