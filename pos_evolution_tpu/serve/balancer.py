"""Multi-front load balancing + the pipelined swarm load generator.

Two pieces that exist only at pool scale:

- ``Balancer`` — a seeded, health-biased front picker. The decision
  input is the shared segment's health board (``serve/shm.py``): every
  worker publishes (beat age, brownout flag, queue depth) into its slot,
  and the balancer weights each front by its workers' aggregate health —
  a front whose workers are all browned out gets a fraction of the
  traffic, a front with no live workers (mid-respawn) gets a trickle
  (probes must keep flowing or recovery is invisible). The *draw* stream
  is seeded per arrival, so runs replay; the weights react to live
  health, which is the point.

- ``SwarmLoadGenerator`` — the open-loop engine rebuilt for 10x the
  arrival rate. The thread-per-request ``LoadGenerator`` spends more CPU
  on Event round-trips than the server spends serving; at 20k+/s on a
  shared core that overhead IS the bottleneck. The swarm splits the loop
  into one **dispatcher** (walks the schedule, batches due frames into
  per-connection buffers, one ``sendall`` per batch) and one **reader
  thread per connection** (demultiplexes responses by id, records
  latency from the *scheduled* arrival — open-loop honesty unchanged).
  A connection killed mid-flight (worker SIGKILL) fails over: its
  pending requests are resent on a fresh connection to the same front —
  SO_REUSEPORT routes them to a surviving sibling — and counted as
  retries, never silently lost. Requests the server sheds with
  ``retry_after_ms`` are retried once within their deadline budget by a
  single timer thread (sheds are rare at interactive tier by
  construction; the timer thread is idle in the common case).

Every bulk response still verifies against its commitment post-run, and
anything unanswered at the drain deadline is recorded as ``lost`` —
the accounting invariant: records == schedule, always.
"""

from __future__ import annotations

import heapq
import json
import socket
import struct
import threading
import time
from bisect import bisect_right

import numpy as np

from pos_evolution_tpu.serve.loadgen import LoadGenerator
from pos_evolution_tpu.telemetry import fleet, tracing
from pos_evolution_tpu.telemetry.tracing import record_span

__all__ = ["Balancer", "SwarmLoadGenerator"]

_LEN = struct.Struct(">I")


class Balancer:
    """Seeded weighted choice over fronts, biased by shared-segment
    health. ``slot_map[j]`` lists the health-board slots (worker front
    ids) serving front ``j``; with no board every front weighs 1.0.

    With a ``metrics_dir``, the fleet metrics pipeline adds a second,
    slower bias input: per-worker error fractions read from the beat
    snapshots (``telemetry/fleet.py``). The health board says a worker
    is *alive*; the metrics say whether it has been *answering well* —
    a worker timing out most of its requests still beats on time, and
    only its counters betray it."""

    STALE_S = 3.0

    def __init__(self, n_fronts: int, board=None,
                 slot_map: list[list[int]] | None = None,
                 refresh_s: float = 0.2,
                 metrics_dir: str | None = None,
                 metrics_refresh_s: float = 1.0):
        assert n_fronts > 0
        self.n_fronts = int(n_fronts)
        self.board = board
        self.slot_map = slot_map or [[j] for j in range(self.n_fronts)]
        assert len(self.slot_map) == self.n_fronts
        self.refresh_s = float(refresh_s)
        self.metrics_dir = metrics_dir
        self.metrics_refresh_s = float(metrics_refresh_s)
        self._lock = threading.Lock()
        self._at = -float("inf")
        self._bias_at = -float("inf")
        self._bias: dict[int, float] = {}
        # cumulative weights as a plain list: ``pick`` runs once per
        # arrival at 20k+/s, where a numpy scalar searchsorted costs
        # more than the whole frame encode — bisect is ~10x cheaper
        self._cum = [(j + 1) / self.n_fronts
                     for j in range(self.n_fronts)]
        self.refreshes = 0
        self.metrics_refreshes = 0

    def _metrics_bias(self) -> dict[int, float]:
        """Per-worker weight multiplier in [0.25, 1.0] from the fleet
        snapshot directory, cached for ``metrics_refresh_s`` (a scan
        rereads every snapshot file — far too heavy per refresh, let
        alone per pick). Workers with too few observed requests get no
        bias: early noise must not starve a cold worker."""
        if self.metrics_dir is None:
            return {}
        now = time.monotonic()
        if now - self._bias_at < self.metrics_refresh_s:
            return self._bias
        self._bias_at = now
        self.metrics_refreshes += 1
        agg = fleet.FleetAggregator.from_dir(self.metrics_dir)
        bias: dict[int, float] = {}
        for w, by_status in agg.worker_status_totals(
                "serve_requests_total").items():
            total = sum(by_status.values())
            if total < 32:
                continue
            bad = total - by_status.get("ok", 0) - by_status.get(
                "shed", 0)  # shed is honest load control, not illness
            try:
                bias[int(w)] = max(0.25, 1.0 - 2.0 * bad / total)
            except ValueError:
                continue
        self._bias = bias
        return bias

    def _weights(self) -> np.ndarray:
        rows = {r["front"]: r for r in self.board.read_health()}
        bias = self._metrics_bias()
        w = np.zeros(self.n_fronts)
        for j, slots in enumerate(self.slot_map):
            live = [rows[s] for s in slots
                    if s in rows and rows[s]["age_s"] < self.STALE_S]
            if not live:
                # mid-respawn front: a trickle keeps probing it — zero
                # traffic would make recovery invisible to the balancer
                w[j] = 0.05
                continue
            browned = sum(1 for r in live if r["brownout"])
            depth = sum(r["depth"] for r in live) / len(live)
            w[j] = len(live) * (0.3 if browned == len(live) else 1.0) \
                / (1.0 + depth / 64.0)
            if bias:
                mult = [bias[s] for s in slots if s in bias]
                if mult:
                    w[j] *= sum(mult) / len(mult)
        if w.sum() <= 0:
            w[:] = 1.0
        return w

    def pick(self, draw: float) -> int:
        """Front index for one seeded ``draw`` in [0, 1). Weights are
        recomputed at most every ``refresh_s`` (health reads are cheap
        but not free at 20k/s)."""
        if self.board is not None:
            now = time.monotonic()
            with self._lock:
                if now - self._at >= self.refresh_s:
                    self._at = now
                    w = self._weights()
                    total = float(w.sum())
                    acc, cum = 0.0, []
                    for v in w:
                        acc += float(v) / total
                        cum.append(acc)
                    self._cum = cum
                    self.refreshes += 1
                cum = self._cum
        else:
            cum = self._cum
        return min(bisect_right(cum, draw), self.n_fronts - 1)


class _SwarmConn:
    """One pipelined connection: socket + pending map + reader thread.

    ``pending[id] = (i, tier, sched, deadline_abs, body, method,
    resends)`` — everything needed to record the outcome or to resend
    the frame verbatim after a connection death."""

    def __init__(self, owner: "SwarmLoadGenerator", front: int,
                 addr: tuple[str, int]):
        self.owner = owner
        self.front = front
        self.addr = addr
        self.sock = socket.create_connection(addr, timeout=5.0)
        self.sock.settimeout(None)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.plock = threading.Lock()
        self.pending: dict[int, tuple] = {}
        self.alive = True
        # writes happen on a per-connection WRITER thread: a front
        # whose worker falls behind fills its TCP buffer, and a
        # blocking sendall from the dispatcher would head-of-line
        # block every OTHER front's dispatch behind the slow one
        self._outbox: list[list] = []
        self._out_cond = threading.Condition()
        self.reader = threading.Thread(target=self._read_loop,
                                       name=f"swarm-read-f{front}",
                                       daemon=True)
        self.writer = threading.Thread(target=self._write_loop,
                                       name=f"swarm-write-f{front}",
                                       daemon=True)
        self.reader.start()
        self.writer.start()

    def send_batch(self, frames: list[tuple[int, bytes, tuple]]) -> bool:
        """Register a batch of (id, encoded frame, meta) and queue it
        for the writer thread; False when the connection is dead (the
        caller re-routes through failover)."""
        if not self.alive:
            return False
        with self.plock:
            for rid, _buf, meta in frames:
                self.pending[rid] = meta
        with self._out_cond:
            if not self.alive:
                # raced a death: roll back so the dying reader's sweep
                # and our False return cannot both claim the frames
                with self.plock:
                    for rid, _buf, _meta in frames:
                        self.pending.pop(rid, None)
                return False
            self._outbox.append(frames)
            self._out_cond.notify()
        return True

    def _write_loop(self) -> None:
        while True:
            with self._out_cond:
                while not self._outbox and self.alive:
                    self._out_cond.wait(0.25)
                if not self._outbox:
                    return  # dead and drained
                batches, self._outbox = self._outbox, []
            frames = [f for batch in batches for f in batch]
            try:
                self.sock.sendall(b"".join(buf for _, buf, _ in frames))
            except OSError:
                # the frames are registered in pending; the reader's
                # death sweep fails them over — just die loudly
                self._die()
                return

    def _die(self) -> None:
        # atomic publish: a single bool store that readers poll
        # lock-free on the send fast path
        # pev: ignore[PEV102]
        self.alive = False
        try:
            self.sock.close()
        except OSError:
            pass
        with self._out_cond:
            self._out_cond.notify_all()

    def _read_loop(self) -> None:
        buf = bytearray()
        sock = self.sock
        while self.alive:
            try:
                chunk = sock.recv(1 << 16)
            except OSError:
                break  # dead socket: the sweep below fails pending over
            if not chunk:
                break
            buf.extend(chunk)
            while len(buf) >= _LEN.size:
                (length,) = _LEN.unpack(buf[:_LEN.size])
                if len(buf) < _LEN.size + length:
                    break
                body = bytes(buf[_LEN.size:_LEN.size + length])
                del buf[:_LEN.size + length]
                # fast path: the overwhelming majority of frames are
                # interactive "ok" replies whose only load-bearing
                # fields are id + status — at 20k+/s on a shared core,
                # json.loads on every one of them IS the client-side
                # capacity limit. Both server encodings open with
                # {"id":N, (compact cache hits and default json.dumps),
                # so the id ends at the first comma.
                rid = -1
                if body.startswith(b'{"id":'):
                    comma = body.find(b",", 6, 24)
                    digits = body[6:comma] if comma > 6 else b""
                    if digits.isdigit():
                        rid = int(digits)
                if rid >= 0 and (b'"status":"ok"' in body
                                 or b'"status": "ok"' in body):
                    with self.plock:
                        meta = self.pending.pop(rid, None)
                    if meta is None:
                        continue
                    # bulk results (and lc_update under verification)
                    # still need the payload — fall through to a full
                    # parse for those
                    if meta[1] == 0 and (meta[5] != "lc_update"
                                         or self.owner.verify_update
                                         is None):
                        self.owner._finish_ok(meta)
                        continue
                    try:
                        self.owner._on_response(json.loads(body), meta)
                    except json.JSONDecodeError:
                        self._die()
                        break
                    continue
                try:
                    resp = json.loads(body)
                except json.JSONDecodeError:
                    self._die()
                    break
                with self.plock:
                    meta = self.pending.pop(resp.get("id"), None)
                if meta is not None:
                    self.owner._on_response(resp, meta)
        # connection lost (worker SIGKILL, server stop): fail the
        # in-flight requests OVER to a fresh connection — the kernel
        # RST is the only notice a killed worker ever gives
        self._die()
        with self.plock:
            orphans = list(self.pending.items())
            self.pending.clear()
        if orphans:
            self.owner._failover(self.front, orphans)


class SwarmLoadGenerator(LoadGenerator):
    """Open-loop load at pool scale: one dispatcher, pipelined
    connections, balancer-routed fronts. Same seeded schedule, same
    deferred verification, same summary shape as ``LoadGenerator`` —
    only the dispatch engine differs."""

    def __init__(self, addrs: list[tuple[str, int]], n_arrivals: int,
                 rate: float, balancer: Balancer | None = None,
                 conns_per_front: int = 2, max_resends: int = 3,
                 **kw):
        kw.setdefault("bulk_fraction", 0.05)
        kw.setdefault("client_threads", 0)  # unused by the swarm engine
        super().__init__(tuple(addrs[0]), n_arrivals, rate, **kw)
        self.addrs = [tuple(a) for a in addrs]
        self.balancer = balancer or Balancer(len(self.addrs))
        assert self.balancer.n_fronts == len(self.addrs)
        self.conns_per_front = int(conns_per_front)
        self.max_resends = int(max_resends)
        rng = np.random.RandomState(self.seed ^ 0xBA1A)
        self._front_draw = rng.random_sample(self.n)
        self._conns: list[list[_SwarmConn | None]] = [
            [None] * self.conns_per_front for _ in self.addrs]
        self._conns_lock = threading.Lock()
        self._rr = 0
        # connect-refusal cooldown per front: a front whose whole
        # REUSEPORT group is dead refuses instantly — remember that
        # briefly instead of re-attempting the connect per arrival
        self._front_down = [0.0] * len(self.addrs)
        # once-only resolution per arrival: a connection-death sweep
        # and a send rollback can race into failing the SAME frames
        # over twice, and a duplicated resend would then resolve (and
        # count) one scheduled arrival twice
        self._resolved = bytearray(self.n)
        self._done = threading.Condition()
        # shed retries wait out their retry_after on ONE timer thread
        self._retry_heap: list[tuple] = []
        self._retry_cond = threading.Condition()
        self._stopping = False
        self.resends = 0
        self.shed_retries = 0
        self.lost = 0
        self.lost_by_reason: dict[str, int] = {}
        self.by_front = [0] * len(self.addrs)
        # arrival index -> trace id for sampled arrivals: written only
        # by the dispatcher, read by reader threads at resolution time
        self._traced: dict[int, str] = {}

    # -- connections -----------------------------------------------------------

    def _conn(self, front: int, k: int | None = None) -> _SwarmConn:
        """A live connection to ``front`` (round-robin across the
        front's slots), reconnecting through its SO_REUSEPORT group —
        after a worker kill the kernel hands the fresh socket to a
        surviving sibling."""
        with self._conns_lock:
            self._rr += 1
            idx = (self._rr if k is None else k) % self.conns_per_front
            c = self._conns[front][idx]
        if c is not None and c.alive:
            return c
        try:
            fresh = _SwarmConn(self, front, self.addrs[front])
        except OSError:
            with self._conns_lock:
                self._front_down[front] = time.monotonic() + 0.25
            raise
        with self._conns_lock:
            c = self._conns[front][idx]
            if c is not None and c.alive:
                winner = c
            else:
                self._conns[front][idx] = winner = fresh
        if winner is not fresh:
            fresh._die()
        return winner

    def _fresh_conn(self, front: int) -> _SwarmConn:
        """A NEWLY-connected conn to ``front``, installed in the grid.

        Failover must not trust pooled conns: when a worker is killed,
        ALL its connections die together but each ``alive`` flag lags
        until that conn's reader sees the RST — resending through the
        pool can hop orphans between doomed siblings until the resend
        quota burns out. A fresh TCP connect, by contrast, can only be
        accepted by a listener that is actually alive."""
        fresh = _SwarmConn(self, front, self.addrs[front])
        with self._conns_lock:
            self._rr += 1
            self._conns[front][self._rr % self.conns_per_front] = fresh
        return fresh

    def _send(self, conn: _SwarmConn,
              frames: list[tuple[int, bytes, tuple]]) -> None:
        """``send_batch`` that fails over instead of dropping: a batch
        rejected by a dead pipe re-enters through the same resend path
        a mid-flight connection death uses."""
        if not conn.send_batch(frames):
            self._failover(conn.front,
                           [(rid, meta) for rid, _buf, meta in frames])

    # -- outcome recording -----------------------------------------------------

    def _finish(self, i: int, tier: int, status: str, latency: float,
                result=None) -> None:
        with self._lock:
            if self._resolved[i]:
                return
            self._resolved[i] = 1
            self.records.append((tier, status, latency, 0))
            if status == "ok" and result is not None:
                if tier == 1:
                    self._bulk_results.append(result)
                elif "update" in result \
                        and self.verify_update is not None:
                    self._update_results.append(result)
            done = len(self.records) >= self.n
        trace = self._traced.get(i)
        if trace is not None:
            record_span(trace, "client", time.time() - latency,
                        latency * 1e3, status=status, tier=tier)
        if done:
            with self._done:
                self._done.notify_all()

    def _finish_ok(self, meta: tuple) -> None:
        """Record an interactive success straight from the byte-scan
        fast path — no parsed response object exists."""
        i, tier, sched, *_ = meta
        self._finish(i, tier, "ok", time.monotonic() - sched, None)

    def _on_response(self, resp: dict, meta: tuple) -> None:
        i, tier, sched, deadline_abs, body, method, resends = meta
        now = time.monotonic()
        status = resp.get("status", "error")
        if status in ("shed", "unavailable"):
            retry_s = float(resp.get("retry_after_ms", 1.0)) / 1e3
            due = now + retry_s
            if due < deadline_abs and resends < self.max_resends:
                with self._lock:
                    self.shed_retries += 1
                with self._retry_cond:
                    heapq.heappush(self._retry_heap,
                                   (due, i, tier, sched, deadline_abs,
                                    body, method, resends + 1))
                    self._retry_cond.notify()
                return
        self._finish(i, tier, status, now - sched,
                     resp.get("result") if status == "ok" else None)

    def _failover(self, front: int, orphans: list[tuple[int, tuple]]
                  ) -> None:
        """Resend a dead connection's in-flight requests; requests past
        their deadline (or out of resend budget) are recorded lost."""
        now = time.monotonic()
        resend: list[tuple[int, bytes, tuple]] = []
        for rid, meta in orphans:
            i, tier, sched, deadline_abs, body, method, resends = meta
            if now >= deadline_abs or resends >= self.max_resends \
                    or self._stopping:
                reason = ("stopping" if self._stopping
                          else "deadline" if now >= deadline_abs
                          else "resend_quota")
                with self._lock:
                    self.lost += 1
                    self.lost_by_reason[reason] = \
                        self.lost_by_reason.get(reason, 0) + 1
                self._finish(i, tier, "lost", now - sched)
                continue
            resend.append((rid, _LEN.pack(len(body)) + body,
                           (i, tier, sched, deadline_abs, body, method,
                            resends + 1)))
        if not resend:
            return
        with self._lock:
            self.resends += len(resend)
        n_fronts = len(self.addrs)
        for attempt in range(2 + n_fronts):
            if time.monotonic() < self._front_down[front]:
                front = (front + 1) % n_fronts
                continue
            try:
                conn = self._fresh_conn(front)
            except OSError:
                # whole front down (respawn backoff window): remember
                # it and rotate to the next front
                with self._conns_lock:
                    self._front_down[front] = time.monotonic() + 0.25
                front = (front + 1) % n_fronts
                continue
            if conn.send_batch(resend):
                return
        now = time.monotonic()
        for _rid, _body, meta in resend:
            i, tier, sched, *_ = meta
            with self._lock:
                self.lost += 1
                self.lost_by_reason["all_fronts_down"] = \
                    self.lost_by_reason.get("all_fronts_down", 0) + 1
            self._finish(i, tier, "lost", now - sched)

    def _retry_loop(self) -> None:
        while True:
            with self._retry_cond:
                while not self._retry_heap and not self._stopping:
                    self._retry_cond.wait(0.25)
                if self._stopping:
                    # the run is over: a shed we chose not to retry
                    # resolves as what the server last said it was
                    leftovers = list(self._retry_heap)
                    self._retry_heap.clear()
                    for item in leftovers:
                        _due, li, ltier, lsched = item[:4]
                        self._finish(li, ltier, "shed",
                                     time.monotonic() - lsched)
                    return
                due = self._retry_heap[0][0]
                now = time.monotonic()
                if due > now:
                    self._retry_cond.wait(min(due - now, 0.25))
                    continue
                item = heapq.heappop(self._retry_heap)
            due, i, tier, sched, deadline_abs, body, method, resends = item
            meta = (i, tier, sched, deadline_abs, body, method, resends)
            front = self.balancer.pick(float(self._front_draw[i]))
            try:
                conn = self._conn(front)
            except OSError:
                self._failover(front, [(i + 1, meta)])
                continue
            frame = (i + 1, _LEN.pack(len(body)) + body, meta)
            self._send(conn, [frame])

    # -- the dispatcher --------------------------------------------------------

    def _encode(self, i: int, targets: dict,
                trace: str | None = None) -> tuple[bytes, int, str,
                                                   float]:
        method, params, deadline, tier = self._build(i, targets)
        obj = {"id": i + 1, "method": method, "params": params,
               "deadline_ms": round(deadline * 1e3, 3), "tier": tier}
        if trace is not None:
            # trace member FIRST: traced frames must miss the servers'
            # byte-scan fast path (see serve/protocol.py)
            obj = {"trace": {"id": trace, "s": 1}, **obj}
        body = json.dumps(obj, separators=(",", ":")).encode()
        return body, tier, method, deadline

    def run(self) -> dict:
        targets_fn = self.targets_fn or (lambda: {"roots": [],
                                                  "n_cells": 0,
                                                  "n_blobs": {}})
        retry_thread = threading.Thread(target=self._retry_loop,
                                        name="swarm-retry", daemon=True)
        retry_thread.start()
        # the dispatch loop runs once per arrival at the full target
        # rate on a core it SHARES with the serving processes — numpy
        # scalar indexing and fresh json.dumps per interactive request
        # would eat the whole per-arrival budget. Schedule arrays drop
        # to plain lists; the three interactive frames (identical but
        # for the id) become prebuilt byte templates.
        offsets = self.offsets.tolist()
        is_bulk = self._is_bulk.tolist()
        front_draw = self._front_draw.tolist()
        pick1 = self._pick[:, 1].tolist()
        idl_ms = round(self.interactive_deadline_s * 1e3, 3)
        tmpl = {m: (f'{{"id":%d,"method":"{m}","params":{{}},'
                    f'"deadline_ms":{idl_ms},"tier":0}}').encode()
                for m in ("head", "finality", "lc_update")}
        pick_front = self.balancer.pick
        by_front = self.by_front
        pack = _LEN.pack
        monotonic = time.monotonic
        trace_rate = self.trace_rate
        trace_seed = self.trace_seed
        t_sample, t_id = tracing.sample, tracing.trace_id
        t_start = monotonic() + 0.05
        max_deadline = max(self.interactive_deadline_s,
                           self.bulk_deadline_s)
        idl_abs = self.interactive_deadline_s + 0.25
        batches: dict[_SwarmConn, list] = {}
        # per-front conn cache: `_conn` costs a lock + round-robin per
        # call, so the dispatcher holds one conn per front and rotates
        # only when a batch flushes on size — round-robin at batch
        # granularity, not per arrival
        conn_cache: list[_SwarmConn | None] = [None] * len(self.addrs)
        late = 0
        i = 0
        while i < self.n:
            now = monotonic()
            sched = t_start + offsets[i]
            if sched > now:
                # flush everything due before sleeping toward the next
                # arrival — batching bounds per-request syscall cost,
                # the sleep keeps the schedule honest
                for conn, frames in batches.items():
                    self._send(conn, frames)
                batches.clear()
                time.sleep(min(sched - now, 0.05))
                continue
            if now - sched > 0.005:
                late += 1
            trace = None
            if trace_rate > 0.0 and t_sample(trace_seed, i, trace_rate):
                trace = t_id(trace_seed, i)
                # single-writer: only this dispatch loop inserts; reader
                # threads .get() a key only after its send, and dict
                # item assignment is atomic  # pev: ignore[PEV101]
                self._traced[i] = trace
            if is_bulk[i]:
                targets = targets_fn()
                body, tier, method, deadline = self._encode(i, targets,
                                                            trace)
                deadline_abs = sched + deadline + 0.25
            else:
                r = pick1[i]
                method = ("head" if r < 0.4 else
                          "finality" if r < 0.7 else "lc_update")
                body = tmpl[method] % (i + 1)
                if trace is not None:
                    # splice the trace member in FRONT of the prebuilt
                    # template bytes — traced frames must fall off the
                    # servers' byte-scan fast path (serve/protocol.py)
                    body = (b'{"trace":{"id":"' + trace.encode()
                            + b'","s":1},' + body[1:])
                tier, deadline_abs = 0, sched + idl_abs
            front = pick_front(front_draw[i])
            if trace is not None:
                record_span(trace, "balancer_pick", time.time(), 0.0,
                            front=front, method=method)
            if monotonic() < self._front_down[front]:
                # known-dark front: rotate to the next one rather than
                # paying a guaranteed connection refusal
                for step in range(1, len(self.addrs)):
                    alt = (front + step) % len(self.addrs)
                    if monotonic() >= self._front_down[alt]:
                        front = alt
                        break
            by_front[front] += 1
            meta = (i, tier, sched, deadline_abs, body, method, 0)
            conn = conn_cache[front]
            if conn is None or not conn.alive:
                try:
                    conn = conn_cache[front] = self._conn(front)
                except OSError:
                    # a refused connect is a ROUTING event, not an
                    # outcome: the arrival fails over like any orphaned
                    # in-flight request and only becomes lost when
                    # every front is dark
                    conn_cache[front] = None
                    self._failover(front, [(i + 1, meta)])
                    i += 1
                    continue
            batch = batches.get(conn)
            if batch is None:
                batch = batches[conn] = []
            batch.append((i + 1, pack(len(body)) + body, meta))
            if len(batch) >= 64:
                self._send(conn, batches.pop(conn))
                conn_cache[conn.front] = None
            i += 1
        for conn, frames in batches.items():
            self._send(conn, frames)
        with self._lock:
            self.late_dispatch += late
        # drain: every scheduled arrival must resolve — answered,
        # retried to resolution, or recorded lost. No fourth outcome.
        drain_deadline = time.monotonic() + max_deadline + 3.0
        with self._done:
            while len(self.records) < self.n:
                remaining = drain_deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._done.wait(min(remaining, 0.25))
        with self._retry_cond:
            self._stopping = True
            self._retry_cond.notify_all()
        retry_thread.join(timeout=3.0)
        # anything STILL unresolved is lost, honestly
        with self._conns_lock:
            conns = [c for row in self._conns for c in row
                     if c is not None]
        for conn in conns:
            with conn.plock:
                orphans = list(conn.pending.items())
                conn.pending.clear()
            now = time.monotonic()
            for _rid, meta in orphans:
                li, ltier, lsched, *_ = meta
                with self._lock:
                    self.lost += 1
                self._finish(li, ltier, "lost", now - lsched)
        self.wall_s = time.monotonic() - t_start
        for conn in conns:
            conn._die()
        self._verify_deferred()
        return self.summary()

    def summary(self) -> dict:
        out = super().summary()
        out["engine"] = "swarm"
        out["fronts"] = len(self.addrs)
        out["by_front"] = list(self.by_front)
        out["resends"] = self.resends
        out["shed_retries"] = self.shed_retries
        out["lost"] = self.lost
        out["lost_by_reason"] = dict(self.lost_by_reason)
        out["traced"] = len(self._traced)
        out["balancer_refreshes"] = self.balancer.refreshes
        out["balancer_metrics_refreshes"] = \
            self.balancer.metrics_refreshes
        return out
