"""Serving-tier client library: deadlines, hedged retries, honest backoff.

The client half of the tail-latency contract:

- every request carries a **deadline budget**; retries and hedges spend
  the same budget (a client that retries past its own deadline is a
  retry storm, not a client);
- a request that has not answered within the **hedge delay** is sent
  again on a DIFFERENT connection (a second chance to land on a worker
  that is not stalled) — first response wins, the loser is discarded by
  id; hedging is capped at one duplicate per attempt, the
  tail-at-scale-safe amount;
- a ``shed`` / ``unavailable`` answer carries ``retry_after_ms`` — the
  client sleeps exactly that (clamped to its remaining budget) before
  retrying: the server said when capacity is expected, guessing harder
  is worse for everyone;
- responses are demultiplexed by ``id`` on a per-connection reader
  thread, so any number of caller threads share a small connection pool
  with pipelining.

``ClientResult`` reports what actually happened (status, attempts,
hedges) — the load generator's goodput/shed accounting is built on it.
"""

from __future__ import annotations

import itertools
import socket
import threading
import time

from pos_evolution_tpu.serve.protocol import ProtocolError, recv_frame, send_frame
from pos_evolution_tpu.telemetry.tracing import record_span

__all__ = ["ServeClient", "ClientResult"]

_ids = itertools.count(1)
_ids_lock = threading.Lock()


def _next_id() -> int:
    with _ids_lock:
        return next(_ids)


class ClientResult:
    __slots__ = ("status", "result", "attempts", "hedges", "retries",
                 "latency_s", "reason", "error")

    def __init__(self, status: str, result=None, attempts: int = 1,
                 hedges: int = 0, retries: int = 0,
                 latency_s: float = 0.0, reason: str | None = None,
                 error: str | None = None):
        self.status = status
        self.result = result
        self.attempts = attempts
        self.hedges = hedges
        self.retries = retries
        self.latency_s = latency_s
        self.reason = reason
        self.error = error

    @property
    def ok(self) -> bool:
        return self.status == "ok"


class _Channel:
    """One pooled connection: socket + reader thread demuxing by id."""

    def __init__(self, addr: tuple[str, int], connect_timeout: float):
        self.sock = socket.create_connection(addr, timeout=connect_timeout)
        self.sock.settimeout(None)
        # without TCP_NODELAY, small frames sit in Nagle's buffer
        # waiting on the peer's delayed ACK — a 40ms floor per
        # request/response ping-pong that looks like server latency
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.wlock = threading.Lock()
        self.pending: dict[int, tuple[threading.Event, list]] = {}
        self.plock = threading.Lock()
        self.alive = True
        self.reader = threading.Thread(target=self._read_loop,
                                       name="serve-client-reader",
                                       daemon=True)
        self.reader.start()

    def _read_loop(self) -> None:
        while self.alive:
            try:
                resp = recv_frame(self.sock)
            # not a swallow: the None sentinel drops through to the
            # connection-lost path below, which fails every waiter loudly
            except (ProtocolError, OSError):  # pev: ignore[PEV005]
                resp = None
            if resp is None:
                self.alive = False
                with self.plock:
                    waiters = list(self.pending.values())
                    self.pending.clear()
                for event, slot in waiters:
                    slot.append({"status": "error",
                                 "error": "connection lost"})
                    event.set()
                return
            with self.plock:
                waiter = self.pending.pop(resp.get("id"), None)
            if waiter is not None:
                event, slot = waiter
                slot.append(resp)
                event.set()
            # an unknown id is a hedge loser arriving after its twin won
            # — dropped by design

    def post(self, frame: dict,
             event: threading.Event | None = None
             ) -> tuple[threading.Event, list] | None:
        """Register a waiter and send; None when the channel is dead.
        A caller-provided ``event`` lets a primary and its hedge share
        one wakeup — whichever response lands first sets it."""
        if not self.alive:
            return None
        event, slot = event or threading.Event(), []
        with self.plock:
            self.pending[frame["id"]] = (event, slot)
        try:
            with self.wlock:
                send_frame(self.sock, frame)
        except OSError:
            self.alive = False
            with self.plock:
                self.pending.pop(frame["id"], None)
            return None
        return event, slot

    def forget(self, rid: int) -> None:
        with self.plock:
            self.pending.pop(rid, None)

    def close(self) -> None:
        self.alive = False
        try:
            self.sock.close()
        except OSError:
            pass


class ServeClient:
    """Thread-safe pooled client with hedging + retry-after semantics."""

    def __init__(self, addr: tuple[str, int], connections: int = 2,
                 hedge_ms: float | None = 50.0, max_retries: int = 3,
                 connect_timeout: float = 5.0):
        self.addr = (addr[0], int(addr[1]))
        self.n_connections = max(int(connections), 1)
        self.hedge_ms = hedge_ms
        self.max_retries = int(max_retries)
        self.connect_timeout = float(connect_timeout)
        self._channels: list[_Channel | None] = [None] * self.n_connections
        self._chan_lock = threading.Lock()
        self._rr = itertools.count()
        self.hedges_sent = 0
        self.retries_sent = 0
        self._stats_lock = threading.Lock()

    def _channel(self, index: int) -> _Channel:
        index %= self.n_connections
        with self._chan_lock:
            ch = self._channels[index]
        if ch is not None and ch.alive:
            return ch
        # connect OUTSIDE the pool lock: a blocking reconnect (up to
        # connect_timeout) held under it would stall every caller —
        # including the hedges whose whole job is routing around stalls
        fresh = _Channel(self.addr, self.connect_timeout)
        with self._chan_lock:
            ch = self._channels[index]
            if ch is not None and ch.alive:
                winner = ch  # another thread reconnected first
            else:
                self._channels[index] = winner = fresh
        if winner is not fresh:
            fresh.close()
        return winner

    def close(self) -> None:
        with self._chan_lock:
            for ch in self._channels:
                if ch is not None:
                    ch.close()
            self._channels = [None] * self.n_connections

    # -- the request state machine ---------------------------------------------

    def request(self, method: str, params: dict | None = None,
                deadline_s: float = 1.0, tier: int = 1,
                hedge_ms: float | None = None,
                trace: str | None = None) -> ClientResult:
        """One logical request under one deadline budget: send, hedge
        once per attempt after ``hedge_ms``, honor retry-after on shed,
        give up (honestly) when the budget is gone. ``trace`` (a sampled
        trace id from ``telemetry/tracing.py``) rides every frame of
        this logical request — primary, hedge, retry — and records one
        client-side span over the whole state machine."""
        t_wall = time.time()
        res = self._request(method, params, deadline_s, tier, hedge_ms,
                            trace)
        if trace is not None:
            record_span(trace, "client", t_wall, res.latency_s * 1e3,
                        method=method, status=res.status,
                        attempts=res.attempts, hedges=res.hedges,
                        retries=res.retries)
        return res

    def _request(self, method: str, params: dict | None,
                 deadline_s: float, tier: int,
                 hedge_ms: float | None,
                 trace: str | None) -> ClientResult:
        t_start = time.monotonic()
        expires = t_start + float(deadline_s)
        hedge_ms = self.hedge_ms if hedge_ms is None else hedge_ms
        attempts = hedges = retries = 0
        last: dict | None = None
        while True:
            remaining = expires - time.monotonic()
            if remaining <= 0 or attempts > self.max_retries:
                status = "timeout" if last is None else last.get(
                    "status", "timeout")
                return ClientResult(
                    "timeout" if status == "ok" else status,
                    attempts=attempts, hedges=hedges, retries=retries,
                    latency_s=time.monotonic() - t_start,
                    reason=(last or {}).get("reason"),
                    error=(last or {}).get("error"))
            attempts += 1
            resp, hedged = self._attempt(method, params, remaining, tier,
                                         hedge_ms, trace=trace)
            hedges += hedged
            if resp is None or resp.get("error") == "connection lost":
                continue  # channel died — next attempt reconnects
            status = resp.get("status")
            if status == "ok":
                with self._stats_lock:
                    self.hedges_sent += hedges
                    self.retries_sent += retries
                return ClientResult("ok", result=resp.get("result"),
                                    attempts=attempts, hedges=hedges,
                                    retries=retries,
                                    latency_s=time.monotonic() - t_start)
            last = resp
            if status in ("shed", "unavailable"):
                retry_after = float(resp.get("retry_after_ms", 1.0)) / 1e3
                remaining = expires - time.monotonic()
                if retry_after >= remaining:
                    # the server's own estimate says capacity returns
                    # after our deadline — retrying would be dishonest
                    with self._stats_lock:
                        self.retries_sent += retries
                    return ClientResult(status, attempts=attempts,
                                        hedges=hedges, retries=retries,
                                        latency_s=(time.monotonic()
                                                   - t_start),
                                        reason=resp.get("reason"))
                retries += 1
                time.sleep(retry_after)
            elif status == "error":
                with self._stats_lock:
                    self.retries_sent += retries
                return ClientResult("error", attempts=attempts,
                                    hedges=hedges, retries=retries,
                                    latency_s=time.monotonic() - t_start,
                                    error=resp.get("error"))
            # status == "timeout": the server refused expired work; fall
            # through and retry within whatever budget remains
            else:
                retries += 1

    def _attempt(self, method, params, budget_s, tier,
                 hedge_ms, trace=None) -> tuple[dict | None, int]:
        """One wire attempt: primary send + at most one hedge. The
        primary and the hedge share ONE event, so whichever response
        lands first wakes the caller — no polling."""
        t0 = time.monotonic()
        deadline = t0 + budget_s
        event = threading.Event()
        primary = self._post(method, params, budget_s, tier, event=event,
                             trace=trace)
        if primary is None:
            return None, 0
        ch0, rid0, slot0, idx0 = primary
        hedge = None
        hedge_wait = (min(hedge_ms / 1e3, budget_s)
                      if hedge_ms is not None else budget_s)
        if not event.wait(hedge_wait):
            remaining = deadline - time.monotonic()
            if hedge_ms is not None and remaining > 0 \
                    and self.n_connections > 1:
                # the hedge must land on a DIFFERENT connection than
                # the primary — same-channel duplicates inherit the
                # exact stall they exist to route around
                hedge = self._post(method, params, remaining, tier,
                                   event=event, index=idx0 + 1,
                                   trace=trace)
                if hedge is not None and trace is not None:
                    # instant marker: when (and why) the duplicate left
                    record_span(trace, "hedge_sent", time.time(), 0.0,
                                method=method,
                                after_ms=round(
                                    (time.monotonic() - t0) * 1e3, 3))
            event.wait(max(deadline - time.monotonic(), 0.0))
        # prefer a real answer over a transport error: a died primary
        # channel writes {"status": "error", "error": "connection lost"}
        # into its slot, which must not mask the hedge's success
        candidates = [s[0] for s in (slot0, hedge[2] if hedge else None)
                      if s]
        winner = next((c for c in candidates
                       if c.get("error") != "connection lost"),
                      candidates[0] if candidates else None)
        ch0.forget(rid0)
        if hedge is not None:
            hedge[0].forget(hedge[1])
        return winner, (1 if hedge is not None else 0)

    def _post(self, method, params, budget_s, tier,
              event: threading.Event | None = None,
              index: int | None = None, trace: str | None = None):
        """Send one frame; returns (channel, id, slot, channel_index).
        ``index`` pins the starting pool slot (hedges pass the
        primary's index + 1 so the duplicate takes another socket);
        None draws from the round-robin."""
        rid = _next_id()
        if trace is not None:
            # trace FIRST: a traced frame must not match the servers'
            # byte-scan fast path (protocol.py's envelope contract)
            frame = {"trace": {"id": trace, "s": 1}, "id": rid,
                     "method": method, "params": params or {},
                     "deadline_ms": round(budget_s * 1e3, 3),
                     "tier": tier}
        else:
            frame = {"id": rid, "method": method, "params": params or {},
                     "deadline_ms": round(budget_s * 1e3, 3),
                     "tier": tier}
        base = next(self._rr) if index is None else index
        for probe in range(self.n_connections):
            idx = (base + probe) % self.n_connections
            try:
                ch = self._channel(idx)
            except OSError:
                continue
            posted = ch.post(frame, event=event)
            if posted is not None:
                _event, slot = posted
                return ch, rid, slot, idx
        return None
