"""Supervised process workers: the serving plane's multi-process half.

``WorkerPool`` spawns N request-serving processes (spawn start method —
fork in a thread-running parent is exactly the hazard the PEV007 lint
exists for). Each worker runs a full ``ServeFront`` — acceptor, readers,
admission, worker threads — bound to its assigned port with
``SO_REUSEPORT``, so the kernel spreads connections across the workers
sharing a port and a dead worker's port keeps serving from its siblings.

The data plane is the shared segment (``serve/shm.ShmViewBoard``): a
worker never receives a view over a pipe — a follower thread polls the
board's generation and republishes into the worker's local
``ServingState`` (one decode per generation), the DAS proof path runs
cross-process single-flight through the board's lease table
(``utils/singleflight.ProcessFlight``), and the worker publishes its
health (generation, brownout, depth, request count) into its board slot.

The control plane is the PR 10 supervision contract, via the extracted
core (``resilience/supervision.py``): every worker heartbeats a
``utils/watchdog.Heartbeat`` file; the pool's monitor detects **crash**
(exitcode), **hang** (stale heartbeat -> SIGKILL), and **leak** (RSS past
the cap -> SIGKILL), then respawns with capped deterministic backoff —
streak reset when the slot's served-request count advances, loud refusal
(slot parked) when failures are systematic. Every interruption is
recorded and emitted as a ``worker_interruption`` telemetry event for
``run_report``'s worker table.

Honest loss accounting: a SIGKILL'd worker's in-flight connections die
with it (kernel RST -> the client's connection-lost retry path); a
SIGTERM'd worker drains its admission queue with ``shed`` + retry-after
before exiting (``ServeFront.stop``) — queued work is answered or
honestly refused, never silently swallowed.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import sys
import threading
import time

from pos_evolution_tpu.resilience.supervision import (
    RetryPolicy,
    heartbeat_age,
    rss_kb,
)

__all__ = ["WorkerPool", "worker_spec"]

_POLL_S = 0.1


def worker_spec(worker_id: int, port: int, board_name: str,
                lock_path: str, run_dir: str, *, host: str = "127.0.0.1",
                scheme: str = "merkle", threads: int = 2,
                front_id: int | None = None, beat_s: float = 0.25,
                proof_cache: int = 4096, max_depth: int = 512,
                max_connections: int = 512,
                default_deadline_ms: float = 1000.0,
                brownout: dict | None = None, chaos: dict | None = None,
                config: dict | None = None,
                trace_dir: str | None = None) -> dict:
    """The picklable worker description ``_worker_main`` boots from —
    plain data only (a spawn child shares no interpreter state): the
    scheme travels by registry NAME, the config by field dict, the
    board by segment name."""
    if config is None:
        from pos_evolution_tpu.config import cfg
        config = dataclasses.asdict(cfg())
    return {
        "worker_id": int(worker_id),
        "front_id": int(front_id if front_id is not None else worker_id),
        "port": int(port), "host": host,
        "board_name": board_name, "lock_path": lock_path,
        "heartbeat_path": os.path.join(run_dir, f"worker{worker_id}.hb"),
        "stats_path": os.path.join(run_dir, f"worker{worker_id}.stats"),
        "run_dir": run_dir, "trace_dir": trace_dir,
        "scheme": scheme, "threads": int(threads),
        "beat_s": float(beat_s), "proof_cache": int(proof_cache),
        "max_depth": int(max_depth),
        "max_connections": int(max_connections),
        "default_deadline_ms": float(default_deadline_ms),
        "brownout": brownout or {}, "chaos": chaos,
        "config": config,
    }


def _atomic_json(path: str, payload: dict) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
        f.write("\n")
    os.replace(tmp, path)


def _worker_main(spec: dict) -> None:
    """Spawn entry: boot config/scheme from plain data, attach the
    board, serve until SIGTERM. Runs in a FRESH interpreter — nothing
    here may assume the parent's threads, locks, or registries exist."""
    from pos_evolution_tpu.config import Config, set_config
    from pos_evolution_tpu.das.commitment import get_scheme
    from pos_evolution_tpu.das.server import DasServer
    from pos_evolution_tpu.serve.admission import BrownoutController
    from pos_evolution_tpu.serve.server import ServeFront
    from pos_evolution_tpu.serve.shm import ShmViewBoard
    from pos_evolution_tpu.serve.state import ServingState
    from pos_evolution_tpu.telemetry import fleet, tracing
    from pos_evolution_tpu.telemetry.registry import MetricsRegistry
    from pos_evolution_tpu.utils.singleflight import ProcessFlight
    from pos_evolution_tpu.utils.watchdog import Heartbeat

    cfg_fields = dict(spec["config"])
    if isinstance(cfg_fields.get("terminal_block_hash"), str):
        cfg_fields["terminal_block_hash"] = bytes.fromhex(
            cfg_fields["terminal_block_hash"])
    set_config(Config(**cfg_fields))

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda _s, _f: stop.set())
    signal.signal(signal.SIGINT, signal.SIG_IGN)

    board = ShmViewBoard.attach(spec["board_name"], spec["lock_path"])
    state = ServingState()
    registry = MetricsRegistry()
    das = DasServer(get_scheme(spec["scheme"]), registry=registry,
                    proof_cache=spec["proof_cache"],
                    flight=ProcessFlight(board))
    brownout = BrownoutController(**spec["brownout"]) \
        if spec["brownout"] else BrownoutController()
    front = ServeFront(
        state, das_server=das, registry=registry,
        workers=spec["threads"], host=spec["host"], port=spec["port"],
        max_depth=spec["max_depth"],
        max_connections=spec["max_connections"],
        default_deadline_ms=spec["default_deadline_ms"],
        brownout=brownout, reuse_port=True,
        ident=f"{os.getpid()}:{spec['worker_id']}",
        metrics_dir=spec.get("run_dir"),
        worker_id=spec["worker_id"])
    # span sink for this process's server-side trace spans; the beat
    # thread flushes it alongside the metrics snapshot
    if spec.get("trace_dir"):
        tracing.install_buffer(spec["trace_dir"],
                               proc=f"worker{spec['worker_id']}")
    front.start()

    seen = {"generation": 0}

    def _follow() -> None:
        # view follower: one decode per generation, republished into
        # the local ServingState (which fires the front's publish hooks)
        while not stop.is_set():
            try:
                gen, view = board.current()
            except Exception:
                break  # board unlinked under us: the pool is stopping
            if view is not None and gen != seen["generation"]:
                seen["generation"] = gen
                state.publish(view)
            stop.wait(0.005)

    # seeded wedge windows (chaos satellite): inside a window the worker
    # keeps SERVING but stops heartbeating — the liveness lie the pool's
    # hang detection must catch and SIGKILL through
    wedges = []
    chaos = spec.get("chaos") or {}
    if chaos.get("wedge_windows"):
        wedges = [(float(lo), float(hi))
                  for lo, hi in chaos["wedge_windows"]]

    def _requests_total() -> int:
        front._flush_fast_metrics()  # fold fast-path tallies first
        return sum(v for k, v in registry.counts().items()
                   if k.startswith("serve_requests_total;"))

    def _beat() -> None:
        hb = Heartbeat(spec["heartbeat_path"])
        while not stop.is_set():
            now = time.time()
            wedged = any(lo <= now < hi for lo, hi in wedges)
            requests = _requests_total()
            if not wedged:
                hb.beat(slot=seen["generation"], requests=requests,
                        rss_kb=rss_kb(os.getpid()),
                        worker=spec["worker_id"])
            try:
                board.write_health(
                    spec["front_id"], generation=seen["generation"],
                    brownout=front.brownout.active,
                    depth=front.queue.depth(), requests=requests,
                    shed=sum(front.queue.shed.values()))
            # not a swallow: a torn-down board just means the pool is
            # stopping — the supervisor sees the exit either way
            except Exception:  # pev: ignore[PEV005]
                pass
            # fleet metrics snapshot (ISSUE 18 leg a): atomic dump of
            # this incarnation's registry, pid-named so a respawn never
            # overwrites the corpse's last-flushed counts. OSError is
            # survivable — a full disk must not kill the worker.
            try:
                fleet.write_snapshot(
                    fleet.snapshot_path(spec["run_dir"],
                                        spec["worker_id"], os.getpid()),
                    registry, spec["worker_id"], os.getpid(),
                    front=spec["front_id"],
                    generation=seen["generation"])
            except OSError:
                registry.counter(
                    "serve_fleet_snapshot_errors_total",
                    "fleet metrics snapshots lost to I/O errors").inc()
            buf = tracing.get_buffer()
            if buf is not None:
                buf.flush()
            _atomic_json(spec["stats_path"], {
                "pid": os.getpid(), "worker": spec["worker_id"],
                "generation": seen["generation"],
                "unix": round(now, 3),
                "summary": front.summary(),
                "singleflight_process": {
                    "leads": das._flight.leads,
                    "waits": das._flight.waits,
                    "takeovers": getattr(das._flight, "takeovers", 0),
                },
                "counts": registry.counts(),
            })
            stop.wait(spec["beat_s"])

    follower = threading.Thread(target=_follow, name="view-follower",
                                daemon=True)
    beater = threading.Thread(target=_beat, name="worker-beat",
                              daemon=True)
    follower.start()
    beater.start()
    stop.wait()
    front.stop()          # honest drain: queued work answers shed
    beater.join(timeout=2.0)
    front._flush_fast_metrics()  # fold the last beat-interval's tallies
    try:
        fleet.write_snapshot(
            fleet.snapshot_path(spec["run_dir"], spec["worker_id"],
                                os.getpid()),
            registry, spec["worker_id"], os.getpid(),
            front=spec["front_id"], generation=seen["generation"])
    except OSError:
        pass
    buf = tracing.get_buffer()
    if buf is not None:
        buf.flush()
    _atomic_json(spec["stats_path"], {
        "pid": os.getpid(), "worker": spec["worker_id"],
        "generation": seen["generation"], "unix": round(time.time(), 3),
        "summary": front.summary(),
        "singleflight_process": {"leads": das._flight.leads,
                                 "waits": das._flight.waits},
        "counts": registry.counts(), "final": True,
    })
    board.close(unlink=False)
    sys.exit(0)


class _Slot:
    """One worker slot: current process + its incarnation history."""

    def __init__(self, spec: dict, policy: RetryPolicy):
        self.spec = spec
        self.policy = policy
        self.proc = None
        self.launched_mono = 0.0
        self.launched_unix = 0.0
        self.respawn_at: float | None = None
        self.restarts = 0
        self.parked = False     # retry budget exhausted: refuse loudly
        self.totals: dict = {}  # counters folded in from dead incarnations


class WorkerPool:
    """Spawn, watch, and honestly restart N serving processes.

    ``ports`` maps workers onto listeners: one port = a kernel-balanced
    SO_REUSEPORT group; several ports = several fronts (worker i serves
    ``ports[i % len(ports)]``), which is how the multi-front balancer
    (``serve/balancer.py``) gets its backends.
    """

    def __init__(self, specs: list[dict], board, *,
                 hang_timeout_s: float = 3.0, rss_limit_mb: float = 0.0,
                 max_failures: int = 5, backoff_s: float = 0.2,
                 backoff_cap_s: float = 5.0, jitter: float = 0.25,
                 seed: int = 0, events_bus=None, chaos=None):
        self.board = board
        self.hang_timeout_s = float(hang_timeout_s)
        self.rss_limit_kb = float(rss_limit_mb) * 1024.0
        self.events_bus = events_bus
        self.chaos = chaos
        self._ctx = None
        self.slots = [
            _Slot(spec, RetryPolicy(max_failures=max_failures,
                                    backoff_s=backoff_s,
                                    backoff_cap_s=backoff_cap_s,
                                    jitter=jitter,
                                    seed=seed ^ (i << 8)))
            for i, spec in enumerate(specs)]
        self.interruptions: list[dict] = []
        self.chaos_kills_delivered = 0
        self._stop = threading.Event()
        self._monitor: threading.Thread | None = None
        self._lock = threading.Lock()

    # -- telemetry -------------------------------------------------------------

    def _emit(self, type_: str, **fields) -> None:
        try:
            if self.events_bus is not None:
                self.events_bus.emit(type_, **fields)
            else:
                from pos_evolution_tpu.telemetry import emit_global
                emit_global(type_, **fields)
        except Exception:
            pass

    # -- lifecycle -------------------------------------------------------------

    def _spawn(self, slot: _Slot) -> None:
        if self._ctx is None:
            import multiprocessing
            # spawn, never fork: the pool lives in a thread-running,
            # lock-holding parent (the exact fork-unsafety PEV007 flags)
            self._ctx = multiprocessing.get_context("spawn")
        # a fresh incarnation must not inherit the corpse's heartbeat
        # as its own liveness (heartbeat_age's attempt-boundary rule
        # covers the file; removing it keeps the stats dir honest too)
        slot.proc = self._ctx.Process(
            target=_worker_main, args=(slot.spec,),
            name=f"serve-worker-{slot.spec['worker_id']}", daemon=True)
        slot.proc.start()
        slot.launched_mono = time.monotonic()
        slot.launched_unix = time.time()
        slot.respawn_at = None
        self._emit("worker_spawn", worker=slot.spec["worker_id"],
                   pid=slot.proc.pid, restarts=slot.restarts)

    def start(self) -> None:
        for slot in self.slots:
            self._spawn(slot)
        self._monitor = threading.Thread(target=self._watch,
                                         name="pool-monitor", daemon=True)
        self._monitor.start()

    def wait_ready(self, timeout_s: float = 30.0) -> bool:
        """Block until every live worker has beaten its heartbeat at
        least once (its front is listening) or the timeout passes."""
        deadline = time.monotonic() + timeout_s
        from pos_evolution_tpu.utils.watchdog import read_heartbeat
        while time.monotonic() < deadline:
            ready = 0
            for slot in self.slots:
                hb = read_heartbeat(slot.spec["heartbeat_path"])
                if hb is not None and hb["payload"].get(
                        "unix", 0) >= slot.launched_unix:
                    ready += 1
            if ready == len(self.slots):
                return True
            time.sleep(0.05)
        return False

    def stop(self, timeout_s: float = 10.0) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=timeout_s)
        for slot in self.slots:
            proc = slot.proc
            if proc is None or not proc.is_alive():
                continue
            try:
                os.kill(proc.pid, signal.SIGTERM)
            except ProcessLookupError:
                continue
        deadline = time.monotonic() + timeout_s
        for slot in self.slots:
            proc = slot.proc
            if proc is None:
                continue
            proc.join(timeout=max(deadline - time.monotonic(), 0.1))
            if proc.is_alive():
                proc.kill()
                proc.join(timeout=2.0)

    # -- the monitor loop ------------------------------------------------------

    def kill_worker(self, worker_id: int,
                    reason: str = "chaos_sigkill") -> int | None:
        """SIGKILL one live worker (the chaos injection's entry point).
        Returns the killed pid, or None when the slot had no live
        process. The monitor then sees an ordinary crash — detection
        and respawn take the same path as a real failure."""
        for slot in self.slots:
            if slot.spec["worker_id"] != worker_id:
                continue
            proc = slot.proc
            if proc is None or not proc.is_alive():
                return None
            pid = proc.pid
            self._emit("worker_chaos_kill", worker=worker_id, pid=pid,
                       reason=reason)
            try:
                os.kill(pid, signal.SIGKILL)
            except ProcessLookupError:
                return None
            return pid
        return None

    def _hb_payload(self, slot: _Slot) -> dict:
        from pos_evolution_tpu.utils.watchdog import read_heartbeat
        hb = read_heartbeat(slot.spec["heartbeat_path"])
        return (hb or {}).get("payload") or {}

    def _fold_stats(self, slot: _Slot) -> None:
        """Fold a dead incarnation's last stats dump into the slot's
        running totals (the dump survives SIGKILL up to the last beat —
        bounded staleness, same posture as checkpoint loss)."""
        try:
            with open(slot.spec["stats_path"]) as f:
                stats = json.load(f)
        except (OSError, json.JSONDecodeError):
            return
        t = slot.totals
        summary = stats.get("summary") or {}
        for k, v in (summary.get("by_status") or {}).items():
            t.setdefault("by_status", {})
            t["by_status"][k] = t["by_status"].get(k, 0) + v
        for key in ("requests_total", "scheme_builds",
                    "slow_loris_closed", "conn_rejected"):
            t[key] = t.get(key, 0) + int(summary.get(key) or 0)
        sf = stats.get("singleflight_process") or {}
        t["sf_leads"] = t.get("sf_leads", 0) + int(sf.get("leads") or 0)
        t["sf_waits"] = t.get("sf_waits", 0) + int(sf.get("waits") or 0)

    def _interrupt(self, slot: _Slot, reason: str, exit_code) -> None:
        payload = self._hb_payload(slot)
        record = {
            "worker": slot.spec["worker_id"],
            "pid": slot.proc.pid if slot.proc else None,
            "reason": reason, "exit_code": exit_code,
            "wall_s": round(time.monotonic() - slot.launched_mono, 3),
            "last_heartbeat": payload or None,
        }
        self._fold_stats(slot)
        # tombstone the dead worker's health slot NOW: the supervisor
        # knows the process is gone (exitcode in hand) — routing must
        # not spend STALE_S believing the last heartbeat
        if self.board is not None:
            try:
                self.board.clear_health(slot.spec["front_id"])
            except (AssertionError, ValueError):
                pass
        delay = slot.policy.record_failure(
            progress=payload.get("requests"))
        with self._lock:
            self.interruptions.append(record)
        self._emit("worker_interruption", **record)
        if delay is None:
            slot.parked = True
            self._emit("worker_gaveup", worker=slot.spec["worker_id"],
                       consecutive_failures=slot.policy.failures)
            return
        slot.respawn_at = time.monotonic() + delay
        slot.restarts += 1
        self._emit("worker_backoff", worker=slot.spec["worker_id"],
                   failures=slot.policy.failures, delay_s=round(delay, 3))

    def _watch(self) -> None:
        while not self._stop.is_set():
            now = time.monotonic()
            if self.chaos is not None:
                for worker_id in self.chaos.worker_kills_due():
                    if self.kill_worker(worker_id) is not None:
                        # delivered to a LIVE process (a kill landing
                        # on an already-dead slot proves nothing)
                        with self._lock:
                            self.chaos_kills_delivered += 1
            for slot in self.slots:
                if slot.parked:
                    continue
                if slot.respawn_at is not None:
                    if now >= slot.respawn_at:
                        self._spawn(slot)
                    continue
                proc = slot.proc
                rc = proc.exitcode
                if rc is not None:
                    self._interrupt(slot, "crash", rc)
                    continue
                started_s = now - slot.launched_mono
                age = heartbeat_age(slot.spec["heartbeat_path"],
                                    slot.launched_unix, started_s)
                if age is not None and age > self.hang_timeout_s:
                    # no SIGTERM courtesy for a hung worker: it may be
                    # wedged past signal delivery; its connections die
                    # with it and the clients' retry path routes around
                    proc.kill()
                    proc.join(timeout=2.0)
                    self._interrupt(slot, "hang", -signal.SIGKILL)
                    continue
                if self.rss_limit_kb and rss_kb(proc.pid) > \
                        self.rss_limit_kb:
                    proc.kill()
                    proc.join(timeout=2.0)
                    self._interrupt(slot, "leak", -signal.SIGKILL)
                    continue
                # sustained liveness heals the streak: 10x the hang
                # timeout without an incident is "the environment
                # recovered", not luck
                if (slot.policy.failures
                        and started_s > 10.0 * self.hang_timeout_s):
                    slot.policy.record_success()
            self._stop.wait(_POLL_S)

    # -- reporting -------------------------------------------------------------

    def worker_rows(self) -> list[dict]:
        """Per-slot liveness rows for the run report's worker table."""
        rows = []
        for slot in self.slots:
            payload = self._hb_payload(slot)
            proc = slot.proc
            age = heartbeat_age(
                slot.spec["heartbeat_path"], slot.launched_unix,
                time.monotonic() - slot.launched_mono) \
                if proc is not None else None
            rows.append({
                "worker": slot.spec["worker_id"],
                "pid": proc.pid if proc is not None else None,
                "alive": bool(proc is not None and proc.is_alive()),
                "parked": slot.parked,
                "restarts": slot.restarts,
                "requests": payload.get("requests"),
                "generation": payload.get("slot"),
                "rss_kb": payload.get("rss_kb"),
                "hb_age_s": round(age, 3) if age is not None else None,
            })
        return rows

    def _read_stats(self, slot: _Slot) -> dict | None:
        try:
            with open(slot.spec["stats_path"]) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def summary(self) -> dict:
        """Pool-level aggregate: live worker stats + folded-in totals
        from dead incarnations + the interruption ledger."""
        agg = {"by_status": {}, "requests_total": 0, "scheme_builds": 0,
               "sf_leads": 0, "sf_waits": 0, "slow_loris_closed": 0,
               "conn_rejected": 0}
        per_worker = []
        for slot in self.slots:
            stats = self._read_stats(slot)
            summary = (stats or {}).get("summary") or {}
            sf = (stats or {}).get("singleflight_process") or {}
            for k, v in (summary.get("by_status") or {}).items():
                agg["by_status"][k] = agg["by_status"].get(k, 0) + v
            agg["requests_total"] += int(summary.get("requests_total")
                                         or 0)
            agg["scheme_builds"] += int(summary.get("scheme_builds")
                                        or 0)
            agg["slow_loris_closed"] += int(
                summary.get("slow_loris_closed") or 0)
            agg["conn_rejected"] += int(summary.get("conn_rejected")
                                        or 0)
            agg["sf_leads"] += int(sf.get("leads") or 0)
            agg["sf_waits"] += int(sf.get("waits") or 0)
            # dead incarnations' folded totals
            t = slot.totals
            for k, v in (t.get("by_status") or {}).items():
                agg["by_status"][k] = agg["by_status"].get(k, 0) + v
            agg["requests_total"] += t.get("requests_total", 0)
            agg["scheme_builds"] += t.get("scheme_builds", 0)
            agg["sf_leads"] += t.get("sf_leads", 0)
            agg["sf_waits"] += t.get("sf_waits", 0)
            agg["slow_loris_closed"] += t.get("slow_loris_closed", 0)
            agg["conn_rejected"] += t.get("conn_rejected", 0)
            per_worker.append({"worker": slot.spec["worker_id"],
                               "summary": summary})
        by_reason: dict[str, int] = {}
        with self._lock:
            interruptions = list(self.interruptions)
        for rec in interruptions:
            by_reason[rec["reason"]] = by_reason.get(rec["reason"], 0) + 1
        return {
            "workers": self.worker_rows(),
            "aggregate": agg,
            "interruptions": interruptions,
            "interruptions_by_reason": by_reason,
            "restarts": sum(s.restarts for s in self.slots),
            "chaos_kills_delivered": self.chaos_kills_delivered,
            "parked": sum(1 for s in self.slots if s.parked),
            "health": (self.board.read_health()
                       if self.board is not None else []),
        }
