"""Length-prefixed JSON wire protocol for the serving tier.

One frame = a 4-byte big-endian length prefix + a UTF-8 JSON object. No
heavyweight RPC dependency (nothing may be pip-installed in this image),
no pickle (clients are untrusted), and self-delimiting so many requests
can be pipelined on one connection and demultiplexed by ``id``.

Request envelope::

    {"id": <int>, "method": "<name>", "params": {...},
     "deadline_ms": <float remaining budget>, "tier": <int advisory>,
     "trace": {"id": "<16-hex>", "s": 1}?}

``trace`` is OPTIONAL and backward-compatible (JSON objects ignore
unknown members): a client that sampled the request for end-to-end
tracing (``telemetry/tracing.py``) attaches its deterministic trace id;
servers record queue-wait/service/backing spans under that id and
otherwise treat the request identically. Clients serialize the trace
member FIRST so traced frames fall off the servers' byte-scan fast path
(the traced request must take the fully-observed queue path), while
untraced frames stay byte-identical to the pre-trace protocol.

Response envelope::

    {"id": <int>, "status": "ok" | "shed" | "timeout" | "unavailable"
                          | "error",
     "result": {...}?, "retry_after_ms": <float>?, "error": "<msg>"?,
     "served_by": <worker>?}

Every non-``ok`` status is an **honest rejection**: the server tells the
client it did not (and will not) do the work, and — for ``shed`` /
``unavailable`` — when it is worth asking again. Binary payloads (cells,
branches, commitments, SSZ bytes) travel hex-encoded; at DAS cell sizes
the 2x overhead is noise next to the framing and the proof bytes are the
payload either way.

``recv_frame`` reads with a per-chunk timeout so a **slow-loris** client
(one that dribbles a frame byte-by-byte forever) stalls only its own
connection reader until the timeout trips — never a worker.
"""

from __future__ import annotations

import json
import socket
import struct

__all__ = ["ProtocolError", "send_frame", "recv_frame",
           "MAX_FRAME_BYTES"]

# Generous for a full-grid cell batch, small enough that a hostile
# length prefix cannot balloon allocation.
MAX_FRAME_BYTES = 8 * 1024 * 1024

_LEN = struct.Struct(">I")


class ProtocolError(Exception):
    """Malformed frame: oversize, non-JSON, or non-object payload."""


def send_frame(sock: socket.socket, obj: dict) -> None:
    """Serialize ``obj`` and write one frame (single ``sendall`` so
    concurrent senders on a shared socket only need a per-socket lock)."""
    body = json.dumps(obj, separators=(",", ":")).encode()
    if len(body) > MAX_FRAME_BYTES:
        raise ProtocolError(f"frame of {len(body)} bytes exceeds "
                            f"MAX_FRAME_BYTES={MAX_FRAME_BYTES}")
    sock.sendall(_LEN.pack(len(body)) + body)


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    """Read exactly ``n`` bytes; None on clean EOF at a frame boundary.
    ``socket.timeout`` propagates — the caller decides whether a stalled
    read is a slow-loris (mid-frame) or just an idle connection."""
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(n - got)
        if not chunk:
            if got == 0:
                return None
            raise ProtocolError("connection closed mid-frame")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_frame(sock: socket.socket) -> dict | None:
    """Read one frame; None on clean EOF. Raises ``ProtocolError`` on
    garbage and lets ``socket.timeout`` escape on a stalled read."""
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME_BYTES:
        raise ProtocolError(f"declared frame length {length} exceeds "
                            f"MAX_FRAME_BYTES={MAX_FRAME_BYTES}")
    body = _recv_exact(sock, length)
    if body is None:
        raise ProtocolError("connection closed between header and body")
    try:
        obj = json.loads(body)
    except json.JSONDecodeError as e:
        raise ProtocolError(f"non-JSON frame body: {e}") from None
    if not isinstance(obj, dict):
        raise ProtocolError("frame body must be a JSON object")
    return obj
