"""BLS12-381 scalar field Fr as vectorized Montgomery limb arithmetic.

``ops/fp.py`` covers the *base* field Fq for the pairing; commitments
need the 255-bit *scalar* field Fr (polynomial coefficients, NTT
twiddles, opening challenges). Same limb idiom — radix 2^12 digits in
32-bit lanes, log-depth carry resolution — but **Montgomery** instead
of Barrett: an NTT chains millions of multiplies by precomputable
constants, and Montgomery's reduction is two truncated convolutions
against fixed vectors (no quotient-window bookkeeping).

Representation: 22 limbs x 12 bits (264 >= 255). Montgomery radix
R = 2^264. Residues live lazily in [0, 2r); REDC keeps them there
(4r < R so the standard t < 2r bound holds), one conditional subtract
canonicalizes.

The module carries THREE implementations, bit-identical by test
(tests/test_kzg.py):

- the **oracle**: plain Python ints mod r — ground truth;
- the **host twin**: batched NumPy int64 over ``[..., 22]`` digit
  vectors (the reference backend and the small-batch path);
- the **device twin**: jitted JAX int32 reusing ``ops/fp.py``'s generic
  digit plumbing (``conv_digits`` / ``carry_norm`` / ``sub_digits``).

Montgomery REDC, formulated without the sequential CIOS loop (the same
reasoning as fp.py's no-32-step-loop rule): with T = a*b,

    m = (T mod R) * n' mod R        (one truncated convolution)
    t = (T + m*r) / R               (one convolution, exact shift)

— every step a log-depth batched op. Column sums <= 22*(2^12-1)^2
< 2^29, inside int32.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "MODULUS", "BITS", "MASK", "L", "R_MONT",
    "to_limbs", "from_limbs", "to_mont_int", "from_mont_int",
    "encode", "decode", "encode_int", "decode_int",
    "mont_mul", "mont_add", "mont_sub", "mont_neg", "mont_canon",
    "mont_inv", "mont_pow", "batch_inv",
    "ONE_M", "ZERO",
]

# the prime order of the BLS12-381 G1/G2 subgroups (crypto/bls12_381.R)
MODULUS = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001

BITS = 12
MASK = (1 << BITS) - 1
L = 22                      # 22 * 12 = 264 bits >= 255
R_MONT = 1 << (BITS * L)    # Montgomery radix 2^264; 4r < R_MONT

# n' = -r^(-1) mod R  (the REDC constant)
_NPRIME = (-pow(MODULUS, -1, R_MONT)) % R_MONT
# R^2 mod r (to_mont multiplier)
_R2 = R_MONT * R_MONT % MODULUS


def to_limbs(x: int, n: int = L) -> np.ndarray:
    """Python int -> little-endian base-2^12 digit vector (int64)."""
    assert 0 <= x
    out = np.zeros(n, dtype=np.int64)
    for i in range(n):
        out[i] = x & MASK
        x >>= BITS
    assert x == 0, "value does not fit in the limb vector"
    return out


def from_limbs(v) -> int:
    out = 0
    for i, d in enumerate(np.asarray(v).reshape(-1).tolist()):
        out += int(d) << (BITS * i)
    return out


P = to_limbs(MODULUS)
TWO_P = to_limbs(2 * MODULUS)
NP = to_limbs(_NPRIME)
R2 = to_limbs(_R2)
ZERO = np.zeros(L, dtype=np.int64)
ONE_M = to_limbs(R_MONT % MODULUS)       # 1 in Montgomery form


def to_mont_int(x: int) -> int:
    return x * R_MONT % MODULUS


def from_mont_int(x: int) -> int:
    return x * pow(R_MONT, -1, MODULUS) % MODULUS


# --- host digit plumbing (NumPy int64, batch-leading [..., n]) ----------------

def _conv_np(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Full digit-space product: [..., m] x [n] or [..., n] ->
    [..., m+n-1] column sums. b broadcasts like a second batch operand."""
    a = np.asarray(a, dtype=np.int64)
    b = np.asarray(b, dtype=np.int64)
    m, n = a.shape[-1], b.shape[-1]
    outer = a[..., :, None] * b[..., None, :]
    out = np.zeros(np.broadcast_shapes(a.shape[:-1], b.shape[:-1])
                   + (m + n - 1,), dtype=np.int64)
    for j in range(n):                      # n is small/static (<= 22)
        out[..., j:j + m] += outer[..., :, j]
    return out


def _carry_np(x: np.ndarray, out_len: int) -> np.ndarray:
    """Normalize non-negative digit sums to canonical digits < 2^12 over
    ``out_len`` limbs (value must fit; carries past the top are dropped
    only when the caller guarantees they are zero). Host twin of
    fp.carry_norm — folds until fixpoint, same canonical result."""
    x = np.asarray(x, dtype=np.int64)
    pad = out_len - x.shape[-1]
    if pad > 0:
        x = np.concatenate(
            [x, np.zeros(x.shape[:-1] + (pad,), dtype=np.int64)], axis=-1)
    elif pad < 0:
        raise ValueError("_carry_np cannot truncate")
    while (x >> BITS).any():
        c = x >> BITS
        x = (x & MASK)
        x[..., 1:] += c[..., :-1]
    return x


def _sub_np(x: np.ndarray, y: np.ndarray):
    """(x - y mod 2^(12*len), underflow) over canonical digit vectors."""
    x = np.asarray(x, dtype=np.int64)
    y = np.broadcast_to(np.asarray(y, dtype=np.int64), x.shape)
    n = x.shape[-1]
    d = x - y
    borrow = np.zeros(x.shape[:-1], dtype=np.int64)
    out = np.empty_like(d)
    for i in range(n):                      # n static and small
        t = d[..., i] - borrow
        borrow = (t < 0).astype(np.int64)
        out[..., i] = t + (borrow << BITS)
    return out, borrow.astype(bool)


def _cond_sub_np(x: np.ndarray, y: np.ndarray) -> np.ndarray:
    d, uf = _sub_np(x, y)
    return np.where(uf[..., None], x, d)


# --- host field ops: Montgomery residues in [0, 2r) ---------------------------

def mont_mul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """REDC(a * b): inputs/outputs Montgomery residues in [0, 2r).
    (2r)^2 < R*r, so t = (T + m*r)/R < 2r without any final subtract."""
    t = _carry_np(_conv_np(a, b), 2 * L)
    m = _carry_np(_conv_np(t[..., :L], NP), 2 * L)[..., :L]
    u = _conv_np(m, P)
    u = np.concatenate(
        [u, np.zeros(u.shape[:-1] + (2 * L + 1 - u.shape[-1],),
                     dtype=np.int64)], axis=-1)
    u[..., :2 * L] += t
    u = _carry_np(u, 2 * L + 1)
    # low L digits are exactly zero (u ≡ 0 mod R); the shift is a slice
    return u[..., L:2 * L]


def mont_add(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    s = _carry_np(np.asarray(a, dtype=np.int64)
                  + np.asarray(b, dtype=np.int64), L)
    return _cond_sub_np(s, TWO_P)


def mont_sub(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    d, uf = _sub_np(np.asarray(a, dtype=np.int64), b)
    wrapped = _carry_np(d + TWO_P, L + 1)[..., :L]
    return np.where(uf[..., None], wrapped, d)


def mont_neg(a: np.ndarray) -> np.ndarray:
    return mont_sub(np.broadcast_to(ZERO, np.asarray(a).shape), a)


def mont_canon(a: np.ndarray) -> np.ndarray:
    """[0, 2r) -> [0, r): canonical digits for equality/serialization."""
    return _cond_sub_np(np.asarray(a, dtype=np.int64), P)


_EXP_BITS = [(MODULUS - 2) >> i & 1
             for i in range(MODULUS.bit_length())][::-1]


def mont_pow(a: np.ndarray, e_bits=None) -> np.ndarray:
    """Square-and-multiply over a static bit string (default r-2:
    inversion by Fermat; 0 -> 0 by that convention)."""
    bits = _EXP_BITS if e_bits is None else e_bits
    acc = np.broadcast_to(ONE_M, np.asarray(a).shape).astype(np.int64)
    for bit in bits:
        acc = mont_mul(acc, acc)
        if bit:
            acc = mont_mul(acc, a)
    return acc


mont_inv = mont_pow


def batch_inv(a: np.ndarray) -> np.ndarray:
    """Montgomery batch inversion over the last-but-one axis: [..., n, L]
    -> elementwise inverses with ONE Fermat inversion total (log-depth
    Hillis-Steele prefix products + a backward sweep). Raises on zero —
    callers invert challenge offsets that are nonzero with overwhelming
    probability, and a silent 0^-1 = 0 would forge-verify."""
    a = np.asarray(a, dtype=np.int64)
    n = a.shape[-2]
    if (mont_canon(a) == 0).all(axis=-1).any():
        raise ZeroDivisionError("batch_inv of zero element")
    prefix = a.copy()                       # prefix[i] = a[0]*...*a[i]
    shift = 1
    while shift < n:
        prefix[..., shift:, :] = mont_mul(prefix[..., shift:, :],
                                          prefix[..., :n - shift, :])
        shift *= 2
    total_inv = mont_inv(prefix[..., n - 1, :])
    out = np.empty_like(a)
    for i in range(n - 1, 0, -1):           # n small (<= domain size)
        out[..., i, :] = mont_mul(total_inv, prefix[..., i - 1, :])
        total_inv = mont_mul(total_inv, a[..., i, :])
    out[..., 0, :] = total_inv
    return out


# --- element <-> limb encodes (host arrays) -----------------------------------

def encode_int(x: int) -> np.ndarray:
    """Canonical int -> Montgomery limb vector."""
    return mont_mul(to_limbs(x % MODULUS), R2)


def decode_int(v: np.ndarray) -> int:
    """Montgomery limb vector -> canonical int."""
    one = np.zeros(L, dtype=np.int64)
    one[0] = 1
    return from_limbs(mont_canon(mont_mul(np.asarray(v, dtype=np.int64),
                                          one)))


def encode(xs) -> np.ndarray:
    """Iterable of ints -> [n, L] Montgomery limbs (vectorized REDC)."""
    arr = np.stack([to_limbs(int(x) % MODULUS) for x in xs])
    return mont_mul(arr, R2)


def decode(v: np.ndarray) -> list[int]:
    """[..., L] Montgomery limbs -> canonical ints."""
    one = np.zeros(L, dtype=np.int64)
    one[0] = 1
    canon = mont_canon(mont_mul(np.asarray(v, dtype=np.int64), one))
    flat = canon.reshape(-1, L)
    return [from_limbs(row) for row in flat]


# --- device twin (jitted JAX int32, fp.py digit plumbing) ---------------------
#
# Imported lazily: the numpy backend must never pull jax in. The device
# functions mirror the host ones digit for digit; the differential tests
# pin host == device == oracle on canonical outputs.

_DEV = None


def _device():
    global _DEV
    if _DEV is None:
        import jax

        from pos_evolution_tpu.backend.jax_init import ensure_x64
        ensure_x64()
        import jax.numpy as jnp

        from pos_evolution_tpu.ops import fp

        p_c = P.astype(np.int32)
        two_p_c = TWO_P.astype(np.int32)
        np_c = NP.astype(np.int32)
        one_m_c = ONE_M.astype(np.int32)

        def mul(a, b):
            t = fp.carry_norm(fp.conv_digits(a, b), 2 * L)
            m = fp.carry_norm(
                fp.conv_digits(t[..., :L], jnp.asarray(np_c)),
                2 * L)[..., :L]
            u = fp.conv_digits(m, jnp.asarray(p_c))
            u = jnp.pad(u, [(0, 0)] * (u.ndim - 1)
                        + [(0, 2 * L + 1 - u.shape[-1])])
            u = u.at[..., :2 * L].add(t)
            return fp.carry_norm(u, 2 * L + 1)[..., L:2 * L]

        def add(a, b):
            s = fp.carry_norm(a + b, L)
            return fp.cond_sub(s, two_p_c)

        def sub(a, b):
            d, uf = fp.sub_digits(a, b)
            wrapped = fp.carry_norm(d + jnp.asarray(two_p_c),
                                    L + 1)[..., :L]
            return jnp.where(uf[..., None], wrapped, d)

        def canon_(a):
            return fp.cond_sub(a, p_c)

        _bits = np.asarray(_EXP_BITS, dtype=bool)

        def inv(a):
            acc = jnp.broadcast_to(jnp.asarray(one_m_c),
                                   a.shape).astype(jnp.int32)

            def step(acc, bit):
                acc = mul(acc, acc)
                return jnp.where(bit, mul(acc, a), acc), None

            acc, _ = jax.lax.scan(step, acc, jnp.asarray(_bits))
            return acc

        _DEV = {
            "mul": mul, "add": add, "sub": sub, "canon": canon_,
            "inv": inv,
            "mul_jit": jax.jit(mul), "add_jit": jax.jit(add),
            "canon_jit": jax.jit(canon_),
        }
    return _DEV


def device_ops() -> dict:
    """The jitted device twin: dict of mul/add/sub/canon/inv closures
    over int32 limb arrays (ntt.py composes them into the NTT kernel)."""
    return _device()
