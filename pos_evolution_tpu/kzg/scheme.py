"""``KzgCellScheme``: pairing-backed cell commitments behind the DAS seam.

A blob's extended grid (n_cells x cell_bytes) IS a polynomial: every
16-byte column chunk packs little-endian into one Fr element (< 2^128
< r, trivially canonical), cell i's chunk j sitting at domain index
i + n_cells*j of the size-N = n_cells*m evaluation domain. That layout
makes each cell exactly the restriction of f to one size-m *coset*
w^i * H (H the order-m subgroup), so per-cell openings have the cheap
vanishing polynomial X^m - w^(i*m) and the committee-wide aggregate of
``kzg/aggregate.py`` applies directly.

The coefficient form comes from ONE batched INTT through the
``ExecutionBackend`` seam (``kzg/ntt.py``) and the commitment MSM runs
on the backend too (host Pippenger on numpy, the per-lane
double-and-add device kernel on jax) — commit is bit-identical either
way, which tests/test_kzg.py pins on randomized blobs.

Wire format: sidecar commitments are pinned SSZ ``Bytes32``, a KZG
commitment is a 48-byte G1 point. The scheme therefore publishes
``wire_bind(point) = sha256(tag || compressed_point)`` as the 32-byte
wire commitment; aggregate proofs ship the real points and every
verifier checks the hash binding before the pairing — binding under
collision resistance, no container/graffiti layout change.

Erasure availability is untouched: the GF(2^8) ``reconstruct_check``
stays the low-degree/extension check in ``BlobStore``; KZG binds the
grid content to the 32-byte commitment the graffiti digest covers.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict

import numpy as np

from pos_evolution_tpu.config import cfg
from pos_evolution_tpu.crypto.bls12_381 import R as _R
from pos_evolution_tpu.crypto.bls12_381 import g1_compress
from pos_evolution_tpu.das.commitment import (
    CellCommitmentScheme,
    register_scheme,
)
from pos_evolution_tpu.kzg import aggregate, curve, fr, ntt
from pos_evolution_tpu.kzg.setup import trusted_setup

__all__ = ["KzgCellScheme", "CHUNK_BYTES"]

# bytes per Fr element: 16 < 31 keeps every chunk canonically < r AND
# the domain small (N = n_cells * cell_bytes/16)
CHUNK_BYTES = 16

_WIRE_TAG = b"pev-kzg-wire-v1"


class KzgCellScheme(CellCommitmentScheme):
    """KZG commitments + aggregated multiproofs for the DAS cell grid."""

    name = "kzg"
    # capability flag: DasServer/serve front serve ONE aggregate proof
    # per (block, sampled set) instead of per-cell merkle branches
    aggregates = True

    def __init__(self):
        # commit memo: grid digest -> (point, compressed, coeffs, wire).
        # One scheme instance is shared engine-wide (every view group's
        # BlobStore + the DAS server), so the memo collapses the
        # per-group commitment recomputation AND the serve tier's
        # proof builds onto one MSM per distinct blob. Locked: the
        # serve tier hits this from worker threads.
        self._memo: OrderedDict = OrderedDict()
        self._memo_lock = threading.Lock()
        self._memo_cap = 256

    # -- geometry --------------------------------------------------------------

    @staticmethod
    def geometry() -> tuple[int, int, int]:
        """(n_cells, m, N) for the active config; loud on bad shapes."""
        c = cfg()
        n_cells = 2 * c.das_cells_per_blob
        if c.das_cell_bytes % CHUNK_BYTES:
            raise ValueError("das_cell_bytes must be a multiple of "
                             f"{CHUNK_BYTES} for the kzg scheme")
        m = c.das_cell_bytes // CHUNK_BYTES
        if m & (m - 1) or n_cells & (n_cells - 1):
            raise ValueError("kzg scheme needs power-of-two cell count "
                             "and chunks per cell")
        return n_cells, m, n_cells * m

    @staticmethod
    def depth_for(n_cells: int) -> int:
        return 0            # no branch walk: proofs are aggregates

    def setup(self):
        n_cells, m, n = self.geometry()
        return trusted_setup(n, cfg().kzg_setup_seed)

    # -- wire binding ----------------------------------------------------------

    @staticmethod
    def wire_bind(compressed_point: bytes) -> bytes:
        """48-byte G1 point -> the 32-byte wire commitment the sidecar
        container / graffiti digest carry."""
        return hashlib.sha256(_WIRE_TAG + bytes(compressed_point)).digest()

    @staticmethod
    def cell_values(cell: np.ndarray) -> tuple:
        """One cell row (cell_bytes,) u8 -> its m Fr evaluations."""
        raw = np.ascontiguousarray(cell, dtype=np.uint8).tobytes()
        return tuple(int.from_bytes(raw[o:o + CHUNK_BYTES], "little")
                     for o in range(0, len(raw), CHUNK_BYTES))

    # -- commit ----------------------------------------------------------------

    def commit_full(self, cells: np.ndarray):
        """(point, compressed, coeffs, wire_commitment) for a grid,
        memoized by content digest — commit is called once per view
        group per sidecar and again on the serving path."""
        grid = np.ascontiguousarray(cells, dtype=np.uint8)
        n_cells, m, n = self.geometry()
        if grid.shape != (n_cells, cfg().das_cell_bytes):
            raise ValueError(f"grid shape {grid.shape} does not match "
                             f"the das config")
        key = (n_cells, m, hashlib.sha256(grid.tobytes()).digest())
        with self._memo_lock:
            hit = self._memo.get(key)
            if hit is not None:
                self._memo.move_to_end(key)
                return hit
        evals = np.zeros(n, dtype=object)
        chunks = grid.reshape(n_cells, m, CHUNK_BYTES)
        for i in range(n_cells):
            for j in range(m):
                evals[i + n_cells * j] = int.from_bytes(
                    chunks[i, j].tobytes(), "little")
        coeffs_mont = ntt.intt(fr.encode(evals.tolist()))
        coeffs = fr.decode(coeffs_mont)
        point = self._msm(coeffs)
        comp = g1_compress(point)
        out = (point, comp, tuple(coeffs), self.wire_bind(comp))
        with self._memo_lock:
            self._memo[key] = out
            self._memo.move_to_end(key)
            while len(self._memo) > self._memo_cap:
                self._memo.popitem(last=False)
        return out

    def _msm(self, coeffs):
        """Commitment MSM through the backend seam: host Pippenger on
        numpy, the device double-and-add kernel on jax (bit-identical)."""
        from pos_evolution_tpu.backend import get_backend
        setup = self.setup()
        dev = getattr(get_backend(), "g1_msm", None)
        if dev is not None:
            return dev(setup, coeffs)
        return curve.g1_lincomb(setup.powers_g1[:len(coeffs)], coeffs)

    def commit(self, cells: np.ndarray) -> bytes:
        return self.commit_full(cells)[3]

    # -- single-blob proofs (CellCommitmentScheme contract) --------------------

    def prove_cells(self, cells: np.ndarray, indices) -> list[bytes]:
        """Aggregate proof for a batch of this one blob's cells,
        encoded as the interface's opaque list[bytes]."""
        point, comp, coeffs, wire = self.commit_full(cells)
        n_cells, m, _n = self.geometry()
        claims = [(0, int(i), self.cell_values(cells[int(i)]))
                  for i in indices]
        proof = aggregate.prove(self.setup(), n_cells, m,
                                [(wire, point, list(coeffs))], claims)
        return self.encode_proof(proof)

    def verify_cells(self, commitment: bytes, cells: np.ndarray, indices,
                     proof: list[bytes]) -> bool:
        """Check sampled cells of one blob against its 32-byte wire
        commitment via the aggregate pairing equation."""
        n_cells, m, _n = self.geometry()
        try:
            decoded = self.decode_proof(proof)
        except (ValueError, IndexError):
            return False
        claims = [(0, int(i), self.cell_values(cells[j]))
                  for j, i in enumerate(indices)]
        return aggregate.verify(self.setup(), n_cells, m,
                                [bytes(commitment)], claims, decoded,
                                self.wire_bind)

    # -- committee aggregates (DasServer / serve tier) -------------------------

    def prove_aggregate(self, grids, samples) -> dict:
        """One proof for everything a committee sampled from one block.
        grids: per-blob cell grids; samples: [(blob, cell), ...]."""
        n_cells, m, _n = self.geometry()
        blobs = []
        for grid in grids:
            point, _comp, coeffs, wire = self.commit_full(grid)
            blobs.append((wire, point, list(coeffs)))
        claims = [(int(b), int(c),
                   self.cell_values(np.asarray(grids[int(b)])[int(c)]))
                  for b, c in samples]
        return aggregate.prove(self.setup(), n_cells, m, blobs, claims)

    def verify_aggregate(self, wire_commitments, samples, cells,
                         proof: dict) -> bool:
        """Committee-side check: sampled cell bytes + per-blob wire
        commitments + the (points, W, W') proof -> one pairing verdict."""
        n_cells, m, _n = self.geometry()
        claims = [(int(b), int(c), self.cell_values(np.asarray(cells[j])))
                  for j, (b, c) in enumerate(samples)]
        return aggregate.verify(self.setup(), n_cells, m,
                                [bytes(wc) for wc in wire_commitments],
                                claims, proof, self.wire_bind)

    # -- proof wire encoding ---------------------------------------------------

    @staticmethod
    def encode_proof(proof: dict) -> list[bytes]:
        return ([aggregate.PROOF_TAG]
                + [bytes(p) for p in proof["points"]]
                + [bytes(proof["w"]), bytes(proof["wp"])])

    @staticmethod
    def decode_proof(parts: list[bytes]) -> dict:
        parts = [bytes(p) for p in parts]
        if len(parts) < 4 or parts[0] != aggregate.PROOF_TAG:
            raise ValueError("malformed kzg aggregate proof")
        return {"points": parts[1:-2], "w": parts[-2], "wp": parts[-1]}

    @staticmethod
    def proof_n_bytes(proof: dict) -> int:
        return aggregate.proof_n_bytes(proof)


register_scheme(KzgCellScheme)
