"""Batched radix-2 NTT/INTT over the 2^32 root-of-unity subgroup of Fr.

r - 1 has 2-adicity 32, so Fr* contains a 2^32-element subgroup of
roots of unity; omega = 7^((r-1)/2^32) generates it (7 is a quadratic
non-residue mod r, hence a generator up to odd part). Every power-of-two
domain size n <= 2^32 uses omega_n = omega^(2^32/n).

Shapes: ``[..., n, fr.L]`` Montgomery limb vectors, transformed along
the -2 axis; batch dims lead. Decimation-in-time Cooley-Tukey with a
precomputed bit-reversal gather and per-stage twiddle tables (Montgomery
constants, host numpy — a jnp constant inside a trace would leak, same
rule as fp._conv_selector).

Dispatch follows the ``ops/merkle_device.py`` seam: spec-level callers
use :func:`ntt`/:func:`intt`, which route through the thread's
``ExecutionBackend`` (``fr_ntt``); the jax backend runs the jitted
device kernel with a host fallback, the numpy backend pins the host
twin. Locked stats counters record where each transform actually ran.
"""

from __future__ import annotations

import threading
from functools import lru_cache

import numpy as np

from pos_evolution_tpu.kzg import fr

__all__ = [
    "OMEGA_2_32", "domain", "root_of_unity",
    "ntt", "intt", "ntt_host", "ntt_device",
    "stats", "reset_stats",
]

# generator of the full 2^32 subgroup
OMEGA_2_32 = pow(7, (fr.MODULUS - 1) >> 32, fr.MODULUS)

_STATS = {"host_ntts": 0, "device_ntts": 0, "fallback_host": 0}
_STATS_LOCK = threading.Lock()


def _bump(key: str, n: int = 1) -> None:
    with _STATS_LOCK:
        _STATS[key] += n


def stats() -> dict:
    with _STATS_LOCK:
        return dict(_STATS)


def reset_stats() -> None:
    with _STATS_LOCK:
        for k in _STATS:
            _STATS[k] = 0


@lru_cache(maxsize=64)
def root_of_unity(n: int) -> int:
    """Primitive n-th root of unity (n a power of two <= 2^32)."""
    assert n & (n - 1) == 0 and 0 < n <= (1 << 32), n
    return pow(OMEGA_2_32, (1 << 32) // n, fr.MODULUS)


@lru_cache(maxsize=64)
def domain(n: int) -> tuple[int, ...]:
    """The evaluation domain (1, w, w^2, ..., w^(n-1)) as ints."""
    w = root_of_unity(n)
    out, acc = [], 1
    for _ in range(n):
        out.append(acc)
        acc = acc * w % fr.MODULUS
    return tuple(out)


@lru_cache(maxsize=64)
def _plan(n: int, inverse: bool):
    """(bit-reversal gather, per-stage twiddle tables, n^-1 scale) —
    host numpy Montgomery constants shared by both twins."""
    assert n & (n - 1) == 0 and n >= 1
    logn = n.bit_length() - 1
    rev = np.zeros(n, dtype=np.int64)
    for i in range(n):
        rev[i] = int(format(i, f"0{logn}b")[::-1], 2) if logn else 0
    w = root_of_unity(n)
    if inverse:
        w = pow(w, -1, fr.MODULUS)
    tables = []
    for s in range(logn):
        m2 = 1 << s                          # butterfly half-width
        step = n // (2 * m2)
        tw = [pow(w, step * j, fr.MODULUS) for j in range(m2)]
        tables.append(fr.encode(tw))
    scale = fr.encode([pow(n, -1, fr.MODULUS)])[0] if inverse else None
    return rev, tuple(tables), scale


def _transform(x, plan, ops, asarray):
    """The shared Cooley-Tukey ladder, parameterized over the field-op
    set (host numpy or jitted device closures)."""
    rev, tables, scale = plan
    n = x.shape[-2]
    x = x[..., rev, :]
    for tw in tables:
        m2 = tw.shape[0]
        shp = x.shape[:-2] + (n // (2 * m2), 2, m2, fr.L)
        x = x.reshape(shp)
        a = x[..., 0, :, :]
        b = ops["mul"](x[..., 1, :, :], asarray(tw))
        x = _stack2(ops, a, b)
        x = x.reshape(x.shape[:-4] + (n, fr.L))
    if scale is not None:
        x = ops["mul"](x, asarray(scale))
    return x


def _stack2(ops, a, b):
    """[(a+b), (a-b)] back into the [..., blocks, 2, m2, L] layout."""
    hi = ops["add"](a, b)
    lo = ops["sub"](a, b)
    return ops["stack"]([hi, lo])


_HOST_OPS = {
    "mul": fr.mont_mul,
    "add": fr.mont_add,
    "sub": fr.mont_sub,
    "stack": lambda xs: np.stack(xs, axis=-3),
}


def ntt_host(values: np.ndarray, inverse: bool = False) -> np.ndarray:
    """Host-NumPy transform: [..., n, L] Montgomery limbs -> same shape.
    Forward maps coefficients to evaluations on ``domain(n)``; inverse
    undoes it (with the n^-1 scale)."""
    values = np.asarray(values, dtype=np.int64)
    return _transform(values, _plan(values.shape[-2], bool(inverse)),
                      _HOST_OPS, lambda c: c)


@lru_cache(maxsize=32)
def _device_kernel(n: int, inverse: bool):
    import jax
    import jax.numpy as jnp

    dev = fr.device_ops()
    plan = _plan(n, inverse)
    ops = {
        "mul": dev["mul"], "add": dev["add"], "sub": dev["sub"],
        "stack": lambda xs: jnp.stack(xs, axis=-3),
    }

    def kernel(x):
        return _transform(x, plan, ops,
                          lambda c: jnp.asarray(c.astype(np.int32)))

    return jax.jit(kernel)


def ntt_device(values: np.ndarray, inverse: bool = False) -> np.ndarray:
    """Jitted JAX transform — bit-identical to :func:`ntt_host` (the
    device twin shares the host plan's twiddle constants digit for
    digit). Kernels are memoized per (n, inverse): no fresh jit per
    call (analysis/ PEV rule)."""
    import jax.numpy as jnp

    values = np.ascontiguousarray(values)
    kernel = _device_kernel(values.shape[-2], bool(inverse))
    out = kernel(jnp.asarray(values.astype(np.int32)))
    return np.asarray(out).astype(np.int64)


# --- backend seam -------------------------------------------------------------

def ntt(values: np.ndarray, inverse: bool = False) -> np.ndarray:
    """Transform through the thread's ``ExecutionBackend``: the numpy
    backend pins the host twin, the jax backend runs the device kernel
    (with a loud-once host fallback, merkle_device-style)."""
    from pos_evolution_tpu.backend import get_backend
    backend = get_backend()
    fn = getattr(backend, "fr_ntt", None)
    if fn is None:
        _bump("host_ntts")
        return ntt_host(values, inverse)
    return fn(values, inverse)


def intt(values: np.ndarray) -> np.ndarray:
    return ntt(values, inverse=True)


def fr_ntt_host_entry(values, inverse):
    """numpy_backend.fr_ntt: pinned host path (the reference oracle
    backend must not pick up device state)."""
    _bump("host_ntts")
    return ntt_host(values, inverse)


_FELL_BACK = False


def fr_ntt_device_entry(values, inverse):
    """jax_backend.fr_ntt: device kernel with one-shot warned host
    fallback (same ladder posture as ops/merkle_device.py — a broken
    jax install degrades, never crashes the serving path)."""
    global _FELL_BACK
    try:
        out = ntt_device(values, inverse)
        _bump("device_ntts")
        return out
    except Exception as e:  # pragma: no cover - exercised only sans jax
        _bump("fallback_host")
        if not _FELL_BACK:
            _FELL_BACK = True
            import warnings
            warnings.warn(f"fr_ntt device path failed ({e!r}); "
                          "falling back to host NTT", RuntimeWarning)
        return ntt_host(values, inverse)
