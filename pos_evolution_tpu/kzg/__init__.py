"""KZG polynomial commitments for the DAS grid (DESIGN.md §23).

The package splits along the repo's standard host/device seam:

- ``fr.py`` — the BLS12-381 *scalar* field Fr as vectorized Montgomery
  limb arithmetic: a pure-Python-int oracle, a batched NumPy host twin
  and a jitted JAX device twin, bit-identical by construction.
- ``ntt.py`` — batched radix-2 NTT/INTT over the 2^32 root-of-unity
  subgroup of Fr*, dispatched through the ``ExecutionBackend`` seam
  (``fr_ntt``) with the same mode/stats ladder as
  ``ops/merkle_device.py``.
- ``curve.py`` — inversion-free Jacobian group arithmetic on Python
  ints (the oracle's affine ``ec_mul`` inverts per step — minutes per
  MSM; this is milliseconds) plus a Pippenger multi-scalar multiply.
- ``setup.py`` — the deterministic *insecure* powers-of-tau setup
  (tau derived from a public seed; fine for a simulator, see DESIGN.md).
- ``aggregate.py`` — the two-group-element multiproof (the polynomial
  multiproofs recipe): all cells a client committee samples from one
  block fold into (W, W') and verify with ONE pairing equation.
- ``scheme.py`` — ``KzgCellScheme``, registered as ``"kzg"`` in the
  ``das/commitment.py`` registry.
"""

__all__ = ["KzgCellScheme"]


def __getattr__(name):
    # lazy: importing the package for the field engine alone must not
    # drag the curve/setup modules (and their import-time constants) in
    if name == "KzgCellScheme":
        from pos_evolution_tpu.kzg.scheme import KzgCellScheme
        return KzgCellScheme
    raise AttributeError(name)
