"""Inversion-free G1 group arithmetic on Python ints (host MSM path).

The oracle's affine ``ec_add``/``ec_mul`` (crypto/bls12_381.py) pay one
Fermat inversion mod Q *per step* — fine for pinning a pairing, hopeless
for the size-N multi-scalar multiplies a KZG commit needs (~0.1 s per
scalar mul makes a 128-term MSM half a minute). Here: Jacobian
coordinates (a = 0 curve, the same formulas as the device kernel
``ops/pairing.g1_double_jac``/``g1_add_jac``), one shared batch
inversion at the very end to normalize back to affine. Differentially
pinned against the oracle in tests/test_kzg.py.

Points: affine = (x, y) ints or None for infinity (oracle convention);
Jacobian = (X, Y, Z) with Z = 0 for infinity.
"""

from __future__ import annotations

from pos_evolution_tpu.crypto.bls12_381 import Q

__all__ = [
    "to_jac", "jac_double", "jac_add", "jac_mul", "jac_to_affine",
    "batch_to_affine", "g1_lincomb",
]

_JAC_INF = (1, 1, 0)


def to_jac(p):
    return _JAC_INF if p is None else (p[0], p[1], 1)


def jac_double(p):
    X, Y, Z = p
    if Z == 0 or Y == 0:
        return _JAC_INF if Y == 0 and Z != 0 else p
    A = X * X % Q
    B = Y * Y % Q
    C = B * B % Q
    t = X + B
    D = 2 * (t * t - A - C) % Q
    E = 3 * A % Q
    X3 = (E * E - 2 * D) % Q
    Y3 = (E * (D - X3) - 8 * C) % Q
    Z3 = 2 * Y * Z % Q
    return (X3, Y3, Z3)


def jac_add(p, q):
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    if Z1 == 0:
        return q
    if Z2 == 0:
        return p
    Z1Z1 = Z1 * Z1 % Q
    Z2Z2 = Z2 * Z2 % Q
    U1 = X1 * Z2Z2 % Q
    U2 = X2 * Z1Z1 % Q
    S1 = Y1 * Z2 * Z2Z2 % Q
    S2 = Y2 * Z1 * Z1Z1 % Q
    if U1 == U2:
        if S1 == S2:
            return jac_double(p)
        return _JAC_INF
    H = (U2 - U1) % Q
    r = (S2 - S1) % Q
    H2 = H * H % Q
    H3 = H * H2 % Q
    V = U1 * H2 % Q
    X3 = (r * r - H3 - 2 * V) % Q
    Y3 = (r * (V - X3) - S1 * H3) % Q
    Z3 = H * Z1 * Z2 % Q
    return (X3, Y3, Z3)


def jac_mul(p, k: int):
    """Scalar multiply (double-and-add; k reduced by the caller)."""
    acc = _JAC_INF
    add = to_jac(p) if len(p) == 2 else p
    while k:
        if k & 1:
            acc = jac_add(acc, add)
        add = jac_double(add)
        k >>= 1
    return acc


def jac_to_affine(p):
    X, Y, Z = p
    if Z == 0:
        return None
    zi = pow(Z, -1, Q)
    zi2 = zi * zi % Q
    return (X * zi2 % Q, Y * zi2 * zi % Q)


def batch_to_affine(points) -> list:
    """Jacobian list -> affine list with ONE field inversion total
    (Montgomery's trick over the Z coordinates)."""
    zs = [p[2] for p in points]
    n = len(zs)
    prefix = [1] * (n + 1)
    for i, z in enumerate(zs):
        prefix[i + 1] = prefix[i] * (z if z else 1) % Q
    inv_total = pow(prefix[n], -1, Q)
    out = [None] * n
    for i in range(n - 1, -1, -1):
        z = zs[i]
        if z == 0:
            continue
        zi = inv_total * prefix[i] % Q
        inv_total = inv_total * z % Q
        zi2 = zi * zi % Q
        X, Y, _ = points[i]
        out[i] = (X * zi2 % Q, Y * zi2 * zi % Q)
    return out


def _msm(pairs):
    """Pippenger multi-scalar multiply: (affine point, int scalar)
    pairs -> affine sum (None = infinity). Window c = 8 — right-sized
    for the N <= a-few-hundred commit MSMs of the DAS grid."""
    pairs = [(p, s) for p, s in pairs if p is not None and s]
    if not pairs:
        return None
    c = 8
    max_bits = max(s.bit_length() for _, s in pairs)
    n_windows = (max_bits + c - 1) // c
    acc = _JAC_INF
    for w in range(n_windows - 1, -1, -1):
        for _ in range(c):
            acc = jac_double(acc)
        buckets: dict[int, tuple] = {}
        for p, s in pairs:
            d = (s >> (w * c)) & ((1 << c) - 1)
            if d:
                cur = buckets.get(d)
                buckets[d] = (jac_add(cur, to_jac(p)) if cur is not None
                              else to_jac(p))
        run, win = _JAC_INF, _JAC_INF
        for d in range(max(buckets) if buckets else 0, 0, -1):
            b = buckets.get(d)
            if b is not None:
                run = jac_add(run, b)
            win = jac_add(win, run)
        acc = jac_add(acc, win)
    return jac_to_affine(acc)


def g1_lincomb(points, scalars) -> tuple | None:
    """sum(s_i * P_i) over affine G1 points with int scalars (reduced
    mod r by the caller or here — either way exact)."""
    from pos_evolution_tpu.crypto.bls12_381 import R
    pairs = [(p, s % R) for p, s in zip(points, scalars)]
    return _msm(pairs)
