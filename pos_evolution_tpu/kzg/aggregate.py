"""Two-group-element KZG multiproof: one opening proof per committee.

The polynomial-multiproofs recipe (the arxiv 2604.16559 shape): a block
carries blobs committed as C_b = [f_b(tau)]G1; a client committee
samples cells, each cell being f_b restricted to one size-m coset of
the evaluation domain. ALL sampled (blob, cell) claims fold into TWO
G1 elements:

    h(X)  = sum_i r_i * (f_{b_i}(X) - I_i(X)) / Z_i(X),     W  = [h(tau)]
    L(X)  = sum_i gamma_i * (f_{b_i}(X) - I_i(s))
            - Z_T(s) * h(X),        gamma_i = r_i * Z_T(s) / Z_i(s)
    W' = [L(tau) / (tau - s)]

with r_i and the second challenge s Fiat-Shamir-derived (s *after* W —
the order matters for soundness), Z_i(X) = X^m - z_i the coset
vanishing polynomial and Z_T the product over distinct sampled cosets.
Since L(s) = 0 by construction, the verifier checks

    e(F + s*W', [1]_2) == e(W', [tau]_2),
    F = sum_b (sum_{i in b} gamma_i) C_b - [sum_i gamma_i I_i(s)]G
        - Z_T(s) W

— ONE pairing equation regardless of how many cells the committee
sampled, 96 proof bytes against ~depth*32 per sample for the Merkle
branches. (A naive "ship sum r_i*pi_i" single-aggregate is forgeable —
the prover can decompose any polynomial across the quotient and
remainder; the second challenge point s is what pins every I_i.)

Verifier field work: I_i(s) by coset-barycentric evaluation,
ell_j(s) = (s^m - z) * x_j / (m * z * (s - x_j)), batched host Fr.
The pairing itself dispatches: the numpy backend pins the exact oracle
(``pairings_equal``); the jax backend packs both sides of the equation
into one doubled Miller scan (``ops/pairing.py`` lane packing, the
``fast_aggregate_verify_batch`` trick).
"""

from __future__ import annotations

import hashlib
from functools import lru_cache

import numpy as np

from pos_evolution_tpu.crypto.bls12_381 import (
    R as _R,
)
from pos_evolution_tpu.crypto.bls12_381 import (
    g1_compress,
    g1_decompress,
    pairings_equal,
)
from pos_evolution_tpu.kzg import curve, fr, ntt

__all__ = ["prove", "verify", "PROOF_TAG", "proof_n_bytes"]

PROOF_TAG = b"pevkzgagg1"


# --- Fiat-Shamir --------------------------------------------------------------

def _transcript(n_cells: int, m: int, wire_commitments, claims) -> bytes:
    h = hashlib.sha256()
    h.update(PROOF_TAG)
    h.update(int(n_cells).to_bytes(4, "little"))
    h.update(int(m).to_bytes(4, "little"))
    h.update(len(wire_commitments).to_bytes(4, "little"))
    for wc in wire_commitments:
        h.update(bytes(wc))
    h.update(len(claims).to_bytes(4, "little"))
    for blob, cell, values in claims:
        h.update(int(blob).to_bytes(4, "little"))
        h.update(int(cell).to_bytes(4, "little"))
        for v in values:
            h.update(int(v).to_bytes(32, "little"))
    return h.digest()


def _challenge(t0: bytes, label: bytes, extra: bytes = b"") -> int:
    d = hashlib.sha256(t0 + label + extra).digest()
    return int.from_bytes(d, "little") % _R


def _rs(t0: bytes, n: int) -> list[int]:
    return [_challenge(t0, b"r", i.to_bytes(4, "little"))
            for i in range(n)]


# --- domain / coset helpers (ints) --------------------------------------------

@lru_cache(maxsize=32)
def _coset_geometry(n_cells: int, m: int):
    """(z per cell, coset points per cell) for the N = n_cells*m domain
    with cell i's chunk j sitting at domain index i + n_cells*j."""
    n = n_cells * m
    dom = ntt.domain(n)
    zs = tuple(dom[(c * m) % n] for c in range(n_cells))
    points = tuple(tuple(dom[(c + n_cells * j) % n] for j in range(m))
                   for c in range(n_cells))
    return zs, points


def _interp_coeffs(values, cell: int, n_cells: int, m: int) -> list[int]:
    """Degree-<m coefficients of the polynomial through cell ``cell``'s
    coset evaluations: size-m INTT (values live on w^c * H in chunk
    order) then the X -> X/w^c coordinate twist."""
    b = fr.decode(ntt.ntt_host(fr.encode(values), inverse=True))
    n = n_cells * m
    dom = ntt.domain(n)
    w_c_inv = pow(dom[cell % n], -1, _R)
    out, tw = [], 1
    for t in range(m):
        out.append(b[t] * tw % _R)
        tw = tw * w_c_inv % _R
    return out


def _div_xm_z(p: list[int], z: int, m: int) -> tuple[list[int], list[int]]:
    """(quotient, remainder) of p by X^m - z: the top-down block
    recurrence q_t = p_{t+m} + z * q_{t+m}, O(len(p)) int muls."""
    n = len(p)
    q = [0] * max(n - m, 0)
    for t in range(n - m - 1, -1, -1):
        q[t] = (p[t + m] + (z * q[t + m] if t + m < n - m else 0)) % _R
    rem = [(p[j] + z * q[j]) % _R if j < len(q) else p[j] % _R
           for j in range(min(m, n))]
    return q, rem


def _poly_eval(p, x: int) -> int:
    acc = 0
    for c in reversed(p):
        acc = (acc * x + c) % _R
    return acc


# --- prover -------------------------------------------------------------------

def prove(setup, n_cells: int, m: int, blobs, claims) -> dict:
    """Aggregate opening proof for one committee's sampled cells.

    blobs:  [(wire_commitment bytes32, point affine, coeffs list[int])]
            — one entry per distinct blob polynomial, coeffs length
            N = n_cells * m.
    claims: [(blob_index, cell_id, values tuple[int] len m)].
    Returns {"points": [48B compressed C_b ...], "w": 48B, "wp": 48B}.
    """
    n = n_cells * m
    claims = sorted(((int(b), int(c), tuple(int(v) for v in values))
                     for b, c, values in claims), key=lambda t: t[:2])
    wires = [bytes(wc) for wc, _pt, _cf in blobs]
    t0 = _transcript(n_cells, m, wires, claims)
    rs = _rs(t0, len(claims))
    zs, _pts = _coset_geometry(n_cells, m)

    # h(X) = sum r_i * (f_i - I_i) / Z_i  — honest data divides exactly
    h = [0] * (n - m)
    for (blob, cell, values), r_i in zip(claims, rs):
        coeffs = blobs[blob][2]
        a = _interp_coeffs(values, cell, n_cells, m)
        num = [(coeffs[t] - (a[t] if t < m else 0)) % _R for t in range(n)]
        q, rem = _div_xm_z(num, zs[cell], m)
        assert not any(rem), "claim values do not lie on the polynomial"
        for t in range(n - m):
            h[t] = (h[t] + r_i * q[t]) % _R
    w_point = curve.g1_lincomb(setup.powers_g1[: n - m], h)
    w_comp = g1_compress(w_point)

    s = _challenge(t0, b"s", w_comp)
    zt_s = 1
    for z in sorted({zs[cell] for _b, cell, _v in claims}):
        zt_s = zt_s * (pow(s, m, _R) - z) % _R
    gammas = [r_i * zt_s * pow(pow(s, m, _R) - zs[cell], -1, _R) % _R
              for (_b, cell, _v), r_i in zip(claims, rs)]

    # L(X) = sum gamma_i f_i(X) - [sum gamma_i I_i(s)] - Z_T(s) h(X)
    big_l = [0] * n
    const = 0
    for (blob, cell, values), g in zip(claims, gammas):
        coeffs = blobs[blob][2]
        for t in range(n):
            big_l[t] = (big_l[t] + g * coeffs[t]) % _R
        a = _interp_coeffs(values, cell, n_cells, m)
        const = (const + g * _poly_eval(a, s)) % _R
    big_l[0] = (big_l[0] - const) % _R
    for t in range(n - m):
        big_l[t] = (big_l[t] - zt_s * h[t]) % _R
    assert _poly_eval(big_l, s) == 0, "L(s) must vanish by construction"

    # W' = [L(tau) / (tau - s)]: synthetic division by (X - s)
    wp = [0] * (n - 1)
    carry = 0
    for t in range(n - 2, -1, -1):
        carry = (big_l[t + 1] + s * carry) % _R
        wp[t] = carry
    wp_point = curve.g1_lincomb(setup.powers_g1[: n - 1], wp)

    return {
        "points": [g1_compress(pt) for _wc, pt, _cf in blobs],
        "w": w_comp,
        "wp": g1_compress(wp_point),
    }


def proof_n_bytes(proof: dict) -> int:
    return (sum(len(p) for p in proof["points"])
            + len(proof["w"]) + len(proof["wp"]))


# --- verifier -----------------------------------------------------------------

def _decompress_checked(comp: bytes):
    """48B -> affine point, subgroup-checked on the fast Jacobian path
    (the oracle's affine r-torsion check inverts per step)."""
    p = g1_decompress(bytes(comp))
    if p is not None and curve.jac_mul(p, _R)[2] != 0:
        raise ValueError("point not in the r-torsion subgroup")
    return p


def verify(setup, n_cells: int, m: int, wire_commitments, claims,
           proof: dict, wire_bind) -> bool:
    """Check an aggregate proof. ``wire_commitments``: 32-byte wire
    commitment per blob index; ``claims`` as in :func:`prove`;
    ``wire_bind(compressed_point) -> bytes32`` is the scheme's binding
    hash (the sidecar commitment field is 32 bytes; the proof ships the
    real 48-byte points, bound by hash)."""
    try:
        n = n_cells * m
        claims = sorted(((int(b), int(c), tuple(int(v) % _R for v in values))
                         for b, c, values in claims), key=lambda t: t[:2])
        wires = [bytes(wc) for wc in wire_commitments]
        if len(proof["points"]) != len(wires):
            return False
        points = []
        for comp, wc in zip(proof["points"], wires):
            if wire_bind(bytes(comp)) != wc:
                return False                    # hash binding broken
            points.append(_decompress_checked(comp))
        w_point = _decompress_checked(proof["w"])
        wp_point = _decompress_checked(proof["wp"])

        t0 = _transcript(n_cells, m, wires, claims)
        rs = _rs(t0, len(claims))
        s = _challenge(t0, b"s", bytes(proof["w"]))
        zs, pts = _coset_geometry(n_cells, m)
        s_m = pow(s, m, _R)
        zt_s = 1
        for z in sorted({zs[cell] for _b, cell, _v in claims}):
            zt_s = zt_s * (s_m - z) % _R
        if zt_s == 0:                           # s hit the domain: 2^-224
            return False

        # I_i(s) by coset barycentric + the gamma-weighted commitment fold
        m_inv = pow(m, -1, _R)
        per_blob: dict[int, int] = {}
        const = 0
        for (blob, cell, values), r_i in zip(claims, rs):
            z = zs[cell]
            g = r_i * zt_s % _R * pow(s_m - z, -1, _R) % _R
            per_blob[blob] = (per_blob.get(blob, 0) + g) % _R
            acc = 0
            for x_j, v in zip(pts[cell], values):
                d = (s - x_j) % _R
                if d == 0:
                    return False
                acc = (acc + v * x_j % _R * pow(d, -1, _R)) % _R
            i_s = (s_m - z) * m_inv % _R * pow(z, -1, _R) % _R * acc % _R
            const = (const + g * i_s) % _R

        from pos_evolution_tpu.crypto.bls12_381 import G1_GEN
        f_pts = [points[b] for b in per_blob]
        f_scs = [per_blob[b] for b in per_blob]
        f_pts += [G1_GEN, w_point, wp_point]
        f_scs += [(-const) % _R, (-zt_s) % _R, s]
        lhs = curve.g1_lincomb(f_pts, f_scs)    # F + s*W'
        return _pairing_check(lhs, wp_point, setup)
    except (ValueError, KeyError, IndexError, TypeError):
        return False


def _pairing_check(lhs, wp_point, setup) -> bool:
    """e(lhs, [1]_2) == e(W', [tau]_2), backend-dispatched: oracle
    pairings on numpy, the doubled-Miller-scan lane packing on jax."""
    from pos_evolution_tpu.backend import get_backend
    if getattr(get_backend(), "name", "numpy") == "jax":
        try:
            return bool(_pairing_check_device(lhs, wp_point, setup))
        except Exception:   # pragma: no cover - broken jax degrades
            pass
    return pairings_equal([(lhs, setup.g2_one)], [(wp_point, setup.g2_tau)])


@lru_cache(maxsize=1)
def _device_pairing_kernel():
    import jax
    import jax.numpy as jnp

    from pos_evolution_tpu.ops.pairing import (
        final_exponentiation,
        g2_neg,
        miller_loop,
    )
    from pos_evolution_tpu.ops.tower import alg_eq, alg_one, fq12_mul

    def kernel(g1s, g2s, infs):
        # both pairing sides ride ONE 63-step Miller scan (lane packing,
        # the fast_aggregate_verify_batch trick), then a product + one
        # final exponentiation decides the equation
        fs = miller_loop(g1s, jnp.concatenate(
            [g2s[:1], g2_neg(g2s[1:])], axis=0), infs)
        f = fq12_mul(fs[:1], fs[1:])
        return alg_eq(final_exponentiation(f), alg_one(12, f.shape[:-2]))

    return jax.jit(kernel)


def _pairing_check_device(lhs, wp_point, setup) -> bool:
    import jax.numpy as jnp

    from pos_evolution_tpu.ops.pairing import (
        g1_affine_encode,
        g2_affine_encode,
    )
    g1s = jnp.asarray(np.stack([g1_affine_encode(lhs),
                                g1_affine_encode(wp_point)]))
    g2s = jnp.asarray(np.stack([g2_affine_encode(setup.g2_one),
                                g2_affine_encode(setup.g2_tau)]))
    infs = jnp.asarray(np.array([lhs is None, wp_point is None]))
    return bool(np.asarray(_device_pairing_kernel()(g1s, g2s, infs))[0])
