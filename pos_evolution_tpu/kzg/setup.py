"""Deterministic insecure-but-sound trusted setup (powers of tau).

A real KZG deployment gets its structured reference string from a
multi-party ceremony precisely so that NOBODY knows tau. A simulator
has the opposite need: every node (and every resumed checkpoint) must
regenerate the identical setup from the chain config alone. So tau is
derived from a public seed — **insecure** (anyone can forge openings if
they bother to read this file) but **sound** in the cryptographic
sense: the commitment scheme's binding argument only needs the SRS to
be well-formed powers [tau^j]G, which this is. DESIGN.md §23 spells out
why that is the honest posture for a reproduction.

Group elements come from the existing oracle (``crypto/bls12_381.py``
generators + encodings); the per-power scalar muls run on the
inversion-free Jacobian path (``kzg/curve.py``) with one batch
normalization, so a fresh 128-power setup is milliseconds, not minutes.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from functools import lru_cache

import numpy as np

from pos_evolution_tpu.crypto.bls12_381 import (
    G1_GEN,
    G2_GEN,
    R,
    ec_mul,
    g1_compress,
)
from pos_evolution_tpu.kzg import curve

__all__ = ["TrustedSetup", "trusted_setup", "tau_from_seed"]


def tau_from_seed(seed: int) -> int:
    """The toxic waste, in the open: tau = H("pos-evo-kzg-tau" || seed)
    reduced mod r (nonzero by construction for every practical seed)."""
    d = hashlib.sha256(b"pos-evo-kzg-tau" + int(seed).to_bytes(8, "little"))
    tau = int.from_bytes(d.digest(), "little") % R
    return tau if tau > 1 else tau + 2


@dataclass(frozen=True)
class TrustedSetup:
    """Powers of tau: [tau^j]G1 for j < n, plus [1]G2 and [tau]G2 (the
    only G2 elements the two-element multiproof check needs)."""

    n: int
    seed: int
    powers_g1: tuple            # n affine G1 points (ints)
    g2_one: tuple               # G2 affine (Fq2 pair)
    g2_tau: tuple

    @property
    def powers_g1_compressed(self) -> tuple:
        return tuple(g1_compress(p) for p in self.powers_g1)

    def device_encoding(self):
        """[n, 2, 32] int32 limb array + [n] inf mask for the device
        MSM kernel (ops/pairing.py encodings), memoized."""
        enc = _device_encoding(self.n, self.seed)
        return enc


@lru_cache(maxsize=8)
def trusted_setup(n: int, seed: int = 0) -> TrustedSetup:
    """The (n, seed)-keyed setup, memoized per process: ROADMAP's
    config3b lesson — never regenerate an identical SRS twice."""
    tau = tau_from_seed(seed)
    jac = []
    t = 1
    for _ in range(n):
        jac.append(curve.jac_mul(curve.to_jac(G1_GEN), t))
        t = t * tau % R
    powers = tuple(curve.batch_to_affine(jac))
    g2_tau = ec_mul(G2_GEN, tau)
    return TrustedSetup(n=n, seed=int(seed), powers_g1=powers,
                        g2_one=G2_GEN, g2_tau=g2_tau)


@lru_cache(maxsize=8)
def _device_encoding(n: int, seed: int):
    from pos_evolution_tpu.ops.pairing import g1_affine_encode
    setup = trusted_setup(n, seed)
    enc = np.stack([g1_affine_encode(p) for p in setup.powers_g1])
    inf = np.array([p is None for p in setup.powers_g1], dtype=bool)
    return enc, inf
