"""ExecutionBackend dispatch (layer LB of SURVEY.md §1).

Every validator-set hot loop — the swap-or-not shuffle over the registry
(north-star config #2), epoch sweeps (#4), fork-choice weight accumulation
(#1), attestation aggregation (#3) — is callable on a ``numpy`` backend
(pure NumPy reference oracle) or a ``jax`` backend (XLA/Pallas on TPU) with
identical signatures. Spec-level functions keep their reference signatures
and dispatch through ``get_backend()``.
"""

from __future__ import annotations

import threading

_local = threading.local()

_BACKENDS = {}


def register_backend(name: str, module) -> None:
    _BACKENDS[name] = module


def get_backend():
    b = getattr(_local, "backend", None)
    if b is None:
        b = _load("numpy")
        _local.backend = b
    return b


def set_backend(name: str):
    _local.backend = _load(name)
    return _local.backend


def _load(name: str):
    if name not in _BACKENDS:
        if name == "numpy":
            from pos_evolution_tpu.backend import numpy_backend
            _BACKENDS[name] = numpy_backend
        elif name == "jax":
            from pos_evolution_tpu.backend import jax_backend
            _BACKENDS[name] = jax_backend
        else:
            raise ValueError(f"unknown ExecutionBackend {name!r}")
    return _BACKENDS[name]
