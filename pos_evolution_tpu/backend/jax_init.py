"""One place that touches process-global JAX configuration.

Every device kernel in this repo depends on exact int64 Gwei/epoch
arithmetic (``jax_enable_x64``); historically each op module flipped the
flag at *import* time, so merely importing ``ops/sha256.py`` mutated the
process for every other jax user in it. ``ensure_x64`` is the
consolidated, idempotent entry point: op modules call it lazily — on
first kernel *use*, never at import — and modules that are jax-only by
contract may call it at the top of their device builders.
"""

from __future__ import annotations

_X64_DONE = False


def ensure_x64() -> None:
    """Enable 64-bit jax types, once per process. Safe to call from
    inside traced code (idempotent, guarded) and cheap after the first
    call."""
    global _X64_DONE
    if _X64_DONE:
        return
    import jax

    # read-before-write: when another module (or a previous call) already
    # enabled it, touching the config again — possibly from inside a
    # trace — is pure risk with zero effect
    if not jax.config.jax_enable_x64:
        jax.config.update("jax_enable_x64", True)
    _X64_DONE = True
