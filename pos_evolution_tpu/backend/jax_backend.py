"""JAX/XLA ExecutionBackend: the TPU compute path behind the spec layer.

Same interface as ``numpy_backend`` — spec-level functions dispatch here
when ``set_backend("jax")`` is active. The hot kernels live in ``ops/``;
this module adapts them to the backend API and flags the accelerated
epoch path (``specs/epoch.process_epoch`` then runs the fused device sweep
with exact host write-back).
"""

from __future__ import annotations

import numpy as np

name = "jax"
accelerated_epoch = True

# --- sharded mode (ISSUE 9 tentpole) ------------------------------------------
#
# A process-global (pods x shard) device mesh. When set, the validator-axis
# sweeps this backend serves — the epoch sweep, the variant vote/link
# tallies, and (via ops/resident.py reading ``sharded_mesh()``) the
# fork-choice vote pass and the fused-transition session columns — run as
# ``shard_map`` kernels over it, with registry columns placed sharded per
# the partition rules in ``parallel/partition.py`` and allreduces ICI-first
# / DCN-second (``parallel/collectives.py`` axis roles). Everything stays
# bit-identical to the single-device kernels (int64 psum reassociates
# exactly); tests/test_sharded_e2e.py pins it across mesh shapes.

_SHARDED = {"mesh": None, "shard_transition": True}


def enable_sharded(n_devices: int | None = None, n_pods: int | None = None,
                   mesh=None, shard_transition: bool = True):
    """Activate sharded dispatch on this backend. ``mesh`` or a
    ``(n_devices, n_pods)`` shape; returns the mesh. ``shard_transition``
    also places the fused block-sweep session columns sharded (see
    ``ops/transition.py`` for when that pays)."""
    if mesh is None:
        from pos_evolution_tpu.parallel.sharded import make_mesh
        mesh = make_mesh(n_devices, n_pods)
    _SHARDED["mesh"] = mesh
    _SHARDED["shard_transition"] = bool(shard_transition)
    from pos_evolution_tpu.ops.transition import reset_session
    reset_session()  # carries placed under the previous layout are stale
    return mesh


def disable_sharded() -> None:
    _SHARDED["mesh"] = None
    from pos_evolution_tpu.ops.transition import reset_session
    reset_session()


def sharded_mesh():
    """The active mesh, or None (single-device dispatch)."""
    return _SHARDED["mesh"]


def shard_transition_enabled() -> bool:
    return _SHARDED["mesh"] is not None and _SHARDED["shard_transition"]


def _next_pow2(x: int) -> int:
    from pos_evolution_tpu.ops.variant_tally import next_pow2
    return next_pow2(x)


def shuffle_permutation(seed: bytes, n: int, rounds: int) -> np.ndarray:
    from pos_evolution_tpu.ops.shuffle import shuffle_permutation_jax
    return np.asarray(shuffle_permutation_jax(seed, n, rounds)).astype(np.uint64)


def committee_weight_sums(effective_balance: np.ndarray, masks: np.ndarray) -> np.ndarray:
    import jax.numpy as jnp
    return np.asarray(
        jnp.asarray(masks, dtype=jnp.int64) @ jnp.asarray(effective_balance))


def segment_sum(values: np.ndarray, segment_ids: np.ndarray,
                num_segments: int) -> np.ndarray:
    import jax
    import jax.numpy as jnp
    return np.asarray(jax.ops.segment_sum(
        jnp.asarray(values), jnp.asarray(segment_ids), num_segments=num_segments))


def sync_update_verify(batch):
    """Light-client update batch verification on device: the attestation
    aggregation kernel over committee-lane pk states + the vectorized
    merkle walk (bit-identical to numpy_backend.sync_update_verify)."""
    from pos_evolution_tpu.ops.sync_verify import verify_batch_device
    return verify_batch_device(batch)


def das_verify(batch):
    """Batched DAS sample verification on device: one SHA-256 lane per
    sampled cell + the jitted scan merkle walk (bit-identical to
    numpy_backend.das_verify). Small batches stay on the host path —
    the fixed device-dispatch overhead only amortizes past the merkle
    crossover (``Config.merkle_device_min_pairs``), and the verdicts are
    bit-identical either way."""
    from pos_evolution_tpu.ops import merkle_device
    from pos_evolution_tpu.ops.das_verify import (
        verify_samples_device,
        verify_samples_host,
    )
    mode = merkle_device.get_mode()
    # one sample ≈ 16 pair-equivalents of SHA-256 work (cell-hash blocks
    # + the branch walk), so the pair-denominated crossover divides down
    floor = merkle_device.small_batch_floor(per_item_pairs=16)
    if mode == "host" or (mode == "auto" and batch.size < floor):
        return verify_samples_host(batch)
    return verify_samples_device(batch)


def merkle_level(left: np.ndarray, right: np.ndarray) -> np.ndarray:
    """One merkle level sweep on device: the batched SHA-256 kernel with
    the Pallas -> XLA -> NumPy fallback ladder (bit-identical to
    numpy_backend.merkle_level)."""
    from pos_evolution_tpu.ops.merkle_device import merkle_level_device
    return merkle_level_device(left, right)


def merkleize(chunks: np.ndarray, limit: int | None = None) -> bytes:
    """Whole-tree merkleization (bit-identical to
    numpy_backend.merkleize). Convenience front: the real per-level seam
    is ``merkle_level`` — ops/merkle_device.merkleize dispatches each
    sweep back through it when the batch is device-eligible."""
    from pos_evolution_tpu.ops.merkle_device import merkleize as _m
    return _m(chunks, limit)


def build_multiproof_paths(leaves: np.ndarray, indices, depth: int):
    """Shared-tree proof-branch extraction: one tree build through the
    dispatch layer (device sweeps when eligible), then vectorized
    sibling gathers on the host copies (bit-identical to
    numpy_backend.build_multiproof_paths, which pins host)."""
    from pos_evolution_tpu.ops.merkle_device import build_multiproof_paths
    return build_multiproof_paths(leaves, indices, depth)


def das_reconstruct(cells: np.ndarray, present: np.ndarray):
    """Erasure-reconstruction consistency check as jitted GF(2^8)
    gather/XOR matmuls (bit-identical to numpy_backend.das_reconstruct)."""
    from pos_evolution_tpu.ops.das_verify import reconstruct_check_device
    return reconstruct_check_device(cells, present)


def variant_tally(block_idx, vote_slot, weight, active, lo_slot, hi_slot,
                  n_blocks):
    """Expiry-windowed vote tally as one jitted masked segment_sum
    (bit-identical to numpy_backend.variant_tally). Under the sharded
    mode the vote batch shards over the validator mesh axes and the
    per-block partials allreduce ICI-first / DCN-second."""
    mesh = sharded_mesh()
    if mesh is None:
        from pos_evolution_tpu.ops.variant_tally import (
            windowed_vote_tally_device,
        )
        return windowed_vote_tally_device(block_idx, vote_slot, weight,
                                          active, lo_slot, hi_slot, n_blocks)
    import jax.numpy as jnp

    from pos_evolution_tpu.parallel.sharded import (
        pad_batch_to_mesh,
        windowed_tally_for,
    )
    nb = _next_pow2(n_blocks)
    (bi, vs, w, ac), _k = pad_batch_to_mesh(
        mesh,
        (np.asarray(block_idx, np.int64), np.asarray(vote_slot, np.int64),
         np.asarray(weight, np.int64), np.asarray(active, bool)),
        fills=(-1, 0, 0, False))
    res = windowed_tally_for(mesh, nb)(
        bi, vs, w, ac, jnp.int64(lo_slot), jnp.int64(hi_slot))
    return np.asarray(res)[:n_blocks]


def link_tally(link_idx, weight, active, n_links):
    """SSF supermajority-link / acknowledgment tally on device
    (bit-identical to numpy_backend.link_tally). Under the sharded mode
    this is the live ``SsfVariant`` fold of the multichip dry run: the
    vote batch shards over (pods, shard) and the per-link stake partials
    reduce over ICI then DCN (north-star config #5)."""
    mesh = sharded_mesh()
    if mesh is None:
        from pos_evolution_tpu.ops.variant_tally import link_tally_device
        return link_tally_device(link_idx, weight, active, n_links)
    from pos_evolution_tpu.parallel.sharded import (
        link_tally_for,
        pad_batch_to_mesh,
    )
    nl = _next_pow2(n_links)
    (li, w, ac), _k = pad_batch_to_mesh(
        mesh,
        (np.asarray(link_idx, np.int64), np.asarray(weight, np.int64),
         np.asarray(active, bool)),
        fills=(-1, 0, False))
    return np.asarray(link_tally_for(mesh, nl)(li, w, ac))[:n_links]


def fr_ntt(values: np.ndarray, inverse: bool = False) -> np.ndarray:
    """Batched Fr NTT/INTT on device: the jitted Cooley-Tukey kernel
    over int32 Montgomery limbs, with a loud-once host fallback
    (bit-identical to numpy_backend.fr_ntt)."""
    from pos_evolution_tpu.kzg.ntt import fr_ntt_device_entry
    return fr_ntt_device_entry(values, inverse)


def g1_msm(points, scalars):
    """G1 multi-scalar multiply on device (kzg/scheme.py commit path):
    per-lane double-and-add scans over int32 limb vectors + a Jacobian
    lane tree (ops/pairing.g1_msm_device), bit-identical to the host
    Pippenger MSM (kzg/curve.py)."""
    from pos_evolution_tpu.ops.pairing import g1_msm_device_entry
    return g1_msm_device_entry(points, scalars)


def subtree_weights(parent: np.ndarray, node_weight: np.ndarray) -> np.ndarray:
    """Same contract as numpy_backend.subtree_weights (parent[i] < i)."""
    w = node_weight.astype(np.int64).copy()
    for i in range(len(w) - 1, 0, -1):
        p = parent[i]
        if p >= 0:
            w[p] += w[i]
    return w


def epoch_sweep(state, cfg, dense=None):
    """Run the fused device epoch sweep for a spec-level BeaconState.

    ``dense`` lets the caller stage the registry once and reuse it for the
    churn kernel in the same boundary. Returns the EpochResult; the caller
    (specs/epoch.py) performs the exact host write-back and the O(changes)
    bookkeeping.
    """
    import jax.numpy as jnp

    from pos_evolution_tpu.ops.epoch import densify, process_epoch_dense
    from pos_evolution_tpu.specs.helpers import get_current_epoch

    mesh = sharded_mesh()
    if mesh is not None:
        return _epoch_sweep_sharded(state, cfg, mesh)
    if dense is None:
        dense = densify(state)
    return process_epoch_dense(
        dense,
        get_current_epoch(state),
        int(state.finalized_checkpoint.epoch),
        jnp.asarray(np.asarray(state.justification_bits, dtype=bool)),
        int(state.previous_justified_checkpoint.epoch),
        int(state.current_justified_checkpoint.epoch),
        int(state.slashings.sum()),
        cfg,
    )


def _epoch_sweep_sharded(state, cfg, mesh):
    """Sharded epoch boundary (north-star config #4 live): registry
    columns are placed sharded over (pods, shard) via per-shard slice
    callbacks — padded with inert rows to mesh divisibility — and the
    fused sweep runs as one ``shard_map`` with every registry-wide tally
    allreduced ICI-first / DCN-second. Output registry columns are
    sliced back to the real row count, so the caller's host write-back
    (specs/epoch.py) is unchanged. The churn kernel keeps its own
    single-device staging (an O(N log N) sort, once per epoch), so the
    caller's ``dense`` is deliberately not reused here: re-extracting the
    host columns (``densify_np``) costs one host pass, while gathering
    the staged device copy back would cost a full d2h transfer — and the
    churn contract needs the *unpadded* single-device staging anyway."""
    import jax
    import jax.numpy as jnp

    from pos_evolution_tpu.ops.epoch import DenseRegistry, densify_sharded
    from pos_evolution_tpu.parallel.sharded import epoch_step_for
    from pos_evolution_tpu.specs.helpers import get_current_epoch

    reg_s, n = densify_sharded(state, mesh)
    step = epoch_step_for(mesh, cfg,
                          donate=jax.default_backend() != "cpu")
    out = step(
        reg_s,
        jnp.int64(get_current_epoch(state)),
        jnp.int64(int(state.finalized_checkpoint.epoch)),
        jnp.asarray(np.asarray(state.justification_bits, dtype=bool)),
        jnp.int64(int(state.previous_justified_checkpoint.epoch)),
        jnp.int64(int(state.current_justified_checkpoint.epoch)),
        jnp.int64(int(state.slashings.sum())),
    )
    if int(out.registry.balance.shape[0]) != n:
        out = out._replace(registry=DenseRegistry(
            *(a[:n] for a in out.registry)))
    return out



def block_sweep(state, rows) -> None:
    """Fused per-block attestation application on device: one jitted scan
    over the block's attestation batch with the swept columns kept
    device-resident across consecutive blocks (bit-identical to
    numpy_backend.block_sweep)."""
    from pos_evolution_tpu.ops.transition import apply_attestation_rows_device
    apply_attestation_rows_device(state, rows)


def multi_block_apply(state, signed_blocks, validate_result=True,
                      pre_block=None, on_applied=None) -> None:
    """Batched multi-block apply: same carried-state loop as the host
    path, but each block's attestation batch runs the jitted fused sweep
    and consecutive blocks reuse its device-resident carry (bit-identical
    to numpy_backend.multi_block_apply)."""
    from pos_evolution_tpu.ops.transition import apply_block_chain
    apply_block_chain(state, signed_blocks, validate_result,
                      pre_block=pre_block, on_applied=on_applied)
