"""JAX/XLA ExecutionBackend: the TPU compute path behind the spec layer.

Same interface as ``numpy_backend`` — spec-level functions dispatch here
when ``set_backend("jax")`` is active. The hot kernels live in ``ops/``;
this module adapts them to the backend API and flags the accelerated
epoch path (``specs/epoch.process_epoch`` then runs the fused device sweep
with exact host write-back).
"""

from __future__ import annotations

import numpy as np

name = "jax"
accelerated_epoch = True


def shuffle_permutation(seed: bytes, n: int, rounds: int) -> np.ndarray:
    from pos_evolution_tpu.ops.shuffle import shuffle_permutation_jax
    return np.asarray(shuffle_permutation_jax(seed, n, rounds)).astype(np.uint64)


def committee_weight_sums(effective_balance: np.ndarray, masks: np.ndarray) -> np.ndarray:
    import jax.numpy as jnp
    return np.asarray(
        jnp.asarray(masks, dtype=jnp.int64) @ jnp.asarray(effective_balance))


def segment_sum(values: np.ndarray, segment_ids: np.ndarray,
                num_segments: int) -> np.ndarray:
    import jax
    import jax.numpy as jnp
    return np.asarray(jax.ops.segment_sum(
        jnp.asarray(values), jnp.asarray(segment_ids), num_segments=num_segments))


def sync_update_verify(batch):
    """Light-client update batch verification on device: the attestation
    aggregation kernel over committee-lane pk states + the vectorized
    merkle walk (bit-identical to numpy_backend.sync_update_verify)."""
    from pos_evolution_tpu.ops.sync_verify import verify_batch_device
    return verify_batch_device(batch)


def das_verify(batch):
    """Batched DAS sample verification on device: one SHA-256 lane per
    sampled cell + the jitted scan merkle walk (bit-identical to
    numpy_backend.das_verify)."""
    from pos_evolution_tpu.ops.das_verify import verify_samples_device
    return verify_samples_device(batch)


def das_reconstruct(cells: np.ndarray, present: np.ndarray):
    """Erasure-reconstruction consistency check as jitted GF(2^8)
    gather/XOR matmuls (bit-identical to numpy_backend.das_reconstruct)."""
    from pos_evolution_tpu.ops.das_verify import reconstruct_check_device
    return reconstruct_check_device(cells, present)


def variant_tally(block_idx, vote_slot, weight, active, lo_slot, hi_slot,
                  n_blocks):
    """Expiry-windowed vote tally as one jitted masked segment_sum
    (bit-identical to numpy_backend.variant_tally)."""
    from pos_evolution_tpu.ops.variant_tally import windowed_vote_tally_device
    return windowed_vote_tally_device(block_idx, vote_slot, weight, active,
                                      lo_slot, hi_slot, n_blocks)


def link_tally(link_idx, weight, active, n_links):
    """SSF supermajority-link / acknowledgment tally on device
    (bit-identical to numpy_backend.link_tally)."""
    from pos_evolution_tpu.ops.variant_tally import link_tally_device
    return link_tally_device(link_idx, weight, active, n_links)


def subtree_weights(parent: np.ndarray, node_weight: np.ndarray) -> np.ndarray:
    """Same contract as numpy_backend.subtree_weights (parent[i] < i)."""
    w = node_weight.astype(np.int64).copy()
    for i in range(len(w) - 1, 0, -1):
        p = parent[i]
        if p >= 0:
            w[p] += w[i]
    return w


def epoch_sweep(state, cfg, dense=None):
    """Run the fused device epoch sweep for a spec-level BeaconState.

    ``dense`` lets the caller stage the registry once and reuse it for the
    churn kernel in the same boundary. Returns the EpochResult; the caller
    (specs/epoch.py) performs the exact host write-back and the O(changes)
    bookkeeping.
    """
    import jax.numpy as jnp

    from pos_evolution_tpu.ops.epoch import densify, process_epoch_dense
    from pos_evolution_tpu.specs.helpers import get_current_epoch

    if dense is None:
        dense = densify(state)
    return process_epoch_dense(
        dense,
        get_current_epoch(state),
        int(state.finalized_checkpoint.epoch),
        jnp.asarray(np.asarray(state.justification_bits, dtype=bool)),
        int(state.previous_justified_checkpoint.epoch),
        int(state.current_justified_checkpoint.epoch),
        int(state.slashings.sum()),
        cfg,
    )



def block_sweep(state, rows) -> None:
    """Fused per-block attestation application on device: one jitted scan
    over the block's attestation batch with the swept columns kept
    device-resident across consecutive blocks (bit-identical to
    numpy_backend.block_sweep)."""
    from pos_evolution_tpu.ops.transition import apply_attestation_rows_device
    apply_attestation_rows_device(state, rows)


def multi_block_apply(state, signed_blocks, validate_result=True,
                      pre_block=None, on_applied=None) -> None:
    """Batched multi-block apply: same carried-state loop as the host
    path, but each block's attestation batch runs the jitted fused sweep
    and consecutive blocks reuse its device-resident carry (bit-identical
    to numpy_backend.multi_block_apply)."""
    from pos_evolution_tpu.ops.transition import apply_block_chain
    apply_block_chain(state, signed_blocks, validate_result,
                      pre_block=pre_block, on_applied=on_applied)
