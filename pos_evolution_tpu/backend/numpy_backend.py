"""Pure-NumPy ExecutionBackend: the reference oracle for the JAX/TPU backend.

Implements the hot kernels of SURVEY.md §7 step 3 with vectorized NumPy.
Differential tests assert bit-identical outputs against the ``jax`` backend.
"""

from __future__ import annotations

import numpy as np

from pos_evolution_tpu.ssz.hash import hash_eth2, sha256_batch, sha256_pairs

name = "numpy"


def shuffle_permutation(seed: bytes, n: int, rounds: int) -> np.ndarray:
    """Vectorized swap-or-not shuffle of all ``n`` indices at once.

    Returns ``p`` with ``p[i] == compute_shuffled_index(i, n, seed)``
    (pos-evolution.md:513-535). Instead of the reference's per-index loop
    (O(rounds) hashes *per validator*), each round hashes the pivot plus
    ceil(n/256) position blocks once and applies the flip decision to every
    index in parallel — O(rounds * n/256) hashes for the whole registry.
    """
    if n == 0:
        return np.zeros(0, dtype=np.uint64)
    idx = np.arange(n, dtype=np.int64)
    n_blocks = (n + 255) // 256
    # Per-round position-block hash inputs: seed(32) | round(1) | block(4)
    msgs = np.zeros((n_blocks, 37), dtype=np.uint8)
    msgs[:, :32] = np.frombuffer(seed, dtype=np.uint8)
    blocks_le = np.arange(n_blocks, dtype="<u4").view(np.uint8).reshape(n_blocks, 4)
    msgs[:, 33:37] = blocks_le
    for r in range(rounds):
        pivot = int.from_bytes(hash_eth2(seed + bytes([r]))[:8], "little") % n
        flip = (pivot - idx) % n
        pos = np.maximum(idx, flip)
        msgs[:, 32] = r
        digests = sha256_batch(msgs)  # (n_blocks, 32)
        byte = digests[pos >> 8, (pos & 0xFF) >> 3]
        bit = (byte >> (pos & 0x07).astype(np.uint8)) & 1
        idx = np.where(bit.astype(bool), flip, idx)
    return idx.astype(np.uint64)


def committee_weight_sums(effective_balance: np.ndarray, masks: np.ndarray) -> np.ndarray:
    """Sum effective balances under each of a batch of boolean masks."""
    return masks.astype(np.uint64) @ effective_balance


def segment_sum(values: np.ndarray, segment_ids: np.ndarray, num_segments: int) -> np.ndarray:
    """Reference segmented reduction (fork-choice weights, SURVEY.md §2.8)."""
    out = np.zeros(num_segments, dtype=values.dtype)
    np.add.at(out, segment_ids, values)
    return out


def sync_update_verify(batch):
    """Light-client update batch verification (ops/sync_verify.py contract):
    hashlib FakeBLS aggregate checks + NumPy merkle walks."""
    from pos_evolution_tpu.ops.sync_verify import verify_batch_host
    return verify_batch_host(batch)


def das_verify(batch):
    """Batched DAS sample verification (ops/das_verify.py contract):
    hashlib/NumPy leaf hashing + vectorized merkle walks."""
    from pos_evolution_tpu.ops.das_verify import verify_samples_host
    return verify_samples_host(batch)


def das_reconstruct(cells: np.ndarray, present: np.ndarray):
    """Erasure-reconstruction consistency check (any >=50% of cells)."""
    from pos_evolution_tpu.ops.das_verify import reconstruct_check_host
    return reconstruct_check_host(cells, present)


def variant_tally(block_idx, vote_slot, weight, active, lo_slot, hi_slot,
                  n_blocks):
    """Expiry-windowed, equivocation-discounted vote tally
    (ops/variant_tally.py contract; variants/ hot loop)."""
    from pos_evolution_tpu.ops.variant_tally import windowed_vote_tally_host
    return windowed_vote_tally_host(block_idx, vote_slot, weight, active,
                                    lo_slot, hi_slot, n_blocks)


def link_tally(link_idx, weight, active, n_links):
    """SSF supermajority-link / acknowledgment tally
    (ops/variant_tally.py contract)."""
    from pos_evolution_tpu.ops.variant_tally import link_tally_host
    return link_tally_host(link_idx, weight, active, n_links)


def merkle_level(left: np.ndarray, right: np.ndarray) -> np.ndarray:
    """One merkle level sweep: sha256(left[i] || right[i]) over (N, 32)
    u8 rows (ops/merkle_device.py contract). The host kernel — native
    C++ core when built, vectorized NumPy lanes otherwise."""
    return sha256_pairs(np.ascontiguousarray(left),
                        np.ascontiguousarray(right))


def merkleize(chunks: np.ndarray, limit: int | None = None) -> bytes:
    """Whole-tree merkleization (SSZ padding rules) on the host path."""
    from pos_evolution_tpu.ssz.merkle import merkleize_chunks
    return merkleize_chunks(chunks, limit)


def build_multiproof_paths(leaves: np.ndarray, indices, depth: int):
    """Shared-tree proof-branch extraction (ops/merkle_device.py
    contract), PINNED to host sweeps — this backend is the reference
    oracle, so it must not pick up the thread's device dispatch state."""
    from pos_evolution_tpu.ops.merkle_device import (
        build_multiproof_paths_host,
    )
    return build_multiproof_paths_host(leaves, indices, depth)


def fr_ntt(values: np.ndarray, inverse: bool = False) -> np.ndarray:
    """Batched Fr NTT/INTT (kzg/ntt.py contract), PINNED to the host
    NumPy twin — this backend is the reference oracle."""
    from pos_evolution_tpu.kzg.ntt import fr_ntt_host_entry
    return fr_ntt_host_entry(values, inverse)


def subtree_weights(parent: np.ndarray, node_weight: np.ndarray) -> np.ndarray:
    """Accumulate each node's weight into all ancestors.

    ``parent[i] < i`` for every non-root node (blocks arrive in topological
    order), so one reverse sweep suffices — the array-level form of
    ``get_latest_attesting_balance`` over every branch at once
    (pos-evolution.md:1102-1116).
    """
    w = node_weight.astype(np.int64).copy()
    for i in range(len(w) - 1, 0, -1):
        p = parent[i]
        if p >= 0:
            w[p] += w[i]
    return w


def block_sweep(state, rows) -> None:
    """Fused per-block attestation application (ops/transition.py contract):
    the NumPy oracle sweep with per-block constants hoisted."""
    from pos_evolution_tpu.ops.transition import apply_attestation_rows_host
    apply_attestation_rows_host(state, rows)


def multi_block_apply(state, signed_blocks, validate_result=True,
                      pre_block=None, on_applied=None) -> None:
    """Batched multi-block apply (backfill/checkpoint-sync): the host loop
    over spec ``state_transition`` with one carried state object."""
    from pos_evolution_tpu.ops.transition import apply_block_chain
    apply_block_chain(state, signed_blocks, validate_result,
                      pre_block=pre_block, on_applied=on_applied)
