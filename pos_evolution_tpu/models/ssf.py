"""Single-slot finality protocol (L7; pos-evolution.md:1611-1650).

RLMD-GHOST with fast confirmation (4Δ slots: propose -> head-vote ->
FFG-vote/fast-confirm -> merge, :1617, :1631-1637) plus a per-slot FFG
gadget:

- checkpoints are (block, slot) pairs; FFG votes link source -> target
  where source = the voter's latest justified checkpoint LJ and target =
  the highest fast-confirmed descendant of LJ (or LJ itself) at the
  current slot (:1624-1629);
- a checkpoint justifies when 2/3 of validators cast the same link in a
  slot (supermajority link, :1626);
- finalization: a justified C with a supermajority link C -> C' at
  C'.t = C.t + 1 finalizes C (:1626); additionally validators *acknowledge*
  a just-justified checkpoint, and 2/3 acknowledgments finalize it within
  its own slot (:1646) — true single-slot finality;
- slashing: an acknowledgment ((C, t), t) conflicts with any FFG vote
  (A, t') -> (B, t'') with t' < t < t'' (surround-the-ack, :1646).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from pos_evolution_tpu.models.pvm import GENESIS_ROOT, ghost_head
from pos_evolution_tpu.models.protocols import PVMAdversary, PVMParams, PVMSimulation


@dataclass(frozen=True)
class SSFCheckpoint:
    block: bytes
    slot: int


@dataclass(frozen=True)
class FFGVote:
    """[FFG-VOTE, C1, C2, v] with C1.t < C2.t (pos-evolution.md:1624)."""

    source: SSFCheckpoint
    target: SSFCheckpoint
    validator: int


@dataclass(frozen=True)
class Acknowledgment:
    """((B, t), t): the voter saw (B, t) justified at slot t (:1646)."""

    checkpoint: SSFCheckpoint
    slot: int
    validator: int


def is_ack_slashable(ack: Acknowledgment, vote: FFGVote) -> bool:
    """Surround-the-ack condition (pos-evolution.md:1646): slashable iff the
    FFG vote's span strictly surrounds the acknowledged slot."""
    return (ack.validator == vote.validator
            and vote.source.slot < ack.slot < vote.target.slot)


class SSFSimulation(PVMSimulation):
    """SSF = RLMD-GHOST (4Δ, fast confirm) + per-slot FFG + acknowledgments."""

    def __init__(self, n_validators: int, eta: int = 4,
                 adversary: PVMAdversary | None = None):
        params = PVMParams(n_validators=n_validators, vote_expiry=eta,
                           fast_confirm=True)
        super().__init__(params, adversary)
        genesis_cp = SSFCheckpoint(block=GENESIS_ROOT, slot=0)
        self.latest_justified: dict[int, SSFCheckpoint] = {
            v: genesis_cp for v in range(n_validators)}
        self.justified: set[SSFCheckpoint] = {genesis_cp}
        self.finalized: set[SSFCheckpoint] = {genesis_cp}
        self.ffg_votes: list[FFGVote] = []
        self.acks: list[Acknowledgment] = []

    # -- fork choice with LJ filtering (pos-evolution.md:1628) -------------
    def head_for(self, val, slot: int) -> bytes:
        head = ghost_head(val.view, slot, self.p.vote_expiry)
        lj = self.latest_justified[val.index]
        if lj.block in val.view.blocks and not val.view.is_ancestor(lj.block, head):
            # branches not containing LJ are filtered; fall back to LJ
            return lj.block
        return head

    def _supermajority(self, count: int) -> bool:
        return 3 * count >= 2 * self.p.n_validators

    def run_slot(self) -> None:
        t = self.slot
        super().run_slot()  # propose, head-vote, fast-confirm, merge

        # --- FFG voting round (3/4 into the slot, :1631-1637) ---
        awake = [v.index for v in self.validators
                 if self.validators[v.index].status == "awake"
                 and not self.adv.asleep(t, v.index)]
        links: dict[tuple[SSFCheckpoint, SSFCheckpoint], set[int]] = {}
        for v in awake:
            val = self.validators[v]
            source = self.latest_justified[v]
            fast = self.fast_confirmed.get(v)
            if (fast is not None and fast in val.view.blocks
                    and val.view.is_ancestor(source.block, fast)):
                target_block = fast
            else:
                target_block = source.block
            target = SSFCheckpoint(block=target_block, slot=t)
            vote = FFGVote(source=source, target=target, validator=v)
            self.ffg_votes.append(vote)
            links.setdefault((source, target), set()).add(v)

        # --- justification on supermajority links (:1626) ---
        newly_justified: list[SSFCheckpoint] = []
        for (source, target), voters in links.items():
            if source in self.justified and self._supermajority(len(voters)):
                if target not in self.justified:
                    self.justified.add(target)
                    newly_justified.append(target)
                # C -> C' with consecutive slots finalizes C (:1626)
                if target.slot == source.slot + 1:
                    self.finalized.add(source)

        # update everyone's LJ (synchrony: justification gossiped in-slot)
        for cp in newly_justified:
            for v in awake:
                if cp.slot > self.latest_justified[v].slot:
                    self.latest_justified[v] = cp

        # --- acknowledgments: 2/3 acks finalize within the slot (:1646) ---
        for cp in newly_justified:
            ackers = set()
            for v in awake:
                ack = Acknowledgment(checkpoint=cp, slot=t, validator=v)
                self.acks.append(ack)
                ackers.add(v)
            if self._supermajority(len(ackers)):
                self.finalized.add(cp)

    # -- observability -----------------------------------------------------
    def finalized_blocks(self) -> set[bytes]:
        return {cp.block for cp in self.finalized}

    def max_finalized_slot(self) -> int:
        return max(cp.slot for cp in self.finalized)

    def detect_ack_slashings(self) -> list[tuple[Acknowledgment, FFGVote]]:
        out = []
        for ack in self.acks:
            for vote in self.ffg_votes:
                if is_ack_slashable(ack, vote):
                    out.append((ack, vote))
        return out
