"""Concrete propose-vote-merge protocols: LMD-GHOST, RLMD-GHOST, Goldfish.

One simulation driver executes the three-phase template of
pos-evolution.md:1602-1608 under the sleepy adversary model
(:191-199, 1547); the protocol instance sets the fork-choice expiry
window, leader election, confirmation rules, and slot shape:

- ``lmd()``      eta = inf, round-robin proposers — (a more secure variant
                 of) LMD-GHOST (pos-evolution.md:1585)
- ``rlmd(eta)``  vote expiry eta, view-merge — RLMD-GHOST (:1581-1609)
- ``goldfish()`` eta = 1, VRF leaders + subsampling, kappa-deep slow
                 confirmation and optional 3/4 fast confirmation in 4-phase
                 slots — Goldfish / GHOST-Eph (:1543-1579)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

import numpy as np

from pos_evolution_tpu.models.pvm import (
    GENESIS_ROOT,
    HeadVote,
    PVMBlock,
    PVMValidator,
    View,
    ghost_head,
    vrf_is_eligible,
    vrf_output,
)


@dataclass
class PVMParams:
    n_validators: int
    vote_expiry: int | None = None   # None = LMD (eta = inf); 1 = Goldfish
    use_vrf: bool = False            # VRF leader election (:1554)
    subsample_rate: float = 1.0      # voter subsampling (:1545)
    kappa: int = 3                   # kappa-deep confirmation (:1556)
    fast_confirm: bool = False       # 4-phase slot with 3/4 rule (:1562-1569)
    fast_confirm_threshold: float = 0.75


def lmd(n: int) -> PVMParams:
    return PVMParams(n_validators=n, vote_expiry=None)


def rlmd(n: int, eta: int) -> PVMParams:
    return PVMParams(n_validators=n, vote_expiry=eta)


def goldfish(n: int, kappa: int = 3, fast_confirm: bool = False,
             subsample_rate: float = 1.0) -> PVMParams:
    return PVMParams(n_validators=n, vote_expiry=1, use_vrf=True,
                     kappa=kappa, fast_confirm=fast_confirm,
                     subsample_rate=subsample_rate)


@dataclass
class PVMAdversary:
    """Adversarial scheduling hooks (all default honest/synchronous).

    - ``asleep(slot, v)``: sleepy model (pos-evolution.md:193, 1547)
    - ``drop_proposal(slot, v)``: proposal does not reach v in time
      (network asynchrony / targeted delay, :197-199, 1328)
    - ``drop_votes(slot, v)``: slot votes do not reach v's merge phase
    """

    asleep: Callable[[int, int], bool] = lambda t, v: False
    drop_proposal: Callable[[int, int], bool] = lambda t, v: False
    drop_votes: Callable[[int, int], bool] = lambda t, v: False


class PVMSimulation:
    """Round-based execution of a propose-vote-merge protocol."""

    def __init__(self, params: PVMParams, adversary: PVMAdversary | None = None):
        self.p = params
        self.adv = adversary or PVMAdversary()
        self.validators = [PVMValidator(i) for i in range(params.n_validators)]
        self.slot = 1
        self.fast_confirmed: dict[int, bytes] = {}  # per-validator latest
        self.log: list[dict] = []

    # -- protocol roles --------------------------------------------------
    def _leaders(self, slot: int, awake: list[int]) -> list[int]:
        if not awake:
            return []
        if self.p.use_vrf:
            # every awake validator with minimal VRF output proposes; voters
            # accept the minimum (pos-evolution.md:1554)
            return [min(awake, key=lambda v: vrf_output(v, slot))]
        return [slot % self.p.n_validators]

    def _eligible_voter(self, v: int, slot: int) -> bool:
        if self.p.subsample_rate >= 1.0:
            return True
        return vrf_is_eligible(v, slot, b"vote", self.p.subsample_rate)

    def head_for(self, v: PVMValidator, slot: int) -> bytes:
        return ghost_head(v.view, slot, self.p.vote_expiry)

    # -- one slot --------------------------------------------------------
    def run_slot(self) -> None:
        t = self.slot
        p = self.p
        awake = [v.index for v in self.validators
                 if not self.adv.asleep(t, v.index)
                 and self.validators[v.index].status == "awake"]

        # wake transitions: asleep -> dreamy -> awake (pos-evolution.md:1547)
        for val in self.validators:
            sleeping = self.adv.asleep(t, val.index)
            if sleeping:
                val.status = "asleep"
            elif val.status == "asleep":
                val.status = "dreamy"   # joins this slot, acts next slot
            elif val.status == "dreamy":
                val.merge_buffer()
                val.status = "awake"

        # --- Propose (round k*t): leader merges buffer, runs FC, extends
        proposals: list[tuple[PVMBlock, View]] = []
        for leader in self._leaders(t, awake):
            lv = self.validators[leader]
            lv.merge_buffer()
            head = self.head_for(lv, t)
            block = PVMBlock(slot=t, parent=head, proposer=leader)
            lv.view.add_block(block)
            proposals.append((block, lv.view.copy()))

        # --- Vote (round k*t + Δ): merge proposed view, vote FC
        votes: list[HeadVote] = []
        for v in awake:
            val = self.validators[v]
            got_proposal = False
            for block, pview in proposals:
                if self.adv.drop_proposal(t, v):
                    continue
                # view-merge: adopt the proposer's referenced view
                val.view.merge(pview)
                val.view.add_block(block)
                got_proposal = True
            if not self._eligible_voter(v, t):
                continue
            head = self.head_for(val, t)
            vote = HeadVote(slot=t, block_root=head, validator=v)
            val.view.add_vote(vote)
            votes.append(vote)

        # --- optional fast-confirmation phase (round k*t + 2Δ, :1562-1569)
        if p.fast_confirm:
            tally: dict[bytes, int] = {}
            for vote in votes:
                tally[vote.block_root] = tally.get(vote.block_root, 0) + 1
            # "more than 3/4 of the *eligible voters* of slot t" (:1567) —
            # the subsampled committee of the full set, awake or not
            eligible = sum(1 for v in range(p.n_validators)
                           if self._eligible_voter(v, t))
            for root, count in tally.items():
                blk_ok = any(b.root == root and b.slot == t for b, _ in proposals)
                if blk_ok and eligible and count > p.fast_confirm_threshold * eligible:
                    for v in awake:
                        if not self.adv.drop_votes(t, v):
                            self.fast_confirmed[v] = root

        # --- Merge (last Δ): deliver votes/blocks into buffers, merge
        for val in self.validators:
            target_asleep = val.status != "awake"
            for block, _ in proposals:
                val.buffer_message(block)
            for vote in votes:
                if not self.adv.drop_votes(t, vote.validator) or target_asleep:
                    val.buffer_message(vote)
            if val.status == "awake" and val.index in awake:
                val.merge_buffer()

        self._record(t, awake, proposals, votes)
        self.slot += 1

    def run_slots(self, n: int) -> None:
        for _ in range(n):
            self.run_slot()

    # -- confirmation rules ----------------------------------------------
    def confirmed_ledger(self, v: int) -> bytes:
        """kappa-deep (slow) confirmation: the prefix of the canonical chain
        at blocks from slots <= t - kappa (pos-evolution.md:1556); a
        previously fast-confirmed block is never rolled back (:1568)."""
        val = self.validators[v]
        head = self.head_for(val, self.slot)
        cutoff = self.slot - self.p.kappa
        cur = head
        while cur != GENESIS_ROOT and val.view.blocks[cur].slot > cutoff:
            cur = val.view.blocks[cur].parent
        fast = self.fast_confirmed.get(v)
        if fast is not None and fast in val.view.blocks:
            if val.view.is_ancestor(cur, fast):
                return fast
        return cur

    def chain_of(self, v: int, root: bytes | None = None) -> list[bytes]:
        val = self.validators[v]
        cur = root if root is not None else self.head_for(val, self.slot)
        out = []
        while True:
            out.append(cur)
            if cur == GENESIS_ROOT:
                return out[::-1]
            cur = val.view.blocks[cur].parent

    def _record(self, t, awake, proposals, votes):
        heads = {v.index: self.head_for(v, t + 1).hex()[:8]
                 for v in self.validators[:4]}
        self.log.append({
            "slot": t, "awake": len(awake),
            "proposals": len(proposals), "votes": len(votes),
            "heads": heads,
        })
