"""Protocol variants (L7): propose-vote-merge family + SSF."""

from pos_evolution_tpu.models.protocols import (
    PVMAdversary,
    PVMParams,
    PVMSimulation,
    goldfish,
    lmd,
    rlmd,
)
from pos_evolution_tpu.models.ssf import (
    Acknowledgment,
    FFGVote,
    SSFCheckpoint,
    SSFSimulation,
    is_ack_slashable,
)
