"""Propose-vote-merge protocol template (L7; pos-evolution.md:1602-1608).

The reference observes that LMD-GHOST, Goldfish and RLMD-GHOST share one
structure: slots of k rounds with a Propose phase (proposer merges its
buffer, runs the fork-choice rule FC, extends the head, broadcasts block +
its view), a Vote phase (validators merge the proposed view — the
*view-merge* technique of pos-evolution.md:1528-1541 — then vote for
FC(view, slot)), and a Merge phase (validators merge their buffers).

This module builds that template once; the concrete protocols plug in a
fork-choice rule and a vote-expiry period:

- ``vote_expiry = None``  -> (secured) LMD-GHOST (pos-evolution.md:1585)
- ``vote_expiry = eta``   -> RLMD-GHOST (pos-evolution.md:1581-1600)
- ``vote_expiry = 1``     -> Goldfish / GHOST-Eph (pos-evolution.md:1543-1579)

Views and buffers are per-validator message sets (pos-evolution.md:201-203,
1596); equivocation discounting (pos-evolution.md:1409-1413) is applied
inside the weight computation; VRF leader election with subsampling
(pos-evolution.md:1554) replaces round-robin when enabled.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field, replace

import numpy as np

GENESIS_ROOT = b"genesis" + b"\x00" * 25


@dataclass(frozen=True)
class PVMBlock:
    """A block in the propose-vote-merge block-tree."""

    slot: int
    parent: bytes
    proposer: int
    salt: int = 0  # distinguishes equivocating blocks

    @property
    def root(self) -> bytes:
        h = hashlib.sha256(
            b"pvm-block" + self.slot.to_bytes(8, "little")
            + self.parent + self.proposer.to_bytes(8, "little", signed=True)
            + self.salt.to_bytes(8, "little"))
        return h.digest()


@dataclass(frozen=True)
class HeadVote:
    """[HEAD-VOTE, B, t, v] (pos-evolution.md:1624)."""

    slot: int
    block_root: bytes
    validator: int


class View:
    """A validator's view G: accepted blocks + votes (pos-evolution.md:201).

    Tracks equivocation evidence: a proposer with two blocks in one slot,
    or a validator with two head-votes in one slot, is discounted forever
    (fork-choice discounting, pos-evolution.md:1411).
    """

    def __init__(self):
        self.blocks: dict[bytes, PVMBlock] = {
            GENESIS_ROOT: PVMBlock(slot=0, parent=GENESIS_ROOT, proposer=-1)}
        # (validator, slot) -> block_root of their vote; conflicts mark
        # the voter as an equivocator.
        self.votes: dict[tuple[int, int], bytes] = {}
        self.equivocators: set[int] = set()
        self._proposals: dict[tuple[int, int], bytes] = {}

    def add_block(self, block: PVMBlock) -> None:
        if block.parent not in self.blocks:
            return  # dependency rule: accept only with ancestors present
        if block.root in self.blocks:
            return
        key = (block.proposer, block.slot)
        prev = self._proposals.get(key)
        if prev is not None and prev != block.root:
            self.equivocators.add(block.proposer)
        self._proposals.setdefault(key, block.root)
        self.blocks[block.root] = block

    def add_vote(self, vote: HeadVote) -> None:
        key = (vote.validator, vote.slot)
        prev = self.votes.get(key)
        if prev is not None and prev != vote.block_root:
            self.equivocators.add(vote.validator)
            return
        self.votes[key] = vote.block_root

    def merge(self, other: "View") -> None:
        # parents always have strictly lower slots, so one slot-ordered pass
        # inserts parent-first (no quadratic fixpoint iteration); the
        # genesis marker is skipped (re-adding it would duplicate it under
        # its computed hash root)
        for root, b in sorted(other.blocks.items(), key=lambda kv: kv[1].slot):
            if root != GENESIS_ROOT:
                self.add_block(b)
        for (v, s), root in other.votes.items():
            self.add_vote(HeadVote(slot=s, block_root=root, validator=v))
        self.equivocators |= other.equivocators

    def copy(self) -> "View":
        out = View()
        out.blocks = dict(self.blocks)
        out.votes = dict(self.votes)
        out.equivocators = set(self.equivocators)
        out._proposals = dict(self._proposals)
        return out

    # -- fork-choice support ---------------------------------------------
    def children(self) -> dict[bytes, list[bytes]]:
        ch: dict[bytes, list[bytes]] = {}
        for root, b in self.blocks.items():
            if root == GENESIS_ROOT:
                continue
            ch.setdefault(b.parent, []).append(root)
        return ch

    def is_ancestor(self, ancestor: bytes, descendant: bytes) -> bool:
        cur = descendant
        while True:
            if cur == ancestor:
                return True
            blk = self.blocks.get(cur)
            if blk is None or cur == GENESIS_ROOT:
                return False
            cur = blk.parent

    def latest_votes(self, slot: int, expiry: int | None) -> dict[int, bytes]:
        """Latest non-equivocating vote per validator within the expiry
        window [slot - eta, slot - 1] (pos-evolution.md:1585, 1596)."""
        lo = 0 if expiry is None else max(slot - expiry, 0)
        latest: dict[int, tuple[int, bytes]] = {}
        for (v, s), root in self.votes.items():
            if v in self.equivocators or not (lo <= s < slot):
                continue
            if root not in self.blocks:
                continue
            cur = latest.get(v)
            if cur is None or s > cur[0]:
                latest[v] = (s, root)
        return {v: root for v, (s, root) in latest.items()}


def ghost_head(view: View, slot: int, expiry: int | None,
               weights: np.ndarray | None = None) -> bytes:
    """(R)LMD-GHOST / GHOST-Eph head: greedy heaviest-subtree descent using
    the (expiry-windowed, equivocation-discounted) latest votes
    (pos-evolution.md:1549, 1585, 1596)."""
    votes = view.latest_votes(slot, expiry)
    weight_of: dict[bytes, float] = {}
    for v, root in votes.items():
        w = 1.0 if weights is None else float(weights[v])
        cur = root
        while True:
            weight_of[cur] = weight_of.get(cur, 0.0) + w
            if cur == GENESIS_ROOT:
                break
            cur = view.blocks[cur].parent
    children = view.children()
    head = GENESIS_ROOT
    while True:
        kids = children.get(head, [])
        if not kids:
            return head
        head = max(kids, key=lambda r: (weight_of.get(r, 0.0), r))


def vanilla_ghost_head(view: View) -> bytes:
    """Pre-LMD GHOST: subtree weight = number of blocks, equivocations NOT
    discounted — the rule the avalanche attack defeats
    (pos-evolution.md:1469-1473). Iterative (no recursion-depth limit)."""
    from pos_evolution_tpu.utils.traversal import postorder

    children = view.children()
    # all subtree sizes in one post-order pass
    size: dict[bytes, int] = {}
    for root in postorder(children, GENESIS_ROOT):
        size[root] = 1 + sum(size[c] for c in children.get(root, ()))

    head = GENESIS_ROOT
    while True:
        kids = children.get(head, [])
        if not kids:
            return head
        head = max(kids, key=lambda r: (size[r], r))


def vrf_output(validator: int, slot: int) -> bytes:
    """Deterministic stand-in VRF evaluation (pos-evolution.md:1554)."""
    return hashlib.sha256(b"pvm-vrf" + validator.to_bytes(8, "little")
                          + slot.to_bytes(8, "little")).digest()


def vrf_is_eligible(validator: int, slot: int, tag: bytes,
                    subsample_rate: float) -> bool:
    """Subsampling predicate: pseudo-random committee self-selection
    (pos-evolution.md:1545)."""
    h = hashlib.sha256(b"pvm-sub" + tag + validator.to_bytes(8, "little")
                       + slot.to_bytes(8, "little")).digest()
    return int.from_bytes(h[:8], "little") < subsample_rate * 2**64


@dataclass
class PVMValidator:
    """A validator in a propose-vote-merge protocol: view + buffer
    (pos-evolution.md:1596)."""

    index: int
    view: View = field(default_factory=View)
    buffer: list = field(default_factory=list)
    # Goldfish sleep states: awake / asleep / dreamy (pos-evolution.md:1547)
    status: str = "awake"
    confirmed_prefix: bytes = GENESIS_ROOT

    def buffer_message(self, msg) -> None:
        self.buffer.append(msg)

    def merge_buffer(self) -> None:
        for msg in self.buffer:
            if isinstance(msg, PVMBlock):
                self.view.add_block(msg)
            elif isinstance(msg, HeadVote):
                self.view.add_vote(msg)
            elif isinstance(msg, View):
                self.view.merge(msg)
        self.buffer = []
