"""Run supervision: self-healing long runs (ISSUE 10, DESIGN.md §18).

Four legs, one contract:

- ``CheckpointManager`` (``manager.py``): atomic, checksummed,
  retention-managed checkpoint steps with an async writer so the epoch
  loop never blocks on serialization;
- ``supervise`` (``supervisor.py``): parent-process crash/hang
  detection over ``utils/watchdog.Heartbeat`` files, resume with capped
  jittered backoff, loud refusal after N consecutive failures;
- ``IntegrityGuard`` (``guard.py``): the deep spec-walk / column-scan
  oracles as a *recovery trigger* — quarantine the suspect checkpoint,
  roll back, replay;
- goodput accounting: every decision lands on the telemetry bus as
  ``checkpoint_*`` / ``supervisor_*`` / ``integrity_violation`` events,
  folded into ``scripts/run_report.py``'s "Resilience" section.

Both drivers opt in with ``autocheckpoint=(every_n_slots, dir)`` (or
the ``AutoCheckpoint`` record for the full knob set); a restarted
process calls ``resume_latest``. ``scripts/resilient_run.py`` is the
CLI that ties the halves together.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass

from pos_evolution_tpu.resilience.guard import (
    IntegrityError,
    IntegrityGuard,
    scan_columns,
)
from pos_evolution_tpu.resilience.manager import (
    CheckpointCorruption,
    CheckpointManager,
    FingerprintMismatch,
)
from pos_evolution_tpu.resilience.runner import RunSupervision
from pos_evolution_tpu.resilience.supervision import (
    RetryPolicy,
    heartbeat_age,
    rss_kb,
)
from pos_evolution_tpu.resilience.supervisor import (
    SupervisorGaveUp,
    backoff_delay,
    supervise,
)

__all__ = [
    "AutoCheckpoint", "CheckpointManager", "CheckpointCorruption",
    "FingerprintMismatch", "IntegrityGuard", "IntegrityError",
    "RetryPolicy", "RunSupervision", "SupervisorGaveUp", "backoff_delay",
    "fingerprint_config", "heartbeat_age", "replayed_slots_from_events",
    "rss_kb", "scan_columns", "state_digest", "supervise",
]


def replayed_slots_from_events(events) -> int:
    """Slots re-executed because interruptions rolled the run back to a
    checkpoint: for each ``supervisor_interruption`` whose last
    heartbeat reached slot H, the next ``run_resumed`` at slot R costs
    ``max(H - R, 0)`` replayed slots. THE one implementation — the
    bench emission (``scripts/resilient_run.py``) and the offline
    report (``scripts/run_report.py``) must never disagree on it."""
    replayed = 0
    last_hb = None
    for ev in events:
        t = ev.get("type")
        if t == "supervisor_interruption":
            last_hb = (ev.get("last_heartbeat") or {}).get("slot")
        elif t == "run_resumed" and last_hb is not None:
            replayed += max(last_hb - ev.get("slot", last_hb), 0)
            last_hb = None
    return replayed


@dataclass
class AutoCheckpoint:
    """The drivers' ``autocheckpoint=`` knob, normalized. Accepted
    spellings at the driver: an ``AutoCheckpoint``, an
    ``(every_n_slots, dir)`` tuple, or a dict of these fields.

    ``async_mode`` keeps serialization off the run loop (bounded
    staleness: at most one interval plus one in-flight step is lost on
    a kill). ``guard_every`` arms an ``IntegrityGuard`` audit every N
    slots (0 = off). ``heartbeat`` names a ``utils/watchdog.Heartbeat``
    file beaten once per slot for the supervisor's hang detection.
    ``digest`` picks the payload checksum: ``"auto"`` (default) resolves
    at supervision construction to the ``"merkle"`` digest
    (``ops/merkle_device.DIGEST_ALGO``) when the jax backend is active —
    payload hashing then rides the device merkle path at gather time —
    and to plain ``"sha256"`` otherwise (on the numpy backend the merkle
    digest is pure overhead: ~2x the hashing with no device to win it
    back). Explicit ``"merkle"``/``"sha256"`` are honored as given."""

    every_n_slots: int
    dir: str
    retain: int = 3
    async_mode: bool = True
    guard_every: int = 0
    heartbeat: str | None = None
    digest: str = "auto"

    @classmethod
    def of(cls, spec) -> "AutoCheckpoint":
        if isinstance(spec, cls):
            return spec
        if isinstance(spec, dict):
            return cls(**spec)
        every, dir_ = spec
        return cls(every_n_slots=int(every), dir=os.fspath(dir_))


def fingerprint_config(cfg) -> str:
    """Stable hash of an active ``config.Config`` for checkpoint
    manifests — mesh shape and device count are deliberately NOT part
    of it (resume-across-mesh-shapes is a supported degraded path)."""
    import dataclasses
    blob = json.dumps(
        {k: (v.hex() if isinstance(v, bytes) else v)
         for k, v in dataclasses.asdict(cfg).items()},
        sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def state_digest(sim) -> str:
    """Mesh-independent digest of a driver's full simulation state —
    the bit-identity witness the kill-resume tests and the CI twin
    compare. Two runs with equal digests hold identical stores/columns,
    metrics, and slot cursors, whatever mesh (or interruption history)
    produced them."""
    h = hashlib.sha256()
    if hasattr(sim, "head_host_walk"):  # DenseSimulation
        import numpy as np

        # registry-scale columns go through the merkle payload digest
        # (device level sweeps when the jax backend is active) and only
        # the 32-byte column digests feed the scalar accumulator —
        # identical witness whichever path hashed the columns
        from pos_evolution_tpu.ops.merkle_device import digest_bytes
        for f in sim.registry._fields:
            h.update(digest_bytes(np.ascontiguousarray(
                np.asarray(getattr(sim.registry, f))[: sim.n]).view(
                    np.uint8)))
        h.update(digest_bytes(np.ascontiguousarray(
            np.asarray(sim.msg_block)[: sim.n]).view(np.uint8)))
        h.update(digest_bytes(np.ascontiguousarray(
            np.asarray(sim.msg_epoch)[: sim.n]).view(np.uint8)))
        meta = {"slot": sim.slot, "roots": [r.hex() for r in sim.roots],
                "parents": sim.parents, "block_slots": sim.block_slots,
                "bits": [bool(b) for b in sim.bits],
                "prev_just": list(sim.prev_just),
                "cur_just": list(sim.cur_just),
                "finalized": list(sim.finalized),
                "metrics": sim.metrics}
        h.update(json.dumps(meta, sort_keys=True).encode())
        return h.hexdigest()
    from pos_evolution_tpu.utils.snapshot import save_store
    for g in sim.groups:
        h.update(save_store(g.store))
    h.update(json.dumps({"slot": sim.slot, "metrics": sim.metrics},
                        sort_keys=True, default=repr).encode())
    return h.hexdigest()
