"""Supervised auto-resume: run a child process until it finishes, or
until its failures exhaust the retry budget (DESIGN.md §18).

``supervise`` is the parent half of the self-healing contract. The
child half is any entry point that (a) autocheckpoints through
``CheckpointManager``, (b) beats a ``utils/watchdog.Heartbeat`` file
from inside its run loop, and (c) resumes from the latest *valid*
checkpoint when restarted (``resume_latest``). The parent then only
needs three senses:

- **crash**: the child exits nonzero (or dies to a signal — an OOM
  kill and a SIGKILL look identical from here, which is the point);
- **hang**: the heartbeat file stops advancing for ``hang_timeout_s``.
  This extends the watchdog's SIGALRM honesty note: an alarm cannot
  interrupt native code, but a *parent* watching a file's age can kill
  a child stuck inside an XLA compile loop just fine;
- **success**: exit 0.

Between failures the parent sleeps a capped exponential backoff with
deterministic jitter (seeded per attempt — reproducible in tests, still
decorrelated across a fleet). After ``max_failures`` consecutive
failures it REFUSES loudly (``SupervisorGaveUp``) instead of thrashing:
by then the failure is systematic, and retry N+1 only burns quota.

Every decision is emitted as a ``supervisor_*`` telemetry event
(append-mode ``EventBus`` when ``events_path`` is given, the global
sink otherwise) so ``scripts/run_report.py`` can reconstruct the
interruption/retry/goodput story offline.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

# the crash/hang/backoff POLICY lives in supervision.py, shared with the
# serving tier's WorkerPool (serve/workers.py) — this module is the
# one-child, run-to-completion parent built on it
from pos_evolution_tpu.resilience.supervision import (
    RetryPolicy,
    backoff_delay,
    heartbeat_age,
)
from pos_evolution_tpu.utils.watchdog import read_heartbeat

__all__ = ["SupervisorGaveUp", "backoff_delay", "supervise"]


class SupervisorGaveUp(RuntimeError):
    """The retry budget is exhausted; the failure is systematic."""


def _emit(bus, type_: str, **fields) -> None:
    if bus is not None:
        bus.emit(type_, **fields)
    else:
        from pos_evolution_tpu.telemetry import emit_global
        emit_global(type_, **fields)


def supervise(build_argv, *, heartbeat_path: str | None = None,
              hang_timeout_s: float | None = None, max_failures: int = 3,
              backoff_s: float = 1.0, backoff_cap_s: float = 30.0,
              jitter: float = 0.25, seed: int = 0, env: dict | None = None,
              poll_s: float = 0.2, events_bus=None,
              on_attempt=None) -> dict:
    """Run ``build_argv(attempt) -> list[str]`` as a child process until
    one attempt exits 0; crash/hang attempts are retried from whatever
    the child's checkpoint store holds. Returns a summary dict::

        {"ok": True, "attempts": N,
         "interruptions": [{"attempt", "reason", "exit_code",
                            "wall_s", "last_heartbeat": {...}}, ...],
         "total_wall_s": ..., "backoff_s": ...}

    Raises ``SupervisorGaveUp`` after ``max_failures`` consecutive
    failed attempts (the summary rides on the exception as ``.summary``
    for the postmortem). ``on_attempt(attempt)`` is a test hook called
    before each launch.
    """
    t_start = time.perf_counter()
    interruptions: list[dict] = []
    policy = RetryPolicy(max_failures=max_failures, backoff_s=backoff_s,
                         backoff_cap_s=backoff_cap_s, jitter=jitter,
                         seed=seed)
    attempt = 0
    while True:
        if on_attempt is not None:
            on_attempt(attempt)
        argv = build_argv(attempt)
        _emit(events_bus, "supervisor_attempt", attempt=attempt,
              argv=[os.path.basename(argv[0])] + list(argv[1:]))
        t0 = time.perf_counter()
        t0_unix = time.time()
        proc = subprocess.Popen(argv, env=env)
        reason = None
        while True:
            rc = proc.poll()
            if rc is not None:
                break
            if heartbeat_path is not None and hang_timeout_s:
                # attempt-boundary rule (supervision.heartbeat_age): a
                # beat from a PREVIOUS attempt is not this child's
                # liveness — until this attempt beats, age is measured
                # from its own launch instead of the stale file
                age = heartbeat_age(heartbeat_path, t0_unix,
                                    time.perf_counter() - t0)
                if age > hang_timeout_s:
                    # no SIGTERM courtesy: a hung child may be wedged in
                    # native code and ignore it; the checkpoint store is
                    # crash-safe by construction, so SIGKILL is honest
                    proc.kill()
                    proc.wait()
                    rc = -signal.SIGKILL
                    reason = "hang"
                    break
            time.sleep(poll_s)
        wall = time.perf_counter() - t0
        if rc == 0:
            summary = {"ok": True, "attempts": attempt + 1,
                       "interruptions": interruptions,
                       "final_wall_s": round(wall, 3),
                       "backoff_s": round(policy.backoff_total_s, 3),
                       "total_wall_s": round(
                           time.perf_counter() - t_start, 3)}
            _emit(events_bus, "supervisor_done", **{
                k: v for k, v in summary.items() if k != "interruptions"},
                n_interruptions=len(interruptions))
            return summary
        hb = (read_heartbeat(heartbeat_path)
              if heartbeat_path is not None else None)
        hb_slot = ((hb or {}).get("payload") or {}).get("slot")
        delay = policy.record_failure(progress=hb_slot)
        record = {"attempt": attempt, "reason": reason or "crash",
                  "exit_code": rc, "wall_s": round(wall, 3),
                  "last_heartbeat": (hb or {}).get("payload")}
        interruptions.append(record)
        _emit(events_bus, "supervisor_interruption", **record)
        if delay is None:
            summary = {"ok": False, "attempts": attempt + 1,
                       "interruptions": interruptions,
                       "backoff_s": round(policy.backoff_total_s, 3),
                       "total_wall_s": round(
                           time.perf_counter() - t_start, 3)}
            _emit(events_bus, "supervisor_gaveup", attempts=attempt + 1,
                  consecutive_failures=policy.failures)
            err = SupervisorGaveUp(
                f"{policy.failures} consecutive failed attempts (last: "
                f"{record['reason']}, exit {rc}) — refusing to thrash; "
                f"inspect the checkpoint store and the child log")
            err.summary = summary
            raise err
        _emit(events_bus, "supervisor_backoff", failures=policy.failures,
              delay_s=round(delay, 3))
        print(f"# supervisor: attempt {attempt} {record['reason']} "
              f"(exit {rc}); retrying in {delay:.2f}s "
              f"[{policy.failures}/{max_failures} failures]",
              file=sys.stderr)
        time.sleep(delay)
        attempt += 1
