"""In-loop corruption detection that triggers *recovery*, not just a log
line (DESIGN.md §18).

The repo already owns the right oracles — the deep spec-walk audit
(``ops/resident.py`` self-checks, ``DenseSimulation.head_host_walk``),
store invariants, and the twin-divergence pins in tests. What was
missing is turning a mid-run mismatch into an action: the
``IntegrityGuard`` runs those oracles every ``every_n_slots`` inside
the autocheckpointing run loop, and on ANY finding the driver

1. emits an ``integrity_violation`` event naming every finding,
2. **quarantines the newest checkpoint** (it may already embed the
   corruption — a checksum cannot see semantic rot, so the newest step
   is guilty until a replay proves otherwise),
3. raises ``IntegrityError`` so the supervised process dies loudly and
   the supervisor resumes from the last *good* step and replays.

Rollback-replay bit-identity vs an uninterrupted twin is pinned in
``tests/test_resilience.py`` — determinism of the drivers is what makes
"roll back and replay" a correctness-preserving recovery instead of a
shrug.
"""

from __future__ import annotations

import numpy as np


class IntegrityError(RuntimeError):
    """Mid-run state corruption detected; the process must not keep
    building on (or checkpointing) the poisoned state."""

    def __init__(self, findings: list[str]):
        super().__init__("integrity check failed: " + "; ".join(findings))
        self.findings = list(findings)


def scan_columns(cols: dict, n_blocks: int | None = None) -> list[str]:
    """Generic resident-column scan: non-finite values in any float
    column, negative balances, and message pointers outside the block
    table — the dense-state analogues of a NaN in a training step."""
    findings = []
    for name, col in cols.items():
        a = np.asarray(col)
        if np.issubdtype(a.dtype, np.floating):
            bad = int((~np.isfinite(a)).sum())
            if bad:
                findings.append(f"{name}: {bad} non-finite value(s)")
        if name in ("balance", "effective_balance") and a.size:
            neg = int((a < 0).sum())
            if neg:
                findings.append(f"{name}: {neg} negative balance(s)")
        if name == "msg_block" and n_blocks is not None and a.size:
            oob = int(((a < -1) | (a >= n_blocks)).sum())
            if oob:
                findings.append(
                    f"msg_block: {oob} pointer(s) outside the "
                    f"{n_blocks}-entry block table")
    return findings


class IntegrityGuard:
    """Periodic deep audit for either driver; ``check(driver)``
    dispatches on the driver's shape and returns a list of human-
    readable findings (empty = clean)."""

    def __init__(self, every_n_slots: int = 8):
        self.every_n_slots = max(int(every_n_slots), 1)
        self.checks = 0
        self._last_finalized: int | None = None

    def due(self, slot: int) -> bool:
        return slot % self.every_n_slots == 0

    def check(self, driver) -> list[str]:
        self.checks += 1
        if hasattr(driver, "head_host_walk"):
            return self._check_dense(driver)
        return self._check_sim(driver)

    # -- dense driver ----------------------------------------------------------

    def _check_dense(self, sim) -> list[str]:
        findings = []
        cols = {f: getattr(sim.registry, f) for f in sim.registry._fields}
        cols["msg_block"] = sim.msg_block
        findings += scan_columns(cols, n_blocks=len(sim.roots))
        # the deep oracle: device fork choice vs the vectorized host
        # spec walk over the gathered message table. On state corrupt
        # enough to crash the walk itself (a poisoned pointer indexing
        # past the tree), the crash IS the finding — the guard must
        # report and trigger rollback, not die of the corruption it
        # exists to catch.
        try:
            device_head = sim.roots[sim._head()]
            host_head = sim.head_host_walk()
            if device_head != host_head:
                findings.append(
                    f"device head {device_head.hex()[:12]} != host "
                    f"spec-walk head {host_head.hex()[:12]}")
        except Exception as e:
            findings.append(f"deep head oracle crashed on corrupt state: "
                            f"{type(e).__name__}: {e}"[:300])
        findings += self._finality_monotone(sim.finalized[0])
        return findings

    # -- spec driver -----------------------------------------------------------

    def _check_sim(self, sim) -> list[str]:
        from pos_evolution_tpu.specs import forkchoice as fc
        findings = []
        for g in sim.groups:
            if g.crashed:
                continue
            store = g.store
            if (int(store.finalized_checkpoint.epoch)
                    > int(store.justified_checkpoint.epoch)):
                findings.append(
                    f"group {g.id}: finalized epoch "
                    f"{int(store.finalized_checkpoint.epoch)} ahead of "
                    f"justified {int(store.justified_checkpoint.epoch)}")
            if g.resident is not None and not g.resident.degraded:
                cols = {"msg_block": g.resident.msg_block,
                        "msg_epoch": g.resident.msg_epoch}
                findings += [f"group {g.id}: {f}"
                             for f in scan_columns(cols)]
            if sim.variant.describe().get("kind") == "GasperVariant":
                # deep oracle (Gasper only: successor variants answer
                # from their own expiry-windowed rules, for which the
                # plain spec walk is the WRONG reference)
                spec_head = fc.get_head(store)
                prod_head = sim.variant.head(sim, g)
                if prod_head != spec_head:
                    findings.append(
                        f"group {g.id}: production head "
                        f"{prod_head.hex()[:12]} != spec-walk head "
                        f"{spec_head.hex()[:12]}")
        findings += self._finality_monotone(sim.finalized_epoch())
        return findings

    def _finality_monotone(self, finalized: int) -> list[str]:
        """Finality can never regress within one run — a rollback of the
        finalized epoch between audits means state was clobbered."""
        out = []
        if (self._last_finalized is not None
                and finalized < self._last_finalized):
            out.append(f"finalized epoch regressed "
                       f"{self._last_finalized} -> {finalized}")
        self._last_finalized = max(finalized,
                                   self._last_finalized
                                   if self._last_finalized is not None
                                   else finalized)
        return out
