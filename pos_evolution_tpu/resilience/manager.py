"""Preemption-tolerant checkpoint store (DESIGN.md §18).

A checkpoint here is a *directory* of payload files plus a
``manifest.json`` carrying a per-file sha256, the byte counts, and the
run's configuration fingerprint. Three invariants make it safe to kill
the writer at ANY instruction:

- **atomicity**: every step is staged under ``.tmp-step_N-<pid>``,
  every file fsync'd, then the directory renamed into place — a crash
  mid-write leaves only a tmp directory that the next manager sweep
  removes; a visible ``step_N`` directory is always complete;
- **verifiability**: ``load``/``validate`` recompute every file's
  sha256 against the manifest and check the fingerprint, so a torn,
  bit-flipped, or doctored checkpoint is *refused with a reason*, never
  half-loaded;
- **quarantine, not deletion**: a checkpoint that fails validation (or
  that an ``IntegrityGuard`` implicates) is renamed under
  ``quarantine/`` — evidence for the postmortem — and ``latest_valid``
  rolls past it to the newest step that still verifies.

**Async mode** is what keeps autocheckpointing out of the epoch loop's
critical path: ``save`` hands the payload (bytes, or a zero-arg
callable that produces them — the dense driver's gather-then-compress
split) to a single background writer thread and returns. Staleness is
bounded by construction: the queue holds at most ONE pending step, so
a caller checkpointing faster than the disk blocks on the *previous*
save — at any crash instant, at most one interval plus one in-flight
step is lost. The time the caller actually spent blocked
(``blocked_s``) vs the work the thread absorbed (``background_s``) is
tracked in ``stats()`` so the overlap is measured, not assumed.
"""

from __future__ import annotations

import hashlib
import json
import os
import queue
import re
import shutil
import threading
import time

MANIFEST_VERSION = 1
_STEP_RE = re.compile(r"^step_(\d{8})$")


class CheckpointCorruption(Exception):
    """A checkpoint failed validation (torn file, checksum mismatch,
    missing manifest, or a configuration fingerprint that does not match
    the run trying to load it)."""


class FingerprintMismatch(CheckpointCorruption):
    """The checkpoint is internally consistent but belongs to a
    different run shape. Refused, but NOT quarantined: the bytes are
    somebody's good checkpoint, just not this run's."""


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _merkle_hex(data: bytes) -> str:
    """Merkle payload digest (``ops/merkle_device.DIGEST_ALGO``): the
    device-portable checksum — 32-byte chunks merkleized through the
    merkle dispatch layer, byte length mixed in. The writer may hash on
    the device (jax backend active at gather time, payload past the
    crossover); validation recomputes on whatever path the loading
    process has — identical hex either way."""
    from pos_evolution_tpu.ops.merkle_device import digest_bytes
    return digest_bytes(data).hex()


_DIGESTS = {"sha256": _sha256, "merkle": _merkle_hex}


def _fsync_write(path: str, data: bytes) -> None:
    with open(path, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())


class CheckpointManager:
    """Durable step store under ``dir``; see the module docstring for
    the atomicity / verifiability / quarantine contract.

    ``fingerprint`` (any JSON-able dict) is stamped into every manifest
    and re-checked on load: a checkpoint from a different run shape
    (validator count, variant, config) must refuse loudly rather than
    resume a subtly different simulation. ``retain`` keeps the newest N
    steps (quarantined steps never count against retention).
    """

    def __init__(self, dir: str | os.PathLike, retain: int = 3,
                 async_mode: bool = False,
                 fingerprint: dict | None = None,
                 digest: str = "sha256"):
        if digest not in _DIGESTS:
            raise ValueError(f"unknown checkpoint digest {digest!r}; "
                             f"one of {sorted(_DIGESTS)}")
        self.dir = os.fspath(dir)
        self.retain = int(retain)
        self.async_mode = bool(async_mode)
        self.fingerprint = fingerprint
        self.digest = digest
        os.makedirs(self.dir, exist_ok=True)
        self._sweep_tmp()
        self._stats = {"saves": 0, "bytes": 0, "blocked_s": 0.0,
                       "background_s": 0.0, "gc_removed": 0,
                       "quarantined": 0}
        # the writer thread (background_s/saves/bytes) and the caller
        # thread (blocked_s, stats() reads) share this dict
        self._stats_lock = threading.Lock()
        self._queue: queue.Queue | None = None
        self._worker: threading.Thread | None = None
        self._worker_error: BaseException | None = None
        if self.async_mode:
            self._queue = queue.Queue(maxsize=1)
            self._worker = threading.Thread(target=self._drain_loop,
                                            name="ckpt-writer", daemon=True)
            self._worker.start()

    # -- paths -----------------------------------------------------------------

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.dir, f"step_{step:08d}")

    def steps(self) -> list[int]:
        """Visible (non-quarantined) step numbers, oldest first."""
        out = []
        for name in os.listdir(self.dir):
            m = _STEP_RE.match(name)
            if m and os.path.isdir(os.path.join(self.dir, name)):
                out.append(int(m.group(1)))
        return sorted(out)

    def _sweep_tmp(self) -> None:
        """Recover from a writer killed mid-save: an ``.old-`` directory
        is a displaced previous copy of a re-saved step — restore it if
        the kill landed before the new copy's rename (the step must
        never be lost to a re-save), drop it otherwise. ``.tmp-``
        staging directories are plain hygiene (invisible to ``steps``)."""
        for name in os.listdir(self.dir):
            path = os.path.join(self.dir, name)
            if name.startswith(".old-"):
                final = os.path.join(self.dir, name.split("-", 2)[1])
                if not os.path.isdir(final):
                    os.replace(path, final)
                else:
                    shutil.rmtree(path, ignore_errors=True)
            elif name.startswith(".tmp-"):
                shutil.rmtree(path, ignore_errors=True)

    # -- write -----------------------------------------------------------------

    def save(self, step: int, payloads, wait: bool = False) -> None:
        """Persist one step. ``payloads`` is ``bytes``, a zero-arg
        callable returning bytes, or a ``{filename: bytes-or-callable}``
        dict. Callables run on the writer thread in async mode — that is
        the overlap: the caller gathers cheap host state, the thread
        pays for serialization/compression. In sync mode (or with
        ``wait=True``) the call returns only once the step is on disk.
        """
        if not isinstance(payloads, dict):
            payloads = {"payload.bin": payloads}
        # the digest policy is pinned at GATHER time: the writer thread
        # hashes under the backend the *caller* had active, so a run on
        # the jax backend gets device payload digests even though the
        # bytes materialize on the background thread
        from pos_evolution_tpu.backend import get_backend
        backend = getattr(get_backend(), "name", "numpy")
        t0 = time.perf_counter()
        if self._queue is None:
            self._write_step(step, payloads)
        else:
            self._raise_worker_error()
            self._queue.put((step, payloads, backend))  # blocks if in flight
            if wait:
                self._queue.join()
                self._raise_worker_error()
        with self._stats_lock:
            self._stats["blocked_s"] += time.perf_counter() - t0

    def _drain_loop(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                self._queue.task_done()
                return
            step, payloads, backend = item
            t0 = time.perf_counter()
            try:
                from pos_evolution_tpu.backend import set_backend
                set_backend(backend)  # thread-local: the caller's policy
                self._write_step(step, payloads)
            except BaseException as e:  # surfaced on the next save/drain
                self._worker_error = e
            finally:
                with self._stats_lock:
                    self._stats["background_s"] += time.perf_counter() - t0
                self._queue.task_done()

    def _raise_worker_error(self) -> None:
        if self._worker_error is not None:
            err, self._worker_error = self._worker_error, None
            raise RuntimeError(
                f"background checkpoint write failed: {err!r}") from err

    def _write_step(self, step: int, payloads: dict) -> None:
        tmp = os.path.join(self.dir, f".tmp-step_{step:08d}-{os.getpid()}")
        os.makedirs(tmp, exist_ok=True)
        files = {}
        total = 0
        for name, data in payloads.items():
            if callable(data):
                data = data()
            _fsync_write(os.path.join(tmp, name), data)
            files[name] = {self.digest: _DIGESTS[self.digest](data),
                           "bytes": len(data)}
            total += len(data)
        manifest = {"v": MANIFEST_VERSION, "step": int(step),
                    "fingerprint": self.fingerprint, "files": files}
        _fsync_write(os.path.join(tmp, "manifest.json"),
                     json.dumps(manifest, sort_keys=True, indent=1).encode())
        final = self._step_dir(step)
        displaced = None
        if os.path.isdir(final):
            # same step re-saved: the durable copy must survive a kill
            # at ANY instruction, so displace it aside (restored by
            # ``_sweep_tmp`` if we die before the new copy's rename —
            # an rmtree-then-rename would lose BOTH in that window)
            displaced = os.path.join(self.dir,
                                     f".old-step_{step:08d}-{os.getpid()}")
            os.replace(final, displaced)
        os.replace(tmp, final)
        # the rename must itself be durable before the step is trusted
        dfd = os.open(self.dir, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        if displaced is not None:
            shutil.rmtree(displaced, ignore_errors=True)
        with self._stats_lock:
            self._stats["saves"] += 1
            self._stats["bytes"] += total
        self.gc()

    def drain(self) -> None:
        """Block until every queued async save is durable."""
        if self._queue is not None:
            self._queue.join()
            self._raise_worker_error()

    def close(self) -> None:
        if self._queue is not None:
            self._queue.join()
            self._queue.put(None)
            self._queue.join()
            self._worker.join(timeout=10)
            self._queue = None
        self._raise_worker_error()

    # -- validate / load -------------------------------------------------------

    def validate(self, step: int) -> dict:
        """Full verification of one step; returns its manifest or raises
        ``CheckpointCorruption`` naming exactly what failed."""
        manifest, _ = self._verify(step, keep_payloads=False)
        return manifest

    def _verify(self, step: int, keep_payloads: bool):
        """One read per payload file serves both the checksum and (when
        ``keep_payloads``) the returned bytes — ``load`` must not pay
        the resume I/O twice on registry-scale checkpoints."""
        d = self._step_dir(step)
        mpath = os.path.join(d, "manifest.json")
        try:
            with open(mpath, "rb") as fh:
                manifest = json.loads(fh.read().decode())
        except FileNotFoundError:
            raise CheckpointCorruption(
                f"step {step}: no manifest at {mpath}") from None
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            raise CheckpointCorruption(
                f"step {step}: manifest unparseable ({e})") from None
        if manifest.get("v") != MANIFEST_VERSION:
            raise CheckpointCorruption(
                f"step {step}: unknown manifest version {manifest.get('v')!r}")
        payloads: dict[str, bytes] = {}
        for name, meta in manifest.get("files", {}).items():
            fpath = os.path.join(d, name)
            try:
                with open(fpath, "rb") as fh:
                    data = fh.read()
            except FileNotFoundError:
                raise CheckpointCorruption(
                    f"step {step}: payload file {name!r} missing") from None
            if len(data) != meta["bytes"]:
                raise CheckpointCorruption(
                    f"step {step}: {name!r} truncated "
                    f"({len(data)} of {meta['bytes']} bytes)")
            # the manifest entry names its own algorithm, so a store can
            # hold (and validate) steps written under either digest
            algo = next((a for a in _DIGESTS if a in meta), None)
            if algo is None:
                raise CheckpointCorruption(
                    f"step {step}: {name!r} carries no known digest "
                    f"(expected one of {sorted(_DIGESTS)})")
            if _DIGESTS[algo](data) != meta[algo]:
                raise CheckpointCorruption(
                    f"step {step}: {name!r} {algo} checksum mismatch "
                    f"(bit flip or doctored manifest)")
            if keep_payloads:
                payloads[name] = data
        fp = manifest.get("fingerprint")
        if (self.fingerprint is not None and fp is not None
                and fp != self.fingerprint):
            raise FingerprintMismatch(
                f"step {step}: fingerprint mismatch — checkpoint from "
                f"{fp}, this run is {self.fingerprint}")
        return manifest, payloads

    def load(self, step: int) -> dict[str, bytes]:
        """Validated read of one step's payload files."""
        _manifest, payloads = self._verify(step, keep_payloads=True)
        return payloads

    def latest_valid(self, quarantine_bad: bool = True):
        """``(step, payloads)`` for the newest step that passes full
        validation, rolling past (and by default quarantining) any that
        fail; ``None`` when no valid checkpoint exists."""
        for step in reversed(self.steps()):
            try:
                return step, self.load(step)
            except CheckpointCorruption as e:
                from pos_evolution_tpu.telemetry import emit_global
                emit_global("checkpoint_rejected", step=step,
                            reason=str(e)[:300])
                if quarantine_bad and not isinstance(e, FingerprintMismatch):
                    self.quarantine(step, reason=str(e))
        return None

    def quarantine(self, step: int, reason: str = "") -> str:
        """Move a bad step out of the visible sequence, keeping it as
        evidence. Returns the quarantine path."""
        qdir = os.path.join(self.dir, "quarantine")
        os.makedirs(qdir, exist_ok=True)
        dst = os.path.join(qdir, f"step_{step:08d}")
        n = 0
        while os.path.exists(dst):
            n += 1
            dst = os.path.join(qdir, f"step_{step:08d}.{n}")
        os.replace(self._step_dir(step), dst)
        try:
            _fsync_write(os.path.join(dst, "QUARANTINE_REASON.txt"),
                         (reason or "unspecified").encode())
        except OSError:
            pass  # the move is the record; the note is best-effort
        with self._stats_lock:
            self._stats["quarantined"] += 1
        from pos_evolution_tpu.telemetry import emit_global
        emit_global("checkpoint_quarantined", step=step,
                    reason=(reason or "")[:300], path=dst)
        return dst

    # -- retention -------------------------------------------------------------

    def gc(self) -> int:
        """Drop the oldest steps beyond ``retain``; returns how many."""
        steps = self.steps()
        removed = 0
        for step in steps[:max(len(steps) - self.retain, 0)]:
            shutil.rmtree(self._step_dir(step), ignore_errors=True)
            removed += 1
        with self._stats_lock:
            self._stats["gc_removed"] += removed
        return removed

    def stats(self) -> dict:
        with self._stats_lock:
            s = dict(self._stats)
        s["blocked_s"] = round(s["blocked_s"], 6)
        s["background_s"] = round(s["background_s"], 6)
        return s
