"""The per-slot supervision hook both drivers share (DESIGN.md §18).

``RunSupervision`` is what a driver's ``autocheckpoint=`` knob
constructs: one object owning the ``CheckpointManager``, the optional
``Heartbeat`` and ``IntegrityGuard``, and the telemetry emissions, with
a single ``tick(driver, slot, capture)`` called at the end of every
slot. The drivers differ only in their ``capture``:

- ``sim/driver.Simulation`` serializes on the caller thread (the
  stores are live mutable Python objects — a background serializer
  would race the next slot's handlers) and overlaps only the
  fsync+rename;
- ``sim/dense_driver.DenseSimulation`` gathers its device columns to
  host synchronously (cheap) and hands the npz compression — the
  expensive part — to the manager's writer thread as a callable.

Order inside a tick matters: heartbeat first (liveness must not wait on
an audit), integrity audit second (a poisoned state must not be
*checkpointed*), checkpoint last.
"""

from __future__ import annotations

import time

from pos_evolution_tpu.resilience.guard import IntegrityError, IntegrityGuard
from pos_evolution_tpu.resilience.manager import CheckpointManager


def run_fingerprint(kind: str, cfg_obj=None) -> dict:
    """Manifest fingerprint for a driver kind: the ACTIVE config (or an
    explicit ``Config`` — the dense driver carries its own). Mesh shape
    / device count are deliberately absent: resuming onto a degraded
    mesh is a supported path, a different protocol config is not."""
    from pos_evolution_tpu.config import cfg
    from pos_evolution_tpu.resilience import fingerprint_config
    return {"kind": kind,
            "cfg": fingerprint_config(cfg() if cfg_obj is None else cfg_obj)}


class RunSupervision:
    """Owns the resilience side-objects of one supervised run."""

    def __init__(self, spec, kind: str, telemetry=None, cfg_obj=None):
        from pos_evolution_tpu.resilience import AutoCheckpoint
        self.cfg = AutoCheckpoint.of(spec)
        digest = self.cfg.digest
        if digest == "auto":
            # merkle digests only pay off when the device path can take
            # them (jax backend active at gather time); otherwise they
            # are ~2x the hashing of a linear sha256 for nothing
            from pos_evolution_tpu.backend import get_backend
            digest = ("merkle"
                      if getattr(get_backend(), "name", "") == "jax"
                      else "sha256")
        self.manager = CheckpointManager(
            self.cfg.dir, retain=self.cfg.retain,
            async_mode=self.cfg.async_mode,
            fingerprint=run_fingerprint(kind, cfg_obj),
            digest=digest)
        self.heartbeat = None
        if self.cfg.heartbeat:
            from pos_evolution_tpu.utils.watchdog import Heartbeat
            self.heartbeat = Heartbeat(self.cfg.heartbeat)
        self.guard = (IntegrityGuard(self.cfg.guard_every)
                      if self.cfg.guard_every else None)
        self.telemetry = telemetry
        self.saves = 0
        # main-thread seconds spent in IN-LOOP saves only (the final
        # wait-for-durability save is end-of-run cost, not epoch-loop
        # overhead — the <10% budget is about the loop)
        self.loop_blocked_s = 0.0

    def _emit(self, type_: str, **fields) -> None:
        if self.telemetry is not None:
            self.telemetry.bus.emit(type_, **fields)
        else:
            from pos_evolution_tpu.telemetry import emit_global
            emit_global(type_, **fields)

    def tick(self, driver, slot: int, capture) -> None:
        """End-of-slot hook. ``capture()`` returns the payload for
        ``CheckpointManager.save`` (bytes, or a callable for
        serialize-in-background captures)."""
        if self.heartbeat is not None:
            self.heartbeat.beat(slot=slot)
        if self.guard is not None and self.guard.due(slot):
            findings = self.guard.check(driver)
            if findings:
                self._integrity_failure(slot, findings)
        if slot > 0 and slot % self.cfg.every_n_slots == 0:
            t0 = time.perf_counter()
            self.manager.save(slot, capture())
            blocked_s = time.perf_counter() - t0
            self.loop_blocked_s += blocked_s
            self.saves += 1
            self._emit("checkpoint_saved", slot=slot, step=slot,
                       async_mode=self.cfg.async_mode,
                       blocked_ms=round(blocked_s * 1e3, 3))

    def _integrity_failure(self, slot: int, findings: list[str]) -> None:
        """Corruption detected mid-run: record it, pull the NEWEST
        checkpoint out of the resume path (a checksum cannot see
        semantic rot — the step written closest to the detection is
        suspect), and die loudly so the supervisor rolls back to the
        last good step and replays."""
        self._emit("integrity_violation", slot=slot, findings=findings)
        self.manager.drain()  # an in-flight suspect step must land first
        steps = self.manager.steps()
        if steps:
            self.manager.quarantine(
                steps[-1],
                reason=f"integrity findings at slot {slot}: "
                       + "; ".join(findings)[:400])
        raise IntegrityError(findings)

    def finish(self, final_slot: int, capture) -> dict:
        """End-of-run: take one final checkpoint (the result must be as
        durable as any mid-run state), drain the writer, and return the
        manager's overhead stats for the goodput report."""
        self.manager.save(final_slot, capture(), wait=True)
        self.saves += 1
        self.manager.drain()
        stats = self.manager.stats()
        stats["loop_blocked_s"] = round(self.loop_blocked_s, 6)
        self._emit("checkpoint_final", slot=final_slot, **stats)
        return stats
