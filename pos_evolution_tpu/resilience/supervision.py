"""The supervision core: child-liveness policy, shared by every parent.

``resilience/supervisor.py`` (one child, run-to-completion) and
``serve/workers.py`` (a pool of long-lived serving processes) watch
children the same three ways — crash (waitpid), hang (a heartbeat file
that stops advancing), resource leak (RSS past a cap) — and answer
failures the same way (capped deterministic backoff, streak reset on
progress, loud refusal when the failure is systematic). This module IS
that shared policy, extracted so the two parents cannot drift:

- ``backoff_delay`` — the capped exponential with deterministic jitter
  (seeded per attempt: reproducible in tests, decorrelated in a fleet);
- ``heartbeat_age`` — the hang clock: how long since the child last
  proved liveness, honoring the attempt boundary (a beat left by a
  PREVIOUS incarnation is not this child's liveness — until this
  attempt beats, age is measured from its own launch);
- ``rss_kb`` — the leak sense, read from ``/proc/<pid>/status`` (0 when
  unreadable: a child we cannot measure is not thereby a leaker);
- ``RetryPolicy`` — the failure-streak state machine: ``record_failure``
  returns the backoff delay for the next attempt or ``None`` when the
  budget is exhausted (the caller refuses loudly), and progress between
  failures restarts the streak so a long run is not doomed by N
  spread-out crashes.
"""

from __future__ import annotations

import os
import random

from pos_evolution_tpu.utils.watchdog import read_heartbeat

__all__ = ["backoff_delay", "heartbeat_age", "rss_kb", "RetryPolicy"]


def backoff_delay(failures: int, base_s: float, cap_s: float,
                  jitter: float, seed: int) -> float:
    """Capped exponential backoff with deterministic jitter: attempt k
    after ``failures`` consecutive failures sleeps
    ``min(cap, base * 2**(failures-1)) * (1 + jitter * u)`` with
    ``u ~ U[0, 1)`` drawn from ``Random(seed, failures)``."""
    if failures <= 0:
        return 0.0
    u = random.Random((int(seed) << 16) ^ int(failures)).random()
    return min(cap_s, base_s * 2 ** (failures - 1)) * (1.0 + jitter * u)


def heartbeat_age(heartbeat_path: str | None, t0_unix: float,
                  started_s: float) -> float | None:
    """Seconds since the watched child last proved liveness, or None
    when no heartbeat is configured (the caller then has no hang sense).

    The attempt boundary rule (shared by ``supervise`` and the worker
    pool): a beat whose payload predates this attempt's launch
    (``t0_unix``) belongs to a previous incarnation, so the age is
    ``started_s`` — time since THIS child launched — not the stale
    file's age."""
    if heartbeat_path is None:
        return None
    hb = read_heartbeat(heartbeat_path)
    stale = hb is None or hb["payload"].get("unix", 0) < t0_unix
    return started_s if stale else hb["age_s"]


def rss_kb(pid: int) -> int:
    """Resident set size of ``pid`` in kB from ``/proc/<pid>/status``,
    0 when unreadable (dead pid, non-Linux): an unmeasurable child must
    never read as a leaker."""
    try:
        with open(f"/proc/{int(pid)}/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1])
    except (OSError, ValueError, IndexError):
        pass
    return 0


class RetryPolicy:
    """Failure-streak accounting + backoff schedule for one supervised
    child (or one worker slot — each slot owns its own policy).

    ``record_failure(progress=...)`` bumps the streak and returns the
    backoff delay before the next attempt, or ``None`` when
    ``max_failures`` consecutive failures are reached — the caller must
    then refuse loudly instead of thrashing. ``progress`` is any
    monotonic achievement marker (the heartbeat's slot, a request
    counter): when it advances past the best any attempt reached, the
    streak restarts at 1 — the failure is environmental, not systematic.
    """

    def __init__(self, max_failures: int = 3, backoff_s: float = 1.0,
                 backoff_cap_s: float = 30.0, jitter: float = 0.25,
                 seed: int = 0):
        self.max_failures = int(max_failures)
        self.backoff_s = float(backoff_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.jitter = float(jitter)
        self.seed = int(seed)
        self.failures = 0
        self.total_failures = 0
        self.best_progress = None
        self.backoff_total_s = 0.0

    def record_failure(self, progress=None) -> float | None:
        """One failed attempt. Returns the delay to sleep before the
        next attempt, or None when the retry budget is exhausted."""
        self.failures += 1
        self.total_failures += 1
        if progress is not None and (self.best_progress is None
                                     or progress > self.best_progress):
            if self.best_progress is not None:
                # advancing between failures = flaky environment, not a
                # systematic fault; restart the streak
                self.failures = 1
            self.best_progress = progress
        if self.failures >= self.max_failures:
            return None
        delay = backoff_delay(self.failures, self.backoff_s,
                              self.backoff_cap_s, self.jitter, self.seed)
        self.backoff_total_s += delay
        return delay

    def record_success(self) -> None:
        """A healthy attempt completed (or a worker proved sustained
        liveness): the streak is over."""
        self.failures = 0

    @property
    def exhausted(self) -> bool:
        return self.failures >= self.max_failures
