"""BLS12-381 pairing on TPU lanes (component N1) — batched ate pairing
and FastAggregateVerify over the dense tower (ops/tower.py).

The reference's signature surface is real pairing crypto in every
deployment: ``bls.Verify`` for deposits (pos-evolution.md:165), aggregate
attestation signatures over ``aggregation_bits`` (:714-717), sync
aggregates (:642). SURVEY.md §2.7 N1 and BASELINE config #3 demand a
batched pairing kernel. Correctness oracle: ``crypto/bls12_381.py``
(exact Python integers); every public function here is differential-
tested against it in ``tests/test_pairing_device.py``.

Design (TPU-first, no data-dependent control flow):

- **Miller loop on the twist.** The oracle untwists Q into Fq12 and runs
  generic Fq12 curve arithmetic with per-step inversions; here the loop
  state is a Jacobian point over Fq2 on the twist E'(Fq2) and the line
  function is evaluated *through* the untwist map algebraically:
  psi(x',y') = (x'/w^2, y'/w^3), so the tangent/chord line at P=(xp,yp)
  scaled by the Fq2 constant 2YZ^3 (resp. piZ) lands in the sparse
  subspace  c0 + cx*xp*w^2 + cy*yp*w^3  (slots (0,1,2,3,8,9) of the
  dense basis — the classic 014 sparsity in Fq6-pair terms). Each line
  is additionally scaled by w^3, and earlier lines are amplified by the
  subsequent Miller squarings, so the Miller value carries a
  loop-dependent factor w^(3M). Harmless: ord(w) divides 6(q^2-1)
  (w^6 = xi lies in Fq2*), and the full final-exponentiation exponent
  e = 3(q^12-1)/r = 3(q^6-1)(q^2+1)*h is a multiple of 6(q^2-1) since
  (q^2-1) | (q^6-1) and 2 | (q^2+1) — so w^(3M*e) = 1 for EVERY M, and
  the same divisibility kills every Fq2 line constant (such as the 2YZ^3
  / piZ scalings). No inversion anywhere in the loop.
- **Fixed schedule.** The loop runs over the static 63-bit tail of
  |t| = 0xd201000000010000 as a ``lax.scan``; the 5 addition steps are
  computed every iteration and masked in (compute-and-select, the jit
  idiom), the final conjugation implements t < 0.
- **Final exponentiation by the x-chain.** Easy part
  f^((q^6-1)(q^2+1)) via conjugation, one tower inversion and one
  Frobenius; hard part uses the exactly-verified identity
  3*(q^4-q^2+1)/r = (x-1)^2 * (x+q) * (x^2+q^2-1) + 3  (gcd(3, r) = 1,
  so the cubed pairing decides the same verification equations) — four
  64-bit pow-by-|x| scans, two Frobenius maps and a handful of
  multiplications; in the cyclotomic subgroup inversion is conjugation.
- **G1 aggregation as a masked reduction tree.** Aggregate pubkeys are
  summed with a unified, branch-free Jacobian add (compute the general
  sum, the doubling, and the infinity cases; select by predicate) over
  log2(lanes) tree levels — the aggregation shape of the reference's
  committees (pos-evolution.md:474-475).

Preconditions: points are decompressed, on-curve and subgroup-checked at
the host boundary (``g1_decompress``/``g2_decompress`` + subgroup checks
in the oracle/native code paths), mirroring how pyspec deployments gate
inputs before the pairing.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from pos_evolution_tpu.crypto import bls12_381 as oracle
from pos_evolution_tpu.ops import fp
from pos_evolution_tpu.ops.tower import (
    alg_eq,
    alg_mul,
    alg_one,
    alg_select,
    fq2_mul,
    fq2_muli,
    fq2_sq,
    fq12_conj,
    fq12_frob1,
    fq12_frob2,
    fq12_inv,
    fq12_mul,
    fq12_sq,
)

BLS_X = oracle.BLS_X                      # |t|; t is negative

# static bit schedules (MSB first)
_LOOP_BITS = np.array([b == "1" for b in bin(BLS_X)[3:]], dtype=bool)
_X_BITS = np.array([b == "1" for b in bin(BLS_X)[2:]], dtype=bool)
_XP1_BITS = np.array([b == "1" for b in bin(BLS_X + 1)[2:]], dtype=bool)

# line sparsity: (w^0, w^2, w^3) as Fq2 pairs in the dense-basis order
LINE_SLOTS = (0, 1, 2, 3, 8, 9)


# --- small helpers ------------------------------------------------------------


def _sel(pred, x, y):
    """Select full-precision values by a [...]-shaped predicate,
    broadcasting over any trailing structure axes."""
    extra = x.ndim - pred.ndim
    return jnp.where(pred.reshape(pred.shape + (1,) * extra), x, y)


def _fq2_scale_fq(c2, s):
    """Fq2 [..., 2, 32] times base-field scalar s [..., 32]."""
    return fp.modmul(c2, s[..., None, :])


def g2_neg(q):
    """Negate an affine twisted point [..., 2(xy), 2, 32]."""
    return jnp.concatenate([q[..., 0:1, :, :], fp.modneg(q[..., 1:2, :, :])],
                           axis=-3)


# --- encoders (host) ----------------------------------------------------------


def g1_affine_encode(p) -> np.ndarray:
    """Oracle G1 affine (ints) or None -> [2, 32] limbs (inf -> zeros;
    pair with an explicit inf mask)."""
    if p is None:
        return np.zeros((2, fp.L), dtype=np.int32)
    return np.stack([fp.to_limbs(p[0]), fp.to_limbs(p[1])])


def g2_affine_encode(q) -> np.ndarray:
    """Oracle G2 affine (Fq2 pair) or None -> [2, 2, 32] limbs."""
    if q is None:
        return np.zeros((2, 2, fp.L), dtype=np.int32)
    x, y = q
    return np.stack([
        np.stack([fp.to_limbs(x.a), fp.to_limbs(x.b)]),
        np.stack([fp.to_limbs(y.a), fp.to_limbs(y.b)]),
    ])


_G1_GEN = g1_affine_encode(oracle.G1_GEN)


# --- Miller loop --------------------------------------------------------------


def _line_embed(c0, cxp, cyp):
    """Pack the three Fq2 line coefficients into the sparse [..., 6, 32]
    operand for ``alg_mul(..., y_slots=LINE_SLOTS)``."""
    return jnp.concatenate([c0, cxp, cyp], axis=-2)


def miller_loop(p_aff: jax.Array, q_aff: jax.Array,
                inf: jax.Array | None = None) -> jax.Array:
    """Batched ate Miller loop: e-numerator for (P in G1, Q in E'(Fq2)).

    p_aff [..., 2, 32] (affine Fq coords), q_aff [..., 2, 2, 32]
    (affine twisted Fq2 coords), inf [...] optional mask marking pairs
    whose contribution must be one (either point at infinity).
    Returns f [..., 12, 32] (pre-final-exponentiation, scaled by an
    Fq2 constant per the module docstring).
    """
    xp, yp = p_aff[..., 0, :], p_aff[..., 1, :]
    xq, yq = q_aff[..., 0, :, :], q_aff[..., 1, :, :]
    batch = xp.shape[:-1]
    one12 = alg_one(12, batch)
    one2 = jnp.asarray(
        np.broadcast_to(np.stack([fp.ONE, fp.ZERO]), batch + (2, fp.L)))

    def body(carry, bit):
        f, X, Y, Z = carry
        # -- doubling step (a=0 Jacobian dbl-2009-l) + tangent line
        A = fq2_sq(X)
        B = fq2_sq(Y)
        C = fq2_sq(B)
        ZZ = fq2_sq(Z)
        D = fq2_muli(fp.modsub(fp.modsub(fq2_sq(fp.modadd(X, B)), A), C), 2)
        E = fq2_muli(A, 3)
        X3 = fp.modsub(fq2_sq(E), fq2_muli(D, 2))
        Y3 = fp.modsub(fq2_mul(E, fp.modsub(D, X3)), fq2_muli(C, 8))
        YZ = fq2_mul(Y, Z)
        Z3 = fq2_muli(YZ, 2)
        c0 = fp.modsub(fq2_muli(B, 2), fq2_muli(fq2_mul(X, A), 3))
        cx = fq2_muli(fq2_mul(A, ZZ), 3)
        cy = fp.modneg(fq2_muli(fq2_mul(YZ, ZZ), 2))
        line = _line_embed(c0, _fq2_scale_fq(cx, xp), _fq2_scale_fq(cy, yp))
        f = fq12_sq(f)
        f = alg_mul(f, line, y_slots=LINE_SLOTS)
        X, Y, Z = X3, Y3, Z3
        # -- mixed addition step (Q affine) + chord line, masked by bit
        ZZ = fq2_sq(Z)
        H = fp.modsub(fq2_mul(xq, ZZ), X)
        r = fp.modsub(fq2_mul(yq, fq2_mul(Z, ZZ)), Y)
        H2 = fq2_sq(H)
        H3 = fq2_mul(H, H2)
        V = fq2_mul(X, H2)
        X4 = fp.modsub(fp.modsub(fq2_sq(r), H3), fq2_muli(V, 2))
        Y4 = fp.modsub(fq2_mul(r, fp.modsub(V, X4)), fq2_mul(Y, H3))
        Z4 = fq2_mul(Z, H)
        c0 = fp.modsub(fq2_mul(Z4, yq), fq2_mul(r, xq))
        line = _line_embed(c0, _fq2_scale_fq(r, xp),
                           _fq2_scale_fq(fp.modneg(Z4), yp))
        f_add = alg_mul(f, line, y_slots=LINE_SLOTS)
        pred = jnp.broadcast_to(bit, batch)
        f = alg_select(pred, f_add, f)
        X = _sel(pred, X4, X)
        Y = _sel(pred, Y4, Y)
        Z = _sel(pred, Z4, Z)
        return (f, X, Y, Z), None

    (f, _, _, _), _ = jax.lax.scan(
        body, (one12, xq, yq, one2), jnp.asarray(_LOOP_BITS))
    f = fq12_conj(f)                       # t < 0
    if inf is not None:
        f = alg_select(inf, one12, f)
    return f


# --- final exponentiation -----------------------------------------------------


def _pow_bits(x, bits):
    """x^e over a static bit schedule for CYCLOTOMIC-subgroup x — every
    ladder input here is a power/Frobenius/conjugate of the easy-part
    output, so the Granger-Scott squaring applies (~3x cheaper per
    squaring than the dense ``fq12_sq``; ~250 squarings per pairing)."""
    from pos_evolution_tpu.ops.tower import fq12_pow_bits_cyclotomic
    return fq12_pow_bits_cyclotomic(x, bits)


def final_exponentiation(f: jax.Array) -> jax.Array:
    """f^(3 * (q^12-1)/r).  The cube (gcd(3, r) = 1) preserves every
    is-one verification decision and admits the inversion-free x-chain
    hard part (identity verified exactly in the test suite)."""
    # easy part: f^((q^6-1)(q^2+1)) — after this, inversion = conjugation
    f1 = fq12_mul(fq12_conj(f), fq12_inv(f))
    f2 = fq12_mul(fq12_frob2(f1), f1)
    # hard part: f2^((x-1)^2 * (x+q) * (x^2+q^2-1)) * f2^3
    a = _pow_bits(_pow_bits(f2, _XP1_BITS), _XP1_BITS)   # (x-1)^2 = (|x|+1)^2
    b = fq12_mul(fq12_conj(_pow_bits(a, _X_BITS)), fq12_frob1(a))  # ^(x+q)
    c = fq12_mul(fq12_mul(_pow_bits(_pow_bits(b, _X_BITS), _X_BITS),
                          fq12_frob2(b)),
                 fq12_conj(b))                            # ^(x^2+q^2-1)
    return fq12_mul(fq12_mul(fq12_sq(f2), f2), c)


def pairing(p_aff, q_aff, inf=None):
    """Full batched pairing e(P, Q)^3 in canonical dense-Fq12 form."""
    return final_exponentiation(miller_loop(p_aff, q_aff, inf))


# --- G1 arithmetic (pubkey aggregation) ---------------------------------------


def g1_double_jac(P):
    """a=0 Jacobian doubling; P [..., 3, 32]."""
    X, Y, Z = P[..., 0, :], P[..., 1, :], P[..., 2, :]
    A = fp.modmul(X, X)
    B = fp.modmul(Y, Y)
    C = fp.modmul(B, B)
    t = fp.modadd(X, B)
    D = _dbl(fp.modsub(fp.modsub(fp.modmul(t, t), A), C))
    E = fp.modadd(fp.modadd(A, A), A)
    X3 = fp.modsub(fp.modmul(E, E), _dbl(D))
    Y3 = fp.modsub(fp.modmul(E, fp.modsub(D, X3)), _mul8(C))
    Z3 = _dbl(fp.modmul(Y, Z))
    return jnp.stack([X3, Y3, Z3], axis=-2)


def _dbl(x):
    return fp.modadd(x, x)


def _mul8(x):
    return _dbl(_dbl(_dbl(x)))


def g1_add_jac(P, Q):
    """Unified branch-free Jacobian add: handles either operand at
    infinity (Z = 0), P == Q (doubling) and P == -Q (infinity) by
    computing every case and selecting."""
    X1, Y1, Z1 = P[..., 0, :], P[..., 1, :], P[..., 2, :]
    X2, Y2, Z2 = Q[..., 0, :], Q[..., 1, :], Q[..., 2, :]
    Z1Z1 = fp.modmul(Z1, Z1)
    Z2Z2 = fp.modmul(Z2, Z2)
    U1 = fp.modmul(X1, Z2Z2)
    U2 = fp.modmul(X2, Z1Z1)
    S1 = fp.modmul(Y1, fp.modmul(Z2, Z2Z2))
    S2 = fp.modmul(Y2, fp.modmul(Z1, Z1Z1))
    H = fp.modsub(U2, U1)
    r = fp.modsub(S2, S1)
    H2 = fp.modmul(H, H)
    H3 = fp.modmul(H, H2)
    V = fp.modmul(U1, H2)
    X3 = fp.modsub(fp.modsub(fp.modmul(r, r), H3), _dbl(V))
    Y3 = fp.modsub(fp.modmul(r, fp.modsub(V, X3)), fp.modmul(S1, H3))
    Z3 = fp.modmul(H, fp.modmul(Z1, Z2))
    gen = jnp.stack([X3, Y3, Z3], axis=-2)

    p_inf = fp.is_zero(Z1)
    q_inf = fp.is_zero(Z2)
    same_x = fp.is_zero(H) & ~p_inf & ~q_inf
    same_y = fp.is_zero(r)
    out = _sel(same_x & same_y, g1_double_jac(P), gen)
    out = _sel(same_x & ~same_y, jnp.zeros_like(out), out)   # P + (-P)
    out = _sel(p_inf, Q, out)
    out = _sel(q_inf & ~p_inf, P, out)
    return out


def g1_sum_masked(points: jax.Array, mask: jax.Array) -> jax.Array:
    """Masked sum of affine points: points [..., C, 2, 32], mask
    [..., C] -> Jacobian [..., 3, 32]. Unset lanes become infinity; a
    log2(C) unified-add tree does the reduction (the committee
    aggregation shape, pos-evolution.md:474-475)."""
    c = points.shape[-3]
    k = 1
    while k < c:
        k *= 2
    z = _sel(mask, jnp.broadcast_to(jnp.asarray(np.asarray(fp.ONE)),
                                    points.shape[:-3] + (c, fp.L)),
             jnp.zeros(points.shape[:-3] + (c, fp.L), jnp.int32))
    jac = jnp.concatenate([points, z[..., None, :]], axis=-2)
    if k != c:
        pad = jnp.zeros(points.shape[:-3] + (k - c, 3, fp.L), jnp.int32)
        jac = jnp.concatenate([jac, pad], axis=-3)
    while k > 1:
        k //= 2
        jac = g1_add_jac(jac[..., :k, :, :], jac[..., k:, :, :])
    return jac[..., 0, :, :]


def g1_to_affine(P):
    """Jacobian -> (affine [..., 2, 32], inf mask [...])."""
    X, Y, Z = P[..., 0, :], P[..., 1, :], P[..., 2, :]
    zi = fp.modinv(fp.canon(Z))
    zi2 = fp.modmul(zi, zi)
    x = fp.modmul(X, zi2)
    y = fp.modmul(Y, fp.modmul(zi, zi2))
    return jnp.stack([x, y], axis=-2), fp.is_zero(Z)


# --- FastAggregateVerify ------------------------------------------------------


def fast_aggregate_verify_batch(pk_table: jax.Array,
                                committees: jax.Array,
                                bits: jax.Array,
                                msg_g2: jax.Array,
                                sig_g2: jax.Array,
                                sig_inf: jax.Array) -> jax.Array:
    """Batched real-BLS FastAggregateVerify (pos-evolution.md:714-717).

    pk_table   [N, 2, 32]      affine G1 pubkeys (host-decompressed)
    committees [B, C] int32    validator index per lane
    bits       [B, C] bool     aggregation bitlist
    msg_g2     [B, 2, 2, 32]   hashed messages on the twist (host N1 map)
    sig_g2     [B, 2, 2, 32]   decompressed aggregate signatures
    sig_inf    [B]     bool    signature-at-infinity flags
    Returns bool[B]: e(sum pk, H(m)) == e(g1, sig), False for empty
    aggregates / infinity signatures (oracle semantics).

    The batch must be exactly 1-D: the pk-vs-H(m) and g1-vs-sig pairings
    ride one doubled Miller scan concatenated on axis 0, so higher-rank
    batches would silently interleave pairings. Reshape to [B, ...]
    first; the check below makes a mis-shaped call fail loudly.
    """
    if committees.ndim != 2:
        raise ValueError(
            "fast_aggregate_verify_batch requires a 1-D batch "
            f"(committees [B, C]); got committees shape {committees.shape}")
    pks = pk_table[committees]                     # [B, C, 2, 32]
    agg = g1_sum_masked(pks, bits)
    pk_aff, pk_inf = g1_to_affine(agg)
    # one Miller scan over the doubled batch (pk vs H(m), g1 vs -sig)
    # instead of two separately traced 63-iteration loops
    g1s = jnp.concatenate(
        [pk_aff, jnp.asarray(np.broadcast_to(_G1_GEN, pk_aff.shape))], axis=0)
    g2s = jnp.concatenate([msg_g2, g2_neg(sig_g2)], axis=0)
    infs = jnp.concatenate([pk_inf, sig_inf], axis=0)
    fs = miller_loop(g1s, g2s, infs)
    b = pk_aff.shape[0]
    f = fq12_mul(fs[:b], fs[b:])
    ok = alg_eq(final_exponentiation(f), alg_one(12, f.shape[:-2]))
    return ok & ~pk_inf & ~sig_inf


# --- G1 multi-scalar multiply (kzg commit path) -------------------------------


from functools import lru_cache  # noqa: E402


@lru_cache(maxsize=8)
def _g1_msm_kernel(n: int):
    """Jitted fixed-shape MSM: every SRS power runs its own 255-step
    double-and-add lane in parallel ([n] lanes x [32] limbs — the
    batched-lane shape every kernel here uses), then a second scan
    folds the lanes sequentially into one point. Traced once per
    domain size n (lru_cache — the PEV no-fresh-jit-per-call rule).

    Both reductions are lax.scans on purpose: the unified Jacobian add
    costs XLA ~80 s of CPU compile PER INSTANCE, so a log-depth
    unrolled lane tree (6 more instances at n=64) blows the one-time
    compile past 10 minutes.  Two scan bodies keep it to one
    double+add instance and one fold-add instance (~4 min cold, cached
    for the process); the n-step sequential fold is runtime noise next
    to the 255-step bit scan."""

    @jax.jit
    def kernel(points, inf, bits):
        # affine -> per-lane Jacobian addend: Z = 1, or 0 for infinity
        # lanes so the unified add treats them as the identity
        one = jnp.broadcast_to(jnp.asarray(np.asarray(fp.ONE)), (n, fp.L))
        z = _sel(~inf, one, jnp.zeros((n, fp.L), jnp.int32))
        pj = jnp.concatenate([points, z[:, None, :]], axis=-2)

        def step(acc, bit_col):
            acc = g1_double_jac(acc)
            cand = g1_add_jac(acc, pj)
            return _sel(bit_col, cand, acc), None

        acc0 = jnp.zeros((n, 3, fp.L), jnp.int32)
        lanes, _ = jax.lax.scan(step, acc0, bits)

        def fold(acc, lane):
            return g1_add_jac(acc, lane[None]), None

        total, _ = jax.lax.scan(fold, lanes[:1], lanes[1:])
        aff, is_inf = g1_to_affine(total[0])
        return fp.canon(aff), is_inf

    return kernel


def g1_msm_device_entry(setup, coeffs):
    """Backend entry for ``kzg/scheme.py``'s commitment MSM:
    sum_j coeffs[j] * setup.powers_g1[j] on device, returned as oracle
    affine ints (or None) — bit-identical to the host Pippenger path
    (``kzg/curve.py:g1_lincomb``), which tests pin on random blobs."""
    scalars = [int(s) % oracle.R for s in coeffs]
    n = len(scalars)
    if n == 0 or n > setup.n:
        raise ValueError(f"msm size {n} vs setup of {setup.n} powers")
    enc, inf = setup.device_encoding()
    nbits = oracle.R.bit_length()                  # 255, MSB first
    bits = np.zeros((nbits, n), dtype=bool)
    for j, s in enumerate(scalars):
        for i in range(nbits):
            if (s >> (nbits - 1 - i)) & 1:
                bits[i, j] = True
    aff, is_inf = _g1_msm_kernel(n)(
        jnp.asarray(enc[:n]), jnp.asarray(inf[:n]), jnp.asarray(bits))
    if bool(is_inf):
        return None
    a = np.asarray(aff)
    return (fp.from_limbs(a[0]), fp.from_limbs(a[1]))
