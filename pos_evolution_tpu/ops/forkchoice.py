"""Dense fork choice on device (north-star config #1).

The spec ``get_head`` (pos-evolution.md:1102-1116) recomputes
``get_latest_attesting_balance`` per fork per child — O(branches x messages x
depth). The array level computes ALL subtree weights in one pass
(SURVEY.md §3.2 "TPU mapping"):

1. per-validator latest messages -> per-block vote weight via
   ``segment_sum`` over the registry (equivocators/inactive masked out,
   pos-evolution.md:1438);
2. a boolean reachability matrix R (R[i,j] = j is i or an ancestor of i)
   built by log2(B) boolean matrix squarings — MXU-friendly matmuls;
3. subtree weights = R^T @ votes (+ proposer boost on the boosted block's
   ancestor row, pos-evolution.md:916, 1355);
4. viable-branch filtering (pos-evolution.md:874-880): keep blocks with a
   viable leaf descendant, computed from the same R;
5. greedy descent as a ``lax.while_loop`` with exact (weight,
   lexicographic-rank) tie-breaking (pos-evolution.md:1114-1116).

The fixed-capacity layout (blocks padded to ``capacity``) keeps every shape
static for XLA. Blocks arrive in topological order so parent index < child
index always holds.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402


class DenseStore(NamedTuple):
    """Fixed-capacity array image of the fork-choice Store (pos-evolution.md
    :889-901): the dict-shaped store becomes parent-index arrays + a latest-
    message table."""

    parent: jax.Array          # int32[B]; -1 for the anchor root
    slot: jax.Array            # int32[B]
    rank: jax.Array            # int32[B] lexicographic rank of the block root
    real: jax.Array            # bool[B] slot occupied
    leaf_viable: jax.Array     # bool[B] leaf carries store's justified/finalized view
    justified_idx: jax.Array   # int32 scalar: descent start
    # latest-message table over validators
    msg_block: jax.Array       # int32[N]; -1 = no message
    msg_epoch: jax.Array       # int64[N]
    weight: jax.Array          # int64[N] effective balance, 0 if masked out
    boost_idx: jax.Array       # int32 scalar; -1 = no boost
    boost_amount: jax.Array    # int64 scalar


def _reachability(parent, real, capacity: int):
    """R[i, j] = block j is i or an ancestor of i (within real blocks).

    Boolean matrix squaring as f32 matmuls: path counts per entry are
    bounded by ``capacity`` (< 2^24), so f32 accumulation is exact and the
    squarings run on the MXU (s64 dots are not TPU-lowerable).
    """
    eye = jnp.eye(capacity, dtype=bool)
    has_parent = (parent >= 0) & real
    p = jnp.where(has_parent, parent, 0)
    step = jnp.zeros((capacity, capacity), dtype=bool)
    step = step.at[jnp.arange(capacity), p].set(has_parent)
    r = eye | step
    hops = max(int(np.ceil(np.log2(max(capacity, 2)))), 1)
    for _ in range(hops):
        rf = r.astype(jnp.float32)
        r = jnp.dot(rf, rf, preferred_element_type=jnp.float32) > 0.5
    return r


def _exact_matvec_i64(r_bool, values_i64, capacity: int):
    """Exact Σ_i R[i,j] * v[i] for int64 increment counts via hi/lo-split
    f32 matmuls (both halves stay < 2^24 per output, so f32 is exact)."""
    lo = (values_i64 & np.int64(0xFFF)).astype(jnp.float32)
    hi = (values_i64 >> np.int64(12)).astype(jnp.float32)
    rf = r_bool.astype(jnp.float32)
    lo_sum = jnp.dot(rf.T, lo, preferred_element_type=jnp.float32)
    hi_sum = jnp.dot(rf.T, hi, preferred_element_type=jnp.float32)
    return hi_sum.astype(jnp.int64) * np.int64(4096) + lo_sum.astype(jnp.int64)


@partial(jax.jit, static_argnames=("capacity", "increment"))
def head_and_weights(store: DenseStore, capacity: int,
                     increment: int = 10**9,
                     min_vote_epoch=None):
    """Returns (head_idx, subtree_weights[B] in Gwei) — one fused pass.

    Effective balances are always multiples of ``increment`` (hysteresis,
    pos-evolution.md:122-133), so subtree sums run as exact hi/lo-split f32
    matmuls over increment counts; the (not increment-aligned) proposer
    boost is added afterwards in int64.

    ``min_vote_epoch`` applies the RLMD-GHOST vote-expiry window
    (pos-evolution.md:1585, 1596): latest messages with target epoch below
    it carry no weight (eta = window size; None = LMD's eta = inf; the
    Goldfish limit keeps only the most recent slot's votes).
    """
    votes_valid = store.msg_block >= 0
    if min_vote_epoch is not None:
        votes_valid = votes_valid & (store.msg_epoch >= min_vote_epoch)
    seg_ids = jnp.where(votes_valid, store.msg_block, capacity)
    vote_weight = jax.ops.segment_sum(
        jnp.where(votes_valid, store.weight, 0), seg_ids,
        num_segments=capacity + 1)[:capacity]

    r = _reachability(store.parent, store.real, capacity)

    vote_incr = vote_weight // np.int64(increment)
    subtree = _exact_matvec_i64(r, vote_incr, capacity) * np.int64(increment)
    # proposer boost rides the boosted block's ancestor chain
    has_boost = store.boost_idx >= 0
    boost_row = jnp.where(
        has_boost,
        r[jnp.maximum(store.boost_idx, 0)],
        jnp.zeros(capacity, dtype=bool))
    subtree = subtree + boost_row.astype(jnp.int64) * store.boost_amount

    # viable-branch filter: block kept iff some viable leaf descends from it
    is_parent = jnp.zeros(capacity, dtype=bool).at[
        jnp.where(store.parent >= 0, store.parent, 0)].max(
        (store.parent >= 0) & store.real)
    leaf = store.real & ~is_parent
    ok_leaf = leaf & store.leaf_viable
    keep = jnp.dot(r.astype(jnp.float32).T, ok_leaf.astype(jnp.float32),
                   preferred_element_type=jnp.float32) > 0.5

    def descend(carry):
        head, _ = carry
        children = (store.parent == head) & keep & store.real
        any_child = children.any()
        w = jnp.where(children, subtree, -1)
        best_w = w.max()
        # exact (weight, lexicographic root) tie-break
        rank_key = jnp.where(children & (w == best_w), store.rank, -1)
        best = jnp.argmax(rank_key).astype(jnp.int32)
        new_head = jnp.where(any_child, best, head)
        return new_head, any_child

    def cond(carry):
        return carry[1]

    head0 = store.justified_idx
    children0 = (store.parent == head0) & keep & store.real
    head, _ = jax.lax.while_loop(cond, descend, (head0, children0.any()))
    return head, subtree


# --- host-side densification --------------------------------------------------

def build_dense_store(store, capacity: int | None = None):
    """Build a DenseStore from a spec-level Store (host side).

    Returns (dense, roots) where roots[i] is the block root at index i.
    """
    from pos_evolution_tpu.specs.forkchoice import (
        _leaf_is_viable, get_current_slot, get_proposer_boost,
    )
    from pos_evolution_tpu.specs.helpers import compute_epoch_at_slot

    roots = list(store.blocks.keys())  # insertion = topological order
    b = len(roots)
    if capacity is None:
        capacity = max(int(2 ** np.ceil(np.log2(max(b, 2)))), 2)
    index_of = {r: i for i, r in enumerate(roots)}
    rank = np.argsort(np.argsort(np.array([r for r in roots], dtype=object)))

    parent = np.full(capacity, -1, dtype=np.int32)
    slot = np.zeros(capacity, dtype=np.int32)
    real = np.zeros(capacity, dtype=bool)
    leaf_viable = np.zeros(capacity, dtype=bool)
    rank_arr = np.zeros(capacity, dtype=np.int32)
    rank_arr[:b] = rank

    jc = store.justified_checkpoint
    for i, root in enumerate(roots):
        block = store.blocks[root]
        real[i] = True
        slot[i] = int(block.slot)
        pr = bytes(block.parent_root)
        parent[i] = index_of.get(pr, -1)
        # same voting-source viability rule as the spec layer
        leaf_viable[i] = _leaf_is_viable(store, root)

    justified_state = store.checkpoint_states[jc.as_key()]
    n = len(justified_state.validators)
    reg = justified_state.validators
    current_epoch = compute_epoch_at_slot(get_current_slot(store))
    active = ((reg.activation_epoch <= np.uint64(current_epoch))
              & (np.uint64(current_epoch) < reg.exit_epoch))

    msg_block = np.full(n, -1, dtype=np.int32)
    msg_epoch = np.zeros(n, dtype=np.int64)
    weight = np.zeros(n, dtype=np.int64)
    for v, message in store.latest_messages.items():
        if v >= n or v in store.equivocating_indices:
            continue
        idx = index_of.get(message.root)
        if idx is None:
            continue
        msg_block[v] = idx
        msg_epoch[v] = message.epoch
    valid = (msg_block >= 0) & active & ~reg.slashed
    weight[valid] = reg.effective_balance[valid].astype(np.int64)
    msg_block[~valid] = -1

    boost_idx = index_of.get(bytes(store.proposer_boost_root), -1) \
        if store.proposer_boost_root != b"\x00" * 32 else -1
    boost_amount = get_proposer_boost(store) if boost_idx >= 0 else 0

    dense = DenseStore(
        parent=jnp.asarray(parent),
        slot=jnp.asarray(slot),
        rank=jnp.asarray(rank_arr),
        real=jnp.asarray(real),
        leaf_viable=jnp.asarray(leaf_viable),
        justified_idx=jnp.int32(index_of[bytes(jc.root)]),
        msg_block=jnp.asarray(msg_block),
        msg_epoch=jnp.asarray(msg_epoch),
        weight=jnp.asarray(weight),
        boost_idx=jnp.int32(boost_idx),
        boost_amount=jnp.int64(boost_amount),
    )
    return dense, roots, capacity


def get_head_dense(store) -> bytes:
    """Drop-in accelerated get_head for a spec-level Store."""
    dense, roots, capacity = build_dense_store(store)
    head_idx, _ = head_and_weights(dense, capacity)
    return roots[int(head_idx)]
