"""Dense fork choice on device (north-star config #1).

The spec ``get_head`` (pos-evolution.md:1102-1116) recomputes
``get_latest_attesting_balance`` per fork per child — O(branches x messages x
depth). The array level computes ALL subtree weights in one pass
(SURVEY.md §3.2 "TPU mapping"):

1. per-validator latest messages -> per-block vote weight via
   ``segment_sum`` over the registry (equivocators/inactive masked out,
   pos-evolution.md:1438) — or, on the persistent/incremental path, a
   resident per-block bucket table updated by scatter deltas as messages
   arrive, so head queries never rescan the registry;
2. subtree weights by **binary-lifting accumulation** over the parent-index
   array: log2(B) rounds of ``segment_sum`` into 2^k-th-ancestor buckets.
   Round k folds every node's partial subtree sum (descendants at depth
   < 2^k) into its 2^k-th ancestor, then composes the ancestor pointers
   (anc <- anc[anc]); after ceil(log2(B)) rounds each node holds its full
   subtree sum. O(B log B) work, no B x B matrix — scales to capacity
   1024+ where the round-1 reachability-matrix design was O(B^2) memory
   and tripped XLA's algebraic-simplifier loop detector;
3. proposer boost and the viable-branch filter (pos-evolution.md:874-880)
   ride the same lifted pass as extra columns (one-hot of the boosted
   block; viable-leaf indicators) — ancestors-or-self of the boost block
   and blocks-with-viable-leaf-descendants drop out of the identical
   recursion;
4. greedy descent as a ``lax.while_loop`` with exact (weight,
   lexicographic-rank) tie-breaking (pos-evolution.md:1114-1116).

The fixed-capacity layout (blocks padded to ``capacity``) keeps every shape
static for XLA. Blocks arrive in topological order so parent index < child
index always holds.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import numpy as np

import jax

from pos_evolution_tpu.backend.jax_init import ensure_x64
ensure_x64()

import jax.numpy as jnp  # noqa: E402


class DenseStore(NamedTuple):
    """Fixed-capacity array image of the fork-choice Store (pos-evolution.md
    :889-901): the dict-shaped store becomes parent-index arrays + a latest-
    message table."""

    parent: jax.Array          # int32[B]; -1 for the anchor root
    slot: jax.Array            # int32[B]
    rank: jax.Array            # int32[B] lexicographic rank of the block root
    real: jax.Array            # bool[B] slot occupied
    leaf_viable: jax.Array     # bool[B] leaf carries store's justified/finalized view
    justified_idx: jax.Array   # int32 scalar: descent start
    # latest-message table over validators
    msg_block: jax.Array       # int32[N]; -1 = no message
    msg_epoch: jax.Array       # int64[N]
    weight: jax.Array          # int64[N] effective balance, 0 if masked out
    boost_idx: jax.Array       # int32 scalar; -1 = no boost
    boost_amount: jax.Array    # int64 scalar


def _subtree_accumulate(parent, real, values, capacity: int):
    """Per-node subtree sums over a parent-index forest by binary lifting.

    ``values`` is ``[B]`` or ``[B, C]`` (any summable dtype). Returns the
    same shape where entry j = Σ values[i] over every i in j's subtree
    (including j). Round k folds each node's partial sum (its descendants
    at depth < 2^k) into its 2^k-th ancestor via ``segment_sum``, then
    squares the ancestor pointer (anc <- anc[anc]); ceil(log2(B)) rounds
    cover the maximum possible depth. Padded/unreal slots point at a null
    bucket and never contribute.
    """
    null = capacity
    anc = jnp.where((parent >= 0) & real, parent, null).astype(jnp.int32)
    w = values
    hops = max(int(np.ceil(np.log2(max(capacity, 2)))), 1)
    for _ in range(hops):
        w = w + jax.ops.segment_sum(w, anc, num_segments=capacity + 1)[:capacity]
        anc_ext = jnp.concatenate([anc, jnp.full((1,), null, jnp.int32)])
        anc = anc_ext[anc]
    return w


def _descend(parent, real, rank, keep, subtree, justified_idx):
    """Greedy HLMD-GHOST descent with exact (weight, lexicographic-rank)
    tie-break (pos-evolution.md:1114-1116)."""

    def descend(carry):
        head, _ = carry
        children = (parent == head) & keep & real
        any_child = children.any()
        w = jnp.where(children, subtree, -1)
        best_w = w.max()
        rank_key = jnp.where(children & (w == best_w), rank, -1)
        best = jnp.argmax(rank_key).astype(jnp.int32)
        new_head = jnp.where(any_child, best, head)
        return new_head, any_child

    def cond(carry):
        return carry[1]

    head0 = justified_idx
    children0 = (parent == head0) & keep & real
    head, _ = jax.lax.while_loop(cond, descend, (head0, children0.any()))
    return head


def _head_from_buckets(parent, real, rank, leaf_viable, justified_idx,
                       vote_weight, boost_idx, boost_amount, capacity: int):
    """Shared core: per-block vote buckets -> (head, subtree weights).

    One lifted pass carries three columns: vote weight, a one-hot of the
    boosted block (its accumulation marks exactly the boost block's
    ancestors-or-self, pos-evolution.md:916, 1355), and viable-leaf
    indicators (their accumulation marks blocks with a viable leaf
    descendant — the filtered block tree, pos-evolution.md:874-880).
    """
    has_boost = boost_idx >= 0
    boost_onehot = (
        (jnp.arange(capacity, dtype=jnp.int32) == boost_idx) & has_boost
    ).astype(jnp.int64)

    is_parent = jnp.zeros(capacity, dtype=bool).at[
        jnp.where(parent >= 0, parent, 0)].max((parent >= 0) & real)
    leaf = real & ~is_parent
    ok_leaf = (leaf & leaf_viable).astype(jnp.int64)

    cols = jnp.stack([vote_weight, boost_onehot, ok_leaf], axis=1)
    acc = _subtree_accumulate(parent, real, cols, capacity)
    subtree = acc[:, 0] + acc[:, 1] * boost_amount
    keep = acc[:, 2] > 0

    head = _descend(parent, real, rank, keep, subtree, justified_idx)
    return head, subtree


@partial(jax.jit, static_argnames=("capacity",))
def head_and_weights(store: DenseStore, capacity: int,
                     min_vote_epoch=None):
    """Returns (head_idx, subtree_weights[B] in Gwei) — one fused pass.

    Scans the full latest-message table (O(N) ``segment_sum``) then runs
    the O(B log B) lifted tree pass. For repeated head queries between
    small message deltas, use the incremental bucket path
    (``apply_latest_messages`` + ``head_from_buckets``) instead.

    ``min_vote_epoch`` applies the RLMD-GHOST vote-expiry window
    (pos-evolution.md:1585, 1596): latest messages with target epoch below
    it carry no weight (eta = window size; None = LMD's eta = inf; the
    Goldfish limit keeps only the most recent slot's votes).
    """
    votes_valid = store.msg_block >= 0
    if min_vote_epoch is not None:
        votes_valid = votes_valid & (store.msg_epoch >= min_vote_epoch)
    seg_ids = jnp.where(votes_valid, store.msg_block, capacity)
    vote_weight = jax.ops.segment_sum(
        jnp.where(votes_valid, store.weight, 0), seg_ids,
        num_segments=capacity + 1)[:capacity]

    return _head_from_buckets(
        store.parent, store.real, store.rank, store.leaf_viable,
        store.justified_idx, vote_weight, store.boost_idx,
        store.boost_amount, capacity)


@partial(jax.jit, static_argnames=("capacity",))
def head_from_buckets(parent, real, rank, leaf_viable, justified_idx,
                      vote_weight, boost_idx, boost_amount, capacity: int):
    """Head query from resident per-block vote buckets: O(B log B), no
    registry scan — the fast path for per-slot ``get_head`` on a
    persistent device store (pos-evolution.md:298,762 run this on every
    propose/attest decision).

    LMD-only (eta = inf): buckets destroy per-vote epochs, so RLMD/
    Goldfish expiry windows (pos-evolution.md:1585) cannot be applied
    here — windowed variants use ``head_and_weights`` with
    ``min_vote_epoch``, which rescans the message table."""
    return _head_from_buckets(parent, real, rank, leaf_viable, justified_idx,
                              vote_weight, boost_idx, boost_amount, capacity)


def _vote_landing(msg_block, msg_epoch, val_idx, new_block, new_epoch,
                  active):
    """Shared landing predicate for the incremental vote kernels: which
    batch entries update the LMD table (pos-evolution.md:1435-1441),
    including the in-batch dedup tournament for duplicate ``val_idx`` —
    the first entry carrying the maximum target epoch among entries that
    could land at all wins (later equal-epoch votes would not land
    against it, :1440; inactive or padded entries never land
    sequentially, so they must not knock out a live lower-epoch vote
    either). Returns (lands, old_block, old_epoch)."""
    old_block = msg_block[val_idx]
    old_epoch = msg_epoch[val_idx]
    lands = (active & (new_block >= 0)
             & ((old_block < 0) | (new_epoch > old_epoch)))
    k = val_idx.shape[0]
    pos = jnp.arange(k, dtype=jnp.int64)
    key = new_epoch.astype(jnp.int64) * (2 * k) + (k - pos)
    competitor = active & (new_block >= 0)
    same = (val_idx[:, None] == val_idx[None, :]) & ~jnp.eye(k, dtype=bool)
    loses = (same & (key[None, :] > key[:, None]) & competitor[None, :]).any(axis=1)
    return lands & ~loses, old_block, old_epoch


@jax.jit
def apply_latest_messages(msg_block, msg_epoch, vote_weight,
                          val_idx, new_block, new_epoch, weight, active):
    """Incremental LMD table update (pos-evolution.md:1435-1441) on device.

    Batched: ``val_idx[K]`` validators vote for ``new_block[K]`` with
    target ``new_epoch[K]``. A vote lands if the validator has no current
    latest message or its target epoch exceeds it (:1440), and the
    validator is ``active`` (not equivocating/slashed — equivocation
    discounting, :1438; use ``remove_latest_messages`` to discount a
    validator whose vote already landed). Returns updated (msg_block,
    msg_epoch, vote_weight) with the per-block buckets adjusted by
    scatter deltas: O(K) instead of the O(N) rescan. Duplicate
    ``val_idx`` entries in one batch are deduplicated in-kernel (an O(K^2)
    pairwise tournament — highest target epoch wins, earliest batch
    position on ties, matching sequential application); batches are
    per-slot deliveries, so K stays far below the registry size.
    ``weight`` must stay consistent with what previously landed for the
    same validator — on effective-balance changes (epoch boundaries) call
    ``rebuild_buckets``.
    """
    lands, old_block, old_epoch = _vote_landing(
        msg_block, msg_epoch, val_idx, new_block, new_epoch, active)

    nb = vote_weight.shape[0]
    # subtract old weight where a previous message existed
    sub_seg = jnp.where(lands & (old_block >= 0), old_block, nb)
    add_seg = jnp.where(lands, new_block, nb)
    w = weight.astype(vote_weight.dtype)
    vote_weight = vote_weight.at[sub_seg].add(
        -jnp.where(lands & (old_block >= 0), w, 0), mode="drop")
    vote_weight = vote_weight.at[add_seg].add(
        jnp.where(lands, w, 0), mode="drop")

    # Non-landing entries must not write at all (a write-back of the old
    # value could race a duplicate winner's write under scatter ordering):
    # route them to an out-of-range slot and drop.
    tgt = jnp.where(lands, val_idx, msg_block.shape[0])
    msg_block = msg_block.at[tgt].set(new_block, mode="drop")
    msg_epoch = msg_epoch.at[tgt].set(new_epoch, mode="drop")
    return msg_block, msg_epoch, vote_weight


@partial(jax.jit, static_argnames=("capacity",))
def rebuild_buckets(msg_block, weight, capacity: int):
    """Wholesale per-block vote-bucket rebuild: one O(N) ``segment_sum``
    over the resident message table. The epoch-boundary hook — effective
    balances change only at epoch processing (pos-evolution.md:122-133),
    so callers refresh ``weight`` then rebuild here instead of trusting
    incremental deltas across a balance change (the
    ``apply_latest_messages`` weight-consistency contract)."""
    seg = jnp.where(msg_block >= 0, msg_block, capacity)
    return jax.ops.segment_sum(
        jnp.where(msg_block >= 0, weight.astype(jnp.int64), 0), seg,
        num_segments=capacity + 1)[:capacity]


@jax.jit
def remove_latest_messages(msg_block, msg_epoch, vote_weight, val_idx, weight):
    """Discount validators whose vote already landed — the incremental
    form of dropping ``store.equivocating_indices`` from LMD weight
    (pos-evolution.md:1438, 1447-1461): subtract their bucketed weight
    and clear their table entries so no future vote from them lands via
    the normal path (callers also mark them inactive).

    ``weight`` must match what landed for each validator (the effective
    balance used at ``apply_latest_messages`` time)."""
    old_block = msg_block[val_idx]
    had = old_block >= 0
    nb = vote_weight.shape[0]
    sub_seg = jnp.where(had, old_block, nb)
    vote_weight = vote_weight.at[sub_seg].add(
        -jnp.where(had, weight.astype(vote_weight.dtype), 0), mode="drop")
    msg_block = msg_block.at[val_idx].set(-1)
    msg_epoch = msg_epoch.at[val_idx].set(0)
    return msg_block, msg_epoch, vote_weight


# --- epoch-windowed buckets: incremental heads for expiry variants ------------
#
# RLMD-GHOST weighs only latest messages from the last eta epochs
# (pos-evolution.md:1581-1609; eta = 1 recovers Goldfish's GHOST-Eph
# :1549, eta = inf recovers LMD). Flat buckets destroy per-vote epochs,
# so expiry variants previously had to rescan the registry per head
# query. These kernels keep per-(block, recent-epoch) weight columns —
# window W is a small static bound on eta — making the expiry-windowed
# head as incremental as the LMD one. Columns are indexed relative to a
# resident ``base_epoch``; sliding the window = the epoch-boundary
# rebuild that the bucket contract already mandates for balance changes.


@partial(jax.jit, static_argnames=("capacity", "window"))
def rebuild_epoch_buckets(msg_block, msg_epoch, weight, capacity: int,
                          window: int, base_epoch):
    """[capacity, window] weight columns: column e holds the summed
    weight of latest messages with target epoch == base_epoch + e.
    Messages older than ``base_epoch`` are permanently expired (the
    window only slides forward) and carry no bucket weight; messages
    ABOVE the window clamp into the top column — exactly correct for
    every query the window can express, since both the true and the
    clamped epoch exceed any representable ``min_vote_epoch``
    (< base + window), and the table keeps the true epoch so later
    delta-subtractions re-clamp consistently."""
    col = jnp.minimum((msg_epoch - base_epoch).astype(jnp.int32), window - 1)
    valid = (msg_block >= 0) & (col >= 0)
    seg = jnp.where(valid, msg_block * window + col, capacity * window)
    flat = jax.ops.segment_sum(
        jnp.where(valid, weight.astype(jnp.int64), 0), seg,
        num_segments=capacity * window + 1)[:capacity * window]
    return flat.reshape(capacity, window)


@jax.jit
def apply_latest_messages_windowed(msg_block, msg_epoch, epoch_buckets,
                                   base_epoch, val_idx, new_block,
                                   new_epoch, weight, active):
    """Windowed twin of ``apply_latest_messages``: same landing/dedup
    semantics, but bucket deltas carry the vote's target epoch. Votes
    below ``base_epoch`` contribute no bucket weight (expired on
    arrival, as the rescan with ``min_vote_epoch >= base_epoch`` treats
    them); votes above the window clamp into the top column (see
    ``rebuild_epoch_buckets`` for why that is exact)."""
    lands, old_block, old_epoch = _vote_landing(
        msg_block, msg_epoch, val_idx, new_block, new_epoch, active)
    capacity, window = epoch_buckets.shape
    flat = epoch_buckets.reshape(capacity * window)
    drop = capacity * window

    def slot(block, epoch, ok):
        col = jnp.minimum((epoch - base_epoch).astype(jnp.int32), window - 1)
        in_win = ok & (col >= 0)
        return jnp.where(in_win, block * window + col, drop), in_win

    w = weight.astype(flat.dtype)
    sub_seg, sub_ok = slot(old_block, old_epoch, lands & (old_block >= 0))
    add_seg, add_ok = slot(new_block, new_epoch, lands)
    flat = flat.at[sub_seg].add(-jnp.where(sub_ok, w, 0), mode="drop")
    flat = flat.at[add_seg].add(jnp.where(add_ok, w, 0), mode="drop")

    tgt = jnp.where(lands, val_idx, msg_block.shape[0])
    msg_block = msg_block.at[tgt].set(new_block, mode="drop")
    msg_epoch = msg_epoch.at[tgt].set(new_epoch, mode="drop")
    return msg_block, msg_epoch, flat.reshape(capacity, window)


@partial(jax.jit, static_argnames=("capacity", "window"))
def _head_from_epoch_buckets_jit(parent, real, rank, leaf_viable,
                                 justified_idx, epoch_buckets, base_epoch,
                                 min_vote_epoch, boost_idx, boost_amount,
                                 capacity: int, window: int):
    cols = base_epoch + jnp.arange(window, dtype=epoch_buckets.dtype)
    vote_weight = jnp.where(cols[:, None] >= min_vote_epoch,
                            epoch_buckets.T, 0).sum(axis=0)
    return _head_from_buckets(parent, real, rank, leaf_viable, justified_idx,
                              vote_weight, boost_idx, boost_amount, capacity)


def head_from_epoch_buckets(parent, real, rank, leaf_viable, justified_idx,
                            epoch_buckets, base_epoch, min_vote_epoch,
                            boost_idx, boost_amount, capacity: int,
                            window: int):
    """Expiry-windowed head from resident columns: mask columns below
    ``min_vote_epoch`` (= current_epoch - eta + 1 in RLMD terms), sum,
    descend. Differential oracle: ``head_and_weights(min_vote_epoch=...)``
    (pinned in tests/test_dense_forkchoice.py).

    Validity window: ``base_epoch <= min_vote_epoch <= base_epoch +
    window - 1``. Below the lower bound behaves as ``base_epoch`` (older
    columns no longer exist, so nothing extra can be unmasked); above the
    upper bound the clamped top column — which holds every vote from
    epoch >= base_epoch + window - 1 — would be masked out and the head
    silently undercounted, so concrete out-of-range values fail loudly
    here. Callers passing traced epochs must size the window themselves
    (the check cannot see traced values)."""
    try:
        hi = int(base_epoch) + window - 1
        mve = int(min_vote_epoch)
    except (jax.errors.TracerIntegerConversionError,
            jax.errors.ConcretizationTypeError):
        pass  # traced epochs: callers must size the window themselves
    else:
        if mve > hi:
            raise ValueError(
                f"min_vote_epoch {mve} is above the top "
                f"resident column (base_epoch {hi - window + 1} + window "
                f"{window} - 1 = {hi}); clamped votes would be masked out. "
                f"Rebuild the buckets with a higher base_epoch instead.")
    return _head_from_epoch_buckets_jit(
        parent, real, rank, leaf_viable, justified_idx, epoch_buckets,
        base_epoch, min_vote_epoch, boost_idx, boost_amount,
        capacity=capacity, window=window)


# --- host-side densification --------------------------------------------------

def next_pow2(x: int) -> int:
    """Capacity rounding shared by the one-shot and resident dense stores."""
    return max(int(2 ** np.ceil(np.log2(max(x, 2)))), 2)


def build_dense_arrays(store, capacity: int | None = None):
    """Host-numpy image of a spec-level Store — the staging form both
    ``build_dense_store`` (device placement) and ``get_head_host`` (the
    vectorized host walk) slice from. Returns (dict of numpy columns,
    roots, capacity)."""
    from pos_evolution_tpu.specs.forkchoice import (
        _leaf_is_viable, get_current_slot, get_proposer_boost,
    )
    from pos_evolution_tpu.specs.helpers import compute_epoch_at_slot

    roots = list(store.blocks.keys())  # insertion = topological order
    b = len(roots)
    if capacity is None:
        capacity = next_pow2(b)
    index_of = {r: i for i, r in enumerate(roots)}
    rank = np.argsort(np.argsort(np.array([r for r in roots], dtype=object)))

    parent = np.full(capacity, -1, dtype=np.int32)
    slot = np.zeros(capacity, dtype=np.int32)
    real = np.zeros(capacity, dtype=bool)
    leaf_viable = np.zeros(capacity, dtype=bool)
    rank_arr = np.zeros(capacity, dtype=np.int32)
    rank_arr[:b] = rank

    jc = store.justified_checkpoint
    for i, root in enumerate(roots):
        block = store.blocks[root]
        real[i] = True
        slot[i] = int(block.slot)
        pr = bytes(block.parent_root)
        parent[i] = index_of.get(pr, -1)
        # same voting-source viability rule as the spec layer
        leaf_viable[i] = _leaf_is_viable(store, root)

    from pos_evolution_tpu.specs.forkchoice import justified_checkpoint_state
    justified_state = justified_checkpoint_state(store)
    n = len(justified_state.validators)
    reg = justified_state.validators
    current_epoch = compute_epoch_at_slot(get_current_slot(store))
    active = ((reg.activation_epoch <= np.uint64(current_epoch))
              & (np.uint64(current_epoch) < reg.exit_epoch))

    msg_block = np.full(n, -1, dtype=np.int32)
    msg_epoch = np.zeros(n, dtype=np.int64)
    weight = np.zeros(n, dtype=np.int64)
    for v, message in store.latest_messages.items():
        if v >= n or v in store.equivocating_indices:
            continue
        idx = index_of.get(message.root)
        if idx is None:
            continue
        msg_block[v] = idx
        msg_epoch[v] = message.epoch
    valid = (msg_block >= 0) & active & ~reg.slashed
    weight[valid] = reg.effective_balance[valid].astype(np.int64)
    msg_block[~valid] = -1

    boost_idx = index_of.get(bytes(store.proposer_boost_root), -1) \
        if store.proposer_boost_root != b"\x00" * 32 else -1
    boost_amount = get_proposer_boost(store) if boost_idx >= 0 else 0

    cols = dict(
        parent=parent, slot=slot, rank=rank_arr, real=real,
        leaf_viable=leaf_viable,
        justified_idx=np.int32(index_of[bytes(jc.root)]),
        msg_block=msg_block, msg_epoch=msg_epoch, weight=weight,
        boost_idx=np.int32(boost_idx),
        boost_amount=np.int64(boost_amount),
    )
    return cols, roots, capacity


def build_dense_store(store, capacity: int | None = None):
    """Build a DenseStore from a spec-level Store (host side).

    Returns (dense, roots) where roots[i] is the block root at index i.
    """
    cols, roots, capacity = build_dense_arrays(store, capacity)
    dense = DenseStore(
        parent=jnp.asarray(cols["parent"]),
        slot=jnp.asarray(cols["slot"]),
        rank=jnp.asarray(cols["rank"]),
        real=jnp.asarray(cols["real"]),
        leaf_viable=jnp.asarray(cols["leaf_viable"]),
        justified_idx=jnp.int32(cols["justified_idx"]),
        msg_block=jnp.asarray(cols["msg_block"]),
        msg_epoch=jnp.asarray(cols["msg_epoch"]),
        weight=jnp.asarray(cols["weight"]),
        boost_idx=jnp.int32(cols["boost_idx"]),
        boost_amount=jnp.int64(cols["boost_amount"]),
    )
    return dense, roots, capacity


def head_host(parent, real, rank, leaf_viable, justified_idx, vote_weight,
              boost_idx, boost_amount):
    """Host-numpy twin of ``_head_from_buckets``: reverse-topological
    subtree accumulation (parent index < child index always holds) plus
    the greedy (weight, lexicographic-rank) descent — no device queue,
    no jit. The cheap independent oracle behind the resident store's
    periodic self-check and the dense driver's spec-walk pin; itself
    pinned bit-identical to ``specs.forkchoice.get_head`` in
    tests/test_sharded_e2e.py."""
    b = parent.shape[0]
    subtree = vote_weight.astype(np.int64).copy()
    boost_col = np.zeros(b, np.int64)
    if boost_idx >= 0:
        boost_col[boost_idx] = 1
    is_parent = np.zeros(b, bool)
    valid_parent = (parent >= 0) & real
    is_parent[parent[valid_parent]] = True
    leaf_ok = ((real & ~is_parent) & leaf_viable).astype(np.int64)
    for i in range(b - 1, 0, -1):
        p = parent[i]
        if p >= 0 and real[i]:
            subtree[p] += subtree[i]
            boost_col[p] += boost_col[i]
            leaf_ok[p] += leaf_ok[i]
    subtree = subtree + boost_col * np.int64(boost_amount)
    keep = leaf_ok > 0

    head = int(justified_idx)
    while True:
        children = np.nonzero((parent == head) & keep & real)[0]
        if children.size == 0:
            return head
        w = subtree[children]
        best_w = w.max()
        tied = children[w == best_w]
        head = int(tied[np.argmax(rank[tied])])


def get_head_host(store) -> bytes:
    """Vectorized host get_head: one O(N) numpy pass over the
    latest-message table + the O(B) host subtree/descent walk —
    bit-identical to the spec walk (``specs.forkchoice.get_head``
    recomputes an O(N)-Python-loop balance per candidate child, which
    costs tens of seconds per call at 64K+ validators; this is the same
    math vectorized). Used by the resident store's periodic self-check
    and anywhere a spec-walk pin is needed at registry scale."""
    cols, roots, capacity = build_dense_arrays(store)
    msg_block = cols["msg_block"]
    valid = msg_block >= 0
    vw = np.zeros(capacity + 1, np.int64)
    np.add.at(vw, np.where(valid, msg_block, capacity),
              np.where(valid, cols["weight"], 0))
    head = head_host(cols["parent"], cols["real"], cols["rank"],
                     cols["leaf_viable"], cols["justified_idx"],
                     vw[:capacity], int(cols["boost_idx"]),
                     int(cols["boost_amount"]))
    return roots[head]


def get_head_dense(store) -> bytes:
    """Drop-in accelerated get_head for a spec-level Store."""
    dense, roots, capacity = build_dense_store(store)
    head_idx, _ = head_and_weights(dense, capacity)
    return roots[int(head_idx)]
