"""BLS12-381 extension-field tower on TPU lanes (component N1, layer 0).

Builds Fq2 -> Fq6 -> Fq12 on top of the base-field limb arithmetic in
``ops/fp.py``, mirroring the oracle tower in ``crypto/bls12_381.py``
(same irreducibles: u^2 = -1, v^3 = u+1, w^2 = v) so every op is
differential-testable against exact Python integers.

Representation — ONE dense array per element, not nested objects:

- Fq element: int32[..., 32] limbs, residues in [0, 2p) (fp.py's domain)
- Fq2  = [..., 2, 32], Fq6 = [..., 6, 32], Fq12 = [..., 12, 32]
  component order = the nested tower flattened:
  Fq12 slot (part, vpow, upart) -> index part*6 + vpow*2 + upart,
  i.e. (a.c0.a, a.c0.b, a.c1.a, ..., b.c2.b).

Multiplication is ONE *stacked* base-field mul over all component pairs
plus two static einsums against the algebra's structure tensor T
(T[i,j,k] = Fq-coefficient of e_k in e_i * e_j), derived at import time
by multiplying oracle basis elements — no hand-written tower formulas to
get wrong, a ~40x smaller XLA graph than composing scalar field ops
(which XLA:CPU cannot compile at Fq12 depth), and every op is a wide
batched limb kernel, which is exactly the shape the TPU VPU/MXU wants.

Signed recombination avoids negative digit vectors by adding a static
multiple of p before subtracting the negative part, then one Barrett
reduction lands each output component back in [0, 2p); all bounds are
asserted at tensor-construction time, not assumed.

Frobenius maps use host-precomputed gamma constants
gamma_k[i] = xi^(i * (q^k - 1) / 6) over the w-power basis, computed
exactly with the oracle at import time.

Cited reference surface: pos-evolution.md:165 (bls.Verify), :714-717
(aggregate attestation signatures), :642 (sync aggregates); SURVEY.md
§2.7 N1 mandates this as a device kernel.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from pos_evolution_tpu.crypto import bls12_381 as oracle
from pos_evolution_tpu.ops import fp

Q = oracle.Q

# --- structure tensors, derived from the oracle -------------------------------


def _fq12_from_coeffs(c: list) -> "oracle.Fq12":
    f2 = [oracle.Fq2(c[2 * i], c[2 * i + 1]) for i in range(6)]
    return oracle.Fq12(oracle.Fq6(*f2[:3]), oracle.Fq6(*f2[3:]))


def _fq12_to_coeffs(x: "oracle.Fq12") -> list:
    out = []
    for part in (x.a, x.b):
        for c2 in (part.a, part.b, part.c):
            out.extend([c2.a, c2.b])
    return out


def _signed(c: int) -> int:
    return c - Q if c > Q // 2 else c


def _structure_tensor(d: int) -> np.ndarray:
    """T[i,j,k] over the first d components (d = 2 -> Fq2, 6 -> Fq6,
    12 -> Fq12; the tower ordering nests, so a prefix of the Fq12 basis
    IS the smaller algebra's basis)."""
    T = np.zeros((d, d, d), dtype=np.int64)
    basis = []
    for i in range(d):
        c = [0] * 12
        c[i] = 1
        basis.append(_fq12_from_coeffs(c))
    for i in range(d):
        for j in range(d):
            prod = _fq12_to_coeffs(basis[i] * basis[j])
            for k, coef in enumerate(prod):
                s = _signed(coef)
                assert abs(s) <= 4, (i, j, k, s)
                assert k < d or s == 0, "product escaped the subalgebra"
                if k < d:
                    T[i, j, k] = s
    return T


def _mul_plan(T: np.ndarray, y_slots=None):
    """Precompute the einsum operands for alg_mul: positive/negative
    parts of T (restricted to ``y_slots`` of the right operand for
    sparse multiplicands) + the digit vector of the p-multiple offset
    that keeps the signed recombination non-negative."""
    if y_slots is not None:
        T = T[:, list(y_slots), :]
    Tpos = np.maximum(T, 0).astype(np.int32)
    Tneg = np.maximum(-T, 0).astype(np.int32)
    neg_bound = int(Tneg.sum(axis=(0, 1)).max())   # worst Σ|neg coef| per k
    pos_bound = int(Tpos.sum(axis=(0, 1)).max())
    m = 2 * neg_bound + 2                          # offset = m*p >= neg*2p
    # every value stays < (2*pos + m + 2) * p; must fit 33 digits
    assert (2 * pos_bound + m + 2) * Q < 2**(12 * 33)
    offset = fp.to_limbs(m * Q, 33)
    # numpy, not jnp: this cache may first fill inside a trace, and a
    # traced-context jnp constant would leak its tracer
    return (Tpos, Tneg, offset)


_T2 = _structure_tensor(2)
_T6 = _structure_tensor(6)
_T12 = _structure_tensor(12)
_PLANS: dict = {}


def _plan(d: int, y_slots=None):
    key = (d, y_slots)
    if key not in _PLANS:
        T = {2: _T2, 6: _T6, 12: _T12}[d]
        _PLANS[key] = _mul_plan(T, y_slots)
    return _PLANS[key]


# --- generic algebra ops ------------------------------------------------------


def alg_mul(x: jax.Array, y: jax.Array, y_slots: tuple | None = None
            ) -> jax.Array:
    """x * y in the d-component algebra; x [..., d, 32], y [..., dy, 32]
    where dy = len(y_slots) if y is sparse (its components live at
    ``y_slots`` of the full basis) else d."""
    d = x.shape[-2]
    tpos, tneg, offset = (jnp.asarray(t) for t in _plan(d, y_slots))
    prods = fp.modmul(x[..., :, None, :], y[..., None, :, :])
    pos = jnp.einsum("ijk,...ijl->...kl", tpos, prods,
                     preferred_element_type=jnp.int32)
    neg = jnp.einsum("ijk,...ijl->...kl", tneg, prods,
                     preferred_element_type=jnp.int32)
    pos = jnp.pad(pos, [(0, 0)] * (pos.ndim - 1) + [(0, 33 - pos.shape[-1])])
    s = fp.carry_norm(pos + offset, 33)
    t = fp.carry_norm(neg, 33)
    diff, uf = fp.sub_digits(s, t)
    return fp.barrett_reduce(diff)


def alg_sq(x: jax.Array) -> jax.Array:
    return alg_mul(x, x)


# add/sub/neg/select/eq are just the base-field ops broadcast over the
# component axis — no algebra-specific code needed
alg_add = fp.modadd
alg_sub = fp.modsub
alg_neg = fp.modneg


def alg_eq(x: jax.Array, y: jax.Array) -> jax.Array:
    return fp.eq(x, y).all(axis=-1)


def alg_is_zero(x: jax.Array) -> jax.Array:
    return fp.is_zero(x).all(axis=-1)


def alg_select(pred: jax.Array, x: jax.Array, y: jax.Array) -> jax.Array:
    """pred [...] broadcast over [..., d, 32]."""
    return jnp.where(pred[..., None, None], x, y)


def alg_one(d: int, batch: tuple = ()) -> jax.Array:
    out = np.zeros(batch + (d, fp.L), dtype=np.int32)
    out[..., 0, :] = fp.ONE
    return jnp.asarray(out)


def alg_zero(d: int, batch: tuple = ()) -> jax.Array:
    return jnp.zeros(batch + (d, fp.L), dtype=jnp.int32)


def embed(x: jax.Array, d: int, slots: tuple) -> jax.Array:
    """Place sparse components x [..., len(slots), 32] at ``slots`` of a
    d-component zero element."""
    out = jnp.zeros(x.shape[:-2] + (d, fp.L), dtype=jnp.int32)
    return out.at[..., jnp.asarray(slots), :].set(x)


# --- Fq2 specifics ------------------------------------------------------------


def fq2_mul(x, y):
    return alg_mul(x, y)


def fq2_sq(x):
    return alg_mul(x, x)


def fq2_conj(x):
    return jnp.stack([x[..., 0, :], fp.modneg(x[..., 1, :])], axis=-2)


def fq2_mul_xi(x):
    """(a+bu)(1+u) = (a-b) + (a+b)u."""
    a, b = x[..., 0, :], x[..., 1, :]
    return jnp.stack([fp.modsub(a, b), fp.modadd(a, b)], axis=-2)


def fq2_inv(x):
    """1/(a+bu) = (a - bu)/(a^2 + b^2); zero maps to zero (Fermat)."""
    a, b = x[..., 0, :], x[..., 1, :]
    d = fp.modinv(fp.modadd(fp.modmul(a, a), fp.modmul(b, b)))
    return jnp.stack([fp.modmul(a, d), fp.modneg(fp.modmul(b, d))], axis=-2)


def fq2_muli(x, k: int):
    """Multiply by a small non-negative int (trace-time shift-add)."""
    acc = None
    add = x
    while k:
        if k & 1:
            acc = add if acc is None else fp.modadd(acc, add)
        add = fp.modadd(add, add)
        k >>= 1
    return acc if acc is not None else jnp.zeros_like(x)


# --- Fq6 / Fq12 specifics -----------------------------------------------------


def fq6_mul_v(x):
    """*v: (c0, c1, c2) -> (c2*xi, c0, c1) over [..., 6, 32] ((vpow,
    upart) flattened)."""
    c0, c1, c2 = x[..., 0:2, :], x[..., 2:4, :], x[..., 4:6, :]
    return jnp.concatenate([fq2_mul_xi(c2), c0, c1], axis=-2)


def fq6_inv(x):
    """Cubic-extension inverse (oracle bls12_381.py:181-187)."""
    a, b, c = x[..., 0:2, :], x[..., 2:4, :], x[..., 4:6, :]
    c0 = fp.modsub(fq2_sq(a), fq2_mul_xi(fq2_mul(b, c)))
    c1 = fp.modsub(fq2_mul_xi(fq2_sq(c)), fq2_mul(a, b))
    c2 = fp.modsub(fq2_sq(b), fq2_mul(a, c))
    t = fq2_inv(fp.modadd(fq2_mul(a, c0), fq2_mul_xi(
        fp.modadd(fq2_mul(c, c1), fq2_mul(b, c2)))))
    return jnp.concatenate([fq2_mul(c0, t), fq2_mul(c1, t), fq2_mul(c2, t)],
                           axis=-2)


def fq12_mul(x, y):
    return alg_mul(x, y)


def fq12_sq(x):
    return alg_mul(x, x)


def fq12_conj(x):
    """Conjugation = Frobenius^6 (oracle :227-229): negate the w-part.
    For elements in the cyclotomic subgroup this IS the inverse."""
    return jnp.concatenate([x[..., 0:6, :], fp.modneg(x[..., 6:12, :])],
                           axis=-2)


def fq12_inv(x):
    """Quadratic-over-Fq6 inverse (oracle :223-225)."""
    a, b = x[..., 0:6, :], x[..., 6:12, :]
    a2 = alg_mul(a, a)
    b2 = alg_mul(b, b)
    t = fq6_inv(fp.modsub(a2, fq6_mul_v(b2)))
    return jnp.concatenate([alg_mul(a, t), fp.modneg(alg_mul(b, t))],
                           axis=-2)


def fq12_pow_bits(x: jax.Array, bits: np.ndarray) -> jax.Array:
    """x^e for the static bit string ``bits`` (MSB first) via lax.scan —
    one Fq12 square + conditional mul per bit."""
    one = alg_one(12, x.shape[:-2])

    def step(acc, bit):
        acc = fq12_sq(acc)
        return alg_select(bit, fq12_mul(acc, x), acc), None

    out, _ = jax.lax.scan(step, one, jnp.asarray(bits))
    return out


def _fp4_sq(a, b):
    """(a + b*s)^2 in Fq4 = Fq2[s]/(s^2 - xi): returns
    (a^2 + xi*b^2, (a+b)^2 - a^2 - b^2). 3 Fq2 squarings total."""
    a2 = fq2_sq(a)
    b2 = fq2_sq(b)
    c0 = fp.modadd(fq2_mul_xi(b2), a2)
    c1 = fp.modsub(fp.modsub(fq2_sq(fp.modadd(a, b)), a2), b2)
    return c0, c1


def fq12_cyclotomic_sq(x):
    """Granger-Scott squaring — valid ONLY for x in the cyclotomic
    subgroup G_{Phi6(q^2)} (any easy-part output qualifies). 9 Fq2
    squarings + cheap adds, versus the dense 12x12 structure-tensor
    product of ``fq12_sq`` — the workhorse of the final-exponentiation
    pow ladders (~250 squarings per pairing).

    Over the w-power basis the subgroup element f = sum g_i w^i splits
    into three Fq4 = Fq2[w^3] pairs (g0, g3), (g1, g4), (g2, g5); in the
    tower slot order (w-powers (0,2,4,1,3,5), see _WPOW) those pairs are
    (z0=x[0:2], z1=x[8:10]), (z2=x[6:8], z3=x[4:6]), (z4=x[2:4],
    z5=x[10:12]), giving the classic schedule [Granger-Scott 2010,
    "Faster squaring in the cyclotomic subgroup of sixth degree
    extensions"]. Differentially pinned against ``fq12_sq`` and the
    oracle in tests/test_tower_device.py.
    """
    z0 = x[..., 0:2, :]
    z4 = x[..., 2:4, :]
    z3 = x[..., 4:6, :]
    z2 = x[..., 6:8, :]
    z1 = x[..., 8:10, :]
    z5 = x[..., 10:12, :]

    def three_minus_two(t, z):   # 3t - 2z
        return fp.modsub(fq2_muli(t, 3), fq2_muli(z, 2))

    def three_plus_two(t, z):    # 3t + 2z
        return fp.modadd(fq2_muli(t, 3), fq2_muli(z, 2))

    t0, t1 = _fp4_sq(z0, z1)
    n0 = three_minus_two(t0, z0)
    n1 = three_plus_two(t1, z1)
    t0, t1 = _fp4_sq(z2, z3)
    t2, t3 = _fp4_sq(z4, z5)
    n4 = three_minus_two(t0, z4)
    n5 = three_plus_two(t1, z5)
    n2 = three_plus_two(fq2_mul_xi(t3), z2)
    n3 = three_minus_two(t2, z3)
    return jnp.concatenate([n0, n4, n3, n2, n1, n5], axis=-2)


def fq12_pow_bits_cyclotomic(x: jax.Array, bits: np.ndarray) -> jax.Array:
    """``fq12_pow_bits`` with Granger-Scott squarings — x MUST be in the
    cyclotomic subgroup (final-exponentiation hard-part ladders)."""
    one = alg_one(12, x.shape[:-2])

    def step(acc, bit):
        acc = fq12_cyclotomic_sq(acc)
        return alg_select(bit, fq12_mul(acc, x), acc), None

    out, _ = jax.lax.scan(step, one, jnp.asarray(bits))
    return out


# --- Frobenius ----------------------------------------------------------------
#
# Over the w-power basis c_i * w^i (i = 0..5, w^6 = xi):
#   frob^k(c_i w^i) = frob^k(c_i) * gamma_k[i] * w^i,
#   gamma_k[i] = xi^(i * (q^k - 1) / 6)
# frob on Fq2 is conjugation (frob^2 = identity on Fq2).
# Tower slot (pairs) <-> w-power: (a.c0, a.c1, a.c2, b.c0, b.c1, b.c2)
#                              =  (w^0,  w^2,  w^4,  w^1,  w^3,  w^5).

_WPOW = [0, 2, 4, 1, 3, 5]


def _gamma_const(k: int) -> np.ndarray:
    """[6, 2, 32] gamma constants per tower Fq2 slot."""
    qk = Q if k == 1 else Q * Q
    out = np.zeros((6, 2, fp.L), dtype=np.int32)
    for slot in range(6):
        g = oracle.XI.pow(_WPOW[slot] * (qk - 1) // 6)
        out[slot, 0] = fp.to_limbs(g.a)
        out[slot, 1] = fp.to_limbs(g.b)
    return out


_G1C = jnp.asarray(_gamma_const(1))
_G2C = jnp.asarray(_gamma_const(2))


def fq12_frob1(x):
    pairs = x.reshape(x.shape[:-2] + (6, 2, fp.L))
    conj = jnp.stack([pairs[..., 0, :], fp.modneg(pairs[..., 1, :])],
                     axis=-2)
    out = alg_mul(conj, jnp.broadcast_to(_G1C, conj.shape))
    return out.reshape(x.shape)


def fq12_frob2(x):
    pairs = x.reshape(x.shape[:-2] + (6, 2, fp.L))
    out = alg_mul(pairs, jnp.broadcast_to(_G2C, pairs.shape))
    return out.reshape(x.shape)


# --- host <-> device codecs ---------------------------------------------------


def fq2_encode(x: "oracle.Fq2") -> np.ndarray:
    return np.stack([fp.to_limbs(x.a), fp.to_limbs(x.b)])


def fq2_decode(x, idx=()) -> "oracle.Fq2":
    arr = np.asarray(x)[idx]
    return oracle.Fq2(fp.from_limbs(arr[0]), fp.from_limbs(arr[1]))


def fq6_encode(x: "oracle.Fq6") -> np.ndarray:
    return np.concatenate([fq2_encode(c) for c in (x.a, x.b, x.c)])


def fq6_decode(x, idx=()) -> "oracle.Fq6":
    arr = np.asarray(x)[idx]
    return oracle.Fq6(*(oracle.Fq2(fp.from_limbs(arr[2 * i]),
                                   fp.from_limbs(arr[2 * i + 1]))
                        for i in range(3)))


def fq12_encode(x: "oracle.Fq12") -> np.ndarray:
    return np.concatenate([fq6_encode(x.a), fq6_encode(x.b)])


def fq12_decode(x, idx=()) -> "oracle.Fq12":
    arr = np.asarray(x)[idx]
    halves = []
    for off in (0, 6):
        halves.append(oracle.Fq6(*(oracle.Fq2(
            fp.from_limbs(arr[off + 2 * i]),
            fp.from_limbs(arr[off + 2 * i + 1])) for i in range(3))))
    return oracle.Fq12(*halves)
