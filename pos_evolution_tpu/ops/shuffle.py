"""Swap-or-not shuffle on device (north-star config #2).

The reference's ``compute_shuffled_index`` (pos-evolution.md:513-535) runs
O(SHUFFLE_ROUND_COUNT) hashes per validator. Here the whole registry is
shuffled at once: a ``lax.fori_loop`` over the rounds (SURVEY.md §2.8),
where each round hashes only ceil(n/256) position blocks with the vectorized
SHA-256 and applies the flip decision to all indices in parallel — the
round hash results are shared across all validators in the same 256-index
position block.

Round pivots depend only on (seed, round) and are precomputed on host;
everything shape-dependent runs under ``jit`` with static (n, rounds).
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

# this kernel's pivots/flip arithmetic are int64: x64 is enabled lazily
# at first shuffle (ops/sha256 no longer flips it at import — ISSUE 15)
from pos_evolution_tpu.backend.jax_init import ensure_x64
from pos_evolution_tpu.ops.sha256 import sha256_words
from pos_evolution_tpu.ssz.hash import hash_eth2


def host_pivots(seed: bytes, n: int, rounds: int) -> np.ndarray:
    """pivot[r] = bytes_to_uint64(H(seed | r)[:8]) % n (pos-evolution.md:522)."""
    return np.array(
        [int.from_bytes(hash_eth2(seed + bytes([r]))[:8], "little") % n
         for r in range(rounds)],
        dtype=np.int64)


def _seed_words(seed: bytes) -> np.ndarray:
    return np.frombuffer(seed, dtype=">u4").astype(np.uint32)


def _shuffle_rounds(seed_words, pivots, idx0, n: int, rounds: int):
    """Run the fixed swap-or-not round schedule on ``idx0`` (any slice of
    the index space — each index's trajectory is independent, which is
    what makes the kernel shardable; see ``parallel.sharded.sharded_shuffle``).
    Positions range over the FULL [0, n), so the per-round digest table
    covers all (n+255)//256 blocks regardless of the slice."""
    n_blocks = (n + 255) // 256

    # Static message template for the per-round block hashes:
    # bytes = seed(32) | round(1) | block_le(4) | 0x80 | zeros | len(296 bits)
    block_ids = jnp.arange(n_blocks, dtype=jnp.uint32)
    b0 = block_ids & 0xFF
    b1 = (block_ids >> 8) & 0xFF
    b2 = (block_ids >> 16) & 0xFF
    b3 = (block_ids >> 24) & 0xFF

    base = jnp.zeros((n_blocks, 16), dtype=jnp.uint32)
    base = base.at[:, 0:8].set(jnp.broadcast_to(seed_words, (n_blocks, 8)))
    base = base.at[:, 9].set((b3 << 24) | np.uint32(0x00800000))
    base = base.at[:, 15].set(np.uint32(37 * 8))

    def round_body(r, idx):
        pivot = pivots[r]
        flip = (pivot - idx.astype(jnp.int64)) % n
        flip = flip.astype(jnp.int32)
        pos = jnp.maximum(idx, flip)
        # word 8 = round_byte<<24 | b0<<16 | b1<<8 | b2
        r32 = r.astype(jnp.uint32)
        msgs = base.at[:, 8].set((r32 << 24) | (b0 << 16) | (b1 << 8) | b2)
        digests = sha256_words(msgs)  # (n_blocks, 8) u32, big-endian words
        # byte k of the digest lives in word k>>2 at big-endian lane 24-8*(k&3)
        k = (pos & 0xFF) >> 3
        word = digests[pos >> 8, k >> 2]
        byte = (word >> (np.uint32(24) - ((k.astype(jnp.uint32) & 3) << 3))) & 0xFF
        bit = (byte >> (pos.astype(jnp.uint32) & 7)) & 1
        return jnp.where(bit.astype(bool), flip, idx)

    return jax.lax.fori_loop(0, rounds, round_body, idx0)


@partial(jax.jit, static_argnames=("n", "rounds"))
def _shuffle_device(seed_words, pivots, n: int, rounds: int):
    """Full permutation: returns p with p[i] = shuffled index of i."""
    idx0 = jnp.arange(n, dtype=jnp.int32)
    return _shuffle_rounds(seed_words, pivots, idx0, n, rounds)


def shuffle_permutation_jax(seed: bytes, n: int, rounds: int) -> jax.Array:
    """Device permutation equivalent to the reference's per-index shuffle."""
    ensure_x64()  # before the jit — int64 pivot avals
    if n == 0:
        return jnp.zeros(0, dtype=jnp.int32)
    return _shuffle_device(jnp.asarray(_seed_words(seed)),
                           jnp.asarray(host_pivots(seed, n, rounds)),
                           n, rounds)
