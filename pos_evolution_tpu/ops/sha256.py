"""SHA-256 on device (JAX/XLA): uint32-lane compression for TPU (N2).

The TPU formulation of the batched SHA-256 in ``ssz/hash.py``: messages are
prepared as (N, 16*blocks) big-endian uint32 word arrays (padding included),
and the 64-round compression runs unrolled under ``jit`` as pure uint32
vector arithmetic on the VPU — one lane per message. Used by the shuffle
kernel (pos-evolution.md:522-530) and the merkleization kernel.

uint32 add/xor/shift are native VPU ops; there is no u64 anywhere in the
compression, which is exactly why SHA-256 maps well onto the TPU vector
unit (SURVEY.md §2.7 N2).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

# Exact Gwei/epoch integer semantics across all device kernels (balances sum
# to ~2^55 at mainnet scale); the differential tests assert bit-equality
# with the NumPy oracle. The flag is flipped LAZILY at first kernel use via
# the consolidated backend helper — importing this module must never mutate
# process-global JAX config (ISSUE 15 satellite).
from pos_evolution_tpu.backend.jax_init import ensure_x64

_K = np.array([
    0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1,
    0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
    0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174, 0xE49B69C1, 0xEFBE4786,
    0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
    0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
    0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
    0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85, 0xA2BFE8A1, 0xA81A664B,
    0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
    0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A,
    0x5B9CCA4F, 0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
    0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
], dtype=np.uint32)

H0 = np.array([
    0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
    0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
], dtype=np.uint32)


def _rotr(x, n: int):
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _unroll_rounds() -> bool:
    # Fully unrolled rounds fuse best on TPU; on XLA:CPU the unrolled
    # multi-compression graph sends compile time superlinear (minutes), so
    # the CPU path loops over a (64, ...) schedule stack instead.
    return jax.default_backend() != "cpu"


def sha256_compress(state, block_words):
    """One compression: state (..., 8) u32, block_words (..., 16) u32."""
    ensure_x64()
    w = [block_words[..., t] for t in range(16)]
    for t in range(16, 64):
        s0 = _rotr(w[t - 15], 7) ^ _rotr(w[t - 15], 18) ^ (w[t - 15] >> np.uint32(3))
        s1 = _rotr(w[t - 2], 17) ^ _rotr(w[t - 2], 19) ^ (w[t - 2] >> np.uint32(10))
        w.append(w[t - 16] + s0 + w[t - 7] + s1)

    def round_step(a, b, c, d, e, f, g, h, kt, wt):
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + kt + wt
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        return (t1 + s0 + maj, a, b, c, d + t1, e, f, g)

    init = tuple(state[..., i] for i in range(8))
    if _unroll_rounds():
        carry = init
        for t in range(64):
            carry = round_step(*carry, np.uint32(_K[t]), w[t])
        a, b, c, d, e, f, g, h = carry
    else:
        w_stack = jnp.stack(w, axis=0)  # (64, ...) leading axis
        k_stack = jnp.asarray(_K)

        def round_body(t, carry):
            wt = jax.lax.dynamic_index_in_dim(w_stack, t, axis=0, keepdims=False)
            return round_step(*carry, k_stack[t], wt)

        a, b, c, d, e, f, g, h = jax.lax.fori_loop(0, 64, round_body, init)
    out = jnp.stack([a, b, c, d, e, f, g, h], axis=-1)
    return state + out


def sha256_words(msg_words):
    """SHA-256 over pre-padded messages: (N, 16*blocks) u32 -> (N, 8) u32."""
    ensure_x64()
    n_blocks = msg_words.shape[-1] // 16
    state = jnp.broadcast_to(jnp.asarray(H0), msg_words.shape[:-1] + (8,))
    for b in range(n_blocks):
        state = sha256_compress(state, msg_words[..., b * 16:(b + 1) * 16])
    return state


def sha256_pair_words(left, right):
    """Merkle combiner: H(left || right) where left/right are (N, 8) u32
    digest words. 64-byte message = one padded second block."""
    ensure_x64()
    n = left.shape[0]
    pad = jnp.zeros((n, 16), dtype=jnp.uint32)
    pad = pad.at[:, 0].set(np.uint32(0x80000000))
    pad = pad.at[:, 15].set(np.uint32(512))
    state = sha256_compress(
        jnp.broadcast_to(jnp.asarray(H0), (n, 8)),
        jnp.concatenate([left, right], axis=-1))
    return sha256_compress(state, pad)


def bytes_to_words(data: bytes) -> np.ndarray:
    """Host helper: big-endian u32 words of a byte string (len % 4 == 0)."""
    return np.frombuffer(data, dtype=">u4").astype(np.uint32)


def words_to_digest(words: np.ndarray) -> bytes:
    """Host helper: (8,) u32 state -> 32-byte digest."""
    return np.asarray(words, dtype=np.uint32).astype(">u4").tobytes()
