"""Batched DAS sample verification + erasure-reconstruction check.

A sampling client's unit of work is one (cell, branch, commitment)
triple: hash the cell to its leaf, walk the branch, compare against the
blob's commitment. This module runs *whole batches* of such samples —
many clients x many cells at once — through either backend:

- **host path**: ``sha256_batch`` leaf hashing + the same per-level
  select/hash merkle walk as ``ops/sync_verify.merkle_roots_host``
  (kept jax-free here so the numpy backend never imports jax);
- **device path**: cells padded to SHA-256 word blocks on the host, leaf
  digests computed by ``ops/sha256.sha256_words`` (one VPU lane per
  cell), then the jitted ``lax.scan`` merkle walk from
  ``ops/sync_verify`` — the batched Merkle/hash kernel shape of the MTU
  tree-unit paper (arxiv 2507.16793).

The 50%-reconstruction check (``reconstruct_check``) is the verifier's
side of the erasure code: interpolate the data cells from any >=k of 2k
present cells and confirm every present cell lies on the degree-<k
polynomial — a single corrupted cell flips the verdict. GF(2^8)
arithmetic is log/exp gathers + XOR on both backends.

Both entry points dispatch through the ``ExecutionBackend``
(``das_verify`` / ``das_reconstruct``); tests pin the two paths
bit-identical on randomized (blob, sample, corruption) inputs. The jax
backend additionally keeps sub-crossover sample batches on the host
path (``ops/merkle_device.small_batch_floor`` — the same measured
threshold as the merkle level sweeps): the verdicts are identical, the
fixed device-dispatch cost is not.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache

import numpy as np

from pos_evolution_tpu.das.erasure import (
    GF_EXP,
    GF_LOG,
    extension_matrix,
    lagrange_matrix,
    reconstruct_blob,
)
from pos_evolution_tpu.ssz.hash import sha256_batch, sha256_pairs

__all__ = [
    "DasSampleBatch",
    "verify_das_samples",
    "verify_samples_host",
    "verify_samples_device",
    "reconstruct_check",
    "reconstruct_check_host",
    "reconstruct_check_device",
]


@dataclass
class DasSampleBatch:
    """Dense form of S coalesced samples (array-level only, so one batch
    feeds either backend — the ``SyncUpdateBatch`` pattern)."""

    cells: np.ndarray        # (S, cell_bytes) u8 — sampled cell payloads
    branches: np.ndarray     # (S, D, 32) u8     — per-sample merkle branches
    indices: np.ndarray      # (S,) i64          — cell index in the grid
    commitments: np.ndarray  # (S, 32) u8        — expected grid commitments

    @property
    def size(self) -> int:
        return self.cells.shape[0]


def _index_bits(index: np.ndarray, depth: int) -> np.ndarray:
    idx = np.asarray(index, dtype=np.int64)
    return ((idx[:, None] >> np.arange(depth, dtype=np.int64)[None, :]) & 1
            ).astype(bool)


def _result(ok, roots, leaves) -> dict:
    return {"ok": np.asarray(ok, dtype=bool),
            "roots": np.asarray(roots, dtype=np.uint8),
            "leaves": np.asarray(leaves, dtype=np.uint8)}


# --- host path ----------------------------------------------------------------

def verify_samples_host(batch: DasSampleBatch) -> dict:
    """NumPy reference path (the oracle the device path must match)."""
    leaves = sha256_batch(np.ascontiguousarray(batch.cells, dtype=np.uint8))
    value = leaves
    branches = np.asarray(batch.branches, dtype=np.uint8)
    bits = _index_bits(batch.indices, branches.shape[1])
    for d in range(branches.shape[1]):
        sib = branches[:, d]
        right_child = bits[:, d][:, None]
        left = np.where(right_child, sib, value)
        right = np.where(right_child, value, sib)
        value = sha256_pairs(np.ascontiguousarray(left),
                             np.ascontiguousarray(right))
    ok = (value == np.asarray(batch.commitments, dtype=np.uint8)).all(axis=1)
    return _result(ok, value, leaves)


# --- device path --------------------------------------------------------------

def verify_samples_device(batch: DasSampleBatch) -> dict:
    """JAX/XLA path: leaf hashing + branch walk stay on device; only the
    padded word arrays move host->device once per batch."""
    import jax.numpy as jnp

    from pos_evolution_tpu.ops.aggregation import messages_to_words
    from pos_evolution_tpu.ops.sha256 import sha256_words
    from pos_evolution_tpu.ops.sync_verify import (
        _merkle_walk_device,
        _words_to_rows,
    )
    from pos_evolution_tpu.ssz.hash import _pad_messages

    s = batch.size
    depth = batch.branches.shape[1]
    cell_words = _pad_messages(
        np.ascontiguousarray(batch.cells, dtype=np.uint8))
    leaf_words = sha256_words(jnp.asarray(cell_words))
    branch_words = messages_to_words(np.ascontiguousarray(
        batch.branches, dtype=np.uint8).reshape(s * depth, 32)
    ).reshape(s, depth, 8)
    roots = _merkle_walk_device(leaf_words, jnp.asarray(branch_words),
                                jnp.asarray(_index_bits(batch.indices, depth)))
    root_rows = _words_to_rows(roots)
    leaf_rows = _words_to_rows(leaf_words)
    ok = (root_rows == np.asarray(batch.commitments, dtype=np.uint8)
          ).all(axis=1)
    return _result(ok, root_rows, leaf_rows)


# --- erasure-reconstruction check ---------------------------------------------

def reconstruct_check_host(cells: np.ndarray, present: np.ndarray
                           ) -> tuple[bool, np.ndarray]:
    """(consistent, data_cells) from any >=50% of the extended grid."""
    data, _full, ok = reconstruct_blob(cells, present)
    return ok, data


@lru_cache(maxsize=None)
def _reconstruct_kernel():
    """Module-singleton jitted reconstruction kernel: built once per
    process, retraced only per (k, cell_bytes) geometry — a fresh
    ``@jax.jit`` closure per call would recompile every invocation."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def _run(interp_m, ext_m, sel_cells, grid, avail_mask):
        def gf_matmul(a, b):
            log_a = jnp.asarray(GF_LOG)[a]
            log_b = jnp.asarray(GF_LOG)[b]
            acc = jnp.zeros((a.shape[0], b.shape[1]), dtype=jnp.uint8)
            for t in range(a.shape[1]):  # k is static: unrolls under jit
                prod = jnp.asarray(GF_EXP)[log_a[:, t][:, None]
                                           + log_b[t][None, :]]
                prod = jnp.where((a[:, t][:, None] == 0)
                                 | (b[t][None, :] == 0),
                                 jnp.uint8(0), prod)
                acc = acc ^ prod
            return acc

        data = gf_matmul(interp_m, sel_cells)
        full = jnp.concatenate([data, gf_matmul(ext_m, data)], axis=0)
        ok = jnp.all(jnp.where(avail_mask[:, None], full == grid, True))
        return ok, data

    return _run


def reconstruct_check_device(cells: np.ndarray, present: np.ndarray
                             ) -> tuple[bool, np.ndarray]:
    """Device twin: the GF(2^8) interpolation + re-extension as uint8
    log/exp gathers and XOR reduction under jit (bit-identical to the
    host path — integer table arithmetic has no rounding)."""
    import jax.numpy as jnp

    cells = np.ascontiguousarray(cells, dtype=np.uint8)
    present = np.asarray(present, dtype=bool)
    k = cells.shape[0] // 2
    avail = np.nonzero(present)[0]
    if avail.size < k:
        raise ValueError(
            f"reconstruction needs >= {k} of {2 * k} cells, got {avail.size}")
    sel = avail[:k]
    interp = lagrange_matrix(tuple(int(x) for x in sel), tuple(range(k)))
    ext = extension_matrix(k)

    ok, data = _reconstruct_kernel()(
        jnp.asarray(interp), jnp.asarray(ext),
        jnp.asarray(cells[sel]), jnp.asarray(cells),
        jnp.asarray(present))
    return bool(ok), np.asarray(data, dtype=np.uint8)


# --- backend dispatch ---------------------------------------------------------

def verify_das_samples(batch: DasSampleBatch) -> dict:
    """Verify a coalesced sample batch through the active backend."""
    from pos_evolution_tpu.backend import get_backend
    fn = getattr(get_backend(), "das_verify", None)
    return verify_samples_host(batch) if fn is None else fn(batch)


def reconstruct_check(cells: np.ndarray, present: np.ndarray
                      ) -> tuple[bool, np.ndarray]:
    """Erasure-consistency check through the active backend."""
    from pos_evolution_tpu.backend import get_backend
    fn = getattr(get_backend(), "das_reconstruct", None)
    return reconstruct_check_host(cells, present) if fn is None \
        else fn(cells, present)
