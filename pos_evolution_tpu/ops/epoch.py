"""Dense epoch processing on device (north-star config #4).

The full-registry sweeps of ``process_epoch`` (SURVEY.md §2.2, §2.8;
pos-evolution.md:122-133, 793-852, 361-369) as one jitted pure function
over a struct-of-arrays ``DenseRegistry``: justification/finalization
tallies (masked reductions), inactivity scores, Altair flag rewards and
penalties, the slashings penalty sweep, and the hysteresis effective-balance
update, plus the participation-flag rotation.

All integer arithmetic is int64 (exact Gwei semantics; differential tests
assert bit-identical results against the NumPy spec oracle). Registry
churn (eligibility marking, churn-limited ejections, the activation
dequeue) is also available on device via ``registry_churn_dense``.

The sharded multi-chip version in ``parallel/sharded.py`` wraps these same
functions in ``shard_map`` with ``psum`` over the validator axis.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import numpy as np

import jax

from pos_evolution_tpu.backend.jax_init import ensure_x64
ensure_x64()

import jax.numpy as jnp  # noqa: E402

from pos_evolution_tpu.config import (  # noqa: E402
    PARTICIPATION_FLAG_WEIGHTS,
    TIMELY_HEAD_FLAG_INDEX,
    TIMELY_TARGET_FLAG_INDEX,
    WEIGHT_DENOMINATOR,
    Config,
)

# FAR_FUTURE_EPOCH (2**64-1) does not fit int64; densification maps it to
# this sentinel. All epoch comparisons behave identically.
FAR_FUTURE_I64 = np.int64(2**62)


class DenseRegistry(NamedTuple):
    """Struct-of-arrays registry + per-epoch participation (the array level
    of SURVEY.md §7)."""

    effective_balance: jax.Array     # int64[N] Gwei
    balance: jax.Array               # int64[N] Gwei
    activation_epoch: jax.Array      # int64[N]
    exit_epoch: jax.Array            # int64[N]
    withdrawable_epoch: jax.Array    # int64[N]
    slashed: jax.Array               # bool[N]
    prev_flags: jax.Array            # uint8[N]
    cur_flags: jax.Array             # uint8[N]
    inactivity_scores: jax.Array     # int64[N]


class EpochResult(NamedTuple):
    registry: DenseRegistry
    total_active_balance: jax.Array      # int64 scalar
    prev_target_balance: jax.Array       # int64 scalar
    cur_target_balance: jax.Array        # int64 scalar
    justify_prev: jax.Array              # bool scalar
    justify_cur: jax.Array               # bool scalar
    new_justification_bits: jax.Array    # bool[4]
    finalize_epoch: jax.Array            # int64 scalar (-1 = no finalization)


def _epochs_to_i64_np(a: np.ndarray) -> np.ndarray:
    """uint64 epoch column -> int64 with FAR_FUTURE mapped to the
    sentinel, host-side (the sharded densify path places these slices
    directly, never through a single-device buffer)."""
    a = a.astype(np.uint64)
    out = np.where(a == np.uint64(2**64 - 1), np.uint64(FAR_FUTURE_I64), a)
    return out.astype(np.int64)


def _epochs_to_i64(a: np.ndarray) -> jax.Array:
    """uint64 epoch column -> int64 with FAR_FUTURE mapped to the sentinel."""
    return jnp.asarray(_epochs_to_i64_np(a))


def i64_to_epochs(col) -> np.ndarray:
    """Inverse of ``_epochs_to_i64``: sentinel back to FAR_FUTURE uint64."""
    a = np.array(col).astype(np.uint64)
    return np.where(a == np.uint64(FAR_FUTURE_I64), np.uint64(2**64 - 1), a)


def densify(state) -> DenseRegistry:
    """Extract the dense arrays from a spec-level BeaconState (host)."""
    return DenseRegistry(*(jnp.asarray(a) for a in densify_np(state)))


def pad_registry(reg: DenseRegistry, n_to: int) -> DenseRegistry:
    """Pad registry columns to ``n_to`` rows with **inert validators**:
    never active (activation epoch at the FAR_FUTURE sentinel), zero
    balances, unslashed, zero flags — every mask in ``epoch_core`` and
    ``registry_churn_dense`` evaluates False on them and every reduction
    they touch contributes zero, so a padded sweep is bit-identical to
    the unpadded one on the first ``n`` rows. This is the divisibility
    shim for the sharded epoch pass (validator axis must divide by the
    mesh device count); callers slice outputs back with
    ``tree_map(lambda a: a[:n], ...)``."""
    fills = {
        "effective_balance": 0, "balance": 0,
        "activation_epoch": FAR_FUTURE_I64, "exit_epoch": FAR_FUTURE_I64,
        "withdrawable_epoch": FAR_FUTURE_I64, "slashed": False,
        "prev_flags": 0, "cur_flags": 0, "inactivity_scores": 0,
    }
    cols = {}
    for f in DenseRegistry._fields:
        a = np.asarray(getattr(reg, f))
        if a.shape[0] < n_to:
            pad = np.full((n_to - a.shape[0],) + a.shape[1:], fills[f],
                          a.dtype)
            a = np.concatenate([a, pad])
        cols[f] = a
    return DenseRegistry(**cols)


def densify_np(state) -> DenseRegistry:
    """Host-numpy twin of ``densify`` (no device buffers): the staging
    form the sharded placement path slices from."""
    reg = state.validators
    return DenseRegistry(
        effective_balance=reg.effective_balance.astype(np.int64),
        balance=state.balances.astype(np.int64),
        activation_epoch=_epochs_to_i64_np(reg.activation_epoch),
        exit_epoch=_epochs_to_i64_np(reg.exit_epoch),
        withdrawable_epoch=_epochs_to_i64_np(reg.withdrawable_epoch),
        slashed=np.asarray(reg.slashed),
        prev_flags=np.asarray(state.previous_epoch_participation),
        cur_flags=np.asarray(state.current_epoch_participation),
        inactivity_scores=state.inactivity_scores.astype(np.int64),
    )


def densify_sharded(state, mesh) -> tuple[DenseRegistry, int]:
    """Densify directly onto the mesh: columns are padded to a multiple
    of the device count and placed sharded over the validator axes via
    per-shard slice callbacks (``parallel/partition.shard_leaf``) — no
    full-size single-device buffer exists at any point. Returns
    (sharded registry, real row count)."""
    from pos_evolution_tpu.parallel.sharded import shard_registry
    reg = densify_np(state)
    n = reg.balance.shape[0]
    npad = ((n + mesh.size - 1) // mesh.size) * mesh.size
    return shard_registry(mesh, pad_registry(reg, npad)), n


def masked_stake_host(mask: np.ndarray, weight: np.ndarray) -> int:
    """Host twin of ``parallel/sharded.masked_stake_for``: summed int64
    stake where ``mask`` — the monitors' gathered-tally oracle (int64
    addition reassociates exactly, so host == sharded bit-for-bit)."""
    return int(np.sum(np.where(np.asarray(mask), np.asarray(weight), 0),
                      dtype=np.int64))


def isqrt_i64(x):
    """Exact integer sqrt for non-negative int64 via float estimate + fixup."""
    s = jnp.floor(jnp.sqrt(x.astype(jnp.float64))).astype(jnp.int64)
    s = jnp.where((s + 1) * (s + 1) <= x, s + 1, s)
    s = jnp.where(s * s > x, s - 1, s)
    return s


def _active(reg: DenseRegistry, epoch):
    return (reg.activation_epoch <= epoch) & (epoch < reg.exit_epoch)


def _has_flag(flags, idx: int):
    return ((flags >> np.uint8(idx)) & np.uint8(1)).astype(bool)


def _masked_sum(values, mask):
    return jnp.sum(jnp.where(mask, values, 0))


def _identity(x):
    return x


def epoch_core(reg: DenseRegistry,
               current_epoch,
               finalized_epoch,
               justification_bits,
               prev_justified_epoch,
               cur_justified_epoch,
               slashings_sum,
               cfg: Config,
               reduce_fn=_identity) -> EpochResult:
    """One epoch boundary over the dense registry.

    Mirrors the spec-layer pipeline order exactly: justification tallies ->
    inactivity updates -> rewards/penalties (using the *new* inactivity
    scores) -> slashings sweep -> hysteresis -> flag rotation.

    ``reduce_fn`` wraps every registry-wide scalar reduction. Identity on a
    single chip; ``lax.psum`` over the validator mesh axes in the
    ``shard_map``-ped multi-chip pass (parallel/sharded.py) — the ICI
    allreduce of north-star config #4.
    """
    current_epoch = jnp.asarray(current_epoch, dtype=jnp.int64)
    prev_epoch = jnp.maximum(current_epoch - 1, 0)
    incr = np.int64(cfg.effective_balance_increment)

    active_cur = _active(reg, current_epoch)
    active_prev = _active(reg, prev_epoch)
    eff = reg.effective_balance

    total_active = jnp.maximum(incr, reduce_fn(_masked_sum(eff, active_cur)))

    # --- justification tallies (pos-evolution.md:793-803) ---
    prev_target_mask = (active_prev
                        & _has_flag(reg.prev_flags, TIMELY_TARGET_FLAG_INDEX)
                        & ~reg.slashed)
    cur_target_mask = (active_cur
                       & _has_flag(reg.cur_flags, TIMELY_TARGET_FLAG_INDEX)
                       & ~reg.slashed)
    prev_target = jnp.maximum(incr, reduce_fn(_masked_sum(eff, prev_target_mask)))
    cur_target = jnp.maximum(incr, reduce_fn(_masked_sum(eff, cur_target_mask)))

    past_genesis = current_epoch > 1
    justify_prev = past_genesis & (prev_target * 3 >= total_active * 2)
    justify_cur = past_genesis & (cur_target * 3 >= total_active * 2)

    # Shift bits and apply the 2/3 rules (pos-evolution.md:827-837).
    bits = justification_bits
    new_bits = jnp.where(
        past_genesis,
        jnp.stack([justify_cur, justify_prev | bits[0], bits[1], bits[2]]),
        bits)

    # 4-case 2-finalization on epoch numbers (pos-evolution.md:842-851);
    # the caller maps the winning epoch back to its checkpoint root.
    new_prev_just = jnp.where(past_genesis, cur_justified_epoch, prev_justified_epoch)
    old_prev, old_cur = prev_justified_epoch, cur_justified_epoch
    fin = jnp.int64(-1)
    fin = jnp.where(new_bits[1] & new_bits[2] & new_bits[3]
                    & (old_prev + 3 == current_epoch), old_prev, fin)
    fin = jnp.where(new_bits[1] & new_bits[2]
                    & (old_prev + 2 == current_epoch), old_prev, fin)
    fin = jnp.where(new_bits[0] & new_bits[1] & new_bits[2]
                    & (old_cur + 2 == current_epoch), old_cur, fin)
    fin = jnp.where(new_bits[0] & new_bits[1]
                    & (old_cur + 1 == current_epoch), old_cur, fin)
    fin = jnp.where(past_genesis, fin, jnp.int64(-1))

    # --- inactivity scores (pos-evolution.md:369) ---
    eligible = active_prev | (reg.slashed & (prev_epoch + 1 < reg.withdrawable_epoch))
    target_participating = prev_target_mask
    finality_delay = prev_epoch - finalized_epoch
    in_leak = finality_delay > 4
    scores = reg.inactivity_scores
    scores = jnp.where(eligible & target_participating,
                       jnp.maximum(scores - 1, 0), scores)
    scores = jnp.where(eligible & ~target_participating,
                       scores + np.int64(cfg.inactivity_score_bias), scores)
    scores = jnp.where(~in_leak & eligible,
                       scores - jnp.minimum(
                           scores, np.int64(cfg.inactivity_score_recovery_rate)),
                       scores)
    new_scores = jnp.where(current_epoch > 0, scores, reg.inactivity_scores)

    # --- rewards & penalties (Altair flag deltas) ---
    base_reward = (eff // incr) * (
        incr * np.int64(cfg.base_reward_factor) // isqrt_i64(total_active))
    active_increments = total_active // incr

    rewards = jnp.zeros_like(eff)
    penalties = jnp.zeros_like(eff)
    for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
        participating = (active_prev
                         & _has_flag(reg.prev_flags, flag_index)
                         & ~reg.slashed)
        participating_increments = reduce_fn(_masked_sum(eff, participating)) // incr
        numer = base_reward * np.int64(weight) * participating_increments
        denom = active_increments * np.int64(WEIGHT_DENOMINATOR)
        rewards = rewards + jnp.where(~in_leak & eligible & participating,
                                      numer // denom, 0)
        if flag_index != TIMELY_HEAD_FLAG_INDEX:
            penalties = penalties + jnp.where(
                eligible & ~participating,
                base_reward * np.int64(weight) // np.int64(WEIGHT_DENOMINATOR), 0)

    inactivity_penalty = (eff * new_scores
                          // np.int64(cfg.inactivity_score_bias
                                      * cfg.inactivity_penalty_quotient))
    penalties = penalties + jnp.where(eligible & ~target_participating,
                                      inactivity_penalty, 0)
    new_balance = jnp.where(current_epoch > 0,
                            jnp.maximum(reg.balance + rewards - penalties, 0),
                            reg.balance)

    # --- slashings sweep (proportional penalties) ---
    vector_half = np.int64(cfg.epochs_per_slashings_vector // 2)
    adjusted_total = jnp.minimum(
        slashings_sum * np.int64(cfg.proportional_slashing_multiplier), total_active)
    hit = reg.slashed & (current_epoch + vector_half == reg.withdrawable_epoch)
    slash_penalty = (eff // incr * adjusted_total) // total_active * incr
    new_balance = jnp.maximum(new_balance - jnp.where(hit, slash_penalty, 0), 0)

    # --- hysteresis effective-balance update (pos-evolution.md:122-133) ---
    h_incr = np.int64(cfg.effective_balance_increment // cfg.hysteresis_quotient)
    downward = h_incr * np.int64(cfg.hysteresis_downward_multiplier)
    upward = h_incr * np.int64(cfg.hysteresis_upward_multiplier)
    needs = ((new_balance + downward < eff) | (eff + upward < new_balance))
    new_eff = jnp.where(
        needs,
        jnp.minimum(new_balance - new_balance % incr,
                    np.int64(cfg.max_effective_balance)),
        eff)

    new_reg = reg._replace(
        effective_balance=new_eff,
        balance=new_balance,
        inactivity_scores=new_scores,
        prev_flags=reg.cur_flags,
        cur_flags=jnp.zeros_like(reg.cur_flags),
    )
    return EpochResult(
        registry=new_reg,
        total_active_balance=total_active,
        prev_target_balance=prev_target,
        cur_target_balance=cur_target,
        justify_prev=justify_prev,
        justify_cur=justify_cur,
        new_justification_bits=new_bits,
        finalize_epoch=fin,
    )


@partial(jax.jit, static_argnames=("cfg",))
def process_epoch_dense(reg: DenseRegistry,
                        current_epoch,
                        finalized_epoch,
                        justification_bits,
                        prev_justified_epoch,
                        cur_justified_epoch,
                        slashings_sum,
                        cfg: Config) -> EpochResult:
    """Single-chip jitted epoch boundary (reduce = local sum)."""
    return epoch_core(reg, current_epoch, finalized_epoch, justification_bits,
                      prev_justified_epoch, cur_justified_epoch, slashings_sum,
                      cfg)


# --- registry churn on device (activation queue + ejections) -----------------

class ChurnResult(NamedTuple):
    activation_eligibility_epoch: jax.Array
    activation_epoch: jax.Array
    exit_epoch: jax.Array
    withdrawable_epoch: jax.Array


def densify_eligibility(state) -> jax.Array:
    """activation_eligibility_epoch column (not part of DenseRegistry's
    sweep pytree; only the churn kernel needs it)."""
    return _epochs_to_i64(state.validators.activation_eligibility_epoch)


@partial(jax.jit, static_argnames=("cfg",))
def registry_churn_dense(reg: DenseRegistry,
                         activation_eligibility_epoch,
                         current_epoch,
                         finalized_epoch,
                         cfg: Config) -> ChurnResult:
    """Device form of ``process_registry_updates`` (SURVEY.md §2.6):
    eligibility marking, balance ejections through the churn-limited exit
    queue, and the activation dequeue — bit-identical to the spec loop.

    The spec assigns exit epochs sequentially (each ejection re-reads the
    queue tail); the closed form below reproduces that exactly: the k-th
    ejection (index order) lands at
      base + (existing + k) // limit          if existing < limit
      base + 1 + k // limit                   otherwise
    where base = max(max existing exit epoch, activation_exit_epoch(cur)).
    """
    current_epoch = jnp.asarray(current_epoch, dtype=jnp.int64)
    far = FAR_FUTURE_I64

    # churn limit from the current active count
    active = _active(reg, current_epoch)
    n_active = jnp.sum(active)
    limit = jnp.maximum(np.int64(cfg.min_per_epoch_churn_limit),
                        n_active // np.int64(cfg.churn_limit_quotient))

    # 1) eligibility marking
    newly_eligible = ((activation_eligibility_epoch == far)
                      & (reg.effective_balance == np.int64(cfg.max_effective_balance)))
    eligibility = jnp.where(newly_eligible, current_epoch + 1,
                            activation_eligibility_epoch)

    # 2) ejections through the exit queue
    ejectable = (active
                 & (reg.effective_balance <= np.int64(cfg.ejection_balance))
                 & (reg.exit_epoch == far))
    exiting = reg.exit_epoch != far
    max_exit = jnp.max(jnp.where(exiting, reg.exit_epoch, 0))  # 0 if none
    act_exit = current_epoch + 1 + np.int64(cfg.max_seed_lookahead)
    base = jnp.maximum(max_exit, act_exit)
    existing = jnp.sum(exiting & (reg.exit_epoch == base))
    k = jnp.cumsum(ejectable) - 1  # rank among ejectable, index order
    epoch_lt = base + (existing + k) // limit
    epoch_ge = base + 1 + k // limit
    assigned = jnp.where(existing < limit, epoch_lt, epoch_ge)
    exit_epoch = jnp.where(ejectable, assigned, reg.exit_epoch)
    withdrawable = jnp.where(
        ejectable,
        assigned + np.int64(cfg.min_validator_withdrawability_delay),
        reg.withdrawable_epoch)

    # 3) activation dequeue: (eligibility, index) order, up to the limit
    queued = ((eligibility <= finalized_epoch) & (reg.activation_epoch == far))
    n = reg.activation_epoch.shape[0]
    idx = jnp.arange(n, dtype=jnp.int64)
    # single sortable key: eligibility * n + index (eligibility < 2^62 / n
    # for any realistic registry; non-queued pushed to the end)
    key = jnp.where(queued, eligibility * np.int64(n) + idx, np.int64(2**63 - 1))
    order = jnp.argsort(key)
    rank = jnp.zeros(n, dtype=jnp.int64).at[order].set(idx)
    dequeued = queued & (rank < limit)
    activation = jnp.where(dequeued, act_exit, reg.activation_epoch)

    return ChurnResult(
        activation_eligibility_epoch=eligibility,
        activation_epoch=activation,
        exit_epoch=exit_epoch,
        withdrawable_epoch=withdrawable,
    )
