"""Device-resident merkleization: the dispatch layer behind every
SHA-256 level sweep (ROADMAP item 4, DESIGN.md §22).

Incremental SSZ (PR 6) made merkleization do *less* hashing; this module
decides where the remaining hashes RUN. Every consumer of the merkle
combiner — ``ssz/incremental.py`` dirty-path rehashes,
``das/commitment.MerkleCellScheme`` leaf-tree builds + proof-branch
extraction, ``ops/das_verify`` sample batches, the resilience checkpoint
payload digests, the dense driver's state witness — funnels its level
sweeps through ``pair_hash``, which picks a path per call:

- **device** (jax backend active, batch past the measured crossover):
  the batched SHA-256 kernel — the Pallas merkle-level kernel
  (``ops/pallas_sha256.merkle_level_pallas``) when an accelerator is
  attached and the padded batch fills its 512-lane tiles, else the
  jitted XLA formulation (``ops/sha256.sha256_pair_words``). Batches are
  padded to the next power of two so the shape lattice (and therefore
  the retrace count) stays logarithmic.
- **host**: ``ssz/hash.sha256_pairs`` (native C++ core when built,
  vectorized NumPy lanes otherwise) — bit-identical by construction
  (SHA-256 is exact integer arithmetic on every path).

The **fallback ladder** is Pallas -> XLA -> NumPy: a missing/broken
Pallas lowering drops to XLA (counted ``fallback_xla``), a missing or
failing jax drops all the way to the host path (``fallback_numpy``) —
a degraded box computes the same roots, slower, loudly (telemetry).

Dispatch is sized, not assumed: ``Config.merkle_device_min_pairs`` is
the crossover below which the fixed device-dispatch overhead loses to
the host path (measured by ``scripts/bench_merkle.py``; the device wins
only on real accelerators, so the *auto* mode also stays on host when
jax is running on CPU). ``set_mode`` forces ``"device"``/``"host"`` for
parity tests and benches. Every decision lands in ``stats()`` — the sim
driver snapshots the deltas per slot and ``run_report.py`` renders the
device-vs-host split and device sweep throughput.

``LevelSweeper`` is the batching half of the tentpole: a lockstep
coordinator that advances MANY trees' dirty-path updates one level per
round and hashes all of a round's pairs in ONE ``pair_hash`` call — one
kernel launch services every dirty path of a ``ContainerTreeCache``
rehash instead of one call per level per field (the MTU tree-unit shape
of arxiv 2507.16793: one tree-structured datapath serving merkleization,
multiproof generation and verification).

Import-time contract: this module imports numpy only; jax is reached
lazily on the first device-eligible sweep (the numpy backend never pays
for it), and process-global jax config goes through
``backend/jax_init.ensure_x64`` — never a module-import side effect.
"""

from __future__ import annotations

import threading
import time
from functools import lru_cache

import numpy as np

from pos_evolution_tpu.ssz.hash import sha256_pairs
from pos_evolution_tpu.ssz.merkle import (
    ZERO_HASHES,
    _tree_levels,
    build_multiproof,
    merkleize_chunks,
    mix_in_length,
)

__all__ = [
    "pair_hash", "merkle_level_device", "merkleize", "tree_levels",
    "build_multiproof_paths", "build_multiproof_paths_host",
    "multiproof", "digest_bytes",
    "LevelSweeper", "drive", "set_mode", "get_mode", "stats",
    "reset_stats", "device_eligible", "small_batch_floor", "DIGEST_ALGO",
]

# Manifest tag for the merkle payload digest (resilience/manager.py):
# 32-byte chunks (zero-padded), SSZ vector-rule merkleization, byte
# length mixed in. Host and device paths produce identical bytes.
DIGEST_ALGO = "merkle32-sha256-v1"

_MODES = ("auto", "device", "host")
_MODE = "auto"

# Cumulative process counters; the sim driver feeds per-slot deltas to
# its MetricsRegistry (``merkle.*``) and run_report.py renders them.
# Locked: pair_hash is reached from serve-tier worker threads (proof
# builds) and the async checkpoint writer, not just the sim loop.
_STATS = {
    "device_sweeps": 0,    # level sweeps that ran on the device path
    "host_sweeps": 0,      # level sweeps served by the host kernel
    "device_pairs": 0,     # sibling pairs hashed on device
    "host_pairs": 0,       # sibling pairs hashed on host
    "fallback_xla": 0,     # Pallas unavailable/failed -> XLA
    "fallback_numpy": 0,   # jax unavailable/failed -> NumPy host
    "batched_launches": 0,  # LevelSweeper rounds (one launch each)
    "batched_jobs": 0,     # tree-update jobs coalesced into those rounds
    "device_ms": 0.0,      # wall-clock spent in device sweeps
}
_STATS_LOCK = threading.Lock()


def _bump(**deltas) -> None:
    with _STATS_LOCK:
        for k, v in deltas.items():
            _STATS[k] += v


def stats() -> dict:
    with _STATS_LOCK:
        return dict(_STATS)


def reset_stats() -> None:
    with _STATS_LOCK:
        for k in _STATS:
            _STATS[k] = 0.0 if k == "device_ms" else 0


def set_mode(mode: str) -> str:
    """Force the dispatch decision: ``"device"`` (always device when the
    jax backend is active), ``"host"`` (never device), ``"auto"``
    (threshold + accelerator crossover). Returns the previous mode."""
    global _MODE
    if mode not in _MODES:
        raise ValueError(f"merkle dispatch mode must be one of {_MODES}")
    prev, _MODE = _MODE, mode
    return prev


def get_mode() -> str:
    return _MODE


def _min_pairs() -> int:
    from pos_evolution_tpu.config import cfg
    return cfg().merkle_device_min_pairs


def small_batch_floor(per_item_pairs: int = 1) -> int:
    """The measured crossover, exported for sibling dispatchers.
    ``per_item_pairs`` converts units: the knob is sized in sibling-PAIR
    compressions, so a dispatcher whose batch items are heavier (a DAS
    sample = cell-hash blocks + a depth-deep branch walk, ~16
    compressions) divides the floor accordingly — same total-work
    crossover, different item count."""
    return max(_min_pairs() // max(per_item_pairs, 1), 1)


def device_eligible(n_pairs: int) -> bool:
    """Would a sweep of ``n_pairs`` sibling pairs go to the device?"""
    if _MODE == "host" or n_pairs <= 0:
        return False
    from pos_evolution_tpu.backend import get_backend
    if getattr(get_backend(), "name", "") != "jax":
        return False
    if _MODE == "device":
        return True
    if n_pairs < _min_pairs():
        return False
    try:
        import jax
        # jax-on-CPU is the same silicon as the host kernel plus
        # dispatch overhead — the crossover never arrives (measured in
        # bench_merkle); real accelerators flip this.
        return jax.default_backend() != "cpu"
    except Exception:
        return False


# --- word/byte plumbing -------------------------------------------------------

def _rows_to_words(rows: np.ndarray) -> np.ndarray:
    """(N, 32) u8 digest rows -> (N, 8) u32 big-endian words."""
    return np.ascontiguousarray(rows, dtype=np.uint8).reshape(
        -1, 8, 4).view(">u4")[..., 0].astype(np.uint32)


def _words_to_rows(words) -> np.ndarray:
    """(N, 8) u32 words -> (N, 32) u8 digest rows."""
    return np.asarray(words, dtype=np.uint32).astype(
        ">u4").view(np.uint8).reshape(-1, 32)


def _next_pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


# Device batches are padded UP to at least this many pairs: the tail
# levels of a tree sweep (1, 2, 4, ... pairs) would otherwise each mint
# their own compiled shape — one padded floor shape absorbs them all,
# and hashing a few dozen zero pairs is cheaper than one retrace.
_MIN_PAD_PAIRS = 128


# --- device kernels (the fallback ladder) -------------------------------------

@lru_cache(maxsize=None)
def _xla_level_for():
    """Memoized jitted XLA level kernel: (N, 16) u32 message words
    (left||right digest words per pair) -> (N, 8) u32 digests. Built
    once per process; retraces only per padded (pow2) batch shape."""
    import jax

    from pos_evolution_tpu.backend.jax_init import ensure_x64
    ensure_x64()

    from pos_evolution_tpu.ops.sha256 import sha256_pair_words

    @jax.jit
    def level(words16):
        return sha256_pair_words(words16[:, :8], words16[:, 8:])

    return level


def _pallas_usable(m: int) -> bool:
    """Top rung precondition: a real accelerator and a padded batch that
    fills the kernel's lane tiles. Split out so the ladder tests can
    force the rung on a CPU box and watch the fallback trip."""
    try:
        import jax

        from pos_evolution_tpu.ops.pallas_sha256 import TILE
    except Exception:
        return False
    return m % TILE == 0 and jax.default_backend() != "cpu"


def _pallas_level(words16: np.ndarray) -> np.ndarray:
    """Pallas rung: (N, 16) u32, N a multiple of TILE. Raises on any
    failure — the caller's ladder catches and drops to XLA."""
    import jax.numpy as jnp

    from pos_evolution_tpu.ops.pallas_sha256 import merkle_level_pallas
    return np.asarray(merkle_level_pallas(
        jnp.asarray(np.ascontiguousarray(words16.T))).T)


def merkle_level_device(left: np.ndarray, right: np.ndarray) -> np.ndarray:
    """One merkle level on the device path: (N, 32)+(N, 32) u8 -> (N, 32)
    u8 digests, Pallas -> XLA -> NumPy ladder. This is the jax backend's
    ``merkle_level`` method; ``pair_hash`` reaches it via dispatch."""
    n = left.shape[0]
    words = np.concatenate(
        [_rows_to_words(left), _rows_to_words(right)], axis=1)
    m = max(_next_pow2(n), _MIN_PAD_PAIRS)
    if m != n:  # pad to pow2: bounded shape lattice, sliced back below
        padded = np.zeros((m, 16), dtype=np.uint32)
        padded[:n] = words
        words = padded
    t0 = time.perf_counter()
    try:
        if _pallas_usable(m):
            try:
                out_words = _pallas_level(words)
            except Exception:
                _bump(fallback_xla=1)
                import jax.numpy as jnp
                out_words = _xla_level_for()(jnp.asarray(words))
        else:
            import jax.numpy as jnp
            out_words = _xla_level_for()(jnp.asarray(words))
        rows = _words_to_rows(out_words)[:n]
    except Exception:
        # jax itself missing/broken: the bottom rung still answers
        _bump(fallback_numpy=1, host_sweeps=1, host_pairs=n)
        return sha256_pairs(np.ascontiguousarray(left),
                            np.ascontiguousarray(right))
    _bump(device_sweeps=1, device_pairs=n,
          device_ms=(time.perf_counter() - t0) * 1e3)
    return rows


def pair_hash(left: np.ndarray, right: np.ndarray) -> np.ndarray:
    """THE merkle combiner: sha256(left[i] || right[i]) over (N, 32) u8
    rows, dispatched device/host per the module policy. Bit-identical on
    every path."""
    n = left.shape[0]
    if n == 0:
        return np.empty((0, 32), dtype=np.uint8)
    if device_eligible(n):
        from pos_evolution_tpu.backend import get_backend
        fn = getattr(get_backend(), "merkle_level", None)
        if fn is not None:
            return fn(left, right)
    _bump(host_sweeps=1, host_pairs=n)
    return sha256_pairs(np.ascontiguousarray(left),
                        np.ascontiguousarray(right))


# --- whole trees --------------------------------------------------------------

def merkleize(chunks: np.ndarray, limit: int | None = None) -> bytes:
    """``ssz.merkle.merkleize_chunks`` semantics (virtual zero padding to
    ``limit``, vector rule when ``limit=None``) with every level routed
    through ``pair_hash``. Small/ineligible trees delegate to the host
    whole-tree path unchanged."""
    chunks = np.ascontiguousarray(chunks, dtype=np.uint8)
    if chunks.ndim == 1:
        chunks = chunks.reshape(-1, 32)
    if not device_eligible(chunks.shape[0] // 2):
        # whole-tree host fast path (one native call); counted so the
        # device/host split stays honest — a padded binary tree over
        # count leaves hashes count-1 internal pairs plus the zero cap
        if chunks.shape[0] > 1:
            _bump(host_sweeps=1, host_pairs=chunks.shape[0] - 1)
        return merkleize_chunks(chunks, limit)
    # the ONE padded walk, with the dispatching combiner
    return merkleize_chunks(chunks, limit, combine=pair_hash)


def tree_levels(leaves: np.ndarray, depth: int) -> list[np.ndarray]:
    """All levels of the padded tree, leaves first: the ONE
    ``ssz.merkle._tree_levels`` walk with the dispatching ``pair_hash``
    as its combiner — each level one (host-or-device) sweep. Virtual
    zero padding stays virtual — callers read out-of-range nodes from
    ``ZERO_HASHES``."""
    return _tree_levels(leaves, depth, combine=pair_hash)


def _paths_from_levels(levels: list[np.ndarray], indices, depth: int
                       ) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized sibling gather off a built tree: ``(leaves[indices],
    (S, depth, 32) branches)`` — replaces per-index Python walks."""
    idx = np.asarray(indices, dtype=np.int64).reshape(-1)
    out = np.zeros((idx.size, depth, 32), dtype=np.uint8)
    cur = idx.copy()
    for d in range(depth):
        layer = levels[d]
        sib = cur ^ 1
        in_range = sib < layer.shape[0]
        if in_range.any():
            out[in_range, d] = layer[sib[in_range]]
        if (~in_range).any():
            out[~in_range, d] = ZERO_HASHES[d]
        cur >>= 1
    return levels[0][idx], out


def build_multiproof_paths(leaves: np.ndarray, indices, depth: int
                           ) -> tuple[np.ndarray, np.ndarray]:
    """Batched proof-branch extraction: one shared tree build (device
    level sweeps when eligible), then the vectorized sibling gather —
    the shape the batched sample-verification kernel consumes."""
    return _paths_from_levels(tree_levels(leaves, depth), indices, depth)


def build_multiproof_paths_host(leaves: np.ndarray, indices, depth: int
                                ) -> tuple[np.ndarray, np.ndarray]:
    """Host-pinned twin (the numpy backend's method): the tree builds on
    ``sha256_pairs`` regardless of the thread's active backend or the
    dispatch mode — an oracle must not depend on the thing it oracles."""
    return _paths_from_levels(
        _tree_levels(leaves, depth, combine=sha256_pairs), indices, depth)


def multiproof(leaves: np.ndarray, leaf_indices, depth: int) -> list[bytes]:
    """``ssz.merkle.build_multiproof`` with the shared tree built through
    the dispatch layer (same helper order, same bytes)."""
    return build_multiproof(leaves, leaf_indices, depth, combine=pair_hash)


# --- byte-blob digests --------------------------------------------------------

def digest_bytes(blob) -> bytes:
    """Length-bound merkle digest of a byte string (``DIGEST_ALGO``):
    32-byte chunks (tail zero-padded), vector-rule merkleization through
    the dispatch layer, byte length mixed in. The device-portable stand-in
    for a linear sha256 over checkpoint payloads / witness columns —
    identical bytes whichever path hashed it."""
    data = np.frombuffer(blob, dtype=np.uint8) if isinstance(
        blob, (bytes, bytearray, memoryview)) else \
        np.ascontiguousarray(blob, dtype=np.uint8).reshape(-1)
    n = int(data.size)
    if n == 0:
        chunks = np.empty((0, 32), dtype=np.uint8)
    elif n % 32 == 0:
        chunks = data.reshape(-1, 32)
    else:
        padded = np.zeros(((n + 31) // 32) * 32, dtype=np.uint8)
        padded[:n] = data
        chunks = padded.reshape(-1, 32)
    return mix_in_length(merkleize(chunks), n)


# --- lockstep batching --------------------------------------------------------

class LevelSweeper:
    """Coalesce many trees' level sweeps into one kernel launch per
    level. Jobs are generators that yield ``(left, right)`` pair blocks
    and receive the digests back via ``send``; each ``run`` round
    concatenates every active job's current block, hashes it with ONE
    ``pair_hash`` call, and scatters the digests back. Trees advance in
    lockstep — level k of every tree hashes together, which is what
    turns a ``ContainerTreeCache`` rehash from one call per level per
    field into one launch per level."""

    def __init__(self):
        self._jobs: list = []

    def add(self, gen) -> None:
        """Register one tree-update generator (primed to its first pair
        block; a generator with no hashing to do completes here)."""
        try:
            req = next(gen)
        except StopIteration:
            return
        self._jobs.append((gen, req))

    def run(self) -> None:
        jobs, self._jobs = self._jobs, []
        if jobs:
            _bump(batched_jobs=len(jobs))
        while jobs:
            lefts = [left for _, (left, _r) in jobs]
            rights = [right for _, (_l, right) in jobs]
            digests = pair_hash(
                np.concatenate(lefts) if len(lefts) > 1 else lefts[0],
                np.concatenate(rights) if len(rights) > 1 else rights[0])
            _bump(batched_launches=1)
            nxt = []
            off = 0
            for gen, (left, _right) in jobs:
                k = left.shape[0]
                try:
                    req = gen.send(digests[off:off + k])
                except StopIteration:
                    pass
                else:
                    nxt.append((gen, req))
                off += k
            jobs = nxt


def drive(gen) -> None:
    """Run one tree-update generator standalone: every yielded pair
    block goes straight through ``pair_hash`` (the no-batching twin of
    ``LevelSweeper`` for single-tree callers)."""
    try:
        req = next(gen)
        while True:
            req = gen.send(pair_hash(*req))
    except StopIteration:
        return
