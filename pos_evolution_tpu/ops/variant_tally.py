"""Vectorized hot loops of the protocol-variant seam (variants/, L7).

The successor protocols of the reference's second half — Goldfish,
RLMD-GHOST, single-slot finality (pos-evolution.md:1528-1650) — share
three batch-friendly reductions that dominate their per-slot work:

- **expiry-windowed vote tally** (pos-evolution.md:1585, 1596): per-block
  summed weight of the latest head votes whose slot lies inside
  ``[lo_slot, hi_slot]``, with equivocators and inactive validators
  discounted (:1411, 1438). ``eta = 1`` recovers Goldfish's GHOST-Eph
  (:1549); an unbounded window recovers LMD.
- **supermajority link tally** (pos-evolution.md:1626): per-link summed
  weight of one slot's FFG votes, the justification/finalization input of
  the per-slot FFG gadget; the acknowledgment tally (:1646) is the same
  reduction over ack ids.
- **subtree weight accumulation**: already a backend primitive
  (``subtree_weights``) shared with the dense Gasper fork choice.

Both reductions are a masked ``segment_sum`` — regular, shape-padded,
identical on NumPy and under ``jax.jit`` (the vectorization-first framing
the ISSUE cites from the Elliptic-Net pairing revisit): the host twins
are the bit-exact oracles for the jitted device twins, pinned in
tests/test_variant_seam.py. Variants reach them through
``ExecutionBackend`` (``backend.variant_tally`` / ``backend.link_tally``),
never per-message Python.

Shape discipline: vote/link batches pad to the next power of two with
``active=False`` rows and segment counts pad likewise, so the jitted
kernels see a small lattice of shapes instead of one per (votes, blocks)
pair (the compile-storm lesson of ROADMAP item 2).
"""

from __future__ import annotations

from functools import partial

import numpy as np


def next_pow2(x: int) -> int:
    """Shape-padding floor (>= 2) — the jax-free shared helper (this
    module imports only numpy, so the spec/numpy paths and the backend
    module can use it without initializing a jax runtime;
    ``ops.forkchoice.next_pow2`` is the same function in the jax-only
    half of the codebase)."""
    return max(int(2 ** np.ceil(np.log2(max(int(x), 2)))), 2)


_next_pow2 = next_pow2  # internal call sites / backward compatibility


# --- host twins (the bit-exact oracles) ---------------------------------------


def windowed_vote_tally_host(block_idx: np.ndarray, vote_slot: np.ndarray,
                             weight: np.ndarray, active: np.ndarray,
                             lo_slot: int, hi_slot: int,
                             n_blocks: int) -> np.ndarray:
    """Per-block summed weight of votes inside the expiry window.

    ``block_idx[K]`` int (−1 = no vote), ``vote_slot[K]``, ``weight[K]``
    (Gwei), ``active[K]`` bool (False = equivocating / slashed / exited).
    Returns int64[n_blocks]."""
    block_idx = np.asarray(block_idx, np.int64)
    vote_slot = np.asarray(vote_slot, np.int64)
    weight = np.asarray(weight, np.int64)
    active = np.asarray(active, bool)
    ok = (active & (block_idx >= 0) & (block_idx < n_blocks)
          & (vote_slot >= int(lo_slot)) & (vote_slot <= int(hi_slot)))
    out = np.zeros(n_blocks, np.int64)
    np.add.at(out, block_idx[ok], weight[ok])
    return out


def link_tally_host(link_idx: np.ndarray, weight: np.ndarray,
                    active: np.ndarray, n_links: int) -> np.ndarray:
    """Per-link summed weight (supermajority-link / acknowledgment tally,
    pos-evolution.md:1626, 1646). ``link_idx[K]`` int (−1 = none).
    Returns int64[n_links]."""
    link_idx = np.asarray(link_idx, np.int64)
    weight = np.asarray(weight, np.int64)
    active = np.asarray(active, bool)
    ok = active & (link_idx >= 0) & (link_idx < n_links)
    out = np.zeros(n_links, np.int64)
    np.add.at(out, link_idx[ok], weight[ok])
    return out


# --- device twins -------------------------------------------------------------
#
# jax imports stay lazy (module-load must not pull jax on the numpy
# backend — the ops/transition.py convention).


def _jit_windowed():
    import jax
    from pos_evolution_tpu.backend.jax_init import ensure_x64
    ensure_x64()  # Gwei sums need int64
    import jax.numpy as jnp

    @partial(jax.jit, static_argnames=("nb",))
    def kern(block_idx, vote_slot, weight, active, lo, hi, nb: int):
        ok = (active & (block_idx >= 0) & (block_idx < nb)
              & (vote_slot >= lo) & (vote_slot <= hi))
        seg = jnp.where(ok, block_idx, nb)
        return jax.ops.segment_sum(
            jnp.where(ok, weight, 0), seg, num_segments=nb + 1)[:nb]

    return kern


_windowed_kern = None
_link_kern = None


def windowed_vote_tally_device(block_idx, vote_slot, weight, active,
                               lo_slot: int, hi_slot: int,
                               n_blocks: int) -> np.ndarray:
    """Jitted twin of ``windowed_vote_tally_host``: pad the vote batch and
    the block axis to powers of two, one masked segment_sum on device,
    trim. Bit-identical (int64 adds commute)."""
    global _windowed_kern
    import jax.numpy as jnp
    if _windowed_kern is None:
        _windowed_kern = _jit_windowed()
    k = len(np.asarray(block_idx))
    kp = _next_pow2(max(k, 1))
    nb = _next_pow2(n_blocks)

    def pad(a, fill, dtype):
        a = np.asarray(a, dtype)
        out = np.full(kp, fill, dtype)
        out[:k] = a
        return jnp.asarray(out)

    res = _windowed_kern(pad(block_idx, -1, np.int64),
                         pad(vote_slot, 0, np.int64),
                         pad(weight, 0, np.int64),
                         pad(active, False, bool),
                         jnp.int64(lo_slot), jnp.int64(hi_slot), nb)
    return np.asarray(res)[:n_blocks]


def link_tally_device(link_idx, weight, active, n_links: int) -> np.ndarray:
    """Jitted twin of ``link_tally_host`` (same padding discipline)."""
    global _link_kern
    import jax
    from pos_evolution_tpu.backend.jax_init import ensure_x64
    ensure_x64()  # Gwei sums need int64
    import jax.numpy as jnp
    if _link_kern is None:
        @partial(jax.jit, static_argnames=("nl",))
        def kern(link_idx, weight, active, nl: int):
            ok = active & (link_idx >= 0) & (link_idx < nl)
            seg = jnp.where(ok, link_idx, nl)
            return jax.ops.segment_sum(
                jnp.where(ok, weight, 0), seg, num_segments=nl + 1)[:nl]
        _link_kern = kern
    k = len(np.asarray(link_idx))
    kp = _next_pow2(max(k, 1))
    nl = _next_pow2(n_links)

    def pad(a, fill, dtype):
        a = np.asarray(a, dtype)
        out = np.full(kp, fill, dtype)
        out[:k] = a
        return jnp.asarray(out)

    res = _link_kern(pad(link_idx, -1, np.int64), pad(weight, 0, np.int64),
                     pad(active, False, bool), nl)
    return np.asarray(res)[:n_links]
