"""Batched attestation aggregation + aggregate-verify on device (config #3).

The reference aggregates one BLS signature per committee over
``aggregation_bits`` (pos-evolution.md:714-717) and verifies with
``FastAggregateVerify``; at mainnet scale that is ~1M signers across 2048
committee aggregates per epoch (64 committees x 32 slots, :472-475).

This kernel runs the whole epoch's verification as one batched pipeline:
gather signer pubkeys by committee index, compute each signer's signature
contribution, mask by the aggregation bitlists, XOR-reduce per committee
(segment reduction), and compare against the provided aggregates.

The signature scheme behind the pipeline is the crypto backend's: here the
deterministic ``FakeBLS`` (sha256-based, XOR aggregation — bit-identical to
``crypto/bls.py``), giving the full memory/gather/reduce shape of the real
pipeline. The BLS12-381 pairing kernel (N1) drops into the same interface.
"""

from __future__ import annotations

import numpy as np

import jax

from pos_evolution_tpu.backend.jax_init import ensure_x64
ensure_x64()

import jax.numpy as jnp  # noqa: E402

from pos_evolution_tpu.ops.sha256 import H0, sha256_compress, sha256_words  # noqa: E402

_PREFIX = b"fakebls-sig-pad!"  # matches crypto/bls.py FakeBLS.SIG_PREFIX


def _chain_hash(words):
    """H(digest) for (..., 8) u32 digest words (32-byte message, 1 block)."""
    shape = words.shape[:-1]
    blk = jnp.zeros(shape + (16,), dtype=jnp.uint32)
    blk = blk.at[..., 0:8].set(words)
    blk = blk.at[..., 8].set(np.uint32(0x80000000))
    blk = blk.at[..., 15].set(np.uint32(256))
    return sha256_words(blk)


def precompute_pk_states(pubkeys_u8: np.ndarray) -> jax.Array:
    """Per-validator midstate: SHA-256 state after (prefix | pubkey), the
    first 64-byte block of every signature this validator ever makes.
    pubkeys_u8: (N, 48) uint8 -> (N, 8) uint32. Computed once per registry.
    """
    n = pubkeys_u8.shape[0]
    block = np.zeros((n, 64), dtype=np.uint8)
    block[:, 0:16] = np.frombuffer(_PREFIX, dtype=np.uint8)
    block[:, 16:64] = pubkeys_u8
    words = block.reshape(n, 16, 4)
    w32 = ((words[..., 0].astype(np.uint32) << 24)
           | (words[..., 1].astype(np.uint32) << 16)
           | (words[..., 2].astype(np.uint32) << 8)
           | words[..., 3].astype(np.uint32))
    state = jnp.broadcast_to(jnp.asarray(H0), (n, 8))
    return sha256_compress(state, jnp.asarray(w32))


def _msg_block2(msg_words):
    """Second signature block: msg(32) | 0x80 pad | length(96 bytes).
    msg_words (..., 8) u32 -> (..., 16) u32."""
    shape = msg_words.shape[:-1]
    blk = jnp.zeros(shape + (16,), dtype=jnp.uint32)
    blk = blk.at[..., 0:8].set(msg_words)
    blk = blk.at[..., 8].set(np.uint32(0x80000000))
    blk = blk.at[..., 15].set(np.uint32(96 * 8))
    return blk


def _committee_aggregates(pk_states, committees, bits, msg_words):
    """Shared pipeline of the verify and sign kernels: per-signer
    signature words, masked by the bitlists and XOR-reduced per
    committee -> (A, 24) aggregate words.

    Per signer: one schedule-shared compression (the message block is per
    attestation, so its schedule is computed once per committee and
    broadcast over lanes) + two chain hashes — the fake-scheme analogue of
    the per-signer pairing work a real BLS kernel does.
    """
    states = pk_states[committees]                    # (A, C, 8)
    # (A, 1, 16): the lane axis stays size-1 so the message schedule is
    # genuinely computed once per committee and broadcast inside the round
    # arithmetic. (An explicit broadcast_to(A, C, 16) here also sent XLA's
    # algebraic simplifier into a 50-run circular-simplification loop.)
    block2 = _msg_block2(msg_words)[:, None, :]
    h1 = sha256_compress(states, block2)
    h2 = _chain_hash(h1)
    h3 = _chain_hash(h2)
    sigs = jnp.concatenate([h1, h2, h3], axis=-1)     # (A, C, 24)
    masked = jnp.where(bits[..., None], sigs, 0)
    return jax.lax.reduce(masked, np.uint32(0),
                          jax.lax.bitwise_xor, dimensions=(1,))


@jax.jit
def aggregate_verify_batch(pk_states, committees, bits, msg_words, signatures):
    """Verify A committee aggregates at once.

    pk_states  (N, 8) uint32 — per-validator signature midstates
               (``precompute_pk_states``, refreshed only on registry change)
    committees (A, C) int32  — validator index per committee lane
    bits       (A, C) bool   — aggregation bitlists
    msg_words  (A, 8) uint32 — signing roots per attestation (u32 words)
    signatures (A, 24) uint32 — provided aggregate signature words
    Returns bool[A].
    """
    agg = _committee_aggregates(pk_states, committees, bits, msg_words)
    return (agg == signatures).all(axis=-1) & bits.any(axis=-1)


@jax.jit
def aggregate_signatures_batch(pk_states, committees, bits, msg_words):
    """The signer side of ``aggregate_verify_batch``: the honest
    committee aggregates from the SAME ``_committee_aggregates``
    pipeline the verifier recomputes — ``aggregate_verify_batch`` over
    the result is True exactly on the committees whose bitlists are
    non-empty (the dense end-to-end driver uses this as each slot's
    aggregation duty, then runs the sharded verification sweep over the
    batch axis)."""
    return _committee_aggregates(pk_states, committees, bits, msg_words)


def messages_to_words(messages_u8: np.ndarray) -> np.ndarray:
    """Host helper: (A, 32) uint8 signing roots -> (A, 8) big-endian u32."""
    q = messages_u8.reshape(-1, 8, 4).astype(np.uint32)
    return (q[..., 0] << 24) | (q[..., 1] << 16) | (q[..., 2] << 8) | q[..., 3]


@jax.jit
def aggregate_bits_and_weights(bits, committee_weights):
    """Aggregation duty (pos-evolution.md:474-475): OR-combine bitlists and
    tally participating weight per committee.

    bits (A, C) bool, committee_weights (A, C) int64 -> (participation
    counts int32[A], participating weight int64[A]).
    """
    count = bits.sum(axis=-1, dtype=jnp.int32)
    weight = jnp.where(bits, committee_weights, 0).sum(axis=-1)
    return count, weight


def pack_signature_words(sig_bytes_list) -> np.ndarray:
    """Host helper: list of 96-byte signatures -> (A, 24) u32 words."""
    raw = np.frombuffer(b"".join(bytes(s) for s in sig_bytes_list), dtype=">u4")
    return raw.astype(np.uint32).reshape(len(sig_bytes_list), 24)
