"""Fused per-block state transition on device (ISSUE 6 tentpole, part 2).

``process_attestation`` was the other half of the ``on_block`` wall: the
reference loop materializes ``get_base_reward`` per attester — and each call
re-derives ``get_total_active_balance``, an O(N) registry sum — so one block
at 64K validators burned ~16K Python calls x an O(N) reduction each. This
module applies a whole block's attestation batch as **one fused sweep** over
the dense participation/balance columns:

- ``apply_attestation_rows_host``   — the NumPy reference: per-block
  constants hoisted (total active balance, base-reward-per-increment and the
  proposer index are invariant across a block's attestations — balances move,
  effective balances and the active set do not), then the exact spec
  semantics per attestation (sequential flag-set order, per-flag unset-gated
  proposer-reward numerators, per-attestation proposer credit).
- ``apply_attestation_rows_device`` — the same sweep as a jitted
  ``lax.scan`` over the attestation axis with donated balance/flag buffers
  (donation off-CPU only; XLA:CPU does not implement it), padded to
  power-of-two (attestations x committee-lane) shapes so recompiles stay
  bounded. Bit-identical to the host path (int64 Gwei arithmetic
  throughout; differential tests pin equality).

Device residency: the jax path keeps the swept columns **device-resident
across consecutive blocks**. A module-level session holds the device arrays
plus host mirrors of the last write-back; the next block's sweep compares the
incoming state columns against those mirrors (a memcmp) and either reuses
the carry as-is, scatter-patches the few rows other processors touched
since (sync-aggregate rewards move ~512 balances per block), or — when the
columns moved wholesale (epoch rotation, deposits, fork switch) — falls
back to a fresh upload. Correctness never depends on lineage tracking: the
carry is used only when it provably equals the host columns. The per-block
device->host write-back of the three mutated columns remains (the
incremental merkleizer and the spec layer read host arrays) and is the
session's only unconditional per-block transfer.

``specs/transition.process_operations`` dispatches here through the
``ExecutionBackend`` (``block_sweep`` on both backends);
``ops/resident.apply_block_batch`` is the batched multi-block entry for
backfill/checkpoint-sync chains.
"""

from __future__ import annotations

import numpy as np

from pos_evolution_tpu.config import (
    PARTICIPATION_FLAG_WEIGHTS,
    PROPOSER_WEIGHT,
    WEIGHT_DENOMINATOR,
)
from pos_evolution_tpu.telemetry import jaxrt

# jax is imported LAZILY (first device sweep): this module is also the
# numpy backend's and ``process_attestation``'s host path, and the spec
# layer must stay importable/runnable without initializing a jax runtime.

__all__ = [
    "apply_attestation_rows_host",
    "apply_attestation_rows_device",
    "apply_block_chain",
    "reset_session",
    "session_stats",
]

_PROPOSER_REWARD_DENOM = ((WEIGHT_DENOMINATOR - PROPOSER_WEIGHT)
                          * WEIGHT_DENOMINATOR // PROPOSER_WEIGHT)


def _block_constants(state):
    """Per-block invariants of the attestation sweep. Within one block the
    active set and effective balances never move (attestations mutate
    participation flags and raw balances only), so the spec's per-attester
    ``get_base_reward`` collapses to one O(N) reduction per block."""
    from pos_evolution_tpu.config import cfg
    from pos_evolution_tpu.specs.helpers import (
        get_base_reward_per_increment,
        get_beacon_proposer_index,
    )
    return (cfg().effective_balance_increment,
            get_base_reward_per_increment(state),
            get_beacon_proposer_index(state))


# --- batched multi-block apply ------------------------------------------------

def apply_block_chain(state, signed_blocks, validate_result: bool = True,
                      pre_block=None, on_applied=None) -> None:
    """Apply a parent-linked run of signed blocks to ``state`` **in place**
    (the batched multi-block entry for backfill / checkpoint-sync chains,
    exposed as ``ExecutionBackend.multi_block_apply``).

    One state object is carried through the whole run — no per-block
    pre-state copy — so on the jax backend consecutive blocks hit the
    fused sweep's resident carry (reuse/patch, not re-upload), and the
    incremental merkleizer diffs each block against the previous one's
    leaves. The per-block work itself is the full spec
    ``state_transition`` (signature + state-root checks included when
    ``validate_result``), which dispatches its attestation batch through
    the current backend — this function is therefore bit-identical across
    backends by construction.

    ``pre_block(sb, state)`` runs before each block's transition (callers
    capture pre-state predicates, e.g. merge-transition detection);
    ``on_applied(sb, state)`` runs after it (callers commit snapshots).
    A failing block raises out with every earlier block fully applied —
    the same partial-progress contract as a sequential loop.
    """
    from pos_evolution_tpu.specs.transition import state_transition
    for sb in signed_blocks:
        if pre_block is not None:
            pre_block(sb, state)
        state_transition(state, sb, validate_result)
        if on_applied is not None:
            on_applied(sb, state)


# --- host (NumPy reference) path ----------------------------------------------

def apply_attestation_rows_host(state, rows) -> None:
    """Apply validated attestation rows to ``state`` — the NumPy oracle.

    ``rows``: list of ``(attesting_indices int64[k], flag_indices, is_current)``
    as produced by ``specs.transition._validate_attestation``, in block
    order (sequential semantics: a later attestation sees the flags earlier
    ones set, and proposer rewards gate on the then-unset flags).
    """
    if not rows:
        return
    incr, per_incr, proposer = _block_constants(state)
    eff_units = (state.validators.effective_balance // np.uint64(incr)
                 ).astype(np.int64)
    for attesting, flag_indices, is_current in rows:
        participation = (state.current_epoch_participation if is_current
                         else state.previous_epoch_participation)
        base_rewards = eff_units[attesting] * int(per_incr)
        new_flags = participation[attesting]
        numerator = 0
        for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
            if flag_index not in flag_indices:
                continue
            unset = ((new_flags >> np.uint8(flag_index)) & np.uint8(1)) == 0
            numerator += int(base_rewards[unset].sum()) * weight
            new_flags = new_flags | np.uint8(1 << flag_index)
        participation[attesting] = new_flags
        state.balances[proposer] += np.uint64(numerator
                                              // _PROPOSER_REWARD_DENOM)


# --- device path --------------------------------------------------------------

def _next_pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


# Lazily-built device namespace: {jax, jnp, jit, donate}. Built once, on
# the first device sweep — never on module import (host-path contract).
_DEVICE: dict | None = None


def _device():
    global _DEVICE
    if _DEVICE is not None:
        return _DEVICE

    import jax

    from pos_evolution_tpu.backend.jax_init import ensure_x64
    ensure_x64()

    import jax.numpy as jnp

    def _block_sweep(balances, prev_flags, cur_flags, eff_units, per_incr,
                     proposer, idx, valid, is_cur, flag_mask):
        """One block's attestation batch as a scan over the attestation
        axis.

        balances int64[N] / prev_flags,cur_flags uint8[N] are the carry;
        eff_units int64[N] is effective balance in whole increments
        (hoisted — no in-kernel division, no config constant baked into
        the trace); idx int32[A,C] (padded committee lanes, ``valid``
        masks the padding), is_cur bool[A], flag_mask uint8[A] (bit b set
        = flag b timely). Padded attestation rows are all-invalid,
        zero-mask no-ops.
        """
        n = balances.shape[0]

        def step(carry, x):
            bal, prev, cur = carry
            row_idx, row_valid, row_is_cur, row_mask = x
            flags = jnp.where(row_is_cur, cur[row_idx], prev[row_idx])
            base = eff_units[row_idx] * per_incr
            numerator = jnp.int64(0)
            new_flags = flags
            for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
                has = ((row_mask >> np.uint8(flag_index))
                       & np.uint8(1)).astype(bool)
                unset = ((new_flags >> np.uint8(flag_index))
                         & np.uint8(1)) == 0
                contrib = jnp.sum(jnp.where(row_valid & unset, base, 0)) \
                    * np.int64(weight)
                numerator = numerator + jnp.where(has, contrib, 0)
                new_flags = jnp.where(
                    has & row_valid, new_flags | np.uint8(1 << flag_index),
                    new_flags)
            cur2 = cur.at[jnp.where(row_valid & row_is_cur, row_idx, n)
                          ].set(new_flags, mode="drop")
            prev2 = prev.at[jnp.where(row_valid & ~row_is_cur, row_idx, n)
                            ].set(new_flags, mode="drop")
            reward = numerator // np.int64(_PROPOSER_REWARD_DENOM)
            bal2 = bal.at[proposer].add(reward)
            return (bal2, prev2, cur2), None

        (balances, prev_flags, cur_flags), _ = jax.lax.scan(
            step, (balances, prev_flags, cur_flags),
            (idx, valid, is_cur, flag_mask))
        return balances, prev_flags, cur_flags

    _DEVICE = {
        "jax": jax,
        "jnp": jnp,
        # donated variant for real devices (the carry is rewritten in
        # place, HBM never holds two copies); XLA:CPU has no donation
        # and would warn per call
        "jit": jax.jit(_block_sweep),
        "donate": jax.jit(_block_sweep, donate_argnums=(0, 1, 2)),
    }
    return _DEVICE


def _sweep_fn():
    dev = _device()
    return (dev["jit"] if dev["jax"].default_backend() == "cpu"
            else dev["donate"])


def prewarm_block_sweep(state, max_attestations: int | None = None) -> int:
    """Compile the fused block sweep for every padded shape a run over
    ``state``'s registry can produce, before the first slot runs.

    The sweep pads its batch to power-of-two (attestations x
    committee-lane) shapes (``apply_attestation_rows_device``), so a new
    shape appearing mid-run — blocks carrying 17 attestations for the
    first time in epoch 2 — triggers an XLA compile exactly where the
    driver is latency-sensitive (the ROADMAP item 2 ``get_head`` tail
    absorbed these as compile-storm spikes). Warming the full pow2
    lattice up front is a handful of compiles (log2(max_attestations) x
    |committee-lane shapes|) and pins ``jax_backend_compiles_total`` flat
    for the rest of the run (tests/test_das.py).

    Executes the jitted sweep on zero-filled inputs (AOT ``lower().
    compile()`` would not seed the jit dispatch cache) and returns the
    number of shapes warmed.
    """
    from pos_evolution_tpu.config import cfg as _cfg
    from pos_evolution_tpu.specs.helpers import (
        active_validator_mask,
        get_committee_count_per_slot,
        get_current_epoch,
    )

    c = _cfg()
    n = len(state.validators)
    if max_attestations is None:
        max_attestations = c.max_attestations
    epoch = get_current_epoch(state)
    count = get_committee_count_per_slot(state, epoch)
    active = int(active_validator_mask(state, epoch).sum())
    per_slot = max(active // c.slots_per_epoch, 1)
    # committees split per-slot actives into count groups of size s or
    # s+1 — but the sweep pads to the pow2 of the PER-AGGREGATE attesting
    # count, so partial aggregates (FaultPlan drops, adversarial
    # withholding) land on every pow2 lane below the full committee too
    lane_hi = _next_pow2(per_slot // count + 1)
    lanes = set()
    lane = 1
    while lane <= lane_hi:
        lanes.add(lane)
        lane *= 2

    dev = _device()
    jnp = dev["jnp"]
    fn = _sweep_fn()
    warmed = 0
    a = 1
    while a <= _next_pow2(max_attestations):
        for lane in sorted(lanes):
            # fresh carries per call: off-CPU the sweep donates them
            fn(jnp.zeros(n, dtype=jnp.int64), jnp.zeros(n, dtype=jnp.uint8),
               jnp.zeros(n, dtype=jnp.uint8), jnp.zeros(n, dtype=jnp.int64),
               jnp.int64(0), jnp.int32(0),
               jnp.zeros((a, lane), dtype=jnp.int32),
               jnp.zeros((a, lane), dtype=bool),
               jnp.zeros(a, dtype=bool),
               jnp.zeros(a, dtype=jnp.uint8))
            warmed += 1
        a *= 2
    return warmed


class _Session:
    """Device residency across consecutive blocks (one per process).

    ``device``: the live carry (balances, prev_flags, cur_flags, eff_units);
    ``mirror``: host copies of the last write-back. A sweep reuses the carry
    iff the incoming state columns equal the mirrors byte-for-byte,
    scatter-patches small diffs (sync-aggregate rewards between blocks),
    and re-uploads wholesale otherwise — epoch rotation, deposits and fork
    switches all land there, so correctness never depends on lineage
    tracking.
    """

    __slots__ = ("device", "mirror", "uploads", "patches", "reuses")

    def __init__(self):
        self.device = None
        self.mirror = None
        self.uploads = 0
        self.patches = 0
        self.reuses = 0


_SESSION = _Session()

# patch at most this fraction of rows before a full upload wins
_PATCH_FRACTION = 8


def reset_session() -> None:
    """Drop the resident carry (tests; config or platform switches)."""
    _SESSION.device = None
    _SESSION.mirror = None


def session_stats() -> dict:
    return {"uploads": _SESSION.uploads, "patches": _SESSION.patches,
            "reuses": _SESSION.reuses}


def _session_arrays(state, eff_units):
    """Resident (balances, prev, cur, eff_units) for ``state``: the carry
    from the previous sweep when the host columns still match its
    write-back mirrors, a scatter-patched carry when only a few rows moved
    since, else a fresh upload."""
    jnp = _device()["jnp"]
    s = _SESSION
    bal = state.balances
    prev = state.previous_epoch_participation
    cur = state.current_epoch_participation
    if s.device is not None and s.mirror is not None:
        m_bal, m_prev, m_cur, m_eff = s.mirror
        if (bal.shape == m_bal.shape
                and prev.shape == m_prev.shape
                and cur.shape == m_cur.shape
                and np.array_equal(eff_units, m_eff)):
            d_bal = np.nonzero(bal != m_bal)[0]
            d_prev = np.nonzero(prev != m_prev)[0]
            d_cur = np.nonzero(cur != m_cur)[0]
            dirty = d_bal.size + d_prev.size + d_cur.size
            if dirty == 0:
                s.reuses += 1
                return s.device
            if dirty <= max(1, bal.shape[0] // _PATCH_FRACTION):
                bal_d, prev_d, cur_d, eff_d = s.device
                if d_bal.size:
                    bal_d = bal_d.at[jnp.asarray(d_bal)].set(
                        jnp.asarray(bal[d_bal].astype(np.int64)))
                if d_prev.size:
                    prev_d = prev_d.at[jnp.asarray(d_prev)].set(
                        jnp.asarray(prev[d_prev]))
                if d_cur.size:
                    cur_d = cur_d.at[jnp.asarray(d_cur)].set(
                        jnp.asarray(cur[d_cur]))
                s.patches += 1
                jaxrt.record_transfer(dirty * 8, direction="h2d",
                                      site="fused_block_patch")
                return bal_d, prev_d, cur_d, eff_d
    s.uploads += 1
    jaxrt.record_transfer(bal.nbytes + prev.nbytes + cur.nbytes
                          + eff_units.nbytes,
                          direction="h2d", site="fused_block_upload")
    place = _session_placer(bal.shape[0])
    return (place("balances", bal.astype(np.int64)),
            place("prev_flags", np.asarray(prev)),
            place("cur_flags", np.asarray(cur)),
            place("eff_units", np.asarray(eff_units)))


def _session_placer(n: int):
    """How session columns land on device: single-device ``jnp.asarray``
    normally; per-shard slice placement over the validator mesh axes when
    the jax backend's sharded mode is active with ``shard_transition``
    (the session-column entry in ``parallel/partition.PARTITION_RULES``).
    Registries that do not divide by the device count stay single-device
    — the sweep's scatter targets would otherwise need padded-row
    bookkeeping for no measurable win."""
    jnp = _device()["jnp"]
    try:
        from pos_evolution_tpu.backend import jax_backend
        if jax_backend.shard_transition_enabled():
            mesh = jax_backend.sharded_mesh()
            if n % mesh.size == 0:
                from pos_evolution_tpu.parallel.partition import (
                    shard_leaf,
                    spec_for,
                )
                return lambda name, a: shard_leaf(
                    mesh, spec_for(f"session/{name}"), a)
    except Exception:
        pass  # sharded placement is an optimization, never a requirement
    return lambda name, a: jnp.asarray(a)


def apply_attestation_rows_device(state, rows) -> None:
    """Device twin of ``apply_attestation_rows_host``: pad the rows, run the
    donated-buffer scan on the resident columns, write the three mutated
    columns back to the host state (the incremental merkleizer diffs host
    arrays), and keep the device outputs as the next block's carry."""
    if not rows:
        return
    incr, per_incr, proposer = _block_constants(state)
    eff_units = (state.validators.effective_balance // np.uint64(incr)
                 ).astype(np.int64)

    a = _next_pow2(len(rows))
    c = _next_pow2(max(r[0].shape[0] for r in rows))
    idx = np.zeros((a, c), dtype=np.int32)
    valid = np.zeros((a, c), dtype=bool)
    is_cur = np.zeros(a, dtype=bool)
    flag_mask = np.zeros(a, dtype=np.uint8)
    for i, (attesting, flag_indices, row_is_cur) in enumerate(rows):
        k = attesting.shape[0]
        idx[i, :k] = attesting
        valid[i, :k] = True
        is_cur[i] = bool(row_is_cur)
        mask = 0
        for f in flag_indices:
            mask |= 1 << f
        flag_mask[i] = mask

    jnp = _device()["jnp"]
    bal_d, prev_d, cur_d, eff_d = _session_arrays(state, eff_units)
    jaxrt.record_dispatch(site="fused_block")
    bal_d, prev_d, cur_d = _sweep_fn()(
        bal_d, prev_d, cur_d, eff_d, jnp.int64(int(per_incr)),
        jnp.int32(int(proposer)), jnp.asarray(idx), jnp.asarray(valid),
        jnp.asarray(is_cur), jnp.asarray(flag_mask))

    new_bal = np.asarray(bal_d).astype(np.uint64)
    new_prev = np.asarray(prev_d)
    new_cur = np.asarray(cur_d)
    jaxrt.record_transfer(new_bal.nbytes + new_prev.nbytes + new_cur.nbytes,
                          direction="d2h", site="fused_block_writeback")
    state.balances = new_bal
    state.previous_epoch_participation = new_prev
    state.current_epoch_participation = new_cur
    _SESSION.device = (bal_d, prev_d, cur_d, eff_d)
    _SESSION.mirror = (new_bal.copy(), new_prev.copy(), new_cur.copy(),
                       eff_units)
