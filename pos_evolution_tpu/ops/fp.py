"""BLS12-381 base-field arithmetic on TPU lanes (component N1, layer 0).

The reference's signature layer is real BLS12-381 in every deployment
(``bls.Verify`` pos-evolution.md:165, aggregate attestation signatures
:714-717, sync aggregates :642). SURVEY.md §2.7 N1 mandates the pairing
as a *device* kernel: Fp elements as fixed-width limb vectors in int32
lanes, batched over attestations.

Design — idiomatic TPU, not a bignum-library port:

- **Radix 2^12, 32 limbs** (384 bits ≥ 381). Limb products are
  ≤ (2^12-1)^2, so even the widest convolution column here (33 terms in
  the Barrett step) sums to 33·(2^12-1)^2 < 2^30 — inside int32, the
  widest integer multiply the VPU natively runs (no u64, no i128, unlike
  CPU bignum code). NOTE: raising BITS to 13 would overflow (33·(2^13-1)^2
  > 2^31).
- **Plain domain + Barrett reduction** (no Montgomery): products are
  digit convolutions (log-depth stacked-shift sums), and the quotient
  estimate is two more convolutions against the precomputed
  ``MU = floor(2^768 / p)``. Everything is data-parallel over limbs and
  batch; there is *no sequential 32-step CIOS loop*, which matters
  because a pairing chains ~30K field multiplies and the loop would
  serialize on the VPU.
- **Carry/borrow resolution in log depth**: large digits are folded with
  3 local rounds (digit-sum bounds shrink 2^31 -> 2^12+1), then the
  final single-bit carries ripple through a Kogge-Stone-style
  carry-lookahead ``associative_scan`` over (generate, propagate) pairs
  — 5 parallel rounds for 32 limbs, never a 32-step ripple.
- **Lazy canonical form**: residues live in [0, 2p); multiplication
  output lands there without any compare (Barrett remainder < 3p, one
  conditional subtract of 2p), adds/subs re-enter it with one
  conditional subtract. Equality canonicalizes with one more.

Correctness oracle: ``crypto/bls12_381.py`` (pure-Python pairing, exact
integers) — every op here is differential-tested against Python ints in
``tests/test_fp_device.py``.
"""

from __future__ import annotations

import numpy as np

import jax

from pos_evolution_tpu.backend.jax_init import ensure_x64
ensure_x64()

import jax.numpy as jnp  # noqa: E402

from pos_evolution_tpu.crypto.bls12_381 import Q as P_INT  # noqa: E402

BITS = 12
MASK = (1 << BITS) - 1
L = 32                       # limbs per element: 32 * 12 = 384 bits
CONV = 2 * L - 1             # full-product digit count


def to_limbs(x: int, n: int = L) -> np.ndarray:
    """Python int -> little-endian base-2^12 digit vector (host side)."""
    assert x >= 0
    out = np.zeros(n, dtype=np.int32)
    for i in range(n):
        out[i] = x & MASK
        x >>= BITS
    assert x == 0, "value does not fit in the limb vector"
    return out


def from_limbs(v) -> int:
    """Digit vector -> Python int (host side; accepts unnormalized)."""
    out = 0
    for i, d in enumerate(np.asarray(v).tolist()):
        out += int(d) << (BITS * i)
    return out


P = to_limbs(P_INT)
TWO_P = to_limbs(2 * P_INT)              # 2p < 2^384: fits 32 limbs
MU = to_limbs(2**768 // P_INT, 33)       # Barrett constant, 33 limbs
ZERO = np.zeros(L, dtype=np.int32)
ONE = to_limbs(1)


# --- digit plumbing (all log-depth, batch-leading shapes [..., n]) ------------

def _gp_compose(lo, hi):
    """(generate, propagate) composition for carry/borrow lookahead —
    the associative operator of a Kogge-Stone scan."""
    g1, p1 = lo
    g2, p2 = hi
    return g2 | (p2 & g1), p2 & p1


def conv_digits(a: jax.Array, b: jax.Array) -> jax.Array:
    """Full product in digit space: [..., m] x [..., n] -> [..., m+n-1]
    column sums (each <= 33 * (2^12-1)^2 < 2^30, inside int32 — the i32
    accumulation is explicit; x64 promotion to int64 would break scan
    carries and leave the VPU's native width).

    Formulated as the outer product followed by ONE matmul against a
    static 0/1 anti-diagonal-selector matrix instead of m padded partial
    products: the graph is 2 ops, so deep compositions (a pairing is
    ~30K of these) stay compilable, and the contraction is matmul-shaped
    for the MXU."""
    m = a.shape[-1]
    n = b.shape[-1]
    outer = a[..., :, None] * b[..., None, :]        # broadcasts batch dims
    prods = outer.reshape(outer.shape[:-2] + (m * n,))
    sel = jnp.asarray(_conv_selector(m, n))
    return jnp.einsum("...p,pk->...k", prods, sel,
                      preferred_element_type=jnp.int32)


_CONV_SELECTORS: dict = {}


def _conv_selector(m: int, n: int) -> np.ndarray:
    """Static [m*n, m+n-1] 0/1 matrix: entry ((i, j), k) = [i + j == k].
    Cached as numpy (a jnp constant created inside a trace would leak)."""
    key = (m, n)
    if key not in _CONV_SELECTORS:
        i = np.arange(m)[:, None, None]
        j = np.arange(n)[None, :, None]
        k = np.arange(m + n - 1)[None, None, :]
        _CONV_SELECTORS[key] = (i + j == k).reshape(
            m * n, m + n - 1).astype(np.int32)
    return _CONV_SELECTORS[key]


def carry_norm(x: jax.Array, out_len: int) -> jax.Array:
    """Normalize arbitrary non-negative digit sums (< 2^31) to canonical
    digits < 2^12 over ``out_len`` limbs. The represented *value* must fit
    ``out_len`` digits (carries past the top limb are dropped); every
    caller here guarantees that by construction (e.g. 4p^2 < 2^768 for the
    64-limb full product).

    3 local fold rounds shrink digits to <= 2^12; the remaining single-bit
    carries resolve in one carry-lookahead ``associative_scan``
    ((generate, propagate) composition — 5 parallel rounds), avoiding the
    worst-case full ripple of repeated local folding (…FFF FFF + 1)."""
    pad = out_len - x.shape[-1]
    if pad > 0:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    elif pad < 0:
        raise ValueError("carry_norm cannot truncate")
    for _ in range(3):
        c = x >> BITS
        x = (x & MASK) + jnp.pad(c, [(0, 0)] * (x.ndim - 1) + [(1, 0)]
                                 )[..., :out_len]
    # digits now in [0, 2^12]; lookahead for the final 0/1 carries
    g = x > MASK                      # generates a carry regardless of c_in
    p = x == MASK                     # propagates an incoming carry
    gs, _ = jax.lax.associative_scan(_gp_compose, (g, p), axis=-1)
    c_in = jnp.pad(gs, [(0, 0)] * (x.ndim - 1) + [(1, 0)])[..., :out_len]
    return (x + c_in.astype(jnp.int32)) & MASK


def sub_digits(x: jax.Array, y: jax.Array):
    """(x - y, underflow) over canonical digit vectors of equal length.
    Borrow resolution by the same lookahead composition — log depth."""
    t = x - y                                  # digits in [-4095, 4095]
    g = t < 0                                  # generates a borrow
    p = t == 0                                 # propagates an incoming borrow
    gs, _ = jax.lax.associative_scan(_gp_compose, (g, p), axis=-1)
    b_in = jnp.pad(gs, [(0, 0)] * (t.ndim - 1) + [(1, 0)])[..., : t.shape[-1]]
    u = t - b_in.astype(jnp.int32)
    d = u + ((u < 0).astype(jnp.int32) << BITS)
    return d, gs[..., -1]


def cond_sub(x: jax.Array, y: np.ndarray) -> jax.Array:
    """x - y if x >= y else x (canonical digits in, canonical out)."""
    d, uf = sub_digits(x, jnp.asarray(y))
    return jnp.where(uf[..., None], x, d)


# --- field ops: residues in [0, 2p), canonical digits -------------------------

def barrett_reduce(x: jax.Array) -> jax.Array:
    """Reduce a canonical-digit value x < p * 2^384 (<= 64 limbs) to
    [0, 2p). Two constraints meet at that bound: the classical q_hat
    error q-2 <= q_hat <= q holds for x < b^(2k) = 2^768 (HAC 14.42-43,
    p a k=32-digit modulus), and this implementation's quotient window
    (q1[..., 33:65], 32 digits) requires q = floor(x/p) < 2^384.
    Callers range from 4p^2 full products to ~50p linear-combination
    folds — all far inside p * 2^384 (~2^765).

    Digit Barrett with m = 32: q_hat = ((x >> 2^(12*31)) * MU) >> 2^(12*33)
    satisfies q - 2 <= q_hat <= q, so r = x - q_hat * p < 3p and one
    conditional subtract of 2p lands in [0, 2p)."""
    n = x.shape[-1]
    if n < 64:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, 64 - n)])
    x_hi = x[..., 31:]                                       # 33 digits
    q1 = carry_norm(conv_digits(x_hi, jnp.asarray(MU)), 66)
    q_hat = q1[..., 33:65]                                   # 32 digits
    qp = carry_norm(conv_digits(q_hat, jnp.asarray(P)), 64)
    r, uf = sub_digits(x, qp)
    # r < 3p < 2^383: upper digits are zero by construction
    r = r[..., :L]
    return cond_sub(r, TWO_P)


def modmul(a: jax.Array, b: jax.Array) -> jax.Array:
    """a * b mod p (inputs/outputs in [0, 2p): 2p * 2p = 4p^2 < 2^384 * p,
    inside Barrett's domain)."""
    return barrett_reduce(carry_norm(conv_digits(a, b), 64))


def modadd(a: jax.Array, b: jax.Array) -> jax.Array:
    s = carry_norm(a + b, L)          # < 4p < 2^384: no spill digit
    return cond_sub(s, TWO_P)


def modsub(a: jax.Array, b: jax.Array) -> jax.Array:
    d, uf = sub_digits(a, b)
    # underflow: d holds a - b + 2^384; add 2p and drop the 2^384 carry-out
    wrapped = carry_norm(d + jnp.asarray(TWO_P), L + 1)[..., :L]
    return jnp.where(uf[..., None], wrapped, d)


def modneg(a: jax.Array) -> jax.Array:
    return modsub(jnp.asarray(ZERO), a)


def canon(a: jax.Array) -> jax.Array:
    """[0, 2p) -> [0, p): exact canonical form for equality/serialization."""
    return cond_sub(a, P)


def eq(a: jax.Array, b: jax.Array) -> jax.Array:
    return (canon(a) == canon(b)).all(axis=-1)


def is_zero(a: jax.Array) -> jax.Array:
    return (canon(a) == 0).all(axis=-1)


_P_MINUS_2_BITS = np.array(
    [(P_INT - 2) >> i & 1 for i in range(P_INT.bit_length())][::-1],
    dtype=bool)


def modinv(a: jax.Array) -> jax.Array:
    """a^(p-2) mod p by square-and-multiply over the static bit string of
    p-2 (``lax.scan``: 380 steps, 2 multiplies each). Rare by design —
    only tower inversions (one per final exponentiation) and affine
    conversions reach it. Returns 0 for a = 0 (Fermat's convention)."""
    one = jnp.broadcast_to(jnp.asarray(ONE), a.shape).astype(jnp.int32)

    def step(acc, bit):
        acc = modmul(acc, acc)
        acc = jnp.where(bit, modmul(acc, a), acc)
        return acc, None

    acc, _ = jax.lax.scan(step, one, jnp.asarray(_P_MINUS_2_BITS))
    return acc


modmul_jit = jax.jit(modmul)
modadd_jit = jax.jit(modadd)
modsub_jit = jax.jit(modsub)
modinv_jit = jax.jit(modinv)
