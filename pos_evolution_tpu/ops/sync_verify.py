"""Batched light-client update verification on device (sync-protocol ops).

A light client's per-update work is (a) one participation-weighted
sync-aggregate verification and (b) two merkle-branch checks into the
attested state. This module runs *batches* of updates through both checks:

- signatures reuse the attestation pipeline (``precompute_pk_states`` +
  ``aggregate_verify_batch``, ops/aggregation.py): one committee lane per
  signer, XOR segment reduction, compare against the provided aggregates —
  the fake-scheme analogue of the batched pairing check a BLS12-381
  crypto-processor performs (arxiv 2201.07496);
- participation counts/weights come from ``aggregate_bits_and_weights``;
- merkle branches run as a vectorized device walk over the SHA-256 op
  (``sha256_pair_words``): per level, select (sibling‖value) or
  (value‖sibling) by the index bit across the whole batch — the device
  analogue of ``ssz.merkle.is_valid_merkle_branch``.

A pure-NumPy host path implements the identical contract behind the same
``ExecutionBackend`` dispatch (``verify_sync_update_batch``); the two are
bit-exact (tests/test_lightclient.py pins every output array).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import jax

from pos_evolution_tpu.backend.jax_init import ensure_x64
ensure_x64()

import jax.numpy as jnp  # noqa: E402

from pos_evolution_tpu.crypto.bls import FakeBLS  # noqa: E402
from pos_evolution_tpu.ops.aggregation import (  # noqa: E402
    aggregate_bits_and_weights,
    aggregate_verify_batch,
    messages_to_words,
    pack_signature_words,
    precompute_pk_states,
)
from pos_evolution_tpu.ops.sha256 import sha256_pair_words  # noqa: E402
from pos_evolution_tpu.ssz.hash import sha256_pairs  # noqa: E402

__all__ = [
    "SyncUpdateBatch",
    "verify_sync_update_batch",
    "verify_batch_host",
    "verify_batch_device",
    "merkle_roots_host",
    "merkle_roots_device",
]


@dataclass
class SyncUpdateBatch:
    """Dense form of B light-client updates over S-lane sync committees.

    Array-level only (no container types) so the same batch feeds either
    backend. Branch groups with ``*_present == False`` still flow through
    the hash walk (lanes are cheap); their verdicts are masked off.
    """

    pubkeys: np.ndarray       # (B, S, 48) u8 — committee pubkeys per update
    bits: np.ndarray          # (B, S) bool  — participation bits
    weights: np.ndarray       # (B, S) i64   — per-lane weight (1 = count)
    messages: np.ndarray      # (B, 32) u8   — signing roots
    signatures: np.ndarray    # (B, 96) u8   — aggregate signatures
    fin_leaf: np.ndarray      # (B, 32) u8   — finalized header roots
    fin_branch: np.ndarray    # (B, FD, 32) u8
    fin_index: np.ndarray     # (B,) i64
    fin_root: np.ndarray      # (B, 32) u8   — attested state roots
    fin_present: np.ndarray   # (B,) bool
    sc_leaf: np.ndarray       # (B, 32) u8   — next-sync-committee roots
    sc_branch: np.ndarray     # (B, SD, 32) u8
    sc_index: np.ndarray      # (B,) i64
    sc_root: np.ndarray       # (B, 32) u8
    sc_present: np.ndarray    # (B,) bool

    @property
    def size(self) -> int:
        return self.bits.shape[0]


def _words_to_rows(words) -> np.ndarray:
    """(B, 8) u32 digest words -> (B, 32) u8 rows."""
    w = np.asarray(words, dtype=np.uint32)
    return w.astype(">u4").view(np.uint8).reshape(w.shape[0], 32)


def _index_bits(index: np.ndarray, depth: int) -> np.ndarray:
    """(B,) indices -> (B, depth) bool: bit d selects right-child at level d."""
    idx = np.asarray(index, dtype=np.int64)
    return ((idx[:, None] >> np.arange(depth, dtype=np.int64)[None, :]) & 1).astype(bool)


# --- merkle walk: host / device ----------------------------------------------

def merkle_roots_host(leaf: np.ndarray, branch: np.ndarray,
                      index: np.ndarray) -> np.ndarray:
    """Recompute the branch roots for a batch of proofs (NumPy path)."""
    value = np.ascontiguousarray(leaf, dtype=np.uint8)
    branch = np.asarray(branch, dtype=np.uint8)
    bits = _index_bits(index, branch.shape[1])
    for d in range(branch.shape[1]):
        sib = branch[:, d]
        right_child = bits[:, d][:, None]
        left = np.where(right_child, sib, value)
        right = np.where(right_child, value, sib)
        value = sha256_pairs(np.ascontiguousarray(left), np.ascontiguousarray(right))
    return value


@jax.jit
def _merkle_walk_device(leaf_words, branch_words, index_bits):
    # scan over tree levels: one compiled compression pair regardless of
    # depth (an unrolled level loop cost ~D× the compile time on XLA:CPU)
    def level(value, xs):
        sib, right_child = xs
        left = jnp.where(right_child[:, None], sib, value)
        right = jnp.where(right_child[:, None], value, sib)
        return sha256_pair_words(left, right), None

    xs = (jnp.swapaxes(branch_words, 0, 1), jnp.swapaxes(index_bits, 0, 1))
    value, _ = jax.lax.scan(level, leaf_words, xs)
    return value


def merkle_roots_device(leaf: np.ndarray, branch: np.ndarray,
                        index: np.ndarray) -> np.ndarray:
    """Device counterpart of ``merkle_roots_host`` (bit-identical)."""
    b = leaf.shape[0]
    depth = branch.shape[1]
    leaf_words = messages_to_words(np.ascontiguousarray(leaf, dtype=np.uint8))
    branch_words = messages_to_words(
        np.ascontiguousarray(branch, dtype=np.uint8).reshape(b * depth, 32)
    ).reshape(b, depth, 8)
    out = _merkle_walk_device(jnp.asarray(leaf_words), jnp.asarray(branch_words),
                              jnp.asarray(_index_bits(index, depth)))
    return _words_to_rows(out)


# --- whole-batch verification -------------------------------------------------

def _result(sig_ok, participation, weight, fin_root, fin_ok, sc_root, sc_ok) -> dict:
    return {
        "sig_ok": np.asarray(sig_ok, dtype=bool),
        "participation": np.asarray(participation, dtype=np.int32),
        "weight": np.asarray(weight, dtype=np.int64),
        "fin_root": np.asarray(fin_root, dtype=np.uint8),
        "fin_ok": np.asarray(fin_ok, dtype=bool),
        "sc_root": np.asarray(sc_root, dtype=np.uint8),
        "sc_ok": np.asarray(sc_ok, dtype=bool),
    }


def verify_batch_host(batch: SyncUpdateBatch) -> dict:
    """NumPy/hashlib reference path (the oracle the device path must match)."""
    b = batch.size
    sig_ok = np.zeros(b, dtype=bool)
    for i in range(b):
        lanes = np.nonzero(batch.bits[i])[0]
        pks = [batch.pubkeys[i, j].tobytes() for j in lanes]
        sig_ok[i] = bool(pks) and FakeBLS.FastAggregateVerify(
            pks, batch.messages[i].tobytes(), batch.signatures[i].tobytes())
    participation = batch.bits.sum(axis=1, dtype=np.int32)
    weight = np.where(batch.bits, batch.weights, 0).sum(axis=1, dtype=np.int64)
    fin_root = merkle_roots_host(batch.fin_leaf, batch.fin_branch, batch.fin_index)
    fin_ok = (fin_root == batch.fin_root).all(axis=1) & batch.fin_present
    sc_root = merkle_roots_host(batch.sc_leaf, batch.sc_branch, batch.sc_index)
    sc_ok = (sc_root == batch.sc_root).all(axis=1) & batch.sc_present
    return _result(sig_ok, participation, weight, fin_root, fin_ok, sc_root, sc_ok)


def verify_batch_device(batch: SyncUpdateBatch) -> dict:
    """JAX/XLA path: committee lanes become their own pk-state table, so the
    attestation kernel verifies sync aggregates unchanged."""
    b, s = batch.bits.shape
    pk_states = precompute_pk_states(
        np.ascontiguousarray(batch.pubkeys, dtype=np.uint8).reshape(b * s, 48))
    committees = np.arange(b * s, dtype=np.int32).reshape(b, s)
    msg_words = messages_to_words(np.ascontiguousarray(batch.messages, dtype=np.uint8))
    sig_words = pack_signature_words([batch.signatures[i].tobytes() for i in range(b)])
    bits = jnp.asarray(batch.bits)
    sig_ok = aggregate_verify_batch(pk_states, jnp.asarray(committees), bits,
                                    jnp.asarray(msg_words), jnp.asarray(sig_words))
    participation, weight = aggregate_bits_and_weights(
        bits, jnp.asarray(batch.weights, dtype=jnp.int64))
    fin_root = merkle_roots_device(batch.fin_leaf, batch.fin_branch, batch.fin_index)
    fin_ok = (fin_root == batch.fin_root).all(axis=1) & batch.fin_present
    sc_root = merkle_roots_device(batch.sc_leaf, batch.sc_branch, batch.sc_index)
    sc_ok = (sc_root == batch.sc_root).all(axis=1) & batch.sc_present
    return _result(np.asarray(sig_ok), np.asarray(participation), np.asarray(weight),
                   fin_root, fin_ok, sc_root, sc_ok)


def verify_sync_update_batch(batch: SyncUpdateBatch) -> dict:
    """Verify a batch through the active ``ExecutionBackend``."""
    from pos_evolution_tpu.backend import get_backend
    backend = get_backend()
    fn = getattr(backend, "sync_update_verify", None)
    if fn is None:
        return verify_batch_host(batch)
    return fn(batch)
