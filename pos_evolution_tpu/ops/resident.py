"""Persistent device fork-choice store (the consumer of the incremental
bucket kernels in ``ops/forkchoice.py``).

The reference runs ``get_head`` on every propose/attest decision
(pos-evolution.md:298, 762) over a store that changes by small deltas:
one block row per ``on_block`` (:986-1036), a handful of latest-message
updates per ``on_attestation`` (:1435-1441), rare equivocator removals
(:1447-1461). ``get_head_dense`` rebuilt the whole dense image from the
spec store on *every* query — an O(blocks + registry) host loop that
dwarfs the kernel it feeds. This class keeps the dense image **resident
on device** and mirrors the spec store incrementally:

- ``note_block``      — append one row (parent/slot/rank/viability);
- ``note_attestation``— queue votes; flushed as one padded
                        ``apply_latest_messages`` scatter batch;
- ``note_slashing``   — ``remove_latest_messages`` + weight zeroing;
- ``head()``          — flush, then ``head_from_buckets``: O(B log B) on
                        device, no registry rescan, no host rebuild.

Wholesale refreshes happen only where the incremental contracts demand
them (the ``rebuild_buckets`` epoch-boundary hook): effective balances
and activation windows move at epoch processing (pos-evolution.md:
122-133), viability and vote weights re-anchor when the justified /
finalized checkpoints move (:874-880, 1026-1036). ``sync()`` detects
those events by comparing cheap fingerprints and triggers a rebuild —
every other head query runs purely from resident state.

Differential contract: ``head()`` must equal the spec walk
(``specs/forkchoice.get_head``) and the rescan kernel
(``head_and_weights``) at every query; ``tests/test_resident.py`` pins
all three across simulated epochs.
"""

from __future__ import annotations

import logging

import numpy as np

import jax.numpy as jnp

logger = logging.getLogger("pos_evolution_tpu.resident")

from pos_evolution_tpu.ops.forkchoice import (
    apply_latest_messages,
    build_dense_store,
    head_from_buckets,
    next_pow2,
    rebuild_buckets,
    remove_latest_messages,
)
from pos_evolution_tpu.telemetry import jaxrt


class ResidentForkChoice:
    """Device-resident dense mirror of one spec-level ``Store``.

    Graceful degradation: the accelerated path is an *optimization* of the
    spec walk, never a source of truth — so any device error, and any
    divergence caught by the periodic self-check (every
    ``selfcheck_every`` head queries, compare against
    ``specs/forkchoice.get_head``), permanently drops this instance to the
    host path. The event is logged and recorded in ``incidents``; the run
    keeps going on spec fork choice (`degraded=True`) instead of dying
    mid-simulation/bench. ``selfcheck_every=0`` disables the periodic
    audit (the differential tests pin equality on every query anyway)."""

    # every DEEPCHECK_FACTOR-th periodic audit uses the pure-Python spec
    # walk instead of the vectorized host walk: get_head_host shares its
    # densification staging with the resident rebuild path, so on its own
    # it cannot catch a staging regression that corrupts both sides the
    # same way — the rare deep audit keeps a fully independent oracle in
    # the loop at bounded cost (one spec walk per ~1K fresh queries).
    DEEPCHECK_FACTOR = 16

    def __init__(self, store, capacity: int = 64, selfcheck_every: int = 64):
        self._min_capacity = capacity
        self.selfcheck_every = selfcheck_every
        self.degraded = False
        self.incidents: list[str] = []
        self._head_queries = 0
        self._pending = []          # rebuild re-creates; safe if it dies
        # Head-query memo: the driver asks for the head several times per
        # slot (propose per group, attest, the per-slot record, light-
        # client and DAS serving) between which nothing head-relevant
        # moved. ``_rev`` bumps on every mutation of the dense image
        # (block row, landed vote batch, slashing, rebuild); the memo key
        # adds the store-side inputs the device query reads (fingerprint,
        # boost root, block count), so a cached answer is exactly what a
        # fresh ``_device_head`` would return.
        self._rev = 0
        self._head_memo: tuple | None = None
        try:
            self.rebuild(store)
        except Exception as e:
            # a box whose device path is broken outright (resume of a
            # degraded checkpoint, crash-restart mid-outage) still gets a
            # working instance: every device-touching method early-returns
            # once degraded, and head() answers from the spec walk
            self._degrade(f"initial rebuild failed: {e!r}")

    def _degrade(self, reason: str) -> None:
        self.degraded = True
        self.incidents.append(reason)
        logger.warning(
            "resident fork choice degraded to the host spec path: %s",
            reason)
        from pos_evolution_tpu.telemetry import emit_global
        emit_global("degradation", component="resident_forkchoice",
                    reason=reason[:400], fallback="host_spec_walk",
                    head_queries=self._head_queries)

    # -- full (re)build --------------------------------------------------------

    def rebuild(self, store) -> None:
        """Densify the spec store from scratch (anchor init, capacity
        growth, prune, or a contract-mandated epoch/checkpoint refresh)."""
        capacity = max(self._min_capacity, next_pow2(len(store.blocks)))
        dense, roots, capacity = build_dense_store(store, capacity)
        self.capacity = capacity
        self.roots: list[bytes] = list(roots)
        self.index_of = {r: i for i, r in enumerate(self.roots)}
        self.parent = dense.parent
        self.slot = dense.slot
        self.rank = dense.rank
        self.real = dense.real
        self.leaf_viable = dense.leaf_viable
        self.msg_block = dense.msg_block
        self.msg_epoch = dense.msg_epoch
        # Full per-validator weights (``build_dense_store`` zeroes weight
        # for validators without a landed message — correct for a one-shot
        # rescan, but the resident store must weight *future* voters too):
        # effective balance under the justified-checkpoint registry, masked
        # by activation window / slashed / equivocating (pos-evolution.md
        # :322, 1438).
        from pos_evolution_tpu.specs.forkchoice import (
            get_current_slot,
            justified_checkpoint_state,
        )
        from pos_evolution_tpu.specs.helpers import compute_epoch_at_slot
        jstate = justified_checkpoint_state(store)
        reg = jstate.validators
        current_epoch = compute_epoch_at_slot(get_current_slot(store))
        active = ((reg.activation_epoch <= np.uint64(current_epoch))
                  & (np.uint64(current_epoch) < reg.exit_epoch))
        weight = np.where(active & ~reg.slashed,
                          reg.effective_balance.astype(np.int64), 0)
        # vote-landing mask: False once a validator equivocates (:1438)
        ok = np.ones(len(reg), dtype=bool)
        for v in store.equivocating_indices:
            if v < ok.shape[0]:
                ok[v] = False
                weight[v] = 0
        # Sharded mode (ISSUE 9): when the jax backend carries an active
        # mesh, the [N] message-table columns are placed sharded over the
        # validator axes (padded with inert rows: no vote, zero weight,
        # never-landing) and the bucket rebuild runs the shard_map vote
        # pass with its two-axis psum — the fork-choice half of the
        # validator-axis sweeps. Incremental scatters (flush / slashing)
        # go through the same jitted kernels, partitioned by GSPMD.
        self._mesh = self._active_mesh()
        if self._mesh is not None:
            from pos_evolution_tpu.parallel.partition import (
                pad_rows,
                shard_leaf,
                spec_for,
            )
            from pos_evolution_tpu.parallel.sharded import vote_weights_for
            n = ok.shape[0]
            npad = ((n + self._mesh.size - 1)
                    // self._mesh.size) * self._mesh.size
            place = lambda name, a, fill: shard_leaf(  # noqa: E731
                self._mesh, spec_for(f"messages/{name}"),
                pad_rows(np.asarray(a), npad, fill))
            self.msg_block = place("msg_block", self.msg_block, -1)
            self.msg_epoch = place("msg_epoch", self.msg_epoch, 0)
            self.ok = place("ok", ok, False)
            self.weight = place("weight", weight, 0)
            self.buckets = vote_weights_for(self._mesh, self.capacity)(
                self.msg_block, self.weight)
        else:
            self.ok = jnp.asarray(ok)
            self.weight = jnp.asarray(weight)
            self.buckets = rebuild_buckets(self.msg_block, self.weight,
                                           self.capacity)
        self._pending: list[tuple[np.ndarray, int, int]] = []
        self._fingerprint = self._store_fingerprint(store)
        self._rev += 1

    @staticmethod
    def _active_mesh():
        from pos_evolution_tpu.backend import get_backend
        backend = get_backend()
        if getattr(backend, "name", "") != "jax":
            return None
        return getattr(backend, "sharded_mesh", lambda: None)()

    def _store_fingerprint(self, store):
        """Events that void the incremental contracts: justified /
        finalized checkpoint moves (weights + viability re-anchor) and
        epoch rollover (activation windows + the viability grace window,
        pos-evolution.md:874-880)."""
        from pos_evolution_tpu.config import cfg
        from pos_evolution_tpu.specs.forkchoice import get_current_slot
        epoch = get_current_slot(store) // cfg().slots_per_epoch
        return (int(store.justified_checkpoint.epoch),
                bytes(store.justified_checkpoint.root),
                int(store.finalized_checkpoint.epoch),
                bytes(store.finalized_checkpoint.root),
                epoch)

    def sync(self, store) -> None:
        """Refresh resident state if a rebuild-mandating event occurred
        (the epoch-boundary hook of the bucket-path contract)."""
        if (len(store.blocks) > self.capacity
                or len(self.roots) != len(store.blocks)
                or self._fingerprint != self._store_fingerprint(store)):
            # No flush: pending votes were already applied to the spec
            # store before being queued, so the rebuild re-reads them from
            # the message table and a device scatter here would be
            # discarded work.
            self.rebuild(store)

    # -- incremental handlers --------------------------------------------------

    def note_block(self, store, block_root: bytes) -> None:
        """Mirror one ``on_block``: append a row. Ranks are order
        statistics over all roots, so the insertion shifts ranks above the
        new root — recomputed host-side in O(B log B) numpy, no device
        rescan. Checkpoint moves triggered by the block are caught by the
        ``sync`` fingerprint."""
        if self.degraded:
            return
        try:
            self._note_block(store, block_root)
        except Exception as e:
            self._degrade(f"note_block failed: {e!r}")

    def _note_block(self, store, block_root: bytes) -> None:
        if len(self.roots) + 1 > self.capacity:
            self.rebuild(store)
            return
        from pos_evolution_tpu.specs.forkchoice import _leaf_is_viable
        i = len(self.roots)
        block = store.blocks[block_root]
        self.roots.append(block_root)
        self.index_of[block_root] = i
        parent_idx = self.index_of.get(bytes(block.parent_root), -1)
        self.parent = self.parent.at[i].set(parent_idx)
        self.slot = self.slot.at[i].set(int(block.slot))
        self.real = self.real.at[i].set(True)
        self.leaf_viable = self.leaf_viable.at[i].set(
            _leaf_is_viable(store, block_root))
        order = np.argsort(np.argsort(np.array(self.roots, dtype=object)))
        rank = np.zeros(self.capacity, np.int32)
        rank[: len(self.roots)] = order
        self.rank = jnp.asarray(rank)
        self._rev += 1
        self.sync(store)

    def note_attestation(self, attesting_indices, target_epoch: int,
                         beacon_block_root: bytes) -> None:
        """Queue latest-message updates; one padded scatter batch lands
        them at the next flush point (head query / slashing / sync)."""
        if self.degraded:
            return
        try:
            self._note_attestation(attesting_indices, target_epoch,
                                   beacon_block_root)
        except Exception as e:
            self._degrade(f"note_attestation failed: {e!r}")

    def _note_attestation(self, attesting_indices, target_epoch: int,
                          beacon_block_root: bytes) -> None:
        idx = self.index_of.get(bytes(beacon_block_root))
        if idx is None:
            return
        vi = np.asarray(attesting_indices, dtype=np.int32)
        # indices past the resident registry (deposits landed after the
        # justified state) would clamp-corrupt the last validator's entry
        # under jnp gather/scatter — drop them like the spec's weight walk
        # does (specs/forkchoice.py latest-message loop, i >= len(reg))
        vi = vi[vi < self.weight.shape[0]]
        if vi.size == 0:
            return
        self._pending.append((vi, int(target_epoch), idx))

    def flush(self) -> None:
        """Apply queued votes in one ``apply_latest_messages`` batch,
        padded to the next power of two so recompiles stay bounded (the
        in-kernel dedup keeps batched semantics equal to sequential
        application)."""
        if not self._pending:
            return
        val_idx = np.concatenate([p[0] for p in self._pending])
        epochs = np.concatenate(
            [np.full(p[0].shape[0], p[1], np.int64) for p in self._pending])
        blocks = np.concatenate(
            [np.full(p[0].shape[0], p[2], np.int32) for p in self._pending])
        self._pending.clear()
        jaxrt.record_dispatch(site="resident_flush")
        k = next_pow2(val_idx.shape[0])
        pad = k - val_idx.shape[0]
        # padded entries: new_block = -1 never lands; epoch 0 + later
        # position never beats a real entry in the dedup tournament
        val_idx = jnp.asarray(np.concatenate(
            [val_idx, np.zeros(pad, np.int32)]))
        blocks = jnp.asarray(np.concatenate(
            [blocks, np.full(pad, -1, np.int32)]))
        epochs = jnp.asarray(np.concatenate([epochs, np.zeros(pad, np.int64)]))
        self.msg_block, self.msg_epoch, self.buckets = apply_latest_messages(
            self.msg_block, self.msg_epoch, self.buckets, val_idx, blocks,
            epochs, self.weight[val_idx], self.ok[val_idx])
        self._rev += 1

    def note_slashing(self, indices) -> None:
        """Mirror ``on_attester_slashing``: discount landed votes and bar
        future ones (equivocation discounting, pos-evolution.md:1438)."""
        if self.degraded:
            return
        try:
            self._note_slashing(indices)
        except Exception as e:
            self._degrade(f"note_slashing failed: {e!r}")

    def _note_slashing(self, indices) -> None:
        idx = np.asarray(sorted(set(int(i) for i in indices)), dtype=np.int32)
        idx = idx[idx < self.weight.shape[0]]
        if idx.size == 0:
            return
        self.flush()  # ordering: votes before the evidence still land
        vi = jnp.asarray(idx)
        self.msg_block, self.msg_epoch, self.buckets = remove_latest_messages(
            self.msg_block, self.msg_epoch, self.buckets, vi, self.weight[vi])
        self.ok = self.ok.at[vi].set(False)
        self.weight = self.weight.at[vi].set(0)
        self._rev += 1

    # -- queries ---------------------------------------------------------------

    def _memo_key(self, store) -> tuple:
        """Everything a fresh ``_device_head`` reads beyond the resident
        arrays themselves (covered by ``_rev``): the rebuild fingerprint
        (justified/finalized checkpoints + epoch — boost *amount* and
        leaf viability are functions of these), the boost root, and the
        block count (``sync`` rebuild trigger)."""
        return (self._rev, self._store_fingerprint(store),
                bytes(store.proposer_boost_root), len(store.blocks))

    def head(self, store) -> bytes:
        """The fast-path head query: flush pending votes, read boost
        scalars from the spec store (they are per-slot host state,
        pos-evolution.md:942-944), descend on device. Repeated queries
        with no intervening mutation answer from the memo — zero device
        work (the driver asks several times per slot). The periodic
        self-check audits fresh computations against the vectorized host
        walk (``ops.forkchoice.get_head_host`` — an independent numpy
        implementation, itself pinned bit-identical to the spec walk;
        the pure-Python ``specs.forkchoice.get_head`` costs tens of
        seconds per call at 64K+ validators and was most of
        SCALE_DEMO_r06's get_head total). Once degraded — device error
        here or in a handler, or a self-check divergence — every query
        answers from the spec walk instead."""
        from pos_evolution_tpu.specs.forkchoice import get_head
        if self.degraded:
            return get_head(store)
        try:
            if not self._pending and self._head_memo is not None:
                key, root = self._head_memo
                if key == self._memo_key(store):
                    return root
            root = self._device_head(store)
            self._head_memo = (self._memo_key(store), root)
        except Exception as e:
            self._degrade(f"device head query failed: {e!r}")
            return get_head(store)
        self._head_queries += 1
        if (self.selfcheck_every
                and self._head_queries % self.selfcheck_every == 0):
            deep_period = self.selfcheck_every * self.DEEPCHECK_FACTOR
            if self._head_queries % deep_period == 0:
                spec_root = get_head(store)   # fully independent oracle
            else:
                from pos_evolution_tpu.ops.forkchoice import get_head_host
                spec_root = get_head_host(store)
            if spec_root != root:
                self._degrade(
                    f"divergence self-check at query {self._head_queries}: "
                    f"device={root.hex()[:8]} spec={spec_root.hex()[:8]}")
                return spec_root
        return root

    def _device_head(self, store) -> bytes:
        from pos_evolution_tpu.specs.forkchoice import get_proposer_boost
        self.sync(store)
        self.flush()
        boost_idx = -1
        boost_amount = 0
        if store.proposer_boost_root != b"\x00" * 32:
            bi = self.index_of.get(bytes(store.proposer_boost_root))
            if bi is not None:
                boost_idx = bi
                boost_amount = get_proposer_boost(store)
        justified_idx = self.index_of[bytes(store.justified_checkpoint.root)]
        jaxrt.record_dispatch(site="resident_head")
        head_idx, _ = head_from_buckets(
            self.parent, self.real, self.rank, self.leaf_viable,
            jnp.int32(justified_idx), self.buckets, jnp.int32(boost_idx),
            jnp.int64(boost_amount), self.capacity)
        # the int() readback is the query's one device->host transfer
        jaxrt.record_transfer(4, direction="d2h", site="resident_head")
        return self.roots[int(head_idx)]


# --- batched multi-block apply (ISSUE 6 tentpole, backfill entry) -------------

def apply_block_batch(state, signed_blocks, validate_result: bool = True,
                      pre_block=None, on_applied=None) -> None:
    """Apply a parent-linked run of signed blocks to ``state`` in place,
    dispatched through the current ``ExecutionBackend``
    (``multi_block_apply`` on both backends; bit-identical host path).

    This is the state-level batched entry for backfill / checkpoint-sync
    chains: one carried state object, the fused per-block sweep's resident
    columns staying hot across consecutive blocks, incremental
    merkleization diffing block-to-block. Store-level batching (commit
    points, checkpoint bookkeeping) lives in
    ``specs/forkchoice.on_block_batch``, which the sim driver's ancestor
    backfill calls; use this function directly when only the final state
    (plus optional per-block callbacks) matters.
    """
    from pos_evolution_tpu.backend import get_backend
    get_backend().multi_block_apply(state, signed_blocks, validate_result,
                                    pre_block=pre_block, on_applied=on_applied)
