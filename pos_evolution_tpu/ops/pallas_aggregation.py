"""Pallas TPU kernel: per-committee batched signature verification (N1/N3).

The aggregation pipeline of ``ops/aggregation.py`` with the per-signer
compression + chain hashes fused into one VMEM-resident kernel: the grid
iterates over committees (one attestation aggregate per step); each step
holds the committee's gathered signer midstates (8 x C u32, word-major /
lane-minor) and the attestation's precomputed message-block schedule
(64 words) in VMEM, runs the three compressions without touching HBM in
between, and writes the 24 signature words per signer. The XOR fold down
to one aggregate per committee stays in XLA (a cheap reduction).

Same FakeBLS semantics as the XLA path — a drop-in; differential tests pin
all three implementations (hashlib / XLA / Pallas) identical.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax

from pos_evolution_tpu.backend.jax_init import ensure_x64
ensure_x64()

import jax.numpy as jnp  # noqa: E402

from pos_evolution_tpu.ops.aggregation import _msg_block2  # noqa: E402
from pos_evolution_tpu.ops.pallas_sha256 import _rounds, _schedule  # noqa: E402
from pos_evolution_tpu.ops.sha256 import _K, H0  # noqa: E402


def _chain_words(h_words: list):
    """Padded single-block message words for H(digest): 8 digest words +
    0x80 pad + 256-bit length, all per-lane."""
    lanes = h_words[0].shape
    zero = jnp.zeros(lanes, dtype=jnp.uint32)
    w16 = list(h_words)
    w16.append(jnp.full(lanes, np.uint32(0x80000000)))
    for _ in range(6):
        w16.append(zero)
    w16.append(jnp.full(lanes, np.uint32(256)))
    return w16


def _agg_sig_kernel(k_ref, w2_ref, states_ref, out_ref, *, unroll: bool):
    """One committee: states (1, 8, C) midstates; w2 (1, 1, 64) the
    attestation's second-block schedule; out (1, 24, C) signature words.
    k_ref: (1, 64) round constants, consulted by the loop form only — on
    the unrolled (compiled) path it is dead weight still DMA'd each grid
    step, kept so one kernel signature serves both modes.

    The per-attestation schedule words are read as (1, 1) static slices so
    they broadcast over the signer lanes without a scalar extract (which
    Mosaic does not lower from VMEM vectors)."""
    c = states_ref.shape[2]
    k_stack = None if unroll else k_ref[0, :]
    w2_stack = [w2_ref[0, 0:1, t:t + 1] for t in range(64)]   # (1, 1) each
    init = tuple(states_ref[0, i:i + 1, :] for i in range(8))  # (1, C) each
    mid = _rounds(init, w2_stack, unroll, k_stack)
    h1 = tuple(mid[i] + init[i] for i in range(8))

    h0c = tuple(jnp.full((1, c), np.uint32(H0[i])) for i in range(8))
    f2 = _rounds(h0c, _schedule(_chain_words(list(h1))), unroll, k_stack)
    h2 = tuple(f2[i] + h0c[i] for i in range(8))
    f3 = _rounds(h0c, _schedule(_chain_words(list(h2))), unroll, k_stack)
    h3 = tuple(f3[i] + h0c[i] for i in range(8))

    for i in range(8):
        out_ref[0, i:i + 1, :] = h1[i]
        out_ref[0, 8 + i:9 + i, :] = h2[i]
        out_ref[0, 16 + i:17 + i, :] = h3[i]


def _schedule_host(w16_words):
    """(A, 16) u32 message blocks -> (A, 64) schedule stacks (XLA, cheap)."""
    return jnp.stack(_schedule([w16_words[:, t] for t in range(16)]), 0).T


def _pallas_sigs(pk_states, committees, msg_words, interpret: bool):
    from jax.experimental import pallas as pl

    a, c = committees.shape
    gathered = pk_states[committees]                       # (A, C, 8)
    states_t = jnp.swapaxes(gathered, 1, 2)                # (A, 8, C)
    w2 = _schedule_host(_msg_block2(msg_words))[:, None, :]  # (A, 1, 64)

    out = pl.pallas_call(
        partial(_agg_sig_kernel, unroll=not interpret),
        out_shape=jax.ShapeDtypeStruct((a, 24, c), jnp.uint32),
        grid=(a,),
        # i*0 not literal 0 in index maps: x64 mode makes literals i64,
        # which Mosaic cannot mix with the i32 grid index
        in_specs=[
            pl.BlockSpec((1, 64), lambda i: (i * 0, i * 0)),
            pl.BlockSpec((1, 1, 64), lambda i: (i, i * 0, i * 0)),
            pl.BlockSpec((1, 8, c), lambda i: (i, i * 0, i * 0)),
        ],
        out_specs=pl.BlockSpec((1, 24, c), lambda i: (i, i * 0, i * 0)),
        interpret=interpret,
    )(jnp.asarray(_K)[None, :], w2, states_t)
    return out  # (A, 24, C)


def aggregate_verify_batch_pallas(pk_states, committees, bits, msg_words,
                                  signatures, interpret: bool = False):
    """Drop-in for ops.aggregation.aggregate_verify_batch via the Pallas
    signer kernel."""
    sigs = _pallas_sigs(pk_states, committees, msg_words, interpret)  # (A,24,C)
    masked = jnp.where(bits[:, None, :], sigs, 0)
    agg = jax.lax.reduce(masked, np.uint32(0), jax.lax.bitwise_xor,
                         dimensions=(2,))
    return (agg == signatures).all(axis=-1) & bits.any(axis=-1)


aggregate_verify_batch_pallas_jit = jax.jit(
    partial(aggregate_verify_batch_pallas, interpret=False))
