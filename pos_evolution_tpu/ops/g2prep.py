"""Batched device preamble for the real-BLS pipeline (component N1).

Round-4 verdict: the per-aggregate hash-to-G2 and G1/G2 decompression
were serial host-side Python (`crypto/bls12_381.py`), bottlenecking the
device pairing pipeline at 2048 aggregates/slot before the Miller loop
even starts. This module moves the expensive modular-arithmetic parts —
square roots (fixed-exponent ladders), sign canonicalization, and the
G2 cofactor clearing — onto the device as batched limb kernels over the
same ``ops/fp.py`` base field the pairing uses, so the whole
FastAggregateVerify path (pos-evolution.md:714-717) runs as one device
pipeline:

    host: SHA candidate scan + cheap Legendre picks (hashlib + one
          base-field pow per candidate)              [O(us) per message]
    device: batched Fq2 sqrt ladder, sign canon, cofactor scalar-mult,
            signature decompression, then ops/pairing.py's Miller loop.

Correctness oracle: ``crypto/bls12_381.py`` (`hash_to_g2`,
`g1_decompress`, `g2_decompress`) — differential-tested in
``tests/test_g2prep.py``.
"""

from __future__ import annotations

import hashlib

import numpy as np

import jax
import jax.numpy as jnp

from pos_evolution_tpu.crypto import bls12_381 as oracle
from pos_evolution_tpu.ops import fp
from pos_evolution_tpu.ops.tower import (
    alg_eq,
    fq2_encode,
    fq2_inv,
    fq2_mul,
    fq2_muli,
    fq2_sq,
)

Q = oracle.Q

# static MSB-first bit schedules for the fixed-exponent ladders
_SQRT_FQ_BITS = np.array(
    [b == "1" for b in bin((Q + 1) // 4)[2:]], dtype=bool)
_SQRT_FQ2_BITS = np.array(
    [b == "1" for b in bin((Q * Q + 7) // 16)[2:]], dtype=bool)
_COFACTOR_BITS = np.array(
    [b == "1" for b in bin(oracle.G2_COFACTOR)[2:]], dtype=bool)

_HALF_Q = fp.to_limbs((Q - 1) // 2)             # sign threshold (canonical y)
_EIGHTH_ROOTS = np.stack([fq2_encode(r) for r in oracle._EIGHTH_ROOTS])
_FQ2_B = fq2_encode(oracle.Fq2(4, 4))           # twist b = 4(u+1)


def _sel(pred, x, y):
    extra = x.ndim - pred.ndim
    return jnp.where(pred.reshape(pred.shape + (1,) * extra), x, y)


# --- fixed-exponent ladders ---------------------------------------------------


def fp_pow_static(x: jax.Array, bits: np.ndarray) -> jax.Array:
    """x^e over the static MSB-first bit string ``bits`` (base field,
    ``lax.scan`` square-and-multiply like ``fp.modinv``)."""
    one = jnp.broadcast_to(jnp.asarray(fp.ONE), x.shape).astype(jnp.int32)

    def step(acc, bit):
        acc = fp.modmul(acc, acc)
        return jnp.where(bit, fp.modmul(acc, x), acc), None

    acc, _ = jax.lax.scan(step, one, jnp.asarray(bits))
    return acc


def fq2_pow_static(x: jax.Array, bits: np.ndarray) -> jax.Array:
    """x^e for Fq2 [..., 2, 32] over a static bit schedule."""
    one = jnp.concatenate(
        [jnp.broadcast_to(jnp.asarray(fp.ONE), x.shape[:-2] + (1, fp.L)),
         jnp.zeros(x.shape[:-2] + (1, fp.L), jnp.int32)], axis=-2)

    def step(acc, bit):
        acc = fq2_sq(acc)
        return _sel(jnp.broadcast_to(bit, acc.shape[:-2]),
                    fq2_mul(acc, x), acc), None

    acc, _ = jax.lax.scan(step, one, jnp.asarray(bits))
    return acc


def fp_sqrt(a: jax.Array):
    """(sqrt, is_square) in Fq (q = 3 mod 4: a^((q+1)/4) candidate)."""
    s = fp_pow_static(a, _SQRT_FQ_BITS)
    ok = fp.eq(fp.modmul(s, s), a)
    return s, ok


def fq2_sqrt_batch(a: jax.Array):
    """(sqrt, is_square) in Fq2 for a [..., 2, 32] — the oracle's
    q^2 = 9 mod 16 method: one candidate ladder, then the four eighth
    roots of unity tried branch-free (compute-and-select)."""
    cand = fq2_pow_static(a, _SQRT_FQ2_BITS)
    roots = jnp.asarray(_EIGHTH_ROOTS)              # [4, 2, 32]
    best = jnp.zeros_like(cand)
    found = jnp.zeros(a.shape[:-2], bool)
    for i in range(4):
        x = fq2_mul(cand, jnp.broadcast_to(roots[i], cand.shape))
        ok = alg_eq(fq2_sq(x), a)
        best = _sel(~found & ok, x, best)
        found = found | ok
    return best, found


# --- sign / parity helpers ----------------------------------------------------


def fp_gt_const(y: jax.Array, const: np.ndarray) -> jax.Array:
    """Canonical y [..., 32] > const (little-endian limb vector):
    big-endian lexicographic compare, vectorized over the batch."""
    return fp_gt_const_pair(fp.canon(y),
                            jnp.broadcast_to(jnp.asarray(const), y.shape))


def fp_y_is_large(y: jax.Array) -> jax.Array:
    """The ZCash compressed-point sign bit: y > (q-1)/2 (canonical)."""
    return fp_gt_const(y, _HALF_Q)


def fp_is_odd(y: jax.Array) -> jax.Array:
    return (fp.canon(y)[..., 0] & 1).astype(bool)


def fq2_y_is_large(y: jax.Array) -> jax.Array:
    """Lexicographic (y.b, y.a) > (-y.b, -y.a) — the oracle's G2 sign."""
    ny = fp.canon(fp.modneg(y))
    ya, yb = fp.canon(y[..., 0, :]), fp.canon(y[..., 1, :])
    na, nb = ny[..., 0, :], ny[..., 1, :]
    b_gt = fp_gt_const_pair(yb, nb)
    b_eq = jnp.all(yb == nb, axis=-1)
    a_gt = fp_gt_const_pair(ya, na)
    return b_gt | (b_eq & a_gt)


def fp_gt_const_pair(y: jax.Array, c: jax.Array) -> jax.Array:
    """Lexicographic compare of two canonical limb arrays (same shape)."""
    gt = y > c
    eq = y == c
    more_sig_eq = jnp.flip(
        jnp.cumprod(jnp.flip(eq, axis=-1), axis=-1), axis=-1)
    prefix_eq = jnp.concatenate(
        [more_sig_eq[..., 1:], jnp.ones(y.shape[:-1] + (1,), bool)], axis=-1)
    return jnp.any(gt & prefix_eq, axis=-1)


def _cond_negate(y: jax.Array, flip: jax.Array) -> jax.Array:
    return _sel(flip, fp.canon(fp.modneg(y)), fp.canon(y))


# --- batched decompression ----------------------------------------------------


def g1_decompress_batch(x: jax.Array, sign_large: jax.Array):
    """Batched ZCash G1 decompression (x [N, 32] canonical limbs,
    sign_large bool[N]) -> (affine [N, 2, 32], valid bool[N]).
    Infinity flags are a host concern (strip before the call)."""
    x2 = fp.modmul(x, x)
    y2 = fp.modadd(fp.modmul(x2, x),
                   jnp.broadcast_to(jnp.asarray(fp.to_limbs(4)), x.shape))
    y, ok = fp_sqrt(y2)
    y = _cond_negate(y, fp_y_is_large(y) != sign_large)
    return jnp.stack([fp.canon(x), y], axis=-2), ok


def g2_decompress_batch(x: jax.Array, sign_large: jax.Array):
    """Batched G2 decompression (x [B, 2, 32] Fq2 limbs, sign bool[B])
    -> (affine [B, 2, 2, 32], valid bool[B])."""
    rhs = fp.modadd(fq2_mul(fq2_sq(x), x),
                    jnp.broadcast_to(jnp.asarray(_FQ2_B), x.shape))
    y, ok = fq2_sqrt_batch(rhs)
    flip = fq2_y_is_large(y) != sign_large
    y = _cond_negate(y, flip[..., None])
    return jnp.stack([jnp.stack([fp.canon(x[..., 0, :]),
                                 fp.canon(x[..., 1, :])], axis=-2), y],
                     axis=-3), ok


def g2_compressed_to_limbs(data: np.ndarray):
    """Host unpack of 96-byte compressed G2 signatures [B, 96] u8 ->
    (x limbs [B, 2, 32], sign bool[B], inf bool[B], invalid bool[B]).

    Canonicality is validated per row instead of silently aliasing
    malformed encodings (a wire signature is attacker-supplied data):
    the ZCash compression flag (bit 383) must be set, both Fq coordinates
    must be fully reduced (< Q — otherwise x and x - Q decode to the same
    point and one signature has two encodings), and the infinity flag
    must come with all-zero payload bits. Invalid rows get zeroed limbs
    and ``invalid=True``; callers decide whether to reject or mask."""
    data = np.asarray(data, np.uint8).reshape(-1, 96)
    out_x = np.zeros((data.shape[0], 2, fp.L), np.int32)
    sign = np.zeros(data.shape[0], bool)
    inf = np.zeros(data.shape[0], bool)
    invalid = np.zeros(data.shape[0], bool)
    for i, row in enumerate(data):
        hi = int.from_bytes(row[:48].tobytes(), "big")
        lo = int.from_bytes(row[48:].tobytes(), "big")
        compressed = bool(hi & (1 << 383))
        inf[i] = bool(hi & (1 << 382))
        sign[i] = bool(hi & (1 << 381))
        x_im = hi & ((1 << 381) - 1)
        if not compressed:
            invalid[i] = True               # uncompressed/garbage framing
            sign[i] = False                 # don't echo garbage flag bits
            inf[i] = False
            continue
        if inf[i]:
            # canonical infinity: no sign, no coordinate bits
            invalid[i] = sign[i] or x_im != 0 or lo != 0
            sign[i] = False
            continue
        if x_im >= Q or lo >= Q:
            invalid[i] = True               # non-reduced field element
            continue
        out_x[i, 1] = fp.to_limbs(x_im)
        out_x[i, 0] = fp.to_limbs(lo)
    return out_x, sign, inf, invalid


# --- G2 (twist) Jacobian arithmetic ------------------------------------------


def g2_double_jac(P):
    """a=0 Jacobian doubling on E'(Fq2); P [..., 3, 2, 32]."""
    X, Y, Z = P[..., 0, :, :], P[..., 1, :, :], P[..., 2, :, :]
    A = fq2_sq(X)
    B = fq2_sq(Y)
    C = fq2_sq(B)
    t = fp.modadd(X, B)
    D = fq2_muli(fp.modsub(fp.modsub(fq2_sq(t), A), C), 2)
    E = fq2_muli(A, 3)
    X3 = fp.modsub(fq2_sq(E), fq2_muli(D, 2))
    Y3 = fp.modsub(fq2_mul(E, fp.modsub(D, X3)), fq2_muli(C, 8))
    Z3 = fq2_muli(fq2_mul(Y, Z), 2)
    return jnp.stack([X3, Y3, Z3], axis=-3)


def _fq2_is_zero(x):
    return fp.is_zero(x[..., 0, :]) & fp.is_zero(x[..., 1, :])


def g2_add_jac(P, Q_):
    """Unified branch-free Jacobian add on the twist — same case
    analysis as ``ops/pairing.py::g1_add_jac`` lifted to Fq2."""
    X1, Y1, Z1 = P[..., 0, :, :], P[..., 1, :, :], P[..., 2, :, :]
    X2, Y2, Z2 = Q_[..., 0, :, :], Q_[..., 1, :, :], Q_[..., 2, :, :]
    Z1Z1 = fq2_sq(Z1)
    Z2Z2 = fq2_sq(Z2)
    U1 = fq2_mul(X1, Z2Z2)
    U2 = fq2_mul(X2, Z1Z1)
    S1 = fq2_mul(Y1, fq2_mul(Z2, Z2Z2))
    S2 = fq2_mul(Y2, fq2_mul(Z1, Z1Z1))
    H = fp.modsub(U2, U1)
    r = fp.modsub(S2, S1)
    H2 = fq2_sq(H)
    H3 = fq2_mul(H, H2)
    V = fq2_mul(U1, H2)
    X3 = fp.modsub(fp.modsub(fq2_sq(r), H3), fq2_muli(V, 2))
    Y3 = fp.modsub(fq2_mul(r, fp.modsub(V, X3)), fq2_mul(S1, H3))
    Z3 = fq2_mul(H, fq2_mul(Z1, Z2))
    gen = jnp.stack([X3, Y3, Z3], axis=-3)

    p_inf = _fq2_is_zero(Z1)
    q_inf = _fq2_is_zero(Z2)
    same_x = _fq2_is_zero(H) & ~p_inf & ~q_inf
    same_y = _fq2_is_zero(r)
    out = _sel(same_x & same_y, g2_double_jac(P), gen)
    out = _sel(same_x & ~same_y, jnp.zeros_like(out), out)
    out = _sel(p_inf, Q_, out)
    out = _sel(q_inf & ~p_inf, P, out)
    return out


def g2_affine_to_jac(q_aff):
    """[..., 2, 2, 32] affine -> [..., 3, 2, 32] Jacobian (Z = 1)."""
    one = jnp.concatenate(
        [jnp.broadcast_to(jnp.asarray(fp.ONE),
                          q_aff.shape[:-3] + (1, fp.L)),
         jnp.zeros(q_aff.shape[:-3] + (1, fp.L), jnp.int32)], axis=-2)
    return jnp.concatenate([q_aff, one[..., None, :, :]], axis=-3)


def g2_jac_to_affine(P):
    """[..., 3, 2, 32] -> (affine [..., 2, 2, 32], inf mask [...])."""
    X, Y, Z = P[..., 0, :, :], P[..., 1, :, :], P[..., 2, :, :]
    za = jnp.stack([fp.canon(Z[..., 0, :]), fp.canon(Z[..., 1, :])], axis=-2)
    zi = fq2_inv(za)
    zi2 = fq2_sq(zi)
    x = fq2_mul(X, zi2)
    y = fq2_mul(Y, fq2_mul(zi, zi2))
    return (jnp.stack([
        jnp.stack([fp.canon(x[..., 0, :]), fp.canon(x[..., 1, :])], axis=-2),
        jnp.stack([fp.canon(y[..., 0, :]), fp.canon(y[..., 1, :])], axis=-2),
    ], axis=-3), _fq2_is_zero(Z))


def g2_mul_static(q_aff: jax.Array, bits: np.ndarray) -> jax.Array:
    """Scalar mult by a STATIC MSB-first bit schedule (the cofactor):
    double-and-add over a ``lax.scan``; returns Jacobian [..., 3, 2, 32]."""
    pj = g2_affine_to_jac(q_aff)
    acc = jnp.zeros_like(pj)                     # Z = 0: infinity

    def step(acc, bit):
        acc = g2_double_jac(acc)
        added = g2_add_jac(acc, pj)
        return _sel(jnp.broadcast_to(bit, acc.shape[:-3]), added, acc), None

    acc, _ = jax.lax.scan(step, acc, jnp.asarray(bits))
    return acc


def g2_mul_scalar_batch(q_aff: jax.Array, scalar_bits: jax.Array) -> jax.Array:
    """Per-element scalar mult: scalar_bits bool[..., nbits] MSB-first
    as DATA (used for bench signing; the verify path never needs it)."""
    pj = g2_affine_to_jac(q_aff)
    acc = jnp.zeros_like(pj)

    def step(acc, bit):                          # bit: bool[...]
        acc = g2_double_jac(acc)
        added = g2_add_jac(acc, pj)
        return _sel(bit, added, acc), None

    acc, _ = jax.lax.scan(step, acc, jnp.moveaxis(scalar_bits, -1, 0))
    return acc


# --- hash to G2, batched ------------------------------------------------------


def hash_to_g2_candidates(messages) -> tuple:
    """Host scan mirroring the oracle's try-and-increment: for each
    message walk ctr = 0, 1, ... and pick the first x candidate whose
    rhs = x^3 + 4(u+1) is a square in Fq2 (one cheap Legendre check on
    the norm per candidate — pow is native C). Returns (x limbs
    [B, 2, 32], ctr picks [B]). The expensive part — the actual sqrt
    ladder, sign canon and cofactor clearing — runs on device in
    ``hash_to_g2_finish``."""
    out = np.zeros((len(messages), 2, fp.L), np.int32)
    picks = np.zeros(len(messages), np.int64)
    exp = (Q - 1) // 2
    for i, message in enumerate(messages):
        ctr = 0
        while True:
            seed = hashlib.sha256(
                b"blsg2" + bytes(message) + ctr.to_bytes(4, "little"))
            d0 = seed.digest()
            d1 = hashlib.sha256(d0).digest()
            d2 = hashlib.sha256(d1).digest()
            xa = int.from_bytes(d0 + d1[:16], "big") % Q
            xb = int.from_bytes(d1[16:] + d2, "big") % Q
            # rhs = x^3 + 4(u+1); square in Fq2 iff norm(rhs) is a QR in Fq
            r_ = oracle.Fq2(xa, xb)
            rhs = r_.sq() * r_ + oracle.Fq2(4, 4)
            norm = (rhs.a * rhs.a + rhs.b * rhs.b) % Q
            if norm == 0 or pow(norm, exp, Q) == 1:
                out[i, 0] = fp.to_limbs(xa)
                out[i, 1] = fp.to_limbs(xb)
                picks[i] = ctr
                break
            ctr += 1
    return out, picks


def hash_to_g2_finish(x: jax.Array):
    """Device finish of the hash-to-G2 map for picked candidates
    x [B, 2, 32]: Fq2 sqrt, canonical (even y.a) sign, cofactor
    clearing. Returns (affine [B, 2, 2, 32], ok bool[B]) — ok False
    only in the measure-zero case of the cleared point at infinity
    (the oracle retries; callers assert instead)."""
    rhs = fp.modadd(fq2_mul(fq2_sq(x), x),
                    jnp.broadcast_to(jnp.asarray(_FQ2_B), x.shape))
    y, is_sq = fq2_sqrt_batch(rhs)
    # oracle canonical sign: negate when y.a is odd
    flip = fp_is_odd(y[..., 0, :])
    y = _cond_negate(y, flip[..., None])
    point = jnp.stack([jnp.stack([fp.canon(x[..., 0, :]),
                                  fp.canon(x[..., 1, :])], axis=-2), y],
                      axis=-3)
    cleared = g2_mul_static(point, _COFACTOR_BITS)
    aff, inf = g2_jac_to_affine(cleared)
    return aff, is_sq & ~inf


def hash_to_g2_batch(messages):
    """Full batched map: host candidate scan + device finish.
    Returns affine [B, 2, 2, 32].

    Graceful degradation in miniature: the (measure-zero)
    cofactor-clears-to-infinity rows — where the device pipeline cannot
    retry without a data-dependent rehash — fall back to the host oracle
    for JUST those messages, keeping the batch result bit-exact with
    ``crypto/bls12_381.hash_to_g2`` instead of aborting the whole batch."""
    x, _ = hash_to_g2_candidates(messages)
    aff, ok = hash_to_g2_finish(jnp.asarray(x))
    ok_np = np.asarray(ok)
    if not ok_np.all():
        from pos_evolution_tpu.ops.pairing import g2_affine_encode
        patched = np.array(aff)
        for i in np.nonzero(~ok_np)[0]:
            patched[int(i)] = g2_affine_encode(
                oracle.hash_to_g2(bytes(messages[int(i)])))
        aff = jnp.asarray(patched)
    return aff
