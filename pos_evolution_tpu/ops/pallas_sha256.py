"""Pallas TPU kernel: batched SHA-256 merkle-level compression (N2).

The hot merkleization shape (SURVEY.md §2.7): hash N pairs of 32-byte
nodes -> N digests, repeated level by level (state roots pos-evolution.md
:423, the balances-array "<32 MB per epoch" rehash :114). The kernel lays
messages out transposed — word index on the sublane axis, message index on
the 128-wide lane axis — so every round is pure uint32 VPU arithmetic over
a (1, TILE) vector, and tiles stream through VMEM on a 1-D grid.

Used through ``merkle_level_pallas`` (one tree level) and
``merkleize_words_device`` (whole tree on device); falls back to the XLA
formulation in ``ops/sha256.py`` when Pallas is unavailable.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

# x64 goes through the one consolidated helper, at first kernel USE —
# importing this module must never mutate process-global JAX config.
from pos_evolution_tpu.backend.jax_init import ensure_x64
from pos_evolution_tpu.ops.sha256 import _K, H0, sha256_pair_words

TILE = 512  # messages per grid step (lanes)


def _rotr(x, n: int):
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _schedule(w16: list) -> list:
    """Expand 16 message words to the 64-entry schedule (list of per-round
    words; callers needing an array stack it themselves)."""
    w = list(w16)
    for t in range(16, 64):
        s0 = _rotr(w[t - 15], 7) ^ _rotr(w[t - 15], 18) ^ (w[t - 15] >> np.uint32(3))
        s1 = _rotr(w[t - 2], 17) ^ _rotr(w[t - 2], 19) ^ (w[t - 2] >> np.uint32(10))
        w.append(w[t - 16] + s0 + w[t - 7] + s1)
    return w


def _rounds(state_words, w_list, unroll: bool = True, k_stack=None):
    """64 compression rounds.

    ``unroll=True`` (Mosaic-compiled path): statically unrolled with the
    round constants baked in as compile-time scalars — Mosaic has no
    dynamic_slice. ``unroll=False`` (interpret / CPU path): a fori_loop
    over the stacked schedule — fully-unrolled SHA graphs compile
    superlinearly on XLA:CPU (minutes), the loop form stays bounded.

    ``w_list`` is a list of 64 per-round words (entries may broadcast
    against the state lanes) or an equivalent (64, ...) stacked array.
    ``k_stack`` (loop form only) is the (64,) round-constant array, which
    must be a kernel *input* — Pallas kernels cannot capture materialized
    constant arrays."""
    if unroll:
        a, b, c, d, e, f, g, h = state_words
        for t in range(64):
            wt = w_list[t]
            s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
            ch = (e & f) ^ (~e & g)
            t1 = h + s1 + ch + np.uint32(_K[t]) + wt
            s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
            maj = (a & b) ^ (a & c) ^ (b & c)
            a, b, c, d, e, f, g, h = t1 + s0 + maj, a, b, c, d + t1, e, f, g
        return (a, b, c, d, e, f, g, h)

    w_stack = jnp.stack(w_list, 0) if isinstance(w_list, list) else w_list

    def body(t, carry):
        a, b, c, d, e, f, g, h = carry
        wt = jax.lax.dynamic_index_in_dim(w_stack, t, axis=0, keepdims=False)
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + k_stack[t] + wt
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        return (t1 + s0 + maj, a, b, c, d + t1, e, f, g)

    return jax.lax.fori_loop(0, 64, body, tuple(state_words))


def _merkle_level_kernel(k_ref, in_ref, out_ref, *, unroll: bool):
    """k_ref: (1, 64) u32 round constants (loop form only — the unrolled
    Mosaic path bakes them in as scalars); in_ref: (16, TILE) u32 — the
    64-byte message block of each pair, transposed; out_ref: (8, TILE) u32
    digests (includes the fixed padding block).

    Every value is kept 2-D ((1, TILE) rows) — Mosaic legalizes 2-D
    sublane×lane vectors, not 1-D ops."""
    lanes = in_ref.shape[1]
    k_stack = None if unroll else k_ref[0, :]
    w_stack = _schedule([in_ref[t:t + 1, :] for t in range(16)])
    init = tuple(jnp.full((1, lanes), np.uint32(H0[i])) for i in range(8))
    mid = _rounds(init, w_stack, unroll, k_stack)
    state1 = tuple(mid[i] + init[i] for i in range(8))

    # second block: fixed SHA-256 padding for a 64-byte message
    zero = jnp.zeros((1, lanes), dtype=jnp.uint32)
    pad16 = [zero] * 16
    pad16[0] = jnp.full((1, lanes), np.uint32(0x80000000))
    pad16[15] = jnp.full((1, lanes), np.uint32(512))
    fin = _rounds(state1, _schedule(pad16), unroll, k_stack)
    for i in range(8):
        out_ref[i:i + 1, :] = fin[i] + state1[i]


def _pallas_level_call(pairs_t: jax.Array, interpret: bool) -> jax.Array:
    from jax.experimental import pallas as pl

    ensure_x64()
    n = pairs_t.shape[1]
    return pl.pallas_call(
        partial(_merkle_level_kernel, unroll=not interpret),
        out_shape=jax.ShapeDtypeStruct((8, n), jnp.uint32),
        grid=(n // TILE,),
        # index maps use i*0 (not literal 0): under jax_enable_x64 a literal
        # becomes i64 next to the i32 grid index, which Mosaic cannot
        # legalize (mixed-type index-map return)
        in_specs=[pl.BlockSpec((1, 64), lambda i: (i * 0, i * 0)),
                  pl.BlockSpec((16, TILE), lambda i: (i * 0, i))],
        out_specs=pl.BlockSpec((8, TILE), lambda i: (i * 0, i)),
        interpret=interpret,
    )(jnp.asarray(_K)[None, :], pairs_t)


_jitted_level = jax.jit(partial(_pallas_level_call, interpret=False))


def merkle_level_pallas(pairs_t: jax.Array, interpret: bool = False) -> jax.Array:
    """One merkle level: pairs_t (16, N) u32 (transposed 64-byte messages,
    N a multiple of TILE) -> (8, N) u32 digests. Interpret mode runs
    eagerly (jit-wrapping the interpreter embeds a huge graph in XLA:CPU)."""
    ensure_x64()  # before entering the jit — never mid-trace
    if interpret:
        return _pallas_level_call(pairs_t, interpret=True)
    return _jitted_level(pairs_t)


def _level_xla(nodes: jax.Array) -> jax.Array:
    """(2k, 8) u32 digest words -> (k, 8): XLA fallback combiner."""
    return sha256_pair_words(nodes[0::2], nodes[1::2])


def _level(nodes: jax.Array, use_pallas: bool, interpret: bool) -> jax.Array:
    k = nodes.shape[0] // 2
    if not use_pallas or k % TILE != 0:
        return _level_xla(nodes)
    pairs_t = nodes.reshape(k, 16).T  # (16, k): word-major, message-minor
    return merkle_level_pallas(pairs_t, interpret=interpret).T


def merkleize_words_device(leaves: jax.Array, depth: int,
                           zero_words: np.ndarray,
                           use_pallas: bool = True,
                           interpret: bool = False) -> jax.Array:
    """Device merkle root of (N, 8) u32 digest-word leaves, padded with
    zero-subtree roots to depth ``depth``. N must be a power of two (pad
    the tail with ``zero_words[0]`` first).

    zero_words: (depth+1, 8) u32 — ZERO_HASHES as big-endian words.
    """
    ensure_x64()
    nodes = leaves
    level = 0
    while nodes.shape[0] > 1:
        nodes = _level(nodes, use_pallas, interpret)
        level += 1
    root = nodes[0]
    # fold the remaining virtual zero-subtrees up to the target depth
    for lv in range(level, depth):
        pair = jnp.stack([root, jnp.asarray(zero_words[lv])])
        root = _level_xla(pair)[0]
    return root
