"""Pallas TPU kernel: batched SHA-256 merkle-level compression (N2).

The hot merkleization shape (SURVEY.md §2.7): hash N pairs of 32-byte
nodes -> N digests, repeated level by level (state roots pos-evolution.md
:423, the balances-array "<32 MB per epoch" rehash :114). The kernel lays
messages out transposed — word index on the sublane axis, message index on
the 128-wide lane axis — so every round is pure uint32 VPU arithmetic over
a (1, TILE) vector, and tiles stream through VMEM on a 1-D grid.

Used through ``merkle_level_pallas`` (one tree level) and
``merkleize_words_device`` (whole tree on device); falls back to the XLA
formulation in ``ops/sha256.py`` when Pallas is unavailable.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402

from pos_evolution_tpu.ops.sha256 import _K, H0, sha256_pair_words  # noqa: E402

TILE = 512  # messages per grid step (lanes)


def _rotr(x, n: int):
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _schedule(w16: list) -> jax.Array:
    """Expand 16 message words to the (64, TILE) schedule stack."""
    w = list(w16)
    for t in range(16, 64):
        s0 = _rotr(w[t - 15], 7) ^ _rotr(w[t - 15], 18) ^ (w[t - 15] >> np.uint32(3))
        s1 = _rotr(w[t - 2], 17) ^ _rotr(w[t - 2], 19) ^ (w[t - 2] >> np.uint32(10))
        w.append(w[t - 16] + s0 + w[t - 7] + s1)
    return jnp.stack(w, axis=0)


def _rounds(state_words, w_stack, k_stack):
    """64 compression rounds as a fori_loop over the schedule stack —
    bounded graph size for both Mosaic and interpret-mode lowering."""

    def body(t, carry):
        a, b, c, d, e, f, g, h = carry
        wt = jax.lax.dynamic_index_in_dim(w_stack, t, axis=0, keepdims=False)
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + k_stack[t] + wt
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        return (t1 + s0 + maj, a, b, c, d + t1, e, f, g)

    return jax.lax.fori_loop(0, 64, body, tuple(state_words))


def _merkle_level_kernel(k_ref, in_ref, out_ref):
    """k_ref: (64,) u32 round constants; in_ref: (16, TILE) u32 — the
    64-byte message block of each pair, transposed; out_ref: (8, TILE) u32
    digests (includes the fixed padding block)."""
    lanes = in_ref.shape[1]
    k_stack = k_ref[:]
    w_stack = _schedule([in_ref[t, :] for t in range(16)])
    init = tuple(jnp.full((lanes,), np.uint32(H0[i])) for i in range(8))
    mid = _rounds(init, w_stack, k_stack)
    state1 = tuple(mid[i] + init[i] for i in range(8))

    # second block: fixed SHA-256 padding for a 64-byte message
    zero = jnp.zeros((lanes,), dtype=jnp.uint32)
    pad16 = [zero] * 16
    pad16[0] = jnp.full((lanes,), np.uint32(0x80000000))
    pad16[15] = jnp.full((lanes,), np.uint32(512))
    fin = _rounds(state1, _schedule(pad16), k_stack)
    for i in range(8):
        out_ref[i, :] = fin[i] + state1[i]


def _pallas_level_call(pairs_t: jax.Array, interpret: bool) -> jax.Array:
    from jax.experimental import pallas as pl

    n = pairs_t.shape[1]
    return pl.pallas_call(
        _merkle_level_kernel,
        out_shape=jax.ShapeDtypeStruct((8, n), jnp.uint32),
        grid=(n // TILE,),
        in_specs=[pl.BlockSpec((64,), lambda i: (0,)),
                  pl.BlockSpec((16, TILE), lambda i: (0, i))],
        out_specs=pl.BlockSpec((8, TILE), lambda i: (0, i)),
        interpret=interpret,
    )(jnp.asarray(_K), pairs_t)


_jitted_level = jax.jit(partial(_pallas_level_call, interpret=False))


def merkle_level_pallas(pairs_t: jax.Array, interpret: bool = False) -> jax.Array:
    """One merkle level: pairs_t (16, N) u32 (transposed 64-byte messages,
    N a multiple of TILE) -> (8, N) u32 digests. Interpret mode runs
    eagerly (jit-wrapping the interpreter embeds a huge graph in XLA:CPU)."""
    if interpret:
        return _pallas_level_call(pairs_t, interpret=True)
    return _jitted_level(pairs_t)


def _level_xla(nodes: jax.Array) -> jax.Array:
    """(2k, 8) u32 digest words -> (k, 8): XLA fallback combiner."""
    return sha256_pair_words(nodes[0::2], nodes[1::2])


def _level(nodes: jax.Array, use_pallas: bool, interpret: bool) -> jax.Array:
    k = nodes.shape[0] // 2
    if not use_pallas or k % TILE != 0:
        return _level_xla(nodes)
    pairs_t = nodes.reshape(k, 16).T  # (16, k): word-major, message-minor
    return merkle_level_pallas(pairs_t, interpret=interpret).T


def merkleize_words_device(leaves: jax.Array, depth: int,
                           zero_words: np.ndarray,
                           use_pallas: bool = True,
                           interpret: bool = False) -> jax.Array:
    """Device merkle root of (N, 8) u32 digest-word leaves, padded with
    zero-subtree roots to depth ``depth``. N must be a power of two (pad
    the tail with ``zero_words[0]`` first).

    zero_words: (depth+1, 8) u32 — ZERO_HASHES as big-endian words.
    """
    nodes = leaves
    level = 0
    while nodes.shape[0] > 1:
        nodes = _level(nodes, use_pallas, interpret)
        level += 1
    root = nodes[0]
    # fold the remaining virtual zero-subtrees up to the target depth
    for lv in range(level, depth):
        pair = jnp.stack([root, jnp.asarray(zero_words[lv])])
        root = _level_xla(pair)[0]
    return root
