"""GasperVariant: today's protocol behind the seam, behavior-identical.

HLMD-GHOST + FFG exactly as ``specs/forkchoice.py`` implements them
(pos-evolution.md:884-1126): head queries answer from the resident device
mirror when one is attached (ops/resident.py) or the spec walk otherwise
— byte-for-byte the pre-seam driver (pinned by the behavior-identity test
in tests/test_variant_seam.py). No overlay is attached
(``needs_view = False``), so the fork-choice handlers' ``variant_view``
hook stays None and the hot path pays one attribute read."""

from __future__ import annotations

from pos_evolution_tpu.specs import forkchoice as fc
from pos_evolution_tpu.variants.base import ProtocolVariant


class GasperVariant(ProtocolVariant):
    name = "gasper"
    needs_view = False

    def head(self, sim, group) -> bytes:
        if group.resident is not None:
            return group.resident.head(group.store)
        return fc.get_head(group.store)

    def describe(self) -> dict:
        return {"kind": "GasperVariant"}
