"""GoldfishVariant: GHOST-Eph in the production driver
(pos-evolution.md:1543-1579).

- ``eta = 1`` vote expiry: only the previous slot's head votes carry
  fork-choice weight (:1549) — the property that makes banked withheld
  votes worthless and kills the swayer balancing attack (:1321-1348)
  without proposer boost;
- VRF leader preference + voter subsampling (:1545, :1554): the beacon
  carrier fixes the proposer *schedule* (block validity pins
  ``proposer_index``), so VRF election manifests as the fork-choice
  preference for the minimal-VRF proposal among same-slot siblings and
  as the subsampled vote-eligibility predicate shared with the
  ``models/`` PVM oracle;
- kappa-deep (slow) and 3/4 fast confirmation (:1556, :1562-1569), fast
  confirmations never rolled back (:1568).
"""

from __future__ import annotations

from pos_evolution_tpu.variants.base import ExpiryVariantBase


class GoldfishVariant(ExpiryVariantBase):
    name = "goldfish"
    eta = 1
    use_vrf = True

    def __init__(self, kappa: int = 4, fast_confirm: bool = True,
                 fast_confirm_threshold: float = 0.75,
                 subsample_rate: float = 1.0):
        super().__init__()
        self.kappa = int(kappa)
        self.fast_confirm = bool(fast_confirm)
        self.fast_confirm_threshold = float(fast_confirm_threshold)
        self.subsample_rate = float(subsample_rate)

    def describe(self) -> dict:
        return {"kind": "GoldfishVariant", "eta": 1, "kappa": self.kappa,
                "fast_confirm": self.fast_confirm,
                "fast_confirm_threshold": self.fast_confirm_threshold,
                "subsample_rate": self.subsample_rate}
