"""Pluggable protocol variants (ROADMAP item 5; DESIGN.md §16).

The protocol itself becomes a seam: ``ProtocolVariant`` abstracts fork
choice + finality over the production driver, so the paper's successor
protocols (view-merge -> Goldfish -> RLMD-GHOST -> single-slot finality,
pos-evolution.md:1528-1650) run end-to-end through ``Simulation`` — under
the PR-5 Byzantine adversaries, safety/liveness monitors, fault plans,
checkpoint/resume, and telemetry — instead of living only in the toy
``models/`` propose-vote-merge layer (which is retained as the
per-variant differential oracle).
"""

from pos_evolution_tpu.variants.base import (
    ProtocolVariant,
    VariantVoteLog,
    variant_from_config,
)
from pos_evolution_tpu.variants.gasper import GasperVariant
from pos_evolution_tpu.variants.goldfish import GoldfishVariant
from pos_evolution_tpu.variants.rlmd import RlmdGhostVariant
from pos_evolution_tpu.variants.ssf import SsfVariant

VARIANTS = {
    "gasper": GasperVariant,
    "goldfish": GoldfishVariant,
    "rlmd": RlmdGhostVariant,
    "ssf": SsfVariant,
}

__all__ = [
    "ProtocolVariant", "VariantVoteLog", "variant_from_config",
    "GasperVariant", "GoldfishVariant", "RlmdGhostVariant", "SsfVariant",
    "VARIANTS",
]
