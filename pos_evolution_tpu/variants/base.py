"""The ProtocolVariant seam (ROADMAP item 5; pos-evolution.md:1528-1650).

A variant owns two protocol decisions the driver used to hard-code:

- **fork choice**: ``head(sim, group)`` answers every head query the
  driver makes (propose, attest, record, light-client/DAS serving,
  adversary omniscience);
- **finality/confirmation**: per-slot hooks (``on_slot_start`` /
  ``on_slot_end``) run the variant's confirmation rules — kappa-deep and
  3/4 fast confirmation (:1556, :1562-1569), per-slot supermajority links
  and acknowledgments (:1626, :1646) — over the votes each view actually
  received.

The beacon chain stays the **carrier**: blocks, committees, attestations
and the FFG state transition are unchanged (GasperVariant is the
behavior-identical default), and a successor variant interprets the same
per-view message stream under its own rule. Votes reach a variant through
``Store.variant_view`` — the fork-choice handlers notify the attached
``VariantVoteLog`` post-commit, so gossip, block-carried, backfilled and
adversarial attestations all land exactly once per view, subject to the
run's FaultPlan and partitions (composability with the PR-5 audit stack
is the point).

Carrier timing note: the driver's wire makes slot-``t`` head votes
deliverable from slot ``t+1`` (``validate_on_attestation``'s
current-slot guard), so a variant's vote-round processing for slot ``t``
runs at the ``t+1`` boundary — the same 3Δ/4Δ phase structure shifted by
one boundary, with the protocol rules themselves unchanged. The
``models/`` PVM simulations run the un-shifted rounds and serve as the
differential oracles for the fork-choice/confirmation rules proper
(tests/test_variant_seam.py).

Hot loops (expiry-windowed tally, supermajority/ack tallies, subtree
accumulation) dispatch through ``ExecutionBackend`` — vectorized on
NumPy and jitted JAX, bit-identical (ops/variant_tally.py).
"""

from __future__ import annotations

from pos_evolution_tpu.specs import forkchoice as fc
from pos_evolution_tpu.specs.helpers import (
    compute_epoch_at_slot,
    get_total_active_balance,
)


class VariantVoteLog:
    """One view's slot-granular vote overlay: the ``(validator, slot,
    root)`` head-vote table the expiry-windowed variants need (the
    carrier's ``Store.latest_messages`` only keeps target epochs), plus
    per-slot equivocation detection (pos-evolution.md:1411) and the
    view-merge buffer (:1528-1541).

    ``note_vote`` lands in the **pending buffer**; ``merge()`` — called by
    the variant at the slot boundary, the Merge phase of the
    propose-vote-merge template (:1602-1608) — folds it into the active
    tables. A message delivered mid-slot therefore influences no head
    query until the next boundary, which is precisely the view-merge
    defense against just-before-the-deadline delivery (:1328, :1540).
    """

    def __init__(self, group_id: int, buffered: bool = True):
        self.group_id = group_id
        self.buffered = buffered
        self.pending: list[tuple[int, int, bytes]] = []  # (v, slot, root)
        self.latest: dict[int, tuple[int, bytes]] = {}   # v -> (slot, root)
        self.slot_votes: dict[tuple[int, int], bytes] = {}  # (v, slot) -> root
        self.by_slot: dict[int, dict[int, bytes]] = {}   # slot -> {v: root}
        self.equivocators: set[int] = set()

    # -- Store.variant_view contract (called by specs/forkchoice.py) ----------

    def note_vote(self, indices, slot: int, root: bytes) -> None:
        slot = int(slot)
        root = bytes(root)
        for v in indices:
            self.pending.append((int(v), slot, root))
        if not self.buffered:
            self.merge()

    def note_equivocators(self, indices) -> None:
        """Slasher-evidenced equivocators (on_attester_slashing) are
        discounted at the variant layer too (pos-evolution.md:1438)."""
        self.equivocators.update(int(i) for i in indices)

    # -- merge phase -----------------------------------------------------------

    def merge(self) -> None:
        for v, slot, root in self.pending:
            prev = self.slot_votes.get((v, slot))
            if prev is not None and prev != root:
                # two head votes in one slot: discounted forever (:1411)
                self.equivocators.add(v)
                continue
            self.slot_votes[(v, slot)] = root
            self.by_slot.setdefault(slot, {})[v] = root
            cur = self.latest.get(v)
            if cur is None or slot > cur[0]:
                self.latest[v] = (slot, root)
        self.pending = []

    def prune(self, below_slot: int) -> None:
        """Drop per-slot records older than ``below_slot`` (the expiry
        window plus confirmation depth bound them; ``latest`` is O(N)
        already)."""
        for s in [s for s in self.by_slot if s < below_slot]:
            del self.by_slot[s]
        for key in [k for k in self.slot_votes if k[1] < below_slot]:
            del self.slot_votes[key]

    # -- snapshot --------------------------------------------------------------

    def state_blob(self) -> dict:
        return {
            "pending": [[v, s, r.hex()] for v, s, r in self.pending],
            "latest": {str(v): [s, r.hex()]
                       for v, (s, r) in sorted(self.latest.items())},
            "slot_votes": [[v, s, r.hex()]
                           for (v, s), r in sorted(self.slot_votes.items())],
            "equivocators": sorted(self.equivocators),
        }

    @classmethod
    def from_blob(cls, group_id: int, blob: dict,
                  buffered: bool = True) -> "VariantVoteLog":
        log = cls(group_id, buffered=buffered)
        log.pending = [(int(v), int(s), bytes.fromhex(r))
                       for v, s, r in blob.get("pending", [])]
        log.equivocators = set(blob.get("equivocators", []))
        for v, s, r in blob.get("slot_votes", []):
            root = bytes.fromhex(r)
            log.slot_votes[(int(v), int(s))] = root
            log.by_slot.setdefault(int(s), {})[int(v)] = root
        for v, (s, r) in blob.get("latest", {}).items():
            log.latest[int(v)] = (int(s), bytes.fromhex(r))
        return log


def densify_view(store) -> tuple[list, dict, "np.ndarray", "np.ndarray"]:
    """Store block-tree -> parent-index arrays (insertion order is
    topological, the ``subtree_weights`` contract). Returns
    (roots, index_of, parent int32[B], slot int64[B])."""
    import numpy as np
    roots = list(store.blocks.keys())
    index_of = {r: i for i, r in enumerate(roots)}
    parent = np.full(len(roots), -1, dtype=np.int32)
    slots = np.zeros(len(roots), dtype=np.int64)
    for i, root in enumerate(roots):
        block = store.blocks[root]
        slots[i] = int(block.slot)
        parent[i] = index_of.get(bytes(block.parent_root), -1)
    return roots, index_of, parent, slots


class ProtocolVariant:
    """Base seam: the behavior contract every variant implements.

    ``needs_view = False`` (Gasper) means no overlay is attached and the
    handlers' ``variant_view`` hook stays ``None`` — the default path is
    byte-for-byte today's driver."""

    name = "variant"
    needs_view = False

    def bind(self, sim) -> None:
        self.sim = sim

    def describe(self) -> dict:
        """Config fingerprint for checkpoints and repro bundles; must
        round-trip through ``variant_from_config``."""
        return {"kind": type(self).__name__}

    # -- per-view overlay ------------------------------------------------------

    def make_view(self, group_id: int):
        """The object attached as ``Store.variant_view`` (None = no
        overlay)."""
        return None

    def reset_view(self, group) -> None:
        """Crash-rejoin: the process died and its overlay with it; the
        checkpoint-synced store gets a fresh one (votes re-arrive via
        backfilled blocks exactly like the carrier's LMD table)."""

    # -- fork choice -----------------------------------------------------------

    def head(self, sim, group) -> bytes:
        raise NotImplementedError

    # -- slot hooks (driver calls; slot 0 included) ----------------------------

    def on_slot_start(self, sim, slot: int) -> None:
        """After the boundary tick: merge view buffers, process the
        completed vote round (fast confirmation, per-slot FFG)."""

    def on_slot_end(self, sim, slot: int) -> dict | None:
        """After the slot's duties: confirmation rules, telemetry record.
        Returns the ``variant`` event payload (None = nothing to emit)."""
        return None

    # -- audit surface (sim/monitors.VariantSafetyMonitor) ---------------------

    def finalized_checkpoints(self, group_id: int) -> list[tuple[bytes, int]]:
        """Variant-finalized (root, slot) pairs in this view (SSF)."""
        return []

    def fast_confirmations(self, group_id: int) -> list[tuple[bytes, int]]:
        """Fast-confirmed (root, slot) pairs in this view (:1562-1569)."""
        return []

    def slashable(self) -> set[int]:
        """Validators implicated by variant-level slashing evidence
        (double per-slot FFG votes, surround-the-ack, :1646)."""
        return set()

    def doctor(self, sim, slot: int) -> bool:
        """Forge a variant-level safety conflict (the chaos-fuzz CI
        negative). Returns False when the variant has no forgeable
        surface — the caller falls back to the store-level doctor."""
        return False

    # -- snapshot --------------------------------------------------------------

    def state_blob(self, sim) -> dict:
        return {}

    def restore_blob(self, sim, blob: dict) -> None:
        pass


# --- shared machinery for the expiry-window family ----------------------------


class ExpiryVariantBase(ProtocolVariant):
    """Common core of Goldfish / RLMD-GHOST / SSF: slot-granular vote
    overlays per view, the expiry-windowed equivocation-discounted GHOST
    head through the backend kernels, kappa-deep confirmation, optional
    3/4 fast confirmation."""

    needs_view = True
    eta: int = 4                      # vote expiry (pos-evolution.md:1585)
    kappa: int = 4                    # kappa-deep confirmation (:1556)
    fast_confirm: bool = False
    fast_confirm_threshold: float = 0.75
    subsample_rate: float = 1.0       # voter subsampling (:1545)
    use_vrf: bool = False             # min-VRF proposal preference (:1554)

    def __init__(self):
        self.views: dict[int, VariantVoteLog] = {}
        # per group: newest fast-confirmed / kappa-confirmed (root, slot)
        self.fast_confirmed: dict[int, tuple[bytes, int]] = {}
        self.confirmed: dict[int, tuple[bytes, int]] = {}

    def bind(self, sim) -> None:
        super().bind(sim)
        self._total_stake = int(get_total_active_balance(sim.genesis_state))

    def make_view(self, group_id: int) -> VariantVoteLog:
        log = VariantVoteLog(group_id, buffered=True)
        self.views[group_id] = log
        return log

    def reset_view(self, group) -> None:
        log = self.make_view(group.id)
        group.variant_view = log
        group.store.variant_view = log
        self.fast_confirmed.pop(group.id, None)
        self.confirmed.pop(group.id, None)

    # -- vote arrays -----------------------------------------------------------

    def _vote_arrays(self, store, log: VariantVoteLog, index_of: dict,
                     slot: int):
        """Latest-vote table -> kernel arrays. Weights come from the
        justified checkpoint state's registry like the carrier's LMD
        weights (pos-evolution.md:916); equivocators (variant-level AND
        slasher-evidenced) carry none (:1438); subsampled-out voters
        carry none (:1545)."""
        import numpy as np
        state = fc.justified_checkpoint_state(store)
        reg = state.validators
        n = len(reg)
        current_epoch = compute_epoch_at_slot(slot)
        items = sorted(log.latest.items())
        k = len(items)
        block_idx = np.full(k, -1, np.int64)
        vote_slot = np.zeros(k, np.int64)
        weight = np.zeros(k, np.int64)
        active = np.zeros(k, bool)
        banned = log.equivocators | store.equivocating_indices
        for j, (v, (s, root)) in enumerate(items):
            vote_slot[j] = s
            block_idx[j] = index_of.get(root, -1)
            if v in banned or v >= n:
                continue
            if not (int(reg.activation_epoch[v]) <= current_epoch
                    < int(reg.exit_epoch[v])) or bool(reg.slashed[v]):
                continue
            if self.subsample_rate < 1.0 and not self._vote_eligible(v, s):
                continue
            active[j] = True
            weight[j] = int(reg.effective_balance[v])
        return block_idx, vote_slot, weight, active

    def _vote_eligible(self, v: int, slot: int) -> bool:
        from pos_evolution_tpu.models.pvm import vrf_is_eligible
        return vrf_is_eligible(v, slot, b"vote", self.subsample_rate)

    # -- head ------------------------------------------------------------------

    def _start_root(self, store, group_id: int) -> bytes:
        """Descent anchor: the newest block the variant refuses to roll
        back — fast-confirmed when present (:1568), else the carrier's
        justified checkpoint (history below it is shared state)."""
        fast = self.fast_confirmed.get(group_id)
        if fast is not None and fast[0] in store.blocks:
            return fast[0]
        jroot = bytes(store.justified_checkpoint.root)
        return jroot if jroot in store.blocks else next(iter(store.blocks))

    def head(self, sim, group) -> bytes:
        from pos_evolution_tpu.backend import get_backend
        store = group.store
        log = self.views[group.id]
        slot = fc.get_current_slot(store)
        lo = max(slot - self.eta, 0)
        hi = slot - 1
        roots, index_of, parent, _slots = densify_view(store)
        block_idx, vote_slot, weight, active = self._vote_arrays(
            store, log, index_of, slot)
        backend = get_backend()
        tally = backend.variant_tally(block_idx, vote_slot, weight, active,
                                      lo, hi, len(roots))
        subtree = backend.subtree_weights(parent, tally)
        children: dict[int, list[int]] = {}
        for i, p in enumerate(parent):
            if p >= 0:
                children.setdefault(int(p), []).append(i)
        start = self._start_root(store, group.id)
        head = index_of.get(start, 0)
        while True:
            kids = children.get(head, [])
            if not kids:
                return roots[head]
            head = max(kids, key=lambda i: (int(subtree[i]),
                                            self._tie_key(store, roots[i]),
                                            roots[i]))

    def _tie_key(self, store, root: bytes):
        """Secondary descent key between equal-weight siblings. Goldfish
        prefers the minimal-VRF proposal of the slot (:1554) — encoded
        complemented so ``max`` picks the smallest VRF output."""
        if not self.use_vrf:
            return b""
        from pos_evolution_tpu.models.pvm import vrf_output
        block = store.blocks[root]
        out = vrf_output(int(block.proposer_index), int(block.slot))
        return bytes(255 - b for b in out)

    # -- slot hooks ------------------------------------------------------------

    def on_slot_start(self, sim, slot: int) -> None:
        """Merge phase (the votes of slot-1 just crossed the boundary),
        then the completed round's confirmation processing."""
        for g in sim.groups:
            if g.crashed or g.id not in self.views:
                continue
            log = self.views[g.id]
            log.merge()
            log.prune(slot - self.eta - self.kappa - 8)
        round_slot = slot - 1
        if round_slot >= 1:
            for g in sim.groups:
                if g.crashed or g.id not in self.views:
                    continue
                if self.fast_confirm:
                    self._fast_confirm_round(sim, g, round_slot)
                self._process_round(sim, g, round_slot)

    def _process_round(self, sim, group, round_slot: int) -> None:
        """Variant-specific per-round processing (SSF's FFG gadget)."""

    def _fast_confirm_round(self, sim, group, round_slot: int) -> None:
        """3/4 fast confirmation (pos-evolution.md:1562-1569): a proposal
        of ``round_slot`` voted by more than ``threshold`` of the slot's
        eligible voters fast-confirms and is never rolled back (:1568).
        The per-candidate tally runs through the backend link kernel."""
        import numpy as np
        from pos_evolution_tpu.backend import get_backend
        store = group.store
        log = self.views[group.id]
        votes = log.by_slot.get(round_slot)
        if not votes:
            return
        candidates = [r for r, b in store.blocks.items()
                      if int(b.slot) == round_slot]
        if not candidates:
            return
        cand_idx = {r: i for i, r in enumerate(candidates)}
        voters = sorted(v for v in votes if v not in log.equivocators)
        link_idx = np.array([cand_idx.get(votes[v], -1) for v in voters],
                            np.int64)
        ones = np.ones(len(voters), np.int64)
        counts = get_backend().link_tally(link_idx, ones,
                                          np.ones(len(voters), bool),
                                          len(candidates))
        eligible = self._eligible_count(store, candidates[0], round_slot)
        if not eligible:
            return
        best = int(np.argmax(counts))
        if counts[best] > self.fast_confirm_threshold * eligible:
            root = candidates[best]
            prev = self.fast_confirmed.get(group.id)
            if prev is None or round_slot > prev[1]:
                self.fast_confirmed[group.id] = (root, round_slot)

    def _eligible_count(self, store, candidate_root: bytes,
                        round_slot: int) -> int:
        """The denominator of :1567 — the slot's (subsampled) committee,
        awake or not, derived from the candidate proposal's own state."""
        from pos_evolution_tpu.sim.adversary import slot_committee
        state = store.block_states.get(candidate_root)
        if state is None:
            return 0
        committee = [int(v) for v in slot_committee(state, round_slot)]
        if self.subsample_rate >= 1.0:
            return len(committee)
        return sum(1 for v in committee
                   if self._vote_eligible(v, round_slot))

    def on_slot_end(self, sim, slot: int) -> dict | None:
        record = {"variant": self.name, "slot": slot, "groups": {}}
        for g in sim.groups:
            if g.crashed or g.id not in self.views:
                continue
            store = g.store
            head = self.head(sim, g)
            confirmed = self._kappa_confirmed(store, g.id, head, slot)
            if confirmed is not None:
                prev = self.confirmed.get(g.id)
                if prev is None or confirmed[1] >= prev[1]:
                    self.confirmed[g.id] = confirmed
            fast = self.fast_confirmed.get(g.id)
            conf = self.confirmed.get(g.id)
            record["groups"][str(g.id)] = {
                "head": head.hex()[:16],
                "head_slot": int(store.blocks[head].slot),
                "confirmed_slot": conf[1] if conf else None,
                "fast_confirmed_slot": fast[1] if fast else None,
                "equivocators": len(self.views[g.id].equivocators),
            }
        return record

    def _kappa_confirmed(self, store, group_id: int, head: bytes,
                         slot: int) -> tuple[bytes, int] | None:
        """kappa-deep confirmation (pos-evolution.md:1556): the head's
        ancestor at slot <= slot - kappa; a fast confirmation deeper in
        the chain than it is never rolled back (:1568)."""
        cutoff = slot - self.kappa
        cur = head
        while cur in store.blocks and int(store.blocks[cur].slot) > cutoff:
            nxt = bytes(store.blocks[cur].parent_root)
            if nxt not in store.blocks:
                break
            cur = nxt
        if cur not in store.blocks:
            return None
        fast = self.fast_confirmed.get(group_id)
        if fast is not None and fast[0] in store.blocks \
                and fast[1] > int(store.blocks[cur].slot) \
                and self._descends(store, fast[0], cur):
            return fast
        return (cur, int(store.blocks[cur].slot))

    @staticmethod
    def _descends(store, descendant: bytes, ancestor: bytes) -> bool:
        cur = descendant
        while cur in store.blocks:
            if cur == ancestor:
                return True
            nxt = bytes(store.blocks[cur].parent_root)
            if nxt == cur:
                return False
            cur = nxt
        return False

    def fast_confirmations(self, group_id: int) -> list[tuple[bytes, int]]:
        fast = self.fast_confirmed.get(group_id)
        return [fast] if fast is not None else []

    def doctor(self, sim, slot: int) -> bool:
        """Forge CONFLICTING same-slot fast confirmations into the first
        two views — two >3/4 quorums that never existed, which the
        ``VariantSafetyMonitor`` must flag (its variant evidence set is
        empty, so the verdict must be ``protocol_violation``)."""
        if not self.fast_confirm or len(sim.groups) < 2:
            return False
        self.fast_confirmed[sim.groups[0].id] = (b"\x0d" * 32, slot)
        self.fast_confirmed[sim.groups[1].id] = (b"\x0e" * 32, slot)
        return True

    # -- snapshot --------------------------------------------------------------

    def state_blob(self, sim) -> dict:
        return {
            "views": {str(gid): log.state_blob()
                      for gid, log in sorted(self.views.items())},
            "fast_confirmed": {str(g): [r.hex(), s]
                               for g, (r, s) in
                               sorted(self.fast_confirmed.items())},
            "confirmed": {str(g): [r.hex(), s]
                          for g, (r, s) in sorted(self.confirmed.items())},
        }

    def restore_blob(self, sim, blob: dict) -> None:
        for gid, vb in blob.get("views", {}).items():
            gid = int(gid)
            self.views[gid] = VariantVoteLog.from_blob(gid, vb, buffered=True)
        self.fast_confirmed = {int(g): (bytes.fromhex(r), int(s))
                               for g, (r, s) in
                               blob.get("fast_confirmed", {}).items()}
        self.confirmed = {int(g): (bytes.fromhex(r), int(s))
                          for g, (r, s) in blob.get("confirmed", {}).items()}
        for g in sim.groups:
            if g.id in self.views:
                g.variant_view = self.views[g.id]
                g.store.variant_view = self.views[g.id]


def variant_from_config(cfg: dict | None):
    """Rebuild a variant from its ``describe()`` fingerprint (checkpoint
    resume, chaos repro bundles, the variant matrix)."""
    from pos_evolution_tpu.variants import (
        GasperVariant,
        GoldfishVariant,
        RlmdGhostVariant,
        SsfVariant,
    )
    if cfg is None:
        return GasperVariant()
    kind = cfg["kind"]
    if kind == "GasperVariant":
        return GasperVariant()
    if kind == "GoldfishVariant":
        return GoldfishVariant(
            kappa=cfg.get("kappa", 4),
            fast_confirm=cfg.get("fast_confirm", True),
            fast_confirm_threshold=cfg.get("fast_confirm_threshold", 0.75),
            subsample_rate=cfg.get("subsample_rate", 1.0))
    if kind == "RlmdGhostVariant":
        return RlmdGhostVariant(eta=cfg.get("eta", 4),
                                kappa=cfg.get("kappa", 4))
    if kind == "SsfVariant":
        return SsfVariant(eta=cfg.get("eta", 4),
                          fast_confirm_threshold=cfg.get(
                              "fast_confirm_threshold", 0.75))
    raise ValueError(f"unknown variant kind {kind!r}")
