"""SsfVariant: single-slot finality in the production driver
(pos-evolution.md:1611-1650).

RLMD-GHOST with fast confirmation (the 4Δ slot: propose -> head-vote ->
FFG-vote/fast-confirm -> merge, :1617, :1631-1637) plus a per-slot FFG
gadget over (block, slot) checkpoints:

- every vote round derives one supermajority-link candidate per view:
  source = the view's latest justified checkpoint LJ, target = the
  highest fast-confirmed descendant of LJ (or LJ's block) at the round's
  slot (:1624-1629); the link's stake is tallied through the backend
  link kernel;
- 2/3 of stake on the link justifies the target (:1626); a link across
  consecutive slots finalizes its source (:1626); the voters then
  *acknowledge* the just-justified checkpoint and 2/3 acknowledgments
  finalize it within its own round (:1646) — single-slot finality;
- slashing: an acknowledgment ((C, t), t) conflicts with any FFG vote
  whose span strictly surrounds t (surround-the-ack, :1646), and two
  distinct links with the same target slot are a double vote — the
  variant keeps a cross-view evidence log so the
  ``VariantSafetyMonitor`` can attribute conflicting finality to >= 1/3
  of stake (the accountable-safety theorem at slot granularity).

Fork choice is LJ-filtered (:1628): the GHOST descent starts at the
view's latest justified block (or its newest fast confirmation when that
sits deeper in the chain).
"""

from __future__ import annotations

import numpy as np

from pos_evolution_tpu.variants.base import ExpiryVariantBase


class SsfVariant(ExpiryVariantBase):
    name = "ssf"
    fast_confirm = True

    def __init__(self, eta: int = 4, fast_confirm_threshold: float = 0.75):
        super().__init__()
        self.eta = int(eta)
        self.kappa = max(int(eta), 2)
        self.fast_confirm = True
        self.fast_confirm_threshold = float(fast_confirm_threshold)
        # per-group FFG state: latest justified (root, slot), the
        # justified set, and the finalized chain of checkpoints
        self.lj: dict[int, tuple[bytes, int]] = {}
        self.justified: dict[int, set[tuple[bytes, int]]] = {}
        self.finalized: dict[int, list[tuple[bytes, int]]] = {}
        # cross-view evidence log (the watchtower's view): derived FFG
        # votes and acknowledgments per validator
        self.ffg_log: dict[tuple[int, int], set] = {}   # (v, tslot) -> links
        self.ack_log: dict[int, set[int]] = {}          # v -> ack slots
        self.vote_spans: dict[int, set[tuple[int, int]]] = {}  # v -> (s, t)
        self._slashable: set[int] = set()

    def describe(self) -> dict:
        return {"kind": "SsfVariant", "eta": self.eta,
                "fast_confirm_threshold": self.fast_confirm_threshold}

    # -- fork choice: LJ filtering (:1628) -------------------------------------

    def _genesis_cp(self, store) -> tuple[bytes, int]:
        anchor = next(iter(store.blocks))
        return (anchor, int(store.blocks[anchor].slot))

    def _start_root(self, store, group_id: int) -> bytes:
        lj = self.lj.get(group_id)
        if lj is None or lj[0] not in store.blocks:
            return super()._start_root(store, group_id)
        fast = self.fast_confirmed.get(group_id)
        if fast is not None and fast[0] in store.blocks \
                and fast[1] > lj[1] and self._descends(store, fast[0], lj[0]):
            return fast[0]
        return lj[0]

    def reset_view(self, group) -> None:
        super().reset_view(group)
        self.lj.pop(group.id, None)
        self.justified.pop(group.id, None)
        # finalized history survives a crash (it is the one thing the
        # protocol promises never to revert); the rejoined view re-earns
        # justification from fresh rounds

    # -- per-round FFG gadget --------------------------------------------------

    def _process_round(self, sim, group, round_slot: int) -> None:
        from pos_evolution_tpu.backend import get_backend
        from pos_evolution_tpu.specs import forkchoice as fc
        store = group.store
        log = self.views[group.id]
        votes = log.by_slot.get(round_slot)
        if not votes:
            return
        gid = group.id
        lj = self.lj.get(gid)
        if lj is None:
            lj = self._genesis_cp(store)
            self.lj[gid] = lj
            self.justified.setdefault(gid, set()).add(lj)
            self.finalized.setdefault(gid, [])
        # target selection (:1624-1629)
        fast = self.fast_confirmed.get(gid)
        if fast is not None and fast[0] in store.blocks \
                and self._descends(store, fast[0], lj[0]):
            target_block = fast[0]
        else:
            target_block = lj[0]
        target = (target_block, round_slot)
        link = (lj[0], lj[1], target_block)

        # Only voters whose head vote SUPPORTS the target cast this
        # view's link (their FFG vote in the real protocol carries their
        # own view's target): a round split between two chains must not
        # let both views claim the full committee for conflicting links —
        # without this filter, honest equivocation-free execution could
        # finalize conflicting checkpoints with zero slashable evidence,
        # which the VariantSafetyMonitor (correctly) rejects.
        voters = sorted(
            v for v in votes if v not in log.equivocators
            and self._descends(store, votes[v], target_block))
        for v in voters:
            links = self.ffg_log.setdefault((v, round_slot), set())
            links.add(link)
            if len(links) > 1:
                self._slashable.add(v)           # double FFG vote (:238)
            span = (lj[1], round_slot)
            self.vote_spans.setdefault(v, set()).add(span)
            for ack_slot in self.ack_log.get(v, ()):
                if span[0] < ack_slot < span[1]:
                    self._slashable.add(v)       # surround-the-ack (:1646)

        # Supermajority-link tally through the backend kernel (:1626).
        # The carrier's per-slot committees subsample the validator set
        # (each validator FFG-votes once per epoch), so the 2/3 threshold
        # applies to the ROUND's eligible stake — committee-subsampled
        # SSF; the paper's full-participation protocol is the
        # subsample -> 1 limit, exercised by the models/ssf.py oracle.
        # Accountability still measures against TOTAL stake: committee
        # rotation accumulates cross-view double votes until the
        # implicated set covers the adversary (VariantSafetyMonitor
        # upgrades its verdict when it crosses 1/3).
        from pos_evolution_tpu.sim.adversary import slot_committee
        from pos_evolution_tpu.specs.validator import advance_state_to_slot
        state = fc.justified_checkpoint_state(store)
        reg = state.validators
        n = len(reg)
        cstate = store.block_states.get(target_block, state)
        if int(cstate.slot) < round_slot:
            cstate = advance_state_to_slot(cstate, round_slot)
        committee = [int(v) for v in slot_committee(cstate, round_slot)]
        eligible = sum(int(reg.effective_balance[v]) for v in committee
                       if v < n and not bool(reg.slashed[v]))
        weights = np.array([int(reg.effective_balance[v])
                            if v < n and not bool(reg.slashed[v]) else 0
                            for v in voters], np.int64)
        link_idx = np.zeros(len(voters), np.int64)
        w = int(get_backend().link_tally(
            link_idx, weights, np.ones(len(voters), bool), 1)[0])
        if eligible == 0 or 3 * w < 2 * eligible:
            return
        if lj not in self.justified.setdefault(gid, {lj}):
            return
        # justification
        newly = target not in self.justified[gid]
        self.justified[gid].add(target)
        if target[1] == lj[1] + 1:
            # consecutive-slot link finalizes the source (:1626)
            self._finalize(gid, lj)
        if newly:
            # acknowledgment round (:1646): the same 2/3 voters saw the
            # justification inside the round and acknowledge it —
            # finalizing the target within its own slot
            for v in voters:
                self.ack_log.setdefault(v, set()).add(round_slot)
                for span in self.vote_spans.get(v, ()):
                    if span[0] < round_slot < span[1]:
                        self._slashable.add(v)
            self._finalize(gid, target)
            if target[1] > self.lj[gid][1]:
                self.lj[gid] = target

    def _finalize(self, gid: int, checkpoint: tuple[bytes, int]) -> None:
        chain = self.finalized.setdefault(gid, [])
        if checkpoint not in chain:
            chain.append(checkpoint)

    # -- audit surface ---------------------------------------------------------

    def finalized_checkpoints(self, group_id: int) -> list[tuple[bytes, int]]:
        return list(self.finalized.get(group_id, []))

    def slashable(self) -> set[int]:
        return set(self._slashable)

    def doctor(self, sim, slot: int) -> bool:
        """Forge CONFLICTING finalized checkpoints into the first two
        views with no double votes behind them: the variant safety
        monitor must flag a protocol_violation — the per-variant CI
        negative. Cross-slot on purpose: a cross-slot conflict is judged
        against TOTAL stake (disjoint committees), so real sub-1/3
        chaos evidence can never launder the forgery into an
        accountable_fault."""
        if len(sim.groups) < 2:
            return False
        self._finalize(sim.groups[0].id, (b"\x0d" * 32, slot))
        self._finalize(sim.groups[1].id, (b"\x0e" * 32, slot + 1))
        return True

    # -- telemetry -------------------------------------------------------------

    def on_slot_end(self, sim, slot: int) -> dict | None:
        record = super().on_slot_end(sim, slot)
        if record is None:
            return None
        for g in sim.groups:
            row = record["groups"].get(str(g.id))
            if row is None:
                continue
            lj = self.lj.get(g.id)
            fin = self.finalized.get(g.id, [])
            row["justified_slot"] = lj[1] if lj else None
            row["finalized_slot"] = max((s for _, s in fin), default=None)
            row["n_finalized"] = len(fin)
        record["slashable_evidence"] = len(self._slashable)
        return record

    # -- snapshot --------------------------------------------------------------

    def state_blob(self, sim) -> dict:
        blob = super().state_blob(sim)
        blob.update({
            "lj": {str(g): [r.hex(), s]
                   for g, (r, s) in sorted(self.lj.items())},
            "justified": {str(g): sorted([r.hex(), s] for r, s in cps)
                          for g, cps in sorted(self.justified.items())},
            "finalized": {str(g): [[r.hex(), s] for r, s in chain]
                          for g, chain in sorted(self.finalized.items())},
            "ffg_log": [[v, t, sorted([sr.hex(), ss, tr.hex()]
                                      for sr, ss, tr in links)]
                        for (v, t), links in sorted(self.ffg_log.items())],
            "ack_log": {str(v): sorted(s)
                        for v, s in sorted(self.ack_log.items())},
            "vote_spans": {str(v): sorted(map(list, s))
                           for v, s in sorted(self.vote_spans.items())},
            "slashable": sorted(self._slashable),
        })
        return blob

    def restore_blob(self, sim, blob: dict) -> None:
        super().restore_blob(sim, blob)
        self.lj = {int(g): (bytes.fromhex(r), int(s))
                   for g, (r, s) in blob.get("lj", {}).items()}
        self.justified = {
            int(g): {(bytes.fromhex(r), int(s)) for r, s in cps}
            for g, cps in blob.get("justified", {}).items()}
        self.finalized = {
            int(g): [(bytes.fromhex(r), int(s)) for r, s in chain]
            for g, chain in blob.get("finalized", {}).items()}
        self.ffg_log = {
            (int(v), int(t)): {(bytes.fromhex(sr), int(ss),
                               bytes.fromhex(tr)) for sr, ss, tr in links}
            for v, t, links in blob.get("ffg_log", [])}
        self.ack_log = {int(v): set(s)
                        for v, s in blob.get("ack_log", {}).items()}
        self.vote_spans = {int(v): {tuple(x) for x in spans}
                           for v, spans in blob.get("vote_spans", {}).items()}
        self._slashable = set(blob.get("slashable", []))
