"""RlmdGhostVariant: RLMD-GHOST in the production driver
(pos-evolution.md:1581-1609).

Eta-expiry LMD: latest head votes from the last ``eta`` slots weigh the
GHOST descent (:1585; ``eta = 1`` recovers Goldfish, ``eta = inf`` LMD),
with the view-merge buffer discipline (:1528-1541) — votes delivered
mid-slot sit in the pending buffer until the next merge boundary, so
just-before-the-deadline delivery (:1328) cannot split the voters. The
protocol tolerates asynchronous periods shorter than ``eta - 1`` slots
(:1600); kappa-deep confirmation gives the output ledger."""

from __future__ import annotations

from pos_evolution_tpu.variants.base import ExpiryVariantBase


class RlmdGhostVariant(ExpiryVariantBase):
    name = "rlmd"

    def __init__(self, eta: int = 4, kappa: int = 4):
        super().__init__()
        self.eta = int(eta)
        self.kappa = int(kappa)
        self.fast_confirm = False

    def describe(self) -> dict:
        return {"kind": "RlmdGhostVariant", "eta": self.eta,
                "kappa": self.kappa}
