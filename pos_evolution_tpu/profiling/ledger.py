"""Compile-provenance ledger: who compiled, and during which phase.

``telemetry/jaxrt.py`` counts backend compiles
(``jax_backend_compiles_total``) but cannot say *which function* or
*which part of the slot* triggered them — jax 0.4.37's
``jax.monitoring`` duration listeners receive no kwargs (no
``fun_name``), so all provenance must come from our own side of the
fence. This module is that side: a thread-local **span context** that
the rest of the repo pushes into —

- ``profiling/phases.py`` sets the *phase* slot on every
  ``with pt.phase(name)`` enter/exit (two attribute writes — the
  steady-state slot loop pays nothing measurable);
- ``parallel/sharded.py`` wraps each memoized kernel in a
  ``function_scope`` carrying the kernel-cache name (``"epoch"``,
  ``"votes"``, ...);
- ``profiling/attribution.py``'s ``ProfiledRegion`` sets the *region*
  slot so ad-hoc profiled blocks name their compiles too.

``CompileLedger.on_duration`` (invoked by the jaxrt listener when a
ledger is attached) reads the context at compile time and charges the
event to a ``(stage, function, phase)`` row. When no explicit function
scope is active but a phase is, the row is named ``inline:<phase>`` —
the single-device dense driver compiles everything inline inside phase
blocks, so those rows are still *named* attribution (the acceptance
bar: >= 95% of ``jax_backend_compiles_total`` lands on a named row).
Rows also flow into the registry as
``jax_compiles_by_provenance_total{stage,function,phase}`` so they ride
snapshots, Prometheus export, and ``perf_gate`` for free.

Everything here is stdlib-only and never raises into the caller: the
ledger is observability, and observability must never be the reason a
run dies.
"""

from __future__ import annotations

import threading

__all__ = [
    "CompileLedger",
    "current_function",
    "current_phase",
    "current_region",
    "function_scope",
    "pop_phase",
    "push_phase",
]

_TLS = threading.local()

#: duration-event suffix -> short stage label used in ledger rows.
_STAGES = {
    "backend_compile_duration": "backend_compile",
    "jaxpr_trace_duration": "trace",
    "jaxpr_to_mlir_module_duration": "lower",
}


def _ctx():
    ctx = getattr(_TLS, "ctx", None)
    if ctx is None:
        ctx = _TLS.ctx = {"function": None, "phase": None, "region": None}
    return ctx


def push_phase(name: str):
    """Set the active phase; returns the previous value for ``pop_phase``."""
    ctx = _ctx()
    prev = ctx["phase"]
    ctx["phase"] = name
    return prev


def pop_phase(prev) -> None:
    _ctx()["phase"] = prev


def push_region(name: str):
    ctx = _ctx()
    prev = ctx["region"]
    ctx["region"] = name
    return prev


def pop_region(prev) -> None:
    _ctx()["region"] = prev


def current_phase() -> str | None:
    return _ctx()["phase"]


def current_function() -> str | None:
    return _ctx()["function"]


def current_region() -> str | None:
    return _ctx()["region"]


def current() -> dict:
    """Copy of the active span context (function/phase/region)."""
    return dict(_ctx())


class function_scope:
    """Cheap ``with`` scope naming the function about to dispatch.

    Nested scopes restore the outer name on exit; exceptions propagate
    (the scope itself never raises). Used by the sharded kernel-cache
    wrapper, hence the ``__slots__`` + no-allocation design: it sits on
    every kernel call.
    """

    __slots__ = ("name", "_prev")

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        ctx = _ctx()
        self._prev = ctx["function"]
        ctx["function"] = self.name
        return self

    def __exit__(self, *exc):
        _ctx()["function"] = self._prev
        return False


def provenance(stage: str) -> tuple[str, str, str]:
    """Resolve the (stage, function, phase) row for a compile event now.

    Precedence for the function name: explicit ``function_scope`` >
    ``inline:<phase>`` when only a phase is active > the active
    ``ProfiledRegion`` name > ``"?"``.
    """
    ctx = _ctx()
    phase = ctx["phase"] or "?"
    fn = ctx["function"]
    if fn is None:
        if ctx["phase"] is not None:
            fn = f"inline:{ctx['phase']}"
        elif ctx["region"] is not None:
            fn = ctx["region"]
        else:
            fn = "?"
    return _STAGES.get(stage, stage), fn, phase


class CompileLedger:
    """Per-(stage, function, phase) decomposition of jax compile events.

    Attach via ``telemetry.jaxrt.attach_ledger(ledger)``; the jaxrt
    duration listener then calls :meth:`on_duration` for every compile/
    trace/lower event, and this ledger charges it to the span context
    active on the calling thread. Thread-safe; bounded by the number of
    distinct (stage, function, phase) triples, which is bounded by the
    kernel + phase taxonomies.
    """

    def __init__(self, registry=None):
        self.registry = registry
        self._lock = threading.Lock()
        # (stage, function, phase) -> [count, seconds]
        self._rows: dict[tuple[str, str, str], list] = {}

    def on_duration(self, event: str, duration: float) -> None:
        stage, fn, phase = provenance(event.rsplit("/", 1)[-1])
        with self._lock:
            row = self._rows.setdefault((stage, fn, phase), [0, 0.0])
            row[0] += 1
            row[1] += float(duration)
        reg = self.registry
        if reg is not None:
            try:
                reg.counter(
                    "jax_compiles_by_provenance_total",
                    "compile events by (stage, function, phase)",
                ).inc(1, stage=stage, function=fn, phase=phase)
            except Exception:
                pass  # pev: ignore[PEV005] — ledger must never kill a run

    def rows(self) -> list[dict]:
        """Ledger rows, heaviest backend-compile time first."""
        with self._lock:
            items = [
                {"stage": k[0], "function": k[1], "phase": k[2],
                 "count": v[0], "seconds": round(v[1], 6)}
                for k, v in self._rows.items()
            ]
        items.sort(key=lambda r: (-r["seconds"], r["stage"], r["function"]))
        return items

    def attribution(self, total: int | None = None) -> dict:
        """How much of ``jax_backend_compiles_total`` has a named row.

        A row is *named* when its phase is known (the phase taxonomy is
        the attribution target; ``inline:<phase>`` functions count).
        ``total`` defaults to every backend_compile event the ledger
        saw — pass the registry's ``jax_backend_compiles_total`` to
        measure against the full listener count instead.
        """
        with self._lock:
            backend = [(k, v[0]) for k, v in self._rows.items()
                       if k[0] == "backend_compile"]
        seen = sum(n for _, n in backend)
        named = sum(n for (_, fn, phase), n in backend
                    if phase != "?" or fn != "?")
        denom = int(total) if total is not None else seen
        pct = round(100.0 * named / denom, 2) if denom else None
        return {"backend_compiles": denom, "seen": seen, "named": named,
                "named_pct": pct}

    def summary(self) -> dict:
        return {"rows": self.rows(), "attribution": self.attribution()}
