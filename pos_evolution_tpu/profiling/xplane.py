"""xplane protobuf parsing — the importable heart of what used to live in
``scripts/trace_summary.py`` (now a deprecation stub; the CLI path is
``scripts/run_report.py --xplane TRACE``).

No ``xplane_pb2`` bindings ship in this image, so this walks the protobuf
wire format directly with the field numbers from
tsl/profiler/protobuf/xplane.proto (stable public schema):

    XSpace.planes = 1
    XPlane.name = 2, XPlane.lines = 3, XPlane.event_metadata = 4 (map)
    XLine.name = 2, XLine.timestamp_ns = 3, XLine.events = 4
    XEvent.metadata_id = 1, XEvent.offset_ps = 2, XEvent.duration_ps = 3
    XEventMetadata.id = 1, XEventMetadata.name = 2

Two views of the same bytes:

- ``parse_xspace``: the full structural view — planes with lines, each
  line with timestamped events — used by the Chrome-trace exporter
  (``profiling/export.py``) and the span-attribution pass
  (``profiling/attribution.py``);
- ``summarize_xplane`` / ``top_table`` / ``summarize_path``: the legacy
  aggregate per-op view (total_ps, count) consumed by ``bench.py
  --trace`` and folded into ``run_report.py``.

``encode_xspace`` writes the same wire format back out; parse∘encode is
the identity on the structural view, which is what lets tests pin the
parser against a small checked-in ``*.xplane.pb`` fixture instead of a
live profiler run (profiler output is nondeterministic; the wire walk
is not).
"""

from __future__ import annotations

import glob
import os


def _varint(buf, i):
    out = shift = 0
    n = len(buf)
    while True:
        if i >= n:
            # a partially written file (killed writer, full disk) must be
            # a loud ValueError, not an IndexError that callers' contracts
            # don't cover
            raise ValueError("truncated xplane message: varint past end")
        b = buf[i]
        out |= (b & 0x7F) << shift
        i += 1
        if not b & 0x80:
            return out, i
        shift += 7


def _fields(buf):
    """Yield (field_number, wire_type, value) over a message buffer.
    Raises ``ValueError`` on truncated/corrupt bytes."""
    i, n = 0, len(buf)
    while i < n:
        key, i = _varint(buf, i)
        fnum, wtype = key >> 3, key & 7
        if wtype == 0:
            val, i = _varint(buf, i)
        elif wtype == 1:
            val, i = buf[i:i + 8], i + 8
        elif wtype == 2:
            ln, i = _varint(buf, i)
            val, i = buf[i:i + ln], i + ln
        elif wtype == 5:
            val, i = buf[i:i + 4], i + 4
        else:
            raise ValueError(f"unsupported wire type {wtype}")
        if wtype != 0 and i > n:
            raise ValueError("truncated xplane message: field past end")
        yield fnum, wtype, val


def is_device_plane(name: str) -> bool:
    """Does a plane name smell like a device timeline (vs host python)?
    THE one definition — the top-table ranking and every attribution view
    must agree on what counts as device time."""
    n = name.lower()
    return "device" in n or "tpu" in n or "gpu" in n or "xla" in n


def select_planes(planes, device_only: bool = True):
    """Device planes when any exist, else every plane: a CPU-only run has
    no device plane, and its host timeline IS the device timeline."""
    if device_only:
        chosen = [p for p in planes if is_device_plane(p["name"])]
        if chosen:
            return chosen
    return list(planes)


def _parse_plane(plane_buf) -> dict:
    name, metadata, lines = "", {}, []
    for pf, _, pv in _fields(plane_buf):
        if pf == 2:
            name = pv.decode("utf-8", "replace")
        elif pf == 3:
            lines.append(pv)
        elif pf == 4:  # map<int64, XEventMetadata> entry
            mid, mname = 0, ""
            for mf, _, mv in _fields(pv):
                if mf == 1:
                    mid = mv
                elif mf == 2:  # XEventMetadata
                    for ef, _, ev in _fields(mv):
                        if ef == 1:
                            mid = ev
                        elif ef == 2:
                            mname = ev.decode("utf-8", "replace")
            metadata[mid] = mname
    parsed_lines = []
    for line_buf in lines:
        lname, ts_ns, events = "", 0, []
        for lf, _, lv in _fields(line_buf):
            if lf == 2:
                lname = lv.decode("utf-8", "replace")
            elif lf == 3:
                ts_ns = lv
            elif lf == 4:
                mid = off = dur = 0
                for ef, _, ev in _fields(lv):
                    if ef == 1:
                        mid = ev
                    elif ef == 2:
                        off = ev
                    elif ef == 3:
                        dur = ev
                events.append({"metadata_id": mid, "offset_ps": off,
                               "duration_ps": dur})
        parsed_lines.append({"name": lname, "timestamp_ns": ts_ns,
                             "events": events})
    return {"name": name, "event_metadata": metadata, "lines": parsed_lines}


def parse_xspace(data: bytes) -> list[dict]:
    """Full structural parse: list of planes, each
    ``{name, event_metadata: {id: op_name}, lines: [{name, timestamp_ns,
    events: [{metadata_id, offset_ps, duration_ps}]}]}``."""
    return [_parse_plane(v) for f, _, v in _fields(data) if f == 1]


def iter_ops(planes):
    """Yield ``(plane_name, line_name, op_name, offset_ps, duration_ps)``
    over a ``parse_xspace`` result — the flat event stream the exporters
    consume."""
    for p in planes:
        meta = p["event_metadata"]
        for line in p["lines"]:
            for ev in line["events"]:
                yield (p["name"], line["name"],
                       meta.get(ev["metadata_id"], f"#{ev['metadata_id']}"),
                       ev["offset_ps"], ev["duration_ps"])


def summarize_planes(planes) -> list[dict]:
    """Structural view -> legacy aggregate view:
    ``[{name, ops: {op_name: [total_ps, count]}}]`` (planes with no
    events are dropped, matching the historic behavior)."""
    out = []
    for p in planes:
        ops: dict[str, list] = {}
        meta = p["event_metadata"]
        for line in p["lines"]:
            for ev in line["events"]:
                key = meta.get(ev["metadata_id"], f"#{ev['metadata_id']}")
                tot = ops.get(key)
                if tot is None:
                    ops[key] = [ev["duration_ps"], 1]
                else:
                    tot[0] += ev["duration_ps"]
                    tot[1] += 1
        if ops:
            out.append({"name": p["name"], "ops": ops})
    return out


def summarize_xplane(data: bytes) -> list[dict]:
    """-> list of planes: {name, ops: {op_name: [total_ps, count]}}."""
    return summarize_planes(parse_xspace(data))


def top_table(planes, top_n: int = 10) -> dict:
    """-> dict plane name -> top-N [{op, total_ms, count}] (device-ish
    planes sorted first)."""
    def rank(p):
        return (0 if is_device_plane(p["name"]) else 1, p["name"])

    out = {}
    for p in sorted(planes, key=rank):
        rows = sorted(p["ops"].items(), key=lambda kv: -kv[1][0])[:top_n]
        out[p["name"]] = [
            {"op": k, "total_ms": round(v[0] / 1e9, 3), "count": v[1]}
            for k, v in rows if v[0] > 0]
    return {k: v for k, v in out.items() if v}


def xplane_files(path) -> list[str]:
    """The ``*.xplane.pb`` files a trace dir (or a single file) holds."""
    path = os.fspath(path)
    return ([path] if os.path.isfile(path) else
            sorted(glob.glob(os.path.join(path, "**", "*.xplane.pb"),
                             recursive=True)))


def parse_path(path) -> list[dict]:
    """``parse_xspace`` over every xplane file under ``path``."""
    files = xplane_files(path)
    if not files:
        raise FileNotFoundError(f"no .xplane.pb under {path}")
    planes = []
    for f in files:
        with open(f, "rb") as fh:
            planes.extend(parse_xspace(fh.read()))
    return planes


def summarize_path(path, top_n: int = 10) -> dict:
    """Aggregate view over a trace dir — one composition of the
    structural helpers, so file discovery/error semantics live only in
    ``parse_path``."""
    return top_table(summarize_planes(parse_path(path)), top_n)


# -- wire-format writer (fixtures / tests) -------------------------------------

def _enc_varint(x: int) -> bytes:
    out = bytearray()
    while True:
        b = x & 0x7F
        x >>= 7
        out.append(b | (0x80 if x else 0))
        if not x:
            return bytes(out)


def _enc_tag(fnum: int, wtype: int) -> bytes:
    return _enc_varint((fnum << 3) | wtype)


def _enc_bytes(fnum: int, data: bytes) -> bytes:
    return _enc_tag(fnum, 2) + _enc_varint(len(data)) + data


def _enc_int(fnum: int, x: int) -> bytes:
    return _enc_tag(fnum, 0) + _enc_varint(x)


def encode_xspace(planes: list[dict]) -> bytes:
    """Encode the ``parse_xspace`` structural view back to xplane wire
    bytes (fixture generator: ``parse_xspace(encode_xspace(p)) == p`` up
    to empty-string/zero-value defaults)."""
    space = bytearray()
    for p in planes:
        plane = bytearray()
        plane += _enc_bytes(2, p["name"].encode())
        for line in p.get("lines", ()):
            lbuf = bytearray()
            if line.get("name"):
                lbuf += _enc_bytes(2, line["name"].encode())
            if line.get("timestamp_ns"):
                lbuf += _enc_int(3, line["timestamp_ns"])
            for ev in line.get("events", ()):
                ebuf = (_enc_int(1, ev["metadata_id"])
                        + (_enc_int(2, ev["offset_ps"])
                           if ev.get("offset_ps") else b"")
                        + (_enc_int(3, ev["duration_ps"])
                           if ev.get("duration_ps") else b""))
                lbuf += _enc_bytes(4, bytes(ebuf))
            plane += _enc_bytes(3, bytes(lbuf))
        for mid, mname in sorted(p.get("event_metadata", {}).items()):
            meta = _enc_int(1, mid) + _enc_bytes(2, mname.encode())
            entry = _enc_int(1, mid) + _enc_bytes(2, bytes(meta))
            plane += _enc_bytes(4, bytes(entry))
        space += _enc_bytes(1, bytes(plane))
    return bytes(space)
