"""Dense-driver phase profiler (ISSUE 18 leg c).

``DenseSimulation.run_slot`` is one host loop dispatching a handful of
device programs (vote kernel, head descent, epoch sweep) around genuine
host work (committee masks, monitors, the audit walk, checkpoint
gathers). ROADMAP item 5 (<1s mainnet epoch) names its levers by phase
— so the first requirement is a per-slot phase budget that accounts for
(almost) all of the slot wall, cheap enough to leave on.

The two-rate design:

- **every slot** is phase-timed with bare ``perf_counter`` pairs — two
  clock reads and a dict add per phase, well under the <2% steady-state
  overhead budget. But JAX dispatch is async: an unfenced phase that
  launches device work charges only its dispatch cost, and the device
  time surfaces in whichever later phase first blocks. Honest *between
  phases*, misleading *within* one; so
- **sampled slots** (every ``sample_every``-th) additionally fence each
  phase with ``jax.block_until_ready`` on the arrays the phase
  produced, so the sampled breakdown charges device time to the phase
  that dispatched it. Fencing serializes the pipeline — that cost is
  confined to sampled slots by construction, which is what keeps the
  steady-state overhead small while the sampled budget stays honest.

``NULL_TIMER`` is the disabled twin: same interface, empty bodies — the
driver always threads a timer so the instrumented path has no branches,
and the uninstrumented twin run (the overhead pin in CI) differs only
by which timer it got.
"""

from __future__ import annotations

import threading
import time

from pos_evolution_tpu.profiling import ledger

__all__ = ["PhaseTimer", "NULL_TIMER", "DENSE_PHASES"]

# The slot taxonomy (DESIGN.md "Fleet observability"): every section of
# ``run_slot`` belongs to exactly one of these, so the budget is a
# partition of the slot wall and ``unaccounted`` measures instrumentation
# drift, not workload.
DENSE_PHASES = (
    "epoch_sweep",        # _epoch_boundary: process_epoch over the views
    "shuffle",            # _start_epoch: committee shuffle + assignment
    "vote_pass",          # _head: the masked vote-weights kernel
    "head_descent",       # _head: head_from_buckets descent
    "vote_apply",         # _deliver_batch/_apply_batch vote landing
    "variant_tally",      # dense variant plane: expiry window / link /
                          # acknowledgment tallies + per-slot gadgets
    "workload",           # DAS sidecar build/sampling + light clients
    "aggregate_verify",   # _verify_slot committee aggregates
    "monitors",           # dense monitor sweep over the tallies
    "host_audit",         # head_host_walk parity check
    "checkpoint_capture",    # supervision tick: device->host gather
    "checkpoint_serialize",  # supervision tick: npz on writer thread
    "record",             # metrics/telemetry bookkeeping
)


class _Phase:
    """One timed section; re-entered phases accumulate."""

    __slots__ = ("timer", "name", "_prev_phase")

    def __init__(self, timer: "PhaseTimer", name: str):
        self.timer = timer
        self.name = name

    def __enter__(self) -> "_Phase":
        # publish the phase to the compile-provenance span context
        # (profiling/ledger.py) so jax compiles, transfers, and
        # donations occurring inside this block name their phase —
        # two attribute writes, nothing measurable at steady state
        self._prev_phase = ledger.push_phase(self.name)
        self.timer._stack.append((self.name, time.perf_counter()))
        return self

    def __exit__(self, *exc) -> None:
        name, t0 = self.timer._stack.pop()
        self.timer._charge(name, time.perf_counter() - t0)
        ledger.pop_phase(self._prev_phase)


class _NullPhase:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return None


_NULL_PHASE = _NullPhase()


class PhaseTimer:
    """Accumulating per-phase wall timer with sampled device fencing.

    >>> pt = PhaseTimer(sample_every=16, registry=reg, bus=bus)
    >>> pt.begin_slot(s)
    >>> with pt.phase("vote_pass"):
    ...     out = kernel(...)
    ...     pt.fence(out)          # block_until_ready at sampled slots
    >>> pt.end_slot(s)
    >>> pt.summary()["accounted_pct"]
    """

    enabled = True

    def __init__(self, sample_every: int = 16, registry=None, bus=None):
        self.sample_every = max(int(sample_every), 1)
        self.registry = registry
        self.bus = bus
        self.sampled = False
        self._stack: list[tuple[str, float]] = []
        self._slot_t0 = 0.0
        self._slot_acc: dict[str, float] = {}
        # all-slots / sampled-slots accumulators: {phase: [seconds, n]}
        self.totals: dict[str, list] = {}
        self.sampled_totals: dict[str, list] = {}
        self.slots = 0
        self.sampled_slots = 0
        self.wall_s = 0.0
        self.sampled_wall_s = 0.0
        # off-loop work (the supervision writer thread's checkpoint
        # serialization) overlaps the slot wall, so it is charged here —
        # NOT into the slot partition, or accounted_pct could top 100
        self._async_lock = threading.Lock()
        self.async_totals: dict[str, list] = {}
        self._hist = (registry.histogram(
            "dense_phase_ms",
            "per-phase slot time at sampled (fenced) slots, ms")
            if registry is not None else None)

    # -- slot lifecycle --------------------------------------------------------

    def begin_slot(self, slot: int) -> None:
        self.sampled = (slot % self.sample_every) == 0
        self._slot_acc = {}
        self._slot_t0 = time.perf_counter()

    def phase(self, name: str) -> _Phase:
        return _Phase(self, name)

    def _charge(self, name: str, dt: float) -> None:
        self._slot_acc[name] = self._slot_acc.get(name, 0.0) + dt

    def charge_async(self, name: str, dt: float) -> None:
        """Charge work that ran OFF the slot loop (another thread) —
        thread-safe, kept out of the slot-wall partition."""
        with self._async_lock:
            row = self.async_totals.setdefault(name, [0.0, 0])
            row[0] += dt
            row[1] += 1

    def fence(self, *arrays) -> None:
        """Synchronize the open phase with the device work it
        dispatched — sampled slots only, so the steady state never
        serializes the pipeline. Accepts anything
        ``jax.block_until_ready`` does (pytrees included); no-jax
        environments and host-only arrays fall through silently."""
        if not self.sampled:
            return
        try:
            import jax
            jax.block_until_ready([a for a in arrays if a is not None])
        except Exception:
            pass  # pev: ignore[PEV005] — fencing is best-effort
            # instrumentation; a host-only run must not die for it

    def end_slot(self, slot: int) -> None:
        wall = time.perf_counter() - self._slot_t0
        self.slots += 1
        self.wall_s += wall
        for name, dt in self._slot_acc.items():
            row = self.totals.setdefault(name, [0.0, 0])
            row[0] += dt
            row[1] += 1
        if not self.sampled:
            return
        self.sampled_slots += 1
        self.sampled_wall_s += wall
        for name, dt in self._slot_acc.items():
            row = self.sampled_totals.setdefault(name, [0.0, 0])
            row[0] += dt
            row[1] += 1
            if self._hist is not None:
                self._hist.observe(dt, phase=name)
        if self.bus is not None:
            try:
                self.bus.emit(
                    "dense_phase", slot=slot,
                    wall_ms=round(wall * 1e3, 4),
                    phases={n: round(dt * 1e3, 4)
                            for n, dt in sorted(self._slot_acc.items())},
                    accounted_pct=round(
                        100.0 * sum(self._slot_acc.values()) / wall, 2)
                    if wall > 0 else None)
            except Exception:
                pass  # a closed bus must not kill the slot it observed

    # -- results ---------------------------------------------------------------

    def summary(self) -> dict:
        def table(acc: dict, wall: float) -> dict:
            return {
                name: {"total_ms": round(sec * 1e3, 3), "count": n,
                       "share_pct": (round(100.0 * sec / wall, 2)
                                     if wall > 0 else None)}
                for name, (sec, n) in sorted(acc.items())
            }

        accounted = sum(sec for sec, _ in self.sampled_totals.values())
        with self._async_lock:
            async_phases = {
                name: {"total_ms": round(sec * 1e3, 3), "count": n}
                for name, (sec, n) in sorted(self.async_totals.items())}
        return {
            "sample_every": self.sample_every,
            "slots": self.slots,
            "sampled_slots": self.sampled_slots,
            "wall_ms": round(self.wall_s * 1e3, 3),
            "sampled_wall_ms": round(self.sampled_wall_s * 1e3, 3),
            "phases": table(self.totals, self.wall_s),
            "sampled_phases": table(self.sampled_totals,
                                    self.sampled_wall_s),
            "accounted_pct": (round(
                100.0 * accounted / self.sampled_wall_s, 2)
                if self.sampled_wall_s > 0 else None),
            "async_phases": async_phases,
        }


class _NullTimer:
    """The disabled twin: identical surface, empty bodies. Class-level
    ``enabled`` lets call sites skip building fence arguments."""

    enabled = False
    sampled = False

    def begin_slot(self, slot: int) -> None:
        pass

    def phase(self, name: str) -> _NullPhase:
        return _NULL_PHASE

    def fence(self, *arrays) -> None:
        pass

    def charge_async(self, name: str, dt: float) -> None:
        pass

    def end_slot(self, slot: int) -> None:
        pass

    def summary(self) -> dict | None:
        return None


NULL_TIMER = _NullTimer()
