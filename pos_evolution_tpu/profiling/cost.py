"""Static cost & memory analysis of the jitted hot paths.

XLA knows, at compile time, how many FLOPs and HBM bytes every kernel
will touch — ``lowered.compile().cost_analysis()`` and
``memory_analysis()`` expose it. This module walks the repo's hot-path
kernels (attestation aggregation, fork-choice rescan + incremental head,
dense epoch sweep, sync-committee merkle walk, swap-or-not shuffle, the
batched SHA-256 merkle level sweep) at a configurable validator count
and emits one per-kernel table:

    {"kernel": {"flops", "bytes_accessed", "transcendentals",
                "argument_bytes", "output_bytes", "temp_bytes",
                "generated_code_bytes", "peak_bytes"}}

``peak_bytes`` approximates peak device memory as arguments + outputs +
temps (XLA's own accounting; aliasing is subtracted when reported). The
table is the static complement to the xplane timeline: the timeline says
where time *went*, this says where the FLOPs/bytes *must* go — the
per-kernel breakdown hardware papers justify designs with, produced on
CPU or TPU backends alike (the analysis runs wherever the kernel
compiles; per-backend numbers differ and the emission records which).

A kernel that fails to build/compile records ``{"error": ...}`` instead
of killing the sweep — a cost table with one hole beats no table.

CLI: ``python -m pos_evolution_tpu.profiling.cost [--json out.json]
[--n 4096] [--capacity 64]``; ``scripts/run_report.py --cost out.json``
folds the emission into a run report.
"""

from __future__ import annotations

import argparse
import json
import sys


def _cost_dict(compiled) -> dict:
    """Normalize ``cost_analysis()`` across jax versions (list-of-dict
    vs dict) into plain floats we care about."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    ca = ca or {}
    out = {}
    for key, name in (("flops", "flops"),
                      ("bytes accessed", "bytes_accessed"),
                      ("transcendentals", "transcendentals"),
                      ("optimal_seconds", "optimal_seconds")):
        v = ca.get(key)
        if isinstance(v, (int, float)) and v == v:  # drop NaN
            out[name] = float(v)
    return out


def _memory_dict(compiled) -> dict:
    """Normalize ``memory_analysis()`` (absent on some backends)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return {}
    if ma is None:
        return {}
    out = {}
    for attr, name in (("argument_size_in_bytes", "argument_bytes"),
                       ("output_size_in_bytes", "output_bytes"),
                       ("temp_size_in_bytes", "temp_bytes"),
                       ("alias_size_in_bytes", "alias_bytes"),
                       ("generated_code_size_in_bytes",
                        "generated_code_bytes")):
        v = getattr(ma, attr, None)
        if isinstance(v, (int, float)):
            out[name] = int(v)
    if {"argument_bytes", "output_bytes", "temp_bytes"} <= out.keys():
        out["peak_bytes"] = (out["argument_bytes"] + out["output_bytes"]
                             + out["temp_bytes"] - out.get("alias_bytes", 0))
    return out


def analyze_fn(fn, *args, **kwargs) -> dict:
    """Lower + compile one jitted callable and return its cost/memory
    row. ``fn`` may already be jitted (``.lower`` is used as-is) or a
    plain callable (wrapped in ``jax.jit`` first)."""
    import jax
    if not hasattr(fn, "lower"):
        fn = jax.jit(fn)
    compiled = fn.lower(*args, **kwargs).compile()
    row = _cost_dict(compiled)
    row.update(_memory_dict(compiled))
    return row


def hot_path_specs(n: int = 4096, capacity: int = 64) -> dict:
    """name -> zero-arg builder returning ``(fn, args, kwargs)`` for each
    hot-path kernel at validator count ``n``, fork-choice capacity
    ``capacity``. Builders are lazy so one import failure doesn't sink
    the others."""
    import numpy as np

    import jax.numpy as jnp

    from pos_evolution_tpu.backend.jax_init import ensure_x64
    ensure_x64()  # the int64 specs below need 64-bit avals regardless of
    # which op modules a --kernel subset happens to import

    rng = np.random.default_rng(0)
    gwei = 10**9

    def _aggregation():
        from pos_evolution_tpu.ops.aggregation import aggregate_verify_batch
        a_total = max(n // 512, 4)
        lanes = max(n // a_total, 1)
        pk_states = jnp.asarray(
            rng.integers(0, 2**32, (n, 8), dtype=np.uint64).astype(np.uint32))
        committees = jnp.asarray(
            rng.permutation(n)[:a_total * lanes]
            .reshape(a_total, lanes).astype(np.int32))
        bits = jnp.asarray(rng.random((a_total, lanes)) < 0.99)
        msgs = jnp.asarray(
            rng.integers(0, 2**32, (a_total, 8), dtype=np.uint64)
            .astype(np.uint32))
        sigs = jnp.asarray(
            rng.integers(0, 2**32, (a_total, 24), dtype=np.uint64)
            .astype(np.uint32))
        return aggregate_verify_batch, (pk_states, committees, bits, msgs,
                                        sigs), {}

    def _dense_store():
        from pos_evolution_tpu.ops.forkchoice import DenseStore
        parent = np.arange(-1, capacity - 1, dtype=np.int32)
        return DenseStore(
            parent=jnp.asarray(parent),
            slot=jnp.arange(capacity, dtype=jnp.int32),
            rank=jnp.asarray(rng.permutation(capacity).astype(np.int32)),
            real=jnp.ones(capacity, bool),
            leaf_viable=jnp.ones(capacity, bool),
            justified_idx=jnp.int32(0),
            msg_block=jnp.asarray(
                rng.integers(0, capacity, n).astype(np.int32)),
            msg_epoch=jnp.zeros(n, jnp.int64),
            weight=jnp.asarray(np.full(n, 32 * gwei, np.int64)),
            boost_idx=jnp.int32(capacity - 1),
            boost_amount=jnp.int64(32 * gwei),
        )

    def _forkchoice_rescan():
        from pos_evolution_tpu.ops.forkchoice import head_and_weights
        return head_and_weights, (_dense_store(),), {"capacity": capacity}

    def _forkchoice_incremental():
        from pos_evolution_tpu.ops.forkchoice import (
            head_from_buckets, rebuild_buckets,
        )
        st = _dense_store()
        buckets = rebuild_buckets(st.msg_block, st.weight, capacity)
        return head_from_buckets, (st.parent, st.real, st.rank,
                                   st.leaf_viable, st.justified_idx, buckets,
                                   st.boost_idx, st.boost_amount), \
            {"capacity": capacity}

    def _epoch():
        from pos_evolution_tpu.config import mainnet_config
        from pos_evolution_tpu.ops.epoch import (
            DenseRegistry, process_epoch_dense,
        )
        reg = DenseRegistry(
            effective_balance=jnp.asarray(np.full(n, 32 * gwei, np.int64)),
            balance=jnp.asarray(
                rng.integers(31 * gwei, 33 * gwei, n).astype(np.int64)),
            activation_epoch=jnp.zeros(n, jnp.int64),
            exit_epoch=jnp.asarray(np.full(n, 2**62, np.int64)),
            withdrawable_epoch=jnp.asarray(np.full(n, 2**62, np.int64)),
            slashed=jnp.zeros(n, bool),
            prev_flags=jnp.asarray(rng.integers(0, 8, n).astype(np.uint8)),
            cur_flags=jnp.asarray(rng.integers(0, 8, n).astype(np.uint8)),
            inactivity_scores=jnp.zeros(n, jnp.int64),
        )
        bits = jnp.zeros(4, bool)
        return process_epoch_dense, (reg, 10, 8, bits, 8, 9, 0,
                                     mainnet_config()), {}

    def _sync_verify():
        from pos_evolution_tpu.ops.sync_verify import _merkle_walk_device
        batch, depth = 8, 6
        leaf = jnp.asarray(
            rng.integers(0, 2**32, (batch, 8), dtype=np.uint64)
            .astype(np.uint32))
        branch = jnp.asarray(
            rng.integers(0, 2**32, (batch, depth, 8), dtype=np.uint64)
            .astype(np.uint32))
        idx_bits = jnp.asarray(
            rng.integers(0, 2, (batch, depth)).astype(bool))
        return _merkle_walk_device, (leaf, branch, idx_bits), {}

    def _shuffle():
        from pos_evolution_tpu.ops.shuffle import (
            _seed_words, _shuffle_device, host_pivots,
        )
        seed = bytes(range(32))
        rounds = 10
        return _shuffle_device, (jnp.asarray(_seed_words(seed)),
                                 jnp.asarray(host_pivots(seed, n, rounds))), \
            {"n": n, "rounds": rounds}

    def _merkle_level():
        # the batched SHA-256 merkle level sweep (ops/merkle_device.py):
        # one (N, 16)-word message per sibling pair, N pairs = a 2N-leaf
        # tree level — the production merkleization kernel behind
        # hash_tree_root / DAS commitments / checkpoint digests
        from pos_evolution_tpu.ops.merkle_device import _xla_level_for
        words = jnp.asarray(
            rng.integers(0, 2**32, (n, 16), dtype=np.uint64)
            .astype(np.uint32))
        return _xla_level_for(), (words,), {}

    return {
        "aggregation.aggregate_verify_batch": _aggregation,
        "forkchoice.head_and_weights": _forkchoice_rescan,
        "forkchoice.head_from_buckets": _forkchoice_incremental,
        "epoch.process_epoch_dense": _epoch,
        "sync_verify.merkle_walk": _sync_verify,
        "shuffle.swap_or_not": _shuffle,
        "merkle_device.level_sweep": _merkle_level,
    }


def analyze_hot_paths(n: int = 4096, capacity: int = 64) -> dict:
    """The full emission: per-kernel cost/memory rows plus the backend
    they were compiled for."""
    import jax
    kernels = {}
    for name, build in hot_path_specs(n=n, capacity=capacity).items():
        try:
            fn, args, kwargs = build()
            kernels[name] = analyze_fn(fn, *args, **kwargs)
        except Exception as e:
            kernels[name] = {"error": f"{e!r:.200}"}
    return {"backend": jax.default_backend(), "n_validators": n,
            "forkchoice_capacity": capacity, "kernels": kernels}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", help="write the cost table to this path")
    ap.add_argument("--n", type=int, default=4096,
                    help="validator count for the analyzed shapes")
    ap.add_argument("--capacity", type=int, default=64,
                    help="fork-choice tree capacity")
    args = ap.parse_args(argv)
    table = analyze_hot_paths(n=args.n, capacity=args.capacity)
    blob = json.dumps(table, indent=1, sort_keys=True)
    if args.json:
        with open(args.json, "w") as fh:
            fh.write(blob + "\n")
    print(blob)
    errors = [k for k, v in table["kernels"].items() if "error" in v]
    if errors:
        print(f"# cost: {len(errors)} kernel(s) failed to analyze: "
              f"{', '.join(errors)}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
