"""Bench history: a schema-versioned JSONL time-series of bench
emissions, and the robust statistics ``scripts/perf_gate.py --history``
gates against.

Why a time-series and not a baseline file: one baseline is one sample.
CPU wall-clock jitters, counters drift legitimately as features land,
and a single-sample gate either cries wolf or sleeps through a slow
regression. With the last N entries on disk, a metric is flagged only
when it falls outside a **robust band** of its own recent history:

    median(xs) ± max(k · 1.4826 · MAD(xs), abs_slack)

MAD (median absolute deviation, scaled by 1.4826 to estimate sigma under
normality) ignores the outliers that a mean/stddev band would be dragged
by — one anomalous CI run does not poison the band. The ``abs_slack``
floor keeps a degenerate band (MAD = 0: identical history values, or a
single entry) from flagging every ±1 count.

Envelope (history schema v1), one JSON object per line:

    {"v": 1, "unix": <float>, "kind": "bench" | "bench_all" | ...,
     "emission": {<the full bench/report JSON>}, ["top_ops": {...}]}

``append_entry`` is commit-on-arrival (line-buffered append, same
posture as the event bus): a crashed bench still leaves every prior
entry parseable. ``read_history`` tolerates a torn final line and
refuses unknown schema versions, mirroring ``telemetry.read_jsonl``.
"""

from __future__ import annotations

import json
import os
import time

HISTORY_SCHEMA_VERSION = 1

# MAD -> sigma under normality
_MAD_SIGMA = 1.4826


def append_entry(path, emission: dict, kind: str,
                 top_ops: dict | None = None, unix: float | None = None
                 ) -> dict:
    """Append one emission to the history file (created on first use);
    returns the envelope written."""
    entry: dict = {"v": HISTORY_SCHEMA_VERSION,
                   "unix": round(time.time() if unix is None else unix, 3),
                   "kind": kind, "emission": emission}
    if top_ops:
        entry["top_ops"] = top_ops
    with open(os.fspath(path), "a", buffering=1) as fh:
        fh.write(json.dumps(entry, sort_keys=True) + "\n")
    return entry


def read_history(path, window: int | None = None) -> list[dict]:
    """Load history entries (oldest first), keeping only the last
    ``window`` when given; a missing file is an empty history. The
    torn-tail / mid-log-corruption / unknown-schema contract is the
    shared ``telemetry.events.read_versioned_jsonl`` — one reader, no
    drift between the event log's semantics and this one's."""
    from pos_evolution_tpu.telemetry.events import read_versioned_jsonl
    path = os.fspath(path)
    if not os.path.exists(path):
        return []
    entries = read_versioned_jsonl(path, HISTORY_SCHEMA_VERSION,
                                   label="bench-history")
    return entries[-window:] if window is not None else entries


def median(xs: list[float]) -> float:
    s = sorted(xs)
    n = len(s)
    if n == 0:
        raise ValueError("median of empty series")
    mid = n // 2
    return float(s[mid]) if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def mad(xs: list[float]) -> float:
    m = median(xs)
    return median([abs(x - m) for x in xs])


def robust_band(xs: list[float], k: float = 4.0,
                abs_slack: float = 4.0, rel_slack: float = 0.0) -> dict:
    """The gate band for one metric's history: ``{"median", "mad",
    "halfwidth", "lo", "hi", "n"}`` with halfwidth =
    max(k·1.4826·MAD, abs_slack, rel_slack·|median|).

    ``abs_slack`` is in the metric's own units — right for counts
    (always the same unit), wrong for timings (a 4-unit floor swallows a
    6x regression of a 0.5 ms metric); timing callers pass
    ``abs_slack=0`` and a ``rel_slack`` fraction instead."""
    m = median(xs)
    d = mad(xs)
    half = max(k * _MAD_SIGMA * d, abs_slack, rel_slack * abs(m))
    return {"median": m, "mad": d, "halfwidth": half,
            "lo": m - half, "hi": m + half, "n": len(xs)}


def band_verdicts(candidate: dict[str, float],
                  history_series: dict[str, list[float]],
                  k: float = 4.0, abs_slack: float = 4.0,
                  rel_slack: float = 0.0,
                  two_sided: bool = False) -> list[dict]:
    """Per-metric verdict rows for every candidate key with history.

    One-sided by default: only ``candidate > hi`` fails (a count/time
    *increase* is the regression; a drop is visible in the row but does
    not gate — vanishing work is usually a renamed metric or a feature
    removal, and the baseline-mode gate never failed those either).
    Keys with no history are skipped rows (``verdict: "skip"``) — a new
    counter is not a regression."""
    rows = []
    for key in sorted(candidate):
        xs = history_series.get(key) or []
        if not xs:
            rows.append({"key": key, "value": candidate[key],
                         "verdict": "skip", "n": 0})
            continue
        band = robust_band(xs, k=k, abs_slack=abs_slack,
                           rel_slack=rel_slack)
        bad_hi = candidate[key] > band["hi"]
        bad_lo = two_sided and candidate[key] < band["lo"]
        rows.append({"key": key, "value": candidate[key],
                     "verdict": "FAIL" if (bad_hi or bad_lo) else "ok",
                     **band})
    return rows


def series_from_history(entries: list[dict], extract) -> dict[str, list[float]]:
    """Apply ``extract(emission_dict) -> {key: value}`` over every
    history entry and pivot into per-key series (oldest first). Entries
    whose emission lacks a key simply contribute nothing to that key's
    series."""
    series: dict[str, list[float]] = {}
    for entry in entries:
        emission = entry.get("emission")
        if not isinstance(emission, dict):
            continue
        for key, value in extract(emission).items():
            series.setdefault(key, []).append(float(value))
    return series
