"""Device-time attribution: from xplane op streams back to the code that
dispatched them.

XLA stamps every HLO op with an ``op_name`` metadata path like

    jit(run)/while/body/closed_call/jit(head_and_weights)/scatter-add

The *innermost* ``jit(...)`` frame names the Python function whose trace
emitted the op — that is the natural attribution key for this codebase,
where every hot path is a named jitted kernel (``head_and_weights``,
``aggregate_verify_batch``, ``process_epoch_dense``, ...). Spans on the
telemetry bus (``blk-3-5``, handler names like ``on_block``/``get_head``,
``TraceAnnotation`` region names) are then matched against those frames
and against raw path segments, folding device milliseconds onto the span
that dispatched them; everything unmatched lands in ``unattributed`` so
the table always sums to the trace total (no silently vanishing time).

``ProfiledRegion`` is the capture harness: a context manager that wraps
any sim/bench section in a ``jax.profiler`` trace, parses the resulting
xplane protobufs with ``profiling/xplane.py``, attributes device ops to
the telemetry spans emitted *during the region*, and (when a telemetry
bundle is attached) emits one ``profile`` event carrying the summary —
so run reports can show "where the device time went" next to "what
happened".
"""

from __future__ import annotations

import os
import re
import shutil
import tempfile

from pos_evolution_tpu.profiling import ledger, xplane

_JIT_RE = re.compile(r"jit\(([^()]*)\)")


def is_python_frame(op_name: str) -> bool:
    """Host python-tracer frames (``$file.py:123 fn``) — timeline context,
    not executed device work; the aggregate views skip them (they nest
    around everything and would double-count the real ops under them)."""
    return op_name.startswith("$")


def op_frames(op_name: str) -> list[str]:
    """The scope path of one op: ``jit(f)/while/body/jit(g)/add`` ->
    ``['jit(f)', 'while', 'body', 'jit(g)', 'add']``."""
    return [seg for seg in op_name.split("/") if seg]


def innermost_jit(op_name: str) -> str | None:
    """The function name of the deepest ``jit(...)`` frame, or None."""
    names = _JIT_RE.findall(op_name)
    return names[-1] if names else None


def group_by_jit(planes, device_only: bool = True,
                 exclude_ops=frozenset()) -> dict[str, dict]:
    """Aggregate a ``parse_xspace`` result by innermost jit frame:
    ``{fn_name: {"total_ms", "count", "ops": {op: [ms, count]}}}``.

    ``exclude_ops``: op names dropped entirely — ``ProfiledRegion``
    passes its own annotation name here, because on the CPU-plane
    fallback the region's ``TraceAnnotation`` slice *envelops* every op
    it dispatched and would double-count the whole region as work.
    Ops with no jit frame key under ``"unjitted"``. ``device_only``
    keeps planes whose name smells like a device (``xplane.
    is_device_plane``); on a CPU-only run nothing matches, so it falls
    back to every plane — the CPU thunk executor timeline is the device
    timeline there."""
    chosen = xplane.select_planes(planes, device_only)
    out: dict[str, dict] = {}
    key_of: dict[str, str] = {}  # op_name -> key: a trace has ~10^5 events
    # but only ~10^2 distinct op names (metadata-interned); resolve once
    for _, _, op, _, dur in xplane.iter_ops(chosen):
        key = key_of.get(op)
        if key is None:
            if is_python_frame(op) or op in exclude_ops:
                key_of[op] = ""
                continue
            key = key_of[op] = innermost_jit(op) or "unjitted"
        elif not key:
            continue
        row = out.setdefault(key, {"total_ms": 0.0, "count": 0, "ops": {}})
        ms = dur / 1e9
        row["total_ms"] += ms
        row["count"] += 1
        cell = row["ops"].setdefault(op, [0.0, 0])
        cell[0] += ms
        cell[1] += 1
    for row in out.values():
        row["total_ms"] = round(row["total_ms"], 4)
        row["ops"] = {k: [round(v[0], 4), v[1]]
                      for k, v in sorted(row["ops"].items(),
                                         key=lambda kv: -kv[1][0])}
    return out


# shard_map regions show up in op scope paths either as a literal
# ``shard_map`` frame or as the traced body's synthesized jit frame
# (``jit(shmap_body)`` / ``shmap_body``), depending on the JAX version
# and whether the body was a named function.
_SHMAP_MARKERS = ("shard_map", "shmap_body")


def shard_map_region(op_name: str) -> str | None:
    """The attribution key for an op dispatched from inside a
    ``shard_map`` region: ``<enclosing jit>/shard_map`` (or bare
    ``shard_map`` when unjitted), None for ops outside any region. The
    enclosing-jit prefix keeps two shard_map call sites (the vote pass
    vs the epoch sweep) distinct in the table."""
    frames = op_frames(op_name)
    marker_at = next((i for i, f in enumerate(frames)
                      if any(m in f for m in _SHMAP_MARKERS)), None)
    if marker_at is None:
        return None
    jits = _JIT_RE.findall("/".join(frames[:marker_at]))
    # the body's own synthesized jit(shmap_body) frame is the marker,
    # not the region's caller — filter marker-ish names out
    jits = [j for j in jits if not any(m in j for m in _SHMAP_MARKERS)]
    outer = jits[-1] if jits else None
    return f"{outer}/shard_map" if outer else "shard_map"


def group_by_shard_map(planes, device_only: bool = True,
                       exclude_ops=frozenset()) -> dict[str, dict]:
    """Aggregate a ``parse_xspace`` result by ``shard_map`` region:
    ``{region: {"total_ms", "count", "ops": {op: [ms, count]}}}``, with
    every op outside a region under ``"unsharded"``. The table is a
    partition of the (filtered) trace, same contract as
    ``group_by_jit`` — region time vs unsharded time sums to the trace
    total, so the sharded share of an epoch is one division away."""
    chosen = xplane.select_planes(planes, device_only)
    out: dict[str, dict] = {}
    key_of: dict[str, str] = {}
    for _, _, op, _, dur in xplane.iter_ops(chosen):
        key = key_of.get(op)
        if key is None:
            if is_python_frame(op) or op in exclude_ops:
                key_of[op] = ""
                continue
            key = key_of[op] = shard_map_region(op) or "unsharded"
        elif not key:
            continue
        row = out.setdefault(key, {"total_ms": 0.0, "count": 0, "ops": {}})
        ms = dur / 1e9
        row["total_ms"] += ms
        row["count"] += 1
        cell = row["ops"].setdefault(op, [0.0, 0])
        cell[0] += ms
        cell[1] += 1
    for row in out.values():
        row["total_ms"] = round(row["total_ms"], 4)
        row["ops"] = {k: [round(v[0], 4), v[1]]
                      for k, v in sorted(row["ops"].items(),
                                         key=lambda kv: -kv[1][0])}
    return out


def attribute_to_spans(planes, span_names, device_only: bool = True,
                       exclude_ops=frozenset()) -> dict:
    """Fold device op time onto telemetry span / region names.
    ``exclude_ops`` as in ``group_by_jit`` (enveloping annotation slices
    must not be counted as the work they contain).

    An op attributes to the first span name (iteration order of
    ``span_names``) that appears in the op's scope path — as a
    ``jit(<name>)`` frame, a literal path segment (TraceAnnotation
    regions show up as segments), or a substring of a frame (so the span
    ``get_head`` catches ``jit(head_from_buckets)`` only if callers map
    it; exact/segment matches are tried first, substring last). Ops no
    span claims land in ``"unattributed"`` — the table is a partition of
    the trace, totals preserved."""
    names = list(dict.fromkeys(span_names))  # de-dup, keep order
    out: dict[str, dict] = {}

    def bucket(key):
        return out.setdefault(key, {"total_ms": 0.0, "count": 0})

    def resolve(op: str) -> str | None:
        if is_python_frame(op) or op in exclude_ops:
            return None
        frames = op_frames(op)
        jits = _JIT_RE.findall(op)
        for name in names:
            if name in jits or name in frames:
                return name
        for name in names:
            if any(name in f for f in frames):
                return name
        return "unattributed"

    # memoize per distinct op name: a big trace has ~10^5 events over
    # ~10^2 metadata-interned names, and resolve() scans span_names —
    # without the cache __exit__ goes quadratic on profiled sims
    target_of: dict[str, str | None] = {}
    for _, _, op, _, dur in xplane.iter_ops(
            xplane.select_planes(planes, device_only)):
        if op in target_of:
            target = target_of[op]
        else:
            target = target_of[op] = resolve(op)
        if target is None:
            continue
        row = bucket(target)
        row["total_ms"] += dur / 1e9
        row["count"] += 1
    for row in out.values():
        row["total_ms"] = round(row["total_ms"], 4)
    return out


class ProfiledRegion:
    """Capture a device trace around a code region and attribute it.

    >>> with ProfiledRegion("epoch", telemetry=tel) as prof:
    ...     run_the_workload()
    >>> prof.top_ops          # plane -> top-N [{op, total_ms, count}]
    >>> prof.by_jit           # innermost-jit attribution table
    >>> prof.attribution      # span-name attribution (needs telemetry)

    The trace lands in ``trace_dir`` (a temp dir deleted on exit unless
    ``keep_trace``/an explicit dir is given). With a telemetry bundle
    attached, span/handler names emitted on the bus *during* the region
    become attribution targets and one ``profile`` event with the summary
    is emitted on exit. Profiling failures (no jax, a second concurrent
    ``jax.profiler`` session, an empty trace) degrade to empty tables
    with ``self.error`` set — profiling must never kill the run it
    observes."""

    def __init__(self, name: str = "profiled", telemetry=None,
                 trace_dir=None, top_n: int = 10, keep_trace: bool = False,
                 extra_span_names=()):
        self.name = name
        self.telemetry = telemetry
        self.trace_dir = os.fspath(trace_dir) if trace_dir is not None \
            else None
        self.top_n = top_n
        self.keep_trace = keep_trace or trace_dir is not None
        self.extra_span_names = list(extra_span_names)
        self.planes: list[dict] = []
        self.top_ops: dict = {}
        self.by_jit: dict = {}
        self.by_shard_map: dict = {}
        self.attribution: dict = {}
        self.error: str | None = None
        self._bus_mark = 0
        self._annotation = None
        self._tracing = False
        self._prev_region = None

    def __enter__(self) -> "ProfiledRegion":
        # name this region in the compile-provenance span context
        # (profiling/ledger.py): compiles triggered inside the region
        # are charged to it when no tighter function scope is active
        self._prev_region = ledger.push_region(self.name)
        if self.trace_dir is None:
            self.trace_dir = tempfile.mkdtemp(prefix=".profiled_region_")
        os.makedirs(self.trace_dir, exist_ok=True)
        if self.telemetry is not None:
            self._bus_mark = len(self.telemetry.bus.events)
        try:
            import jax
            jax.profiler.start_trace(self.trace_dir)
            self._tracing = True
            self._annotation = jax.profiler.TraceAnnotation(self.name)
            self._annotation.__enter__()
        except Exception as e:  # no jax / profiler already active
            self.error = f"trace start failed: {e!r:.200}"
        return self

    def _region_span_names(self) -> list[str]:
        names = list(self.extra_span_names)
        names.append(self.name)
        if self.telemetry is not None:
            for ev in self.telemetry.bus.events[self._bus_mark:]:
                h = ev.get("handler")
                if h:
                    names.append(h)
                s = ev.get("span")
                if s:
                    names.append(s)
        return names

    def __exit__(self, *exc) -> None:
        ledger.pop_region(self._prev_region)
        if self._annotation is not None:
            try:
                self._annotation.__exit__(*exc)
            except Exception:
                pass
        if self._tracing:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception as e:
                self.error = self.error or f"trace stop failed: {e!r:.200}"
            else:
                try:
                    self.planes = xplane.parse_path(self.trace_dir)
                    self.top_ops = xplane.top_table(
                        xplane.summarize_planes(self.planes), self.top_n)
                    # the region's own annotation slice envelops every op
                    # it dispatched — exclude it or CPU-fallback tables
                    # double-count the whole region (the legacy top_ops
                    # view keeps it: there it reads as a total, not work)
                    self.by_jit = group_by_jit(self.planes,
                                               exclude_ops={self.name})
                    self.by_shard_map = group_by_shard_map(
                        self.planes, exclude_ops={self.name})
                    self.attribution = attribute_to_spans(
                        self.planes, self._region_span_names(),
                        exclude_ops={self.name})
                except Exception as e:
                    # truncated protobufs (killed writer, full disk),
                    # missing files, anything: profiling must never kill
                    # the run it observes
                    self.planes = []
                    self.error = f"trace parse failed: {e!r:.200}"
        if self.telemetry is not None:
            payload = {
                "name": self.name,
                "by_jit": {k: {"total_ms": v["total_ms"],
                               "count": v["count"]}
                           for k, v in self.by_jit.items()},
                "by_shard_map": {k: {"total_ms": v["total_ms"],
                                     "count": v["count"]}
                                 for k, v in self.by_shard_map.items()},
                "attribution": self.attribution,
            }
            if self.error is not None:
                payload["error"] = self.error
            if self.keep_trace:
                payload["trace_dir"] = self.trace_dir
            try:
                self.telemetry.bus.emit("profile", **payload)
            except Exception:
                pass  # a closed bus must not raise out of the region
        if not self.keep_trace and self.trace_dir is not None:
            shutil.rmtree(self.trace_dir, ignore_errors=True)
