"""Trace exporters: telemetry JSONL + device ops -> Chrome trace_event
JSON (Perfetto-loadable) and collapsed-stack flamegraphs.

Two timelines, one file:

- **sim lane (pid 1)**: the message-lifecycle span tree from the event
  bus. ``deliver`` events carry sim-time ``t`` (seconds) and measured
  ``duration_ms``; ``gossip`` edges carry ``t``; ``propose``/``attest``
  roots carry no time of their own, so they inherit the earliest ``t``
  of their children (the span tree is deterministic ids, so the join is
  exact). Events with no derivable time fall back to ``seq``
  microseconds — structurally valid, ordinal rather than temporal.
  ``tid`` is the view-group id, so each group's deliveries read as one
  thread track.
- **device lane (pid 2)**: xplane ops (``profiling/xplane.py`` parse),
  one thread per trace line, using the line's ``timestamp_ns`` +
  per-event ``offset_ps``. Device timestamps are wall-clock and sim
  ``t`` is simulation time — the two lanes are separate pids precisely
  because their clocks do not share an origin; Perfetto renders them as
  independent process tracks.

Flamegraphs are Brendan-Gregg collapsed stacks (``a;b;c <weight>``):
the sim view stacks event types along span lineage
(``propose;gossip;deliver:on_block``) weighted by measured microseconds
(count when unmeasured); the device view splits the HLO ``op_name``
scope path (``jit(run);while;body;jit(head_and_weights);scatter-add``)
weighted by device microseconds.

CLI:
    python -m pos_evolution_tpu.profiling.export events.jsonl
        [--chrome out.json] [--flame out.txt] [--xplane trace_dir]
        [--device-flame out2.txt]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from pos_evolution_tpu.profiling import xplane as _xplane

SIM_PID = 1
DEVICE_PID = 2

# span-carrying / duration-carrying event types rendered as slices; any
# OTHER bus event that carries sim-time ``t`` (faults on a timed edge,
# custom emitters) becomes an instant marker — events with no derivable
# time are dropped rather than plotted at a fake position
_SLICE_TYPES = {"propose", "attest", "gossip", "deliver", "handler"}


def _event_name(ev: dict) -> str:
    t = ev.get("type", "?")
    qual = ev.get("handler") or ev.get("kind")
    return f"{t}:{qual}" if qual else t


def _span_times(events) -> dict[str, float]:
    """span id -> start seconds: own ``t`` when carried, else the
    earliest ``t`` among descendants (exact: ids are deterministic)."""
    children: dict[str, list[dict]] = {}
    by_span: dict[str, dict] = {}
    for ev in events:
        s = ev.get("span")
        if s is not None:
            by_span.setdefault(s, ev)
        p = ev.get("parent")
        if p is not None:
            children.setdefault(p, []).append(ev)

    times: dict[str, float] = {}

    def start_of(span, ev, depth=0) -> float | None:
        if span in times:
            return times[span]
        t = ev.get("t")
        if t is None and depth < 8:
            kids = [start_of(k.get("span"), k, depth + 1)
                    for k in children.get(span, ())]
            kids = [k for k in kids if k is not None]
            t = min(kids) if kids else None
        if t is not None:
            times[span] = float(t)
        return times.get(span)

    for span, ev in by_span.items():
        start_of(span, ev)
    return times


def chrome_trace(events, device_planes=None,
                 max_device_events: int | None = None) -> dict:
    """-> ``{"traceEvents": [...], "displayTimeUnit": "ms"}`` (the JSON
    object form of the Chrome trace_event format; Perfetto and
    chrome://tracing both load it).

    ``max_device_events`` caps the device lane at the N longest slices —
    a CPU epoch records hundreds of thousands of per-thunk executions
    (tens of MB of JSON) and the long ones are the ones worth looking
    at. Never a silent cap: the dropped count lands in a ``truncated``
    metadata event and the caller's log."""
    out = [
        {"ph": "M", "pid": SIM_PID, "name": "process_name",
         "args": {"name": "simulation (sim-time)"}},
    ]
    times = _span_times(events)
    for ev in events:
        typ = ev.get("type")
        if typ not in _SLICE_TYPES:
            if ev.get("t") is not None:  # timed marker (e.g. fault)
                out.append({"name": _event_name(ev), "cat": typ, "ph": "i",
                            "s": "p", "ts": round(float(ev["t"]) * 1e6, 3),
                            "pid": SIM_PID,
                            "tid": int(ev.get("group",
                                              ev.get("dst", 0)) or 0)})
            continue
        span = ev.get("span")
        t = ev.get("t")
        if t is None and span is not None:
            t = times.get(span)
        ts_us = float(t) * 1e6 if t is not None \
            else float(ev.get("seq", 0))  # ordinal fallback
        dur_ms = ev.get("duration_ms")
        dur_us = float(dur_ms) * 1e3 if dur_ms is not None else 1.0
        args = {k: v for k, v in ev.items()
                if k in ("slot", "status", "reason", "proposer", "committee",
                         "src", "dst", "kind", "handler", "span", "parent")}
        out.append({
            "name": _event_name(ev), "cat": typ, "ph": "X",
            "ts": round(ts_us, 3), "dur": round(dur_us, 3),
            "pid": SIM_PID, "tid": int(ev.get("group", ev.get("dst", 0)) or 0),
            "args": args,
        })
    if device_planes:
        out.append({"ph": "M", "pid": DEVICE_PID, "name": "process_name",
                    "args": {"name": "device (wall-clock)"}})
        tid = 0
        dev = []
        t0_ns = min((line["timestamp_ns"] for p in device_planes
                     for line in p["lines"] if line["events"]), default=0)
        for plane in device_planes:
            for line in plane["lines"]:
                if not line["events"]:
                    continue
                tid += 1
                out.append({"ph": "M", "pid": DEVICE_PID, "tid": tid,
                            "name": "thread_name",
                            "args": {"name": f"{plane['name']}/"
                                             f"{line['name'] or tid}"}})
                base_us = (line["timestamp_ns"] - t0_ns) / 1e3
                meta = plane["event_metadata"]
                for e in line["events"]:
                    op = meta.get(e["metadata_id"], f"#{e['metadata_id']}")
                    dev.append({
                        "name": op.rsplit("/", 1)[-1] or op, "cat": "device",
                        "ph": "X",
                        "ts": round(base_us + e["offset_ps"] / 1e6, 3),
                        "dur": round(max(e["duration_ps"] / 1e6, 0.001), 3),
                        "pid": DEVICE_PID, "tid": tid,
                        "args": {"op_name": op},
                    })
        if max_device_events is not None and len(dev) > max_device_events:
            dropped = len(dev) - max_device_events
            dev.sort(key=lambda e: -e["dur"])
            dev = sorted(dev[:max_device_events], key=lambda e: e["ts"])
            out.append({"ph": "M", "pid": DEVICE_PID, "name": "truncated",
                        "args": {"dropped_short_events": dropped,
                                 "kept": max_device_events}})
        out.extend(dev)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def collapsed_stacks(events) -> list[str]:
    """Sim-side flamegraph: one line per unique span-lineage stack,
    ``frame;frame;frame weight`` with integer microsecond weights
    (1 per event when no duration was measured)."""
    by_span: dict[str, dict] = {}
    for ev in events:
        s = ev.get("span")
        if s is not None and s not in by_span:
            by_span[s] = ev

    stacks: dict[str, int] = {}
    for ev in events:
        if ev.get("type") not in _SLICE_TYPES:
            continue
        frames = [_event_name(ev)]
        parent = ev.get("parent")
        hops = 0
        while parent is not None and hops < 8:
            pev = by_span.get(parent)
            if pev is None:
                break
            frames.append(_event_name(pev))
            parent = pev.get("parent")
            hops += 1
        key = ";".join(reversed(frames))
        dur_ms = ev.get("duration_ms")
        weight = int(round(float(dur_ms) * 1e3)) if dur_ms is not None else 1
        stacks[key] = stacks.get(key, 0) + max(weight, 1)
    return [f"{k} {v}" for k, v in sorted(stacks.items())]


def device_collapsed_stacks(planes, exclude_ops=frozenset()) -> list[str]:
    """Device-side flamegraph: the HLO scope path as the stack, device
    microseconds as the weight. Planes go through the shared
    ``xplane.select_planes`` device filter, and ``exclude_ops`` drops
    enveloping annotation slices (a region's ``TraceAnnotation`` overlaps
    every op it dispatched — folding both in double-counts), matching
    the attribution views."""
    from pos_evolution_tpu.profiling.attribution import is_python_frame
    stacks: dict[str, int] = {}
    for _, _, op, _, dur in _xplane.iter_ops(_xplane.select_planes(planes)):
        if is_python_frame(op) or op in exclude_ops:
            continue
        key = ";".join(seg.replace(" ", "_")
                       for seg in op.split("/") if seg) or "unknown"
        us = max(int(round(dur / 1e6)), 1)
        stacks[key] = stacks.get(key, 0) + us
    return [f"{k} {v}" for k, v in sorted(stacks.items())]


def write_artifacts(outdir, events=(), planes=None, top_ops=None,
                    max_device_events: int | None = None,
                    exclude_ops=frozenset()) -> dict:
    """Write the standard artifact set into ``outdir`` and return
    ``{artifact: path}`` — the ONE place the filenames live (bench.py,
    the sim driver, and ``run_report.py`` auto-discovery all depend on
    them agreeing):

    - ``chrome_trace.json``  always (sim spans + device ops);
    - ``flame.txt``          when span events were given;
    - ``flame_device.txt``   when xplane planes were given;
    - ``top_ops.json``       when a top-op table was given (callers that
      own a separate top_ops protocol — bench.py --trace — pass None).
    """
    outdir = os.fspath(outdir)
    os.makedirs(outdir, exist_ok=True)
    events = list(events)
    written = {}

    def _path(name):
        written[name] = os.path.join(outdir, name)
        return written[name]

    with open(_path("chrome_trace.json"), "w") as fh:
        json.dump(chrome_trace(events, device_planes=planes,
                               max_device_events=max_device_events), fh)
        fh.write("\n")
    if events:
        with open(_path("flame.txt"), "w") as fh:
            fh.write("\n".join(collapsed_stacks(events)) + "\n")
    if planes:
        with open(_path("flame_device.txt"), "w") as fh:
            fh.write("\n".join(
                device_collapsed_stacks(planes, exclude_ops=exclude_ops))
                + "\n")
    if top_ops:
        with open(_path("top_ops.json"), "w") as fh:
            json.dump({"source": "profiled_region", "planes": top_ops},
                      fh, indent=1)
            fh.write("\n")
    return written


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("events", help="telemetry JSONL file")
    ap.add_argument("--chrome", help="write Chrome trace_event JSON here")
    ap.add_argument("--flame", help="write sim collapsed stacks here")
    ap.add_argument("--xplane",
                    help="xplane trace dir/file to fold device ops in")
    ap.add_argument("--device-flame",
                    help="write device collapsed stacks here")
    ap.add_argument("--max-device-events", type=int, default=50_000,
                    help="cap the Chrome device lane at the N longest "
                         "slices (0 = unlimited; CPU traces record one "
                         "event per thunk — tens of MB untruncated)")
    args = ap.parse_args(argv)

    from pos_evolution_tpu.telemetry import read_jsonl
    events = read_jsonl(args.events)
    planes = _xplane.parse_path(args.xplane) if args.xplane else None
    cap = args.max_device_events or None

    wrote = []
    if args.chrome:
        with open(args.chrome, "w") as fh:
            json.dump(chrome_trace(events, device_planes=planes,
                                   max_device_events=cap), fh)
            fh.write("\n")
        wrote.append(args.chrome)
    if args.flame:
        with open(args.flame, "w") as fh:
            fh.write("\n".join(collapsed_stacks(events)) + "\n")
        wrote.append(args.flame)
    if args.device_flame:
        if planes is None:
            print("--device-flame needs --xplane", file=sys.stderr)
            return 2
        with open(args.device_flame, "w") as fh:
            fh.write("\n".join(device_collapsed_stacks(planes)) + "\n")
        wrote.append(args.device_flame)
    if not wrote:
        json.dump(chrome_trace(events, device_planes=planes,
                               max_device_events=cap), sys.stdout)
        sys.stdout.write("\n")
    else:
        print("wrote: " + ", ".join(wrote), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
