"""Profiling subsystem (ISSUE 4): where did the device time, FLOPs and
HBM go — and is it drifting?

Four legs, one pipeline (capture → attribute → export → gate):

- **device-time attribution** (``xplane.py`` + ``attribution.py``): an
  importable xplane protobuf wire-format parser (what
  ``scripts/trace_summary.py`` used to be) plus ``ProfiledRegion``, a
  context manager that wraps any sim/bench section in a ``jax.profiler``
  trace and attributes device op time back to the telemetry spans /
  jitted kernels that dispatched it;
- **static cost & memory analysis** (``cost.py``): per-kernel
  FLOPs / bytes-accessed / peak-memory tables for the hot paths via
  ``lower().compile().cost_analysis()`` + ``memory_analysis()``;
- **trace export** (``export.py``): telemetry JSONL span trees and
  attributed device ops rendered as Chrome ``trace_event`` JSON
  (Perfetto-loadable) and collapsed-stack flamegraphs;
- **bench history** (``history.py``): every bench emission appended to a
  schema-versioned ``bench_history.jsonl``; ``scripts/perf_gate.py
  --history`` flags a metric only when it falls outside a robust
  median ± k·MAD band of the recent entries.

Timing caveats are inherited from ``utils/benchtime.py``: on async
relays wall-clock around a dispatch measures enqueue latency, so device
*timelines* (this package) complement — never replace — the fused-loop
work-difference *numbers* (benchtime).
"""

from pos_evolution_tpu.profiling.attribution import (
    ProfiledRegion,
    attribute_to_spans,
    group_by_jit,
    group_by_shard_map,
    innermost_jit,
)
from pos_evolution_tpu.profiling.ledger import (
    CompileLedger,
    function_scope,
)
from pos_evolution_tpu.profiling.phases import (
    DENSE_PHASES,
    NULL_TIMER,
    PhaseTimer,
)
from pos_evolution_tpu.profiling.history import (
    HISTORY_SCHEMA_VERSION,
    append_entry,
    band_verdicts,
    read_history,
    robust_band,
)
from pos_evolution_tpu.profiling.xplane import (
    encode_xspace,
    parse_xspace,
    summarize_path,
    summarize_xplane,
    top_table,
)

__all__ = [
    "ProfiledRegion", "attribute_to_spans", "group_by_jit",
    "group_by_shard_map", "innermost_jit",
    "PhaseTimer", "NULL_TIMER", "DENSE_PHASES",
    "CompileLedger", "function_scope",
    "HISTORY_SCHEMA_VERSION", "append_entry", "band_verdicts",
    "read_history", "robust_band",
    "encode_xspace", "parse_xspace", "summarize_path", "summarize_xplane",
    "top_table",
]
