"""BLS12-381 pairing and signatures, from scratch (component N1).

The reference's signature layer — ``bls.Verify`` for deposits
(pos-evolution.md:165), aggregate attestation signatures over
``aggregation_bits`` (:714-717), sync aggregates (:642) — is real
BLS12-381 in every deployment. This module implements the full pairing
stack in pure Python integers as the *correctness oracle* for the native
and TPU kernels (SURVEY.md §2.7 N1):

- the field tower Fq -> Fq2 (u^2 = -1) -> Fq6 (v^3 = u+1) -> Fq12 (w^2 = v)
- curve arithmetic on E(Fq): y^2 = x^3 + 4 (G1) and the sextic M-twist
  E'(Fq2): y^2 = x^3 + 4(u+1) (G2), with subgroup cofactor clearing
- the ate pairing: generic Miller loop over the untwisted points with the
  BLS parameter t = -0xd201000000010000, final exponentiation by
  (q^12 - 1) / r
- min-pubkey-size signatures: pk in G1 (48 B compressed), signatures in G2
  (96 B compressed), hash-to-G2 by try-and-increment + cofactor clearing
  (deterministic; NOT the IETF hash_to_curve ciphersuite — the protocol
  simulator only needs a consistent, sound scheme), aggregation by G2 sum.

Slow by design (~1 s/pairing): protocol tests run on FakeBLS; this backend
exists so crypto tests and future accelerated kernels have exact vectors.
"""

from __future__ import annotations

import hashlib
import os

# --- parameters ---------------------------------------------------------------

Q = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
BLS_X = 0xD201000000010000  # |t|; t is negative for BLS12-381

G1_COFACTOR = 0x396C8C005555E1568C00AAAB0000AAAB
G2_COFACTOR = 0x5D543A95414E7F1091D50792876A202CD91DE4547085ABAA68A205B2E5A7DDFA628F1CB4D9E82EF21537E293A6691AE1616EC6E786F0C70CF1C38E31C7238E5

G1_GEN = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
)
_G2X = (0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
        0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E)
_G2Y = (0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
        0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE)


# --- Fq -----------------------------------------------------------------------

def fq_inv(a: int) -> int:
    return pow(a, Q - 2, Q)


# --- Fq2: a + b*u with u^2 = -1 ----------------------------------------------

class Fq2:
    __slots__ = ("a", "b")

    def __init__(self, a: int, b: int = 0):
        self.a = a % Q
        self.b = b % Q

    def __add__(s, o):
        return Fq2(s.a + o.a, s.b + o.b)

    def __sub__(s, o):
        return Fq2(s.a - o.a, s.b - o.b)

    def __neg__(s):
        return Fq2(-s.a, -s.b)

    def __mul__(s, o):
        if isinstance(o, int):
            return Fq2(s.a * o, s.b * o)
        t0 = s.a * o.a
        t1 = s.b * o.b
        t2 = (s.a + s.b) * (o.a + o.b)
        return Fq2(t0 - t1, t2 - t0 - t1)

    __rmul__ = __mul__

    def sq(s):
        # (a+bu)^2 = (a+b)(a-b) + 2ab u
        return Fq2((s.a + s.b) * (s.a - s.b), 2 * s.a * s.b)

    def inv(s):
        d = fq_inv((s.a * s.a + s.b * s.b) % Q)
        return Fq2(s.a * d, -s.b * d)

    def conj(s):
        return Fq2(s.a, -s.b)

    def __eq__(s, o):
        return isinstance(o, Fq2) and s.a == o.a and s.b == o.b

    def __hash__(s):
        return hash((s.a, s.b))

    def is_zero(s):
        return s.a == 0 and s.b == 0

    def pow(s, e: int):
        out, base = FQ2_ONE, s
        while e:
            if e & 1:
                out = out * base
            base = base.sq()
            e >>= 1
        return out

    def __repr__(s):
        return f"Fq2({hex(s.a)}, {hex(s.b)})"


FQ2_ZERO = Fq2(0)
FQ2_ONE = Fq2(1)
XI = Fq2(1, 1)  # the sextic twist parameter u + 1

# G2 generator (constructed here because Fq2 must exist first)
G2_GEN = (Fq2(*_G2X), Fq2(*_G2Y))


def fq2_sqrt(a: Fq2):
    """Square root in Fq2 (q^2 = 9 mod 16 method); None if non-residue."""
    cand = a.pow((Q * Q + 7) // 16)
    for root in _EIGHTH_ROOTS:
        x = cand * root
        if x.sq() == a:
            return x
    return None


def _compute_eighth_roots():
    # powers of a primitive 8th root of unity: (u+1)^((q^2-1)/8) generates
    # them since u+1 is a non-residue
    base = XI.pow((Q * Q - 1) // 8)
    roots = [FQ2_ONE]
    for _ in range(3):
        roots.append(roots[-1] * base)
    return roots


_EIGHTH_ROOTS = _compute_eighth_roots()


# --- Fq6: a + b*v + c*v^2 over Fq2 with v^3 = XI ------------------------------

class Fq6:
    __slots__ = ("a", "b", "c")

    def __init__(self, a: Fq2, b: Fq2, c: Fq2):
        self.a, self.b, self.c = a, b, c

    def __add__(s, o):
        return Fq6(s.a + o.a, s.b + o.b, s.c + o.c)

    def __sub__(s, o):
        return Fq6(s.a - o.a, s.b - o.b, s.c - o.c)

    def __neg__(s):
        return Fq6(-s.a, -s.b, -s.c)

    def __mul__(s, o):
        if isinstance(o, Fq2):
            return Fq6(s.a * o, s.b * o, s.c * o)
        t0 = s.a * o.a
        t1 = s.b * o.b
        t2 = s.c * o.c
        return Fq6(
            t0 + ((s.b + s.c) * (o.b + o.c) - t1 - t2) * XI,
            (s.a + s.b) * (o.a + o.b) - t0 - t1 + t2 * XI,
            (s.a + s.c) * (o.a + o.c) - t0 - t2 + t1,
        )

    def sq(s):
        return s * s

    def mul_by_v(s):
        return Fq6(s.c * XI, s.a, s.b)

    def inv(s):
        # standard cubic-extension inverse
        c0 = s.a.sq() - s.b * s.c * XI
        c1 = s.c.sq() * XI - s.a * s.b
        c2 = s.b.sq() - s.a * s.c
        t = (s.a * c0 + (s.c * c1 + s.b * c2) * XI).inv()
        return Fq6(c0 * t, c1 * t, c2 * t)

    def __eq__(s, o):
        return s.a == o.a and s.b == o.b and s.c == o.c

    def is_zero(s):
        return s.a.is_zero() and s.b.is_zero() and s.c.is_zero()


FQ6_ZERO = Fq6(FQ2_ZERO, FQ2_ZERO, FQ2_ZERO)
FQ6_ONE = Fq6(FQ2_ONE, FQ2_ZERO, FQ2_ZERO)


# --- Fq12: a + b*w over Fq6 with w^2 = v --------------------------------------

class Fq12:
    __slots__ = ("a", "b")

    def __init__(self, a: Fq6, b: Fq6):
        self.a, self.b = a, b

    def __add__(s, o):
        return Fq12(s.a + o.a, s.b + o.b)

    def __sub__(s, o):
        return Fq12(s.a - o.a, s.b - o.b)

    def __mul__(s, o):
        t0 = s.a * o.a
        t1 = s.b * o.b
        return Fq12(t0 + t1.mul_by_v(),
                    (s.a + s.b) * (o.a + o.b) - t0 - t1)

    def sq(s):
        return s * s

    def inv(s):
        t = (s.a * s.a - (s.b * s.b).mul_by_v()).inv()
        return Fq12(s.a * t, -(s.b * t))

    def conj(s):
        """Conjugation = Frobenius^6: a - b*w."""
        return Fq12(s.a, -s.b)

    def pow(s, e: int):
        out, base = FQ12_ONE, s
        while e:
            if e & 1:
                out = out * base
            base = base.sq()
            e >>= 1
        return out

    def __eq__(s, o):
        return s.a == o.a and s.b == o.b

    def is_one(s):
        return s == FQ12_ONE


FQ12_ONE = Fq12(FQ6_ONE, FQ6_ZERO)


def fq2_to_fq12(x: Fq2) -> Fq12:
    return Fq12(Fq6(x, FQ2_ZERO, FQ2_ZERO), FQ6_ZERO)


# w and its powers for the untwist map psi(x', y') = (x'/w^2, y'/w^3)
_W = Fq12(FQ6_ZERO, FQ6_ONE)
_W2_INV = (_W * _W).inv()
_W3_INV = (_W * _W * _W).inv()


# --- generic curve arithmetic (affine, over any of the fields) ----------------

def ec_add(p1, p2, zero=None):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if y1 == y2:
            return ec_double(p1)
        return None  # P + (-P)
    lam = (y2 - y1) * _inv_of(x2 - x1)
    if isinstance(x1, int):
        lam %= Q
        x3 = (lam * lam - x1 - x2) % Q
        return (x3, (lam * (x1 - x3) - y1) % Q)
    x3 = lam * lam - x1 - x2
    return (x3, lam * (x1 - x3) - y1)


def ec_double(p):
    if p is None:
        return None
    x, y = p
    lam = 3 * (x * x) * _inv_of(2 * y) if isinstance(x, int) else \
        (x * x * 3) * _inv_of(y * 2)
    if isinstance(x, int):
        lam %= Q
    x3 = lam * lam - x - x
    if isinstance(x, int):
        x3 %= Q
        return (x3, (lam * (x - x3) - y) % Q)
    return (x3, lam * (x - x3) - y)


def _inv_of(v):
    if isinstance(v, int):
        return fq_inv(v % Q)
    return v.inv()


def ec_mul(p, k: int):
    out = None
    add = p
    while k:
        if k & 1:
            out = ec_add(out, add)
        add = ec_double(add)
        k >>= 1
    return out


def ec_neg(p):
    if p is None:
        return None
    x, y = p
    return (x, (-y) % Q if isinstance(y, int) else -y)


def g1_on_curve(p) -> bool:
    if p is None:
        return True
    x, y = p
    return (y * y - x * x * x - 4) % Q == 0


def g2_on_curve(p) -> bool:
    if p is None:
        return True
    x, y = p
    return y.sq() - x.sq() * x == Fq2(4, 4)


def subgroup_check_g1(p) -> bool:
    return g1_on_curve(p) and ec_mul(p, R) is None


def subgroup_check_g2(p) -> bool:
    return g2_on_curve(p) and ec_mul(p, R) is None


# --- pairing ------------------------------------------------------------------

def _untwist(q):
    """E'(Fq2) -> E(Fq12): (x, y) -> (x/w^2, y/w^3)."""
    x, y = q
    return (fq2_to_fq12(x) * _W2_INV, fq2_to_fq12(y) * _W3_INV)


def _line(a, b, px, py) -> Fq12:
    """Line through a, b (E(Fq12) points) evaluated at (px, py)."""
    xa, ya = a
    xb, yb = b
    if not (xa == xb):
        lam = (yb - ya) * (xb - xa).inv()
        return (px - xa) * lam - (py - ya)
    if ya == yb:
        lam = (xa * xa * Fq12(Fq6(Fq2(3), FQ2_ZERO, FQ2_ZERO), FQ6_ZERO)) \
            * (ya + ya).inv()
        return (px - xa) * lam - (py - ya)
    return px - xa


def miller_loop(q_twisted, p_g1) -> Fq12:
    """Ate Miller loop for e(P, Q) with P in G1, Q in G2 (twisted coords)."""
    if q_twisted is None or p_g1 is None:
        return FQ12_ONE
    qx, qy = _untwist(q_twisted)
    px = fq2_to_fq12(Fq2(p_g1[0]))
    py = fq2_to_fq12(Fq2(p_g1[1]))
    r_pt = (qx, qy)
    f = FQ12_ONE
    for bit in bin(BLS_X)[3:]:
        f = f * f * _line(r_pt, r_pt, px, py)
        r_pt = _ec12_double(r_pt)
        if bit == "1":
            f = f * _line(r_pt, (qx, qy), px, py)
            r_pt = _ec12_add(r_pt, (qx, qy))
    # BLS parameter t is negative: conjugate
    return f.conj()


def _ec12_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if y1 == y2:
            return _ec12_double(p1)
        return None
    lam = (y2 - y1) * (x2 - x1).inv()
    x3 = lam * lam - x1 - x2
    return (x3, lam * (x1 - x3) - y1)


def _ec12_double(p):
    x, y = p
    three = Fq12(Fq6(Fq2(3), FQ2_ZERO, FQ2_ZERO), FQ6_ZERO)
    lam = (x * x * three) * (y + y).inv()
    x3 = lam * lam - x - x
    return (x3, lam * (x - x3) - y)


_FINAL_EXP = (Q**12 - 1) // R


def pairing(p_g1, q_g2) -> Fq12:
    """e(P, Q) for P in G1 (affine ints), Q in G2 (affine Fq2)."""
    return miller_loop(q_g2, p_g1).pow(_FINAL_EXP)


def pairings_equal(pairs_a, pairs_b) -> bool:
    """Check prod e(a) == prod e(b) via one final exponentiation."""
    f = FQ12_ONE
    for p, q in pairs_a:
        f = f * miller_loop(q, p)
    for p, q in pairs_b:
        f = f * miller_loop(ec_neg_g2(q), p)
    return f.pow(_FINAL_EXP).is_one()


def ec_neg_g2(q):
    if q is None:
        return None
    x, y = q
    return (x, -y)


# --- hash to G2 (try-and-increment + cofactor clearing) -----------------------

_G2_DST = b"blsg2"


def _g2_cache_path(message: bytes, dst: bytes):
    """Cache file for one (message, dst) pair, or None when the
    ``POS_G2_CACHE_DIR`` knob is unset (the default: no disk IO)."""
    cache_dir = os.environ.get("POS_G2_CACHE_DIR")
    if not cache_dir:
        return None
    key = hashlib.sha256(b"g2cache-v1\x00" + dst + b"\x00" + message)
    return os.path.join(cache_dir, f"g2_{key.hexdigest()}.bin")


def _g2_cache_load(path: str):
    """Stored point, or None on miss/corruption (caller recomputes)."""
    try:
        with open(path, "rb") as fh:
            raw = fh.read()
    except OSError:
        return None
    if len(raw) != 192:
        return None
    a, b, c, d = (int.from_bytes(raw[i:i + 48], "big")
                  for i in range(0, 192, 48))
    point = (Fq2(a, b), Fq2(c, d))
    if max(a, b, c, d) >= Q or not g2_on_curve(point):
        return None
    return point


def _g2_cache_store(path: str, point) -> None:
    """Atomic tmp+rename write; cache misses must never break signing."""
    x, y = point
    raw = b"".join(v.to_bytes(48, "big") for v in (x.a, x.b, y.a, y.b))
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as fh:
            fh.write(raw)
        os.replace(tmp, path)
    except OSError:
        pass


def hash_to_g2(message: bytes, dst: bytes = _G2_DST):
    """Deterministic map to the r-torsion of E'(Fq2).

    NOT the IETF SSWU ciphersuite; a sound simple construction for the
    simulator: derive x candidates from H(dst || message || ctr), solve
    y^2 = x^3 + 4(u+1), clear the cofactor.

    The cofactor clearing is the dominant cost (~a full-width ec_mul),
    so results are disk-cached keyed on (message, dst) when the
    ``POS_G2_CACHE_DIR`` environment knob names a directory — repeated
    runs over the same message population (chaos episodes, CI smoke
    jobs) skip straight to the stored point. Corrupt or truncated
    cache entries fail closed into recomputation.
    """
    path = _g2_cache_path(message, dst)
    if path is not None:
        cached = _g2_cache_load(path)
        if cached is not None:
            return cached
    ctr = 0
    while True:
        seed = hashlib.sha256(dst + message + ctr.to_bytes(4, "little"))
        d0 = seed.digest()
        d1 = hashlib.sha256(d0).digest()
        d2 = hashlib.sha256(d1).digest()
        x = Fq2(int.from_bytes(d0 + d1[:16], "big"),
                int.from_bytes(d1[16:] + d2, "big"))
        rhs = x.sq() * x + Fq2(4, 4)
        y = fq2_sqrt(rhs)
        if y is not None:
            # canonical sign
            if y.a % 2 == 1:
                y = -y
            point = ec_mul((x, y), G2_COFACTOR)
            if point is not None:
                if path is not None:
                    _g2_cache_store(path, point)
                return point
        ctr += 1


# --- serialization (ZCash-style compressed points) ----------------------------

_FLAG_COMPRESSED = 1 << 383
_FLAG_INFINITY = 1 << 382
_FLAG_SIGN = 1 << 381


def _y_is_large(y: int) -> bool:
    return y > (Q - 1) // 2


def g1_compress(p) -> bytes:
    if p is None:
        return ((_FLAG_COMPRESSED | _FLAG_INFINITY) >> 0).to_bytes(48, "big")
    x, y = p
    bits = x | _FLAG_COMPRESSED | (_FLAG_SIGN if _y_is_large(y) else 0)
    return bits.to_bytes(48, "big")


def g1_decompress(data: bytes):
    bits = int.from_bytes(data, "big")
    if bits & _FLAG_INFINITY:
        return None
    sign_large = bool(bits & _FLAG_SIGN)
    x = bits & ((1 << 381) - 1)
    y2 = (pow(x, 3, Q) + 4) % Q
    y = pow(y2, (Q + 1) // 4, Q)
    if (y * y) % Q != y2:
        raise ValueError("invalid G1 point")
    if _y_is_large(y) != sign_large:
        y = Q - y
    return (x, y)


def g2_compress(p) -> bytes:
    if p is None:
        hi = (_FLAG_COMPRESSED | _FLAG_INFINITY).to_bytes(48, "big")
        return hi + b"\x00" * 48
    x, y = p
    # sign flag: y lexicographically greater than -y (compare (b, a))
    sign_large = (y.b, y.a) > ((Q - y.b) % Q, (Q - y.a) % Q)
    hi = x.b | _FLAG_COMPRESSED | (_FLAG_SIGN if sign_large else 0)
    return hi.to_bytes(48, "big") + x.a.to_bytes(48, "big")


def g2_decompress(data: bytes):
    hi = int.from_bytes(data[:48], "big")
    if hi & _FLAG_INFINITY:
        return None
    sign_large = bool(hi & _FLAG_SIGN)
    x = Fq2(int.from_bytes(data[48:], "big"), hi & ((1 << 381) - 1))
    y = fq2_sqrt(x.sq() * x + Fq2(4, 4))
    if y is None:
        raise ValueError("invalid G2 point")
    if ((y.b, y.a) > ((Q - y.b) % Q, (Q - y.a) % Q)) != sign_large:
        y = -y
    return (x, y)


# --- the BLS signature scheme (min-pubkey-size) -------------------------------

class PyBLS:
    """Real BLS12-381 backend with the crypto/bls.py interface."""

    name = "bls12_381"

    @staticmethod
    def SkToPk(sk: int) -> bytes:
        return g1_compress(ec_mul(G1_GEN, sk % R))

    @staticmethod
    def Sign(sk: int, message: bytes) -> bytes:
        return g2_compress(ec_mul(hash_to_g2(bytes(message)), sk % R))

    @staticmethod
    def Verify(pubkey: bytes, message: bytes, signature: bytes) -> bool:
        try:
            pk = g1_decompress(bytes(pubkey))
            sig = g2_decompress(bytes(signature))
        except ValueError:
            return False
        if pk is None or sig is None or not subgroup_check_g2(sig):
            return False
        h = hash_to_g2(bytes(message))
        # e(pk, H(m)) == e(g1, sig)
        return pairings_equal([(pk, h)], [(G1_GEN, sig)])

    @staticmethod
    def Aggregate(signatures) -> bytes:
        acc = None
        for s in signatures:
            acc = ec_add(acc, g2_decompress(bytes(s)))
        return g2_compress(acc)

    @staticmethod
    def AggregatePKs(pubkeys) -> bytes:
        acc = None
        for pk in pubkeys:
            acc = ec_add(acc, g1_decompress(bytes(pk)))
        return g1_compress(acc)

    @classmethod
    def FastAggregateVerify(cls, pubkeys, message: bytes, signature: bytes) -> bool:
        if not pubkeys:
            return False
        return cls.Verify(cls.AggregatePKs(pubkeys), message, signature)

    @classmethod
    def AggregateVerify(cls, pubkeys, messages, signature: bytes) -> bool:
        if not pubkeys or len(pubkeys) != len(messages):
            return False
        try:
            sig = g2_decompress(bytes(signature))
        except ValueError:
            return False
        if sig is None or not subgroup_check_g2(sig):
            return False
        pairs = [(g1_decompress(bytes(pk)), hash_to_g2(bytes(m)))
                 for pk, m in zip(pubkeys, messages)]
        return pairings_equal(pairs, [(G1_GEN, sig)])
