"""BLS12-381 signature interface with pluggable backends (component N1).

The reference assumes a BLS library throughout: ``bls.Verify`` for deposits
(pos-evolution.md:165), aggregate signatures over ``aggregation_bits``
(:714-717), and sync aggregates (:642). Mirroring the pyspec bls-setting
toggle (SURVEY.md §4.4a), we expose one interface with two backends:

- ``FakeBLS`` (default): deterministic hash-based scheme. "Signatures" are
  sha256 commitments to (pubkey, message); aggregation is XOR, so aggregate
  verification is order-independent and batched. Protocol-logic tests run
  against this.
- ``PyBLS`` (crypto/bls12_381.py): a real BLS12-381 pairing implementation
  used as the correctness oracle for the native/TPU kernels.

Keys: a validator's secret key is an integer; ``FakeBLS`` pubkeys are 48-byte
digests of the secret key, matching the real key-size layout.
"""

from __future__ import annotations

import hashlib
from typing import Sequence

import numpy as np

__all__ = ["bls", "FakeBLS", "set_bls_backend", "get_bls_backend"]


def _xor_bytes(parts: Sequence[bytes], size: int) -> bytes:
    acc = np.zeros(size, dtype=np.uint8)
    for p in parts:
        acc ^= np.frombuffer(p, dtype=np.uint8)
    return acc.tobytes()


class FakeBLS:
    """Deterministic stand-in scheme preserving the BLS API shape.

    sign(sk, msg)            = H(pubkey(sk) || msg) expanded to 96 bytes
    Aggregate(sigs)          = XOR of signatures
    FastAggregateVerify      = XOR of individual expected signatures == agg
    """

    name = "fake"

    @staticmethod
    def SkToPk(sk: int) -> bytes:
        h = hashlib.sha256(b"fakebls-pk" + int(sk).to_bytes(32, "little")).digest()
        return (h + h[:16])  # 48 bytes

    # 16-byte prefix: the first SHA-256 block of (prefix | pubkey) is then
    # exactly 64 bytes and depends only on the pubkey, so the TPU batch
    # kernel (ops/aggregation.py) precomputes it once per validator.
    SIG_PREFIX = b"fakebls-sig-pad!"

    @staticmethod
    def _sig_for(pubkey: bytes, message: bytes) -> bytes:
        h1 = hashlib.sha256(FakeBLS.SIG_PREFIX + pubkey + message).digest()
        h2 = hashlib.sha256(h1).digest()
        h3 = hashlib.sha256(h2).digest()
        return h1 + h2 + h3  # 96 bytes

    @classmethod
    def Sign(cls, sk: int, message: bytes) -> bytes:
        return cls._sig_for(cls.SkToPk(sk), message)

    @classmethod
    def Verify(cls, pubkey: bytes, message: bytes, signature: bytes) -> bool:
        return signature == cls._sig_for(bytes(pubkey), bytes(message))

    @classmethod
    def Aggregate(cls, signatures: Sequence[bytes]) -> bytes:
        if not signatures:
            raise ValueError("cannot aggregate zero signatures")
        return _xor_bytes(signatures, 96)

    @classmethod
    def AggregatePKs(cls, pubkeys: Sequence[bytes]) -> bytes:
        return _xor_bytes(pubkeys, 48)

    @classmethod
    def FastAggregateVerify(cls, pubkeys: Sequence[bytes], message: bytes,
                            signature: bytes) -> bool:
        """All pubkeys signed the same message (attestation aggregation)."""
        if not pubkeys:
            return False
        expected = _xor_bytes([cls._sig_for(bytes(pk), bytes(message)) for pk in pubkeys], 96)
        return expected == bytes(signature)

    @classmethod
    def AggregateVerify(cls, pubkeys: Sequence[bytes], messages: Sequence[bytes],
                        signature: bytes) -> bool:
        if not pubkeys or len(pubkeys) != len(messages):
            return False
        expected = _xor_bytes(
            [cls._sig_for(bytes(pk), bytes(m)) for pk, m in zip(pubkeys, messages)], 96)
        return expected == bytes(signature)


class _Dispatch:
    """`bls` module-like object the spec code calls into (pos-evolution.md:165)."""

    def __init__(self):
        self._backend = FakeBLS

    def __getattr__(self, item):
        return getattr(self._backend, item)


bls = _Dispatch()


def set_bls_backend(backend) -> None:
    bls._backend = backend


def get_bls_backend():
    return bls._backend
