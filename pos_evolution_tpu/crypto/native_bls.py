"""ctypes bindings for the native C++ BLS12-381 (component N1).

``NativeBLS`` implements the same interface as ``FakeBLS``/``PyBLS``
(crypto/bls.py) over ``native/build/libbls12381.so`` — differential tests
pin it byte-identical to the pure-Python oracle. Use via
``set_bls_backend(NativeBLS)``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from functools import lru_cache
from typing import Sequence

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), os.pardir, "native")
_LIB_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "build", "libbls12381.so"))


@lru_cache(maxsize=1)
def _load():
    if not os.path.exists(_LIB_PATH):
        try:
            subprocess.run(["make", "-C", os.path.abspath(_NATIVE_DIR)], check=True,
                           capture_output=True, timeout=180)
        except Exception:
            return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.bls_sk_to_pk.argtypes = [u8p, u8p]
    lib.bls_sign.argtypes = [u8p, u8p, ctypes.c_uint64, u8p]
    lib.bls_verify.argtypes = [u8p, u8p, ctypes.c_uint64, u8p]
    lib.bls_verify.restype = ctypes.c_int
    lib.bls_aggregate.argtypes = [u8p, ctypes.c_uint64, u8p]
    lib.bls_aggregate.restype = ctypes.c_int
    lib.bls_aggregate_pks.argtypes = [u8p, ctypes.c_uint64, u8p]
    lib.bls_aggregate_pks.restype = ctypes.c_int
    lib.bls_fast_aggregate_verify.argtypes = [
        u8p, ctypes.c_uint64, u8p, ctypes.c_uint64, u8p]
    lib.bls_fast_aggregate_verify.restype = ctypes.c_int
    return lib


def available() -> bool:
    return _load() is not None


def _buf(b: bytes):
    return (ctypes.c_uint8 * len(b)).from_buffer_copy(bytes(b))


class NativeBLS:
    """Real BLS12-381 via the C++ core; byte-identical to crypto/bls12_381."""

    name = "bls12_381_native"

    @staticmethod
    def SkToPk(sk: int) -> bytes:
        out = (ctypes.c_uint8 * 48)()
        _load().bls_sk_to_pk(_buf((sk % _R).to_bytes(32, "big")), out)
        return bytes(out)

    @staticmethod
    def Sign(sk: int, message: bytes) -> bytes:
        out = (ctypes.c_uint8 * 96)()
        m = bytes(message)
        _load().bls_sign(_buf((sk % _R).to_bytes(32, "big")), _buf(m), len(m), out)
        return bytes(out)

    @staticmethod
    def Verify(pubkey: bytes, message: bytes, signature: bytes) -> bool:
        m = bytes(message)
        return bool(_load().bls_verify(_buf(bytes(pubkey)), _buf(m), len(m),
                                       _buf(bytes(signature))))

    @staticmethod
    def Aggregate(signatures: Sequence[bytes]) -> bytes:
        if not signatures:
            raise ValueError("cannot aggregate zero signatures")
        out = (ctypes.c_uint8 * 96)()
        flat = b"".join(bytes(s) for s in signatures)
        if not _load().bls_aggregate(_buf(flat), len(signatures), out):
            raise ValueError("invalid signature in aggregate")
        return bytes(out)

    @staticmethod
    def AggregatePKs(pubkeys: Sequence[bytes]) -> bytes:
        out = (ctypes.c_uint8 * 48)()
        flat = b"".join(bytes(p) for p in pubkeys)
        if not _load().bls_aggregate_pks(_buf(flat), len(pubkeys), out):
            raise ValueError("invalid pubkey in aggregate")
        return bytes(out)

    @staticmethod
    def FastAggregateVerify(pubkeys: Sequence[bytes], message: bytes,
                            signature: bytes) -> bool:
        if not pubkeys:
            return False
        flat = b"".join(bytes(p) for p in pubkeys)
        m = bytes(message)
        return bool(_load().bls_fast_aggregate_verify(
            _buf(flat), len(pubkeys), _buf(m), len(m), _buf(bytes(signature))))

    @classmethod
    def AggregateVerify(cls, pubkeys, messages, signature: bytes) -> bool:
        # distinct-message verify stays on the Python oracle (rarely used)
        from pos_evolution_tpu.crypto.bls12_381 import PyBLS
        return PyBLS.AggregateVerify(pubkeys, messages, signature)


_R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
