"""Crypto primitives (L0): BLS signature interface + backends."""

from pos_evolution_tpu.crypto.bls import FakeBLS, bls, get_bls_backend, set_bls_backend
