"""Round-based simulation driver (L6): the validator duty loop of
SURVEY.md §3.4 over per-view-group fork-choice stores.

Each slot (3Δ rounds, pos-evolution.md:193, 1536):
  round 0 (propose):   the slot's proposer runs get_head on its view and
                       broadcasts a block (pos-evolution.md:597)
  round 1 (attest):    committee members attest to their view's head
                       (head vote + FFG vote, pos-evolution.md:681-683)
  round 2 (aggregate): aggregation is implicit in the per-committee
                       aggregates (pos-evolution.md:474-475, 1536)

Validators whose messages arrive identically share one ``Store`` (a "view
group") — the adversary's delivery strategy (the ``Schedule``) induces the
partition, so honest runs cost one store and attack runs cost a handful.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field

import numpy as np

from pos_evolution_tpu.config import cfg
from pos_evolution_tpu.specs import forkchoice as fc
from pos_evolution_tpu.specs.genesis import make_genesis
from pos_evolution_tpu.specs.helpers import (
    compute_epoch_at_slot,
    get_beacon_proposer_index,
    get_committee_count_per_slot,
)
from pos_evolution_tpu.specs.validator import (
    advance_state_to_slot,
    build_block,
    make_committee_attestation,
)
from pos_evolution_tpu.sim.schedule import Schedule, honest_schedule
from pos_evolution_tpu.ssz import hash_tree_root


@dataclass(order=True)
class _QueuedMessage:
    time: float
    seq: int
    kind: str = field(compare=False)     # "block" | "attestation" | "slashing"
    payload: object = field(compare=False)


class ViewGroup:
    """One equivalence class of validator views: a Store + a message queue
    + an attestation pool for proposals made from this view."""

    def __init__(self, group_id: int, store: fc.Store, members: np.ndarray,
                 resident=None):
        self.id = group_id
        self.store = store
        self.members = members
        self.queue: list[_QueuedMessage] = []
        self.pool: dict[bytes, object] = {}  # attestation root -> Attestation
        # Attestation roots carried by each processed block (block root ->
        # [attestation roots]): lets proposers dedup against inclusion on
        # the CANONICAL chain only (a walk from their head), the
        # operation-pool behavior of real clients. Without dedup each
        # proposer re-packs the oldest in-window attestations (already
        # on-chain), starving fresh ones once committees/slot x window >
        # max_attestations (n >~ 20K) — which delayed justification a full
        # epoch at 64K validators (r5 scale_demo catch). Keying by block
        # keeps it reorg-correct: votes included only on a losing fork
        # stay packable on the winning one.
        self.block_atts: dict[bytes, list] = {}
        self._seq = 0
        # Device-resident dense mirror (ops/resident.py) when the sim runs
        # accelerated fork choice; handlers below forward their deltas.
        self.resident = resident

    def enqueue(self, time: float, kind: str, payload) -> None:
        heapq.heappush(self.queue, _QueuedMessage(time, self._seq, kind, payload))
        self._seq += 1

    def _mirror_attestation(self, att, indices) -> None:
        if self.resident is not None and indices is not None:
            self.resident.note_attestation(
                indices, int(att.data.target.epoch),
                bytes(att.data.beacon_block_root))

    def deliver_due(self, now: float, timer) -> None:
        track = timer.track
        while self.queue and self.queue[0].time <= now:
            msg = heapq.heappop(self.queue)
            try:
                if msg.kind == "block":
                    # block-carried attestations are part of on_block cost
                    with track("on_block"):
                        fc.on_block(self.store, msg.payload)
                        block_root = hash_tree_root(msg.payload.message)
                        if self.resident is not None:
                            self.resident.note_block(self.store, block_root)
                        carried = []
                        for att in msg.payload.message.body.attestations:
                            carried.append(hash_tree_root(att))
                            try:
                                idx = fc.on_attestation(self.store, att,
                                                        is_from_block=True)
                                self._mirror_attestation(att, idx)
                            except AssertionError:
                                pass
                        self.block_atts[block_root] = carried
                elif msg.kind == "attestation":
                    with track("on_attestation"):
                        idx = fc.on_attestation(self.store, msg.payload)
                        self._mirror_attestation(msg.payload, idx)
                    self.pool[hash_tree_root(msg.payload)] = msg.payload
                elif msg.kind == "slashing":
                    with track("on_attester_slashing"):
                        evil = fc.on_attester_slashing(self.store, msg.payload)
                        if self.resident is not None:
                            self.resident.note_slashing(evil)
            except AssertionError:
                # Invalid-at-this-time messages are dropped (the reference
                # permits re-queueing, pos-evolution.md:967-968; the driver
                # keeps the simple policy).
                continue


class Simulation:
    """Round-based multi-validator simulation over a Schedule."""

    def __init__(self, n_validators: int, schedule: Schedule | None = None,
                 genesis_time: int = 0, accelerated_forkchoice: bool = False):
        self.cfg = cfg()
        self.schedule = schedule or honest_schedule(n_validators)
        state, anchor = make_genesis(n_validators, genesis_time)
        self.genesis_state = state
        self.anchor_root = hash_tree_root(anchor)
        # One PoW-chain view per Simulation (shared by its groups — the PoW
        # chain is objective): merge-transition state never leaks between
        # Simulation instances in the same process.
        from pos_evolution_tpu.specs.merge import PowChainView
        self.pow_chain = PowChainView()
        def _make_group(g):
            store = fc.get_forkchoice_store(state, anchor,
                                            pow_chain=self.pow_chain)
            resident = None
            if accelerated_forkchoice:
                from pos_evolution_tpu.ops.resident import ResidentForkChoice
                resident = ResidentForkChoice(store)
            return ViewGroup(g, store, self.schedule.members(g), resident)

        self.groups = [_make_group(g) for g in range(self.schedule.n_groups)]
        self.slot = 0
        self.metrics: list[dict] = []
        # Device fork choice: every head query runs on the persistent
        # device store (ops/resident.py) — incremental bucket updates as
        # messages arrive, O(B log B) head_from_buckets per query, no
        # per-query host rebuild — differential-equal to the spec walk by
        # test_resident.py / test_dense_forkchoice.py.
        self.accelerated_forkchoice = accelerated_forkchoice
        # Per-handler tracing (SURVEY.md §5): wall-clock p50/p95 for
        # get_head / on_block / on_attestation via utils.metrics.
        from pos_evolution_tpu.utils.metrics import HandlerTimer
        self.timer = HandlerTimer()

    def _get_head(self, group: ViewGroup) -> bytes:
        with self.timer.track("get_head"):
            if group.resident is not None:
                return group.resident.head(group.store)
            return fc.get_head(group.store)

    def trace_summary(self) -> dict:
        """Per-handler timing percentiles for this run."""
        return self.timer.summary()

    # -- time helpers --
    def slot_start(self, slot: int) -> int:
        return slot * self.cfg.seconds_per_slot

    @property
    def delta(self) -> int:
        return self.cfg.seconds_per_slot // self.cfg.intervals_per_slot

    def _tick_all(self, time: float) -> None:
        for g in self.groups:
            fc.on_tick(g.store, int(time))
            g.deliver_due(time, timer=self.timer)

    # -- duties --
    def _head_state(self, group: ViewGroup, slot: int):
        head = self._get_head(group)
        return head, advance_state_to_slot(group.store.block_states[head], slot)

    def _propose(self, slot: int) -> None:
        t0 = self.slot_start(slot)
        proposed: set[int] = set()
        for group in self.groups:
            head, head_state = self._head_state(group, slot)
            proposer = get_beacon_proposer_index(head_state)
            if proposer in proposed:
                continue
            if proposer not in set(int(v) for v in group.members):
                continue
            if int(proposer) in self.schedule.corrupted:
                continue  # Byzantine proposers act via attack scripts
            round_index = slot * self.cfg.intervals_per_slot
            if not self.schedule.awake(round_index, int(proposer)):
                continue
            proposed.add(proposer)
            atts = self._pack_attestations(group, slot, head)
            sb = build_block(group.store.block_states[head], slot, attestations=atts)
            for dst in self.groups:
                delay = self.schedule.block_delay(int(proposer), slot, dst.id)
                if delay is None:
                    continue
                dst.enqueue(t0 + delay, "block", sb)

    def _pack_attestations(self, group: ViewGroup, slot: int,
                           head: bytes) -> list:
        c = self.cfg
        # inclusion set of the proposer's CANONICAL chain, within the
        # attestation window: walk head ancestry while blocks are recent
        # enough to carry still-packable attestations
        onchain: set[bytes] = set()
        b = head
        while b in group.store.blocks:
            blk = group.store.blocks[b]
            if int(blk.slot) + c.slots_per_epoch < slot:
                break
            onchain.update(group.block_atts.get(b, ()))
            b = bytes(blk.parent_root)
        out = []
        expired = []
        for root, att in group.pool.items():
            a_slot = int(att.data.slot)
            if slot > a_slot + c.slots_per_epoch:
                expired.append(root)           # prune: bounds the pool
                continue
            if a_slot + c.min_attestation_inclusion_delay > slot:
                continue
            if root in onchain:
                continue                       # already on this chain
            if len(out) < c.max_attestations:
                out.append(att)
        for root in expired:
            del group.pool[root]
        return out

    def _attest(self, slot: int) -> None:
        t_next = self.slot_start(slot + 1)
        for group in self.groups:
            head, head_state = self._head_state(group, slot)
            honest = set(int(v) for v in self.schedule.honest_members(group.id))
            if not honest:
                continue
            round_index = slot * self.cfg.intervals_per_slot + 1
            awake = set(v for v in honest if self.schedule.awake(round_index, v))
            if not awake:
                continue
            count = get_committee_count_per_slot(head_state, compute_epoch_at_slot(slot))
            for index in range(count):
                try:
                    att = make_committee_attestation(
                        head_state, slot, index, head,
                        participants=np.array(sorted(awake), dtype=np.int64))
                except ValueError:
                    continue  # no awake member in this committee
                for dst in self.groups:
                    delay = self.schedule.attestation_delay(group.id, slot, dst.id)
                    if delay is None:
                        continue
                    dst.enqueue(t_next + delay, "attestation", att)

    # -- main loop --
    def run_slot(self) -> None:
        slot = self.slot
        t0 = self.slot_start(slot)
        self._tick_all(t0)
        if slot > 0:
            self._propose(slot)
            self._tick_all(t0 + 1)  # timely blocks land within the boost window
            self._tick_all(t0 + self.delta)
            self._attest(slot)
            self._tick_all(t0 + 2 * self.delta)
        self._record_metrics(slot)
        self.slot += 1

    def run_until_slot(self, slot: int) -> None:
        while self.slot <= slot:
            self.run_slot()

    def run_epochs(self, n_epochs: int) -> None:
        self.run_until_slot(n_epochs * self.cfg.slots_per_epoch)

    # -- observability (SURVEY.md §5: structured per-slot log) --
    def _record_metrics(self, slot: int) -> None:
        g0 = self.groups[0].store
        head = self._get_head(self.groups[0])
        self.metrics.append({
            "slot": slot,
            "head": head.hex()[:8],
            "head_slot": int(g0.blocks[head].slot),
            "justified_epoch": int(g0.justified_checkpoint.epoch),
            "finalized_epoch": int(g0.finalized_checkpoint.epoch),
            "n_blocks": len(g0.blocks),
            "equivocators": len(g0.equivocating_indices),
        })

    # -- accessors --
    def store(self, group: int = 0) -> fc.Store:
        return self.groups[group].store

    def finalized_epoch(self, group: int = 0) -> int:
        return int(self.groups[group].store.finalized_checkpoint.epoch)

    def justified_epoch(self, group: int = 0) -> int:
        return int(self.groups[group].store.justified_checkpoint.epoch)
