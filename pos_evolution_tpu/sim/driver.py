"""Round-based simulation driver (L6): the validator duty loop of
SURVEY.md §3.4 over per-view-group fork-choice stores.

Each slot (3Δ rounds, pos-evolution.md:193, 1536):
  round 0 (propose):   the slot's proposer runs get_head on its view and
                       broadcasts a block (pos-evolution.md:597)
  round 1 (attest):    committee members attest to their view's head
                       (head vote + FFG vote, pos-evolution.md:681-683)
  round 2 (aggregate): aggregation is implicit in the per-committee
                       aggregates (pos-evolution.md:474-475, 1536)

Validators whose messages arrive identically share one ``Store`` (a "view
group") — the adversary's delivery strategy (the ``Schedule``) induces the
partition, so honest runs cost one store and attack runs cost a handful.
"""

from __future__ import annotations

import heapq
import time as _time
from dataclasses import dataclass, field

import numpy as np

from pos_evolution_tpu.config import cfg
from pos_evolution_tpu.specs import forkchoice as fc
from pos_evolution_tpu.specs.genesis import make_genesis
from pos_evolution_tpu.specs.helpers import (
    compute_epoch_at_slot,
    get_beacon_proposer_index,
    get_committee_count_per_slot,
)
from pos_evolution_tpu.specs.validator import (
    advance_state_to_slot,
    build_block,
    make_committee_attestation,
    make_sync_aggregate,
)
from pos_evolution_tpu.sim.schedule import Schedule, honest_schedule
from pos_evolution_tpu.ssz import cached_root, hash_tree_root


@dataclass(order=True)
class _QueuedMessage:
    time: float
    seq: int
    kind: str = field(compare=False)     # "block" | "attestation" | "slashing"
    payload: object = field(compare=False)
    # telemetry lineage: the gossip-edge span this copy belongs to (None
    # when telemetry is off or after resume — spans are not sim state)
    span: str | None = field(compare=False, default=None)


def _span_id(kind: str, slot: int, src: int, msg_id: int) -> str:
    """Deterministic message-span identity: the same run always names the
    same spans (no uuids), so lineage is replayable and test-pinnable.
    Honest proposals use msg_id 0 and keep their historical span names;
    an adversarial double proposal (sim/adversary.Equivocator) needs the
    msg_id suffix to keep the two conflicting blocks' spans distinct."""
    if kind == "block":
        return f"blk-{slot}-{src}" if msg_id == 0 \
            else f"blk-{slot}-{src}-{msg_id}"
    if kind == "attestation":
        return f"att-{slot}-g{src}-c{msg_id}"
    return f"{kind}-{slot}-{src}-{msg_id}"


_HANDLER_OF = {"block": "on_block", "attestation": "on_attestation",
               "slashing": "on_attester_slashing",
               "blob": "on_blob_sidecar"}


class ViewGroup:
    """One equivalence class of validator views: a Store + a message queue
    + an attestation pool for proposals made from this view."""

    def __init__(self, group_id: int, store: fc.Store, members: np.ndarray,
                 resident=None, telemetry=None):
        self.id = group_id
        self.store = store
        self.members = members
        # Telemetry (pos_evolution_tpu/telemetry): when attached, every
        # delivery emits a lifecycle event; when its debug flag is set,
        # every handler call runs under the StoreInvariantChecker
        # (failed-handler-must-not-mutate, pos-evolution.md:1041).
        self.telemetry = telemetry
        self.invariants = None
        if telemetry is not None and telemetry.debug:
            from pos_evolution_tpu.utils.metrics import StoreInvariantChecker
            self.invariants = StoreInvariantChecker(store)
        # Crash-fault state (sim/faults.py CrashWindow): a crashed group
        # processes nothing and receives nothing until it rejoins via
        # weak-subjectivity checkpoint sync. Always recomputable from the
        # FaultPlan and the current slot (never serialized).
        self.crashed = False
        self.queue: list[_QueuedMessage] = []
        self.pool: dict[bytes, object] = {}  # attestation root -> Attestation
        # Attestation roots carried by each processed block (block root ->
        # [attestation roots]): lets proposers dedup against inclusion on
        # the CANONICAL chain only (a walk from their head), the
        # operation-pool behavior of real clients. Without dedup each
        # proposer re-packs the oldest in-window attestations (already
        # on-chain), starving fresh ones once committees/slot x window >
        # max_attestations (n >~ 20K) — which delayed justification a full
        # epoch at 64K validators (r5 scale_demo catch). Keying by block
        # keeps it reorg-correct: votes included only on a losing fork
        # stay packable on the winning one.
        self.block_atts: dict[bytes, list] = {}
        self._seq = 0
        # Device-resident dense mirror (ops/resident.py) when the sim runs
        # accelerated fork choice; handlers below forward their deltas.
        self.resident = resident
        # DAS availability view (das/engine.BlobStore) when the sim runs a
        # blob workload; also attached to ``store.blob_store`` so on_block
        # gates imports on verified sidecars (DESIGN.md §15).
        self.blob_store = None
        # Protocol-variant overlay (variants/base.VariantVoteLog) when a
        # successor variant drives the run; mirrored on
        # ``store.variant_view`` so the handlers feed it (DESIGN.md §16).
        self.variant_view = None

    def enqueue(self, time: float, kind: str, payload,
                span: str | None = None) -> None:
        heapq.heappush(self.queue,
                       _QueuedMessage(time, self._seq, kind, payload, span))
        self._seq += 1

    def _call_handler(self, handler, *args, **kwargs):
        """Route one fork-choice handler call through the debug-gated
        ``StoreInvariantChecker``; a violation (a FAILED handler that
        mutated the store) is surfaced as a telemetry event before the
        assertion propagates to the caller's drop policy."""
        if self.invariants is None:
            return handler(self.store, *args, **kwargs)
        n0 = len(self.invariants.violations)
        try:
            return self.invariants.call(handler, *args, **kwargs)
        except AssertionError:
            if len(self.invariants.violations) > n0:
                self.telemetry.bus.emit(
                    "invariant_violation", group=self.id,
                    handler=getattr(handler, "__name__", str(handler)),
                    detail=self.invariants.violations[-1])
            raise

    def _mirror_attestation(self, att, indices) -> None:
        if self.resident is not None and indices is not None:
            self.resident.note_attestation(
                indices, int(att.data.target.epoch),
                bytes(att.data.beacon_block_root))

    def _process_block(self, signed_block) -> None:
        """One ``on_block`` plus its carried attestations and the resident
        mirror — the gossip-delivery entry (backfilled ancestor runs go
        through ``_process_block_chain``)."""
        block_root = cached_root(signed_block.message)
        if block_root in self.store.blocks:
            # redelivery (FaultPlan duplicate_p, or a backfilled block
            # arriving again via gossip): reprocessing would re-run the
            # state transition AND append a duplicate row to the resident
            # mirror, splitting its vote weights — gossip dedup is part of
            # every real client's pipeline
            return
        self._call_handler(fc.on_block, signed_block)
        self._absorb_block(signed_block, block_root)

    def _process_block_chain(self, signed_blocks) -> None:
        """A parent-linked backfill run through ``fc.on_block_batch`` —
        one carried pre-state, one finalized-descent walk — then absorb
        each committed block's carried ops. A mid-run reject commits the
        prefix exactly like the sequential loop, so absorption walks the
        run until the first uncommitted block even when the batch raises."""
        pending = [sb for sb in signed_blocks
                   if cached_root(sb.message) not in self.store.blocks]
        if not pending:
            return
        try:
            self._call_handler(fc.on_block_batch, pending)
        finally:
            for sb in pending:
                block_root = cached_root(sb.message)
                if block_root not in self.store.blocks:
                    break
                self._absorb_block(sb, block_root)

    def _absorb_block(self, signed_block, block_root: bytes) -> None:
        """Post-``on_block`` bookkeeping: resident-mirror row, block-carried
        attestations, and the carried-root index for op-pool dedup."""
        if self.resident is not None:
            self.resident.note_block(self.store, block_root)
        carried = []
        for att in signed_block.message.body.attestations:
            carried.append(cached_root(att))
            try:
                idx = self._call_handler(fc.on_attestation, att,
                                         is_from_block=True)
                self._mirror_attestation(att, idx)
            except AssertionError:
                # block-carried attestation rejects are counted, not
                # per-event (a block carries up to max_attestations of
                # them; the interesting signal is the rate)
                if self.telemetry is not None:
                    self.telemetry.registry.counter(
                        "carried_attestation_rejects_total",
                        "on_attestation(is_from_block=True) asserts",
                    ).inc(group=self.id)
        self.block_atts[block_root] = carried

    def deliver_due(self, now: float, timer, resolver=None) -> None:
        track = timer.track
        bus = self.telemetry.bus if self.telemetry is not None else None
        while self.queue and self.queue[0].time <= now:
            msg = heapq.heappop(self.queue)
            t0 = _time.perf_counter()
            status, reason = "accept", None
            try:
                if msg.kind == "block":
                    # block-carried attestations are part of on_block cost
                    with track("on_block"):
                        if resolver is not None:
                            resolver(self, msg.payload)
                        self._process_block(msg.payload)
                elif msg.kind == "attestation":
                    with track("on_attestation"):
                        idx = self._call_handler(fc.on_attestation,
                                                 msg.payload)
                        self._mirror_attestation(msg.payload, idx)
                    self.pool[cached_root(msg.payload)] = msg.payload
                elif msg.kind == "slashing":
                    with track("on_attester_slashing"):
                        evil = self._call_handler(fc.on_attester_slashing,
                                                  msg.payload)
                        if self.resident is not None:
                            self.resident.note_slashing(evil)
                elif msg.kind == "blob":
                    # sidecar gossip: verification (commitment recompute +
                    # erasure consistency) happens inside the blob store;
                    # a failed sidecar is a reject, not an exception
                    with track("on_blob_sidecar"):
                        accepted = (self.blob_store is not None
                                    and self.blob_store.on_sidecar(
                                        msg.payload))
                    if not accepted:
                        status = "reject"
                        reason = "sidecar failed verification"
            except AssertionError as e:
                # Invalid-at-this-time messages are dropped (the reference
                # permits re-queueing, pos-evolution.md:967-968; the driver
                # keeps the simple policy). Pre-anchor walks in a
                # checkpoint-synced view land here too via the handlers'
                # own asserts (get_ancestor clamps to the anchor instead
                # of raising, so a genuine KeyError stays a loud bug).
                status = "reject"
                reason = (str(e) or "assertion failed")[:200]
            if bus is not None:
                handler = _HANDLER_OF[msg.kind]
                extra = {"reason": reason} if reason is not None else {}
                bus.emit(
                    "deliver",
                    span=(f"{msg.span}/d{self.id}" if msg.span else None),
                    parent=msg.span, group=self.id, kind=msg.kind,
                    handler=handler, t=msg.time, status=status,
                    duration_ms=round((_time.perf_counter() - t0) * 1e3, 4),
                    **extra)
                self.telemetry.registry.counter(
                    "handler_calls_total",
                    "fork-choice handler invocations from delivery",
                ).inc(handler=handler, status=status)


class Simulation:
    """Round-based multi-validator simulation over a Schedule."""

    def __init__(self, n_validators: int, schedule: Schedule | None = None,
                 genesis_time: int = 0, accelerated_forkchoice: bool = False,
                 telemetry=None, profile=None, adversaries=(), monitors=(),
                 das=None, prewarm: bool = False, compile_cache=None,
                 variant=None, sharded=None, autocheckpoint=None,
                 serve=None):
        self.cfg = cfg()
        self.schedule = schedule or honest_schedule(n_validators)
        self.n_validators = n_validators
        self.genesis_time = genesis_time
        # Sharded execution (ISSUE 9, DESIGN.md §17): ``sharded`` turns on
        # the jax backend's device-mesh mode BEFORE any resident state is
        # built, so registry columns, the resident fork-choice message
        # table and (optionally) the fused-transition session land sharded
        # over the (pods, shard) validator axes and the hot sweeps run as
        # shard_map kernels. Accepted forms: True (auto mesh over all
        # devices), a (pods, shard) tuple, or a prebuilt Mesh; False
        # explicitly disables a previously enabled mode (the mode is
        # process-global on the backend module); None leaves it untouched.
        # Bit-identity with the single-device path is pinned in
        # tests/test_sharded_e2e.py.
        self.sharded = None
        if sharded is not None:
            from pos_evolution_tpu.backend import get_backend
            backend = get_backend()
            is_jax = getattr(backend, "name", "") == "jax"
            if sharded is False:
                if is_jax:
                    backend.disable_sharded()
            else:
                if not is_jax:
                    raise ValueError(
                        "Simulation(sharded=...) requires the jax backend "
                        "(set_backend('jax'))")
                if sharded is True:
                    mesh = backend.enable_sharded()
                elif isinstance(sharded, tuple):
                    pods, shard = sharded
                    mesh = backend.enable_sharded(int(pods) * int(shard),
                                                  int(pods))
                else:
                    mesh = backend.enable_sharded(mesh=sharded)
                self.sharded = {a: int(s) for a, s in
                                zip(mesh.axis_names, mesh.devices.shape)}
        # Telemetry (pos_evolution_tpu/telemetry.Telemetry): opt-in event
        # bus + metrics registry. NOT simulation state — checkpoint()
        # excludes it (like wall-clock timings); pass it again to resume()
        # to keep recording. Fault attribution flows through the plan's
        # sink: the Simulation OWNS the sink of the plan it runs — set to
        # this run's bus, or cleared when no telemetry is attached — so a
        # reused schedule never leaks fault events onto a previous run's
        # (possibly closed) bus. To use a custom sink without Telemetry,
        # set plan.sink AFTER constructing the Simulation. A plan shared
        # across CONCURRENT sims is not supported (its log would
        # interleave anyway).
        self.telemetry = telemetry
        # Opt-in profiling (pos_evolution_tpu/profiling, ISSUE 4): a
        # directory path. The FIRST top-level run (run_until_slot /
        # run_epochs) is captured under a jax.profiler trace; on completion
        # the directory receives chrome_trace.json (sim spans + device ops,
        # Perfetto-loadable), flame.txt / flame_device.txt (collapsed
        # stacks), and top_ops.json (xplane summary — run_report.py
        # auto-discovers it next to an event log). One capture only:
        # jax.profiler supports a single session, and the first run segment
        # is the one that includes compiles — the honest-timing caveats of
        # utils/benchtime.py apply to any wall-clock read off the trace.
        import os as _os
        self.profile = _os.fspath(profile) if profile is not None else None
        self._profiled = False
        if self.schedule.faults is not None:
            self.schedule.faults.sink = (telemetry.bus
                                         if telemetry is not None else None)
        # Compile hygiene (ROADMAP item 2 remainder): ``compile_cache``
        # points jax's persistent compilation cache at a directory so
        # repeat runs skip XLA backend compiles entirely; ``prewarm``
        # AOT-warms every padded attestation-batch shape of the fused
        # block sweep at init, so the epoch 2-3 get_head tail no longer
        # absorbs compile storms as new shapes appear mid-run (pinned via
        # the jax_backend_compiles_total counter in tests/test_das.py).
        if compile_cache is not None:
            import os as _os2

            import jax as _jax
            _jax.config.update("jax_compilation_cache_dir",
                               _os2.fspath(compile_cache))
            try:
                _jax.config.update(
                    "jax_persistent_cache_min_entry_size_bytes", -1)
                _jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 0.0)
            except Exception:
                pass  # knob names drift across jax versions; dir is enough
        state, anchor = make_genesis(n_validators, genesis_time)
        self.genesis_state = state
        self.anchor_root = hash_tree_root(anchor)
        # Protocol variant (variants/, ROADMAP item 5, DESIGN.md §16):
        # the fork-choice + finality rules the driver dispatches through.
        # Default GasperVariant is behavior-identical to the pre-seam
        # driver (pinned in tests/test_variant_seam.py); Goldfish /
        # RLMD-GHOST / SSF attach per-view vote overlays to every store.
        # Like the schedule, the variant is passed again to ``resume``
        # (or rebuilt from the checkpoint's describe() fingerprint).
        if variant is None:
            from pos_evolution_tpu.variants import GasperVariant
            variant = GasperVariant()
        self.variant = variant
        self.variant.bind(self)
        if prewarm:
            from pos_evolution_tpu.backend import get_backend
            if getattr(get_backend(), "name", "") == "jax":
                from pos_evolution_tpu.ops.transition import (
                    prewarm_block_sweep,
                )
                prewarm_block_sweep(state)
        # DAS blob workload (das/, DESIGN.md §15): ``das`` is a
        # das.engine.BlobEngine (or True for the default one). Proposals
        # then carry blob sidecars, every view group runs an availability
        # store gating on_block imports, and ``attach_das_clients`` hangs
        # a sampling population off the serving group. Like the schedule,
        # the engine is passed again to ``resume`` (sidecar payloads are
        # seeded pure functions of the chain, so a resumed run
        # regenerates them bit-identically).
        if das is True or isinstance(das, str):
            # das="kzg"/"merkle" picks the cell-commitment scheme
            # (das/commitment.py registry); True keeps the default
            from pos_evolution_tpu.das import BlobEngine
            das = BlobEngine(scheme="merkle" if das is True else das)
        self.das = das
        self.blob_archive: dict[bytes, list] = {}
        self.das_server = None
        self.das_population = None
        self._das_group = 0
        self._das_window = 2
        # Live serving tier (serve/, DESIGN.md §19): ``serve`` turns on
        # per-slot publication of an immutable ``ServeView`` (head +
        # finality scalars, the pre-serialized best light-client update,
        # the DAS window's sidecars) into a ``ServingState`` that a
        # socket-facing ``serve.ServeFront`` — live in this process, or
        # replaying the recorded view history on a wall-clock schedule —
        # reads atomically. Accepts True (fresh recording state) or an
        # existing ``ServingState``. Not simulation state: checkpoints
        # exclude it, a resumed run re-attaches.
        self.serving_state = None
        if serve:
            from pos_evolution_tpu.serve import ServingState
            self.serving_state = (serve if isinstance(serve, ServingState)
                                  else ServingState(keep_history=True))
        # One PoW-chain view per Simulation (shared by its groups — the PoW
        # chain is objective): merge-transition state never leaks between
        # Simulation instances in the same process.
        from pos_evolution_tpu.specs.merge import PowChainView
        self.pow_chain = PowChainView()
        def _make_group(g):
            store = fc.get_forkchoice_store(state, anchor,
                                            pow_chain=self.pow_chain)
            resident = None
            if accelerated_forkchoice:
                from pos_evolution_tpu.ops.resident import ResidentForkChoice
                resident = ResidentForkChoice(store)
            group = ViewGroup(g, store, self.schedule.members(g), resident,
                              telemetry=telemetry)
            if self.variant.needs_view:
                view = self.variant.make_view(g)
                group.variant_view = view
                store.variant_view = view
            if self.das is not None:
                from pos_evolution_tpu.das import BlobStore
                group.blob_store = BlobStore(
                    self.das,
                    registry=(telemetry.registry if telemetry is not None
                              else None),
                    group=g)
                store.blob_store = group.blob_store
            return group

        self.groups = [_make_group(g) for g in range(self.schedule.n_groups)]
        self.slot = 0
        self.metrics: list[dict] = []
        # Every block ever broadcast, by root — the "some peer has it"
        # pool backing block-by-root req/resp sync (``_sync_ancestors``).
        # Without a catch-up path one dropped block would fork a view
        # PERMANENTLY, making post-GST recovery impossible by
        # construction; real clients re-fetch missing parents.
        self.block_archive: dict[bytes, object] = {}
        # Device fork choice: every head query runs on the persistent
        # device store (ops/resident.py) — incremental bucket updates as
        # messages arrive, O(B log B) head_from_buckets per query, no
        # per-query host rebuild — differential-equal to the spec walk by
        # test_resident.py / test_dense_forkchoice.py.
        self.accelerated_forkchoice = accelerated_forkchoice
        # Per-handler tracing (SURVEY.md §5): wall-clock p50/p95 for
        # get_head / on_block / on_attestation via utils.metrics.
        from pos_evolution_tpu.utils.metrics import HandlerTimer
        self.timer = HandlerTimer()
        # Light clients following this simulation via sync-protocol updates
        # (lightclient/): attached with ``attach_light_client``, served one
        # update per slot from the serving group's head, subject to the
        # run's FaultPlan. Not simulation state: a resumed run re-attaches.
        self.light_clients: list = []
        self._lc_group = 0
        # In-loop adversary engine + online property monitors
        # (sim/adversary.py, sim/monitors.py). Neither is simulation
        # state: like the schedule and telemetry, pass them again to
        # ``resume`` (RandomByzantine's stateless seeded decisions replay
        # identically; stateful strategies replay from an episode-start
        # checkpoint — the chaos-fuzz repro-bundle contract).
        self.adversaries = list(adversaries)
        self.monitors = list(monitors)
        self.monitor_violations: list[dict] = []
        if telemetry is not None:
            telemetry.bus.emit(
                "run_start", n_validators=n_validators,
                n_groups=self.schedule.n_groups, genesis_time=genesis_time,
                accelerated_forkchoice=accelerated_forkchoice,
                sharded=self.sharded, debug=telemetry.debug)
        self._bind_adversaries_and_monitors()
        # Run supervision (resilience/, ISSUE 10, DESIGN.md §18):
        # ``autocheckpoint=(every_n_slots, dir)`` (or an AutoCheckpoint
        # record) arms per-slot heartbeats, periodic integrity audits,
        # and atomic checksummed autocheckpoints with bounded staleness.
        # Like telemetry, NOT simulation state: a restarted process
        # re-arms via ``resume_latest(..., autocheckpoint=...)``.
        self.supervision = None
        if autocheckpoint is not None:
            self.attach_autocheckpoint(autocheckpoint)

    def _get_head(self, group: ViewGroup) -> bytes:
        t0 = _time.perf_counter()
        with self.timer.track("get_head"):
            # variant seam (DESIGN.md §16): GasperVariant answers from the
            # resident mirror / spec walk exactly as the pre-seam driver;
            # successor variants run their expiry-windowed rules
            head = self.variant.head(self, group)
        if self.telemetry is not None:
            self.telemetry.bus.emit(
                "handler", handler="get_head", group=group.id,
                duration_ms=round((_time.perf_counter() - t0) * 1e3, 4))
            self.telemetry.registry.counter(
                "handler_calls_total",
                "fork-choice handler invocations from delivery",
            ).inc(handler="get_head", status="accept")
        return head

    def trace_summary(self) -> dict:
        """Per-handler timing percentiles for this run."""
        return self.timer.summary()

    # -- adversary engine + monitors (sim/adversary.py, sim/monitors.py) -------

    def _bind_adversaries_and_monitors(self) -> None:
        """Fold controlled validators into the schedule's corrupted set
        (the honest duty loop must never act for them) and hand each
        strategy/monitor its simulation handle."""
        for strat in self.adversaries:
            self.schedule.corrupted.update(strat.controlled)
            strat.bind(self)
        for mon in self.monitors:
            mon.bind(self)
        if self.monitors and self.telemetry is not None:
            self.telemetry.bus.emit(
                "monitor_attach",
                monitors=[m.describe() for m in self.monitors],
                adversaries=[s.describe() for s in self.adversaries])

    def _adversary_phase(self, phase: str, slot: int, now: float) -> None:
        """Run one hook round: every strategy acts, then anything it
        injected for immediate delivery is flushed — so honest duties
        that follow see the adversarial messages, which is the whole
        point of in-loop attacks."""
        if not self.adversaries:
            return
        from pos_evolution_tpu.sim.adversary import AdversaryContext
        ctx = AdversaryContext(self, slot, phase, now)
        for strat in self.adversaries:
            getattr(strat, phase)(ctx)
        self._tick_all(now)

    def _observe(self, kind: str, payload) -> None:
        """Show one ORIGINATED message (honest or adversarial, before any
        fault decision) to every monitor — the watchtower's wire tap."""
        for mon in self.monitors:
            mon.observe(kind, payload)

    def _run_monitors(self, slot: int) -> None:
        for mon in self.monitors:
            for violation in mon.on_slot_end(self, slot):
                record = {"slot": slot, **violation}
                self.monitor_violations.append(record)
                if self.telemetry is not None:
                    self.telemetry.bus.emit("monitor", **record)

    # -- time helpers --
    def slot_start(self, slot: int) -> int:
        return slot * self.cfg.seconds_per_slot

    @property
    def delta(self) -> int:
        return self.cfg.seconds_per_slot // self.cfg.intervals_per_slot

    def _tick_all(self, time: float) -> None:
        for g in self.groups:
            if g.crashed:
                continue
            fc.on_tick(g.store, int(time))
            g.deliver_due(time, timer=self.timer,
                          resolver=self._sync_ancestors)

    def _sync_ancestors(self, dst: ViewGroup, signed_block) -> None:
        """Block-by-root backfill (the req/resp sync of real clients):
        when a gossiped block's ancestry is missing from ``dst``'s view,
        pull the gap from the archive and process oldest-first. This is
        what makes faults *transient*: a dropped block becomes a delayed
        one the moment any descendant arrives, and a checkpoint-synced
        rejoiner catches up from its anchor the same way. Deterministic
        (the archive is part of the checkpointed state), so resume
        replays it exactly."""
        self._resolve_blobs(dst, signed_block)
        missing = []
        parent = bytes(signed_block.message.parent_root)
        while parent not in dst.store.blocks:
            sb = self.block_archive.get(parent)
            if sb is None:
                return  # unconnectable (pre-anchor history): let on_block fail
            missing.append(sb)
            parent = bytes(sb.message.parent_root)
        chain = list(reversed(missing))
        for sb in chain:
            self._resolve_blobs(dst, sb)
        dst._process_block_chain(chain)

    def _resolve_blobs(self, group: ViewGroup, signed_block) -> None:
        """Blob-by-root backfill (the sidecar req/resp of real DAS nets):
        when a block is about to import but its committed sidecars never
        arrived (FaultPlan drops, crash outages), pull them from the
        archive and run them through the group's verifying store — a
        dropped sidecar becomes a delayed one exactly like a dropped
        block, keeping faults transient."""
        if group.blob_store is None:
            return
        block = signed_block.message
        root = cached_root(block)
        if group.blob_store.is_available(root, block):
            return
        backfilled = 0
        for sc in self.blob_archive.get(root, ()):
            group.blob_store.on_sidecar(sc)
            backfilled += 1
        if backfilled and self.telemetry is not None:
            self.telemetry.registry.counter(
                "das_blob_backfills_total",
                "sidecars pulled by req/resp at import time",
            ).inc(backfilled, group=group.id)

    # -- fault layer (sim/faults.py) -------------------------------------------

    def _send(self, dst: ViewGroup, base_time: float, delay: float | None,
              kind: str, payload, slot: int, src: int, msg_id: int) -> None:
        """Deliver one message copy-set to ``dst``, routed through the
        ``FaultPlan`` (drop / duplicate / reorder) when one is attached.
        Crashed groups receive nothing (the wire has no mailbox for them:
        whatever is sent during the outage is lost, pos-evolution.md:191)."""
        if delay is None or dst.crashed:
            return
        t = base_time + delay
        span = None
        if self.telemetry is not None:
            # one gossip-edge span per (message, recipient group); a drop
            # leaves this span childless — run_report counts fault events
            # against exactly these edges ("counts vs. effects")
            root_span = _span_id(kind, slot, src, msg_id)
            span = f"{root_span}/g{dst.id}"
            self.telemetry.bus.emit("gossip", span=span, parent=root_span,
                                    kind=kind, slot=slot, src=src,
                                    msg_id=msg_id, dst=dst.id, t=t)
        plan = self.schedule.faults
        if plan is None:
            dst.enqueue(t, kind, payload, span=span)
            return
        for extra in plan.delivery_offsets(kind, slot, src, msg_id, dst.id, t):
            dst.enqueue(t + extra, kind, payload, span=span)

    def _apply_fault_transitions(self, slot: int) -> None:
        """Crash / rejoin view groups at slot boundaries per the plan's
        ``CrashWindow``s. Crash state is a pure function of the slot, so a
        checkpoint taken mid-outage resumes into the same state."""
        plan = self.schedule.faults
        if plan is None or not plan.crashes:
            return
        for g in self.groups:
            down = plan.crashed(g.id, slot)
            if down and not g.crashed:
                g.crashed = True
                # the process died: in-flight messages and the op pool go
                # with it (the store survives on disk — rejoin discards it
                # anyway in favor of the synced checkpoint)
                n_inflight = len(g.queue)
                g.queue.clear()
                g.pool.clear()
                g.block_atts.clear()
                if self.telemetry is not None:
                    self.telemetry.bus.emit("crash", group=g.id, slot=slot,
                                            lost_in_flight=n_inflight)
            elif g.crashed and not down:
                self._rejoin_group(g, slot)
                if self.telemetry is not None:
                    store = g.store
                    self.telemetry.bus.emit(
                        "rejoin", group=g.id, slot=slot,
                        sync_checkpoint_epoch=int(
                            store.justified_checkpoint.epoch),
                        sync_checkpoint_root=bytes(
                            store.justified_checkpoint.root).hex()[:16])

    def _rejoin_group(self, group: ViewGroup, slot: int) -> None:
        """Checkpoint sync: the restarted group boots from a live peer's
        JUSTIFIED checkpoint — the reference's own resume mechanism
        ("checkpoints that act as new genesis", pos-evolution.md:1216) —
        after passing the weak-subjectivity gate (:1293-1302). History
        before the checkpoint is gone; blocks since it arrive via
        ``_sync_ancestors`` backfill.

        The anchor must be a checkpoint, never a raw head: store init
        marks the anchor justified at its own current epoch, and a head
        snapshot would claim a justified epoch the chain never reached —
        every later leaf then fails the viability filter's voting-source
        check (specs/forkchoice._leaf_is_viable) and the synced store
        freezes at its anchor forever. The justified checkpoint is
        exactly the newest point whose descendants' voting sources keep
        the filter satisfied."""
        from pos_evolution_tpu.specs.weak_subjectivity import (
            checkpoint_for_state,
            is_within_weak_subjectivity_period,
        )
        from pos_evolution_tpu.utils.snapshot import (
            load_anchor,
            resume_store,
            save_anchor,
        )
        donors = [g for g in self.groups if g is not group and not g.crashed]
        if not donors:
            raise RuntimeError("crash-restart: no live peer to sync from")
        donor = donors[0].store
        jroot = bytes(donor.justified_checkpoint.root)
        snap = save_anchor(donor.block_states[jroot], donor.blocks[jroot])
        store = resume_store(snap, pow_chain=self.pow_chain)
        fc.on_tick(store, self.slot_start(slot))
        ws_state, ws_checkpoint = checkpoint_for_state(load_anchor(snap)[0])
        if not is_within_weak_subjectivity_period(store, ws_state,
                                                  ws_checkpoint):
            raise RuntimeError(
                "crash-restart: checkpoint outside the weak-subjectivity "
                "period — a rejoin would be vulnerable to long-range forks "
                "(pos-evolution.md:1200)")
        group.store = store
        group.queue.clear()
        group.pool.clear()
        group.block_atts = {}
        group.crashed = False
        if group.invariants is not None:
            # the checker fingerprints ONE store; re-anchor it on the
            # freshly synced one or every later check reads stale state
            from pos_evolution_tpu.utils.metrics import StoreInvariantChecker
            group.invariants = StoreInvariantChecker(store)
        if group.resident is not None:
            from pos_evolution_tpu.ops.resident import ResidentForkChoice
            group.resident = ResidentForkChoice(store)
        if self.variant.needs_view:
            # the process died and its variant overlay with it; the synced
            # store gets a fresh one and re-earns its vote tables from
            # backfilled blocks exactly like the carrier's LMD table
            self.variant.reset_view(group)

    # -- duties --
    def _head_state(self, group: ViewGroup, slot: int):
        head = self._get_head(group)
        return head, advance_state_to_slot(group.store.block_states[head], slot)

    def _propose(self, slot: int) -> None:
        t0 = self.slot_start(slot)
        proposed: set[int] = set()
        for group in self.groups:
            if group.crashed:
                continue  # its members' processes are down
            head, head_state = self._head_state(group, slot)
            proposer = get_beacon_proposer_index(head_state)
            if proposer in proposed:
                continue
            if proposer not in set(int(v) for v in group.members):
                continue
            if int(proposer) in self.schedule.corrupted:
                continue  # Byzantine proposers act via attack scripts
            round_index = slot * self.cfg.intervals_per_slot
            if not self.schedule.awake(round_index, int(proposer)):
                continue
            proposed.add(proposer)
            atts = self._pack_attestations(group, slot, head,
                                           head_state=head_state)
            sync_agg = self._make_sync_aggregate(group, slot, head,
                                                 head_state, round_index)
            # DAS: blob payloads are committed at build time through the
            # graffiti marker (state_root covers graffiti), so grids and
            # commitments exist BEFORE the block does.
            graffiti = b"\x00" * 32
            das_grids = das_commitments = None
            if self.das is not None:
                das_grids, das_commitments, graffiti = \
                    self.das.build_for(slot, head)
            try:
                sb = build_block(group.store.block_states[head], slot,
                                 attestations=atts, sync_aggregate=sync_agg,
                                 graffiti=graffiti)
            except AssertionError:
                # Rare fault-era residue: an attestation that passed the
                # cheap packing filter is still unincludable (e.g. a
                # committee reshuffled across an epoch-crossing fork).
                # A real proposer drops the op, not the proposal.
                sb = build_block(group.store.block_states[head], slot,
                                 attestations=[], sync_aggregate=sync_agg,
                                 graffiti=graffiti)
            block_root = cached_root(sb.message)
            self.block_archive[block_root] = sb
            if das_grids:
                self.blob_archive[block_root] = self.das.sidecars_for(
                    sb, block_root, das_grids, das_commitments)
            self._observe("block", sb)
            if self.telemetry is not None:
                # lifecycle root span: propose -> per-group gossip edges
                # -> per-group deliveries hang off this id
                self.telemetry.bus.emit(
                    "propose", span=_span_id("block", slot, int(proposer), 0),
                    slot=slot, proposer=int(proposer), group=group.id,
                    block_root=block_root.hex()[:16],
                    n_attestations=len(atts))
            for dst in self.groups:
                delay = self.schedule.block_delay(int(proposer), slot, dst.id)
                # sidecars ride the block's gossip timing but their own
                # fault decisions (a dropped sidecar with a delivered
                # block leaves the block unimportable until the req/resp
                # backfill pulls the blobs) — enqueued BEFORE the block so
                # the in-order case verifies availability pre-import
                for sc in self.blob_archive.get(block_root, ()):
                    self._send(dst, t0, delay, "blob", sc, slot,
                               src=int(proposer),
                               msg_id=int(sc.blob_index))
                self._send(dst, t0, delay, "block", sb, slot,
                           src=int(proposer), msg_id=0)

    def _make_sync_aggregate(self, group: ViewGroup, slot: int, head: bytes,
                             head_state, round_index: int):
        """Sync-committee duty at proposal time: the committee members the
        proposer's view group can reach — honest and awake this round —
        sign the head the block builds on (pos-evolution.md:548-557)."""
        honest = self.schedule.honest_members(group.id)
        participants = set(int(v) for v in honest
                           if self.schedule.awake(round_index, int(v)))
        if not participants:
            return None
        return make_sync_aggregate(head_state, head, participants=participants)

    def _includable(self, state, att) -> bool:
        """Cheap op-pool validity filter mirroring process_attestation's
        asserts that can fail for a STALE pool entry under faults: target
        epoch outside the state's window, an FFG source that no longer
        matches the proposal state's justified checkpoint (justification
        moved while the attestation sat in the pool), a committee index
        out of range, or a committee size mismatch. Real clients validate
        ops at packing time; without this, one stale vote aborts the
        whole proposal."""
        from pos_evolution_tpu.specs.helpers import (
            get_beacon_committee,
            get_current_epoch,
            get_previous_epoch,
        )
        data = att.data
        target_epoch = int(data.target.epoch)
        if target_epoch not in (get_previous_epoch(state),
                                get_current_epoch(state)):
            return False
        if int(data.index) >= get_committee_count_per_slot(state,
                                                           target_epoch):
            return False
        expected = (state.current_justified_checkpoint
                    if target_epoch == get_current_epoch(state)
                    else state.previous_justified_checkpoint)
        if (int(data.source.epoch) != int(expected.epoch)
                or bytes(data.source.root) != bytes(expected.root)):
            return False
        try:
            committee = get_beacon_committee(state, int(data.slot),
                                             int(data.index))
        except (AssertionError, IndexError):
            return False
        return np.asarray(att.aggregation_bits).shape[0] == committee.shape[0]

    def _pack_attestations(self, group: ViewGroup, slot: int,
                           head: bytes, head_state=None) -> list:
        c = self.cfg
        # inclusion set of the proposer's CANONICAL chain, within the
        # attestation window: walk head ancestry while blocks are recent
        # enough to carry still-packable attestations
        onchain: set[bytes] = set()
        b = head
        while b in group.store.blocks:
            blk = group.store.blocks[b]
            if int(blk.slot) + c.slots_per_epoch < slot:
                break
            onchain.update(group.block_atts.get(b, ()))
            b = bytes(blk.parent_root)
        out = []
        expired = []
        for root, att in group.pool.items():
            a_slot = int(att.data.slot)
            if slot > a_slot + c.slots_per_epoch:
                expired.append(root)           # prune: bounds the pool
                continue
            if a_slot + c.min_attestation_inclusion_delay > slot:
                continue
            if root in onchain:
                continue                       # already on this chain
            if len(out) < c.max_attestations:
                # validity filter LAST, only for entries actually packed
                # (it computes a committee — O(max_attestations) per
                # proposal, not O(pool))
                if head_state is None or self._includable(head_state, att):
                    out.append(att)
        for root in expired:
            del group.pool[root]
        return out

    def _attest(self, slot: int) -> None:
        t_next = self.slot_start(slot + 1)
        for group in self.groups:
            if group.crashed:
                continue
            head, head_state = self._head_state(group, slot)
            honest = set(int(v) for v in self.schedule.honest_members(group.id))
            if not honest:
                continue
            round_index = slot * self.cfg.intervals_per_slot + 1
            awake = set(v for v in honest if self.schedule.awake(round_index, v))
            if not awake:
                continue
            count = get_committee_count_per_slot(head_state, compute_epoch_at_slot(slot))
            for index in range(count):
                try:
                    att = make_committee_attestation(
                        head_state, slot, index, head,
                        participants=np.array(sorted(awake), dtype=np.int64))
                except ValueError:
                    continue  # no awake member in this committee
                self._observe("attestation", att)
                if self.telemetry is not None:
                    self.telemetry.bus.emit(
                        "attest",
                        span=_span_id("attestation", slot, group.id, index),
                        slot=slot, group=group.id, committee=index,
                        head=head.hex()[:16])
                for dst in self.groups:
                    delay = self.schedule.attestation_delay(group.id, slot, dst.id)
                    self._send(dst, t_next, delay, "attestation", att, slot,
                               src=group.id, msg_id=index)

    # -- main loop --
    def run_slot(self) -> None:
        slot = self.slot
        t0 = self.slot_start(slot)
        self._apply_fault_transitions(slot)
        self._tick_all(t0)
        # Variant merge phase (DESIGN.md §16): the previous slot's votes
        # just crossed the boundary tick — fold view buffers and process
        # the completed vote round (fast confirmation, per-slot FFG)
        # before any of this slot's head queries.
        self.variant.on_slot_start(self, slot)
        if slot > 0:
            self._adversary_phase("before_propose", slot, t0)
            self._propose(slot)
            self._tick_all(t0 + 1)  # timely blocks land within the boost window
            self._tick_all(t0 + self.delta)
            self._adversary_phase("before_attest", slot, t0 + self.delta)
            self._attest(slot)
            self._tick_all(t0 + 2 * self.delta)
            self._adversary_phase("after_attest", slot, t0 + 2 * self.delta)
        variant_record = self.variant.on_slot_end(self, slot)
        if variant_record is not None and self.telemetry is not None:
            self.telemetry.bus.emit("variant", **variant_record)
        self._record_metrics(slot)
        self._run_monitors(slot)
        self._serve_light_clients(slot)
        self._serve_das(slot)
        self._publish_serve_view(slot)
        self.slot += 1
        if self.supervision is not None:
            # heartbeat -> integrity audit -> autocheckpoint, in that
            # order (liveness never waits on an audit; a poisoned state
            # is never checkpointed). The capture serializes on THIS
            # thread — the stores are live mutable objects — so only
            # the fsync+rename overlaps in async mode.
            self.supervision.tick(self, self.slot, self.checkpoint)

    def run_until_slot(self, slot: int) -> None:
        if self.profile is not None and not self._profiled:
            self._profiled = True
            self._run_profiled(slot)
            return
        while self.slot <= slot:
            self.run_slot()

    def _run_profiled(self, slot: int) -> None:
        """One profiled run segment: capture a device trace around the
        slot loop, attribute device ops to the telemetry spans emitted
        during it, and write the exporter artifacts into ``self.profile``
        (see ``__init__``). Profiling failures degrade to a plain run —
        the artifacts are best-effort, the simulation is not."""
        from pos_evolution_tpu.profiling import ProfiledRegion
        from pos_evolution_tpu.profiling.export import write_artifacts
        if self.telemetry is not None and not self.telemetry.bus.keep_in_memory:
            # the sim lane and span attribution are built from the
            # in-memory event view; say so rather than silently emit an
            # empty lane + all-unattributed tables
            self.telemetry.bus.emit(
                "profile_export_note",
                warning="bus keep_in_memory=False: profile artifacts will "
                        "carry no sim-time lane or span attribution")
        mark = (len(self.telemetry.bus.events)
                if self.telemetry is not None else 0)
        with ProfiledRegion("sim_run", telemetry=self.telemetry) as prof:
            while self.slot <= slot:
                self.run_slot()
        events = (self.telemetry.bus.events[mark:]
                  if self.telemetry is not None else [])
        try:
            # device slices capped to the longest 50K (a CPU run records
            # one event per thunk execution — tens of MB untruncated; the
            # cap lands in a "truncated" metadata event)
            written = write_artifacts(self.profile, events=events,
                                      planes=prof.planes,
                                      top_ops=prof.top_ops,
                                      max_device_events=50_000,
                                      exclude_ops={"sim_run"})
            if self.telemetry is not None:
                # record where the artifacts landed so offline consumers
                # (run_report top-ops auto-discovery) can find them from
                # the event log alone
                self.telemetry.bus.emit("profile_artifacts",
                                        dir=self.profile,
                                        files=sorted(written))
        except Exception as e:
            # not just OSError: a non-JSON-serializable payload some
            # emitter slipped onto an in-memory bus surfaces here as
            # TypeError — the completed run must survive it regardless
            if self.telemetry is not None:
                self.telemetry.bus.emit("profile_export_failed",
                                        error=f"{e!r:.200}")

    def run_epochs(self, n_epochs: int) -> None:
        self.run_until_slot(n_epochs * self.cfg.slots_per_epoch)

    # -- observability (SURVEY.md §5: structured per-slot log) --
    def _record_metrics(self, slot: int) -> None:
        """One ``utils.metrics.slot_record`` per slot — the driver no
        longer hand-rolls a subset (the old copy silently lacked
        ``participation``/``justification_bits``/``n_latest_messages``).
        The legacy ``head`` key (8-hex prefix) is kept so ``metrics``
        entries stay a superset of every pre-telemetry consumer's keys,
        and everything remains JSON-round-trippable for
        ``checkpoint()``/``resume()`` snapshots."""
        from pos_evolution_tpu.utils.metrics import slot_record
        group = self.groups[0]
        head = self._get_head(group)
        rec = slot_record(group.store, slot, head=head)
        rec["head"] = rec["head_root"][:8]
        self.metrics.append(rec)
        if self.telemetry is not None:
            self.telemetry.bus.emit("slot", **rec)
            self.telemetry.registry.gauge(
                "finalized_epoch", "group-0 finalized epoch").set(
                rec["finalized_epoch"])
            self.telemetry.registry.gauge(
                "justified_epoch", "group-0 justified epoch").set(
                rec["justified_epoch"])
            self._record_merkleization(slot)

    def _record_merkleization(self, slot: int) -> None:
        """Per-slot deltas of the incremental-merkleization counters
        (``ssz/incremental.stats()``) and the fused-transition residency
        counters (``ops/transition.session_stats()``) — both are
        process-cumulative, so the driver keeps a mark and feeds only this
        simulation's deltas to the MetricsRegistry (``ssz.htr_cache_hit``
        etc.) plus one ``merkleization`` event per slot that saw activity.
        ``run_report.py`` folds the events into its merkleization section."""
        from pos_evolution_tpu.ssz import incremental
        cur = {f"ssz.{k}": v for k, v in incremental.stats().items()}
        try:
            from pos_evolution_tpu.ops.transition import session_stats
            cur.update({f"fused.{k}": v for k, v in session_stats().items()})
        except Exception:
            pass  # transition module unavailable: ssz counters still flow
        from pos_evolution_tpu.ops import merkle_device
        cur.update({f"merkle.{k}": v
                    for k, v in merkle_device.stats().items()})
        mark = getattr(self, "_merkle_mark", None)
        self._merkle_mark = cur
        if mark is None:
            # first record (fresh __init__ or a resumed checkpoint): the
            # cumulative counters include other sims / pre-checkpoint work
            # in this process, so the first slot only seeds the mark
            return
        delta = {k: v - mark.get(k, 0) for k, v in cur.items()
                 if v - mark.get(k, 0)}
        reg = self.telemetry.registry
        for k, v in delta.items():
            reg.counter(k, "incremental merkleization / fused transition "
                           "(per-sim delta of the process counters)").inc(v)
        if delta:
            self.telemetry.bus.emit("merkleization", slot=slot, **{
                k.replace(".", "_"): v for k, v in delta.items()})

    # -- light clients (lightclient/) ------------------------------------------

    def attach_light_client(self, group: int = 0):
        """Bootstrap a ``LightClientNode`` from ``group``'s finalized
        (weak-subjectivity) checkpoint and register it for per-slot update
        serving. The serving group is fixed to the first attach."""
        from pos_evolution_tpu.lightclient import (
            LightClientNode,
            bootstrap_from_store,
        )
        g = self.groups[group]
        assert not g.crashed, "cannot bootstrap from a crashed group"
        assert not self.light_clients or group == self._lc_group, \
            "light clients are all served from one group; re-attach uses " \
            f"group {self._lc_group}"
        trusted_root, bootstrap = bootstrap_from_store(g.store)
        state = g.store.block_states[bytes(g.store.finalized_checkpoint.root)]
        node = LightClientNode.from_bootstrap(
            trusted_root, bootstrap,
            fork_version=bytes(state.fork.current_version),
            genesis_validators_root=bytes(state.genesis_validators_root),
            node_id=len(self.light_clients))
        self._lc_group = group
        self.light_clients.append(node)
        return node

    def _serve_light_clients(self, slot: int) -> None:
        """End-of-slot update serving: derive the best update from the
        serving group's head and offer it to every attached client, routed
        through the FaultPlan (a dropped update is simply never seen — the
        client survives on the force-update path)."""
        if not self.light_clients:
            return
        group = self.groups[self._lc_group]
        # A crashed server stops SERVING, but the clients are independent
        # processes: their force-update timeout still ticks and their lag
        # is measured against the server's frozen view.
        head = self._get_head(group)
        update = None
        if not group.crashed:
            if self.das_server is not None:
                # best-update LRU (das/server.py): one proof build per
                # distinct head, however many slots serve it
                update = self.das_server.best_update(
                    group.store, head, archive=self.block_archive)
            else:
                from pos_evolution_tpu.lightclient import build_update
                update = build_update(group.store, head,
                                      archive=self.block_archive)
        full_head_slot = int(group.store.blocks[head].slot)
        full_finalized_epoch = int(group.store.finalized_checkpoint.epoch)
        plan = self.schedule.faults
        t = self.slot_start(slot)
        for node in self.light_clients:
            if update is not None:
                delivered = (plan is None
                             or plan.delivery_offsets("lc_update", slot,
                                                      self._lc_group, 0,
                                                      1_000_000 + node.id, t))
                if delivered:
                    node.on_update(update, current_slot=slot)
            record = node.advance(slot, full_head_slot, full_finalized_epoch)
            if self.telemetry is not None:
                self.telemetry.bus.emit("light_client_lag", node=node.id,
                                        **record)

    # -- DAS sampling clients (das/, DESIGN.md §15) ----------------------------

    def attach_das_clients(self, n_clients: int,
                           samples_per_client: int | None = None,
                           group: int = 0, seed: int = 0,
                           proof_cache: int = 4096, update_cache: int = 64,
                           window: int = 2):
        """Attach a vectorized sampling-client population (10^5-10^6
        clients as arrays, das/sampler.py) served once per slot from
        ``group``'s head through a coalescing ``DasServer``. Clients
        sample the newest ``window`` blocks of the canonical chain each
        slot (the availability-window retry behaviour of real DAS nets)
        — re-served blocks answer from the proof-path LRU, which is what
        makes the cache-hit metrics meaningful. Also swaps the
        light-client update serving onto the server's best-update LRU.
        Not simulation state: a resumed run re-attaches."""
        assert self.das is not None, \
            "attach_das_clients requires Simulation(das=...)"
        from pos_evolution_tpu.das import DasServer, SamplingClientPopulation
        registry = (self.telemetry.registry if self.telemetry is not None
                    else None)
        self._das_group = group
        self._das_window = max(int(window), 1)
        self.das_server = DasServer(self.das.scheme, registry=registry,
                                    proof_cache=proof_cache,
                                    update_cache=update_cache)
        self.das_population = SamplingClientPopulation(
            n_clients, samples_per_client, seed=seed)
        if registry is not None:
            registry.gauge("das_clients",
                           "attached DAS sampling clients").set(n_clients)
        if self.telemetry is not None:
            self.telemetry.bus.emit("das_attach",
                                    **self.das_population.describe(),
                                    engine=self.das.describe())
        return self.das_population

    def _das_targets(self, group) -> list[tuple[bytes, object]]:
        """The newest ``window`` canonical blob-carrying blocks from
        ``group``'s head — the per-slot serving window shared by the
        vectorized sampling round and the published ``ServeView``."""
        from pos_evolution_tpu.das.containers import parse_das_graffiti
        targets = []
        root = self._get_head(group)
        while len(targets) < self._das_window and root in group.store.blocks:
            block = group.store.blocks[root]
            if parse_das_graffiti(bytes(block.body.graffiti)) is not None:
                targets.append((root, block))
            if int(block.slot) == 0:
                break
            root = bytes(block.parent_root)
        return targets

    def _serve_das(self, slot: int) -> None:
        """End-of-slot sampling round: the serving group's head block's
        sidecars are sampled by the whole population through the
        coalescing server; the summary lands on the bus as a
        ``das_serve`` event (run_report.py's "DAS serving" section)."""
        if self.das_population is None:
            return
        from pos_evolution_tpu.das.containers import parse_das_graffiti
        group = self.groups[self._das_group]
        if group.crashed:
            return
        # the head freshly, its recent ancestors again (their cells
        # answer from the proof-path LRU warmed by the previous slots)
        for age, (root, block) in enumerate(self._das_targets(group)):
            n_blobs = parse_das_graffiti(bytes(block.body.graffiti))[0]
            sidecars = (group.blob_store.sidecars_for_block(root)
                        if group.blob_store is not None else [])
            if len(sidecars) < n_blobs:
                sidecars = self.blob_archive.get(root, [])
            if len(sidecars) < n_blobs:
                continue  # serving node itself lacks the data
            summary = self.das_server.serve_samples(root, sidecars,
                                                    self.das_population)
            if self.telemetry is not None:
                self.telemetry.bus.emit("das_serve", slot=slot, age=age,
                                        block_root=root.hex()[:16],
                                        **summary)

    # -- live serving tier (serve/, DESIGN.md §19) -----------------------------

    def _publish_serve_view(self, slot: int) -> None:
        """End-of-slot view publication for the socket-facing serve tier:
        one immutable snapshot of everything the RPC handlers answer from
        (serve/state.py), swapped in atomically. A crashed serving group
        freezes the view — the front keeps serving its last published
        state, exactly like a real node that lost its beacon backend."""
        if self.serving_state is None:
            return
        from pos_evolution_tpu.serve import ServeView
        group = self.groups[self._das_group if self.das is not None
                            else self._lc_group]
        if group.crashed:
            return
        head = self._get_head(group)
        store = group.store
        update_ssz = update_root = None
        if self.das_server is not None:
            update = self.das_server.best_update(
                store, head, archive=self.block_archive)
        else:
            from pos_evolution_tpu.lightclient import build_update
            update = build_update(store, head, archive=self.block_archive)
        if update is not None:
            from pos_evolution_tpu.ssz import hash_tree_root as _htr
            from pos_evolution_tpu.ssz import serialize as _ser
            update_ssz = _ser(update)
            update_root = bytes(_htr(update))
        sidecars: dict[bytes, list] = {}
        if self.das is not None:
            for root, _block in self._das_targets(group):
                cars = (group.blob_store.sidecars_for_block(root)
                        if group.blob_store is not None else [])
                if not cars:
                    cars = self.blob_archive.get(root, [])
                if cars:
                    sidecars[root] = cars
        self.serving_state.publish(ServeView(
            slot=slot,
            head_root=bytes(head),
            head_slot=int(store.blocks[head].slot),
            justified_epoch=int(store.justified_checkpoint.epoch),
            justified_root=bytes(store.justified_checkpoint.root),
            finalized_epoch=int(store.finalized_checkpoint.epoch),
            finalized_root=bytes(store.finalized_checkpoint.root),
            update_ssz=update_ssz, update_root=update_root,
            sidecars=sidecars,
            n_cells=2 * self.cfg.das_cells_per_blob,
            scheme=(self.das.scheme.name if self.das is not None
                    else "merkle")))

    def flush_light_clients(self) -> None:
        """Serve one off-chain finality update for the serving group's
        CURRENT head: the sync committee's signatures over the head exist
        before any block includes them (real networks gossip them as
        FinalityUpdates), so attached clients converge to the full node's
        exact finalized head instead of trailing one inclusion round."""
        if not self.light_clients:
            return
        group = self.groups[self._lc_group]
        if group.crashed:
            return
        from pos_evolution_tpu.lightclient import build_head_update
        head = self._get_head(group)
        head_state = group.store.block_states[head]
        signature_slot = int(group.store.blocks[head].slot) + 1
        signing_state = advance_state_to_slot(head_state, signature_slot)
        round_index = signature_slot * self.cfg.intervals_per_slot
        aggregate = self._make_sync_aggregate(group, signature_slot, head,
                                              signing_state, round_index)
        if aggregate is None:
            return
        update = build_head_update(group.store, head, aggregate,
                                   signature_slot, archive=self.block_archive)
        if update is None:
            return
        full_head_slot = int(group.store.blocks[head].slot)
        full_finalized_epoch = int(group.store.finalized_checkpoint.epoch)
        for node in self.light_clients:
            node.on_update(update, current_slot=signature_slot)
            record = node.advance(signature_slot, full_head_slot,
                                  full_finalized_epoch)
            if self.telemetry is not None:
                self.telemetry.bus.emit("light_client_lag", node=node.id,
                                        offchain=True, **record)

    # -- whole-simulation checkpoint / resume ----------------------------------
    def checkpoint(self) -> bytes:
        """Serialize the ENTIRE simulation — every view group's store,
        message queue, attestation pool and inclusion index, plus the slot
        cursor and per-slot metrics — such that ``Simulation.resume``
        continues the run bit-identically (property-pinned by
        tests/test_faults.py). Wall-clock handler timings are the one
        thing deliberately excluded (they are not simulation state)."""
        from pos_evolution_tpu.utils.snapshot import save_simulation
        return save_simulation(self)

    @classmethod
    def resume(cls, data: bytes, schedule: Schedule | None = None,
               telemetry=None, adversaries=(), monitors=(),
               das=None, variant=None, sharded=None) -> "Simulation":
        """Rebuild a checkpointed simulation mid-run. ``schedule`` must be
        the same delivery/fault policy the original run used (schedules
        hold callables, which do not serialize); None resumes an honest
        synchronous run. Crash state re-derives from the FaultPlan, so a
        checkpoint taken during an outage resumes into the outage.
        ``telemetry`` re-attaches an event bus/registry (telemetry is not
        sim state; the resumed run records only post-resume events).
        ``adversaries``/``monitors`` re-attach strategy and monitor
        instances (also not sim state): a stateless strategy
        (``RandomByzantine``) replays exactly from any checkpoint slot;
        stateful strategies and monitors replay exactly from an
        episode-START checkpoint — the repro-bundle contract of
        ``scripts/chaos_fuzz.py``. ``das`` re-attaches a BlobEngine: blob
        payloads regenerate from the seed and each view's verified-sidecar
        set replays, so availability gating resumes where it stopped.
        ``variant`` re-attaches a ProtocolVariant; None rebuilds one from
        the checkpoint's describe() fingerprint (variant state — vote
        overlays, confirmations, per-slot FFG — is serialized, so a chaos
        repro bundle replays under the variant that produced it); a
        mismatched explicit variant raises. ``sharded`` overrides the
        checkpointed mesh shape (None re-enables the recorded one;
        resident columns rebuild sharded on the CURRENT mesh, so resuming
        on a different mesh shape — or a different device count — is a
        gather + re-shard, bit-identical by the kernel contracts)."""
        from pos_evolution_tpu.utils.snapshot import load_simulation
        return load_simulation(data, schedule=schedule, telemetry=telemetry,
                               adversaries=adversaries, monitors=monitors,
                               das=das, variant=variant, sharded=sharded)

    # -- run supervision (resilience/, ISSUE 10) -------------------------------

    def attach_autocheckpoint(self, spec) -> None:
        """Arm (or re-arm, after a resume) run supervision: accepts an
        ``(every_n_slots, dir)`` tuple, a dict, or a full
        ``resilience.AutoCheckpoint``."""
        from pos_evolution_tpu.resilience import RunSupervision
        self.supervision = RunSupervision(spec, kind="sim",
                                          telemetry=self.telemetry)

    def finish_autocheckpoint(self) -> dict | None:
        """Take a final checkpoint at the current slot and drain the
        async writer; returns the manager's overhead stats. Call once
        at the end of a supervised run — the finished state must be as
        durable as any mid-run step."""
        if self.supervision is None:
            return None
        return self.supervision.finish(self.slot, self.checkpoint)

    @classmethod
    def resume_latest(cls, dir, schedule: Schedule | None = None,
                      telemetry=None, adversaries=(), monitors=(),
                      das=None, variant=None, sharded=None,
                      autocheckpoint=None) -> "Simulation":
        """Resume from the newest *valid* checkpoint under ``dir``
        (``resilience.CheckpointManager`` layout): checksum + manifest
        + active-config fingerprint are verified, corrupt steps are
        quarantined and rolled past, and a fingerprint from a different
        config refuses loudly. ``autocheckpoint`` re-arms supervision
        on the resumed run (pass the same spec the original run used so
        the restarted process keeps checkpointing into the same store).
        Raises ``FileNotFoundError`` when no valid checkpoint exists —
        the caller decides whether a fresh start is acceptable."""
        from pos_evolution_tpu.resilience import CheckpointManager
        from pos_evolution_tpu.resilience.runner import run_fingerprint
        found = CheckpointManager(
            dir, fingerprint=run_fingerprint("sim")).latest_valid()
        if found is None:
            raise FileNotFoundError(
                f"no valid checkpoint under {dir!r} to resume from")
        step, payloads = found
        sim = cls.resume(payloads["payload.bin"], schedule=schedule,
                         telemetry=telemetry, adversaries=adversaries,
                         monitors=monitors, das=das, variant=variant,
                         sharded=sharded)
        if autocheckpoint is not None:
            sim.attach_autocheckpoint(autocheckpoint)
        if telemetry is not None:
            import os as _os3
            telemetry.bus.emit("run_resumed", step=step, slot=sim.slot,
                               dir=_os3.fspath(dir))
        return sim

    # -- accessors --
    def store(self, group: int = 0) -> fc.Store:
        return self.groups[group].store

    def finalized_epoch(self, group: int = 0) -> int:
        return int(self.groups[group].store.finalized_checkpoint.epoch)

    def justified_epoch(self, group: int = 0) -> int:
        return int(self.groups[group].store.justified_checkpoint.epoch)
