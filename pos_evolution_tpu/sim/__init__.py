"""Network & adversary simulation layer (L6)."""

from pos_evolution_tpu.sim.driver import Simulation, ViewGroup
from pos_evolution_tpu.sim.faults import (
    CrashWindow,
    FaultPlan,
    chaos_plan,
    lossy_plan,
)
from pos_evolution_tpu.sim.schedule import (
    Schedule,
    faulty_schedule,
    honest_schedule,
    partition_schedule,
)
