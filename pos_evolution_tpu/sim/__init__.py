"""Network & adversary simulation layer (L6)."""

from pos_evolution_tpu.sim.adversary import (
    AdversaryContext,
    AdversaryStrategy,
    Balancer,
    Equivocator,
    RandomByzantine,
    SplitVoter,
    Withholder,
)
from pos_evolution_tpu.sim.dense_driver import DenseSimulation
from pos_evolution_tpu.sim.driver import Simulation, ViewGroup
from pos_evolution_tpu.sim.faults import (
    CrashWindow,
    FaultPlan,
    chaos_plan,
    lossy_plan,
    stateless_unit,
)
from pos_evolution_tpu.sim.monitors import (
    AccountableSafetyMonitor,
    FinalityLivenessMonitor,
    ForkChoiceParityMonitor,
    Monitor,
    VariantSafetyMonitor,
    default_monitors,
)
from pos_evolution_tpu.sim.schedule import (
    Schedule,
    faulty_schedule,
    honest_schedule,
    partition_schedule,
)
