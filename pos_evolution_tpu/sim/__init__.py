"""Network & adversary simulation layer (L6)."""

from pos_evolution_tpu.sim.adversary import (
    AdversaryContext,
    AdversaryStrategy,
    Balancer,
    Equivocator,
    RandomByzantine,
    SplitVoter,
    Withholder,
)
from pos_evolution_tpu.sim.dense_adversary import (
    DenseAdversaryStrategy,
    DenseBalancer,
    DenseEquivocator,
    DenseSplitVoter,
    DenseWithholder,
    VoteBatch,
)
from pos_evolution_tpu.sim.dense_driver import DenseSimulation
from pos_evolution_tpu.sim.dense_monitors import (
    DenseAccountableSafetyMonitor,
    DenseFinalityLivenessMonitor,
    DenseForkChoiceParityMonitor,
    DenseMonitor,
    default_dense_monitors,
)
from pos_evolution_tpu.sim.driver import Simulation, ViewGroup
from pos_evolution_tpu.sim.faults import (
    CrashWindow,
    DenseCrashWindow,
    DenseFaultPlan,
    FaultPlan,
    chaos_plan,
    lossy_plan,
    stateless_unit,
    stateless_unit_array,
)
from pos_evolution_tpu.sim.monitors import (
    AccountableSafetyMonitor,
    FinalityLivenessMonitor,
    ForkChoiceParityMonitor,
    Monitor,
    VariantSafetyMonitor,
    default_monitors,
)
from pos_evolution_tpu.sim.schedule import (
    Schedule,
    faulty_schedule,
    honest_schedule,
    partition_schedule,
)
