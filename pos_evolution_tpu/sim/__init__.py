"""Network & adversary simulation layer (L6)."""

from pos_evolution_tpu.sim.driver import Simulation, ViewGroup
from pos_evolution_tpu.sim.schedule import Schedule, honest_schedule, partition_schedule
