"""Adversarial schedule: fault injection as data (SURVEY.md §5).

The reference's adversary model is first-class: Byzantine corruption of up
to f validators (pos-evolution.md:183-185), per-round sleep/awake scheduling
(:191-199), adversary-controlled message delays up to Δ under synchrony
(:197), GST/GAT partial synchrony (:199), and targeted delivery used by the
balancing attacks (:1328: "be able to target a message for delivery to an
honest validator just before a certain point in time").

A ``Schedule`` captures all of that as plain data — per-round awake masks,
per-(message, recipient-group) delivery offsets, corrupted sets — so the
same simulation driver executes honest runs and attack scenarios without
control-flow forks.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

import numpy as np


@dataclass
class Schedule:
    """Delivery/corruption/sleep policy for one simulation run."""

    n_validators: int
    # Validator index -> view-group id. Validators in one group receive
    # exactly the same messages at the same times (pos-evolution.md:201-203:
    # views are per-validator; groups are the equivalence classes the
    # adversary's delivery strategy induces).
    group_of: np.ndarray = None
    # Corrupted (Byzantine) validator indices (pos-evolution.md:183-185).
    corrupted: set = field(default_factory=set)
    # awake(round_index, validator_index) -> bool (sleepy model, :191-199).
    awake: Callable[[int, int], bool] = None
    # block_delay(proposer, slot, group) -> seconds after slot start at which
    # the group receives the block (None = withhold forever).
    block_delay: Callable[[int, int, int], float | None] = None
    # attestation_delay(attester_group, slot, group) -> seconds after the
    # *next* slot start (wire attestations are only usable from slot+1).
    attestation_delay: Callable[[int, int, int], float | None] = None
    # Message-level fault policy (sim/faults.py): per-message drop /
    # duplicate / reorder, GST windows, crash-restart view groups. None =
    # faithful delivery at exactly the scheduled delays (the model above).
    faults: "FaultPlan | None" = None

    def __post_init__(self):
        if self.group_of is None:
            self.group_of = np.zeros(self.n_validators, dtype=np.int64)
        self.group_of = np.asarray(self.group_of, dtype=np.int64)
        if self.awake is None:
            self.awake = lambda r, v: True
        if self.block_delay is None:
            self.block_delay = lambda proposer, slot, group: 0.0
        if self.attestation_delay is None:
            self.attestation_delay = lambda src_group, slot, group: 0.0

    @property
    def n_groups(self) -> int:
        return int(self.group_of.max()) + 1 if self.group_of.size else 1

    def members(self, group: int) -> np.ndarray:
        return np.nonzero(self.group_of == group)[0]

    def honest_members(self, group: int) -> np.ndarray:
        m = self.members(group)
        if not self.corrupted:
            return m
        return np.array([v for v in m if int(v) not in self.corrupted], dtype=np.int64)


def honest_schedule(n_validators: int) -> Schedule:
    """Synchronous, all-honest, single-view run."""
    return Schedule(n_validators=n_validators)


def partition_schedule(n_validators: int, n_groups: int,
                       corrupted: set | None = None) -> Schedule:
    """Round-robin split of the validator set into ``n_groups`` views."""
    return Schedule(
        n_validators=n_validators,
        group_of=np.arange(n_validators, dtype=np.int64) % n_groups,
        corrupted=corrupted or set(),
    )


def faulty_schedule(n_validators: int, faults, n_groups: int = 1,
                    corrupted: set | None = None) -> Schedule:
    """A partitioned (or single-view) schedule with a ``FaultPlan``
    attached — the composition point for the sim/faults.py adversary."""
    sched = (honest_schedule(n_validators) if n_groups == 1 else
             partition_schedule(n_validators, n_groups, corrupted))
    sched.faults = faults
    return sched
