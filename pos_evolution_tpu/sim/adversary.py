"""In-loop Byzantine adversary engine (pos-evolution.md:1319-1527).

The attack reproductions used to live outside the driver as one-shot
scripts (``sim/attacks.py``). This module makes the adversary a
first-class *participant* of ``Simulation``: a pluggable
``AdversaryStrategy`` acts every slot over its controlled validator
indices, with exactly the reference's adversarial powers — equivocation,
private chains with timed release, targeted just-in-time delivery
(pos-evolution.md:1328) — while the honest duty loop, the ``FaultPlan``
message faults, crash windows, telemetry, and the online monitors
(``sim/monitors.py``) all keep running around it.

Hook contract (driven by ``Simulation.run_slot`` for slots >= 1):

- ``before_propose(ctx)``: round 0, after queued deliveries, before the
  honest proposer acts — release withheld chains here so a timely
  adversarial block lands inside the proposer-boost window;
- ``before_attest(ctx)``: 1Δ into the slot, before honest committees
  vote — the "just before a certain point in time" delivery target of
  the balancing attacks;
- ``after_attest(ctx)``: end of slot, after honest votes are broadcast —
  bank withheld votes, record per-slot observations.

Strategies inject messages only through ``AdversaryContext.broadcast``,
which routes through the driver's ``_send`` — so adversarial traffic is
subject to the same FaultPlan drops/duplications/reorders, crash-window
blackouts, telemetry gossip spans, and monitor observation as honest
traffic (composability is the point).

Determinism: ``RandomByzantine`` draws every decision from the same
stateless seeded hash as ``FaultPlan`` (``sim/faults.stateless_unit``):
no RNG cursor, so a checkpointed run resumed mid-attack replays the
identical adversarial behavior, and episode ordering in the chaos fuzzer
cannot perturb any episode's attack pattern.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from pos_evolution_tpu.config import cfg
from pos_evolution_tpu.sim.faults import stateless_unit
from pos_evolution_tpu.specs import forkchoice as fc
from pos_evolution_tpu.specs.helpers import (
    compute_epoch_at_slot,
    get_beacon_committee,
    get_beacon_proposer_index,
    get_committee_count_per_slot,
)
from pos_evolution_tpu.specs.transition import state_transition
from pos_evolution_tpu.specs.validator import (
    advance_state_to_slot,
    build_block,
    make_committee_attestation,
)
from pos_evolution_tpu.ssz import hash_tree_root

# src-id namespace for adversarial attestation gossip: honest attestation
# spans use the view-group id as src (small ints), adversarial ones use
# ATT_SRC_BASE + validator index — distinct span/fault identities without
# colliding with any honest message.
ATT_SRC_BASE = 1_000


def slot_committee(state, slot: int) -> np.ndarray:
    """All committee members of ``slot``, concatenated (the per-slot W of
    the reference's attack arithmetic)."""
    epoch = compute_epoch_at_slot(slot)
    count = get_committee_count_per_slot(state, epoch)
    return np.concatenate([get_beacon_committee(state, slot, i)
                           for i in range(count)])


def committee_attestations(state, slot: int, head_root: bytes,
                           participants) -> list:
    """Aggregates restricted to ``participants`` across all committees of
    ``slot`` (empty committees skipped)."""
    participants = np.asarray(participants, dtype=np.int64)
    epoch = compute_epoch_at_slot(slot)
    count = get_committee_count_per_slot(state, epoch)
    out = []
    for index in range(count):
        try:
            out.append(make_committee_attestation(
                state, slot, index, head_root, participants=participants))
        except ValueError:
            continue
    return out


class AdversaryContext:
    """One strategy invocation's window into the simulation: omniscient
    reads (the reference adversary sees every honest view and knows
    honest decision times, pos-evolution.md:1328) plus targeted writes
    routed through the driver's delivery path."""

    def __init__(self, sim, slot: int, phase: str, now: float):
        self.sim = sim
        self.slot = slot
        self.phase = phase
        self.now = now
        self._msg_seq = 0

    # -- omniscient reads ------------------------------------------------------

    def store(self, group: int = 0) -> fc.Store:
        return self.sim.groups[group].store

    def head(self, group: int = 0) -> bytes:
        """The head as the run's protocol variant computes it (the
        adversary forks off what honest validators actually follow).
        Under the Gasper default this stays the spec walk, byte-identical
        to the pre-seam context."""
        sim = self.sim
        if sim.variant.needs_view:
            return sim.variant.head(sim, sim.groups[group])
        return fc.get_head(sim.groups[group].store)

    def n_groups(self) -> int:
        return len(self.sim.groups)

    # -- targeted writes -------------------------------------------------------

    def broadcast(self, kind: str, payload, *, src: int,
                  delay: float | dict = 0.0, groups=None,
                  msg_id: int | None = None) -> None:
        """Send one message through the driver's fault-aware delivery.

        ``delay`` seconds after ``now`` (or a per-group-id dict — the
        targeted-delivery power); ``groups`` restricts recipients (None =
        every view group). Blocks are registered in the block archive so
        ``_sync_ancestors`` backfill works for late receivers, exactly as
        for honest proposals."""
        sim = self.sim
        if msg_id is None:
            msg_id = self._msg_seq
            self._msg_seq += 1
        if kind == "block":
            sim.block_archive[hash_tree_root(payload.message)] = payload
        sim._observe(kind, payload)
        targets = (sim.groups if groups is None
                   else [sim.groups[g] for g in groups])
        for dst in targets:
            d = delay.get(dst.id, None) if isinstance(delay, dict) else delay
            sim._send(dst, self.now, d, kind, payload, self.slot,
                      src=int(src), msg_id=int(msg_id))

    def deliver(self) -> None:
        """Flush everything due at ``now`` into the stores — lets a
        strategy observe the effect of its own injection within the same
        hook (the swayer's release-until-leading loop)."""
        self.sim._tick_all(self.now)


class AdversaryStrategy:
    """Base strategy: holds the controlled validator set and no-ops every
    hook. ``controlled`` indices are folded into ``Schedule.corrupted``
    at bind time, so the honest duty loop never proposes or attests for
    them — Byzantine actions happen only through the hooks."""

    name = "adversary"

    def __init__(self, controlled=()):
        self.controlled = tuple(int(v) for v in controlled)

    def bind(self, sim) -> None:
        self.sim = sim

    def describe(self) -> dict:
        """Config fingerprint for repro bundles (scripts/chaos_fuzz.py)."""
        return {"kind": type(self).__name__,
                "controlled": list(self.controlled)}

    def before_propose(self, ctx: AdversaryContext) -> None:
        pass

    def before_attest(self, ctx: AdversaryContext) -> None:
        pass

    def after_attest(self, ctx: AdversaryContext) -> None:
        pass


class Equivocator(AdversaryStrategy):
    """Double proposals and double votes (pos-evolution.md:233-238,
    1154-1156): when a controlled validator is the proposer of an active
    slot it publishes TWO conflicting blocks; controlled attesters vote
    both fork tips. Pure evidence generator — the slasher must catch all
    of it and the fork-choice discounting must neutralize the votes."""

    name = "equivocator"

    def __init__(self, controlled=(), slots=None):
        super().__init__(controlled)
        self.slots = None if slots is None else set(int(s) for s in slots)

    def describe(self) -> dict:
        d = super().describe()
        d["slots"] = None if self.slots is None else sorted(self.slots)
        return d

    def _active(self, slot: int) -> bool:
        return self.slots is None or slot in self.slots

    def before_propose(self, ctx: AdversaryContext) -> None:
        if not self._active(ctx.slot):
            return
        store = ctx.store(0)
        head = ctx.head(0)
        head_state = advance_state_to_slot(store.block_states[head], ctx.slot)
        proposer = int(get_beacon_proposer_index(head_state))
        if proposer not in self.controlled:
            return
        parent_state = store.block_states[head]
        sb_a = build_block(parent_state, ctx.slot, graffiti=b"\xe1" * 32)
        sb_b = build_block(parent_state, ctx.slot, graffiti=b"\xe2" * 32)
        ctx.broadcast("block", sb_a, src=proposer, msg_id=0)
        ctx.broadcast("block", sb_b, src=proposer, msg_id=1)

    def before_attest(self, ctx: AdversaryContext) -> None:
        if not self._active(ctx.slot):
            return
        store = ctx.store(0)
        head = ctx.head(0)
        # two targets: the head and its highest-slot sibling-or-ancestor
        # fork tip (our own equivocating proposal when one exists)
        others = [r for r, b in store.blocks.items()
                  if r != head and int(b.slot) == ctx.slot]
        alt = max(others) if others else bytes(store.blocks[head].parent_root)
        if alt == head or alt not in store.block_states:
            return  # no second tip to equivocate onto (e.g. head == anchor)
        for root in (head, alt):
            state = advance_state_to_slot(store.block_states[root], ctx.slot)
            mine = [v for v in self.controlled
                    if v in set(int(i) for i in slot_committee(state, ctx.slot))]
            if not mine:
                return
            for att in committee_attestations(state, ctx.slot, root, mine):
                ctx.broadcast("attestation", att,
                              src=ATT_SRC_BASE + mine[0],
                              delay=float(self.sim.delta))


@dataclass
class _PrivateChain:
    """A withheld fork: blocks built but not broadcast, plus the private
    votes controlled validators cast on it."""

    parent_root: bytes = b""
    state: object = None          # post-state of the tip
    blocks: list = field(default_factory=list)
    votes: list = field(default_factory=list)

    @property
    def tip(self) -> bytes:
        return hash_tree_root(self.blocks[-1].message)


class Withholder(AdversaryStrategy):
    """Private chain + timed release — the generalized ex-ante reorg
    (pos-evolution.md:1503-1526). At ``fork_slot`` the strategy starts a
    private chain on the honest head; controlled proposers extend it and
    controlled attesters vote it privately for ``vote_slots``; at
    ``release_slot`` everything is published in one burst (optionally
    followed by a timely controlled proposal on the private tip, the
    boost-stealing step of the 7%/0.8W variant)."""

    name = "withholder"

    def __init__(self, controlled=(), fork_slot: int = 2,
                 release_slot: int = 3, release_phase: str = "before_attest",
                 vote_slots=(), private_attesters=None,
                 propose_on_release: bool = False):
        super().__init__(controlled)
        self.fork_slot = int(fork_slot)
        self.release_slot = int(release_slot)
        self.release_phase = release_phase
        self.vote_slots = tuple(int(s) for s in vote_slots)
        # slot -> validator indices voting the private tip that slot;
        # None = every controlled member of the slot's committee
        self.private_attesters = private_attesters or {}
        self.propose_on_release = bool(propose_on_release)
        self.chain = _PrivateChain()
        self.released = False

    def describe(self) -> dict:
        d = super().describe()
        d.update(fork_slot=self.fork_slot, release_slot=self.release_slot,
                 release_phase=self.release_phase,
                 vote_slots=list(self.vote_slots),
                 propose_on_release=self.propose_on_release)
        return d

    def _extend_private(self, ctx: AdversaryContext) -> None:
        store = ctx.store(0)
        head = ctx.head(0)
        parent_state = store.block_states[head]
        # stay inside the adversary model: the private block is signed by
        # the slot's rightful proposer, so the fork only starts if that
        # proposer is ours (the curated scenarios corrupt it explicitly;
        # chaos compositions simply skip the fork otherwise — forging an
        # honest proposer's signature would frame an honest validator)
        proposer = int(get_beacon_proposer_index(
            advance_state_to_slot(parent_state, ctx.slot)))
        if proposer not in self.controlled:
            return
        sb = build_block(parent_state, ctx.slot, graffiti=b"\xad" * 32)
        post = parent_state.copy()
        state_transition(post, sb, True)
        self.chain.parent_root = head
        self.chain.state = post
        self.chain.blocks.append(sb)

    def _vote_private(self, ctx: AdversaryContext) -> None:
        view = advance_state_to_slot(self.chain.state, ctx.slot)
        voters = self.private_attesters.get(ctx.slot)
        committee = set(int(i) for i in slot_committee(view, ctx.slot))
        mine = [v for v in (self.controlled if voters is None else voters)
                if v in committee]
        if not mine:
            return
        self.chain.votes.extend(
            committee_attestations(view, ctx.slot, self.chain.tip, mine))

    def _release(self, ctx: AdversaryContext) -> None:
        self.released = True
        if not self.chain.blocks:
            return  # fork never started (fork-slot proposer not ours)
        src = self.controlled[0] if self.controlled else 0
        for sb in self.chain.blocks:
            ctx.broadcast("block", sb, src=int(sb.message.proposer_index))
        for att in self.chain.votes:
            ctx.broadcast("attestation", att, src=ATT_SRC_BASE + src)
        if self.propose_on_release:
            sb = build_block(self.chain.state, ctx.slot,
                             graffiti=b"\x44" * 32)
            ctx.broadcast("block", sb, src=int(sb.message.proposer_index))
        ctx.deliver()

    def before_propose(self, ctx: AdversaryContext) -> None:
        if ctx.slot == self.fork_slot:
            self._extend_private(ctx)
        if (not self.released and ctx.slot == self.release_slot
                and self.release_phase == "before_propose"):
            self._release(ctx)

    def before_attest(self, ctx: AdversaryContext) -> None:
        if self.chain.blocks and ctx.slot in self.vote_slots:
            self._vote_private(ctx)
        if (not self.released and ctx.slot == self.release_slot
                and self.release_phase == "before_attest"):
            self._release(ctx)


class Balancer(AdversaryStrategy):
    """Swayer-vote balancing against pre-boost Gasper
    (pos-evolution.md:1321-1348), as a strategy: the controlled slot-1
    proposer equivocates into two chains delivered one per view group;
    thereafter withheld controlled votes are released "just before a
    certain point in time" (:1328) — the attestation deadline — so each
    view sees its own chain strictly leading when its honest half votes,
    and fresh votes are banked every slot. Releasing any earlier is
    self-defeating IN-LOOP: a vote released before the proposal round
    lands in the recipient view's op pool and the next honest BLOCK
    re-gossips it to the other view mid-slot, instantly — exactly the
    honest re-gossip the reference's adversary model forbids relying on.
    (Proposals carry no fork-choice weight at boost 0, so the attester
    deadline is the only decision point that matters.)

    Requires a 2-group schedule and boost 0 (the attack the mainline W/4
    boost was introduced to kill). The tie survives exactly as long as
    the swayer banks cover each slot's honest committee imbalance
    between the views — the reference's "enough Byzantine validators in
    every slot" precondition (:1330); see
    ``sim/attacks.committee_balanced_split_schedule`` for the view
    assignment that makes epoch-0 committees split evenly."""

    name = "balancer"

    def __init__(self, controlled=()):
        super().__init__(controlled)
        self.fork_roots: tuple | None = None
        self.bank: dict[int, list] = {0: [], 1: []}

    def bind(self, sim) -> None:
        super().bind(sim)
        assert len(sim.groups) == 2, "Balancer needs exactly two view groups"
        assert cfg().proposer_score_boost_percent == 0, \
            "the swayer balancing attack targets pre-boost Gasper"

    def before_propose(self, ctx: AdversaryContext) -> None:
        if ctx.slot == 1:
            self._equivocate_genesis(ctx)

    def before_attest(self, ctx: AdversaryContext) -> None:
        if self.fork_roots is not None:
            self._sway(ctx)

    def _equivocate_genesis(self, ctx: AdversaryContext) -> None:
        store = ctx.store(0)
        anchor = ctx.head(0)
        state = store.block_states[anchor]
        proposer = int(get_beacon_proposer_index(
            advance_state_to_slot(state, 1)))
        assert proposer in self.controlled, \
            "Balancer needs the slot-1 proposer under adversary control"
        sb_l = build_block(state, 1, graffiti=b"\x1f" * 32)
        sb_r = build_block(state, 1, graffiti=b"\xf1" * 32)
        sps = float(cfg().seconds_per_slot)
        # each side sees "its" block in time to attest; the other arrives
        # a slot later (targeted delivery, pos-evolution.md:1328)
        ctx.broadcast("block", sb_l, src=proposer, msg_id=0,
                      delay={0: 0.0, 1: sps})
        ctx.broadcast("block", sb_r, src=proposer, msg_id=1,
                      delay={0: sps, 1: 0.0})
        ctx.deliver()
        self.fork_roots = (hash_tree_root(sb_l.message),
                           hash_tree_root(sb_r.message))

    def _sway(self, ctx: AdversaryContext) -> None:
        """Release banked withheld votes to each side until that side
        sees its own chain strictly leading (released votes reach the
        other side a slot later via gossip)."""
        c = cfg()
        epoch = compute_epoch_at_slot(ctx.slot)
        for side in (0, 1):
            # prune withheld votes that fell out of the validity window
            self.bank[side] = [(v, a) for v, a in self.bank[side]
                               if int(a.data.target.epoch) >= epoch - 1]
        for side in (0, 1):
            own, other = self.fork_roots[side], self.fork_roots[1 - side]
            store = ctx.store(side)
            while self.bank[side]:
                try:
                    w_own = fc.get_latest_attesting_balance(store, own)
                    w_other = fc.get_latest_attesting_balance(store, other)
                except KeyError:
                    break
                if w_own > w_other:
                    break
                voter, att = self.bank[side].pop(0)
                ctx.broadcast("attestation", att, src=ATT_SRC_BASE + voter,
                              delay={side: 0.0,
                                     1 - side: float(c.seconds_per_slot)})
                ctx.deliver()

    def after_attest(self, ctx: AdversaryContext) -> None:
        """Bank fresh withheld votes for each side's tip, alternating so
        both banks stay stocked."""
        if self.fork_roots is None:
            return
        view0 = advance_state_to_slot(
            ctx.store(0).block_states[ctx.head(0)], ctx.slot)
        committee = [int(v) for v in slot_committee(view0, ctx.slot)]
        corrupted_here = [v for v in committee if v in set(self.controlled)]
        for k, v in enumerate(corrupted_here):
            side = (k + ctx.slot) % 2
            store = ctx.store(side)
            head = fc.get_head(store)
            head_state = advance_state_to_slot(store.block_states[head],
                                               ctx.slot)
            self.bank[side].extend(
                (v, a) for a in
                committee_attestations(head_state, ctx.slot, head, [v]))


class SplitVoter(AdversaryStrategy):
    """The accountable-safety theorem's worst case, operational: with the
    network partitioned into two isolated view groups (cross-group
    delivery withheld by the Schedule), every controlled validator votes
    BOTH groups' heads every slot, and controlled proposers equivocate —
    one block per view, each packing that view's attestation pool. With
    exactly 1/3 controlled and the honest set split evenly, each view
    sees 2/3 of stake attesting its own chain and the two views finalize
    CONFLICTING checkpoints — at which point Casper FFG's theorem
    (pos-evolution.md:233-238) says the double votes themselves are the
    evidence: ``AccountableSafetyMonitor`` must attribute >= 1/3 of stake
    from them. Strictly stronger than ``Equivocator``: it equivocates
    *coherently enough to kill safety*, not just to feed the slasher.

    Use with a 2-group schedule whose ``block_delay``/``attestation_delay``
    return None across groups (``sim/attacks.split_brain_schedule``)."""

    name = "split_voter"

    def bind(self, sim) -> None:
        super().bind(sim)
        assert len(sim.groups) >= 2, "SplitVoter needs a partitioned network"

    def before_propose(self, ctx: AdversaryContext) -> None:
        sim = ctx.sim
        for g in range(ctx.n_groups()):
            group = sim.groups[g]
            if group.crashed:
                continue
            head = ctx.head(g)
            head_state = advance_state_to_slot(group.store.block_states[head],
                                               ctx.slot)
            proposer = int(get_beacon_proposer_index(head_state))
            if proposer not in self.controlled:
                continue
            # equivocating proposal: this view's chain advances with this
            # view's pool packed (the adversary builds both chains)
            atts = sim._pack_attestations(group, ctx.slot, head,
                                          head_state=head_state)
            try:
                sb = build_block(group.store.block_states[head], ctx.slot,
                                 attestations=atts,
                                 graffiti=bytes([0xD0 + g]) * 32)
            except AssertionError:
                sb = build_block(group.store.block_states[head], ctx.slot,
                                 graffiti=bytes([0xD0 + g]) * 32)
            ctx.broadcast("block", sb, src=proposer, msg_id=g, groups=[g])

    def before_attest(self, ctx: AdversaryContext) -> None:
        sim = ctx.sim
        # votes ride the wire like honest ones: usable from the next slot
        wire_delay = sim.slot_start(ctx.slot + 1) - ctx.now
        for g in range(ctx.n_groups()):
            if sim.groups[g].crashed:
                continue
            store = ctx.store(g)
            head = ctx.head(g)
            state = advance_state_to_slot(store.block_states[head], ctx.slot)
            mine = np.array(sorted(self.controlled), dtype=np.int64)
            for att in committee_attestations(state, ctx.slot, head, mine):
                ctx.broadcast("attestation", att, src=ATT_SRC_BASE + g,
                              delay=wire_delay, groups=[g])


class RandomByzantine(AdversaryStrategy):
    """Seeded stateless chaos over the controlled set: per (slot,
    validator), a hash draw picks abstain / equivocate-vote /
    stale-head-vote; controlled proposers coin-flip a double proposal.
    Same determinism discipline as ``FaultPlan`` — every decision is
    ``stateless_unit(seed, domain, slot, validator)``, so behavior is
    identical across checkpoint/resume, episode ordering, and array
    backends (all messages are built with spec builders and are
    valid-or-cleanly-rejected at the handlers)."""

    name = "random_byzantine"

    # decision domains (first key element of the seeded hash)
    _D_ACTION, _D_PROPOSE, _D_PICK = 0, 1, 2

    def __init__(self, controlled=(), seed: int = 0,
                 p_equivocate: float = 0.3, p_stale_vote: float = 0.2,
                 p_abstain: float = 0.2, p_double_propose: float = 0.5):
        super().__init__(controlled)
        self.seed = int(seed)
        self.p_equivocate = p_equivocate
        self.p_stale_vote = p_stale_vote
        self.p_abstain = p_abstain
        self.p_double_propose = p_double_propose

    def describe(self) -> dict:
        d = super().describe()
        d.update(seed=self.seed, p_equivocate=self.p_equivocate,
                 p_stale_vote=self.p_stale_vote, p_abstain=self.p_abstain,
                 p_double_propose=self.p_double_propose)
        return d

    def decisions(self, slot: int) -> dict[int, str]:
        """The pure decision table for ``slot`` (exposed for the
        determinism pin): validator -> action name."""
        out = {}
        for v in self.controlled:
            u = stateless_unit(self.seed, self._D_ACTION, slot, v)
            if u < self.p_abstain:
                out[v] = "abstain"
            elif u < self.p_abstain + self.p_equivocate:
                out[v] = "equivocate"
            elif u < self.p_abstain + self.p_equivocate + self.p_stale_vote:
                out[v] = "stale_vote"
            else:
                out[v] = "honest_vote"
        return out

    def before_propose(self, ctx: AdversaryContext) -> None:
        store = ctx.store(0)
        head = ctx.head(0)
        head_state = advance_state_to_slot(store.block_states[head], ctx.slot)
        proposer = int(get_beacon_proposer_index(head_state))
        if proposer not in self.controlled:
            return
        u = stateless_unit(self.seed, self._D_PROPOSE, ctx.slot, proposer)
        if u < self.p_double_propose:
            parent_state = store.block_states[head]
            sb_a = build_block(parent_state, ctx.slot, graffiti=b"\xb1" * 32)
            sb_b = build_block(parent_state, ctx.slot, graffiti=b"\xb2" * 32)
            ctx.broadcast("block", sb_a, src=proposer, msg_id=0)
            ctx.broadcast("block", sb_b, src=proposer, msg_id=1)
        # else: withhold the slot entirely (a missed proposal)

    def before_attest(self, ctx: AdversaryContext) -> None:
        table = self.decisions(ctx.slot)
        store = ctx.store(0)
        head = ctx.head(0)
        head_state = advance_state_to_slot(store.block_states[head], ctx.slot)
        committee = set(int(i) for i in slot_committee(head_state, ctx.slot))
        delta = float(self.sim.delta)
        # advancing a state to the slot can run epoch processing; the
        # controlled set mostly votes the same few roots, so share it
        advanced = {head: head_state}

        def _state_at(root):
            if root not in advanced:
                advanced[root] = advance_state_to_slot(
                    store.block_states[root], ctx.slot)
            return advanced[root]

        for v, action in sorted(table.items()):
            if v not in committee or action == "abstain":
                continue
            roots = [head]
            if action == "equivocate":
                siblings = sorted(r for r, b in store.blocks.items()
                                  if r != head
                                  and int(b.slot) >= ctx.slot - 1)
                if siblings:
                    pick = int(stateless_unit(self.seed, self._D_PICK,
                                              ctx.slot, v) * len(siblings))
                    roots.append(siblings[min(pick, len(siblings) - 1)])
            elif action == "stale_vote":
                older = sorted(r for r, b in store.blocks.items()
                               if int(b.slot) < ctx.slot)
                if older:
                    pick = int(stateless_unit(self.seed, self._D_PICK,
                                              ctx.slot, v) * len(older))
                    roots = [older[min(pick, len(older) - 1)]]
            for root in roots:
                # vote from the target chain's own state so the LMD/FFG
                # consistency checks pass (a valid, merely-wrong vote)
                state = _state_at(root)
                for att in committee_attestations(state, ctx.slot, root, [v]):
                    ctx.broadcast("attestation", att, src=ATT_SRC_BASE + v,
                                  delay=delta)
