"""Online property monitors for the dense driver (ISSUE 13).

The spec monitors (``sim/monitors.py``) audit per-object stores: they
wire-tap every attestation, feed a ``Slasher``, and walk checkpoint
ancestry through the block archive. At 10^6 validators the same audit
runs on **gathered tallies**: the monitors read the per-slot origination
masks (bool[N] vote batches, BEFORE the fault masks — evidence of a
violation can be observed by someone even when some recipients never
see the message), accumulate the implicated double-voter set as one
boolean column, and price it with the masked-stake tally kernel
(``parallel/sharded.masked_stake_for`` on a mesh, its host twin on a
single device — bit-identical either way).

Classification is EXACTLY the spec monitors' rule:

- ``DenseAccountableSafetyMonitor``: on conflicting finalized (or
  same-epoch justified) checkpoints across views, evidence covering
  >= 1/3 of genesis stake is the Casper FFG theorem holding — an
  ``accountable_fault``, attributable to the attackers; anything less
  is a genuine ``protocol_violation`` (the dense doctor forges exactly
  this: conflicting finality with an empty evidence column).
- ``DenseFinalityLivenessMonitor``: post-GST (and past every crash
  window), with < 1/3 controlled, the best finalized epoch across
  views must trail the current epoch by at most ``bound_epochs``;
  loudly disarmed when the preconditions cannot hold (>= 1/3
  controlled, faults with no GST, a fully partitioned network).
- ``DenseForkChoiceParityMonitor``: the sharded device head must equal
  the vectorized host spec-walk on every view — the
  ``resident_head_equals_spec_walk`` pin promoted to a continuous
  attack-time audit that yields violation dicts instead of a bool.

Violations land on ``DenseSimulation.monitor_violations`` and as
``monitor`` telemetry events, so ``scripts/run_report.py``'s property
audit and ``scripts/chaos_fuzz.py``'s repro bundles work unchanged.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "DenseMonitor", "DenseAccountableSafetyMonitor",
    "DenseVariantSafetyMonitor", "DenseFinalityLivenessMonitor",
    "DenseForkChoiceParityMonitor",
    "default_dense_monitors", "dense_monitor_from_config",
]


class DenseMonitor:
    """Base monitor: observes origination masks, checks once per slot."""

    name = "monitor"

    def bind(self, sim) -> None:
        self.sim = sim

    def describe(self) -> dict:
        return {"kind": type(self).__name__}

    def on_votes(self, sim, slot: int, originated: list) -> None:
        """``originated``: [(view, VoteBatch), ...] — pre-fault masks."""

    def on_slot_end(self, sim, slot: int) -> list[dict]:
        return []

    # checkpoint support (mirrors the adversary contract)
    def state_meta(self) -> dict:
        return {}

    def state_arrays(self) -> dict:
        return {}

    def restore_state(self, meta: dict, arrays: dict) -> None:
        pass


class DenseAccountableSafetyMonitor(DenseMonitor):
    """Safety auditor over the double-vote evidence column."""

    name = "accountable_safety"

    def bind(self, sim) -> None:
        super().bind(sim)
        self.implicated = np.zeros(sim.n, dtype=bool)
        self._reported: set = set()

    # -- observation -----------------------------------------------------------

    def on_votes(self, sim, slot: int, originated: list) -> None:
        """The FFG double-vote rule, vectorized: two origination masks
        with the same target epoch and different target blocks overlap
        only on equivocators — their intersection joins the evidence
        column. O(batches^2) mask ANDs per slot with batches <= a
        handful, each AND one O(N) vector op."""
        for i in range(len(originated)):
            for j in range(i + 1, len(originated)):
                (_, a), (_, b) = originated[i], originated[j]
                if a.epoch == b.epoch and a.block != b.block:
                    both = a.mask & b.mask
                    if both.any():
                        self.implicated |= both

    # -- per-slot check --------------------------------------------------------

    def _conflicting(self, sim, ca: tuple, cb: tuple) -> bool:
        (ea, ia), (eb, ib) = ca, cb
        if ea == 0 or eb == 0:
            return False        # genesis conflicts with nothing
        if ea == eb:
            return ia != ib
        lo, hi = (ia, ib) if ea < eb else (ib, ia)
        # ancestry over the shared block tree — the driver's own walk
        return not sim._descends(hi, lo)

    def on_slot_end(self, sim, slot: int) -> list[dict]:
        out = []
        views = sim.views
        for i in range(len(views)):
            for j in range(i + 1, len(views)):
                vi, vj = views[i], views[j]
                pairs = [("finalized", vi.finalized, vj.finalized),
                         ("justified", vi.cur_just, vj.cur_just)]
                for label, ca, cb in pairs:
                    # conflicting *justified* checkpoints are slashable
                    # only at the SAME epoch (2/3 + 2/3 overlap) —
                    # exactly the spec monitor's rule
                    if label == "justified" and ca[0] != cb[0]:
                        continue
                    if not self._conflicting(sim, ca, cb):
                        continue
                    key = (label, i, j, ca[0], ca[1], cb[0], cb[1])
                    if key in self._reported:
                        continue
                    self._reported.add(key)
                    stake = sim.stake_of(self.implicated)
                    total = sim.total_stake
                    accountable = 3 * stake >= total
                    out.append({
                        "monitor": self.name,
                        "kind": ("accountable_fault" if accountable
                                 else "protocol_violation"),
                        "checkpoint": label,
                        "groups": [i, j],
                        "epochs": [int(ca[0]), int(cb[0])],
                        "roots": [sim.roots[ca[1]].hex()[:16],
                                  sim.roots[cb[1]].hex()[:16]],
                        "evidence_size": int(self.implicated.sum()),
                        "slashable_stake": int(stake),
                        "total_stake": int(total),
                        "detail": (
                            f"conflicting {label} checkpoints between "
                            f"views {i}/{j}; double-vote evidence covers "
                            f"{stake}/{total} stake"
                            + ("" if accountable else
                               " — BELOW the 1/3 accountable-safety"
                               " bound")),
                    })
        return out

    def state_meta(self) -> dict:
        return {"reported": [list(k) for k in sorted(self._reported)]}

    def state_arrays(self) -> dict:
        return {"implicated": self.implicated}

    def restore_state(self, meta: dict, arrays: dict) -> None:
        self.implicated = np.asarray(arrays["implicated"], dtype=bool).copy()
        self._reported = {tuple(k) for k in meta.get("reported", [])}


class DenseVariantSafetyMonitor(DenseMonitor):
    """Judges each variant by ITS OWN finality rule (ISSUE 20): the FFG
    monitor above prices conflicting epoch checkpoints, but the per-slot
    variants decide at slot granularity — SSF finalizes in-slot, the
    expiry variants confirm per slot. This monitor reads the variant's
    per-view decision state (``fin_log`` / ``conf_idx``) and prices
    conflicts with the same double-vote evidence column, now keyed by
    SLOT (two votes cast the same slot for different blocks — exactly
    the per-slot equivocation the SSF slashing conditions name):

    - conflicting per-view SSF finalizations at the same slot with
      evidence >= 1/3 stake -> ``accountable_double_finality`` (the
      theorem holding at slot granularity); with less evidence ->
      ``protocol_violation`` (what the doctored negative forges);
    - cross-view Goldfish/RLMD confirmations where neither confirmed
      block descends from the other -> ``confirmation_divergence``
      (expected under a partition: confirmation is synchrony-dependent,
      pos-evolution.md:1573 — the monitor names it, the matrix expects
      it).

    Inert under Gasper (no per-slot decision state to read)."""

    name = "variant_safety"

    def bind(self, sim) -> None:
        super().bind(sim)
        self.implicated = np.zeros(sim.n, dtype=bool)
        self._reported: set = set()

    def on_votes(self, sim, slot: int, originated: list) -> None:
        for i in range(len(originated)):
            for j in range(i + 1, len(originated)):
                (_, a), (_, b) = originated[i], originated[j]
                sa = slot if a.slot is None else a.slot
                sb = slot if b.slot is None else b.slot
                if sa == sb and a.block != b.block:
                    both = a.mask & b.mask
                    if both.any():
                        self.implicated |= both

    def on_slot_end(self, sim, slot: int) -> list[dict]:
        variant = sim.variant
        out = []
        fin_log = getattr(variant, "fin_log", None)
        if fin_log is not None:
            # SSF: any same-slot, different-block pair across views
            for i in range(sim.n_groups):
                for j in range(i + 1, sim.n_groups):
                    for s_i, b_i in fin_log[i]:
                        for s_j, b_j in fin_log[j]:
                            if s_i != s_j or b_i == b_j:
                                continue
                            key = ("fin", i, j, s_i, b_i, b_j)
                            if key in self._reported:
                                continue
                            self._reported.add(key)
                            stake = sim.stake_of(self.implicated)
                            total = sim.total_stake
                            accountable = 3 * stake >= total
                            out.append({
                                "monitor": self.name,
                                "kind": ("accountable_double_finality"
                                         if accountable
                                         else "protocol_violation"),
                                "rule": variant.name,
                                "groups": [i, j],
                                "decision_slot": int(s_i),
                                "roots": [sim.roots[b_i].hex()[:16],
                                          sim.roots[b_j].hex()[:16]],
                                "evidence_size":
                                    int(self.implicated.sum()),
                                "slashable_stake": int(stake),
                                "total_stake": int(total),
                                "detail": (
                                    f"views {i}/{j} finalized conflicting "
                                    f"blocks at slot {s_i} under "
                                    f"{variant.name}; per-slot double-vote "
                                    f"evidence covers {stake}/{total} stake"
                                    + ("" if accountable else
                                       " — BELOW the 1/3 bound")),
                            })
        conf = getattr(variant, "conf_idx", None)
        if conf is not None:
            for i in range(sim.n_groups):
                for j in range(i + 1, sim.n_groups):
                    a, b = conf[i], conf[j]
                    if a == b or sim._descends(a, b) \
                            or sim._descends(b, a):
                        continue
                    key = ("conf", i, j, a, b)
                    if key in self._reported:
                        continue
                    self._reported.add(key)
                    out.append({
                        "monitor": self.name,
                        "kind": "confirmation_divergence",
                        "rule": variant.name,
                        "groups": [i, j],
                        "roots": [sim.roots[a].hex()[:16],
                                  sim.roots[b].hex()[:16]],
                        "detail": (
                            f"views {i}/{j} confirmed diverging blocks "
                            f"under {variant.name} (confirmation is "
                            f"synchrony-dependent — expected under a "
                            f"partition, never under clean conditions)"),
                    })
        return out

    def state_meta(self) -> dict:
        return {"reported": [list(k) for k in sorted(self._reported)]}

    def state_arrays(self) -> dict:
        return {"implicated": self.implicated}

    def restore_state(self, meta: dict, arrays: dict) -> None:
        self.implicated = np.asarray(arrays["implicated"],
                                     dtype=bool).copy()
        self._reported = {tuple(k) for k in meta.get("reported", [])}


class DenseFinalityLivenessMonitor(DenseMonitor):
    """Plausible-liveness auditor; disarmed (loudly, in ``describe``)
    when the theorem's preconditions cannot hold."""

    name = "finality_liveness"

    def __init__(self, bound_epochs: int = 4,
                 armed_after_epoch: int | None = None):
        self.bound_epochs = int(bound_epochs)
        self.armed_after_epoch = armed_after_epoch
        self.disarmed_reason: str | None = None
        self._worst_lag = 0

    def describe(self) -> dict:
        return {"kind": type(self).__name__,
                "bound_epochs": self.bound_epochs,
                "armed_after_epoch": self.armed_after_epoch,
                "disarmed": self.disarmed_reason}

    def bind(self, sim) -> None:
        super().bind(sim)
        n_controlled = int(sim.controlled_any.sum())
        if 3 * n_controlled >= sim.n:
            self.disarmed_reason = (f"{n_controlled}/{sim.n} controlled "
                                    f">= 1/3: liveness not guaranteed")
            return
        plan = sim.fault_plan
        if self.armed_after_epoch is not None:
            return
        armed = 0
        if plan is not None:
            if plan.partition == "full":
                self.disarmed_reason = \
                    "fully partitioned network: no synchrony to rely on"
                return
            if (plan.drop_p or plan.delay_p) and plan.gst_slot is None:
                self.disarmed_reason = \
                    "message faults with no GST: no synchrony to rely on"
                return
            if plan.gst_slot is not None:
                armed = max(armed, -(-int(plan.gst_slot) // sim.S))
            for w in plan.crashes:
                armed = max(armed, -(-w.rejoin_slot // sim.S))
        self.armed_after_epoch = armed

    def on_slot_end(self, sim, slot: int) -> list[dict]:
        if self.disarmed_reason is not None:
            return []
        epoch = slot // sim.S
        if epoch < (self.armed_after_epoch or 0) + self.bound_epochs:
            return []
        best = max(v.finalized[0] for v in sim.views)
        lag = epoch - best
        if lag <= self.bound_epochs or lag <= self._worst_lag:
            return []   # report once per lag level, not per stalled slot
        self._worst_lag = lag
        return [{
            "monitor": self.name,
            "kind": "liveness_violation",
            "epoch": int(epoch),
            "best_finalized_epoch": int(best),
            "lag_epochs": int(lag),
            "bound_epochs": self.bound_epochs,
            "armed_after_epoch": self.armed_after_epoch,
            "detail": (f"finality lag {lag} epochs > bound "
                       f"{self.bound_epochs} at epoch {epoch} "
                       f"(post-GST, < 1/3 controlled)"),
        }]

    def state_meta(self) -> dict:
        return {"worst_lag": self._worst_lag,
                "armed_after_epoch": self.armed_after_epoch,
                "disarmed": self.disarmed_reason}

    def restore_state(self, meta: dict, arrays: dict) -> None:
        self._worst_lag = int(meta.get("worst_lag", 0))
        self.armed_after_epoch = meta.get("armed_after_epoch")
        self.disarmed_reason = meta.get("disarmed")


class DenseForkChoiceParityMonitor(DenseMonitor):
    """Device/host-walk head parity per view, under attack traffic."""

    name = "forkchoice_parity"

    def __init__(self, every: int = 1):
        self.every = int(every)

    def describe(self) -> dict:
        return {"kind": type(self).__name__, "every": self.every}

    def on_slot_end(self, sim, slot: int) -> list[dict]:
        if self.every <= 0 or slot % self.every != 0:
            return []
        out = []
        for g in range(sim.n_groups):
            # a fresh POST-vote device head query (the proposed block is
            # not the head when an attack reorgs mid-slot) vs the
            # independent host walk over the gathered table
            device = sim.roots[sim._head(g)]
            walk = sim.head_host_walk(g)
            if device != walk:
                out.append({
                    "monitor": self.name,
                    "kind": "parity_violation",
                    "group": g,
                    "slot": int(slot),
                    "device_head": device.hex()[:16],
                    "spec_head": walk.hex()[:16],
                    "detail": (f"view {g} device head diverged from the "
                               f"host spec-walk at slot {slot}"),
                })
        return out


def default_dense_monitors(bound_epochs: int = 4,
                           parity_every: int = 1) -> list[DenseMonitor]:
    """The full dense audit stack (dense chaos fuzzing default)."""
    return [DenseAccountableSafetyMonitor(),
            DenseFinalityLivenessMonitor(bound_epochs=bound_epochs),
            DenseForkChoiceParityMonitor(every=parity_every),
            DenseVariantSafetyMonitor()]


_MONITORS = {
    "DenseAccountableSafetyMonitor": DenseAccountableSafetyMonitor,
    "DenseVariantSafetyMonitor": DenseVariantSafetyMonitor,
    "DenseFinalityLivenessMonitor": DenseFinalityLivenessMonitor,
    "DenseForkChoiceParityMonitor": DenseForkChoiceParityMonitor,
}


def dense_monitor_from_config(d: dict) -> DenseMonitor:
    """Rebuild a monitor from its ``describe()`` dict."""
    kind = d["kind"]
    cls = _MONITORS.get(kind)
    if cls is None:
        raise ValueError(f"unknown dense monitor kind {kind!r}")
    if kind == "DenseFinalityLivenessMonitor":
        return cls(bound_epochs=d.get("bound_epochs", 4),
                   armed_after_epoch=d.get("armed_after_epoch"))
    if kind == "DenseForkChoiceParityMonitor":
        return cls(every=d.get("every", 1))
    return cls()
