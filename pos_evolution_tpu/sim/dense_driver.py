"""Mainnet-scale end-to-end dense simulation on a device mesh (ISSUES 9, 13).

The spec-level ``sim/driver.py`` carries per-message Python objects —
the right tool for adversarial/faulted protocol audits, and the wrong
one for 10^6 validators (building one slot's attestations would cost
minutes of host Python). This driver is the **array level of the whole
simulation loop**: the registry, the latest-message table and the
participation flags live as sharded device columns from genesis, and
every per-slot protocol step is one of the three validator-axis sweeps
run as ``shard_map`` kernels over the ``(pods, shard)`` mesh:

- **fork choice** (north-star config #1): the head query rebuilds the
  per-block vote buckets with the sharded segment-sum vote pass
  (``parallel/sharded.vote_weights_for`` — psum ICI-first, DCN-second),
  then descends on the replicated O(B) block tree
  (``ops/forkchoice.head_from_buckets``);
- **attestation flow**: committee assignment via the swap-or-not
  shuffle (sharded per ``sharded_shuffle``'s index-parallel form), votes
  land as masked elementwise updates on the sharded message/flag
  columns — the dense image of one slot's gossip;
- **aggregation verify** (config #3): each slot's committee aggregates
  run through ``aggregate_verify_batch`` sharded over the committee
  axis;
- **epoch processing** (config #4): the fused ``epoch_core`` sweep as a
  ``shard_map`` with two-axis psum; justification bits and the 4-case
  finalization rule drive real finality.

**Robustness at this scale (ISSUE 13):** the spec driver's scenario
machinery folds in as data on the same sweeps —

- a ``DenseFaultPlan`` (sim/faults.py) turns message loss, delivery
  delay, GST windows and crash blackouts into per-(slot, view,
  validator) masks ANDed INSIDE the masked vote pass
  (``parallel/sharded.vote_apply_for``): faulted is literally
  unfaulted-with-masks, so an all-pass plan is bit-identical to no
  plan, on every mesh shape;
- vectorized adversary strategies (sim/dense_adversary.py) act through
  three hooks per slot, emitting masked ``VoteBatch``\\ es and extra
  block-tree entries; their traffic goes through the same fault-masked
  apply and is observed at origination by
- the dense monitors (sim/dense_monitors.py), which read the gathered
  tallies and classify accountable faults vs protocol violations
  exactly as ``sim/monitors.py``;
- ``n_groups=2`` splits the network into per-view message tables /
  flag columns / FFG state over ONE shared block tree with per-view
  visibility masks — the partitioned (SplitVoter) and delay-partitioned
  (Balancer) networks of the attack reproductions, at 10^6 validators.

Everything is integer math, so the sharded run is **bit-identical** to
the single-device one (``mesh=None``) on every mesh shape — pinned in
tests/test_sharded_e2e.py and tests/test_dense_chaos.py together with
the host-walk oracle (the device head must equal the vectorized NumPy
walk over the gathered message table).

Checkpoint/resume gathers the sharded columns to host (`.npz` + JSON
meta, including every view's state and the full chaos configuration +
mutable adversary/monitor state) and re-shards on the mesh active at
resume time — resuming on a *different* mesh shape (or a single device)
mid-attack is bit-identical by the same kernel contracts.

``scripts/multichip_demo.py`` drives this at 1M validators for
``MULTICHIP_r09.json``; ``scripts/dense_chaos_demo.py`` runs the
adversarial acceptance episodes for ``CHAOS_DENSE_r13.json``;
``scripts/chaos_fuzz.py --dense N`` fuzzes compositions.
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import time

import numpy as np

from pos_evolution_tpu.config import Config, mainnet_config

__all__ = ["DenseSimulation"]

GWEI = 10**9
_GENESIS_EFF = 32 * GWEI


def _hash(*parts) -> bytes:
    h = hashlib.sha256()
    for p in parts:
        h.update(p if isinstance(p, bytes) else str(p).encode())
    return h.digest()


from pos_evolution_tpu.ops.variant_tally import (  # noqa: E402
    next_pow2 as _next_pow2,
)


class _View:
    """One view group's mutable state: its own message table, flag
    columns (inside the registry), FFG scalars and block visibility.
    ``n_groups=1`` runs exactly one of these — the pre-ISSUE-13 driver."""

    __slots__ = ("registry", "msg_block", "msg_epoch", "msg_slot", "bits",
                 "prev_just", "cur_just", "finalized", "epoch_start_idx",
                 "vis_host", "vis_d", "pending")

    def __init__(self):
        self.bits = np.zeros(4, dtype=bool)
        self.prev_just = (0, 0)   # (epoch, block index)
        self.cur_just = (0, 0)
        self.finalized = (0, 0)
        self.epoch_start_idx: dict[int, int] = {0: 0}
        self.pending: list = []   # delayed VoteBatches for the next slot


class DenseSimulation:
    """Multi-epoch run, entirely at the array level — honest and
    synchronous by default; adversarial, faulted and partitioned when
    given a chaos composition.

    ``mesh=None`` runs the identical loop on a single device (the
    differential twin). ``n_validators`` must divide by ``mesh.size``
    when a mesh is given (the shuffle shards the index axis evenly).
    """

    def __init__(self, n_validators: int, cfg: Config | None = None,
                 mesh=None, seed: int = 0, shuffle_rounds: int = 10,
                 verify_aggregates: bool = True, capacity: int = 256,
                 check_walk_every: int = 16, autocheckpoint=None,
                 n_groups: int = 1, fault_plan=None, adversaries=(),
                 monitors=(), telemetry=None, phase_profile=None,
                 flight_recorder=None, variant=None, riders=()):
        import jax.numpy as jnp
        self.cfg = cfg or mainnet_config()
        self.n = int(n_validators)
        self.mesh = mesh
        self.seed = int(seed)
        self.shuffle_rounds = int(shuffle_rounds)
        self.verify_aggregates = bool(verify_aggregates)
        self.check_walk_every = int(check_walk_every)
        self.S = int(self.cfg.slots_per_epoch)
        if mesh is not None and self.n % mesh.size != 0:
            raise ValueError(
                f"n_validators={self.n} must divide by the mesh device "
                f"count {mesh.size}")
        self._npad = self.n  # registry rows incl. inert padding (== n here)

        # --- chaos composition (ISSUE 13) ----------------------------------
        self.n_groups = int(n_groups)
        assert self.n_groups in (1, 2), "1 or 2 view groups"
        self.fault_plan = fault_plan
        if self.n_groups > 1:
            assert fault_plan is not None and fault_plan.partition, \
                "multi-view runs need a partitioned DenseFaultPlan"
        self.adversaries = list(adversaries)
        self.monitors = list(monitors)
        self.telemetry = telemetry
        # protocol-variant seam (ISSUE 20): head/confirmation policy,
        # duty shape (committee vs full participation), expiry windows,
        # per-slot gadgets — DenseGasper reproduces the pre-variant
        # driver bit-for-bit (no window, no anchor override, boost 0)
        from pos_evolution_tpu.sim.dense_variants import (
            dense_variant_from_config,
        )
        self.variant = dense_variant_from_config(variant)
        self.riders = [r for r in riders if r is not None]
        # per-view proposer-boost candidate: the newest timely (visible)
        # proposal; None while withheld or before the first slot
        self._boost: list[int | None] = [None] * self.n_groups
        # phase profiler (ISSUE 18 leg c): ``phase_profile=N`` fences
        # every N-th slot; None/0 threads the disabled twin so the loop
        # body stays branch-free either way
        from pos_evolution_tpu.profiling.phases import (
            NULL_TIMER,
            PhaseTimer,
        )
        self.phases = (PhaseTimer(
            sample_every=int(phase_profile),
            registry=telemetry.registry if telemetry else None,
            bus=telemetry.bus if telemetry else None)
            if phase_profile else NULL_TIMER)
        # device flight recorder (ISSUE 19): memory watermarks at slot/
        # epoch/checkpoint boundaries, shard-skew probes at its sampled
        # slots, and the compile-provenance ledger. Armed lazily at the
        # first ``run_slot`` — NOT here — so construction-time warm-up
        # compiles (jnp.full fills, block-tree init: no phase active)
        # never land as unattributed ledger rows; that lazy arming is
        # what the >=95% named-attribution bar assumes.
        self.flight = flight_recorder
        self._flight_probe = False  # True during a probed slot's phases
        self.monitor_violations: list[dict] = []
        # honest duty split: view group per validator (parity keeps the
        # shuffled committees near-balanced between the halves)
        self.group_of = (np.arange(self.n, dtype=np.int64)
                         % self.n_groups).astype(np.int8)
        self.controlled_any = np.zeros(self.n, dtype=bool)
        for adv in self.adversaries:
            idx = adv.controlled[adv.controlled < self.n]
            self.controlled_any[idx] = True
        self._eff_genesis = np.full(self.n, _GENESIS_EFF, dtype=np.int64)
        self.total_stake = int(self.n) * _GENESIS_EFF
        self._originated: list = []      # this slot's (view, batch) taps
        self._pending_vis: list = []     # (block_idx, view, at_slot)

        # --- registry: sharded-resident from genesis -----------------------
        far = np.int64(2**62)  # FAR_FUTURE_I64

        def fill_const(v, dtype):
            return lambda lo, hi: np.full(hi - lo, v, dtype)

        col_fills = {
            "effective_balance": (_GENESIS_EFF, np.int64),
            "balance": (_GENESIS_EFF, np.int64),
            "activation_epoch": (0, np.int64),
            "exit_epoch": (far, np.int64),
            "withdrawable_epoch": (far, np.int64),
            "slashed": (False, bool),
            "prev_flags": (0, np.uint8),
            "cur_flags": (0, np.uint8),
            "inactivity_scores": (0, np.int64),
        }
        from pos_evolution_tpu.ops.epoch import DenseRegistry
        self.views = [_View() for _ in range(self.n_groups)]
        for view in self.views:
            if mesh is not None:
                # never materialized unsharded: each device fills its
                # slice, placed per the partition rules
                from pos_evolution_tpu.parallel.partition import (
                    build_sharded,
                    spec_for,
                )
                view.registry = DenseRegistry(**{
                    f: build_sharded(mesh, spec_for(f"registry/{f}"),
                                     (self.n,), dt, fill_const(v, dt))
                    for f, (v, dt) in col_fills.items()})
                view.msg_block = build_sharded(
                    mesh, spec_for("messages/msg_block"), (self.n,),
                    np.int32, fill_const(-1, np.int32))
                view.msg_epoch = build_sharded(
                    mesh, spec_for("messages/msg_epoch"), (self.n,),
                    np.int64, fill_const(0, np.int64))
                view.msg_slot = build_sharded(
                    mesh, spec_for("messages/msg_slot"), (self.n,),
                    np.int64, fill_const(0, np.int64))
            else:
                view.registry = DenseRegistry(**{
                    f: jnp.full(self.n, v, dtype=dt)
                    for f, (v, dt) in col_fills.items()})
                view.msg_block = jnp.full(self.n, -1, dtype=jnp.int32)
                view.msg_epoch = jnp.zeros(self.n, dtype=jnp.int64)
                view.msg_slot = jnp.zeros(self.n, dtype=jnp.int64)

        # --- replicated O(B) block tree ------------------------------------
        self.capacity = _next_pow2(capacity)
        self.roots: list[bytes] = []
        self.parents: list[int] = []
        self.block_slots: list[int] = []
        self._parent_d = jnp.full(self.capacity, -1, dtype=jnp.int32)
        self._slot_d = jnp.zeros(self.capacity, dtype=jnp.int32)
        self._rank_d = jnp.zeros(self.capacity, dtype=jnp.int32)
        self._real_d = jnp.zeros(self.capacity, dtype=bool)
        self._viable_d = jnp.ones(self.capacity, dtype=bool)
        for view in self.views:
            view.vis_host = np.zeros(self.capacity, dtype=bool)
            view.vis_d = jnp.zeros(self.capacity, dtype=bool)

        # --- run scalars ----------------------------------------------------
        self.slot = 0
        self.metrics: list[dict] = []
        self.aggregates_verified = 0
        self.walk_checks: list[bool] = []
        self.view_heads: list[bytes] = [b""] * self.n_groups
        self._epoch_ready = -1
        self._perm_host: np.ndarray | None = None
        self._assigned_host: np.ndarray | None = None

        # synthetic per-validator pubkeys -> replicated signature midstates
        # (the pk table is replicated by design, SURVEY's config #3 note)
        from pos_evolution_tpu.ops.aggregation import precompute_pk_states
        rng = np.random.default_rng(self.seed)
        self.pk_states = precompute_pk_states(
            rng.integers(0, 256, (self.n, 48)).astype(np.uint8))

        self._append_block(_hash(b"genesis", self.seed), -1, 0)

        self.variant.bind(self)
        for r in self.riders:
            r.bind(self)
        for adv in self.adversaries:
            adv.bind(self)
        for mon in self.monitors:
            mon.bind(self)
        self._emit("run_start", n_validators=self.n,
                   n_groups=self.n_groups, dense=True,
                   mesh=self._mesh_shape(), variant=self.variant.name)
        if self.variant.name != "gasper" or self.riders:
            self._emit("variant_attach", variant=self.variant.describe(),
                       riders=[r.describe() for r in self.riders])
        if self.adversaries or self.monitors:
            self._emit("monitor_attach",
                       monitors=[m.describe() for m in self.monitors],
                       adversaries=[a.describe()
                                    for a in self.adversaries],
                       faults=(self.fault_plan.describe()
                               if self.fault_plan else None))

        # Run supervision (resilience/, ISSUE 10, DESIGN.md §18): the
        # dense driver's async capture is the gather-then-compress
        # split — columns come to host synchronously (host_gather, the
        # cheap device-synchronous part), npz compression runs on the
        # manager's writer thread, so multi-epoch walls never stall on
        # serialization.
        self.supervision = None
        if autocheckpoint is not None:
            self.attach_autocheckpoint(autocheckpoint)

    # -- back-compat accessors (view 0 is the run when n_groups == 1) ----------

    registry = property(lambda s: s.views[0].registry,
                        lambda s, v: setattr(s.views[0], "registry", v))
    msg_block = property(lambda s: s.views[0].msg_block,
                         lambda s, v: setattr(s.views[0], "msg_block", v))
    msg_epoch = property(lambda s: s.views[0].msg_epoch,
                         lambda s, v: setattr(s.views[0], "msg_epoch", v))
    msg_slot = property(lambda s: s.views[0].msg_slot,
                        lambda s, v: setattr(s.views[0], "msg_slot", v))
    bits = property(lambda s: s.views[0].bits,
                    lambda s, v: setattr(s.views[0], "bits", v))
    prev_just = property(lambda s: s.views[0].prev_just,
                         lambda s, v: setattr(s.views[0], "prev_just", v))
    cur_just = property(lambda s: s.views[0].cur_just,
                        lambda s, v: setattr(s.views[0], "cur_just", v))
    finalized = property(lambda s: s.views[0].finalized,
                         lambda s, v: setattr(s.views[0], "finalized", v))
    epoch_start_idx = property(
        lambda s: s.views[0].epoch_start_idx,
        lambda s, v: setattr(s.views[0], "epoch_start_idx", v))

    def _mesh_shape(self):
        return (None if self.mesh is None else
                {a: int(x) for a, x in zip(self.mesh.axis_names,
                                           self.mesh.devices.shape)})

    def _emit(self, type_: str, **fields) -> None:
        if self.telemetry is not None:
            self.telemetry.bus.emit(type_, **fields)

    # -- block tree ------------------------------------------------------------

    def _append_block(self, root: bytes, parent: int, slot: int,
                      visible_to=None) -> int:
        """``visible_to``: None = every view, () = private (withheld),
        or an iterable of view ids (partitioned proposals)."""
        import jax.numpy as jnp
        i = len(self.roots)
        if i >= self.capacity:
            self._grow(self.capacity * 2)
        self.roots.append(root)
        self.parents.append(parent)
        self.block_slots.append(slot)
        self._parent_d = self._parent_d.at[i].set(parent)
        self._slot_d = self._slot_d.at[i].set(slot)
        self._real_d = self._real_d.at[i].set(True)
        vis = (range(self.n_groups) if visible_to is None
               else tuple(visible_to))
        for g, view in enumerate(self.views):
            see = g in vis
            view.vis_host[i] = see
            if see:
                view.vis_d = view.vis_d.at[i].set(True)
        order = np.argsort(np.argsort(np.array(self.roots, dtype=object)))
        rank = np.zeros(self.capacity, np.int32)
        rank[: len(self.roots)] = order
        self._rank_d = jnp.asarray(rank)
        return i

    def _grow(self, new_capacity: int) -> None:
        import jax.numpy as jnp
        new_capacity = _next_pow2(new_capacity)
        b = len(self.roots)
        parent = np.full(new_capacity, -1, np.int32)
        parent[:b] = self.parents
        slot = np.zeros(new_capacity, np.int32)
        slot[:b] = self.block_slots
        real = np.zeros(new_capacity, bool)
        real[:b] = True
        old_capacity = self.capacity
        self.capacity = new_capacity
        self._parent_d = jnp.asarray(parent)
        self._slot_d = jnp.asarray(slot)
        self._rank_d = jnp.zeros(new_capacity, jnp.int32)
        self._real_d = jnp.asarray(real)
        self._viable_d = jnp.ones(new_capacity, bool)
        for view in self.views:
            vis = np.zeros(new_capacity, dtype=bool)
            vis[:old_capacity] = view.vis_host
            view.vis_host = vis
            view.vis_d = jnp.asarray(vis)

    def adversary_block(self, parent: int, slot: int, tag=(),
                        visible: bool = True) -> int:
        """Adversary-built block (equivocating sibling / private-chain
        extension): deterministic root from the identity, appended with
        full or zero visibility."""
        root = _hash(b"ablock", self.seed, slot, self.roots[parent], *tag)
        return self._append_block(root, parent, slot,
                                  visible_to=None if visible else ())

    def reveal_blocks(self, indices) -> None:
        """Flip withheld blocks visible in every view (the release)."""
        import jax.numpy as jnp  # noqa: F401
        for view in self.views:
            for i in indices:
                view.vis_host[i] = True
                view.vis_d = view.vis_d.at[i].set(True)

    def withhold_proposal(self, g: int, idx: int) -> None:
        """Adversary proposer withholds this slot's proposal: the block
        goes private in EVERY view (it was never broadcast), honest duty
        falls back to voting its parent, and it earns no proposer boost
        — the opening move of the ex-ante reorg. ``reveal_blocks``
        undoes it at release time."""
        for view in self.views:
            view.vis_host[idx] = False
            view.vis_d = view.vis_d.at[idx].set(False)

    # -- variant seam ----------------------------------------------------------

    def duty_mask(self, slot: int) -> np.ndarray:
        """bool[N]: who votes this slot — the slot committee under
        Gasper, everyone under the full-participation variants (the
        per-slot audit the spec tier can't afford, ISSUE 20)."""
        if self.variant.full_participation:
            return np.ones(self.n, dtype=bool)
        return self.committee_mask(slot)

    def _vote_target(self, g: int, idx: int) -> int:
        """What view ``g`` actually votes for when told to vote ``idx``:
        the block if it is visible, else its parent (a withheld proposal
        cannot attract honest votes)."""
        return idx if self.views[g].vis_host[idx] else self.parents[idx]

    def _variant_head_inputs(self, g: int):
        """(window, start_idx, boost_idx, boost_amount) for one view's
        head query — the SINGLE source both the device descent and the
        host-walk oracle consume, so variant policy can never split
        them. Window is as-of ``self.slot + 1`` (the next decision
        point: during the propose pass that is the slot being built,
        after ``self.slot = s`` it is the head entering slot s+1)."""
        v = self.variant
        win = v.window(self.slot + 1)
        anchor = v.anchor(g)
        start = self.views[g].cur_just[1] if anchor is None else anchor
        bidx, bamt = -1, 0
        if v.boost_percent and self._boost[g] is not None:
            bidx = self._boost[g]
            # the spec's committee-sized boost: one slot's share of
            # total stake, scaled — exact integer math
            bamt = (self.total_stake // self.S
                    * v.boost_percent // 100)
        return win, start, bidx, bamt

    # -- committees ------------------------------------------------------------

    def _start_epoch(self, epoch: int) -> None:
        """Shuffle the registry into this epoch's slot assignment
        (config #2: the index axis is embarrassingly parallel)."""
        import jax.numpy as jnp
        seed = _hash(b"shuffle", self.seed, epoch)[:32]
        if self.mesh is not None:
            from pos_evolution_tpu.ops.shuffle import _seed_words, host_pivots
            from pos_evolution_tpu.parallel.sharded import shuffle_for
            shuf = shuffle_for(self.mesh, self.n, self.shuffle_rounds)
            perm = shuf(jnp.asarray(_seed_words(seed)),
                        jnp.asarray(host_pivots(seed, self.n,
                                                self.shuffle_rounds)),
                        jnp.arange(self.n, dtype=jnp.int32))
        else:
            from pos_evolution_tpu.ops.shuffle import shuffle_permutation_jax
            perm = shuffle_permutation_jax(seed, self.n, self.shuffle_rounds)
        perm_host = np.asarray(perm).astype(np.int64)
        self._perm_host = perm_host
        self._inv_perm = np.argsort(perm_host).astype(np.int64)
        assigned = perm_host * self.S // self.n
        self._assigned_host = assigned.astype(np.int64)
        self._epoch_ready = epoch

    def committee_mask(self, slot: int) -> np.ndarray:
        """bool[N]: this slot's committee members (host side — the
        origination masks and fault compositions are host numpy)."""
        return self._assigned_host == (slot % self.S)

    def _place_validator_col(self, a: np.ndarray,
                             name: str = "messages/assigned"):
        import jax.numpy as jnp
        if self.mesh is None:
            return jnp.asarray(a)
        from pos_evolution_tpu.parallel.partition import shard_leaf, spec_for
        return shard_leaf(self.mesh, spec_for(name), a)

    def _slot_attesters(self, slot_in_epoch: int) -> np.ndarray:
        t = int(slot_in_epoch)
        lo = (t * self.n + self.S - 1) // self.S
        hi = ((t + 1) * self.n + self.S - 1) // self.S
        return self._inv_perm[lo:hi]

    # -- fork choice -----------------------------------------------------------

    def _head(self, g: int = 0) -> int:
        import jax.numpy as jnp

        from pos_evolution_tpu.ops.forkchoice import (
            head_from_buckets,
            rebuild_buckets,
        )
        view = self.views[g]
        win, start, bidx, bamt = self._variant_head_inputs(g)
        with self.phases.phase("vote_pass"):
            msg = view.msg_block
            if win is not None:
                # expiry-windowed variants: filter the message table
                # before the unchanged weights pass (sharded twin /
                # single-device jit twin — identical elementwise math)
                if self.mesh is not None:
                    from pos_evolution_tpu.parallel.sharded import (
                        expiry_mask_for,
                    )
                    msg = expiry_mask_for(self.mesh)(
                        msg, view.msg_slot, jnp.int64(win[0]),
                        jnp.int64(win[1]))
                else:
                    from pos_evolution_tpu.sim.dense_variants import (
                        expiry_kernel,
                    )
                    msg = expiry_kernel()(msg, view.msg_slot,
                                          jnp.int64(win[0]),
                                          jnp.int64(win[1]))
            if self.mesh is not None:
                from pos_evolution_tpu.parallel.sharded import (
                    vote_weights_for,
                )
                buckets = vote_weights_for(self.mesh, self.capacity)(
                    msg, view.registry.effective_balance)
            else:
                buckets = rebuild_buckets(msg,
                                          view.registry.effective_balance,
                                          self.capacity)
            if self._flight_probe:
                # before the fence: afterwards every shard is ready and
                # the per-device arrival spread is unobservable
                self.flight.probe_skew("vote_pass", buckets,
                                       slot=self.slot + 1)
            self.phases.fence(buckets)
        # the int() materialization blocks, so this phase is honestly
        # fenced on EVERY slot, sampled or not
        with self.phases.phase("head_descent"):
            head_idx, _ = head_from_buckets(
                self._parent_d, self._real_d & view.vis_d, self._rank_d,
                self._viable_d, jnp.int32(start), buckets,
                jnp.int32(bidx), jnp.int64(bamt), self.capacity)
            return int(head_idx)

    def head_host_walk(self, g: int = 0) -> bytes:
        """The spec-walk oracle: gather the view's message table,
        accumulate vote weights and subtree sums in NumPy, descend
        greedily — the ``resident_head_equals_spec_walk`` pin of
        MULTICHIP_r09, per view, withheld blocks masked out."""
        from pos_evolution_tpu.ops.forkchoice import head_host
        view = self.views[g]
        win, start, bidx, bamt = self._variant_head_inputs(g)
        msg = np.asarray(view.msg_block)[: self.n]
        if win is not None:
            ms = np.asarray(view.msg_slot)[: self.n]
            msg = np.where((ms >= win[0]) & (ms <= win[1]), msg, -1)
        eff = np.asarray(view.registry.effective_balance)[: self.n]
        valid = msg >= 0
        vw = np.zeros(self.capacity + 1, np.int64)
        np.add.at(vw, np.where(valid, msg, self.capacity),
                  np.where(valid, eff, 0))
        b = len(self.roots)
        parent = np.full(self.capacity, -1, np.int32)
        parent[:b] = self.parents
        real = np.zeros(self.capacity, bool)
        real[:b] = True
        rank = np.asarray(self._rank_d)
        idx = head_host(parent, real & view.vis_host, rank,
                        np.ones(self.capacity, bool), start,
                        vw[: self.capacity], bidx, bamt)
        return self.roots[idx]

    # -- monitors' gathered-tally helpers --------------------------------------

    def stake_of(self, mask: np.ndarray) -> int:
        """Genesis-stake tally of a validator mask — the monitors'
        evidence pricing. On a mesh the mask is placed sharded and the
        tally runs as the two-axis psum kernel
        (``parallel/sharded.masked_stake_for``); the single-device path
        is the host twin. Bit-identical (int64)."""
        if self.mesh is not None:
            from pos_evolution_tpu.parallel.sharded import masked_stake_for
            placed = self._place_validator_col(np.asarray(mask, dtype=bool),
                                               "messages/evidence")
            eff = self._place_validator_col(self._eff_genesis,
                                            "messages/stake")
            return int(masked_stake_for(self.mesh)(placed, eff))
        from pos_evolution_tpu.ops.epoch import masked_stake_host
        return masked_stake_host(mask, self._eff_genesis)

    def _descends(self, idx: int, ancestor: int) -> bool:
        cur = idx
        while cur >= 0:
            if cur == ancestor:
                return True
            cur = self.parents[cur]
        return False

    def _target_matches(self, g: int, block_idx: int, epoch: int) -> bool:
        """The spec's flag rule at array level: a vote earns the view's
        timely-target participation flag only when its target chain
        carries the view's checkpoint for that epoch (process_attestation
        requires att.data.target == the state's current checkpoint; a
        vote for the OTHER partition's chain must not count toward this
        view's justification)."""
        boundary = self.views[g].epoch_start_idx.get(epoch)
        if boundary is None:
            return False
        return self._descends(block_idx, boundary)

    # -- votes -----------------------------------------------------------------

    def _apply_batch(self, g: int, mask_np: np.ndarray, block_idx: int,
                     epoch: int, vote_slot: int, flag_on: bool) -> None:
        """One masked vote landing on view ``g``'s sharded columns —
        the shard_map kernel on a mesh, its jitted elementwise twin on
        a single device (identical math). ``vote_slot`` stamps the
        landed rows with the vote's ORIGINATION slot — the expiry and
        per-slot-tally input of the variant plane."""
        import jax.numpy as jnp
        view = self.views[g]
        mask_col = self._place_validator_col(
            np.ascontiguousarray(mask_np, dtype=bool), "messages/allow")
        if self.mesh is not None:
            from pos_evolution_tpu.parallel.sharded import vote_apply_for
            kern = vote_apply_for(self.mesh)
        else:
            kern = _vote_kernel()
        mb, me, ms, cf = kern(view.msg_block, view.msg_epoch,
                              view.msg_slot, view.registry.cur_flags,
                              mask_col, jnp.int32(block_idx),
                              jnp.int64(epoch), jnp.int64(vote_slot),
                              jnp.bool_(flag_on))
        view.msg_block, view.msg_epoch, view.msg_slot = mb, me, ms
        view.registry = view.registry._replace(cur_flags=cf)

    def _fault_masks(self, slot: int, g: int):
        """(dropped, delayed, crashed) bool[N] for one (slot, view)."""
        if self.fault_plan is None:
            z = np.zeros(self.n, dtype=bool)
            return z, z, z
        dropped, delayed = self.fault_plan.delivery_masks(slot, g, self.n)
        crashed = self.fault_plan.crashed_mask(slot, self.n)
        return dropped, delayed, crashed

    def _deliver_batch(self, g: int, batch, slot: int,
                       epoch_now: int) -> np.ndarray:
        """Route one VoteBatch into view ``g`` through the fault masks;
        the non-delivered delayed slice re-queues for the next slot.
        Returns the mask that actually landed."""
        from pos_evolution_tpu.sim.dense_adversary import VoteBatch
        mask = batch.mask
        # origination stamp: a delayed/banked vote keeps its true slot
        # through any number of requeues — expiry judges when the vote
        # was CAST, not when it landed
        vslot = slot if batch.slot is None else int(batch.slot)
        if not self.variant.admit(vslot, slot):
            # RLMD staleness gate: too old to merge into the view at all
            self._emit("dense_fault", slot=slot, view=g,
                       expired=int(mask.sum()))
            return np.zeros(self.n, dtype=bool)
        if batch.faultable:
            dropped, delayed, crashed = self._fault_masks(slot, g)
            land = mask & ~crashed & ~dropped & ~delayed
            late = mask & ~crashed & delayed
            if late.any():
                self.views[g].pending.append(
                    VoteBatch(late, batch.block, batch.epoch, views=(g,),
                              flag=batch.flag, faultable=False,
                              slot=vslot))
            n_d, n_l = int((mask & dropped).sum()), int(late.sum())
            if n_d or n_l:
                self._emit("dense_fault", slot=slot, view=g,
                           dropped=n_d, delayed=n_l)
        else:
            land = mask
        if not land.any():
            return land
        if batch.flag is not None:
            flag_on = bool(batch.flag)
        else:
            # a vote delayed across an epoch boundary still updates the
            # LMD table but no longer earns the (rotated) current-epoch
            # participation flag — deterministic and conservative
            flag_on = (batch.epoch == epoch_now
                       and self._target_matches(g, batch.block, batch.epoch))
        self._apply_batch(g, land, batch.block, batch.epoch, vslot, flag_on)
        return land

    def apply_votes_now(self, batches, slot: int) -> None:
        """Immediate application for release hooks (before_propose):
        the batches go through the same fault masks and the same
        origination tap as everything else."""
        epoch_now = slot // self.S
        for batch in batches:
            for g in range(self.n_groups):
                if batch.for_view(g):
                    self._originated.append((g, batch))
                    self._deliver_batch(g, batch, slot, epoch_now)

    # -- aggregation verify ----------------------------------------------------

    def _verify_slot(self, slot_in_epoch: int, block_root: bytes,
                     landed: np.ndarray) -> None:
        """Committee aggregates over the validators whose vote for this
        block actually landed (drops shrink the aggregate; identical to
        the pre-ISSUE-13 sweep when ``landed`` covers the committee)."""
        import jax.numpy as jnp

        from pos_evolution_tpu.ops.aggregation import messages_to_words
        attesters = self._slot_attesters(slot_in_epoch)
        if attesters.size == 0:
            return
        a_real = int(self.cfg.max_committees_per_slot)
        lanes = _next_pow2(-(-attesters.size // a_real))
        committees = np.zeros((a_real, lanes), np.int32)
        bits = np.zeros((a_real, lanes), bool)
        for c in range(a_real):
            member = attesters[c::a_real]
            committees[c, : member.size] = member
            bits[c, : member.size] = landed[member]
        msg = messages_to_words(
            np.frombuffer(block_root, dtype=np.uint8)[None, :].repeat(
                a_real, axis=0))
        sigs = _make_aggregates(self.pk_states, jnp.asarray(committees),
                                jnp.asarray(bits), jnp.asarray(msg))
        if self.mesh is not None:
            from pos_evolution_tpu.parallel.sharded import (
                aggregation_verify_for,
            )
            a_pad = -(-a_real // self.mesh.size) * self.mesh.size
            if a_pad != a_real:
                committees = np.concatenate(
                    [committees, np.zeros((a_pad - a_real, lanes), np.int32)])
                bits_p = np.concatenate(
                    [bits, np.zeros((a_pad - a_real, lanes), bool)])
                msg = np.concatenate(
                    [msg, np.zeros((a_pad - a_real, 8), np.uint32)])
                sigs = jnp.concatenate(
                    [sigs, jnp.zeros((a_pad - a_real, 24), jnp.uint32)])
            else:
                bits_p = bits
            ok = aggregation_verify_for(self.mesh)(
                self.pk_states, jnp.asarray(committees),
                jnp.asarray(bits_p), jnp.asarray(msg), sigs)
        else:
            from pos_evolution_tpu.ops.aggregation import (
                aggregate_verify_batch,
            )
            ok = aggregate_verify_batch(self.pk_states,
                                        jnp.asarray(committees),
                                        jnp.asarray(bits), jnp.asarray(msg),
                                        sigs)
        ok = np.asarray(ok)[:a_real]
        nonempty = bits.any(axis=1)
        if not ok[nonempty].all():
            raise AssertionError(
                f"aggregate verification failed at slot {self.slot + 1}")
        self.aggregates_verified += int(nonempty.sum())

    # -- epoch boundary --------------------------------------------------------

    def _epoch_boundary(self, view: _View, entering_epoch: int) -> None:
        """Spec-mirrored epoch processing for one view when entering
        ``entering_epoch`` (``current_epoch`` = the epoch just
        completed, exactly like ``process_epoch`` at slot E*S - 1)."""
        import jax.numpy as jnp
        cur_e = entering_epoch - 1
        if self.mesh is not None:
            from pos_evolution_tpu.parallel.sharded import epoch_step_for
            import jax
            donate = jax.default_backend() != "cpu"
            step = epoch_step_for(self.mesh, self.cfg, donate=donate)
        else:
            from pos_evolution_tpu.ops.epoch import process_epoch_dense
            donate = False
            step = lambda *a: process_epoch_dense(*a, self.cfg)  # noqa: E731
        if self.flight is not None:
            # donation efficacy (ROADMAP item 5): registry bytes the
            # epoch step donates (or, armed=0, copies) each boundary
            from pos_evolution_tpu.telemetry import jaxrt
            jaxrt.record_donation(
                sum(a.nbytes for a in view.registry
                    if hasattr(a, "nbytes")),
                site="epoch_step", armed=donate)
        out = step(view.registry, jnp.int64(cur_e),
                   jnp.int64(view.finalized[0]), jnp.asarray(view.bits),
                   jnp.int64(view.prev_just[0]),
                   jnp.int64(view.cur_just[0]), jnp.int64(0))
        view.registry = out.registry
        if cur_e > 1:
            old_prev, old_cur = view.prev_just, view.cur_just
            view.prev_just = view.cur_just
            if bool(out.justify_prev):
                view.cur_just = (cur_e - 1,
                                 view.epoch_start_idx[cur_e - 1])
            if bool(out.justify_cur):
                view.cur_just = (cur_e, view.epoch_start_idx[cur_e])
            view.bits = np.asarray(out.new_justification_bits)
            fin = int(out.finalize_epoch)
            if fin >= 0:
                # later finalization cases use the old CURRENT justified
                # checkpoint and win in the spec — check it first
                if fin == old_cur[0]:
                    view.finalized = old_cur
                elif fin == old_prev[0]:
                    view.finalized = old_prev

    # -- main loop -------------------------------------------------------------

    def _cross_views(self, g: int):
        """Where (and when) view ``g``'s traffic reaches other views:
        [] under a full partition, the other view one slot late under
        the delay partition, immediately otherwise."""
        if self.n_groups == 1:
            return []
        mode = self.fault_plan.partition if self.fault_plan else None
        if mode == "full":
            return []
        delay = 1 if mode == "delay" else 0
        return [(h, delay) for h in range(self.n_groups) if h != g]

    def _merge_active(self) -> bool:
        """View-merge (Goldfish/RLMD): the slot proposer broadcasts its
        merged view, so every group votes for the proposer group's
        proposal and proposals reveal across views in-slot. A full
        partition severs the broadcast — merge can't cross it."""
        if not self.variant.view_merge or self.n_groups <= 1:
            return False
        mode = self.fault_plan.partition if self.fault_plan else None
        return mode != "full"

    def run_slot(self) -> None:
        from pos_evolution_tpu.sim.dense_adversary import VoteBatch
        pt = self.phases
        s = self.slot + 1
        epoch = s // self.S
        fr = self.flight
        if fr is not None and not fr.installed:
            # armed at the first slot, not at construction: see __init__
            fr.install()
        self._flight_probe = fr is not None and fr.should_probe(s)
        pt.begin_slot(s)
        if s % self.S == 0 and s > 0:
            with pt.phase("epoch_sweep"):
                for view in self.views:
                    self._epoch_boundary(view, epoch)
                if self._flight_probe:
                    # pre-fence, or the spread is unobservable
                    fr.probe_skew("epoch_sweep",
                                  self.views[0].registry.balance, slot=s)
                pt.fence(*(v.registry.balance for v in self.views))
            if fr is not None:
                fr.on_epoch(slot=s)
        if self._epoch_ready < epoch:
            # _start_epoch ends on np.asarray(perm) — host-materialized,
            # so this phase is self-fencing
            with pt.phase("shuffle"):
                self._start_epoch(epoch)
        self._originated = []
        with pt.phase("record"):
            # delayed cross-view block visibility lands at slot start
            still = []
            for idx, g, at_slot in self._pending_vis:
                if at_slot <= s:
                    view = self.views[g]
                    view.vis_host[idx] = True
                    view.vis_d = view.vis_d.at[idx].set(True)
                else:
                    still.append((idx, g, at_slot))
            self._pending_vis = still

            for adv in self.adversaries:
                adv.before_propose(self, s)

        # --- per-view proposals (head queries charge vote_pass /
        # head_descent inside _head; the block-tree bookkeeping around
        # them is "record") -------------------------------------------------
        merge = self._merge_active()
        new_idx: list[int] = []
        for g in range(self.n_groups):
            head = self._head(g)
            with pt.phase("record"):
                if self.n_groups == 1:
                    root = _hash(b"block", self.seed, s, self.roots[head])
                else:
                    root = _hash(b"block", self.seed, s,
                                 self.roots[head], g)
                visible_to = None
                cross = self._cross_views(g)
                if self.n_groups > 1 and not merge:
                    visible_to = [g] + [h for h, d in cross if d == 0]
                idx = self._append_block(root, head, s,
                                         visible_to=visible_to)
                if not merge:
                    # view-merge reveals proposals across views in-slot
                    # (the proposer broadcasts its merged view); without
                    # it, delayed cross visibility lands next slot
                    for h, d in cross:
                        if d > 0:
                            self._pending_vis.append((idx, h, s + d))
                if s % self.S == 0:
                    self.views[g].epoch_start_idx[epoch] = idx
                new_idx.append(idx)

        with pt.phase("record"):
            for adv in self.adversaries:
                adv.on_proposals(self, s, new_idx)
            # proposer-boost candidates for every head query until the
            # next proposal: this slot's proposal, unless withheld
            for g in range(self.n_groups):
                self._boost[g] = (new_idx[g]
                                  if self.views[g].vis_host[new_idx[g]]
                                  else None)
        if self.riders:
            with pt.phase("workload"):
                for r in self.riders:
                    if hasattr(r, "on_proposals"):
                        r.on_proposals(self, s, new_idx)

        # --- votes: pending (delayed) first, then honest, then adversarial
        with pt.phase("vote_apply"):
            landed_own = [np.zeros(self.n, dtype=bool)
                          for _ in range(self.n_groups)]
            for g, view in enumerate(self.views):
                pending, view.pending = view.pending, []
                for batch in pending:
                    self._originated.append((g, batch))
                    land = self._deliver_batch(g, batch, s, epoch)
                    if batch.block == new_idx[g]:
                        landed_own[g] |= land
            # view-merge variants vote ONE merged target per slot (the
            # proposer group's proposal — pos-evolution.md:1560); the
            # others vote their own view's proposal. A withheld target
            # falls back to its parent (the honest view never saw it).
            vote_targets = [new_idx[s % self.n_groups] if merge
                            else new_idx[g]
                            for g in range(self.n_groups)]
            duty_all = self.duty_mask(s)
            for g in range(self.n_groups):
                duty = (duty_all & (self.group_of == g)
                        & ~self.controlled_any)
                tgt = self._vote_target(g, vote_targets[g])
                batch = VoteBatch(duty, tgt, epoch, views=(g,))
                self._originated.append((g, batch))
                land = self._deliver_batch(g, batch, s, epoch)
                if tgt == new_idx[g]:
                    landed_own[g] |= land
                for h, delay in self._cross_views(g):
                    # stamp at origination: the delayed copy must carry
                    # slot s into the next slot's delivery (expiry and
                    # the per-slot tallies judge the cast slot)
                    cross = VoteBatch(duty.copy(), tgt, epoch,
                                      views=(h,), slot=s)
                    if delay == 0:
                        self._originated.append((h, cross))
                        self._deliver_batch(h, cross, s, epoch)
                    else:
                        self.views[h].pending.append(cross)
            for adv in self.adversaries:
                for batch in adv.vote_batches(self, s, new_idx):
                    for g in range(self.n_groups):
                        if batch.for_view(g):
                            self._originated.append((g, batch))
                            land = self._deliver_batch(g, batch, s, epoch)
                            if batch.block == new_idx[g]:
                                landed_own[g] |= land
            pt.fence(*(v.msg_block for v in self.views))

        if self.verify_aggregates and not self.variant.full_participation:
            # _verify_slot materializes the ok vector — self-fencing.
            # Full-participation variants replace committee aggregation
            # with per-slot everyone-votes, so there is no committee
            # aggregate to verify.
            with pt.phase("aggregate_verify"):
                for g in range(self.n_groups):
                    if landed_own[g].any():
                        self._verify_slot(s % self.S,
                                          self.roots[new_idx[g]],
                                          landed_own[g])

        self.slot = s
        self.view_heads = [self.roots[new_idx[g]]
                           for g in range(self.n_groups)]

        # --- variant plane: per-slot tallies / gadgets over the sharded
        # link tallies (expiry confirmation, SSF justify/finalize) ---------
        with pt.phase("variant_tally"):
            self.variant.on_slot_end(self, s, vote_targets)
        if self.riders:
            with pt.phase("workload"):
                for r in self.riders:
                    if hasattr(r, "on_slot_end"):
                        r.on_slot_end(self, s)

        # --- monitors over the gathered tallies ---------------------------
        with pt.phase("monitors"):
            for mon in self.monitors:
                mon.on_votes(self, s, self._originated)
            for mon in self.monitors:
                for v in mon.on_slot_end(self, s):
                    v.setdefault("slot", s)
                    self.monitor_violations.append(v)
                    self._emit("monitor", **v)

        if self.check_walk_every and s % self.check_walk_every == 0:
            # device head vs independent host walk (not the proposed
            # block: an adversary can legitimately move the head). The
            # head query charges its own phases; only the NumPy walk
            # itself is the audit.
            dev_head = self.roots[self._head(0)]
            with pt.phase("host_audit"):
                self.walk_checks.append(self.head_host_walk(0) ==
                                        dev_head)
                if self.mesh is not None and self.variant.name != "gasper":
                    # sharded windowed tally vs the ops/variant_tally
                    # host oracle — the variant plane's parity audit
                    from pos_evolution_tpu.sim.dense_variants import (
                        variant_tally_parity,
                    )
                    self.walk_checks.append(
                        variant_tally_parity(self, 0, s))
        with pt.phase("record"):
            m = {
                "slot": s, "head_root": self.view_heads[0].hex()[:16],
                "justified_epoch": self.views[0].cur_just[0],
                "finalized_epoch": self.views[0].finalized[0],
                "n_blocks": len(self.roots),
            }
            if self.n_groups > 1:
                m["views"] = [
                    {"head_root": self.view_heads[g].hex()[:16],
                     "justified_epoch": self.views[g].cur_just[0],
                     "finalized_epoch": self.views[g].finalized[0]}
                    for g in range(self.n_groups)]
            self.metrics.append(m)
            self._emit("slot", slot=s, head_slot=s,
                       justified_epoch=self.views[0].cur_just[0],
                       finalized_epoch=self.views[0].finalized[0])
        if self.supervision is not None:
            with pt.phase("checkpoint_capture"):
                self.supervision.tick(self, s,
                                      self._checkpoint_async_capture)
        if fr is not None and self._flight_probe:
            with pt.phase("record"):
                fr.on_slot(s)  # memory watermark at the slot boundary
        self._flight_probe = False
        pt.end_slot(s)

    def run_epochs(self, n_epochs: int) -> None:
        """Run through the first slot of epoch ``n_epochs`` (inclusive),
        so the boundary entering it — the one that can finalize epoch
        ``n_epochs - 2`` — has been processed (the spec driver's
        ``run_epochs`` shape)."""
        while self.slot < n_epochs * self.S:
            self.run_slot()

    # -- results ---------------------------------------------------------------

    def summary(self) -> dict:
        # final parity pin: host walk vs a fresh DEVICE head query — not
        # roots[-1], which under an adversary is whatever block was
        # appended last (an equivocating sibling, a private extension)
        head = self.roots[self._head(0)]
        self.walk_checks.append(self.head_host_walk(0) == head)
        out = {
            "n_validators": self.n,
            "mesh": self._mesh_shape(),
            "slots": self.slot,
            "epochs": self.slot // self.S,
            "n_blocks": len(self.roots),
            "justified_epoch": self.views[0].cur_just[0],
            "finalized_epoch": self.views[0].finalized[0],
            "finality_reached": self.views[0].finalized[0] > 0,
            "aggregates_verified": self.aggregates_verified,
            "resident_head_equals_spec_walk": all(self.walk_checks),
            "walk_checks": len(self.walk_checks),
            "head_root": head.hex()[:16],
        }
        if self.n_groups > 1:
            out["n_groups"] = self.n_groups
            out["views"] = [{"justified_epoch": v.cur_just[0],
                             "finalized_epoch": v.finalized[0],
                             "head_root": self.view_heads[g].hex()[:16]}
                            for g, v in enumerate(self.views)]
        out["variant"] = self.variant.name
        if self.variant.name != "gasper":
            out["variant_decisions"] = len(self.variant.decisions)
            vs = self.variant.summary_fields(self)
            if vs:
                out["variant_state"] = vs
        if self.riders:
            out["workload"] = {r.kind: r.stats() for r in self.riders}
        if self.monitors or self.adversaries:
            out["monitor_violations"] = len(self.monitor_violations)
            out["violation_kinds"] = sorted(
                {v["kind"] for v in self.monitor_violations})
        if self.phases.enabled:
            out["dense_phases"] = self.phases.summary()
        if self.flight is not None:
            out["device"] = self.flight.summary()
        return out

    # -- checkpoint / resume (gather -> host -> re-shard) ----------------------

    def checkpoint(self, path: str | None = None) -> bytes:
        """Gather every device column to host and serialize. The layout
        (mesh shape, sharding) is deliberately NOT part of the format:
        ``resume`` re-places columns on whatever mesh it is given —
        checkpoint on 2x4, resume on 4x2/1x8/single-device, bit-identical
        (tests/test_sharded_e2e.py pins the round trip; the chaos
        composition and every adversary's/monitor's mutable state ride
        along, so a resume MID-ATTACK replays the identical episode —
        tests/test_dense_chaos.py). ``path`` additionally lands the
        bytes on disk atomically."""
        data = self._checkpoint_serialize(*self._checkpoint_capture())
        if path is not None:
            from pos_evolution_tpu.utils.snapshot import atomic_write_bytes
            atomic_write_bytes(path, data)
        return data

    def _checkpoint_capture(self):
        """The device-synchronous half: JSON-able meta plus host copies
        of every sharded column (``parallel/sharded.host_gather``).
        Cheap relative to compression — this is all that runs on the
        epoch loop's critical path in async autocheckpoint mode.
        Every mutable collection is COPIED, never referenced: in async
        mode the writer thread serializes while the loop keeps mutating."""
        from pos_evolution_tpu.parallel.sharded import host_gather
        views_meta = []
        cols: dict[str, np.ndarray] = {}
        for g, view in enumerate(self.views):
            prefix = "" if g == 0 else f"g{g}_"
            vc = host_gather({f: getattr(view.registry, f)
                              for f in view.registry._fields})
            for f, a in vc.items():
                cols[prefix + f] = a[: self.n]
            cols[prefix + "msg_block"] = np.asarray(view.msg_block)[: self.n]
            cols[prefix + "msg_epoch"] = np.asarray(view.msg_epoch)[: self.n]
            cols[prefix + "msg_slot"] = np.asarray(view.msg_slot)[: self.n]
            pend_meta = []
            for j, b in enumerate(view.pending):
                cols[f"v{g}_pend{j}_idx"] = \
                    np.flatnonzero(b.mask).astype(np.int64)
                pend_meta.append({"block": int(b.block),
                                  "epoch": int(b.epoch),
                                  "flag": b.flag,
                                  "faultable": bool(b.faultable),
                                  "slot": (None if b.slot is None
                                           else int(b.slot))})
            views_meta.append({
                "bits": [bool(x) for x in view.bits],
                "prev_just": list(view.prev_just),
                "cur_just": list(view.cur_just),
                "finalized": list(view.finalized),
                "epoch_start_idx": {str(k): v for k, v
                                    in view.epoch_start_idx.items()},
                "vis": [bool(x) for x in
                        view.vis_host[: len(self.roots)]],
                "pending": pend_meta,
            })
        chaos = None
        if self.fault_plan or self.adversaries or self.monitors:
            for i, adv in enumerate(self.adversaries):
                for name, arr in adv.state_arrays().items():
                    cols[f"adv{i}_{name}"] = np.asarray(arr)
            for i, mon in enumerate(self.monitors):
                for name, arr in mon.state_arrays().items():
                    cols[f"mon{i}_{name}"] = np.asarray(arr)
            chaos = {
                "faults": (self.fault_plan.describe()
                           if self.fault_plan else None),
                "adversaries": [{"config": a.describe(),
                                 "state": a.state_meta()}
                                for a in self.adversaries],
                "monitors": [{"config": m.describe(),
                              "state": m.state_meta()}
                             for m in self.monitors],
            }
        for i, r in enumerate(self.riders):
            for name, arr in r.state_arrays().items():
                cols[f"rider{i}_{name}"] = np.asarray(arr)
        meta = {
            "version": 3, "n": self.n, "seed": self.seed,
            # the variant fingerprint: resume reconstructs the policy
            # from this and refuses an ``expect_variant`` mismatch loudly
            "variant": self.variant.describe(),
            "variant_state": self.variant.state_meta(),
            "riders": [{"config": r.describe(), "state": r.state_meta()}
                       for r in self.riders],
            "boost": [None if b is None else int(b) for b in self._boost],
            "shuffle_rounds": self.shuffle_rounds,
            "verify_aggregates": self.verify_aggregates,
            "capacity": self.capacity,
            "check_walk_every": self.check_walk_every,
            "n_groups": self.n_groups,
            "cfg": {k: (["__bytes__", v.hex()] if isinstance(v, bytes) else v)
                    for k, v in dataclasses.asdict(self.cfg).items()},
            "slot": self.slot,
            "views": views_meta,
            "pending_vis": [list(t) for t in self._pending_vis],
            "roots": [r.hex() for r in self.roots],
            "parents": list(self.parents),
            "block_slots": list(self.block_slots),
            "aggregates_verified": self.aggregates_verified,
            "walk_checks": [bool(b) for b in self.walk_checks],
            "view_heads": [h.hex() for h in self.view_heads],
            "metrics": [dict(m) for m in self.metrics],
            "epoch_ready": self._epoch_ready,
            "chaos": chaos,
            "monitor_violations": [dict(v)
                                   for v in self.monitor_violations],
        }
        if self._perm_host is not None:
            cols["perm"] = self._perm_host
        # ISSUE 19: charge the full capture to the transfer ledger under
        # its own site (host_gather already charged the registry columns
        # it moved — the sites stay distinct, don't sum them) and take a
        # memory watermark while both device state and its host copy are
        # live: this is the run's realistic high-water point.
        try:
            from pos_evolution_tpu.telemetry import jaxrt
            jaxrt.record_transfer(
                sum(a.nbytes for a in cols.values() if hasattr(a, "nbytes")),
                direction="d2h", site="checkpoint_capture")
        except Exception:
            pass  # pev: ignore[PEV005] — accounting must never kill this
        if self.flight is not None:
            self.flight.sample_memory(site="checkpoint", slot=self.slot)
        return meta, cols

    @staticmethod
    def _checkpoint_serialize(meta: dict, cols: dict) -> bytes:
        """The expensive half (json + npz compression): pure function
        of the captured host state, safe on a background thread."""
        out = io.BytesIO()
        head = json.dumps(meta).encode()
        out.write(np.uint64(len(head)).tobytes())
        out.write(head)
        np.savez_compressed(out, **cols)
        return out.getvalue()

    def _checkpoint_async_capture(self):
        """RunSupervision capture: gather now, serialize whenever the
        writer thread gets to it (the captured host copies are frozen —
        the loop mutating ``self`` no longer races the write)."""
        meta, cols = self._checkpoint_capture()

        def job():
            t0 = time.perf_counter()
            data = self._checkpoint_serialize(meta, cols)
            self.phases.charge_async("checkpoint_serialize",
                                     time.perf_counter() - t0)
            return data
        return job

    @classmethod
    def resume(cls, data: bytes, mesh=None, telemetry=None,
               expect_variant: str | None = None, phase_profile=None,
               flight_recorder=None) -> "DenseSimulation":
        from pos_evolution_tpu.sim.dense_adversary import (
            VoteBatch,
            dense_adversary_from_config,
        )
        from pos_evolution_tpu.sim.dense_monitors import (
            dense_monitor_from_config,
        )
        from pos_evolution_tpu.sim.dense_variants import (
            dense_rider_from_config,
            dense_variant_from_config,
        )
        from pos_evolution_tpu.sim.faults import DenseFaultPlan
        buf = io.BytesIO(data)
        (n_head,) = np.frombuffer(buf.read(8), dtype=np.uint64)
        meta = json.loads(buf.read(int(n_head)).decode())
        assert meta["version"] in (1, 2, 3), meta["version"]
        v1 = meta["version"] == 1
        ckpt_variant = (meta.get("variant") or {"kind": "gasper"})["kind"]
        if expect_variant is not None and ckpt_variant != expect_variant:
            raise ValueError(
                f"checkpoint was written under variant {ckpt_variant!r}, "
                f"refusing to resume it as {expect_variant!r}: the "
                f"message-table semantics (expiry stamps, per-slot "
                f"gadget state) are not interchangeable across variants")
        cfg = Config(**{
            k: (bytes.fromhex(v[1])
                if isinstance(v, list) and len(v) == 2 and v[0] == "__bytes__"
                else v)
            for k, v in meta["cfg"].items()})
        chaos = None if v1 else meta.get("chaos")
        fault_plan = adversaries = monitors = None
        if chaos is not None:
            fault_plan = DenseFaultPlan.from_config(chaos.get("faults"))
            adversaries = [dense_adversary_from_config(a["config"])
                           for a in chaos.get("adversaries", [])]
            monitors = [dense_monitor_from_config(m["config"])
                        for m in chaos.get("monitors", [])]
        riders = [dense_rider_from_config(r["config"])
                  for r in meta.get("riders", [])]
        sim = cls(meta["n"], cfg=cfg, mesh=mesh, seed=meta["seed"],
                  shuffle_rounds=meta["shuffle_rounds"],
                  verify_aggregates=meta["verify_aggregates"],
                  capacity=meta["capacity"],
                  check_walk_every=meta["check_walk_every"],
                  n_groups=meta.get("n_groups", 1),
                  fault_plan=fault_plan,
                  adversaries=adversaries or (),
                  monitors=monitors or (), telemetry=telemetry,
                  phase_profile=phase_profile,
                  flight_recorder=flight_recorder,
                  variant=dense_variant_from_config(meta.get("variant")),
                  riders=riders)
        views_meta = ([{
            "bits": meta["bits"], "prev_just": meta["prev_just"],
            "cur_just": meta["cur_just"], "finalized": meta["finalized"],
            "epoch_start_idx": meta["epoch_start_idx"], "vis": None,
            "pending": [],
        }] if v1 else meta["views"])
        with np.load(buf) as z:
            from pos_evolution_tpu.ops.epoch import DenseRegistry
            arrays = {k: z[k] for k in z.files}
        sim.roots = [bytes.fromhex(r) for r in meta["roots"]]
        sim.parents = list(meta["parents"])
        sim.block_slots = list(meta["block_slots"])
        b = len(sim.roots)
        import jax.numpy as jnp
        parent = np.full(sim.capacity, -1, np.int32)
        parent[:b] = sim.parents
        slot = np.zeros(sim.capacity, np.int32)
        slot[:b] = sim.block_slots
        real = np.zeros(sim.capacity, bool)
        real[:b] = True
        order = np.argsort(np.argsort(np.array(sim.roots, dtype=object)))
        rank = np.zeros(sim.capacity, np.int32)
        rank[:b] = order
        sim._parent_d = jnp.asarray(parent)
        sim._slot_d = jnp.asarray(slot)
        sim._rank_d = jnp.asarray(rank)
        sim._real_d = jnp.asarray(real)
        for g, (view, vm) in enumerate(zip(sim.views, views_meta)):
            prefix = "" if g == 0 else f"g{g}_"
            view.registry = DenseRegistry(**{
                f: sim._place_validator_col(arrays[prefix + f],
                                            f"registry/{f}")
                for f in DenseRegistry._fields})
            view.msg_block = sim._place_validator_col(
                arrays[prefix + "msg_block"], "messages/msg_block")
            view.msg_epoch = sim._place_validator_col(
                arrays[prefix + "msg_epoch"], "messages/msg_epoch")
            ms_key = prefix + "msg_slot"
            view.msg_slot = sim._place_validator_col(
                arrays[ms_key] if ms_key in arrays
                else np.zeros(sim.n, np.int64),  # pre-v3: no stamps
                "messages/msg_slot")
            view.bits = np.asarray(vm["bits"], dtype=bool)
            view.prev_just = tuple(vm["prev_just"])
            view.cur_just = tuple(vm["cur_just"])
            view.finalized = tuple(vm["finalized"])
            view.epoch_start_idx = {int(k): v for k, v
                                    in vm["epoch_start_idx"].items()}
            vis = np.zeros(sim.capacity, dtype=bool)
            if vm["vis"] is None:
                vis[:b] = True
            else:
                vis[:b] = np.asarray(vm["vis"], dtype=bool)
            view.vis_host = vis
            view.vis_d = jnp.asarray(vis)
            view.pending = []
            for j, pm in enumerate(vm.get("pending", [])):
                mask = np.zeros(sim.n, dtype=bool)
                mask[arrays[f"v{g}_pend{j}_idx"]] = True
                pslot = pm.get("slot")
                view.pending.append(VoteBatch(
                    mask, int(pm["block"]), int(pm["epoch"]), views=(g,),
                    flag=pm.get("flag"),
                    faultable=bool(pm.get("faultable", False)),
                    slot=None if pslot is None else int(pslot)))
        sim._pending_vis = [tuple(t) for t in meta.get("pending_vis", [])]
        sim.slot = meta["slot"]
        sim.aggregates_verified = meta["aggregates_verified"]
        sim.walk_checks = list(meta["walk_checks"])
        sim.view_heads = [bytes.fromhex(h)
                          for h in meta.get("view_heads",
                                            [""] * sim.n_groups)]
        sim.metrics = list(meta["metrics"])
        sim._epoch_ready = meta["epoch_ready"]
        sim.monitor_violations = list(meta.get("monitor_violations", []))
        if chaos is not None:
            for i, (adv, am) in enumerate(zip(sim.adversaries,
                                              chaos.get("adversaries", []))):
                adv.restore_state(am.get("state", {}), {
                    k[len(f"adv{i}_"):]: v for k, v in arrays.items()
                    if k.startswith(f"adv{i}_")})
            for i, (mon, mm) in enumerate(zip(sim.monitors,
                                              chaos.get("monitors", []))):
                mon.restore_state(mm.get("state", {}), {
                    k[len(f"mon{i}_"):]: v for k, v in arrays.items()
                    if k.startswith(f"mon{i}_")})
        sim.variant.restore_state(meta.get("variant_state", {}))
        for i, (r, rm) in enumerate(zip(sim.riders,
                                        meta.get("riders", []))):
            r.restore_state(rm.get("state", {}), {
                k[len(f"rider{i}_"):]: v for k, v in arrays.items()
                if k.startswith(f"rider{i}_")})
        boost = meta.get("boost")
        if boost is not None:
            sim._boost = [None if b is None else int(b) for b in boost]
        perm = arrays.get("perm")
        if perm is not None and sim._epoch_ready >= 0:
            sim._perm_host = perm.astype(np.int64)
            sim._inv_perm = np.argsort(sim._perm_host).astype(np.int64)
            sim._assigned_host = (sim._perm_host * sim.S
                                  // sim.n).astype(np.int64)
        return sim

    # -- run supervision (resilience/, ISSUE 10) -------------------------------

    def attach_autocheckpoint(self, spec) -> None:
        """Arm (or re-arm, after a resume) run supervision — see
        ``Simulation.attach_autocheckpoint``; the dense driver's capture
        additionally backgrounds the npz compression."""
        from pos_evolution_tpu.resilience import RunSupervision
        self.supervision = RunSupervision(spec, kind="dense",
                                          cfg_obj=self.cfg)

    def finish_autocheckpoint(self) -> dict | None:
        """Final checkpoint at the current slot + writer drain; returns
        the manager's overhead stats (None when unsupervised)."""
        if self.supervision is None:
            return None
        return self.supervision.finish(self.slot,
                                       self._checkpoint_async_capture)

    @classmethod
    def resume_latest(cls, dir, mesh=None, autocheckpoint=None,
                      expect_variant: str | None = None
                      ) -> "DenseSimulation":
        """Resume from the newest *valid* checkpoint under ``dir``,
        quarantining and rolling past corrupt steps — onto whatever
        mesh is ACTIVE now (``mesh=None`` = single device), which is
        the device-loss path: a run checkpointed on 2x4 resumes
        bit-identically on 1x4 or one device. Raises
        ``FileNotFoundError`` when nothing valid exists."""
        # no fingerprint pin here: the dense checkpoint carries its own
        # Config in-band and ``resume`` reconstructs from it, so there
        # is no "active config" to cross-check (unlike the spec driver)
        from pos_evolution_tpu.resilience import CheckpointManager
        found = CheckpointManager(dir).latest_valid()
        if found is None:
            raise FileNotFoundError(
                f"no valid checkpoint under {dir!r} to resume from")
        step, payloads = found
        sim = cls.resume(payloads["payload.bin"], mesh=mesh,
                         expect_variant=expect_variant)
        if autocheckpoint is not None:
            sim.attach_autocheckpoint(autocheckpoint)
        from pos_evolution_tpu.telemetry import emit_global
        import os as _os
        emit_global("run_resumed", step=step, slot=sim.slot,
                    dir=_os.fspath(dir))
        return sim


_VOTE_KERNEL = None


def _vote_kernel():
    """Single-device twin of ``parallel/sharded.vote_apply_for``:
    identical elementwise math, one jitted executable per process."""
    global _VOTE_KERNEL
    if _VOTE_KERNEL is None:
        import jax
        import jax.numpy as jnp

        def kern(msg_block, msg_epoch, msg_slot, cur_flags, mask,
                 idx, ep, vslot, flag_on):
            return (jnp.where(mask, idx, msg_block),
                    jnp.where(mask, ep, msg_epoch),
                    jnp.where(mask, vslot, msg_slot),
                    jnp.where(mask & flag_on,
                              cur_flags | np.uint8(7), cur_flags))
        _VOTE_KERNEL = jax.jit(kern)
    return _VOTE_KERNEL


def _make_aggregates(pk_states, committees, bits, msg_words):
    """Each slot's aggregation duty: the honest committee aggregates
    from ``ops.aggregation.aggregate_signatures_batch`` (the signer side
    of the verification sweep)."""
    from pos_evolution_tpu.ops.aggregation import aggregate_signatures_batch
    return aggregate_signatures_batch(pk_states, committees, bits,
                                      msg_words)
